"""Why don't stacked segments overlap MXU compute under the DMA stream?

The round-3 cost model (docs/KERNELS.md) measured multi-stage segments
at DMA + compute SERIAL (bench 3-stage: ~80 ms/pass at 30q vs the 34.7
pass baseline + ~45 ms summed stage cost), while single-stage segments
hide their compute almost entirely. Automatic Pallas pipelining should
give max(DMA, compute). Hypotheses, each one experiment (28q so a
non-aliased variant fits HBM):

  H1  input_output_aliases breaks the pipeliner's overlap (conservative
      buffer-level hazard between block i's store and block i+1's load).
      -> same segment with and without aliasing.
  H2  dimension semantics: grid marked arbitrary serializes. -> parallel.
  H3  neither: the compute genuinely saturates a shared resource.

Each case runs in a subprocess (one compile failure must not kill the
matrix). Usage: python scripts/probe_pipeline.py [n]   (default 28)
"""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import json, sys, time
sys.path.insert(0, %(repo)r)
from quest_tpu.precision import enable_compile_cache
enable_compile_cache()
import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

mode = %(mode)r
n = %(n)d
reps = %(reps)d

from quest_tpu.ops import pallas_band as PB

if mode != "alias":
    # strip the in-place aliasing / force dimension semantics by
    # intercepting pallas_call (probe-only: the production path keeps
    # aliasing for the 30q memory story)
    real_call = pl.pallas_call
    def patched(kernel, **kw):
        if mode == "noalias":
            kw.pop("input_output_aliases", None)
        elif mode == "parallel":
            from quest_tpu import compat
            grid = kw.get("grid")
            _, params_cls = compat.pallas_tpu_names()
            kw["compiler_params"] = params_cls(
                vmem_limit_bytes=PB.VMEM_LIMIT_BYTES,
                dimension_semantics=("parallel",) * len(grid))
        return real_call(kernel, **kw)
    pl.pallas_call = patched
    PB.pl.pallas_call = patched

# the bench-shaped 3-stage segment: b0 + b1 + scb8 (the measured
# "stacking exposes compute" case), identity values (perf only)
stages = []
arrays = []
g128 = np.zeros((2, 128, 128), np.float32); g128[0] = np.eye(128)
stages.append(PB.MatStage(kind="b0", dim=128, real_only=False,
                          lane_preds=(), row_preds=()))
arrays.append(jnp.asarray(g128))
stages.append(PB.MatStage(kind="b1", dim=128, real_only=False,
                          lane_preds=(), row_preds=()))
arrays.append(jnp.asarray(g128))
d = 8; w = 3
g8 = np.zeros((2, d, d), np.float32); g8[0] = np.eye(d)
stages.append(PB.MatStage(kind="scb", bit=n - 7 - w, dim=d,
                          real_only=False, lane_preds=(), row_preds=()))
arrays.append(jnp.asarray(g8))

fn = PB.compile_segment(stages, n)
donate = (0,) if mode == "alias" else ()
jfn = jax.jit(lambda a: fn(a, arrays), donate_argnums=donate)
from quest_tpu.state import basis_planes, fused_state_shape
amps = basis_planes(0, n=n, rdt=jnp.float32, shape=fused_state_shape(n))
out = jfn(amps)
_ = np.asarray(out[0, 0, :4])
if mode == "alias":
    amps = out
t0 = time.perf_counter()
for _ in range(reps):
    if mode == "alias":
        amps = jfn(amps)
    else:
        out = jfn(amps)
_ = np.asarray((amps if mode == "alias" else out)[0, 0, :4])
dt = (time.perf_counter() - t0) / reps
gb = 2 * 2 * (1 << n) * 4 / 2**30
print("[probe-result] " + json.dumps(dict(
    mode=mode, n=n, ms=round(dt * 1e3, 2),
    eff_gb_s=round(gb / dt, 1))), flush=True)
"""


def run(mode, n, reps=8):
    code = WORKER % dict(repo=REPO, mode=mode, n=n, reps=reps)
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=1200, cwd=REPO)
    except subprocess.TimeoutExpired:
        print(f"[probe] TIMEOUT mode={mode}", flush=True)
        return None
    for line in r.stdout.splitlines():
        if line.startswith("[probe-result]"):
            print(line, flush=True)
            return json.loads(line[len("[probe-result]"):])
    print(f"[probe] FAILED mode={mode}: {r.stdout[-400:]} "
          f"{r.stderr[-1500:]}", flush=True)
    return None


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 28
    for mode in ("alias", "noalias", "parallel"):
        run(mode, n)


if __name__ == "__main__":
    main()
