#!/usr/bin/env bash
# Run the project's static analysis: quest-lint (the project-invariant
# analyzer, docs/ANALYSIS.md) plus ruff's errors-only baseline
# ([tool.ruff] in pyproject.toml). Exits non-zero on any violation.
# ruff is optional tooling — environments without it (the TPU container
# bakes only the jax toolchain) still get the quest-lint half, and
# tests/test_lint.py skips its ruff case with the same probe.
set -u
cd "$(dirname "$0")/.."

rc=0

# extra flags pass straight through to the analyzer:
#   scripts/lint.sh --rules QL005,QL007 --format json
echo "== quest-lint (python -m quest_tpu.analysis) =="
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m quest_tpu.analysis quest_tpu/ scripts/ tests/ "$@" || rc=1

echo "== ruff (errors-only baseline) =="
if command -v ruff >/dev/null 2>&1; then
    ruff check quest_tpu scripts tests || rc=1
else
    echo "ruff not installed; skipping (pip install ruff to enable)"
fi

exit $rc
