"""Pretty-print a serving metrics snapshot (quest_tpu.serve.metrics).

Reads one `metrics.snapshot()` dict — the stable JSON schema
{"counters": {...}, "histograms": {name: {count, mean, p50, p95, p99}}}
— OR a Prometheus text-format scrape (what `Registry.scrape()` /
`python -m quest_tpu.serve.metrics --port` emit: the input is parsed
as JSON first, then as scrape text) — and renders aligned tables.
Sources, in order:

    python scripts/serve_stats.py snapshot.json    # a dumped snapshot
    curl -s localhost:9464/metrics | python scripts/serve_stats.py -
    some-producer | python scripts/serve_stats.py -  # JSON on stdin
    python scripts/serve_stats.py --demo           # run a tiny in-process
                                                   # serve workload and
                                                   # print ITS snapshot

The demo is the zero-to-aha path (no TPU needed: interpret-mode
kernels): it spins a ServeEngine, pushes a few dozen coalescing
requests through, and prints what a serving dashboard would scrape —
see docs/SERVING.md for the metric meanings.

Latency histograms (`*_s` suffix) render in milliseconds; occupancy
and other unitless histograms render as-is. Fleet runs (ServeFleet,
docs/SERVING.md §fleet) get their own fleet/tenant section whenever
any fleet_/tenant_/shed_ series is present.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _fmt(name: str, v: float) -> str:
    if name.endswith("_s"):
        return f"{v * 1e3:10.3f}"
    return f"{v:10.4f}"


# the resilience counters/gauges (docs/RESILIENCE.md) get their own
# section: on a healthy engine they are all zero and an operator wants
# that fact visible at a glance, not buried alphabetically
_RESILIENCE = ("serve_worker_restarts", "serve_faults_injected",
               "serve_launch_failures", "serve_batches_split",
               "serve_requests_failed", "serve_demux_failures",
               "serve_degraded_dispatches", "serve_breaker_opens",
               "serve_breaker_probes", "serve_breaker_closes",
               "serve_breakers_open")

# the fleet/tenant metrics (docs/SERVING.md §fleet) get their own
# section whenever any fleet_/tenant_/shed_ series is present: routing
# health, failover activity and shed pressure are the figures a fleet
# operator reads first
_FLEET = ("fleet_replicas", "fleet_replicas_healthy", "fleet_pressure",
          "fleet_requests_routed", "fleet_affinity_hits",
          "fleet_affinity_spills", "fleet_failovers",
          "fleet_requeued_requests", "fleet_durable_jobs",
          "shed_requests", "shed_evictions", "tenant_quota_rejections")


def render(snap: dict, out=sys.stdout) -> None:
    counters = snap.get("counters", {})
    gauges = snap.get("gauges", {})
    histograms = snap.get("histograms", {})
    if counters:
        w = max(len(n) for n in counters)
        print("counters", file=out)
        for n in sorted(counters):
            print(f"  {n:<{w}}  {counters[n]}", file=out)
    if gauges:
        w = max(len(n) for n in gauges)
        print("gauges", file=out)
        for n in sorted(gauges):
            print(f"  {n:<{w}}  {gauges[n]:g}", file=out)
    if counters or gauges:
        vals = {**counters, **gauges}
        w = max(len(n) for n in _RESILIENCE)
        print("resilience (docs/RESILIENCE.md; healthy = all zero)",
              file=out)
        for n in _RESILIENCE:
            print(f"  {n:<{w}}  {vals.get(n, 0):g}", file=out)
        fleet_present = any(n.startswith(("fleet_", "tenant_", "shed_"))
                            for n in vals)
        if fleet_present:
            w = max(len(n) for n in _FLEET)
            print("fleet/tenant (docs/SERVING.md §fleet)", file=out)
            for n in _FLEET:
                print(f"  {n:<{w}}  {vals.get(n, 0):g}", file=out)
            extras = sorted(n for n in vals
                            if n.startswith(("shed_requests_p",
                                             "tenant_pending_")))
            for n in extras:
                print(f"  {n:<{w}}  {vals[n]:g}", file=out)
    if histograms:
        w = max(len(n) for n in histograms)
        unit = "ms for *_s"
        print(f"histograms (count / mean / p50 / p95 / p99; {unit})",
              file=out)
        print(f"  {'':<{w}}  {'count':>8} {'mean':>10} {'p50':>10} "
              f"{'p95':>10} {'p99':>10}", file=out)
        for n in sorted(histograms):
            h = histograms[n]
            row = " ".join(_fmt(n, h[k]) for k in ("mean", "p50",
                                                   "p95", "p99"))
            print(f"  {n:<{w}}  {h['count']:>8} {row}", file=out)
    if not counters and not histograms:
        print("(empty snapshot)", file=out)


def _demo_snapshot() -> dict:
    import numpy as np

    from quest_tpu.circuit import Circuit
    from quest_tpu.serve import ServeEngine, metrics, warmup

    n = 6
    c = Circuit(n)
    for q in range(n):
        c.h(q)
    c.cnot(0, 1).rz(2, 0.25)
    rng = np.random.default_rng(0)
    states = rng.standard_normal((32, 2, 1 << n)).astype(np.float32)
    states /= np.sqrt((states ** 2).sum(axis=(1, 2), keepdims=True))
    reg = metrics.Registry()
    with ServeEngine(max_wait_ms=5, max_batch=8, registry=reg) as eng:
        warmup(eng, [c], buckets=[8])
        for f in [eng.submit(c, state=s) for s in states]:
            f.result(timeout=300)
    return reg.snapshot()


def _load_snapshot(text: str) -> dict:
    """JSON snapshot or Prometheus scrape text — both render the same.
    JSON is tried first (every snapshot starts with '{'); anything else
    goes through metrics.parse_scrape, which raises loudly on input
    that is neither."""
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        from quest_tpu.serve import metrics
        return metrics.parse_scrape(text)


def main(argv) -> int:
    if argv and argv[0] == "--demo":
        render(_demo_snapshot())
        return 0
    if not argv or argv[0] == "-":
        snap = _load_snapshot(sys.stdin.read())
    else:
        with open(argv[0]) as f:
            snap = _load_snapshot(f.read())
    render(snap)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
