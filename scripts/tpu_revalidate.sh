#!/usr/bin/env bash
# One-shot revalidation after TPU access returns (the axon tunnel drops
# occasionally): on-chip certification sweep, the headline bench, and the
# 30q RCS wall-clock, in the order that surfaces failures fastest.
# Smoke-test measurements ([smoke-metric] lines) are teed into
# benchmarks/oncip_certification.log as round evidence.
#
# The tunnel can die MID-RUN (observed round 3: the relay exited between
# the prewarm and profile stages, and the profile silently fell back to a
# useless 40-min host-CPU run). Every stage is preceded by a cheap
# relay-port check, and a stage FAILURE re-checks the port to tell a real
# failure (exit 1) from a mid-stage drop (exit 2, retryable): the watcher
# (scripts/tunnel_watch.sh) re-runs on exit 2 and stops on exit 1.
set -uo pipefail
cd "$(dirname "$0")/.."
. scripts/tunnel_lib.sh

require_tunnel() {
    if ! tunnel_up; then
        echo "TUNNEL DROPPED before stage '$1' (relay port $AXON_PORT dead); aborting for retry"
        exit 2
    fi
}

# A failed stage is only a REAL failure if the CHIP survived it: a relay
# that died mid-stage, or a relay whose port still answers while the
# backend lease is gone (port-up-but-chip-dead — the state tunnel_up
# cannot see), both make the stage error retryable (exit 2) so the
# watcher keeps using future uptime windows.
fail_stage() {
    if ! tunnel_up; then
        echo "stage '$1' failed AND tunnel is down -> treating as mid-stage drop; aborting for retry"
        exit 2
    fi
    if ! probe_tpu 120; then
        echo "stage '$1' failed with the relay port open but no live accelerator behind it -> retryable outage"
        exit 2
    fi
    echo "stage '$1' failed with the chip still live -> real failure"
    exit 1
}

echo "== devices =="
require_tunnel devices
# the probe must see a real accelerator: a CPU-fallback jax prints
# CpuDevice and exits 0, which would run the whole ~2 h suite on host CPU.
# A failed INITIAL probe is always a retryable outage (it IS the liveness
# check — routing it through fail_stage could re-probe successfully and
# then exit 1, permanently stopping the watcher on a transient).
probe_tpu 300 || { echo "initial accelerator probe failed; retrying later"; exit 2; }

echo "== pre-warm persistent compile cache =="
require_tunnel prewarm
timeout 2400 python scripts/tpu_prewarm.py || echo "prewarm incomplete (continuing)"

echo "== compile-latency profile (cold vs warm) =="
require_tunnel profile
# port-up-but-chip-dead would run the whole profile on host CPU ('|| true'
# swallows everything); require a live accelerator before spending 2400 s
probe_tpu 120 || { echo "chip not live before profile stage"; exit 2; }
timeout 2400 python scripts/profile_compile.py 30 20 || true
require_tunnel profile-warm
timeout 600 python scripts/profile_compile.py 30 20 || true

echo "== on-chip certification sweep (tests/test_tpu_smoke.py) =="
require_tunnel smoke
# metrics are collected via QUEST_METRICS_FILE, NOT the captured stream:
# pytest's fd-level capture swallows stderr from PASSING tests, so a
# fully green sweep would leave zero [smoke-metric] lines in the tee
# (bit in r3 — the evidence gate failed a perfect run)
METRICS_FILE=/tmp/tpu_smoke_metrics.log
: > "$METRICS_FILE"
QUEST_METRICS_FILE="$METRICS_FILE" QUEST_TEST_PLATFORM=axon \
    timeout 3000 python -m pytest tests/test_tpu_smoke.py -q 2>&1 \
    | tee /tmp/tpu_smoke_out.log || fail_stage smoke
# a CPU-fallback run SKIPS every test and still exits 0; require real
# on-chip evidence before touching the certification log, and never
# truncate previously captured evidence with an empty file
if ! grep -q "smoke-metric" "$METRICS_FILE"; then
    echo "smoke run produced no [smoke-metric] evidence (CPU fallback or all skipped)"
    fail_stage smoke-evidence
fi
grep "smoke-metric" "$METRICS_FILE" > benchmarks/oncip_certification.log

echo "== headline bench =="
require_tunnel bench
timeout 1800 python bench.py | tee /tmp/bench_out.json || fail_stage bench
# a backend death mid-run leaves the relay port open while bench degrades
# loudly-but-successfully to host CPU; its JSON labels the platform —
# require on-chip evidence, don't let a CPU number close the stage
if grep -q '(cpu)' /tmp/bench_out.json; then
    echo "bench ran on host CPU fallback, not the chip"
    fail_stage bench-evidence
fi

echo "== 30q depth-20 RCS wall-clock (benchmarks/run.py rcs) =="
require_tunnel rcs
timeout 1800 python -u benchmarks/run.py rcs | tee /tmp/rcs_out.json || fail_stage rcs
if ! grep -q '"platform": "\(tpu\|axon\)"' /tmp/rcs_out.json; then
    echo "rcs produced no on-chip evidence (platform != tpu/axon)"
    fail_stage rcs-evidence
fi

echo "== revalidation COMPLETE =="
