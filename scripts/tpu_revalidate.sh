#!/usr/bin/env bash
# One-shot revalidation after TPU access returns (the axon tunnel drops
# occasionally): on-chip certification sweep, the headline bench, and the
# 30q RCS wall-clock, in the order that surfaces failures fastest.
# Smoke-test measurements ([smoke-metric] lines) are teed into
# benchmarks/oncip_certification.log as round evidence.
set -uo pipefail
cd "$(dirname "$0")/.."

echo "== devices =="
timeout 300 python -c "import jax; print(jax.devices())" || {
    echo "TPU still unreachable"; exit 1; }

echo "== pre-warm persistent compile cache =="
timeout 2400 python scripts/tpu_prewarm.py || echo "prewarm incomplete (continuing)"

echo "== compile-latency profile (cold vs warm) =="
timeout 2400 python scripts/profile_compile.py 30 20 || true
timeout 600 python scripts/profile_compile.py 30 20 || true

echo "== on-chip certification sweep (tests/test_tpu_smoke.py) =="
QUEST_TEST_PLATFORM=axon timeout 3000 python -m pytest tests/test_tpu_smoke.py -q 2>&1 \
    | tee /tmp/tpu_smoke_out.log || exit 1
grep "smoke-metric" /tmp/tpu_smoke_out.log > benchmarks/oncip_certification.log || true

echo "== headline bench =="
timeout 1800 python bench.py || exit 1

echo "== 30q depth-20 RCS wall-clock (benchmarks/run.py rcs) =="
timeout 1800 python -u benchmarks/run.py rcs || exit 1
