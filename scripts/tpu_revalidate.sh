#!/usr/bin/env bash
# One-shot revalidation after TPU access returns (the axon tunnel drops
# occasionally): on-chip certification sweep, the headline bench, and the
# 30q RCS wall-clock, in the order that surfaces failures fastest.
# Smoke-test measurements ([smoke-metric] lines) are teed into
# benchmarks/oncip_certification.log as round evidence.
#
# The tunnel can die MID-RUN (observed round 3: the relay exited between
# the prewarm and profile stages, and the profile silently fell back to a
# useless 40-min host-CPU run). Every stage is preceded by a cheap
# relay-port check, and a stage FAILURE re-checks the port to tell a real
# failure (exit 1) from a mid-stage drop (exit 2, retryable): the watcher
# (scripts/tunnel_watch.sh) re-runs on exit 2 and stops on exit 1.
set -uo pipefail
cd "$(dirname "$0")/.."
. scripts/tunnel_lib.sh

require_tunnel() {
    if ! tunnel_up; then
        echo "TUNNEL DROPPED before stage '$1' (relay port $AXON_PORT dead); aborting for retry"
        exit 2
    fi
}

# A failed stage is only a REAL failure if the CHIP survived it: a relay
# that died mid-stage, or a relay whose port still answers while the
# backend lease is gone (port-up-but-chip-dead — the state tunnel_up
# cannot see), both make the stage error retryable (exit 2) so the
# watcher keeps using future uptime windows.
fail_stage() {
    if ! tunnel_up; then
        echo "stage '$1' failed AND tunnel is down -> treating as mid-stage drop; aborting for retry"
        exit 2
    fi
    if ! probe_tpu 120; then
        echo "stage '$1' failed with the relay port open but no live accelerator behind it -> retryable outage"
        exit 2
    fi
    echo "stage '$1' failed with the chip still live -> real failure"
    exit 1
}

echo "== devices =="
require_tunnel devices
# the probe must see a real accelerator: a CPU-fallback jax prints
# CpuDevice and exits 0, which would run the whole ~2 h suite on host CPU.
# A failed INITIAL probe is always a retryable outage (it IS the liveness
# check — routing it through fail_stage could re-probe successfully and
# then exit 1, permanently stopping the watcher on a transient).
probe_tpu 300 || { echo "initial accelerator probe failed; retrying later"; exit 2; }

echo "== pre-warm persistent compile cache =="
require_tunnel prewarm
timeout 2400 python scripts/tpu_prewarm.py || echo "prewarm incomplete (continuing)"

echo "== compile-latency profile (cold vs warm) =="
require_tunnel profile
# port-up-but-chip-dead would run the whole profile on host CPU ('|| true'
# swallows everything); require a live accelerator before spending 2400 s
probe_tpu 120 || { echo "chip not live before profile stage"; exit 2; }
timeout 2400 python scripts/profile_compile.py 30 20 || true
require_tunnel profile-warm
timeout 600 python scripts/profile_compile.py 30 20 || true

echo "== on-chip certification sweep (tests/test_tpu_smoke.py) =="
require_tunnel smoke
# metrics are collected via QUEST_METRICS_FILE, NOT the captured stream:
# pytest's fd-level capture swallows stderr from PASSING tests, so a
# fully green sweep would leave zero [smoke-metric] lines in the tee
# (bit in r3 — the evidence gate failed a perfect run)
METRICS_FILE=/tmp/tpu_smoke_metrics.log
: > "$METRICS_FILE"
QUEST_METRICS_FILE="$METRICS_FILE" QUEST_TEST_PLATFORM=axon \
    timeout 3000 python -m pytest tests/test_tpu_smoke.py -q 2>&1 \
    | tee /tmp/tpu_smoke_out.log || fail_stage smoke
# a CPU-fallback run SKIPS every test and still exits 0; require real
# on-chip evidence before touching the certification log, and never
# truncate previously captured evidence with an empty file
if ! grep -q "smoke-metric" "$METRICS_FILE"; then
    echo "smoke run produced no [smoke-metric] evidence (CPU fallback or all skipped)"
    fail_stage smoke-evidence
fi
grep "smoke-metric" "$METRICS_FILE" > benchmarks/oncip_certification.log

echo "== headline bench =="
require_tunnel bench
timeout 1800 python bench.py | tee /tmp/bench_out.json || fail_stage bench
# a backend death mid-run leaves the relay port open while bench degrades
# loudly-but-successfully to host CPU; its JSON labels the platform —
# require on-chip evidence, don't let a CPU number close the stage
if grep -q '(cpu)' /tmp/bench_out.json; then
    echo "bench ran on host CPU fallback, not the chip"
    fail_stage bench-evidence
fi

echo "== 30q depth-20 RCS wall-clock (benchmarks/run.py rcs) =="
require_tunnel rcs
timeout 1800 python -u benchmarks/run.py rcs | tee /tmp/rcs_out.json || fail_stage rcs
if ! grep -q '"platform": "\(tpu\|axon\)"' /tmp/rcs_out.json; then
    echo "rcs produced no on-chip evidence (platform != tpu/axon)"
    fail_stage rcs-evidence
fi

echo "== fused-scan path (QUEST_FUSED_SCAN=1 vs baseline amplitudes) =="
# the executed lax.scan segment path cannot run in interpret mode (its
# compile explodes on CPU — circuit.py make_scan_applier docstring), so
# its ONLY validation is here on silicon: same circuit with and without
# the flag must agree amplitude-for-amplitude
require_tunnel fused-scan
timeout 1800 python - << 'PYEOF' || fail_stage fused-scan
import os, subprocess, sys, json, tempfile

CHILD = r'''
import os, sys, json
import numpy as np
import quest_tpu as qt
from quest_tpu.circuit import Circuit, flatten_ops
from quest_tpu.ops import fusion as F
from quest_tpu.ops import pallas_band as PB
from quest_tpu.state import to_dense

# phase-heavy circuit: identical consecutive 32-PhaseStage segments, the
# scan-eligible shape (QFT only produces such runs at 30q; this builds
# the same structure cheaply at 20q)
n = 20
rng = np.random.default_rng(4)
c = Circuit(n)
for _ in range(200):
    a, b = rng.choice(n, size=2, replace=False)
    c.cphase(float(rng.uniform(0, 6.28)), int(a), int(b))
parts = PB.segment_plan(
    F.plan(flatten_ops(c.ops, n, False), n, bands=PB.plan_bands(n)), n)
sigs = [tuple(p[1]) for p in parts if p[0] == "segment"]
run = best = 1
for x, y in zip(sigs, sigs[1:]):
    run = run + 1 if x == y else 1
    best = max(best, run)
assert best >= 3, f"plan lost its scan-eligible run (best={best})"
q = qt.init_debug_state(qt.create_qureg(n))
v = to_dense(c.apply_fused(q))
np.save(sys.argv[1], np.stack([v.real, v.imag]))
print(json.dumps({"platform": __import__("jax").devices()[0].platform}))
'''
outs = {}
for flag in ("0", "1"):
    env = dict(os.environ); env["QUEST_FUSED_SCAN"] = flag
    path = tempfile.mktemp(suffix=".npy")
    r = subprocess.run([sys.executable, "-c", CHILD, path],
                       capture_output=True, text=True, env=env, timeout=1700)
    assert r.returncode == 0, r.stderr[-2000:]
    assert '"platform"' in r.stdout and ("axon" in r.stdout or "tpu" in r.stdout), r.stdout[-200:]
    import numpy as np
    outs[flag] = np.load(path)
d = float(abs(outs["0"] - outs["1"]).max())
print(f"fused-scan maxdiff {d}")
assert d < 1e-5, d
PYEOF

echo "== revalidation COMPLETE =="

# ---- round-4 probes: f64 ceiling (VERDICT r3 item 4) and the
# per-kernel vs per-byte relay-cost experiment (item 5). A probe whose
# failure coincides with a DEAD tunnel exits 2 so the watcher re-runs
# the next uptime window (the resume contract the core stages use); a
# probe failing WITH the tunnel up is a real failure — logged, not
# looped on, and it does not un-complete the core revalidation above.
run_probe() {
    name="$1"; shift
    require_tunnel "probe-$name"
    echo "== probe: $name ($*) =="
    if ! timeout 3600 python "$@" | tee "/tmp/probe_${name}.out"; then
        if ! tunnel_up; then
            echo "probe $name lost the tunnel; resuming next window"
            exit 2
        fi
        echo "probe $name FAILED with the tunnel up (real failure; logged)"
    fi
}
run_probe f64 scripts/probe_f64.py 28
run_probe cold-start scripts/probe_cold_start.py 26 24
run_probe stage-report -m quest_tpu.profiling --n 30
