#!/usr/bin/env bash
# One-shot revalidation after TPU access returns (the axon tunnel drops
# occasionally): on-chip certification sweep, the headline bench, and the
# 30q RCS wall-clock, in the order that surfaces failures fastest.
# Smoke-test measurements ([smoke-metric] lines) are teed into
# benchmarks/oncip_certification.log as round evidence.
#
# The tunnel can die MID-RUN (observed round 3: the relay exited between
# the prewarm and profile stages, and the profile silently fell back to a
# useless 40-min host-CPU run). Every stage is preceded by a cheap
# relay-port check, and a stage FAILURE re-checks the port to tell a real
# failure (exit 1) from a mid-stage drop (exit 2, retryable): the watcher
# (scripts/tunnel_watch.sh) re-runs on exit 2 and stops on exit 1.
set -uo pipefail
cd "$(dirname "$0")/.."
. scripts/tunnel_lib.sh

require_tunnel() {
    if ! tunnel_up; then
        echo "TUNNEL DROPPED before stage '$1' (relay port $AXON_PORT dead); aborting for retry"
        exit 2
    fi
}

# A failed stage is only a REAL failure if the tunnel survived it; a relay
# that died mid-stage makes any stage error retryable (exit 2).
fail_stage() {
    if ! tunnel_up; then
        echo "stage '$1' failed AND tunnel is down -> treating as mid-stage drop; aborting for retry"
        exit 2
    fi
    echo "stage '$1' failed with the tunnel still up -> real failure"
    exit 1
}

echo "== devices =="
require_tunnel devices
# the probe must see a real accelerator: a CPU-fallback jax prints
# CpuDevice and exits 0, which would run the whole ~2 h suite on host CPU
probe_tpu 300 || fail_stage devices

echo "== pre-warm persistent compile cache =="
require_tunnel prewarm
timeout 2400 python scripts/tpu_prewarm.py || echo "prewarm incomplete (continuing)"

echo "== compile-latency profile (cold vs warm) =="
require_tunnel profile
timeout 2400 python scripts/profile_compile.py 30 20 || true
require_tunnel profile-warm
timeout 600 python scripts/profile_compile.py 30 20 || true

echo "== on-chip certification sweep (tests/test_tpu_smoke.py) =="
require_tunnel smoke
QUEST_TEST_PLATFORM=axon timeout 3000 python -m pytest tests/test_tpu_smoke.py -q 2>&1 \
    | tee /tmp/tpu_smoke_out.log || fail_stage smoke
# a CPU-fallback run SKIPS every test and still exits 0; require real
# on-chip evidence before touching the certification log, and never
# truncate previously captured evidence with an empty file
if ! grep -q "smoke-metric" /tmp/tpu_smoke_out.log; then
    echo "smoke run produced no [smoke-metric] evidence (CPU fallback or all skipped)"
    fail_stage smoke-evidence
fi
grep "smoke-metric" /tmp/tpu_smoke_out.log > benchmarks/oncip_certification.log

echo "== headline bench =="
require_tunnel bench
timeout 1800 python bench.py || fail_stage bench

echo "== 30q depth-20 RCS wall-clock (benchmarks/run.py rcs) =="
require_tunnel rcs
timeout 1800 python -u benchmarks/run.py rcs || fail_stage rcs

echo "== revalidation COMPLETE =="
