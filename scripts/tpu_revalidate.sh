#!/usr/bin/env bash
# One-shot revalidation after TPU access returns (the axon tunnel drops
# occasionally): on-chip smoke tests, the headline bench, and the 30q
# RCS wall-clock, in the order that surfaces failures fastest.
set -uo pipefail
cd "$(dirname "$0")/.."

echo "== devices =="
timeout 300 python -c "import jax; print(jax.devices())" || {
    echo "TPU still unreachable"; exit 1; }

echo "== on-chip smoke tests =="
QUEST_TEST_PLATFORM=axon timeout 1500 python -m pytest tests/test_tpu_smoke.py -q || exit 1

echo "== headline bench =="
timeout 1500 python bench.py || exit 1

echo "== 30q depth-20 RCS wall-clock (benchmarks/run.py rcs) =="
timeout 1500 python -u benchmarks/run.py rcs || exit 1
