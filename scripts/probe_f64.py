"""On-chip probe: what can double precision actually run at on a v5e?

TPU v5e has no f64 vector hardware; XLA emulates f64 in software. This
probe measures every candidate path for the reference-default-precision
story (VERDICT r3 item 4: f64 @28q >= 30 gates/s, or a measured
impossibility argument in docs/PRECISION.md):

  raw-mul     a donated elementwise f64 multiply over the 28q state —
              the emulation's streaming floor (compare f32's 461 GB/s)
  raw-dot     one f64 (rows,128)@(128,128) band contraction — the MXU
              has no f64 path at all, so this is the software wall that
              makes the banded engine 9 gates/s
  pergate     the per-gate XLA engine on complex128 (elementwise
              butterflies, NO dots) — the dot-free route
  banded      the banded engine on complex128 — since round 5 its band
              contractions ride the MXU as exact-integer limb dots
              (ops/apply.py _limb_band_contract), the candidate that
              should clear the 30 gates/s bar
  banded-native  the same engine with QUEST_F64_MXU=0 (software-f64
              dots, the pre-r5 9 gates/s wall) for the A/B

Each case runs in a subprocess. Usage: python scripts/probe_f64.py [n]
"""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import json, sys, time
sys.path.insert(0, %(repo)r)
from quest_tpu.precision import enable_compile_cache
enable_compile_cache()
import jax
jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp
import numpy as np

mode = %(mode)r
n = %(n)d
reps = %(reps)d

import os
if mode == "banded-native":
    os.environ["QUEST_F64_MXU"] = "0"   # the pre-r5 emulated-f64 path

if mode in ("raw-mul", "raw-dot"):
    x = jnp.zeros((2, 1 << n), dtype=jnp.float64)

    if mode == "raw-mul":
        fn = jax.jit(lambda a: a * 1.000000001, donate_argnums=(0,))
    else:
        g = jnp.eye(128, dtype=jnp.float64)

        def dot(a):
            v = a.reshape(2, -1, 128)
            return jnp.einsum("prl,lk->prk", v, g,
                              precision=jax.lax.Precision.HIGHEST
                              ).reshape(2, -1)
        fn = jax.jit(dot, donate_argnums=(0,))
    x = fn(x); _ = np.asarray(x[0, :4])
    t0 = time.perf_counter()
    for _ in range(reps):
        x = fn(x)
    _ = np.asarray(x[0, :4])
    dt = (time.perf_counter() - t0) / reps
    gb = 2 * 2 * (1 << n) * 8 / 2**30
    print("[probe-result] " + json.dumps(dict(
        mode=mode, n=n, ms=round(dt * 1e3, 2),
        eff_gb_s=round(gb / dt, 1))), flush=True)
else:
    from quest_tpu.circuit import Circuit
    rng = np.random.default_rng(42)
    c = Circuit(n)
    for i in range(16):
        c.rx(1 + i %% (n - 1), float(rng.uniform(0, 2 * np.pi)))
    iters = 4
    if mode == "pergate":
        step = c.compiled(n, density=False, donate=True, iters=iters)
    else:
        # 'banded' now rides the MXU limb scheme by default on TPU
        # (ops/apply.py _limb_band_contract); 'banded-native' pins the
        # old software-f64 dot for the A/B
        step = c.compiled_banded(n, density=False, donate=True, iters=iters)
    amps = jnp.zeros((2, 1 << n), dtype=jnp.float64).at[0, 0].set(1.0)
    amps = step(amps)
    _ = np.asarray(amps[0, :4])
    t0 = time.perf_counter()
    for _ in range(reps):
        amps = step(amps)
    _ = np.asarray(amps[0, :4])
    dt = (time.perf_counter() - t0) / reps
    print("[probe-result] " + json.dumps(dict(
        mode=mode, n=n, ms_per_gate=round(dt / iters / 16 * 1e3, 2),
        gates_per_sec=round(16 * iters / dt, 1))), flush=True)
"""


def run(mode, n, reps=4):
    code = WORKER % dict(repo=REPO, mode=mode, n=n, reps=reps)
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=2400, cwd=REPO)
    except subprocess.TimeoutExpired:
        print(f"[probe] TIMEOUT mode={mode}", flush=True)
        return None
    for line in r.stdout.splitlines():
        if line.startswith("[probe-result]"):
            print(line, flush=True)
            return json.loads(line[len("[probe-result]"):])
    print(f"[probe] FAILED mode={mode}: {r.stdout[-300:]} "
          f"{r.stderr[-1200:]}", flush=True)
    return None


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 28
    for mode in ("raw-mul", "pergate", "banded", "banded-native",
                 "raw-dot"):
        run(mode, n)


if __name__ == "__main__":
    main()
