"""Stage-count sweep: is a stacked segment max(DMA, compute) or
DMA + compute?

Time segments of k identical b0 stages (identity values) for
k = 0..8 at fixed geometry. The k=0 point (one PhaseStage, ~free) is
the pure-DMA floor. If the curve is flat until k*dot > DMA then linear
with slope = dot cost, the pipeline overlaps; if it is linear from
k=1 with intercept = DMA floor, compute and DMA serialize and manual
multi-buffering is worth building.

Also sweeps the same ladder with 3 scattered bits claimed (the bench
segment's DMA pattern: 8-strip gathers) to separate gather cost from
overlap behavior.

Usage: python scripts/probe_stack.py [n]   (default 28)
"""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import json, sys, time
sys.path.insert(0, %(repo)r)
from quest_tpu.precision import enable_compile_cache
enable_compile_cache()
import jax
import jax.numpy as jnp
import numpy as np

n = %(n)d
k = %(k)d
scat = %(scat)d
reps = %(reps)d

from quest_tpu.ops import pallas_band as PB

stages, arrays = [], []
if scat:
    # claim top scattered bits with a cheap sc butterfly so the DMA
    # pattern matches the bench segment's strip gathers
    g2 = np.zeros((2, 2, 2), np.float32); g2[0] = np.eye(2)
    for j in range(scat):
        stages.append(PB.MatStage(kind="sc", bit=n - 8 - j, dim=2,
                                  real_only=False, lane_preds=(),
                                  row_preds=()))
        arrays.append(jnp.asarray(g2))
if k == 0:
    stages.append(PB.PhaseStage())
    arrays.append(jnp.asarray(np.zeros((1, 8), np.float32)))
else:
    g128 = np.zeros((2, 128, 128), np.float32); g128[0] = np.eye(128)
    for _ in range(k):
        stages.append(PB.MatStage(kind="b0", dim=128, real_only=False,
                                  lane_preds=(), row_preds=()))
        arrays.append(jnp.asarray(g128))

fn = PB.compile_segment(stages, n)
jfn = jax.jit(lambda a: fn(a, arrays), donate_argnums=(0,))
from quest_tpu.state import basis_planes, fused_state_shape
amps = basis_planes(0, n=n, rdt=jnp.float32, shape=fused_state_shape(n))
amps = jfn(amps)
_ = np.asarray(amps[0, 0, :4])
t0 = time.perf_counter()
for _ in range(reps):
    amps = jfn(amps)
_ = np.asarray(amps[0, 0, :4])
dt = (time.perf_counter() - t0) / reps
gb = 2 * 2 * (1 << n) * 4 / 2**30
print("[probe-result] " + json.dumps(dict(
    k=k, scat=scat, n=n, ms=round(dt * 1e3, 2),
    eff_gb_s=round(gb / dt, 1))), flush=True)
"""


def run(n, k, scat, reps=8):
    code = WORKER % dict(repo=REPO, n=n, k=k, scat=scat, reps=reps)
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=1200, cwd=REPO)
    except subprocess.TimeoutExpired:
        print(f"[probe] TIMEOUT k={k} scat={scat}", flush=True)
        return None
    for line in r.stdout.splitlines():
        if line.startswith("[probe-result]"):
            print(line, flush=True)
            return json.loads(line[len("[probe-result]"):])
    print(f"[probe] FAILED k={k} scat={scat}: {r.stdout[-300:]} "
          f"{r.stderr[-1200:]}", flush=True)
    return None


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 28
    for scat in (0, 3):
        for k in (0, 1, 2, 4, 8):
            run(n, k, scat)


if __name__ == "__main__":
    main()
