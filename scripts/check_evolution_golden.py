#!/usr/bin/env python
"""CI fast-fail gate for the Trotter-evolution workload
(docs/EVOLUTION.md): fails if the pooled emission regresses above the
committed golden sweep counts, if the fused-vs-per-term plan advantage
drops below 5x, if a short CPU quench's energy drift exceeds the
documented bound, or if the QUEST_TROTTER_FUSION=0 record stops
matching the legacy per-term emission model — all CPU-side through
`evolution.trotter_plan_stats` and a small real quench (the
check_expec_golden.py discipline; no chip).

Goldens: the 30q TFIM order-2 step lowers to at most 3 HBM sweeps on
the fused engine (one sublane-region sweep plus one per scattered
band — the same geometry floor QFT-30 meets at 6) vs >= 15 passes for
the per-term emission; a 20-step 8q quench at dt=0.05 conserves <H>
within bench.TROTTER_DRIFT_PER_TERM per term. The goldens live HERE
and are mirrored by the tier-1 assertions in tests/test_evolution.py;
a planner change that moves either must update both, consciously.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

TFIM30_GOLDEN_SWEEPS_PER_STEP = 3
TFIM30_MIN_BASELINE_PASSES = 15
MIN_PLAN_ADVANTAGE = 5
DRIFT_N, DRIFT_STEPS = 8, 20


def main() -> int:
    import numpy as np

    import bench
    from quest_tpu import evolution as EV
    from quest_tpu.ops import expec as E

    spec30 = E.PauliSum.of(*bench._build_tfim_sum(30), 30)
    fused = EV.trotter_plan_stats(spec30, bench.TROTTER_DT, order=2,
                                  steps=50)

    prior = os.environ.get("QUEST_TROTTER_FUSION")
    os.environ["QUEST_TROTTER_FUSION"] = "0"
    try:
        legacy = EV.trotter_plan_stats(spec30, bench.TROTTER_DT,
                                       order=2, steps=50)
    finally:
        if prior is None:
            del os.environ["QUEST_TROTTER_FUSION"]
        else:
            os.environ["QUEST_TROTTER_FUSION"] = prior

    # a real (tiny) quench: per-step energy drift vs the documented
    # bound — the contract the bench's trot_energy_drift key reports
    import quest_tpu as qt
    spec = E.PauliSum.of(*bench._build_tfim_sum(DRIFT_N), DRIFT_N)
    q0 = qt.init_plus_state(qt.create_qureg(DRIFT_N))
    res = EV.run_evolution(spec, bench.TROTTER_DT, DRIFT_STEPS,
                           state=q0, order=2, energy_every=5)
    drift = float(np.abs(res.energies[:, 0] - res.energies[0, 0]).max())
    drift_bound = bench.TROTTER_DRIFT_PER_TERM * len(spec.codes)

    rec = {
        "tfim30_hbm_sweeps_per_step": fused["hbm_sweeps_per_step"],
        "tfim30_baseline_hbm_sweeps_per_step":
            fused["baseline_hbm_sweeps_per_step"],
        "tfim30_diag_groups": fused["diag_groups"],
        "tfim30_frames": fused["frames"],
        "knob_off_hbm_sweeps_per_step": legacy["hbm_sweeps_per_step"],
        "quench_energy_drift": drift,
        "quench_energy_drift_bound": drift_bound,
    }
    print(json.dumps(rec))
    ok = True
    if fused["hbm_sweeps_per_step"] > TFIM30_GOLDEN_SWEEPS_PER_STEP:
        print(f"REGRESSION: TFIM-30 hbm_sweeps_per_step "
              f"{fused['hbm_sweeps_per_step']} > golden "
              f"{TFIM30_GOLDEN_SWEEPS_PER_STEP}", file=sys.stderr)
        ok = False
    if fused["baseline_hbm_sweeps_per_step"] < TFIM30_MIN_BASELINE_PASSES:
        print(f"MODEL DRIFT: per-term baseline "
              f"{fused['baseline_hbm_sweeps_per_step']} passes/step < "
              f"{TFIM30_MIN_BASELINE_PASSES} — the legacy model no "
              f"longer reflects one pass per term application",
              file=sys.stderr)
        ok = False
    if (fused["baseline_hbm_sweeps_per_step"]
            < MIN_PLAN_ADVANTAGE * max(fused["hbm_sweeps_per_step"], 1)):
        print(f"REGRESSION: fused-vs-per-term plan advantage below "
              f"{MIN_PLAN_ADVANTAGE}x", file=sys.stderr)
        ok = False
    if legacy["fusion"] or (legacy["hbm_sweeps_per_step"]
                            != legacy["baseline_hbm_sweeps_per_step"]):
        print("REGRESSION: QUEST_TROTTER_FUSION=0 record no longer "
              "reports the legacy per-term emission it dispatches",
              file=sys.stderr)
        ok = False
    if drift > drift_bound:
        print(f"REGRESSION: {DRIFT_STEPS}-step {DRIFT_N}q quench "
              f"energy drift {drift:.3e} > documented bound "
              f"{drift_bound:.3e} (docs/EVOLUTION.md §energy drift)",
              file=sys.stderr)
        ok = False
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
