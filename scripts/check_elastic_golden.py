#!/usr/bin/env python
"""CI gate for elastic durable resume + the dispatch watchdog
(docs/RESILIENCE.md §elastic / §watchdog): fails if

  * a 2dev-sharded chain preempted mid-run does NOT resume on 1 device
    to the exact native 1-device amplitudes (sha256 — the elastic
    contract on the mesh-portable circuit is BIT identity), or the
    1dev -> 2dev direction regresses;
  * digest re-verification on reshard breaks: a corrupted newest
    checkpoint must be SKIPPED (loudly, counted) in favor of the older
    one, still landing bit-identical — never consumed;
  * a mesh mismatch without elastic=True stops rejecting typed
    DurableError (elastic must stay opt-in);
  * the dispatch watchdog does not fail a wedged launch with typed
    DispatchTimeout within 2x QUEST_DISPATCH_TIMEOUT_S, or the engine
    cannot serve afterwards (the wedged worker must be REPLACED, not
    merely timed out).

The committed budgets live HERE; the per-path pins live in
tests/test_elastic.py — a change that moves either must update both,
consciously.
"""

import hashlib
import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=2"
                               ).strip()
# the mesh-portable circuit's discipline (bench._build_elastic_circuit):
# the scheduler's pooling re-merges its isolated rotations
os.environ["QUEST_SCHEDULE"] = "0"

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

WATCHDOG_S = 0.5           # deadline under test; gate bound is 2x + slack


def _sha(arr) -> str:
    import numpy as np
    return hashlib.sha256(np.ascontiguousarray(arr).tobytes()).hexdigest()


def main() -> int:
    import numpy as np
    import jax

    import bench
    import quest_tpu as qt
    from quest_tpu import checkpoint as ckpt
    from quest_tpu.parallel import make_amp_mesh, shard_qureg
    from quest_tpu.resilience import (DurableError, FaultPlan, faults,
                                      run_durable)
    from quest_tpu.serve import metrics

    n = 10
    c = bench._build_elastic_circuit(n)
    mesh = make_amp_mesh(2)

    def sv():
        base = np.zeros((2, 1 << n), dtype=np.float32)
        base[0, 0] = 1.0
        return qt.Qureg(amps=jax.numpy.asarray(base), num_qubits=n,
                        is_density=False)

    def amps(q):
        return np.asarray(jax.device_get(q.amps))

    def preempted(runner, after):
        plan = FaultPlan().inject("durable.preempt", after_n=after,
                                  times=1)
        with faults.active(plan):
            try:
                runner()
            except faults.InjectedFault:
                return True
        return False

    rec = {}
    ok = True
    with tempfile.TemporaryDirectory() as root:
        native1 = amps(run_durable(c, sv(), os.path.join(root, "r1"),
                                   every=3, engine="banded"))
        native2 = amps(run_durable(c, shard_qureg(sv(), mesh),
                                   os.path.join(root, "r2"), every=3,
                                   mesh=mesh))

        # -- 2dev -> 1dev ---------------------------------------------------
        d = os.path.join(root, "a")
        fired = preempted(
            lambda: run_durable(c, shard_qureg(sv(), mesh), d, every=3,
                                mesh=mesh), after=5)
        rec["elastic_preempt_fired"] = fired
        rec["elastic_stamped_before_kill"] = bool(ckpt.step_dirs(d))
        # without elastic: typed reject (never a silent restart)
        try:
            run_durable(c, sv(), d, every=3, engine="banded")
            rec["elastic_strict_rejects"] = False
        except DurableError:
            rec["elastic_strict_rejects"] = True
        out = run_durable(c, sv(), d, every=3, engine="banded",
                          elastic=True)
        rec["elastic_2to1_bitexact"] = _sha(amps(out)) == _sha(native1)
        rec["elastic_chain_consumed"] = ckpt.step_dirs(d) == []

        # -- 1dev -> 2dev ---------------------------------------------------
        d = os.path.join(root, "b")
        preempted(lambda: run_durable(c, sv(), d, every=3,
                                      engine="banded"), after=5)
        out = run_durable(c, shard_qureg(sv(), mesh), d, every=3,
                          mesh=mesh, elastic=True)
        rec["elastic_1to2_bitexact"] = _sha(amps(out)) == _sha(native2)

        # -- digest re-verification on reshard ------------------------------
        d = os.path.join(root, "c")
        c4 = bench._build_elastic_circuit(n, layers=4)
        native_c4 = amps(run_durable(c4, sv(), os.path.join(root, "rc"),
                                     every=2, engine="banded"))
        preempted(lambda: run_durable(c4, shard_qureg(sv(), mesh), d,
                                      every=2, mesh=mesh, keep=3),
                  after=9)
        dirs = ckpt.step_dirs(d)
        rec["elastic_fallback_available"] = len(dirs) >= 2
        path = os.path.join(dirs[-1][1], "amps.npz")
        blob = bytearray(open(path, "rb").read())
        blob[len(blob) // 2] ^= 0xFF
        open(path, "wb").write(bytes(blob))
        reg = metrics.Registry()
        out = run_durable(c4, sv(), d, every=2, engine="banded",
                          elastic=True, registry=reg)
        rec["elastic_corrupt_skipped"] = (
            reg.counter("durable_corrupt_checkpoints_skipped").value >= 1)
        rec["elastic_reshard_after_corrupt_bitexact"] = (
            _sha(amps(out)) == _sha(native_c4))

    # -- watchdog -----------------------------------------------------------
    from quest_tpu.circuit import Circuit
    from quest_tpu.serve.admission import DispatchTimeout
    from quest_tpu.serve.engine import ServeEngine

    cw = Circuit(4).h(0).cnot(0, 1)
    state = np.zeros((2, 16), dtype=np.float32)
    state[0, 0] = 1.0
    reg = metrics.Registry()
    with ServeEngine(max_wait_ms=1, registry=reg, backoff_base_s=0.0,
                     dispatch_timeout_s=WATCHDOG_S) as eng:
        eng.submit(cw, state=state).result(timeout=300)   # warm compile
        orig = eng._apply_program

        def wedged(q, b, rung):
            fn = orig(q, b, rung)

            def run(batch):
                time.sleep(30.0)
                return fn(batch)

            run.bucket = fn.bucket
            return run

        eng._apply_program = wedged
        t0 = time.monotonic()
        fut = eng.submit(cw, state=state)
        try:
            fut.result(timeout=10.0)
            rec["watchdog_fired_typed"] = False
        except DispatchTimeout:
            rec["watchdog_fired_typed"] = True
        except Exception:
            rec["watchdog_fired_typed"] = False
        dt = time.monotonic() - t0
        rec["watchdog_latency_s"] = round(dt, 3)
        rec["watchdog_within_2x"] = dt <= 2 * WATCHDOG_S + 0.25
        eng._apply_program = orig
        out = eng.submit(cw, state=state).result(timeout=300)
        rec["watchdog_engine_recovered"] = (
            np.asarray(out).shape == (2, 16))
        eng.drain(timeout_s=30.0)
    rec["watchdog_timeouts_counted"] = (
        reg.snapshot()["counters"].get("serve_dispatch_timeouts", 0) >= 1)

    print(json.dumps(rec))
    checks = {
        "elastic_preempt_fired": "the seeded preempt never fired — the "
                                 "scenario no longer exercises resume",
        "elastic_stamped_before_kill": "the kill landed before the "
                                       "first stamp — hollow restart",
        "elastic_strict_rejects": "mesh mismatch without elastic=True "
                                  "no longer rejects typed",
        "elastic_2to1_bitexact": "2dev->1dev elastic resume is NOT "
                                 "bit-identical to the native run",
        "elastic_chain_consumed": "completed elastic run left its chain",
        "elastic_1to2_bitexact": "1dev->2dev elastic resume is NOT "
                                 "bit-identical to the native run",
        "elastic_fallback_available": "scenario lost its older "
                                      "checkpoint — nothing to verify",
        "elastic_corrupt_skipped": "the corrupted checkpoint was not "
                                   "skipped (digest re-verification "
                                   "broken)",
        "elastic_reshard_after_corrupt_bitexact": "resume past the "
                                                  "corrupt checkpoint "
                                                  "diverged",
        "watchdog_fired_typed": "the wedged launch did not fail typed "
                                "DispatchTimeout",
        "watchdog_within_2x": "the watchdog took more than 2x the "
                              "deadline to fire",
        "watchdog_engine_recovered": "the engine could not serve after "
                                     "the wedge — worker not replaced",
        "watchdog_timeouts_counted": "serve_dispatch_timeouts metric "
                                     "not advanced",
    }
    for key, msg in checks.items():
        if not rec.get(key):
            print(f"REGRESSION: {msg}", file=sys.stderr)
            ok = False
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
