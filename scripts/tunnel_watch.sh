#!/usr/bin/env bash
# Watch for the axon TPU tunnel to come (back) up and run the on-chip
# revalidation when it does. The tunnel drops for hours at a time
# (rounds 2 and 3 both lost it mid-round); this loop turns "the tunnel
# happened to be up while someone was looking" into "any uptime window
# gets used".
#
#   nohup scripts/tunnel_watch.sh > /tmp/tunnel_watch.log 2>&1 &
#
# Exits after a COMPLETE revalidation (rc=0) or a real failure (rc=1,
# needs a human/agent — rerunning won't clear it). A mid-run tunnel drop
# (rc=2) goes back to watching for the next uptime window.
set -u
cd "$(dirname "$0")/.."
. scripts/tunnel_lib.sh
POLL_S="${QUEST_TUNNEL_POLL_S:-180}"

while :; do
    if tunnel_up; then
        # port answering is necessary, not sufficient — confirm the probe
        # reaches a real TPU (a CPU-fallback jax still prints devices,
        # which is exactly the silent-CPU-run this watcher must prevent)
        if probe_tpu 180; then
            echo "[watch] $(date -u +%H:%M:%S) tunnel live; running revalidation"
            bash scripts/tpu_revalidate.sh >> /tmp/revalidate_r3.log 2>&1
            rc=$?
            echo "[watch] $(date -u +%H:%M:%S) revalidation rc=$rc"
            [ "$rc" -eq 0 ] && exit 0
            if [ "$rc" -ne 2 ]; then
                # a non-tunnel failure (smoke test, bench) will not clear
                # by rerunning — don't burn the uptime window on repeats;
                # leave the log for a human/agent to investigate
                echo "[watch] deterministic failure (rc=$rc); exiting"
                exit "$rc"
            fi
        else
            echo "[watch] $(date -u +%H:%M:%S) port open but probe failed"
        fi
    else
        echo "[watch] $(date -u +%H:%M:%S) tunnel down (port $AXON_PORT)"
    fi
    sleep "$POLL_S"
done
