"""Stage-level Pallas kernel timing on the real chip: which stage type is
slow? Compiles tiny segments (b0 / b1 / b2 / parity / combinations) and
times each at the given size."""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")
from quest_tpu.precision import enable_compile_cache
enable_compile_cache()

from quest_tpu.ops import pallas_band as PB


def seg(stages, arrays, n, reps=20):
    fn = PB.compile_segment(stages, n)
    jfn = jax.jit(lambda a: fn(a, arrays), donate_argnums=(0,))
    amps = jnp.zeros((2, 1 << (n - 7), 128),
                     dtype=jnp.float32).at[0, 0, 0].set(1.0)
    amps = jfn(amps)
    _ = np.asarray(amps[0, 0, :4])
    t0 = time.perf_counter()
    for _ in range(reps):
        amps = jfn(amps)
    _ = np.asarray(amps[0, 0, :4])
    dt = (time.perf_counter() - t0) / reps
    bw = 2 * 2 * (1 << n) * 4 / dt
    return dt * 1e3, bw / 1e9


def g_input(d, real=False):
    rng = np.random.default_rng(d)
    q, _ = np.linalg.qr(rng.standard_normal((d, d)))
    gim = np.zeros_like(q) if real else q * 0.1
    return jnp.asarray(np.stack([q, gim]).astype(np.float32))


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 26
    print("devices:", jax.devices(), flush=True)
    hi_bit = n - 8  # a scattered (grid-range) row bit
    cases = {
        "b0 (complex)": ([PB.MatStage("b0", 128, False, (), ())],
                          [g_input(128)]),
        "b0 (real)": ([PB.MatStage("b0", 128, True, (), ())],
                       [g_input(128, real=True)]),
        "b1": ([PB.MatStage("b1", 128, False, (), ())], [g_input(128)]),
        "sc": ([PB.MatStage("sc", 2, False, (), (), hi_bit)],
               [g_input(2)]),
        "parity": ([PB.ParityStage()],
                   [jnp.asarray(np.array(
                       [[np.cos(0.15), np.sin(0.15),
                         (1 << 1) | (1 << 3),        # lane targets 1, 3
                         (1 << 2) | (1 << 12), 0,    # row targets 2, 12
                         0, 0, 0]], dtype=np.float32))]),
        "scb": ([PB.MatStage("scb", 128, False, (), (), n - 14)],
                [g_input(128)]),
        "b0+b1+sc": ([PB.MatStage("b0", 128, False, (), ()),
                      PB.MatStage("b1", 128, False, (), ()),
                      PB.MatStage("sc", 2, False, (), (), hi_bit)],
                     [g_input(128), g_input(128), g_input(2)]),
        "b0 x3": ([PB.MatStage("b0", 128, False, (), ())] * 3,
                  [g_input(128)] * 3),
    }
    for name, (stages, arrays) in cases.items():
        ms, bw = seg(stages, arrays, n)
        print(f"{name:14s}: {ms:7.2f} ms/pass   {bw:6.1f} GB/s r+w", flush=True)


if __name__ == "__main__":
    main()
