#!/usr/bin/env python
"""CI smoke gate for the batched execution engine (docs/BATCHING.md):
asserts — CPU-side, through pure host planning (Circuit.plan_stats /
trajectories.plan_stats, no compile, no chip) — that

  * a B=256 trajectory workload at n=20 plans the SAME hbm_sweeps as
    the unbatched (B=1) plan: launches do not scale with B;
  * the compiled_batched plan of the headline bench circuit reports the
    same hbm_sweeps as the unbatched fused plan;
  * bucketing is live: B=5 and B=8 resolve to one bucket (8) under the
    default QUEST_BATCH_BUCKET=pow2.

The goldens mirror the tier-1 assertions in tests/test_batched.py; a
planner change that moves either must update both, consciously.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    import bench
    from quest_tpu import trajectories as T
    from quest_tpu.env import batch_bucket

    traj = bench._build_traj_circuit(20)
    one = T.plan_stats(traj, 1)
    many = T.plan_stats(traj, 256)

    head = bench._build_circuit(24)
    fused = head.plan_stats(batch=256)

    rec = {
        "traj20_hbm_sweeps_B1": one["hbm_sweeps"],
        "traj20_hbm_sweeps_B256": many["hbm_sweeps"],
        "traj20_channels": many["channels"],
        "headline_hbm_sweeps": fused["fused"]["hbm_sweeps"],
        "headline_batched_hbm_sweeps": fused["batched"]["hbm_sweeps"],
        "bucket_of_5": batch_bucket(5),
        "bucket_of_8": batch_bucket(8),
    }
    print(json.dumps(rec))
    ok = True
    if many["hbm_sweeps"] != one["hbm_sweeps"]:
        print(f"REGRESSION: trajectory launches scale with B "
              f"({one['hbm_sweeps']} at B=1 vs {many['hbm_sweeps']} at "
              f"B=256)", file=sys.stderr)
        ok = False
    if fused["batched"]["hbm_sweeps"] != fused["fused"]["hbm_sweeps"]:
        print("REGRESSION: compiled_batched plans a different launch "
              "count than the unbatched fused plan", file=sys.stderr)
        ok = False
    if not (rec["bucket_of_5"] == rec["bucket_of_8"] == 8):
        print("REGRESSION: batch bucketing no longer maps B=5 and B=8 "
              "to one compiled bucket", file=sys.stderr)
        ok = False
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
