"""Probe: does the Pallas fused engine compile+run on the real chip, and
how fast is it vs the XLA per-gate path? Prints full tracebacks instead of
swallowing them (bench.py's except Exception hid the round-1 failure)."""
import sys
import time
import traceback

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")


def build(n, gates):
    from quest_tpu.circuit import Circuit
    rng = np.random.default_rng(42)
    c = Circuit(n)
    for i in range(gates):
        q = 1 + i % (n - 1)
        c.rx(q, float(rng.uniform(0, 2 * np.pi)))
    return c


def timed(step, state, reps, label, gates):
    t0 = time.perf_counter()
    state = step(state)
    _ = np.asarray(state[0, :4])
    print(f"  {label}: first call (compile) {time.perf_counter()-t0:.1f}s",
          flush=True)
    t0 = time.perf_counter()
    for _ in range(reps):
        state = step(state)
    _ = np.asarray(state[0, :4])
    dt = time.perf_counter() - t0
    gps = gates * reps / dt
    bw = gps * 2 * (1 << n) * 4 * 2  # read+write both planes, f32
    print(f"  {label}: {gps:.1f} gates/s  ({bw/1e9:.1f} GB/s effective)",
          flush=True)
    return state


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 20
    gates = 16
    reps = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    print("devices:", jax.devices(), flush=True)
    circ = build(n, gates)

    engines = sys.argv[3].split(",") if len(sys.argv) > 3 else \
        ["banded", "fused", "xla"]
    for name in engines:
        print(f"n={n} {name} engine:", flush=True)
        try:
            circ = build(n, gates)
            if name == "banded":
                step = circ.compiled_banded(n, density=False, donate=True)
            elif name == "fused":
                step = circ.compiled_fused(n, density=False, donate=True)
            else:
                step = circ.compiled(n, density=False, donate=True)
            state = jnp.zeros((2, 1 << n), dtype=jnp.float32).at[0, 0].set(1.0)
            timed(step, state, reps, name, gates)
        except Exception:
            traceback.print_exc()
