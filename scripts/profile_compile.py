"""Split compile latency of the flagship workloads into its phases.

VERDICT r2 weak #2: RCS 30q d20 cost 71.4 s compile+first-run against
6.76 s of execution. This harness measures, per workload:

  plan    - circuit flatten + band planning + segmentation (host Python)
  trace   - jax tracing to jaxpr/StableHLO (jit(...).lower())
  compile - XLA + Mosaic compilation (lowered.compile()); Mosaic kernel
            count comes from the segment cache
  run1    - first execution (device upload + any deferred work)

Run on the chip:   python scripts/profile_compile.py [n] [depth]
Also meaningful on CPU for the plan/trace phases (compile there measures
XLA:CPU, not Mosaic). A warm persistent cache (the default; see
quest_tpu.precision.enable_compile_cache) makes `compile` ~disk-load —
run twice to see cold vs warm.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(n=30, depth=20):
    from quest_tpu.precision import enable_compile_cache
    enable_compile_cache()
    from quest_tpu.env import ensure_live_backend
    ensure_live_backend()

    import jax
    import jax.numpy as jnp

    from quest_tpu.circuit import random_circuit
    from quest_tpu.ops import fusion as F
    from quest_tpu.ops import pallas_band as PB
    from quest_tpu.state import basis_planes, fused_state_shape

    rec = {"n": n, "depth": depth,
           "platform": jax.devices()[0].platform}

    t0 = time.perf_counter()
    c = random_circuit(n, depth=depth, seed=7, entangler="cz")
    items = F.plan(c._flat_ops(n, False), n, bands=PB.plan_bands(n))
    parts = PB.segment_plan(items, n)
    keys = {tuple(p[1]) for p in parts if p[0] == "segment"}
    rec["plan_s"] = round(time.perf_counter() - t0, 2)
    rec["segments"] = sum(1 for p in parts if p[0] == "segment")
    rec["distinct_kernels"] = len(keys)

    interp = rec["platform"] not in ("tpu", "axon")  # CPU: interpreter
    rec["interpret"] = interp

    t0 = time.perf_counter()
    step = c.compiled_fused(n, density=False, donate=True, interpret=interp)
    shape = fused_state_shape(n)
    s = basis_planes(0, n=n, rdt=jnp.float32, shape=shape)
    lowered = jax.jit(
        lambda a: step(a), donate_argnums=()).lower(
            jax.ShapeDtypeStruct(shape, jnp.float32))
    rec["trace_s"] = round(time.perf_counter() - t0, 2)

    t0 = time.perf_counter()
    compiled = lowered.compile()
    rec["compile_s"] = round(time.perf_counter() - t0, 2)

    # sync via sync_array (tiny native-layout slice): reading through
    # .reshape(2, -1) forces a full relayout copy of the tiled state on
    # device (8 GB at 30q -> OOM next to the live state on a 16 GB v5e),
    # and jax.block_until_ready returns early on the axon tunnel
    from quest_tpu.env import sync_array
    t0 = time.perf_counter()
    out = step(s)
    sync_array(out)
    rec["run1_s"] = round(time.perf_counter() - t0, 2)

    t0 = time.perf_counter()
    out = step(out)
    sync_array(out)
    rec["steady_s"] = round(time.perf_counter() - t0, 3)
    del compiled
    print(json.dumps(rec))


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 30
    depth = int(sys.argv[2]) if len(sys.argv) > 2 else 20
    main(n, depth)
