#!/usr/bin/env python
"""CI gate for the adjoint differentiation engine (docs/AUTODIFF.md):
fails when the O(1)-memory gradient walk drifts from finite
differences, from the taped (jax.grad) reference, or across the shard
boundary — or when the plan IR's grad axis stops pricing the engines
the way the capacity model promises.

Gates:
  * FD PARITY: adjoint gradients on a golden VQE ansatz vs a 5-point
    finite-difference stencil over the f64 taped energy — 1e-6 in f32,
    1e-10 in f64 (scaled by the gradient's own magnitude floor);
  * PEAK MEMORY IS A MODEL INVARIANT: the capacity model the autotuner
    prices with must report adjoint peak == exactly 3 state registers
    + the O(masks) sign/control tables, INDEPENDENT of parameter count
    and depth, while taped residuals grow as (P+2) registers — asserted
    on CPU over a (P, depth) grid (XLA-CPU's temp arena does not model
    buffer reuse, so the liveness claim is pinned on the model the
    planner actually consults, and the model is what routes dispatch);
  * SHARDED == SINGLE-DEVICE: the 2-device adjoint walk's value and
    gradients equal the unsharded engine's to f32 eps on a circuit with
    global-bit targets (the backward walk rides the comm planner);
  * INCUMBENT-WINS-TIES ON THE GRAD AXIS: over an (HBM budget, width)
    grid, plan.autotune's grad record never picks adjoint where taped's
    residuals fit the budget — adjoint is a capability extension, not
    a re-route of working widths;
  * THE CAPACITY CLIFF (the 3x headline's CI form): at the widest width
    where BOTH engines fit the modeled v5e HBM, taped already holds
    >= 3x adjoint's live bytes — the ratio that collapses taped
    steps/s to zero one width later — and at 30q (the width the paper
    trains at) taped CANNOT run on a 4-device mesh while adjoint fits.
    The honest wall-clock ratio at CPU-feasible widths is ~1.2-1.4x
    (both engines bandwidth-bound off-chip; bench.py training reports
    it); the measured leg here gates non-regression, not the 3x.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")
# the goldens must not move under a user's ambient knobs
for _k in ("QUEST_ADJOINT", "QUEST_HBM_BYTES", "QUEST_COMM_TOPOLOGY",
           "QUEST_PLAN_CACHE", "QUEST_PLAN_CACHE_DIR"):
    os.environ.pop(_k, None)

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _golden_ansatz(n, layers, seed=3):
    import numpy as np
    from quest_tpu.circuit import Circuit
    rng = np.random.default_rng(seed)
    c = Circuit(n)
    for _ in range(layers):
        for q in range(n):
            c.ry(q, float(rng.uniform(-np.pi, np.pi)))
        for q in range(0, n - 1, 2):
            c.cnot(q, q + 1)
        for q in range(n):
            c.rz(q, float(rng.uniform(-np.pi, np.pi)))
        c.multi_rotate_z((0, n - 1), float(rng.uniform(-1, 1)))
    return c


def _tfim(n):
    import numpy as np
    from quest_tpu.ops import expec as E
    codes, cf = [], []
    for i in range(n - 1):
        row = [0] * n
        row[i] = row[i + 1] = 3
        codes.append(row)
        cf.append(-1.0)
    for i in range(n):
        row = [0] * n
        row[i] = 1
        codes.append(row)
        cf.append(-0.7)
    return E.PauliSum.of(np.array(codes), np.array(cf), n)


def _fd_grads(fn, theta, eps):
    """5-point central stencil: O(eps^4) truncation, so the f64 gate
    can sit at 1e-10 without the stencil's own error showing."""
    import numpy as np
    th = np.asarray(theta, np.float64)
    g = np.zeros_like(th)
    for i in range(th.size):
        vals = []
        for k in (-2, -1, 1, 2):
            t = th.copy()
            t[i] += k * eps
            vals.append(float(fn(t)[0]))
        g[i] = (vals[0] - 8 * vals[1] + 8 * vals[2] - vals[3]) / (12 * eps)
    return g


def main() -> int:
    import jax
    jax.config.update("jax_enable_x64", True)   # the f64 FD truth source
    import numpy as np
    import jax.numpy as jnp

    from quest_tpu import adjoint as AD
    from quest_tpu import plan as P
    from quest_tpu.env import AMP_AXIS
    from jax.sharding import Mesh

    ok = True
    rec = {}

    n, layers = 6, 2
    circ = _golden_ansatz(n, layers)
    ham = _tfim(n)

    # gate 1: FD parity (f64 stencil as the truth source for both)
    f64 = AD.value_and_grad(circ, ham, engine="taped", dtype=np.float64)
    th0 = np.asarray(f64.initial_params, np.float64)
    g_fd = _fd_grads(f64, th0, eps=3e-4)
    scale = max(1.0, float(np.max(np.abs(g_fd))))

    f32_adj = AD.value_and_grad(circ, ham, engine="adjoint")
    _, g32 = f32_adj(jnp.asarray(th0, jnp.float32))
    err32 = float(np.max(np.abs(np.asarray(g32, np.float64) - g_fd)))
    f64_adj = AD.value_and_grad(circ, ham, engine="adjoint",
                                dtype=np.float64)
    _, g64 = f64_adj(jnp.asarray(th0, jnp.float64))
    err64 = float(np.max(np.abs(np.asarray(g64) - g_fd)))
    rec["fd_parity"] = {"params": f32_adj.num_params,
                        "f32_err": err32, "f64_err": err64,
                        "grad_scale": scale}
    if err32 > 1e-6 * scale:
        print(f"REGRESSION: f32 adjoint grads off FD by {err32:.3e} "
              f"(gate 1e-6 x scale {scale:.2f})", file=sys.stderr)
        ok = False
    if err64 > 1e-10 * scale:
        print(f"REGRESSION: f64 adjoint grads off FD by {err64:.3e} "
              f"(gate 1e-10 x scale {scale:.2f})", file=sys.stderr)
        ok = False

    # gate 2: the capacity model's liveness invariant
    from quest_tpu.ops import expec as E
    state20 = 2 * (1 << 20) * 4
    mask20 = 4 * (1 << E._SEG_BITS) * 4 * -(-20 // E._SEG_BITS)
    caps = [AD.capacity_stats(20, p, d, np.float32)
            for p, d in ((40, 100), (400, 1000), (4000, 10000))]
    peaks = {c["adjoint_peak_bytes"] for c in caps}
    rec["capacity"] = {"adjoint_peak_bytes": sorted(peaks),
                       "expected": 3 * state20 + mask20,
                       "taped_residual_bytes":
                           [c["taped_residual_bytes"] for c in caps]}
    if peaks != {3 * state20 + mask20}:
        print(f"REGRESSION: adjoint peak must be exactly 3 state "
              f"registers + masks independent of (P, depth); model "
              f"reported {sorted(peaks)} vs "
              f"{3 * state20 + mask20}", file=sys.stderr)
        ok = False
    for c, (p, _d) in zip(caps, ((40, 100), (400, 1000), (4000, 10000))):
        if c["taped_residual_bytes"] != (p + 2) * state20:
            print(f"REGRESSION: taped residuals at P={p} reported "
                  f"{c['taped_residual_bytes']}, expected "
                  f"{(p + 2) * state20}", file=sys.stderr)
            ok = False

    # gate 3: sharded 2-device == single device
    mesh = Mesh(np.array(jax.devices()[:2]), (AMP_AXIS,))
    f_sh = AD.value_and_grad(circ, ham, engine="adjoint", mesh=mesh)
    v_sh, g_sh = f_sh(jnp.asarray(th0, jnp.float32))
    v_1d, g_1d = f32_adj(jnp.asarray(th0, jnp.float32))
    dv = abs(float(v_sh) - float(v_1d))
    dg = float(np.max(np.abs(np.asarray(g_sh) - np.asarray(g_1d))))
    rec["sharded"] = {"value_diff": dv, "grad_diff": dg,
                      "comm": f_sh.comm_record}
    if dv > 1e-6 or dg > 1e-6 * scale:
        print(f"REGRESSION: sharded-2dev adjoint off single-device by "
              f"value {dv:.3e} / grads {dg:.3e}", file=sys.stderr)
        ok = False

    # gate 4: autotune never picks adjoint where taped fits the budget
    grid_bad = []
    for hbm in (None, 10 * state20, 3 * state20 + mask20 + 1):
        if hbm is None:
            os.environ.pop("QUEST_HBM_BYTES", None)
        else:
            os.environ["QUEST_HBM_BYTES"] = str(hbm)
        for m, lay in ((6, 1), (6, 3), (8, 2)):
            c = _golden_ansatz(m, lay)
            g = P.autotune(c, persist=False).grad
            if g["engine"] == "adjoint" and g["taped"]["fits"]:
                grid_bad.append((hbm, m, lay, g))
    os.environ.pop("QUEST_HBM_BYTES", None)
    rec["grad_axis_grid_violations"] = len(grid_bad)
    if grid_bad:
        print(f"REGRESSION: plan.autotune grad axis picked adjoint "
              f"where taped fits (incumbent-wins-ties broken): "
              f"{grid_bad[:2]}", file=sys.stderr)
        ok = False
    # ... and where taped does NOT fit but adjoint does, auto resolves
    # to adjoint (the capability extension actually extends)
    wide = _golden_ansatz(8, 4)
    cap8 = AD.capacity_stats(8, 68, len(wide.ops), np.float32)
    # a budget between the two peaks: adjoint fits, taped's P+2
    # residual registers do not
    os.environ["QUEST_HBM_BYTES"] = str(
        (cap8["adjoint_peak_bytes"] + cap8["taped_residual_bytes"]) // 2)
    g = P.autotune(wide, persist=False).grad
    os.environ.pop("QUEST_HBM_BYTES", None)
    if g["engine"] != "adjoint" or g["taped"]["fits"]:
        print(f"REGRESSION: past the taped fit width auto must resolve "
              f"to adjoint; grad record {g}", file=sys.stderr)
        ok = False

    # gate 5: the capacity cliff. Scenario P(m) = 4m (the bench VQE's
    # 2-layer parameter density); v5e default budget
    def scenario(m):
        return AD.capacity_stats(m, 4 * m, 10 * m, np.float32)

    widest_both = max(m for m in range(8, 41)
                      if scenario(m)["taped_fits"]
                      and scenario(m)["adjoint_fits"])
    at = scenario(widest_both)
    ratio = at["taped_residual_bytes"] / at["adjoint_peak_bytes"]
    c30 = AD.capacity_stats(30, 120, 300, np.float32)
    g30 = dict(c30)
    for key in ("adjoint_peak_bytes", "taped_residual_bytes",
                "state_bytes"):
        g30[key] //= 4                       # 4-device mesh shards all
    rec["cliff"] = {
        "widest_both_fit_n": widest_both,
        "live_bytes_ratio": round(ratio, 2),
        "taped_fits_30q_1dev": c30["taped_fits"],
        "taped_fits_30q_4dev":
            g30["taped_residual_bytes"] <= c30["hbm_bytes"],
        "adjoint_fits_30q_4dev":
            g30["adjoint_peak_bytes"] <= c30["hbm_bytes"],
    }
    if ratio < 3.0:
        print(f"REGRESSION: at the widest both-fit width "
              f"({widest_both}q) taped must hold >= 3x adjoint's live "
              f"bytes (got {ratio:.2f}x) — the steps/s cliff the "
              f"adjoint engine exists for", file=sys.stderr)
        ok = False
    if rec["cliff"]["taped_fits_30q_4dev"]:
        print("REGRESSION: the 30q training step should NOT fit the "
              "taped engine on a 4-device mesh", file=sys.stderr)
        ok = False
    if not rec["cliff"]["adjoint_fits_30q_4dev"]:
        print("REGRESSION: the 30q training step must fit the adjoint "
              "engine on a 4-device mesh", file=sys.stderr)
        ok = False

    # measured non-regression leg (interleaved best-of; the honest CPU
    # ratio — the 3x is the capacity gate above, not this wall-clock)
    import time
    f_tap = AD.value_and_grad(circ, ham, engine="taped")
    th32 = jnp.asarray(th0, jnp.float32)
    f32_adj(th32), f_tap(th32)
    dt_a = dt_t = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(10):
            f32_adj(th32)[1].block_until_ready()
        dt_a = min(dt_a, time.perf_counter() - t0)
        t0 = time.perf_counter()
        for _ in range(10):
            f_tap(th32)[1].block_until_ready()
        dt_t = min(dt_t, time.perf_counter() - t0)
    rec["measured"] = {"adjoint_steps_per_s": round(10 / dt_a, 1),
                       "taped_steps_per_s": round(10 / dt_t, 1),
                       "ratio": round(dt_t / dt_a, 2)}
    if dt_a > 2.0 * dt_t:
        print(f"REGRESSION: adjoint wall-clock fell to "
              f"{dt_t / dt_a:.2f}x of taped at {n}q — the engine must "
              f"not cost the widths it does not help", file=sys.stderr)
        ok = False

    print(json.dumps(rec))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
