"""Isolate per-dispatch (axon tunnel) overhead from real kernel cost:
- empty jit on a tiny array
- identity jit on the full state (pure donate/alias)
- 1 pallas pass per dispatch vs 8 passes per dispatch
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")
from quest_tpu.precision import enable_compile_cache
enable_compile_cache()

from quest_tpu.ops import pallas_band as PB


def timeit(jfn, amps, reps, label, n, passes=1):
    amps = jfn(amps)
    _ = np.asarray(amps.ravel()[:4])
    t0 = time.perf_counter()
    for _ in range(reps):
        amps = jfn(amps)
    _ = np.asarray(amps.ravel()[:4])
    dt = (time.perf_counter() - t0) / reps
    bw = passes * 2 * 2 * (1 << n) * 4 / dt
    print(f"{label:22s}: {dt*1e3:8.3f} ms/call "
          f"({bw/1e9:7.1f} GB/s per-pass r+w x {passes})", flush=True)
    return amps


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 26
    print("devices:", jax.devices(), flush=True)

    tiny = jnp.zeros((8, 128), dtype=jnp.float32)
    jfn = jax.jit(lambda a: a + 1.0)
    timeit(jfn, tiny, 50, "tiny add", 10)

    amps = jnp.zeros((2, 1 << n), dtype=jnp.float32).at[0, 0].set(1.0)
    jfn = jax.jit(lambda a: a, donate_argnums=(0,))
    amps = timeit(jfn, amps, 20, "identity (donated)", n)

    jfn = jax.jit(lambda a: a * 1.0000001, donate_argnums=(0,))
    amps = timeit(jfn, amps, 20, "scale (1 pass)", n)

    rng = np.random.default_rng(0)
    q, _ = np.linalg.qr(rng.standard_normal((128, 128)))
    g = jnp.asarray(np.stack([q, q * 0.1]).astype(np.float32))
    seg = PB.compile_segment([PB.MatStage("b0", 128, False, (), ())], n)

    amps3 = amps.reshape(2, -1, 128)
    jfn = jax.jit(lambda a: seg(a, [g]), donate_argnums=(0,))
    amps3 = timeit(jfn, amps3, 20, "pallas b0 (1 pass)", n)

    def eight(a):
        for _ in range(8):
            a = seg(a, [g])
        return a
    jfn = jax.jit(eight, donate_argnums=(0,))
    amps3 = timeit(jfn, amps3, 20, "pallas b0 (8 passes)", n, passes=8)


if __name__ == "__main__":
    main()
