"""Pre-warm the persistent compile cache with the flagship programs.

XLA+Mosaic compilation of the 30q fused RCS program costs ~70 s cold
(VERDICT r2); all quest_tpu entry points share one persistent cache
(quest_tpu.precision.enable_compile_cache), so compiling the common
programs ONCE here makes every later cold process — bench.py, the driver
entry points, a user's first circuit — a disk-cache load instead.

Run after the tunnel comes up (scripts/tpu_revalidate.sh runs it first):
    python scripts/tpu_prewarm.py
Warms: the bench ladder shapes (30/28/26/24/22q fused+banded steps) and
RCS 30q depth-20. Safe to re-run; warm entries are no-ops.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    from quest_tpu.precision import enable_compile_cache
    enable_compile_cache()
    from quest_tpu.env import ensure_live_backend
    platform = ensure_live_backend()
    if platform == "cpu":
        print("[prewarm] no TPU; nothing to warm for the chip", file=sys.stderr)
        return

    import jax.numpy as jnp

    from quest_tpu.circuit import random_circuit
    from quest_tpu.state import basis_planes, fused_state_shape

    import bench as B

    for n in (22, 24, 26, 28, 30):
        for engine in ("fused", "banded"):
            if engine == "banded" and not B.banded_fits(n):
                continue  # would OOM after ~20 min of compile (see bench)
            t0 = time.perf_counter()
            try:
                c = B._build_circuit(n)
                if engine == "fused":
                    step = c.compiled_fused(n, density=False, donate=True,
                                            iters=B.INNER_STEPS)
                    shape = fused_state_shape(n)
                else:
                    step = c.compiled_banded(n, density=False, donate=True,
                                             iters=B.INNER_STEPS)
                    shape = (2, 1 << n)
                s = step(basis_planes(0, n=n, rdt=jnp.float32, shape=shape))
                del s, step
                print(f"[prewarm] bench {engine} {n}q: "
                      f"{time.perf_counter()-t0:.1f}s", file=sys.stderr)
            except Exception as e:  # a failed size must not block the rest
                print(f"[prewarm] bench {engine} {n}q FAILED: {e!r}",
                      file=sys.stderr)

    t0 = time.perf_counter()
    n = 30
    c = random_circuit(n, depth=20, seed=7, entangler="cz")
    step = c.compiled_fused(n, density=False, donate=True)
    s = step(basis_planes(0, n=n, rdt=jnp.float32,
                          shape=fused_state_shape(n)))
    del s, step
    print(f"[prewarm] rcs 30q d20: {time.perf_counter()-t0:.1f}s",
          file=sys.stderr)

    # QFT 30q: the certification sweep's coldest program (290.9 s cold,
    # measured r3 — its all-to-all segment structure shares nothing with
    # the RCS/bench kernels)
    t0 = time.perf_counter()
    try:
        from quest_tpu.circuit import qft_circuit
        step = qft_circuit(n).compiled_fused(n, density=False, donate=True)
        s = step(basis_planes(0, n=n, rdt=jnp.float32,
                              shape=fused_state_shape(n)))
        del s, step
        print(f"[prewarm] qft 30q: {time.perf_counter()-t0:.1f}s",
              file=sys.stderr)
    except Exception as e:
        print(f"[prewarm] qft 30q FAILED: {e!r}", file=sys.stderr)

    # the driver's entry() compile-check program (28q depth-4 RCS on
    # the fused engine): not covered by any of the above — it is a
    # different circuit than the bench/RCS programs, and the driver
    # should pay a cache load, not a fresh compile
    t0 = time.perf_counter()
    try:
        import jax

        import __graft_entry__ as g
        fn, args = g.entry()
        jax.jit(fn).lower(*args).compile()
        print(f"[prewarm] graft entry: {time.perf_counter()-t0:.1f}s",
              file=sys.stderr)
    except Exception as e:
        print(f"[prewarm] graft entry FAILED: {e!r}", file=sys.stderr)


if __name__ == "__main__":
    main()
