#!/usr/bin/env python
"""CI smoke gate for the comm planner (docs/DISTRIBUTED.md): fails if
the deep-global testbed's PLANNED collective schedule regresses above
its committed goldens, asserted CPU-side through the comm predictor —
pure host planning, no mesh, no compile, no chip (the comm analogue of
check_sweep_golden.py; tests/test_comm.py separately pins the same
predictions EQUAL to XLA's lowered StableHLO accounting).

Gates (8-device shard geometry, f64 registers):
  * per-gate engine: planned bytes >= 2x below the lazy-relabel plan —
    the mpiQulacs-style coalescing must keep beating per-qubit SWAPs;
  * banded engine: planned bytes no worse than BOTH its pre-lazy
    baseline (the plain composed schedule) and its layer-amortized
    relabel incumbent — the planner can only ever improve it;
  * absolute ceilings on the chosen plan (6 all-to-alls / 672 B).

The goldens live HERE (the CI gate) and are mirrored by the tier-1
assertions in tests/test_comm.py; a planner change that moves either
must update both, consciously.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

DEEPGLOBAL_GOLDEN_EXCHANGES = 6
DEEPGLOBAL_GOLDEN_BYTES = 672       # f64, 8 devices
N, DEPTH, DEVICES = 6, 6, 8
BPR = 8                              # f64 planes


def main() -> int:
    import bench
    from quest_tpu.circuit import flatten_ops
    from quest_tpu.ops import fusion as F
    from quest_tpu.parallel import comm as C
    from quest_tpu.parallel import relabel as R
    from quest_tpu.parallel import sharded as S

    local_n = N - (DEVICES.bit_length() - 1)
    c = bench._build_deep_global_circuit(N, DEPTH)
    flat = flatten_ops(c.ops, N, False)

    def stats_flat(lst):
        return C.comm_stats(C.predict_exchanges_flat(lst, local_n),
                            num_devices=DEVICES, bytes_per_real=BPR)

    def stats_items(lst):
        items = F.plan(lst, N, bands=S._shard_bands(N, local_n))
        return C.comm_stats(C.predict_exchanges_items(items, local_n),
                            num_devices=DEVICES, bytes_per_real=BPR)

    pg_info: dict = {}
    pg = stats_flat(S.pergate_flat(c.ops, N, False, local_n,
                                   comm_info=pg_info))
    pg_lazy = stats_flat(R.lazy_relabel_ops(flat, N, local_n))
    bd_info: dict = {}
    bd = stats_items(S.engine_flat(c.ops, N, False, local_n,
                                   comm_info=bd_info))
    bd_plain = stats_items(list(F.maybe_schedule(flat, N)))
    bd_relabel = stats_items(R.plan_full_relabels(
        list(F.maybe_schedule(flat, N)), N, local_n))

    rec = {
        "pergate_bytes": pg["comm_bytes"],
        "pergate_exchanges": pg["comm_exchanges"],
        "pergate_strategy": pg_info.get("strategy"),
        "pergate_lazy_bytes": pg_lazy["comm_bytes"],
        "banded_bytes": bd["comm_bytes"],
        "banded_exchanges": bd["comm_exchanges"],
        "banded_strategy": bd_info.get("strategy"),
        "banded_plain_bytes": bd_plain["comm_bytes"],
        "banded_relabel_bytes": bd_relabel["comm_bytes"],
    }
    print(json.dumps(rec))
    ok = True
    if 2 * pg["comm_bytes"] > pg_lazy["comm_bytes"]:
        print(f"REGRESSION: per-gate planned bytes {pg['comm_bytes']} "
              f"not >=2x below the lazy-relabel plan "
              f"{pg_lazy['comm_bytes']}", file=sys.stderr)
        ok = False
    if bd["comm_bytes"] > bd_plain["comm_bytes"]:
        print(f"REGRESSION: banded planned bytes {bd['comm_bytes']} "
              f"above the pre-lazy plain baseline "
              f"{bd_plain['comm_bytes']}", file=sys.stderr)
        ok = False
    if bd["comm_bytes"] > bd_relabel["comm_bytes"]:
        print(f"REGRESSION: banded planned bytes {bd['comm_bytes']} "
              f"above the layer-amortized relabel incumbent "
              f"{bd_relabel['comm_bytes']} — choose_plan's tie-break "
              f"contract is broken", file=sys.stderr)
        ok = False
    for name, st in (("pergate", pg), ("banded", bd)):
        if st["comm_exchanges"] > DEEPGLOBAL_GOLDEN_EXCHANGES:
            print(f"REGRESSION: {name} exchanges {st['comm_exchanges']} "
                  f"> golden {DEEPGLOBAL_GOLDEN_EXCHANGES}",
                  file=sys.stderr)
            ok = False
        if st["comm_bytes"] > DEEPGLOBAL_GOLDEN_BYTES:
            print(f"REGRESSION: {name} bytes {st['comm_bytes']} > "
                  f"golden {DEEPGLOBAL_GOLDEN_BYTES}", file=sys.stderr)
            ok = False
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
