#!/usr/bin/env python
"""CI smoke gate for the comm planner (docs/DISTRIBUTED.md): fails if
the deep-global testbed's PLANNED collective schedule regresses above
its committed goldens, asserted CPU-side through the comm predictor —
pure host planning, no mesh, no compile, no chip (the comm analogue of
check_sweep_golden.py; tests/test_comm.py separately pins the same
predictions EQUAL to XLA's lowered StableHLO accounting).

Gates (8-device shard geometry, f64 registers):
  * per-gate engine: planned bytes >= 2x below the lazy-relabel plan —
    the mpiQulacs-style coalescing must keep beating per-qubit SWAPs;
  * banded engine: planned bytes no worse than BOTH its pre-lazy
    baseline (the plain composed schedule) and its layer-amortized
    relabel incumbent — the planner can only ever improve it;
  * absolute ceilings on the chosen plan (6 all-to-alls / 672 B);
  * TOPOLOGY (docs/DISTRIBUTED.md §topology): under the hosts=2 model
    the hierarchical plan's DCI bytes must sit >= 2x below the flat
    plan's DCI share (the cluster-coalescing headline: 384 -> 192 B),
    and with the topology FLAT the chosen plan must be byte-identical
    to the pre-topology goldens (6 events / 672 B EXACTLY — the
    knob-off bit-for-bit contract).

The goldens live HERE (the CI gate) and are mirrored by the tier-1
assertions in tests/test_comm.py + tests/test_topology.py; a planner
change that moves either must update both, consciously.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the flat goldens must not move under a user's ambient topology knob
os.environ.pop("QUEST_COMM_TOPOLOGY", None)

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

DEEPGLOBAL_GOLDEN_EXCHANGES = 6
DEEPGLOBAL_GOLDEN_BYTES = 672       # f64, 8 devices
DEEPGLOBAL_FLAT_DCI_BYTES = 384     # the 6 a2as' cross-host share, hosts=2
DEEPGLOBAL_HIER_DCI_CEILING = 192   # >= 2x below flat (measured exactly 2x)
N, DEPTH, DEVICES = 6, 6, 8
BPR = 8                              # f64 planes


def main() -> int:
    import bench
    from quest_tpu.circuit import flatten_ops
    from quest_tpu.ops import fusion as F
    from quest_tpu.parallel import comm as C
    from quest_tpu.parallel import relabel as R
    from quest_tpu.parallel import sharded as S

    local_n = N - (DEVICES.bit_length() - 1)
    c = bench._build_deep_global_circuit(N, DEPTH)
    flat = flatten_ops(c.ops, N, False)

    def stats_flat(lst):
        return C.comm_stats(C.predict_exchanges_flat(lst, local_n),
                            num_devices=DEVICES, bytes_per_real=BPR)

    def stats_items(lst, topo=None):
        items = F.plan(lst, N, bands=S._shard_bands(N, local_n))
        ib = topo.ici_bits(DEVICES) if (topo and topo.hierarchical) \
            else None
        return C.comm_stats(C.predict_exchanges_items(items, local_n, ib),
                            num_devices=DEVICES, bytes_per_real=BPR,
                            topo=topo)

    pg_info: dict = {}
    pg = stats_flat(S.pergate_flat(c.ops, N, False, local_n,
                                   comm_info=pg_info))
    pg_lazy = stats_flat(R.lazy_relabel_ops(flat, N, local_n))
    bd_info: dict = {}
    bd = stats_items(S.engine_flat(c.ops, N, False, local_n,
                                   comm_info=bd_info))
    bd_plain = stats_items(list(F.maybe_schedule(flat, N)))
    bd_relabel = stats_items(R.plan_full_relabels(
        list(F.maybe_schedule(flat, N)), N, local_n))

    # topology gate: price the deep-global circuit under the hosts=2
    # hierarchical model — the flat planner's chosen plan (its DCI
    # share re-priced) vs the hierarchical planner's choice — and
    # verify the FLAT choice is byte-identical to the pre-topology
    # goldens (the knob-off contract: QUEST_COMM_TOPOLOGY=0 plans
    # bit-for-bit like PR 8)
    topo2 = C.Topology(hosts=2)
    flat_sched = list(F.maybe_schedule(flat, N))
    bands = S._shard_bands(N, local_n)
    flat_plan, flat_info = C.choose_plan(flat_sched, N, local_n,
                                         engine="banded", bands=bands,
                                         topo=C.FLAT)
    hier_plan, hier_info = C.choose_plan(flat_sched, N, local_n,
                                         engine="banded", bands=bands,
                                         topo=topo2)
    flat_h = stats_items(flat_plan, topo2)    # flat plan, hier pricing
    hier_h = stats_items(hier_plan, topo2)

    rec = {
        "pergate_bytes": pg["comm_bytes"],
        "pergate_exchanges": pg["comm_exchanges"],
        "pergate_strategy": pg_info.get("strategy"),
        "pergate_lazy_bytes": pg_lazy["comm_bytes"],
        "banded_bytes": bd["comm_bytes"],
        "banded_exchanges": bd["comm_exchanges"],
        "banded_strategy": bd_info.get("strategy"),
        "banded_plain_bytes": bd_plain["comm_bytes"],
        "banded_relabel_bytes": bd_relabel["comm_bytes"],
        "flat_dci_bytes": flat_h["comm_dci_bytes"],
        "hier_dci_bytes": hier_h["comm_dci_bytes"],
        "hier_dci_exchanges": hier_h["comm_dci_exchanges"],
        "hier_strategy": hier_info.get("strategy"),
    }
    print(json.dumps(rec))
    ok = True
    flat_b = stats_items(flat_plan)
    if (flat_b["comm_bytes"] != DEEPGLOBAL_GOLDEN_BYTES
            or flat_b["comm_exchanges"] != DEEPGLOBAL_GOLDEN_EXCHANGES):
        print(f"REGRESSION: flat-topology plan "
              f"{flat_b['comm_exchanges']} events / "
              f"{flat_b['comm_bytes']} B not IDENTICAL to the "
              f"pre-topology goldens "
              f"({DEEPGLOBAL_GOLDEN_EXCHANGES} / "
              f"{DEEPGLOBAL_GOLDEN_BYTES}) — the knob-off bit-for-bit "
              f"contract is broken", file=sys.stderr)
        ok = False
    if flat_h["comm_dci_bytes"] != DEEPGLOBAL_FLAT_DCI_BYTES:
        print(f"REGRESSION: flat plan's hosts=2 DCI share "
              f"{flat_h['comm_dci_bytes']} != golden "
              f"{DEEPGLOBAL_FLAT_DCI_BYTES}", file=sys.stderr)
        ok = False
    if 2 * hier_h["comm_dci_bytes"] > flat_h["comm_dci_bytes"]:
        print(f"REGRESSION: hierarchical DCI bytes "
              f"{hier_h['comm_dci_bytes']} not >= 2x below the flat "
              f"plan's {flat_h['comm_dci_bytes']}", file=sys.stderr)
        ok = False
    if hier_h["comm_dci_bytes"] > DEEPGLOBAL_HIER_DCI_CEILING:
        print(f"REGRESSION: hierarchical DCI bytes "
              f"{hier_h['comm_dci_bytes']} > ceiling "
              f"{DEEPGLOBAL_HIER_DCI_CEILING}", file=sys.stderr)
        ok = False
    if 2 * pg["comm_bytes"] > pg_lazy["comm_bytes"]:
        print(f"REGRESSION: per-gate planned bytes {pg['comm_bytes']} "
              f"not >=2x below the lazy-relabel plan "
              f"{pg_lazy['comm_bytes']}", file=sys.stderr)
        ok = False
    if bd["comm_bytes"] > bd_plain["comm_bytes"]:
        print(f"REGRESSION: banded planned bytes {bd['comm_bytes']} "
              f"above the pre-lazy plain baseline "
              f"{bd_plain['comm_bytes']}", file=sys.stderr)
        ok = False
    if bd["comm_bytes"] > bd_relabel["comm_bytes"]:
        print(f"REGRESSION: banded planned bytes {bd['comm_bytes']} "
              f"above the layer-amortized relabel incumbent "
              f"{bd_relabel['comm_bytes']} — choose_plan's tie-break "
              f"contract is broken", file=sys.stderr)
        ok = False
    for name, st in (("pergate", pg), ("banded", bd)):
        if st["comm_exchanges"] > DEEPGLOBAL_GOLDEN_EXCHANGES:
            print(f"REGRESSION: {name} exchanges {st['comm_exchanges']} "
                  f"> golden {DEEPGLOBAL_GOLDEN_EXCHANGES}",
                  file=sys.stderr)
            ok = False
        if st["comm_bytes"] > DEEPGLOBAL_GOLDEN_BYTES:
            print(f"REGRESSION: {name} bytes {st['comm_bytes']} > "
                  f"golden {DEEPGLOBAL_GOLDEN_BYTES}", file=sys.stderr)
            ok = False
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
