"""North-star projection: 40-qubit depth-20 RCS on a 256-chip pod.

BASELINE.json's north star is "40q depth-20 RCS wall-clock faster than
MPI+CUDA QuEST on 32xA100, on TPU v5p-256". No pod is attached to this
container, so this script does the strongest thing short of one: it
builds the EXACT 40-qubit, 256-device program through the production
sharded engine, lowers it to StableHLO over a 256-virtual-device mesh
(tracing allocates no state), and derives the wall-clock from the
program's OWN collective/pass schedule plus stated hardware constants.

The communication term prices through the planner's HIERARCHICAL
topology model (docs/DISTRIBUTED.md §topology): the mesh splits into
--hosts groups, the planner optimizes for weighted link cost (so the
schedule it prices is the one a topology-aware run would execute), and
the projection charges the verified intra-host bytes at --ici GB/s and
the cross-host share at --dci GB/s separately.

Outputs one JSON object; assumptions are fields, not prose, so the
projection recomputes under different constants
(--hbm/--ici/--dci GB/s, --hosts). See docs/POD_PROJECTION.md for the analysis,
including why the reference side of the north star is infeasible as
stated (QuEST cannot hold 2^40 amplitudes on 32 A100s at any precision).

Run: python scripts/pod_projection.py  (spawns a 256-device subprocess)
"""

import argparse
import json
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r'''
import jax
jax.config.update("jax_platforms", "cpu")
import json, sys, time
import numpy as np
sys.path.insert(0, %(repo)r)
from jax.sharding import Mesh
from quest_tpu.circuit import qft_circuit, random_circuit
from quest_tpu.env import AMP_AXIS
from quest_tpu.parallel.introspect import sharded_schedule

n, depth, D = %(n)d, %(depth)d, %(D)d
circuit_kind = %(circuit)r
c = (qft_circuit(n) if circuit_kind == "qft"
     else random_circuit(n, depth=depth, seed=7, entangler="cz"))
devs = jax.devices()
assert len(devs) == D
mesh = Mesh(np.array(devs), (AMP_AXIS,))

t0 = time.time()
rec = sharded_schedule(c.ops, n, False, mesh, engine="banded")
lower_s = time.time() - t0

# the projection builds on the comm planner's metric, which must match
# XLA's lowered accounting — a projection from a drifted predictor
# would be fiction (tests/test_comm.py pins this; re-asserted here).
# The hierarchical split must also tile the asserted total exactly.
assert rec["comm_matches_hlo"], rec
assert rec["comm_ici_bytes"] + rec["comm_dci_bytes"] \
    == rec["comm_bytes"], rec

print(json.dumps({
    "gates": len(c.ops), "lower_s": round(lower_s, 2),
    "collective_permutes": rec["collective_permutes"],
    "comm_exchanges": rec["comm_exchanges"],
    "comm_all_to_alls": rec["comm_all_to_alls"],
    "comm_bytes": rec["comm_bytes"],
    "comm_ici_bytes": rec["comm_ici_bytes"],
    "comm_dci_bytes": rec["comm_dci_bytes"],
    "comm_dci_exchanges": rec["comm_dci_exchanges"],
    "comm_strategy": rec["comm_strategy"],
    "comm_topology": rec["comm_topology"],
    "ici_bytes_per_device_per_step": rec["ici_bytes_per_device"],
    "local_band_passes": rec["local_band_passes"],
    "global_qubit_items": rec["global_qubit_items"],
    "local_n": rec["local_qubits"], "g": rec["global_qubits"],
}))
'''


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--circuit", choices=("rcs", "qft"), default="rcs")
    ap.add_argument("--n", type=int, default=40)
    ap.add_argument("--depth", type=int, default=20)
    ap.add_argument("--devices", type=int, default=256)
    ap.add_argument("--hbm", type=float, default=1550.0,
                    help="per-chip EFFECTIVE HBM GB/s. Default is the "
                    "CONSERVATIVE v5p figure: 2765 datasheet x 0.56, the "
                    "in-place streaming derate MEASURED on the attached "
                    "v5e (461 of 819 GB/s, docs/KERNELS.md) — the "
                    "headline projection quotes this number; pass "
                    "--hbm 2765 for the datasheet bound")
    ap.add_argument("--ici", type=float, default=450.0,
                    help="per-chip ICI egress GB/s (default: conservative "
                    "v5p 3D-torus estimate)")
    ap.add_argument("--hosts", type=int, default=64,
                    help="hosts the mesh splits into for the "
                    "hierarchical comm model (QUEST_COMM_TOPOLOGY; "
                    "default: 64 — a v5p-256 pod slice is 64 hosts x 4 "
                    "chips); 1 = flat single-tier pricing")
    ap.add_argument("--dci", type=float, default=100.0,
                    help="per-chip cross-host (DCI/DCN) egress GB/s "
                    "(default: conservative 100 — pod-level optical "
                    "interconnect per chip)")
    args = ap.parse_args()

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={args.devices}")
    # project the UNSLICED schedule: the HBM term below charges one
    # chunk read+write per exchange, and a sliced exchange (S
    # collective-permutes of 1/S chunk each) would inflate that by the
    # slice factor at unchanged real traffic
    env["QUEST_EXCHANGE_SLICES"] = "1"
    env["QUEST_EXCHANGE_SLICES_DCI"] = "0"
    # the hierarchical model the planner prices (and the projection
    # charges per-link below); weights mirror the bandwidth ratio so
    # plan CHOICE optimizes the same objective the projection reports
    if args.hosts > 1:
        env["QUEST_COMM_TOPOLOGY"] = (
            f"hosts={args.hosts},ici=1,"
            f"dci={max(args.ici / args.dci, 1.0):g}")
    else:
        env["QUEST_COMM_TOPOLOGY"] = "0"
    code = WORKER % {"repo": REPO, "n": args.n, "depth": args.depth,
                     "D": args.devices, "circuit": args.circuit}
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=900)
    if r.returncode != 0:
        print(r.stderr[-3000:], file=sys.stderr)
        raise SystemExit(1)
    rec = json.loads(r.stdout.strip().splitlines()[-1])

    chunk_gb = 2 * 4 * (1 << args.n) / args.devices / 1e9
    # each local band pass reads+writes the chunk; each collective
    # exchange (pair permute OR all-to-all relabel) also costs ~1
    # read+write to apply/shuffle what moved. comm_exchanges is the comm
    # planner's HLO-verified count — the old hand-derived
    # collective_permutes figure missed the all-to-all events entirely
    hbm_gb = (rec["local_band_passes"] + rec["comm_exchanges"]) \
        * 2 * chunk_gb
    # per-link GB from the planner's verified, topology-split payload:
    # intra-host traffic rides ICI at its bandwidth, the cross-host
    # share rides the (much slower) DCI — pricing DCI bytes at the flat
    # ICI rate is exactly the optimism the hierarchical model exists to
    # remove (docs/DISTRIBUTED.md §topology). The two are separate
    # media and overlap; the comm wall is the slower stream.
    ici_gb = rec["comm_ici_bytes"] / 1e9
    dci_gb = rec["comm_dci_bytes"] / 1e9
    t_hbm = hbm_gb / args.hbm
    t_ici = ici_gb / args.ici
    t_dci = dci_gb / args.dci
    t_comm = max(t_ici, t_dci)
    rec.update({
        "circuit": args.circuit,
        "n": args.n, "depth": args.depth, "devices": args.devices,
        "hosts": args.hosts,
        "chunk_gb": round(chunk_gb, 2),
        "assumed_hbm_gbps": args.hbm, "assumed_ici_gbps": args.ici,
        "assumed_dci_gbps": args.dci,
        "hbm_gb_per_device": round(hbm_gb, 1),
        "ici_gb_per_device": round(ici_gb, 2),
        "dci_gb_per_device": round(dci_gb, 2),
        "t_hbm_s": round(t_hbm, 2), "t_ici_s": round(t_ici, 2),
        "t_dci_s": round(t_dci, 2),
        "projected_wall_clock_s": round(max(t_hbm, t_comm) + 0.2 * min(
            t_hbm, t_comm), 2),  # collectives overlap compute imperfectly
        "hbm_provenance": ("v5p datasheet 2765 GB/s x 0.56 measured v5e "
                           "in-place derate (docs/KERNELS.md); "
                           "--hbm 2765 for the datasheet bound"
                           if args.hbm == 1550.0 else "CLI override"),
    })
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
