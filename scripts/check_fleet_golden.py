#!/usr/bin/env python
"""CI gate for the serve fleet (docs/SERVING.md §fleet): fails if

  * FAILOVER loses a future — with a seeded plan killing one replica
    past its restart budget mid-stream, every submitted future must
    resolve (the dead replica's undispatched requests re-served by the
    survivor; `fleet_failover_unresolved` must be 0 and the failover
    path must actually fire), or
  * SHED hits the wrong class — under overload with two priority
    classes, 100% of shed rejections must land on the lower class
    (`fleet_shed_lowest_only`), or
  * a DURABLE job through serve does not resume — the seeded
    `durable.preempt` kill must fire mid-checkpoint-chain, the job
    must resume from the chain (not restart hollow from op 0), and the
    final amplitudes must hash bit-identical to an uninterrupted
    `run_durable` (`fleet_durable_resume_bitexact`).

The committed contracts live HERE (the CI gate) next to the
sweep/batch/expec/comm/durable gates; the per-path pins live in
tests/test_fleet.py — a change that moves either must update both,
consciously.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    import bench

    rec = bench._measure_fleet()
    print(json.dumps(rec))
    ok = True
    if rec["fleet_failovers"] < 1:
        print("GATE BROKEN: the seeded replica kill never caused a "
              "fleet failover — the scenario no longer exercises the "
              "requeue path", file=sys.stderr)
        ok = False
    if rec["fleet_failover_unresolved"] != 0:
        print(f"REGRESSION: {rec['fleet_failover_unresolved']} "
              f"future(s) left unresolved after a replica death — the "
              f"failover contract lost requests", file=sys.stderr)
        ok = False
    if rec["fleet_shed_requests"] < 1:
        print("GATE BROKEN: the overload leg shed nothing — pressure "
              "never crossed the threshold and the shed contract went "
              "unexercised", file=sys.stderr)
        ok = False
    if not rec["fleet_shed_lowest_only"]:
        print(f"REGRESSION: sheds hit the higher priority class "
              f"({rec['fleet_shed_p1']} class-1 sheds vs "
              f"{rec['fleet_shed_p0']} class-0) — the "
              f"lowest-class-first contract broke", file=sys.stderr)
        ok = False
    if not rec["fleet_durable_preempted"]:
        print("GATE BROKEN: the seeded durable.preempt plan never "
              "fired — the durable leg no longer exercises resume",
              file=sys.stderr)
        ok = False
    if rec["fleet_durable_resumed"] < 1:
        print("GATE BROKEN: the kill landed before the first stamp — "
              "the durable 'resume' restarted from op 0 and verified "
              "nothing about checkpoint restore", file=sys.stderr)
        ok = False
    if not rec["fleet_durable_resume_bitexact"]:
        print("REGRESSION: the preempted durable-through-serve job is "
              "NOT bit-identical to the uninterrupted run",
              file=sys.stderr)
        ok = False
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
