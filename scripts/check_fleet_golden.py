#!/usr/bin/env python
"""CI gate for the serve fleet (docs/SERVING.md §fleet): fails if

  * FAILOVER loses a future — with a seeded plan killing one replica
    past its restart budget mid-stream, every submitted future must
    resolve (the dead replica's undispatched requests re-served by the
    survivor; `fleet_failover_unresolved` must be 0 and the failover
    path must actually fire), or
  * SHED hits the wrong class — under overload with two priority
    classes, 100% of shed rejections must land on the lower class
    (`fleet_shed_lowest_only`), or
  * a DURABLE job through serve does not resume — the seeded
    `durable.preempt` kill must fire mid-checkpoint-chain, the job
    must resume from the chain (not restart hollow from op 0), and the
    final amplitudes must hash bit-identical to an uninterrupted
    `run_durable` (`fleet_durable_resume_bitexact`), or
  * the PROCESS fleet (docs/SERVING.md §process-fleet) breaks one of
    its three PR-18 contracts — a 2-process fleet must serve results
    BIT-IDENTICAL to one in-process ServeEngine (the IPC boundary is
    a transport, never a numerics change); a mid-stream SIGKILL of one
    worker must lose ZERO accepted requests (heartbeat-loss respawn +
    resubmit); and the autoscaler must CONVERGE — grow under a held
    backlog, shrink back to min when it drains, no thrash past the
    bounds.

The committed contracts live HERE (the CI gate) next to the
sweep/batch/expec/comm/durable gates; the per-path pins live in
tests/test_fleet.py and tests/test_ipc.py — a change that moves
either must update both, consciously.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def check_process_fleet() -> bool:
    """The three PR-18 process-fleet gates, run directly (no bench
    sweep — CI wants the fast fail): bit-identity vs one in-process
    engine, zero loss under SIGKILL, autoscaler convergence."""
    import signal

    import jax
    import numpy as np

    import bench as B
    from quest_tpu.serve import Autoscaler, ServeEngine, ServeFleet, metrics

    ok = True
    n = 9
    n_req = 32
    circ = B._build_circuit(n)
    rng = np.random.default_rng(7)
    states = rng.standard_normal((n_req, 2, 1 << n)).astype(np.float32)
    states /= np.sqrt((states ** 2).sum(axis=(1, 2), keepdims=True))

    def bitexact(a, b) -> bool:
        """Recursive bit-identity: shots results are tuples of arrays
        with per-element shapes, state results plain arrays."""
        if isinstance(a, (tuple, list)):
            return (isinstance(b, (tuple, list)) and len(a) == len(b)
                    and all(bitexact(x, y) for x, y in zip(a, b)))
        return np.array_equal(np.asarray(jax.device_get(a)),
                              np.asarray(jax.device_get(b)))

    # gate 1: bit-identity — the same stream through one in-process
    # engine and through a 2-process fleet must match to the bit
    with ServeEngine(max_wait_ms=2, max_batch=8,
                     registry=metrics.Registry()) as eng:
        refs = [eng.submit(circ, state=states[i]).result(timeout=300)
                for i in range(n_req)]
        ref_shots = eng.submit(circ, shots=64,
                               key=jax.random.key(3)).result(timeout=300)
    with ServeFleet(replicas=2, process=True, max_wait_ms=2,
                    max_batch=8, registry=metrics.Registry()) as fleet:
        outs = [fleet.submit(circ, state=states[i]).result(timeout=300)
                for i in range(n_req)]
        out_shots = fleet.submit(
            circ, shots=64, key=jax.random.key(3)).result(timeout=300)
        mismatch = sum(not bitexact(r, o) for r, o in zip(refs, outs))
        if mismatch or not bitexact(ref_shots, out_shots):
            print(f"REGRESSION: process fleet served {mismatch} "
                  f"state result(s) (shots match: "
                  f"{bitexact(ref_shots, out_shots)}) that are "
                  f"NOT bit-identical to the in-process engine — the "
                  f"IPC boundary changed numerics", file=sys.stderr)
            ok = False

        # gate 2: SIGKILL one worker mid-stream — zero accepted
        # requests may be lost (respawn + resubmit on the proxy, or
        # requeue onto the survivor)
        futs = [fleet.submit(circ, state=states[i])
                for i in range(n_req)]
        os.kill(fleet._engines[0].worker_pid(), signal.SIGKILL)
        lost = 0
        for f in futs:
            try:
                f.result(timeout=300)
            except Exception:
                lost += 1
        if lost:
            print(f"REGRESSION: SIGKILL of one process replica lost "
                  f"{lost}/{n_req} accepted request(s) — the "
                  f"heartbeat-loss respawn/resubmit contract broke",
                  file=sys.stderr)
            ok = False

    # gate 3: autoscaler convergence — a held backlog must grow the
    # fleet toward max, the drained fleet must shrink back to min, and
    # the loop must sit still at both ends (no thrash past the bounds)
    # shed_threshold at its 1.0 ceiling and a backlog priced under it:
    # this leg needs the queue to HOLD (the autoscaler's signal), not
    # shed away. 13 queued / 16 capacity = 0.81 pressure at 1 replica,
    # 0.41 at 2, 0.27 at 3 — a (0.1, 0.3) band converges at max=3.
    with ServeFleet(replicas=1, process=True, max_wait_ms=600_000,
                    max_batch=4 * n_req, max_queue=16,
                    shed_threshold=1.0,
                    registry=metrics.Registry()) as fleet:
        auto = Autoscaler(fleet, min_replicas=1, max_replicas=3,
                          high_water=0.3, low_water=0.1,
                          up_ticks=1, down_ticks=2, cooldown_ticks=0)
        futs = [fleet.submit(circ, state=states[i]) for i in range(13)]
        grew = [auto.tick() for _ in range(6)]
        if fleet.replicas != 3 or grew.count("up") != 2:
            print(f"REGRESSION: autoscaler did not converge up under "
                  f"backlog (replicas={fleet.replicas}, "
                  f"actions={auto.stats()['actions']})", file=sys.stderr)
            ok = False
        fleet.drain(timeout_s=300)
        for f in futs:
            f.result(timeout=300)
        shrank = [auto.tick() for _ in range(8)]
        if fleet.replicas != 1 or shrank.count("down") != 2:
            print(f"REGRESSION: autoscaler did not converge back to "
                  f"min after drain (replicas={fleet.replicas}, "
                  f"actions={auto.stats()['actions']})", file=sys.stderr)
            ok = False
    if ok:
        print("process fleet gates: bit-identity, kill-zero-loss, "
              "autoscaler convergence all hold")
    return ok


def main() -> int:
    import bench

    rec = bench._measure_fleet()
    print(json.dumps(rec))
    ok = True
    if rec["fleet_failovers"] < 1:
        print("GATE BROKEN: the seeded replica kill never caused a "
              "fleet failover — the scenario no longer exercises the "
              "requeue path", file=sys.stderr)
        ok = False
    if rec["fleet_failover_unresolved"] != 0:
        print(f"REGRESSION: {rec['fleet_failover_unresolved']} "
              f"future(s) left unresolved after a replica death — the "
              f"failover contract lost requests", file=sys.stderr)
        ok = False
    if rec["fleet_shed_requests"] < 1:
        print("GATE BROKEN: the overload leg shed nothing — pressure "
              "never crossed the threshold and the shed contract went "
              "unexercised", file=sys.stderr)
        ok = False
    if not rec["fleet_shed_lowest_only"]:
        print(f"REGRESSION: sheds hit the higher priority class "
              f"({rec['fleet_shed_p1']} class-1 sheds vs "
              f"{rec['fleet_shed_p0']} class-0) — the "
              f"lowest-class-first contract broke", file=sys.stderr)
        ok = False
    if not rec["fleet_durable_preempted"]:
        print("GATE BROKEN: the seeded durable.preempt plan never "
              "fired — the durable leg no longer exercises resume",
              file=sys.stderr)
        ok = False
    if rec["fleet_durable_resumed"] < 1:
        print("GATE BROKEN: the kill landed before the first stamp — "
              "the durable 'resume' restarted from op 0 and verified "
              "nothing about checkpoint restore", file=sys.stderr)
        ok = False
    if not rec["fleet_durable_resume_bitexact"]:
        print("REGRESSION: the preempted durable-through-serve job is "
              "NOT bit-identical to the uninterrupted run",
              file=sys.stderr)
        ok = False
    if not check_process_fleet():
        ok = False
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
