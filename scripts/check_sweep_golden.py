#!/usr/bin/env python
"""CI smoke gate for the sweep-fusion layer (docs/SWEEPS.md): fails if
QFT-30 or the fusion-resistant chain benchmark regress above their
committed golden `hbm_sweeps` values, asserted CPU-side through
Circuit.plan_stats() — pure host planning, no compile, no chip.

The goldens live HERE (the CI gate) and are mirrored by the tier-1
assertions in tests/test_sweeps.py; a planner change that moves either
must update both, consciously.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

QFT30_GOLDEN_SWEEPS = 6
CHAIN30_GOLDEN_SWEEPS = 1


def main() -> int:
    import bench
    from quest_tpu.circuit import qft_circuit

    qft = qft_circuit(30).plan_stats()["fused"]
    chain = bench._build_chain_circuit(30).plan_stats()["fused"]
    rec = {
        "qft30_hbm_sweeps": qft["hbm_sweeps"],
        "qft30_stages": qft["stages"],
        "chain30_hbm_sweeps": chain["hbm_sweeps"],
        "chain30_stages": chain["stages"],
    }
    print(json.dumps(rec))
    ok = True
    if qft["hbm_sweeps"] > QFT30_GOLDEN_SWEEPS:
        print(f"REGRESSION: QFT-30 hbm_sweeps {qft['hbm_sweeps']} > "
              f"golden {QFT30_GOLDEN_SWEEPS}", file=sys.stderr)
        ok = False
    if not qft["hbm_sweeps"] < qft["stages"]:
        print("REGRESSION: QFT-30 hbm_sweeps not strictly below the "
              "per-stage pass count", file=sys.stderr)
        ok = False
    if chain["hbm_sweeps"] > CHAIN30_GOLDEN_SWEEPS:
        print(f"REGRESSION: chain hbm_sweeps {chain['hbm_sweeps']} > "
              f"golden {CHAIN30_GOLDEN_SWEEPS}", file=sys.stderr)
        ok = False
    if not 2 * chain["hbm_sweeps"] <= chain["stages"]:
        print("REGRESSION: chain sweep reduction below 2x",
              file=sys.stderr)
        ok = False
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
