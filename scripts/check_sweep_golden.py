#!/usr/bin/env python
"""CI smoke gate for the sweep-fusion layer (docs/SWEEPS.md): fails if
QFT-30 or the fusion-resistant chain benchmark regress above their
committed golden `hbm_sweeps` values, asserted CPU-side through
Circuit.plan_stats() — pure host planning, no compile, no chip.

Round 6 additions (the decoupled sweep pipeline, ISSUE 11):

  * the headline plan must report the pipeline schedule
    (`pipeline_in_slots` / `pipeline_out_slots` /
    `pipeline_overlap_steps`, with overlap >= 1 — every launch streams
    the next block under the current block's stage loop);
  * `QUEST_FUSED_PIPELINE=0` must reproduce the legacy fused record
    BIT-FOR-BIT (same keys, same values, no pipeline_* keys) — the
    silicon A/B control cannot drift;
  * the bench headline schema (bench.HEADLINE_JSON_KEYS) must carry
    the round's new keys (pipeline_*, f64_28q_*, rcs_*) so the next
    chip run lands in the BENCH_r*.json trajectory without
    hand-editing.

The goldens live HERE (the CI gate) and are mirrored by the tier-1
assertions in tests/test_sweeps.py; a planner change that moves either
must update both, consciously.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

QFT30_GOLDEN_SWEEPS = 6
CHAIN30_GOLDEN_SWEEPS = 1

# bench.py keys the trajectory parser needs for the round's deltas
REQUIRED_BENCH_KEYS = {
    "pipeline_in_slots", "pipeline_out_slots", "pipeline_overlap_steps",
    "f64_28q_peak_bytes", "f64_28q_fits_hbm", "f64_28q_chunk_elems",
    "f64_28q_value", "rcs_value", "rcs_gates_per_sec",
}


def _fused_stats(build, knob: str):
    os.environ["QUEST_FUSED_PIPELINE"] = knob
    try:
        return build().plan_stats()["fused"]
    finally:
        os.environ.pop("QUEST_FUSED_PIPELINE", None)


def main() -> int:
    import bench
    from quest_tpu.circuit import qft_circuit

    qft = qft_circuit(30).plan_stats()["fused"]
    chain = bench._build_chain_circuit(30).plan_stats()["fused"]
    # the pipeline gates below force the knob both ways; the printed
    # record reports the SAME knob-on plan the gates check, so the
    # emitted JSON always describes what was gated (an ambient
    # QUEST_FUSED_PIPELINE=0 in the environment cannot skew it)
    on = _fused_stats(lambda: bench._build_circuit(30), "1")
    rec = {
        "qft30_hbm_sweeps": qft["hbm_sweeps"],
        "qft30_stages": qft["stages"],
        "chain30_hbm_sweeps": chain["hbm_sweeps"],
        "chain30_stages": chain["stages"],
        "pipeline_in_slots": on.get("pipeline_in_slots"),
        "pipeline_out_slots": on.get("pipeline_out_slots"),
        "pipeline_overlap_steps": on.get("pipeline_overlap_steps"),
    }
    print(json.dumps(rec))
    ok = True
    if qft["hbm_sweeps"] > QFT30_GOLDEN_SWEEPS:
        print(f"REGRESSION: QFT-30 hbm_sweeps {qft['hbm_sweeps']} > "
              f"golden {QFT30_GOLDEN_SWEEPS}", file=sys.stderr)
        ok = False
    if not qft["hbm_sweeps"] < qft["stages"]:
        print("REGRESSION: QFT-30 hbm_sweeps not strictly below the "
              "per-stage pass count", file=sys.stderr)
        ok = False
    if chain["hbm_sweeps"] > CHAIN30_GOLDEN_SWEEPS:
        print(f"REGRESSION: chain hbm_sweeps {chain['hbm_sweeps']} > "
              f"golden {CHAIN30_GOLDEN_SWEEPS}", file=sys.stderr)
        ok = False
    if not 2 * chain["hbm_sweeps"] <= chain["stages"]:
        print("REGRESSION: chain sweep reduction below 2x",
              file=sys.stderr)
        ok = False

    # -- decoupled-pipeline schedule gates (ISSUE 11) -----------------
    if on.get("pipeline_overlap_steps", 0) < 1:
        print(f"REGRESSION: headline plan pipeline_overlap_steps "
              f"{on.get('pipeline_overlap_steps')} < 1 — the read "
              f"stream no longer runs ahead of compute", file=sys.stderr)
        ok = False
    if on.get("pipeline_in_slots", 0) < 2 or on.get(
            "pipeline_out_slots", 0) < 1:
        print(f"REGRESSION: pipeline slot rings degenerate "
              f"(in={on.get('pipeline_in_slots')}, "
              f"out={on.get('pipeline_out_slots')})", file=sys.stderr)
        ok = False
    off = _fused_stats(lambda: bench._build_circuit(30), "0")
    stripped = {k: v for k, v in on.items()
                if not k.startswith("pipeline_")}
    if any(k.startswith("pipeline_") for k in off):
        print("REGRESSION: QUEST_FUSED_PIPELINE=0 still reports "
              "pipeline_* keys — the legacy record drifted",
              file=sys.stderr)
        ok = False
    if off != stripped:
        print(f"REGRESSION: QUEST_FUSED_PIPELINE=0 fused record is not "
              f"bit-for-bit the knob-on record minus pipeline_* keys "
              f"(off={off}, on-minus-pipeline={stripped}) — the A/B "
              f"control plans a different schedule", file=sys.stderr)
        ok = False

    # -- bench JSON schema carries the round's keys -------------------
    missing = REQUIRED_BENCH_KEYS - bench.HEADLINE_JSON_KEYS
    if missing:
        print(f"REGRESSION: bench.HEADLINE_JSON_KEYS is missing "
              f"{sorted(missing)} — the next chip run cannot land its "
              f"deltas in the trajectory files", file=sys.stderr)
        ok = False
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
