"""Pin the relay first-execution cost: per-KERNEL or per-BYTE?

Round-3 measured a fresh process paying 51-266 s before its first step
completes even with a fully warm XLA cache (measured_tpu.json
compile_latency note). VERDICT r3 item 5 asks whether shrinking the
distinct Mosaic-kernel count would cut it, or whether the cost tracks
program SIZE. The existing numbers already hint per-byte (QFT-30: only
8 distinct kernels, 266 s; bench: few kernels, small program, 8-14 s);
this probe separates the variables with two synthetic programs of the
SAME total size and very different kernel counts:

  one-kernel   ONE segment structure applied k times (operands differ,
               structure shared -> 1 Mosaic kernel, large program)
  k-kernels    k structurally DISTINCT segments (phase-predicate
               layouts force distinct geometries via scattered bits),
               same program length

Each runs in a FRESH subprocess twice: run 1 (cold process, warm XLA
disk cache after the first iteration) and run 2 (second fresh process)
— the difference between programs at matched size is the per-kernel
cost; the growth with k at matched kernel count is the per-byte cost.

Usage: python scripts/probe_cold_start.py [n] [k]   (default 26, 24)
"""
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import json, sys, time
sys.path.insert(0, %(repo)r)
t_import0 = time.perf_counter()
from quest_tpu.precision import enable_compile_cache
enable_compile_cache()
import jax
import jax.numpy as jnp
import numpy as np
from quest_tpu.ops import pallas_band as PB
from quest_tpu.state import basis_planes, fused_state_shape

mode = %(mode)r
n = %(n)d
k = %(k)d

stages_list = []
arrays_list = []
rng = np.random.default_rng(3)
for j in range(k):
    if mode == "one-kernel":
        bit = n - 10          # same structure every time
    else:
        bit = 3 + (j %% (n - 13))   # distinct scattered geometry per j
    g = rng.standard_normal((2, 2, 2)).astype(np.float32)
    stages_list.append([PB.MatStage("sc", 2, False, (), (), bit)])
    arrays_list.append([jnp.asarray(g)])

fns = [PB.compile_segment(st, n) for st in stages_list]

def program(amps):
    for fn, arrs in zip(fns, arrays_list):
        amps = fn(amps, arrs)
    return amps

jfn = jax.jit(program, donate_argnums=(0,))
amps = basis_planes(0, n=n, rdt=jnp.float32, shape=fused_state_shape(n))
t0 = time.perf_counter()
amps = jfn(amps)
_ = np.asarray(amps[0, 0, :4])
first = time.perf_counter() - t0
t0 = time.perf_counter()
amps = jfn(amps)
_ = np.asarray(amps[0, 0, :4])
steady = time.perf_counter() - t0
print("[probe-result] " + json.dumps(dict(
    mode=mode, n=n, k=k,
    first_s=round(first, 2), steady_s=round(steady, 3))), flush=True)
"""


def run(mode, n, k):
    code = WORKER % dict(repo=REPO, mode=mode, n=n, k=k)
    t0 = time.time()
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=2400, cwd=REPO)
    except subprocess.TimeoutExpired:
        print(f"[probe] TIMEOUT mode={mode} k={k}", flush=True)
        return None
    wall = time.time() - t0
    for line in r.stdout.splitlines():
        if line.startswith("[probe-result]"):
            rec = json.loads(line[len("[probe-result]"):])
            rec["process_wall_s"] = round(wall, 1)
            print("[probe-result] " + json.dumps(rec), flush=True)
            return rec
    print(f"[probe] FAILED mode={mode} k={k}: {r.stdout[-300:]} "
          f"{r.stderr[-1200:]}", flush=True)
    return None


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 26
    k = int(sys.argv[2]) if len(sys.argv) > 2 else 24
    for mode in ("one-kernel", "k-kernels"):
        # twice: first process populates the persistent XLA cache for
        # this structure set; the second isolates the relay cost
        run(mode, n, k)
        run(mode, n, k)
    # size scaling at fixed kernel count
    run("one-kernel", n, k * 2)


if __name__ == "__main__":
    main()
