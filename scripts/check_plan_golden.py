#!/usr/bin/env python
"""CI smoke gate for the plan autotuner (docs/PLANNING.md): fails if
the priced chooser can regress a golden circuit, if its comm
predictions drift from the lowered HLO, or if the persistent plan
cache stops making a warm restart a LOAD instead of a search.

Gates:
  * INCUMBENT-NEVER-WORSE on every golden circuit (the headline
    rotation block, the fusion-resistant chain, the deep-global
    sharded testbed; unsharded and over the 8-device shard geometry):
    the chosen plan's priced total_ms must sit <= the incumbent
    candidate's — incumbent-wins-ties means a violation is a broken
    tie-break, the same contract check_comm_golden.py holds for
    choose_plan;
  * PLAN == HLO on the comm axis: the autotuned plan's predicted
    collective schedule for the deep-global circuit over an 8-device
    mesh must equal the lowered StableHLO's collective accounting
    exactly (introspect.assert_plan_comm — the plan->predict->assert
    discipline, tests/test_comm.py's contract lifted to the IR);
  * WARM RESTART IS A LOAD: prices a serve-warmup grid cold (fresh
    plan-cache dir), then re-prices REBUILT equal circuits — the
    simulated process restart — and requires zero plan searches (every
    plan loads content-addressed from disk) and zero compile-cache
    misses (the persistent compile cache's half of the same contract;
    the in-process zero-RETRACE pin under CompileAuditor lives in
    tests/test_plan.py).
"""

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
# the goldens must not move under a user's ambient knobs
for _k in ("QUEST_COMM_TOPOLOGY", "QUEST_APPLY_AUTOROUTE",
           "QUEST_PLAN_CACHE", "QUEST_PLAN_CACHE_DIR"):
    os.environ.pop(_k, None)

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

DEVICES = 8


def _golden_circuits(bench):
    return (
        ("headline16", bench._build_circuit(16), None),
        ("chain16", bench._build_chain_circuit(16), None),
        ("deepglobal", bench._build_deep_global_circuit(6, 6), None),
        ("headline16-sharded", bench._build_circuit(16), DEVICES),
        ("deepglobal-sharded", bench._build_deep_global_circuit(6, 6),
         DEVICES),
    )


def main() -> int:
    import jax
    import numpy as np

    import bench
    from quest_tpu.precision import enable_compile_cache
    from quest_tpu import plan as P
    from quest_tpu.env import AMP_AXIS
    from quest_tpu.parallel import introspect as I
    from jax.sharding import Mesh

    ok = True
    rec = {}

    # gate 1: incumbent-never-worse, every golden circuit
    for name, c, devices in _golden_circuits(bench):
        plan = P.autotune(c, devices=devices, persist=False)
        chosen = plan.cost["total_ms"]
        inc = plan.candidates[plan.incumbent]["total_ms"]
        rec[name] = {"engine": plan.engine, "incumbent": plan.incumbent,
                     "chosen_ms": chosen, "incumbent_ms": inc}
        if chosen > inc:
            print(f"REGRESSION: {name}: chosen plan "
                  f"{plan.engine!r} priced at {chosen} ms ABOVE the "
                  f"incumbent {plan.incumbent!r} at {inc} ms — "
                  f"incumbent-wins-ties is broken", file=sys.stderr)
            ok = False

    # gate 2: the plan's comm predictions == lowered StableHLO
    c = bench._build_deep_global_circuit(6, 6)
    mesh = Mesh(np.array(jax.devices()[:DEVICES]), (AMP_AXIS,))
    plan = P.autotune(c, mesh=mesh, persist=False)
    try:
        lowered = I.assert_plan_comm(plan, c.ops, 6, False, mesh,
                                     engine="banded")
        rec["plan_vs_hlo"] = {
            "exchanges": plan.comm["comm_exchanges"],
            "bytes": plan.comm["comm_bytes"],
            "matches": bool(lowered["comm_matches_hlo"]),
        }
        if not lowered["comm_matches_hlo"]:
            print("REGRESSION: lowered schedule's own predictor "
                  "parity (comm_matches_hlo) is false", file=sys.stderr)
            ok = False
    except AssertionError as e:
        print(f"REGRESSION: {e}", file=sys.stderr)
        ok = False

    # gate 3: warm restart is a load — zero searches, zero compiles
    from quest_tpu.serve import metrics
    from quest_tpu.serve.engine import ServeEngine
    from quest_tpu.serve.warmup import warmup
    with tempfile.TemporaryDirectory() as d:
        os.environ["QUEST_PLAN_CACHE_DIR"] = d
        # the XLA side of the warm-restart contract: min_compile_secs=0
        # so even this gate's millisecond programs persist to disk —
        # the rebuilt circuits' re-traces must all be disk hits
        enable_compile_cache(path=os.path.join(d, "xla"),
                             min_compile_secs=0.0)
        with ServeEngine(max_batch=2) as eng:
            cold = warmup(eng, [bench._build_circuit(4),
                                bench._build_chain_circuit(4)],
                          buckets=(1, 2))
            # the simulated restart: REBUILT equal circuits (fresh
            # objects — no instance-level caches to hide behind), warm
            # plan cache + warm XLA compile cache on disk. A re-trace
            # still happens (fresh jit functions); what must be ZERO is
            # fresh XLA compiles (every lookup a disk hit — the
            # compile-cache listener's miss counter) and fresh plan
            # searches
            P.reset_cache_stats()
            misses0 = metrics.snapshot()["counters"].get(
                "compile_cache_misses", 0)
            warm = warmup(eng, [bench._build_circuit(4),
                                bench._build_chain_circuit(4)],
                          buckets=(1, 2))
            miss_delta = metrics.snapshot()["counters"].get(
                "compile_cache_misses", 0) - misses0
        os.environ.pop("QUEST_PLAN_CACHE_DIR", None)
        rec["warmup"] = {"cold": cold["plan_cache"],
                         "warm": warm["plan_cache"],
                         "warm_compile_misses": miss_delta}
        if cold["plan_cache"]["searches"] < 2:
            print(f"REGRESSION: cold warmup should have priced 2 "
                  f"circuits, searched {cold['plan_cache']['searches']}",
                  file=sys.stderr)
            ok = False
        if warm["plan_cache"]["searches"] != 0:
            print(f"REGRESSION: warm-cache warmup ran "
                  f"{warm['plan_cache']['searches']} plan search(es); "
                  f"a warm restart must LOAD every plan from disk",
                  file=sys.stderr)
            ok = False
        if warm["plan_cache"]["hits"] < 2:
            print(f"REGRESSION: warm-cache warmup hit only "
                  f"{warm['plan_cache']['hits']} of 2 plans",
                  file=sys.stderr)
            ok = False
        if miss_delta != 0:
            print(f"REGRESSION: warm-cache warmup took "
                  f"{miss_delta} compile-cache miss(es); the persistent "
                  f"compile cache must make a warm restart compile 0 "
                  f"fresh programs", file=sys.stderr)
            ok = False

    print(json.dumps(rec))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
