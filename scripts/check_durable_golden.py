#!/usr/bin/env python
"""CI gate for the durable execution runtime (docs/RESILIENCE.md
§durable): fails if

  * a preempted-at-a-boundary durable run does NOT resume to the exact
    uninterrupted amplitudes (sha256 over the final planes — the resume
    contract is BIT identity, no tolerance), or
  * checkpoint overhead exceeds 10% of the sweep time, measured from
    the executor's own `durable_checkpoint_s` histogram over the
    `bench.py durable` scenario (per-cut sentinel + host gather +
    atomic write vs the same run's step time — one instrumented run,
    not a wall-clock A/B difference).

The committed budget lives HERE (the CI gate); the bit-identity pins
per engine live in tests/test_durable.py — a change that moves either
must update both, consciously.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

OVERHEAD_BUDGET = 0.10     # fraction of sweep time (measured ~0.03-0.05
                           # on the CI host at every=64: 2.5x margin)


def main() -> int:
    import bench

    rec = bench._measure_durable()
    print(json.dumps(rec))
    ok = True
    if not rec["durable_preempted"]:
        print("GATE BROKEN: the seeded durable.preempt plan never "
              "fired — the scenario no longer exercises resume",
              file=sys.stderr)
        ok = False
    if not rec["durable_resumed_from_checkpoint"]:
        print("GATE BROKEN: the kill landed before the first stamp — "
              "the 'resume' leg restarted from op 0 and verified "
              "nothing about checkpoint restore", file=sys.stderr)
        ok = False
    if not rec["durable_resume_bitexact"]:
        print("REGRESSION: preempted+resumed durable run is NOT "
              "bit-identical to the uninterrupted run",
              file=sys.stderr)
        ok = False
    if rec["durable_checkpoints"] < 1:
        print("GATE BROKEN: the scenario stamped no checkpoints — "
              "nothing was measured", file=sys.stderr)
        ok = False
    if rec["durable_overhead_frac"] > OVERHEAD_BUDGET:
        print(f"REGRESSION: durable checkpoint overhead "
              f"{rec['durable_overhead_frac']:.3f} > budget "
              f"{OVERHEAD_BUDGET} of sweep time", file=sys.stderr)
        ok = False
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
