"""Per-band microbenchmark + HLO inspection on the real chip: times one
band contraction per band index and counts transpose/copy fusions in the
optimized HLO (the suspected bandwidth thief)."""
import re
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")
from quest_tpu.ops import apply as A
from quest_tpu.ops import fusion as F


def one_band(n, b, reps=10):
    ql, w = F.band_range(n, b)
    rng = np.random.default_rng(b)
    m = rng.standard_normal((1 << w, 1 << w))
    q_, _ = np.linalg.qr(m)          # real orthogonal -> real_only path
    gre, gim = q_.astype(np.float32), np.zeros_like(q_, dtype=np.float32)

    def run(amps):
        return A.apply_band(amps, n, (gre, gim), ql, w)

    jit = jax.jit(run, donate_argnums=(0,))
    lowered = jit.lower(jax.ShapeDtypeStruct((2, 1 << n), jnp.float32))
    compiled = lowered.compile()
    txt = compiled.as_text()
    n_tr = len(re.findall(r"transpose", txt))
    n_copy = len(re.findall(r"\bcopy", txt))
    fusions = len(re.findall(r"kLoop|kInput|kOutput", txt))
    ca = compiled.cost_analysis()
    ca = ca[0] if isinstance(ca, list) else ca
    bytes_acc = ca.get("bytes accessed", float("nan")) if ca else float("nan")

    amps = jnp.zeros((2, 1 << n), dtype=jnp.float32).at[0, 0].set(1.0)
    out = jit(amps)
    out.block_until_ready()
    t0 = time.perf_counter()
    for _ in range(reps):
        out = jit(out)
    out.block_until_ready()
    dt = (time.perf_counter() - t0) / reps
    state_bytes = 2 * (1 << n) * 4
    print(f"band {b} (ql={ql},w={w}): {dt*1e3:7.2f} ms/pass  "
          f"{2*state_bytes/dt/1e9:6.1f} GB/s r+w  "
          f"hlo: transpose={n_tr} copy={n_copy} fusions={fusions} "
          f"bytes_accessed={bytes_acc:.3g}", flush=True)


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 24
    print("devices:", jax.devices(), flush=True)
    for b in range((n + 6) // 7):
        one_band(n, b)
