#!/usr/bin/env python
"""CI smoke gate for the grouped Pauli-sum expectation engine
(docs/EXPECTATION.md): fails if the grouped planner regresses above the
committed golden sweep counts, asserted CPU-side through
quest_tpu.ops.expec.plan_stats — pure host planning, no compile, no
chip (the check_sweep_golden.py discipline).

Goldens: an M-term all-diagonal sum is ONE |amp|^2 sweep; the 30q TFIM
sum (30 ZZ + 30 X) is at most 2 mask-group sweeps vs the per-term
baseline's 120 passes; the bench's 100-term random-support scenario
stays within 3 sweeps. The goldens live HERE and are mirrored by the
tier-1 assertions in tests/test_expec.py; a planner change that moves
either must update both, consciously.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

DIAG_GOLDEN_SWEEPS = 1
TFIM30_GOLDEN_SWEEPS = 2
RANDOM100_GOLDEN_SWEEPS = 3


def main() -> int:
    import numpy as np

    import bench
    from quest_tpu.ops import expec as E

    rng = np.random.default_rng(7)
    diag = E.plan_stats(np.where(rng.random((40, 30)) < 0.4, 3, 0), 30)
    tfim = E.plan_stats(bench._build_tfim_sum(30)[0], 30)
    rand = E.plan_stats(bench._build_random_support_sum(30)[0], 30)
    rec = {
        "diag30_expec_hbm_sweeps": diag["expec_hbm_sweeps"],
        "tfim30_expec_hbm_sweeps": tfim["expec_hbm_sweeps"],
        "tfim30_baseline_hbm_sweeps": tfim["baseline_hbm_sweeps"],
        "random100_expec_hbm_sweeps": rand["expec_hbm_sweeps"],
        "random100_expec_groups": rand["expec_groups"],
        "random100_baseline_hbm_sweeps": rand["baseline_hbm_sweeps"],
    }
    print(json.dumps(rec))
    ok = True
    if diag["expec_hbm_sweeps"] > DIAG_GOLDEN_SWEEPS:
        print(f"REGRESSION: all-diagonal sum expec_hbm_sweeps "
              f"{diag['expec_hbm_sweeps']} > golden {DIAG_GOLDEN_SWEEPS}",
              file=sys.stderr)
        ok = False
    if tfim["expec_hbm_sweeps"] > TFIM30_GOLDEN_SWEEPS:
        print(f"REGRESSION: TFIM-30 expec_hbm_sweeps "
              f"{tfim['expec_hbm_sweeps']} > golden {TFIM30_GOLDEN_SWEEPS}",
              file=sys.stderr)
        ok = False
    if not tfim["expec_hbm_sweeps"] * 10 <= tfim["baseline_hbm_sweeps"]:
        print("REGRESSION: TFIM-30 sweep reduction below 10x the "
              "per-term baseline", file=sys.stderr)
        ok = False
    if rand["expec_hbm_sweeps"] > RANDOM100_GOLDEN_SWEEPS:
        print(f"REGRESSION: 100-term random-support sum "
              f"expec_hbm_sweeps {rand['expec_hbm_sweeps']} > golden "
              f"{RANDOM100_GOLDEN_SWEEPS}", file=sys.stderr)
        ok = False
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
