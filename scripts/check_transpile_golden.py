#!/usr/bin/env python
"""CI smoke gate for the circuit transpiler (docs/TRANSPILE.md): fails
if a rewrite pass loses its fixture guarantee, if a rewritten stream
drifts from its raw stream, or if the transpile axis can regress a
golden circuit's plan.

Gates:
  * OP-COUNT CEILINGS on the pass fixtures: an inverse-pair chain must
    cancel to 0 ops; a 1q-run ladder must merge to 1 op per qubit; the
    rz/cx/rz/cx/rz exporter form of cp must resynthesize to one
    poolable diagonal; an adjacent Clifford+T toffoli pair must erase
    through the 3q identity-window scan;
  * EPS PARITY: on every workload-gallery class (bench.build_gallery_qasm,
    the corpus `bench.py gallery` sweeps), the rewritten stream's dense
    unitary per stretch matches the raw stream's to 1e-9 in complex128,
    and the executed f32 states stay eps-close;
  * INCUMBENT-NEVER-WORSE under QUEST_TRANSPILE=auto on every plan
    golden (the same circuits check_plan_golden.py prices): the chosen
    plan — transpiled family included in the pool — must price <= the
    raw incumbent; 'auto' keeps incumbent-wins-ties, so no golden can
    regress by construction;
  * KNOB-OFF IS BIT-FOR-BIT: with QUEST_TRANSPILE=0 the emitted plan
    stats must equal a pre-transpiler plan exactly — same keys (no
    "transpile" record), same values — so the axis is invisible when
    switched off (the cache key differs by the keyed knob; the PRICED
    ANSWER must not).
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
# the goldens must not move under a user's ambient knobs
for _k in ("QUEST_TRANSPILE", "QUEST_COMM_TOPOLOGY",
           "QUEST_APPLY_AUTOROUTE", "QUEST_PLAN_CACHE",
           "QUEST_PLAN_CACHE_DIR", "QUEST_FUSE"):
    os.environ.pop(_k, None)

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

DEVICES = 8


def main() -> int:
    import numpy as np

    import bench
    import quest_tpu as qt
    from quest_tpu import plan as P
    from quest_tpu import transpile as T
    from quest_tpu.circuit import Circuit, GateOp
    from quest_tpu.state import to_dense

    ok = True
    rec = {}

    # gate 1: op-count ceilings on the pass fixtures
    chain = Circuit(3)
    for q in range(3):
        chain.x(q).x(q).h(q).h(q).rz(q, 0.9).rz(q, -0.9)
    chain.cnot(0, 1).cnot(0, 1).cz(1, 2).cz(1, 2)
    ladder = Circuit(3)
    for _ in range(5):
        for q in range(3):
            ladder.h(q).rz(q, 0.2 * (q + 1)).ry(q, 0.1)
    cp = Circuit(2)
    cp.rz(0, 0.35).cnot(0, 1).rz(1, -0.35).cnot(0, 1).rz(1, 0.35)
    ccx2 = Circuit(3)
    sdg = np.conj(np.array([1.0, np.exp(0.25j * np.pi)]))
    for _ in range(2):
        ccx2.h(2).cnot(1, 2)
        ccx2.ops.append(GateOp("diagonal", (2,), operand=sdg))
        ccx2.cnot(0, 2).t(2).cnot(1, 2)
        ccx2.ops.append(GateOp("diagonal", (2,), operand=sdg))
        ccx2.cnot(0, 2).t(1).t(2).h(2).cnot(0, 1).t(0)
        ccx2.ops.append(GateOp("diagonal", (1,), operand=sdg))
        ccx2.cnot(0, 1)
    fixtures = (("inverse-chain", chain, 0),
                ("1q-ladder", ladder, 3),
                ("cp-exporter", cp, 1),
                ("toffoli-pair", ccx2, 1))
    for name, c, ceiling in fixtures:
        ops, rep = T.transpile_ops(c.ops, c.num_qubits)
        rec[name] = {"ops_in": rep["ops_in"], "ops_out": rep["ops_out"],
                     "ceiling": ceiling}
        if len(ops) > ceiling:
            print(f"REGRESSION: {name}: transpiled to {len(ops)} op(s), "
                  f"ceiling is {ceiling}", file=sys.stderr)
            ok = False

    # gate 2: eps parity on the gallery corpus (the bench's own circuits)
    worst = 0.0
    for cls, text in bench.build_gallery_qasm(6).items():
        raw = Circuit.from_qasm(text, transpile=False)
        tc, rep = T.transpile(raw)
        if cls == "ghz":
            import jax
            key = jax.random.PRNGKey(7)
            a, oa = raw.apply_measured(
                qt.init_debug_state(qt.create_qureg(6)), key)
            b, ob = tc.apply_measured(
                qt.init_debug_state(qt.create_qureg(6)), key)
            if not np.array_equal(np.asarray(oa), np.asarray(ob)):
                print(f"REGRESSION: {cls}: transpiled outcome sequence "
                      f"diverged under an identical key", file=sys.stderr)
                ok = False
            a, b = to_dense(a), to_dense(b)
        else:
            a = to_dense(raw.apply(
                qt.init_debug_state(qt.create_qureg(6)), donate=False))
            b = to_dense(tc.apply(
                qt.init_debug_state(qt.create_qureg(6)), donate=False))
        err = float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
        worst = max(worst, err)
        if err > 1e-4:
            print(f"REGRESSION: {cls}: transpiled state drifted "
                  f"{err:.2e} from the raw stream (f32 bound 1e-4)",
                  file=sys.stderr)
            ok = False
    rec["gallery_worst_state_err"] = worst

    # gate 3: incumbent-never-worse under auto, every plan golden
    goldens = (
        ("headline16", bench._build_circuit(16), None),
        ("chain16", bench._build_chain_circuit(16), None),
        ("deepglobal", bench._build_deep_global_circuit(6, 6), None),
        ("headline16-sharded", bench._build_circuit(16), DEVICES),
        ("deepglobal-sharded", bench._build_deep_global_circuit(6, 6),
         DEVICES),
    )
    os.environ["QUEST_TRANSPILE"] = "auto"
    for name, c, devices in goldens:
        plan = P.autotune(c, devices=devices, persist=False)
        chosen = plan.cost["total_ms"]
        inc = plan.candidates[plan.incumbent]["total_ms"]
        rec[name] = {"engine": plan.engine, "chosen_ms": chosen,
                     "incumbent_ms": inc}
        if chosen > inc:
            print(f"REGRESSION: {name}: under QUEST_TRANSPILE=auto the "
                  f"chosen plan {plan.engine!r} priced at {chosen} ms "
                  f"ABOVE the raw incumbent {plan.incumbent!r} at "
                  f"{inc} ms — the transpile axis broke "
                  f"incumbent-wins-ties", file=sys.stderr)
            ok = False

    # gate 4: knob-off record is bit-for-bit the pre-transpiler plan
    c = bench._build_circuit(16)
    os.environ["QUEST_TRANSPILE"] = "0"
    off = P.autotune(c, persist=False).stats()
    os.environ["QUEST_TRANSPILE"] = "auto"
    on = P.autotune(c, persist=False).stats()
    os.environ.pop("QUEST_TRANSPILE", None)
    if "transpile" in off:
        print("REGRESSION: QUEST_TRANSPILE=0 still emits a transpile "
              "record — the off switch must be invisible",
              file=sys.stderr)
        ok = False
    on_minus = {k: v for k, v in on.items() if k != "transpile"}
    if json.dumps(off, sort_keys=True, default=str) != \
            json.dumps(on_minus, sort_keys=True, default=str):
        print("REGRESSION: plan stats under QUEST_TRANSPILE=0 differ "
              "from auto beyond the transpile record itself — the axis "
              "leaked into another subsystem's pricing", file=sys.stderr)
        ok = False
    rec["knob_off_bit_identical"] = ok

    print(json.dumps(rec))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
