#!/usr/bin/env python
"""One-session silicon A/B bundle: every knob the stack shipped with a
"validate on first chip run" note, swept in ONE chip session and
emitted as ONE JSON report (ISSUE 11 satellite; closes the PR 3/4/8
flagged debts plus this round's pipeline knob):

  pipeline   QUEST_FUSED_PIPELINE 1 (decoupled multi-buffer rings) vs
             0 (legacy in-place slots) on the bench step — the
             tentpole's primary A/B
  nbuf       QUEST_FUSED_NBUF 2/3/4 under the LEGACY driver (the
             in-place slot count; 23.8 vs 20.5 ms history)
  sweep      QUEST_SWEEP_FUSION 1 (MAX_SWEEP_STAGES=64 merged sweeps)
             vs 0 (raw segment plan) — the PR 3 Mosaic
             register-pressure debt
  batch      compiled_batched(B) vs jax.lax.map of compiled_fused over
             the same B states — the PR 4 batch-grid debt
  exchange   QUEST_EXCHANGE_SLICES 1 vs 4 on the sharded fused step —
             the PR 8 ICI-overlap debt (needs >= 2 devices; recorded
             as skipped otherwise)
  autotune   the priced plan chooser's pick vs every forced engine
             (QUEST_APPLY_AUTOROUTE 1 vs 0) — whether the CPU cost
             model ranks engines the way silicon does (ISSUE 16,
             docs/PLANNING.md)
  transpile  QUEST_TRANSPILE auto vs 0 on the QASM workload gallery —
             whether the rewriter's predicted-sweep wins survive as
             real per-class requests/s on silicon (ISSUE 20,
             docs/TRANSPILE.md)

Every experiment runs in a SUBPROCESS: the kernel knobs are
import-once/keyed, so a fresh process per value is the only schedule
that cannot hand back a stale program, and one OOM/compile failure
cannot kill the matrix (the sweep_perf.py discipline).

Usage:
  python scripts/ab_silicon.py            # chip session (n=30 bench)
  python scripts/ab_silicon.py 28         # smaller headline size
  python scripts/ab_silicon.py --smoke    # CPU path smoke: tiny n,
                                          # interpret-mode kernels,
                                          # exercises every experiment
The report prints as one `[ab-silicon] {...}` JSON line (and pretty
JSON to stdout), keyed by experiment — ready to paste into the
round's benchmarks/measured_tpu.json notes.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import json, os, sys, time
sys.path.insert(0, %(repo)r)
from quest_tpu.precision import enable_compile_cache
enable_compile_cache()
import jax
import jax.numpy as jnp
import numpy as np

mode = %(mode)r
n = %(n)d
reps = %(reps)d
batch = %(batch)d
interpret = %(interpret)d == 1


def out(**kw):
    print("[ab-result] " + json.dumps(kw), flush=True)


def sync(x):
    from quest_tpu.env import sync_array
    sync_array(x)


if mode == "bench":
    # the headline step: 16 independent rotations, INNER_STEPS unrolled
    import bench
    from quest_tpu.state import basis_planes, fused_state_shape
    c = bench._build_circuit(n)
    iters = 8 if not interpret else 2
    step = c.compiled_fused(n, density=False, donate=True, iters=iters,
                            interpret=interpret)
    s = basis_planes(0, n=n, rdt=jnp.float32, shape=fused_state_shape(n))
    s = step(s)
    sync(s)
    t0 = time.perf_counter()
    for _ in range(reps):
        s = step(s)
    sync(s)
    dt = (time.perf_counter() - t0) / reps
    rec = c.plan_stats()["fused"]
    out(mode=mode, n=n,
        pipeline=os.environ.get("QUEST_FUSED_PIPELINE", "1"),
        nbuf=os.environ.get("QUEST_FUSED_NBUF", "3"),
        sweep_fusion=os.environ.get("QUEST_SWEEP_FUSION", "1"),
        hbm_sweeps=rec["hbm_sweeps"],
        overlap_steps=rec.get("pipeline_overlap_steps"),
        ms_per_application=round(dt / iters * 1e3, 2),
        gates_per_sec=round(16 * iters / dt, 1))
elif mode == "batch":
    # PR 4 debt: the batch grid dimension vs lax.map of the unbatched
    # program over the same states
    import bench
    c = bench._build_circuit(n)
    rng = np.random.default_rng(0)
    amps_b = jnp.asarray(
        rng.standard_normal((batch, 2, 1 << n)).astype(np.float32))
    fn_b = c.compiled_batched(batch, donate=False, interpret=interpret)
    got = fn_b(amps_b)
    sync(got)
    t0 = time.perf_counter()
    for _ in range(reps):
        got = fn_b(got)
    sync(got)
    dt_b = (time.perf_counter() - t0) / reps
    fused = c.compiled_fused(n, density=False, donate=False,
                             interpret=interpret)
    import functools
    from quest_tpu.ops import pallas_band as PB

    def one(a):
        return fused(a.reshape(2, -1, PB.LANES)).reshape(2, -1)
    fn_m = jax.jit(lambda ab: jax.lax.map(one, ab))
    got_m = fn_m(amps_b)
    sync(got_m)
    t0 = time.perf_counter()
    for _ in range(reps):
        got_m = fn_m(got_m)
    sync(got_m)
    dt_m = (time.perf_counter() - t0) / reps
    out(mode=mode, n=n, batch=batch,
        batched_ms=round(dt_b * 1e3, 2),
        laxmap_ms=round(dt_m * 1e3, 2),
        speedup=round(dt_m / dt_b, 2))
elif mode == "sharded":
    # PR 8 debt: exchange slicing on the sharded fused step
    from quest_tpu.parallel.mesh import make_amp_mesh
    import bench
    ndev = len(jax.devices())
    if ndev < 2:
        out(mode=mode, skipped="needs >= 2 devices", devices=ndev)
        sys.exit(0)
    mesh = make_amp_mesh(2)
    c = bench._build_deep_global_circuit(n, depth=4)
    fn = c.compiled_sharded_fused(n, density=False, mesh=mesh,
                                  donate=False, interpret=interpret)
    rng = np.random.default_rng(1)
    amps = jnp.asarray(rng.standard_normal((2, 1 << n)).astype(np.float32))
    got = fn(amps)
    sync(got)
    t0 = time.perf_counter()
    for _ in range(reps):
        got = fn(got)
    sync(got)
    dt = (time.perf_counter() - t0) / reps
    out(mode=mode, n=n, devices=2,
        slices=os.environ.get("QUEST_EXCHANGE_SLICES", "1"),
        dci_slices=os.environ.get("QUEST_EXCHANGE_SLICES_DCI", "0"),
        topology=os.environ.get("QUEST_COMM_TOPOLOGY", ""),
        ms_per_application=round(dt * 1e3, 2))
elif mode == "autotune":
    # ISSUE 16 satellite: the priced chooser on real silicon — plan
    # search wall time, the chosen engine, and chooser-pick vs every
    # forced engine on the headline circuit (the CPU cost model only
    # has to RANK right; this leg measures whether it did)
    import bench
    from quest_tpu import plan as P
    from quest_tpu.ops import pallas_band as PB
    from quest_tpu.state import basis_planes
    c = bench._build_circuit(n)
    t0 = time.perf_counter()
    plan = P.autotune(c, persist=False)
    search_ms = (time.perf_counter() - t0) * 1e3

    def time_engine(fn):
        amps = basis_planes(0, n=n, rdt=jnp.float32)
        amps = fn(amps)
        sync(amps)
        t0 = time.perf_counter()
        for _ in range(reps):
            amps = fn(amps)
        sync(amps)
        return (time.perf_counter() - t0) / reps * 1e3

    forced = {"pergate": c.compiled(n, False, donate=True),
              "banded": c.compiled_banded(n, False, donate=True)}
    if PB.usable(n):
        fused = c.compiled_fused(n, False, donate=True,
                                 interpret=interpret)
        forced["fused"] = (lambda a: fused(
            a.reshape(2, -1, PB.LANES)).reshape(2, -1))
    ms = {}
    for name, fn in forced.items():
        try:
            ms[name] = round(time_engine(fn), 3)
        except Exception as e:
            ms[name] = f"failed: {e!r}"[:120]
    timed = {k: v for k, v in ms.items() if isinstance(v, float)}
    chosen = ms.get(plan.engine)
    out(mode=mode, n=n,
        autoroute=os.environ.get("QUEST_APPLY_AUTOROUTE", "1"),
        engine=plan.engine, incumbent=plan.incumbent,
        candidates=len(plan.candidates),
        search_ms=round(search_ms, 2),
        forced_ms=ms,
        chooser_ranked_right=(
            chosen == min(timed.values()) if timed and
            isinstance(chosen, float) else None))
elif mode == "transpile":
    # ISSUE 20 satellite: the circuit transpiler's workload gallery on
    # real silicon. QUEST_TRANSPILE resolves at QASM import time in
    # THIS process, so the auto/0 legs exercise the exact routing a
    # real OpenQASM workload gets; per class we report the stream the
    # planner actually prices (op count, predicted HBM sweeps) next to
    # measured requests/s. The dynamic GHZ class rides
    # compiled_measured — serve rejects mid-circuit measurement.
    import bench
    from quest_tpu import transpile as TR
    circs = bench._gallery_circuits(n, None)      # env-resolved knob
    classes = {}
    for cls, c in circs.items():
        sweeps, count = TR.stream_cost(c)
        timer = bench._time_measured if cls == "ghz" \
            else bench._time_serve_apply
        try:
            rps = round(timer(c, n, reps), 2)
        except Exception as e:
            rps = f"failed: {e!r}"[:120]
        classes[cls] = {"ops": count, "sweeps": sweeps, "rps": rps}
    out(mode=mode, n=n,
        transpile=os.environ.get("QUEST_TRANSPILE", "auto"),
        classes=classes)
elif mode == "grad":
    # ISSUE 19 satellite: the adjoint differentiation engine on real
    # silicon — optimizer steps/s of the VQE training step under
    # whatever engine QUEST_ADJOINT resolves to in THIS process
    # (0=taped, 1=adjoint, unset=capacity auto), plus gradient parity
    # against the taped reference so a chip-only numerics drift is
    # caught in the same session that times it
    import bench
    from quest_tpu import adjoint as AD
    from quest_tpu.ops import expec as E
    layers = 2 if interpret else 4
    c = bench._build_vqe_ansatz(n, layers)
    ham = E.PauliSum.of(*bench._build_tfim_sum(n), n)
    fn = AD.value_and_grad(c, ham)            # knob-resolved engine
    th = jnp.asarray(fn.initial_params, jnp.float32)
    v, g = fn(th)
    sync(g)
    steps = 3 if interpret else 10
    t0 = time.perf_counter()
    for _ in range(steps):
        v, g = fn(th)
        th = th - 0.05 * g
    sync(th)
    dt = (time.perf_counter() - t0) / steps
    parity = None
    if fn.engine != "taped":
        ref = AD.value_and_grad(c, ham, engine="taped")
        _, gt = ref(jnp.asarray(fn.initial_params, jnp.float32))
        _, ga = fn(jnp.asarray(fn.initial_params, jnp.float32))
        parity = float(jnp.max(jnp.abs(ga - gt)))
    cap = AD.capacity_stats(n, fn.num_params, len(c.ops), np.float32)
    out(mode=mode, n=n, engine=fn.engine,
        knob=os.environ.get("QUEST_ADJOINT", "auto"),
        params=fn.num_params,
        steps_per_s=round(1.0 / dt, 3),
        ms_per_step=round(dt * 1e3, 2),
        adjoint_peak_bytes=cap["adjoint_peak_bytes"],
        taped_residual_bytes=cap["taped_residual_bytes"],
        grad_parity=parity)
else:
    raise SystemExit(f"unknown mode {mode!r}")
"""


def run(mode, n, env=None, reps=5, batch=8, interpret=False,
        timeout=1800):
    params = dict(repo=REPO, mode=mode, n=n, reps=reps, batch=batch,
                  interpret=1 if interpret else 0)
    code = WORKER % params
    e = dict(os.environ)
    e.update(env or {})
    label = f"mode={mode} env={env}"
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout, env=e, cwd=REPO)
    except subprocess.TimeoutExpired:
        print(f"[ab-silicon] TIMEOUT {label}", flush=True)
        return {"error": "timeout"}
    for line in r.stdout.splitlines():
        if line.startswith("[ab-result]"):
            print(f"[ab-silicon] {label}: {line[len('[ab-result] '):]}",
                  flush=True)
            return json.loads(line[len("[ab-result]"):])
    print(f"[ab-silicon] FAILED {label}: {r.stdout[-400:]} "
          f"{r.stderr[-1200:]}", flush=True)
    return {"error": (r.stderr or r.stdout)[-300:]}


def main():
    args = [a for a in sys.argv[1:]]
    smoke = "--smoke" in args
    args = [a for a in args if a != "--smoke"]
    if smoke:
        n, nb, ns, reps, interpret = 10, 10, 8, 1, True
    else:
        n = int(args[0]) if args else 30
        nb = 24                 # batch size cap: B states must fit HBM
        ns = 28                 # sharded A/B size: the exchange overlap
        # only shows at HBM-scale shards (a small state times dispatch
        # overhead, not ICI) — 2^27 amps/device on a 2-dev mesh
        reps, interpret = 5, False

    report = {"n": n, "smoke": smoke}

    # 1. the tentpole A/B: decoupled pipeline vs legacy in-place slots
    report["pipeline"] = {
        v: run("bench", n, env={"QUEST_FUSED_PIPELINE": v}, reps=reps,
               interpret=interpret)
        for v in ("1", "0")}

    # 2. legacy slot count (only meaningful with the pipeline off)
    report["nbuf"] = {
        v: run("bench", n,
               env={"QUEST_FUSED_PIPELINE": "0", "QUEST_FUSED_NBUF": v},
               reps=reps, interpret=interpret)
        for v in ("2", "3", "4")}

    # 3. MAX_SWEEP_STAGES=64 merged sweeps vs the raw segment plan
    report["sweep_fusion"] = {
        v: run("bench", n, env={"QUEST_SWEEP_FUSION": v}, reps=reps,
               interpret=interpret)
        for v in ("1", "0")}

    # 4. batch grid vs lax.map of the unbatched program
    report["batch_grid"] = run("batch", nb, reps=reps, batch=8 if not
                               smoke else 2, interpret=interpret)

    # 5. exchange slicing on a 2-device mesh (forced host devices off
    # chip so the smoke run exercises the path)
    env2 = {}
    if smoke:
        env2["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                             + " --xla_force_host_platform_device_count=2"
                             ).strip()
    report["exchange_slices"] = {
        v: run("sharded", ns, env={**env2, "QUEST_EXCHANGE_SLICES": v},
               reps=reps, interpret=interpret)
        for v in ("1", "4")}

    # 6. the DCI leg (ISSUE 13 satellite): under a hosts=2 topology a
    # 2-dev mesh's every exchange crosses the host boundary, so
    # QUEST_EXCHANGE_SLICES_DCI alone governs the slicing — A/B finer
    # DCI slicing against the unsliced baseline above. On a single-host
    # chip pair this measures the knob's overhead floor; on a real
    # multi-host slice it measures the overlap win (docs/DISTRIBUTED.md
    # §topology).
    report["exchange_slices_dci"] = {
        v: run("sharded", ns,
               env={**env2, "QUEST_EXCHANGE_SLICES": "1",
                    "QUEST_EXCHANGE_SLICES_DCI": v,
                    "QUEST_COMM_TOPOLOGY": "hosts=2"},
               reps=reps, interpret=interpret)
        for v in ("0", "4")}

    # 7. the priced plan chooser (ISSUE 16 satellite): chooser pick vs
    # every forced engine, with the auto-route knob on and off — on
    # chip this validates that the CPU-side cost model RANKS engines
    # the way silicon does (docs/PLANNING.md §pricing)
    report["autotune"] = {
        v: run("autotune", n, env={"QUEST_APPLY_AUTOROUTE": v},
               reps=reps, interpret=interpret)
        for v in ("1", "0")}

    # 8. the adjoint differentiation engine (ISSUE 19 satellite):
    # forced-taped vs forced-adjoint vs capacity-auto on the VQE
    # training step — on chip this measures the steps/s ratio the CPU
    # host can only model (docs/AUTODIFF.md; the capacity gates live in
    # scripts/check_adjoint_golden.py). Sized down from the headline n:
    # the taped leg materializes (P+2) state registers
    ng = 10 if smoke else min(n, 26)
    report["grad"] = {
        v or "auto": run("grad", ng,
                         env={"QUEST_ADJOINT": v} if v else {},
                         reps=reps, interpret=interpret)
        for v in ("0", "1", None)}

    # 9. the circuit transpiler (ISSUE 20 satellite): the QASM gallery
    # corpus imported under QUEST_TRANSPILE auto vs 0 — on chip this
    # prices the rewriter's predicted-sweep wins against real per-class
    # requests/s (docs/TRANSPILE.md; the equivalence and never-worse
    # gates live in scripts/check_transpile_golden.py). Sized below the
    # serve tier's HBM headroom: B=8 batched states per request.
    nt = 9 if smoke else min(n, 24)
    report["transpile"] = {
        v: run("transpile", nt, env={"QUEST_TRANSPILE": v},
               reps=2 if smoke else 16, interpret=interpret)
        for v in ("auto", "0")}

    print("[ab-silicon] " + json.dumps(report), flush=True)
    print(json.dumps(report, indent=1))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
