#!/bin/bash
# Launch the benchmark suite across a TPU pod slice — the analogue of the
# reference's SLURM/PBS submission scripts (examples/submissionScripts/).
#
# Usage: ./scripts/tpu_pod_bench.sh <tpu-name> <zone>
#
# QUEST_COMM_TOPOLOGY (docs/DISTRIBUTED.md §topology) passes through to
# every worker so the comm planner prices the slice's real host
# grouping; unset it to let the planner auto-derive hosts from
# jax.devices() process ids (the default on a real pod).

set -euo pipefail
TPU_NAME=${1:?tpu name}
ZONE=${2:?zone}
TOPOLOGY=${QUEST_COMM_TOPOLOGY:-}

# an EMPTY knob must stay unset on the workers (knobs parse loudly;
# '' is malformed) — only export it when the caller actually set one
ENVPREFIX=""
if [ -n "$TOPOLOGY" ]; then
  ENVPREFIX="QUEST_COMM_TOPOLOGY='${TOPOLOGY}' "
fi

gcloud compute tpus tpu-vm ssh "$TPU_NAME" --zone "$ZONE" --worker=all \
  --command "cd quest_tpu && ${ENVPREFIX}python bench.py && ${ENVPREFIX}python bench.py multichip && python benchmarks/run.py"
