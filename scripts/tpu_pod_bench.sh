#!/bin/bash
# Launch the benchmark suite across a TPU pod slice — the analogue of the
# reference's SLURM/PBS submission scripts (examples/submissionScripts/).
#
# Usage: ./scripts/tpu_pod_bench.sh <tpu-name> <zone>

set -euo pipefail
TPU_NAME=${1:?tpu name}
ZONE=${2:?zone}

gcloud compute tpus tpu-vm ssh "$TPU_NAME" --zone "$ZONE" --worker=all \
  --command 'cd quest_tpu && python bench.py && python benchmarks/run.py'
