"""On-chip perf experiment matrix for the fused kernel (round 3).

Answers three questions the recorded stage timings raise:

  E1  pass-baseline vs block size: a single sc-butterfly segment moves
      state bytes and does ~no flops, yet measured 2.2x the HBM roofline
      at 29q. Sweep QUEST_ROWS_EFF_BITS (subprocess per value — the knob
      is read once at import, see pallas_band._rows_eff_override).
  E2  MXU cost vs dot dim: time scb segments at d=128/16/8. If cost is
      ~flat in d (tile padding), the current 7-qubit bands are optimal;
      if it scales with d, splitting bands into 4+3 saves ~5x MACs.
  E3  the bench step (16 rx @ 30q) at the winning block size, HIGHEST
      and HIGH tiers — the would-be new headline.

Each experiment runs in a subprocess so block-size/precision knobs are
honored and a single OOM/compile failure cannot kill the matrix.
Usage: python scripts/sweep_perf.py [n]   (default 30)
"""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import json, os, sys, time
sys.path.insert(0, %(repo)r)
from quest_tpu.precision import enable_compile_cache
enable_compile_cache()
import jax
import jax.numpy as jnp
import numpy as np

mode = %(mode)r
n = %(n)d
reps = %(reps)d

def out(**kw):
    print("[sweep-result] " + json.dumps(kw), flush=True)

if mode == "segment":
    from quest_tpu.ops import pallas_band as PB
    kind = %(kind)r
    d = %(d)d
    if kind == "sc":
        bit = n - 8   # a high scattered bit
        stages = [PB.MatStage(kind="sc", bit=bit, dim=2, real_only=False,
                              lane_preds=(), row_preds=())]
        g = np.zeros((2, 2, 2), np.float32); g[0] = np.eye(2)
        arrays = [jnp.asarray(g)]
    else:  # scb over the TOP w bits, like the real high band
        w = d.bit_length() - 1
        bit = n - 7 - w
        stages = [PB.MatStage(kind="scb", bit=bit, dim=d, real_only=False,
                              lane_preds=(), row_preds=())]
        g = np.zeros((2, d, d), np.float32); g[0] = np.eye(d)
        arrays = [jnp.asarray(g)]
    fn = PB.compile_segment(stages, n)
    jfn = jax.jit(lambda a: fn(a, arrays), donate_argnums=(0,))
    from quest_tpu.state import basis_planes, fused_state_shape
    # ONE fused device buffer: zeros().at.set() would briefly hold two
    # full states (16 GB at 30q -> guaranteed OOM on a 15.75 GiB v5e)
    amps = basis_planes(0, n=n, rdt=jnp.float32,
                        shape=fused_state_shape(n))
    amps = jfn(amps)
    _ = np.asarray(amps[0, 0, :4])
    t0 = time.perf_counter()
    for _ in range(reps):
        amps = jfn(amps)
    _ = np.asarray(amps[0, 0, :4])
    dt = (time.perf_counter() - t0) / reps
    gb = 2 * 2 * (1 << n) * 4 / 2**30
    out(mode=mode, kind=kind, d=d, n=n,
        rows_bits=os.environ.get("QUEST_ROWS_EFF_BITS", "default"),
        ms=round(dt * 1e3, 2), eff_gb_s=round(gb / dt, 1))
else:  # bench step
    from quest_tpu.circuit import Circuit
    from quest_tpu.state import basis_planes, fused_state_shape
    rng = np.random.default_rng(42)
    c = Circuit(n)
    for i in range(16):
        c.rx(1 + i %% (n - 1), float(rng.uniform(0, 2 * np.pi)))
    iters = 8
    step = c.compiled_fused(n, density=False, donate=True, iters=iters)
    shape = fused_state_shape(n)
    s = basis_planes(0, n=n, rdt=jnp.float32, shape=shape)
    s = step(s)
    from quest_tpu.env import sync_array
    sync_array(s)
    t0 = time.perf_counter()
    for _ in range(reps):
        s = step(s)
    sync_array(s)
    dt = (time.perf_counter() - t0) / reps
    gps = 16 * iters / dt
    out(mode=mode, n=n,
        rows_bits=os.environ.get("QUEST_ROWS_EFF_BITS", "default"),
        prec=os.environ.get("QUEST_MATMUL_PRECISION", "highest"),
        ms_per_application=round(dt / iters * 1e3, 2),
        gates_per_sec=round(gps, 1))
"""


def run(mode, n, env=None, **kw):
    params = dict(repo=REPO, mode=mode, n=n, reps=kw.pop("reps", 6),
                  kind=kw.pop("kind", ""), d=kw.pop("d", 0))
    code = WORKER % params
    e = dict(os.environ)
    e.update(env or {})
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=1200, env=e, cwd=REPO)
    except subprocess.TimeoutExpired:
        print(f"[sweep] TIMEOUT mode={mode} env={env}", flush=True)
        return None
    for line in r.stdout.splitlines():
        if line.startswith("[sweep-result]"):
            print(line, flush=True)
            return json.loads(line[len("[sweep-result]"):])
    print(f"[sweep] FAILED mode={mode} env={env}: "
          f"{r.stdout[-400:]} {r.stderr[-1500:]}", flush=True)
    return None


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 30
    results = []

    # E1: pass baseline vs block size (single butterfly, ~zero flops)
    for bits in ("10", "11", "12", "13"):
        results.append(run("segment", n, kind="sc", d=2,
                           env={"QUEST_ROWS_EFF_BITS": bits}))

    # E2: MXU cost vs dot dim at the default block size
    for d in (128, 16, 8):
        results.append(run("segment", n, kind="scb", d=d))

    # E3: the bench step at default and best block size, both tiers
    best = None
    e1 = [r for r in results[:4] if r]
    if e1:
        best = min(e1, key=lambda r: r["ms"])["rows_bits"]
    envs = [{}]
    if best and best != "12":
        envs.append({"QUEST_ROWS_EFF_BITS": best})
    envs.append({"QUEST_MATMUL_PRECISION": "high"})
    if best and best != "12":
        envs.append({"QUEST_MATMUL_PRECISION": "high",
                     "QUEST_ROWS_EFF_BITS": best})
    for e in envs:
        results.append(run("bench", n, env=e))

    print(json.dumps([r for r in results if r], indent=1))


if __name__ == "__main__":
    main()
