"""Measure the QFT-30 cold-process cost with the fused-scan path on/off.

probe_cold_start.py pinned the relay first-execution cost as PER-BYTE
(program size), not per-kernel — so QUEST_FUSED_SCAN, which rolls QFT's
repeated identical phase segments into ONE lax.scan body instead of
inlining every copy, is the lever for VERDICT r4 item 3 (QFT-30 cold
process 266 s; target <= 120 s).

Each arm runs in a FRESH subprocess twice: run 1 populates the
persistent XLA cache for that arm's program, run 2 isolates the
relay-side per-program cost that dominates the cold wall.

Usage: python scripts/probe_qft_cold.py [n]   (default 30)
"""
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import json, sys, time
t_proc = time.perf_counter()
sys.path.insert(0, %(repo)r)
from quest_tpu.precision import enable_compile_cache
enable_compile_cache()
import os
import jax
import jax.numpy as jnp
import numpy as np
from quest_tpu.circuit import qft_circuit
from quest_tpu.state import basis_planes, fused_state_shape

n = %(n)d
c = qft_circuit(n)
step = c.compiled_fused(n, density=False, donate=True)
amps = basis_planes(0, n=n, rdt=jnp.float32, shape=fused_state_shape(n))
t0 = time.perf_counter()
amps = step(amps)
_ = np.asarray(amps[0, 0, :4])
first = time.perf_counter() - t0
t0 = time.perf_counter()
amps = step(amps)
_ = np.asarray(amps[0, 0, :4])
steady = time.perf_counter() - t0
print("[probe-result] " + json.dumps(dict(
    scan=os.environ.get("QUEST_FUSED_SCAN", "unset"), n=n,
    platform=jax.devices()[0].platform,
    first_s=round(first, 2), steady_s=round(steady, 3),
    cold_process_s=round(time.perf_counter() - t_proc, 1))), flush=True)
"""


def run(flag, n):
    env = dict(os.environ)
    env["QUEST_FUSED_SCAN"] = flag
    code = WORKER % dict(repo=REPO, n=n)
    t0 = time.time()
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=2400, cwd=REPO, env=env)
    except subprocess.TimeoutExpired:
        print(f"[probe] TIMEOUT scan={flag}", flush=True)
        return None
    wall = time.time() - t0
    for line in r.stdout.splitlines():
        if line.startswith("[probe-result]"):
            rec = json.loads(line[len("[probe-result]"):])
            rec["process_wall_s"] = round(wall, 1)
            print("[probe-result] " + json.dumps(rec), flush=True)
            return rec
    print(f"[probe] FAILED scan={flag}: {r.stdout[-300:]} "
          f"{r.stderr[-1500:]}", flush=True)
    return None


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 30
    for flag in ("0", "1"):
        # twice: run 1 warms the persistent cache for this arm's
        # program; run 2 is the relay-cost measurement
        run(flag, n)
        run(flag, n)


if __name__ == "__main__":
    main()
