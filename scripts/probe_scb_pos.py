"""On-chip probe: scattered-contraction (scb) cost vs width and position.

The round-4 decision record behind "do NOT Kron-split a factorizable
band operator" (docs/KERNELS.md round-4 findings, segment_plan comment):
a narrow scb's MXU time is ~flat in d — a small-M dot idles most of the
systolic array — so splitting one wide dot into factors multiplies
cost. Measured 30q, v5e: whole d=128 42.6 ms; the d4+d4+d8 split of the
same band 161.4 ms; lone d=8 at top/mid/bottom scat positions
40.3/40.3/42.5 ms; seven stacked sc butterflies 160.3 ms.

Usage: python scripts/probe_scb_pos.py   (needs the TPU tunnel)
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from quest_tpu.precision import enable_compile_cache
enable_compile_cache()
import jax, jax.numpy as jnp, numpy as np
from quest_tpu.ops import pallas_band as PB
from quest_tpu.state import basis_planes, fused_state_shape

n = 30

def run(tag, stages, arrays):
    fn = PB.compile_segment(stages, n)
    arrays = [jnp.asarray(a) for a in arrays]
    jfn = jax.jit(lambda a: fn(a, arrays), donate_argnums=(0,))
    amps = basis_planes(0, n=n, rdt=jnp.float32, shape=fused_state_shape(n))
    amps = jfn(amps); _ = np.asarray(amps[0,0,:4])
    t0 = time.perf_counter()
    for _ in range(5): amps = jfn(amps)
    _ = np.asarray(amps[0,0,:4])
    print(tag, round((time.perf_counter()-t0)/5*1e3, 2), 'ms', flush=True)

def mat(kind, d, bit):
    g = np.zeros((2, d, d), np.float32); g[0] = np.eye(d)
    if kind == 'scb' and d == 128:
        pass  # identity symmetric; transpose moot
    return PB.MatStage(kind, d, False, (), (), bit), g

# the high band qubits 14-20 = row bits 7..13
# A: whole-band d=128 (two-step mirror path)
st, g = mat('scb', 128, 7)
run('whole-d128', [st], [g])
# B: the real split shape: d4(bits 7-8) + d4(9-10) + d8(11-13)
sts, gs = [], []
for kind, d, bit in (('scb',4,7), ('scb',4,9), ('scb',8,11)):
    s, g = mat(kind, d, bit); sts.append(s); gs.append(g)
run('split-4/4/8', sts, gs)
# C: single narrow at TOP position (pre=1): d8 at bits 20-22
st, g = mat('scb', 8, 20)
run('top-d8', [st], [g])
# D: single narrow MID position: d8 at bits 11-13 alone
st, g = mat('scb', 8, 11)
run('mid-d8', [st], [g])
# E: single narrow BOTTOM: d8 at bits 7-9 alone
st, g = mat('scb', 8, 7)
run('bot-d8', [st], [g])
# F: 7 sc butterflies (bits 7..13)
sts, gs = [], []
for b in range(7, 14):
    s, g = mat('sc', 2, b); sts.append(s); gs.append(g)
run('sc-x7', sts, gs)
