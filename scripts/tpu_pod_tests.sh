#!/bin/bash
# Run the unit suite on every host of a TPU pod slice — the analogue of
# the reference's examples/submissionScripts/mpi_SLURM_unit_tests.sh
# (4-node MPI ctest run). Each host runs the same suite; multi-host
# registers shard over the full pod mesh via jax.distributed.
#
# Usage: ./scripts/tpu_pod_tests.sh <tpu-name> <zone>

set -euo pipefail
TPU_NAME=${1:?tpu name}
ZONE=${2:?zone}

gcloud compute tpus tpu-vm ssh "$TPU_NAME" --zone "$ZONE" --worker=all \
  --command 'cd quest_tpu && QUEST_TEST_PLATFORM=tpu python -m pytest tests/ -q'
