# Shared axon-tunnel helpers, sourced by tpu_revalidate.sh and
# tunnel_watch.sh. The relay port default (8093) and the
# QUEST_AXON_PORT=0 "disable the port check" convention live HERE for
# shell; quest_tpu/env.py:ensure_live_backend carries the same
# convention for Python (kept in sync by tests/test_scripts.py).
AXON_PORT="${QUEST_AXON_PORT:-8093}"

tunnel_up() {
    [ "$AXON_PORT" = "0" ] && return 0   # port check disabled
    if timeout 5 bash -c "exec 3<>/dev/tcp/127.0.0.1/$AXON_PORT" 2>/dev/null; then
        return 0
    fi
    # Same rule as quest_tpu/env.py: a dead DEFAULT port might just be a
    # nonstandard relay setup, so fall through to a short real probe
    # before declaring the tunnel down. An operator-set QUEST_AXON_PORT
    # is trusted as-is (and keeps the check cheap).
    [ -n "${QUEST_AXON_PORT:-}" ] && return 1
    probe_tpu 60
}

# Probe JAX in a bounded subprocess and require a real accelerator:
# a CPU-fallback jax prints CpuDevice and must NOT count as live.
probe_tpu() {
    timeout "${1:-180}" python -c "import jax; print(jax.devices())" \
        | grep -qi "tpu\|axon"
}
