"""Benchmark harness for the five BASELINE.json config scenarios.

Prints one JSON line per scenario:
  {"scenario": ..., "metric": ..., "value": N, "unit": ...}

Scenarios (BASELINE.json "configs"):
  1. tutorial   — the 3-qubit tutorial circuit, eager QuEST-compatible API
  2. rcs        — random-circuit-sampling statevector, whole circuit jitted
  3. genunitary — multi-controlled + general k-qubit ComplexMatrixN gates
  4. channels   — density-matrix decoherence (damping/depolarising/Kraus)
  5. qft        — QFT sharded over the device mesh (ppermute engine)

Sizes adapt to the platform: full scale on TPU, scaled-down on CPU so the
suite stays fast. Run: python benchmarks/run.py [scenario ...]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np


def _sync(x):
    from quest_tpu.env import sync_array
    sync_array(x)  # true device sync (see sync_array's axon caveat)


def _emit(scenario, metric, value, unit, **extra):
    # platform is evidence: scripts/tpu_revalidate.sh gates on it to tell
    # a real on-chip measurement from a loud-but-successful CPU fallback
    print(json.dumps({"scenario": scenario, "metric": metric,
                      "value": round(value, 3), "unit": unit,
                      "platform": jax.devices()[0].platform, **extra}))


def _on_tpu():
    return jax.devices()[0].platform in ("tpu", "axon")


# -- 1. tutorial -------------------------------------------------------------


def bench_tutorial():
    from quest_tpu import api as Q

    def run_once():
        qubits = Q.createQureg(3)
        Q.hadamard(qubits, 0)
        Q.controlledNot(qubits, 0, 1)
        Q.rotateY(qubits, 2, 0.1)
        Q.multiControlledPhaseFlip(qubits, [0, 1, 2])
        u = np.array([[0.5 + 0.5j, 0.5 - 0.5j], [0.5 - 0.5j, 0.5 + 0.5j]])
        Q.unitary(qubits, 0, u)
        Q.compactUnitary(qubits, 1, 0.5 + 0.5j, 0.5 - 0.5j)
        Q.rotateAroundAxis(qubits, 2, 3.14 / 2, (1, 0, 0))
        Q.controlledCompactUnitary(qubits, 0, 1, 0.5 + 0.5j, 0.5 - 0.5j)
        Q.multiControlledUnitary(qubits, [0, 1], 2, u)
        return Q.calcProbOfOutcome(qubits, 2, 1)

    run_once()  # warmup/compile
    t0 = time.perf_counter()
    reps = 5
    for _ in range(reps):
        p = run_once()
    dt = (time.perf_counter() - t0) / reps
    assert abs(p - 0.749178) < 1e-4
    _emit("tutorial", "eager tutorial circuit wall-clock", dt * 1000, "ms/run")


# -- 2. RCS ------------------------------------------------------------------


def bench_rcs():
    from quest_tpu.circuit import random_circuit

    from quest_tpu.state import basis_planes, fused_state_shape

    n = 30 if _on_tpu() else 20
    depth = 20
    circ = random_circuit(n, depth, seed=1)
    num_gates = len(circ.ops)
    if _on_tpu():
        # fused band-segment engine with its native (2, rows, 128) state,
        # built directly in that layout (see bench.py: an out-of-jit
        # reshape or a zeros().at.set would transiently double the 8 GB
        # state at 30q)
        fn = circ.compiled_fused(n, density=False, donate=True)
        amps = basis_planes(0, n=n, rdt=jnp.float32,
                            shape=fused_state_shape(n))
    else:
        fn = circ.compiled_banded(n, density=False, donate=True)
        amps = basis_planes(0, n=n, rdt=jnp.float32)
    amps = fn(amps)
    _sync(amps)
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        amps = fn(amps)
    _sync(amps)
    dt = (time.perf_counter() - t0) / reps
    _emit("rcs", f"RCS depth-{depth} @ {n}q wall-clock", dt * 1000, "ms/run",
          gates_per_sec=round(num_gates / dt, 1))


# -- 3. general unitaries ----------------------------------------------------


def bench_general_unitaries():
    from quest_tpu.ops import gates as G
    import quest_tpu as qt

    n = 24 if _on_tpu() else 18
    rng = np.random.default_rng(5)
    q = qt.create_qureg(n)

    def rand_u(k):
        z = rng.normal(size=(1 << k, 1 << k)) + 1j * rng.normal(size=(1 << k, 1 << k))
        u, _ = np.linalg.qr(z)
        return u

    u1, u2, u3 = rand_u(1), rand_u(2), rand_u(3)
    # warmup all shapes
    q = G.multi_controlled_unitary(q, [n - 1, n - 2], 0, u1)
    q = G.two_qubit_unitary(q, 1, 5, u2)
    q = G.multi_qubit_unitary(q, [0, 3, 7], u3)
    _sync(q.amps)
    t0 = time.perf_counter()
    reps = 4
    for _ in range(reps):
        q = G.multi_controlled_unitary(q, [n - 1, n - 2], 0, u1)
        q = G.two_qubit_unitary(q, 1, 5, u2)
        q = G.multi_qubit_unitary(q, [0, 3, 7], u3)
    _sync(q.amps)
    dt = (time.perf_counter() - t0) / (3 * reps)
    _emit("genunitary", f"general k-qubit unitaries @ {n}q", dt * 1000,
          "ms/gate")


# -- 4. density channels -----------------------------------------------------


def bench_channels():
    from quest_tpu.ops import channels as ch
    import quest_tpu as qt

    n = 12 if _on_tpu() else 9
    rng = np.random.default_rng(6)
    q = qt.init_plus_state(qt.create_density_qureg(n))
    ops = None
    from tests.oracle import random_kraus_map  # reuse the CPTP generator
    ops = random_kraus_map(1, 4, rng)

    def step(q):
        q = ch.mix_damping(q, 0, 0.05)
        q = ch.mix_depolarising(q, n // 2, 0.05)
        q = ch.mix_two_qubit_dephasing(q, 1, n - 1, 0.05)
        q = ch.mix_kraus_map(q, 2, ops)
        return q

    q = step(q)
    _sync(q.amps)
    t0 = time.perf_counter()
    reps = 4
    for _ in range(reps):
        q = step(q)
    _sync(q.amps)
    dt = (time.perf_counter() - t0) / (4 * reps)
    _emit("channels", f"decoherence channels @ {n}q density", dt * 1000,
          "ms/channel")


# -- 5. distributed QFT ------------------------------------------------------


def bench_qft_sharded():
    from quest_tpu.circuit import qft_circuit
    from quest_tpu.parallel.mesh import make_amp_mesh, amp_sharding

    devices = jax.devices()
    d = 1 << (len(devices).bit_length() - 1)
    n = 26 if _on_tpu() else 20
    mesh = make_amp_mesh(d)
    from quest_tpu.state import basis_planes

    circ = qft_circuit(n)
    fn = circ.compiled_sharded(n, density=False, mesh=mesh, donate=True)
    amps = basis_planes(0, n=n, rdt=jnp.float32)
    amps = jax.device_put(amps, amp_sharding(mesh))
    amps = fn(amps)
    _sync(amps)
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        amps = fn(amps)
    _sync(amps)
    dt = (time.perf_counter() - t0) / reps
    _emit("qft", f"QFT @ {n}q over {d}-device mesh", dt * 1000, "ms/run",
          devices=d)


# -- 6. trajectory noise (beyond the BASELINE five) --------------------------


def bench_trajectories():
    """Noisy-circuit shots via stochastic Kraus unraveling, vmapped over
    a shot batch — statevector memory per shot where the reference needs
    the 4^n density register (quest_tpu/trajectories.py). Reported as
    noisy shots/sec; the density-register equivalent at this size would
    square the memory."""
    from quest_tpu import trajectories as T
    from quest_tpu.circuit import random_circuit
    from quest_tpu.state import basis_planes

    n = 20 if _on_tpu() else 12
    shots = 64 if _on_tpu() else 16
    depth = 4
    c = random_circuit(n, depth=depth, seed=13)

    def shot(key):
        amps = basis_planes(0, n=n, rdt=jnp.float32)
        amps = c.compiled(n, density=False, donate=False)(amps)
        for q in (0, n // 2, n - 1):
            amps, key, _ = T.damping(amps, key, n, q, 0.05)
        return amps[0, 0]

    run = jax.jit(jax.vmap(shot))
    keys = jax.random.split(jax.random.key(1), shots)
    out = run(keys)
    _sync(out)
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        out = run(keys)
    _sync(out)
    dt = (time.perf_counter() - t0) / reps
    _emit("trajectories", f"noisy RCS shots @ {n}q (3 damping channels)",
          shots / dt, "shots/sec", shots=shots)


ALL = {
    "tutorial": bench_tutorial,
    "rcs": bench_rcs,
    "genunitary": bench_general_unitaries,
    "channels": bench_channels,
    "qft": bench_qft_sharded,
    "trajectories": bench_trajectories,
}


def main(argv):
    # bound the wait on a dead TPU tunnel and fall back loudly to CPU
    # (run.py hung here pre-probe; see env.ensure_live_backend). A caller
    # that already pinned a platform (conftest, CI) is unaffected.
    from quest_tpu.env import ensure_live_backend
    ensure_live_backend()
    names = argv or list(ALL)
    for name in names:
        ALL[name]()


if __name__ == "__main__":
    main(sys.argv[1:])
