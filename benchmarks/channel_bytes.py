"""Measure ICI traffic of distributed density-matrix channels.

The reference's distributed density backend packs and exchanges HALF-chunks
for outer-qubit channels (exchangePairStateVectorHalves,
QuEST_cpu_distributed.c:511-542, used by mixDamping/mixDepolarising
:545-697) — 0.5 chunk-sizes on the wire per channel. quest_tpu routes
distributed superoperators through the generic machinery; this script
reports what each path actually puts on the wire, by compiling a damping
channel on an inner and an outer qubit over the virtual 8-device mesh and
summing the collective-permute operand bytes in the optimized HLO.

Run: JAX_PLATFORMS=cpu python benchmarks/channel_bytes.py
Prints one JSON object; also used by tests/test_distributed.py to pin the
outer-channel byte budget.
"""

import json
import os
import re
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _setup():
    import os
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")


_DTYPE_BYTES = {"f32": 4, "f64": 8, "bf16": 2, "s32": 4, "u32": 4,
                "pred": 1, "c64": 8, "c128": 16}

_CP_RE = re.compile(
    r"(\w+)\[([\d,]*)\][^=]*\bcollective-permute(?:-start)?\(")


def collective_permute_bytes(hlo_text: str) -> int:
    """Total bytes a single execution moves through collective-permutes,
    summed over instructions (each appears once in the unrolled program)."""
    total = 0
    for m in _CP_RE.finditer(hlo_text):
        dtype, dims = m.group(1), m.group(2)
        if dtype not in _DTYPE_BYTES:
            continue
        size = 1
        for d in dims.split(","):
            if d:
                size *= int(d)
        total += size * _DTYPE_BYTES[dtype]
    return total


def measure(n: int = 6, prob: float = 0.3):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from quest_tpu.circuit import Circuit
    from quest_tpu.env import AMP_AXIS
    from quest_tpu.parallel import make_amp_mesh
    from quest_tpu.parallel.sharded import compile_circuit_sharded

    mesh = make_amp_mesh(8)
    D = mesh.devices.size
    state_qubits = 2 * n                       # doubled register
    chunk_bytes = 2 * 4 * (1 << state_qubits) // D   # re+im f32 planes

    results = {"n": n, "devices": int(D), "chunk_bytes": chunk_bytes,
               "reference_halfchunk_bytes": chunk_bytes // 2}
    amps = jnp.zeros((2, 1 << state_qubits), dtype=jnp.float32).at[0, 0].set(1.0)
    amps = jax.device_put(amps, NamedSharding(mesh, P(None, AMP_AXIS)))

    for chan in ("damping", "dephasing", "depolarising"):
        for label, t in (("inner", 0), ("outer", n - 1)):
            c = getattr(Circuit(n), chan)(t, prob)
            step = compile_circuit_sharded(c.ops, state_qubits, density=True,
                                           mesh=mesh, donate=False)
            hlo = step.lower(amps).compile().as_text()
            b = collective_permute_bytes(hlo)
            results[f"{chan}_{label}_bytes"] = b
            if label == "outer":
                results[f"{chan}_outer_vs_ref"] = round(b / (chunk_bytes / 2), 3)
    return results


if __name__ == "__main__":
    _setup()
    print(json.dumps(measure()))
