"""Build the reference QuEST CPU library and measure the BASELINE.json
configs on this host (BASELINE.md: "all baseline numbers must be
self-measured"). Writes benchmarks/reference_baseline.json, which bench.py
uses as the vs_baseline denominator.

Builds out-of-tree (the reference tree is read-only) via the reference's
own CMake USER_SOURCE hook (reference CMakeLists.txt:19-22), once per
precision: PRECISION=1 (float, comparable to the TPU engine's f32 planes)
and PRECISION=2 (double, the reference default).
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF = "/root/reference"
OUT = os.path.join(REPO, "benchmarks", "reference_baseline.json")


def build(precision: int, build_dir: str) -> str:
    os.makedirs(build_dir, exist_ok=True)
    subprocess.run(
        ["cmake", "-S", REF, "-B", build_dir,
         "-DCMAKE_BUILD_TYPE=Release",
         f"-DUSER_SOURCE={REPO}/benchmarks/reference_bench.c",
         "-DOUTPUT_EXE=refbench",
         f"-DPRECISION={precision}",
         # serial: this host has one core, and the reference's OpenMP
         # default(none) pragmas reject modern GCC's const-sharing rules
         "-DMULTITHREADED=0"],
        check=True, capture_output=True, text=True)
    subprocess.run(["cmake", "--build", build_dir, "-j"],
                   check=True, capture_output=True, text=True)
    return os.path.join(build_dir, "refbench")


def run(exe: str, *args: str) -> list[dict]:
    res = subprocess.run([exe, *args], check=True, capture_output=True,
                         text=True, timeout=1800)
    out = []
    for line in res.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            out.append(json.loads(line))
    return out


def main():
    gates_n = int(sys.argv[1]) if len(sys.argv) > 1 else 26
    results = {"host_cores": os.cpu_count()}
    for prec, tag in ((1, "f32"), (2, "f64")):
        exe = build(prec, f"/tmp/refbuild_p{prec}")
        print(f"built reference (PRECISION={prec}); running...", flush=True)
        rows = run(exe, "all", str(gates_n))
        results[tag] = {r["config"]: r for r in rows}
        print(json.dumps(rows, indent=1), flush=True)

    # headline entry consumed by bench.py: the reference's own butterfly
    # throughput in amps/sec, measured at float precision (apples-to-apples
    # with the TPU engine's f32 planes) on this host
    g = results["f32"]["gates"]
    results["single_qubit_gates"] = {
        "amps_per_sec": g["amps_per_sec"],
        "gates_per_sec_at_n": g["gates_per_sec"],
        "n": g["n"],
        "config": f"reference CPU build, PRECISION=1, "
                  f"{os.cpu_count()} host core(s)",
    }
    with open(OUT, "w") as f:
        json.dump(results, f, indent=1)
    print(f"wrote {OUT}")


if __name__ == "__main__":
    main()
