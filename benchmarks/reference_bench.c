/* Benchmark driver for the REFERENCE QuEST build (BASELINE.md: "all
 * baseline numbers must be self-measured: run the reference's CPU build on
 * the BASELINE.json configs").
 *
 * Compiled against /root/reference via its own CMake (USER_SOURCE hook,
 * reference CMakeLists.txt:19-22) by benchmarks/measure_reference.py.
 * Prints one JSON object per config on stdout.
 *
 * Configs (BASELINE.json "configs"):
 *   gates    - single-qubit gates/sec on a dense statevector (north star)
 *   tutorial - the 3-qubit tutorial circuit (tutorial_example.c:50-105)
 *   rcs      - random-circuit-sampling layers (rotations + CZ brick)
 *   channels - density-matrix decoherence (mixDamping/Depolarising/Kraus)
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>

#include "QuEST.h"

static double now_sec(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return ts.tv_sec + 1e-9 * ts.tv_nsec;
}

/* same shape as /root/repo/bench.py: 16 rotateX round-robin over qubits
 * [1, n-1], timed over reps */
static void bench_gates(QuESTEnv env, int n, int gates_per_step, int reps) {
    Qureg q = createQureg(n, env);
    initZeroState(q);
    /* warmup one step */
    for (int i = 0; i < gates_per_step; i++)
        rotateX(q, 1 + i % (n - 1), 0.37 + 0.01 * i);
    double t0 = now_sec();
    for (int r = 0; r < reps; r++)
        for (int i = 0; i < gates_per_step; i++)
            rotateX(q, 1 + i % (n - 1), 0.37 + 0.01 * i);
    double dt = now_sec() - t0;
    double gates = (double)gates_per_step * reps;
    double gps = gates / dt;
    double amps_per_sec = gps * (double)(1LL << n);
    printf("{\"config\": \"gates\", \"n\": %d, \"gates_per_sec\": %.3f, "
           "\"amps_per_sec\": %.3e, \"precision\": %d, \"seconds\": %.3f}\n",
           n, gps, amps_per_sec, (int)sizeof(qreal) / 4, dt);
    destroyQureg(q, env);
}

/* tutorial_example.c:50-105 circuit, repeated */
static void bench_tutorial(QuESTEnv env, int reps) {
    Qureg q = createQureg(3, env);
    double t0 = now_sec();
    for (int r = 0; r < reps; r++) {
        initZeroState(q);
        hadamard(q, 0);
        controlledNot(q, 0, 1);
        rotateY(q, 2, .1);
        multiControlledPhaseFlip(q, (int[]){0, 1, 2}, 3);
        ComplexMatrix2 u = {.real = {{.5, .5}, {.5, .5}},
                            .imag = {{.5, -.5}, {-.5, .5}}};
        unitary(q, 0, u);
        Complex a = {.real = .5, .imag = .5};
        Complex b = {.real = .5, .imag = -.5};
        compactUnitary(q, 1, a, b);
        Vector v = {1, 0, 0};
        rotateAroundAxis(q, 2, 3.14 / 2, v);
        controlledCompactUnitary(q, 0, 1, a, b);
        multiControlledUnitary(q, (int[]){0, 1}, 2, 2, u);
        (void)calcProbOfOutcome(q, 2, 1);
    }
    double dt = now_sec() - t0;
    printf("{\"config\": \"tutorial\", \"reps\": %d, \"seconds\": %.4f, "
           "\"circuits_per_sec\": %.1f}\n", reps, dt, reps / dt);
    destroyQureg(q, env);
}

/* RCS layers: per layer, a random rotation on every qubit then a CZ brick
 * (same structure as quest_tpu.circuit.random_circuit) */
static void bench_rcs(QuESTEnv env, int n, int depth) {
    Qureg q = createQureg(n, env);
    initZeroState(q);
    srand(7);
    double t0 = now_sec();
    for (int d = 0; d < depth; d++) {
        for (int i = 0; i < n; i++) {
            double angle = 6.28 * rand() / (double)RAND_MAX;
            switch (rand() % 3) {
                case 0: rotateX(q, i, angle); break;
                case 1: rotateY(q, i, angle); break;
                default: rotateZ(q, i, angle); break;
            }
        }
        for (int i = d % 2; i < n - 1; i += 2)
            controlledPhaseFlip(q, i, i + 1);
    }
    double dt = now_sec() - t0;
    int gates = depth * n + depth * (n - 1) / 2;
    printf("{\"config\": \"rcs\", \"n\": %d, \"depth\": %d, "
           "\"seconds\": %.3f, \"gates\": %d, \"gates_per_sec\": %.2f}\n",
           n, depth, dt, gates, gates / dt);
    destroyQureg(q, env);
}

/* density-matrix channels (BASELINE.json config 4) */
static void bench_channels(QuESTEnv env, int n, int reps) {
    Qureg rho = createDensityQureg(n, env);
    initPlusState(rho);
    ComplexMatrix2 k0 = {.real = {{1, 0}, {0, .8}}, .imag = {{0, 0}, {0, 0}}};
    ComplexMatrix2 k1 = {.real = {{0, .6}, {0, 0}}, .imag = {{0, 0}, {0, 0}}};
    ComplexMatrix2 kraus[2] = {k0, k1};
    double t0 = now_sec();
    for (int r = 0; r < reps; r++) {
        mixDamping(rho, r % n, 0.1);
        mixDepolarising(rho, (r + 1) % n, 0.1);
        mixDephasing(rho, (r + 2) % n, 0.1);
        mixKrausMap(rho, (r + 3) % n, kraus, 2);
    }
    double dt = now_sec() - t0;
    double cps = 4.0 * reps / dt;
    printf("{\"config\": \"channels\", \"n\": %d, \"seconds\": %.3f, "
           "\"channels_per_sec\": %.2f}\n", n, dt, cps);
    destroyQureg(rho, env);
}

int main(int argc, char **argv) {
    QuESTEnv env = createQuESTEnv();
    const char *cfg = argc > 1 ? argv[1] : "all";
    int gates_n = argc > 2 ? atoi(argv[2]) : 26;
    if (!strcmp(cfg, "gates") || !strcmp(cfg, "all"))
        bench_gates(env, gates_n, 16, 4);
    if (!strcmp(cfg, "tutorial") || !strcmp(cfg, "all"))
        bench_tutorial(env, 2000);
    if (!strcmp(cfg, "rcs") || !strcmp(cfg, "all"))
        bench_rcs(env, 22, 4);
    if (!strcmp(cfg, "channels") || !strcmp(cfg, "all"))
        bench_channels(env, 11, 8);
    destroyQuESTEnv(env);
    return 0;
}
