"""Collective-schedule introspection for the sharded engines.

The numbers come from the StableHLO that XLA actually lowered for the
given mesh — not from re-deriving the dispatch rules — so the report
cannot drift from the engine. Tracing allocates no state: a 40q/256-dev
schedule can be inspected on a laptop (scripts/pod_projection.py builds
its north-star projection on exactly this).

Reference analogue: none. The reference's exchange schedule is implicit
in C control flow (exchangeStateVectors call sites,
QuEST_cpu_distributed.c:481-509); there is nothing a user can ask for
short of reading the source.
"""
from __future__ import annotations

import re
from typing import Sequence

import jax
import jax.numpy as jnp


_FUNC_RE = re.compile(r"func\.func\s+(?:public|private)?\s*@([\w$.-]+)\s*\(")
_CALL_RE = re.compile(r"(?<!custom_)call\s+@([\w$.-]+)\s*\(")
_WHILE_RE = re.compile(r"stablehlo\.while\(([^)]*)\)")
_CONST_RE = re.compile(r"%([\w.#]+)\s*=\s*stablehlo\.constant\s+"
                       r"dense<(-?\d+)>\s*:\s*tensor<i\d+>")
_CMP_LT_RE = re.compile(r"stablehlo\.compare\s+LT,\s*%([\w.#]+),"
                        r"\s*%([\w.#]+)")
_COLLECTIVE_RE = re.compile(r'"?stablehlo\.(collective_permute|all_to_all'
                            r"|all_reduce)\"?[\s(]")
_OPERAND_RE = re.compile(r"tensor<([0-9x]+)xf(32|64)>")


def _scan_collectives(stablehlo_text: str):
    """Walk the module function by function, tracking `stablehlo.while`
    regions (the body of a lax.fori_loop/scan — its collectives execute
    TRIP-COUNT times, not once) and call-graph multiplicity (XLA often
    outlines a loop body into a private func; its collectives belong to
    every call site). A flat regex over the text counts each textual
    occurrence once — exactly the undercount that would let a comm_stats
    parity assertion pass vacuously on looped programs. Trip counts are
    derived from the canonical fori pattern (counter init constant,
    `compare LT` against a constant bound, unit step); anything else
    conservatively counts once.

    Returns {func: {"ops": [(op, elems, dtype_bytes, mult)],
                    "calls": [(callee, mult)], "public": bool}}."""
    funcs = {}
    cur = None
    # scope stack entries: (kind, mult_at_entry); mult = product of
    # enclosing while trip counts
    stack = []
    mult = 1
    consts = {}
    pending_while = None    # {"inits": {arg: ssa}, "cond_done": bool,
    #                          "bound": int|None, "arg": str|None}
    for raw in stablehlo_text.splitlines():
        line = raw.strip()
        mfun = _FUNC_RE.search(line)
        if mfun and cur is None:
            cur = mfun.group(1)
            funcs[cur] = {"ops": [], "calls": [],
                          "public": "public" in line.split("@")[0]}
            stack = [("func", 1)]
            mult = 1
            consts = {}
            pending_while = None
            continue
        if cur is None:
            continue
        for mc in _CONST_RE.finditer(line):
            consts[mc.group(1)] = int(mc.group(2))
        mw = _WHILE_RE.search(line)
        if mw:
            inits = {}
            for part in mw.group(1).split(","):
                if "=" in part:
                    a, v = part.split("=", 1)
                    inits[a.strip().lstrip("%")] = v.strip().lstrip("%")
            pending_while = {"inits": inits, "cond_done": False,
                            "trip": None}
        if pending_while is not None and not pending_while["cond_done"]:
            mcmp = _CMP_LT_RE.search(line)
            if mcmp:
                arg, bound = mcmp.group(1), mcmp.group(2)
                init_ssa = pending_while["inits"].get(arg)
                if init_ssa is not None and bound in consts \
                        and init_ssa in consts:
                    pending_while["trip"] = max(
                        consts[bound] - consts[init_ssa], 0)
        mcoll = _COLLECTIVE_RE.search(line)
        if mcoll:
            op = mcoll.group(1)
            elems, dbytes = 0, 0
            for mo in _OPERAND_RE.finditer(line[mcoll.end():]):
                e = 1
                for d in mo.group(1).split("x"):
                    e *= int(d)
                elems, dbytes = e, (4 if mo.group(2) == "32" else 8)
                break
            funcs[cur]["ops"].append((op, elems, dbytes, mult))
        for mcall in _CALL_RE.finditer(line):
            funcs[cur]["calls"].append((mcall.group(1), mult))
        # region tracking: every '{' opens a scope carrying the loop
        # multiplicity inside it; every '}' returns to the enclosing one
        for ch in line:
            if ch == "{":
                kind, m = "plain", mult
                if pending_while is not None:
                    if not pending_while["cond_done"]:
                        kind = "cond"
                    else:
                        kind = "do"
                        t = pending_while["trip"]
                        m = mult * (t if t is not None else 1)
                        pending_while = None
                stack.append((kind, m))
                mult = m
            elif ch == "}":
                if not stack:
                    continue
                kind, _ = stack.pop()
                if kind == "cond" and pending_while is not None:
                    pending_while["cond_done"] = True
                if kind == "func" or not stack:
                    cur = None
                    stack = []
                    mult = 1
                else:
                    mult = stack[-1][1]
    return funcs


def parse_collectives(stablehlo_text: str, num_devices: int = None) -> dict:
    """Counts and per-device payload bytes of cross-device collectives
    in a lowered module's StableHLO text. all-to-all relabel events
    (parallel/relabel.py) ship (D-1)/D of their operand off-device;
    pass `num_devices` for that accounting (defaults to counting the
    whole operand, an upper bound).

    Counts THROUGH `stablehlo.while` bodies (x derivable trip count) and
    called private functions (x call-site multiplicity): XLA lowers
    lax.fori_loop/scan-wrapped exchanges as one textual op executing many
    times, and the flat count would otherwise undercount — letting the
    comm_stats parity assertion pass vacuously (fixture-pinned in
    tests/test_comm.py)."""
    funcs = _scan_collectives(stablehlo_text)
    # execution counts through the call graph (a DAG in HLO): public
    # funcs run once; a callee runs caller_count x call multiplicity
    exec_count = {name: (1 if rec["public"] else 0)
                  for name, rec in funcs.items()}
    for _ in range(len(funcs)):
        nxt = {name: (1 if rec["public"] else 0)
               for name, rec in funcs.items()}
        for name, rec in funcs.items():
            for callee, m in rec["calls"]:
                if callee in nxt:
                    nxt[callee] += exec_count[name] * m
        if nxt == exec_count:
            break
        exec_count = nxt

    cp_bytes, a2a_bytes = [], []
    all_reduces = 0
    for name, rec in funcs.items():
        runs = exec_count[name]
        for op, elems, dbytes, m in rec["ops"]:
            count = runs * m
            if op == "all_reduce":
                all_reduces += count
            elif op == "collective_permute":
                cp_bytes += [elems * dbytes] * count
            elif op == "all_to_all":
                a2a_bytes += [elems * dbytes] * count
    if num_devices:
        a2a_bytes = [b * (num_devices - 1) // num_devices
                     for b in a2a_bytes]
    return {
        "collective_permutes": len(cp_bytes),
        "all_to_alls": len(a2a_bytes),
        "collective_exchanges": len(cp_bytes) + len(a2a_bytes),
        "ici_bytes_per_device": int(sum(cp_bytes) + sum(a2a_bytes)),
        "all_reduces": all_reduces,
    }


def _merge_comm(rec: dict, predicted, cinfo: dict, D: int,
                bytes_per_real: int, topo=None) -> None:
    """Fold the comm planner's PREDICTED schedule into a sharded-
    schedule record and flag whether it matches XLA's lowered collective
    accounting — the plan->predict->assert contract (tests/test_comm.py
    and bench.py multichip assert comm_matches_hlo). Under a
    hierarchical topology the record additionally splits the predicted
    bytes into comm_ici_bytes/comm_dci_bytes (summing EXACTLY to the
    HLO-asserted total — XLA's lowered text cannot see hosts, so the
    split is the planner's, the total is the contract's)."""
    from quest_tpu.parallel import comm as C
    if topo is None:
        topo = C.topology(D)
    rec.update(C.comm_stats(predicted, num_devices=D,
                            bytes_per_real=bytes_per_real, topo=topo))
    rec["comm_strategy"] = cinfo.get("strategy", "plain")
    rec["comm_plan_enabled"] = C.plan_enabled()
    rec["comm_topology"] = topo.describe(D)
    rec["comm_matches_hlo"] = (
        rec["comm_collective_permutes"] == rec["collective_permutes"]
        and rec["comm_all_to_alls"] == rec["all_to_alls"]
        and rec["comm_exchanges"] == rec["collective_exchanges"]
        and rec["comm_bytes"] == rec["ici_bytes_per_device"]
        and rec["comm_ici_bytes"] + rec["comm_dci_bytes"]
        == rec["comm_bytes"])


def sharded_schedule(ops: Sequence, n: int, density: bool, mesh,
                     engine: str = "banded") -> dict:
    """Lower (don't compile) the sharded program for `mesh` and report
    its communication schedule plus the local plan it rides on. `n` is
    the STATE-qubit count (2x the logical count for density registers),
    matching the compile_circuit_sharded* builders."""
    from quest_tpu import precision
    from quest_tpu.ops import fusion as F
    from quest_tpu.parallel import sharded as S

    builders = {"banded": S.compile_circuit_sharded_banded,
                "fused": S.compile_circuit_sharded_fused,
                "pergate": S.compile_circuit_sharded}
    if engine not in builders:
        raise ValueError(f"engine must be one of {sorted(builders)}, "
                         f"got {engine!r}")
    D = int(mesh.devices.size)
    g = D.bit_length() - 1
    local_n = n - g
    # lower with the dtype the run would really use (the engines take it
    # from the input array): byte figures must reflect f64 registers
    rdt = precision.real_dtype_of(precision.get_default_dtype())
    bytes_per_real = jnp.dtype(rdt).itemsize
    # interpret-mode kernels for the fused engine: the collective
    # schedule is identical (kernels are purely local) and non-interpret
    # pallas_call refuses to LOWER on a CPU host — which is exactly
    # where pod-scale introspection runs
    kw = {"interpret": True} if engine == "fused" else {}
    step = builders[engine](ops, n, density, mesh=mesh, donate=False, **kw)
    lowered = jax.jit(step).lower(
        jax.ShapeDtypeStruct((2, 1 << n), rdt))
    rec = parse_collectives(lowered.as_text(), num_devices=D)
    rec.update({
        "devices": D,
        "local_qubits": local_n,
        "global_qubits": g,
        "engine": engine,
        "chunk_bytes": 2 * bytes_per_real * (1 << n) // D,
    })

    from quest_tpu.parallel import comm as C

    topo = C.topology(D)
    ici_b = topo.ici_bits(D) if topo.hierarchical else None

    if engine == "pergate":
        # the per-gate engine runs one pass per op — band-plan stats
        # would describe passes it never executes. The op list comes
        # from the SAME policy home the compiler executes
        # (S.pergate_flat), so the comm plan below is the executed one
        cinfo: dict = {}
        chosen = S.pergate_flat(ops, n, density, local_n,
                                comm_info=cinfo)
        # gate counts exclude planner-injected relabel events (their
        # targets span every qubit; they have their own line below)
        gate_ops = [op for op in chosen if op.kind != "relabel"]
        rec["local_ops"] = sum(
            1 for op in gate_ops if max(op.targets) < local_n)
        rec["global_ops"] = len(gate_ops) - rec["local_ops"]
        rec["relabel_events"] = len(chosen) - len(gate_ops)
        predicted = C.predict_exchanges_flat(chosen, local_n, ici_b)
        _merge_comm(rec, predicted, cinfo, D, bytes_per_real, topo)
    else:
        # band layout AND op-list rewrite PER ENGINE, via the engines'
        # own helpers (S.engine_flat is the ONE home of the rewrite
        # policy) so the reported plan cannot drift from the executed
        # one — the banded and fused builders both run the
        # layer-amortized relabel pass by default, so the plan stats
        # describe the POST-relabel schedule (its remaining global
        # items are the lowered collective-permutes; its relabel
        # events are the all-to-alls)
        bands = None
        fused_bands = None
        if engine == "fused":
            fused_bands = S.fused_shard_bands(n, local_n)
            bands = fused_bands
        if bands is None:
            bands = S._shard_bands(n, local_n)
        # engine_flat schedules before relabeling; ONE scheduler run
        # serves both the plan and the reported counters
        sstats: dict = {}
        cinfo = {}
        flat_r = S.engine_flat(ops, n, density, local_n,
                               sched_stats=sstats, bands=bands,
                               comm_info=cinfo)
        rec["scheduler"] = sstats
        items = cinfo.get("items")
        if items is None:
            items = F.plan(flat_r, n, bands=bands)
        _merge_comm(rec, C.predict_exchanges_items(items, local_n, ici_b),
                    cinfo, D, bytes_per_real, topo)
        rec["local_band_passes"] = sum(
            1 for it in items
            if isinstance(it, F.BandOp) and it.ql < local_n)
        rec["global_qubit_items"] = sum(
            1 for it in items
            if isinstance(it, F.BandOp) and it.ql >= local_n)
        rec["relabel_events"] = sum(
            1 for op in flat_r if op.kind == "relabel")
        if fused_bands is not None:
            # per-shard sweep metrics through the SAME structural
            # planner the fused compiler executes
            # (sharded.plan_fused_structural + pallas_band.maybe_sweep);
            # sweep_stats keeps the metric definition consistent with
            # plan_stats — EVERY part (kernel sweep or sharded item)
            # counts as one full-state pass in hbm_sweeps
            from quest_tpu.ops import pallas_band as PB
            sparts = S.plan_fused_structural(items, local_n)
            sw = PB.sweep_stats(PB.maybe_sweep(sparts, local_n))
            rec["kernel_segments"] = sum(
                1 for p in sparts if p[0] == "segment")
            rec["hbm_sweeps"] = sw["hbm_sweeps"]
            rec["kernel_sweeps"] = sw["kernel_sweeps"]
            rec["sweep_stages"] = sw["sweep_stages"]
    return rec


def sharded_measured_schedule(ops: Sequence, n: int, density: bool, mesh,
                              engine: str = "banded",
                              relabel: bool = None) -> dict:
    """The DYNAMIC-circuit counterpart of sharded_schedule: lower the
    measured program for `mesh` and report its collective schedule plus
    the per-stretch plan (measurement-free stretches relabel/fuse like
    the static engines — parallel.sharded.plan_measured_program is the
    one home of that planning, read here so the report cannot drift
    from the execution)."""
    from quest_tpu import precision
    from quest_tpu.circuit import flatten_ops
    from quest_tpu.ops import fusion as F
    from quest_tpu.parallel import sharded as S

    D = int(mesh.devices.size)
    g = D.bit_length() - 1
    local_n = n - g
    rdt = precision.real_dtype_of(precision.get_default_dtype())
    bytes_per_real = jnp.dtype(rdt).itemsize
    # interpret-mode kernels: same collective schedule, and the only
    # form that LOWERS on a CPU host (see sharded_schedule above)
    step = S.compile_circuit_sharded_measured(
        ops, n, density, mesh, donate=False, engine=engine,
        relabel=relabel, interpret=True)
    key = jax.ShapeDtypeStruct((2,), jnp.uint32)
    lowered = jax.jit(step).lower(
        jax.ShapeDtypeStruct((2, 1 << n), rdt), key)
    rec = parse_collectives(lowered.as_text(), num_devices=D)

    engine, relabel = S.resolve_measured_engine(engine, relabel)
    flat = flatten_ops(ops, n, density)
    # interpret=True here too: this stats pass re-plans the program (the
    # compiler's own plan isn't exposed), and non-interpret segment
    # closures would be pointlessly built for counting
    program, resolved = S.plan_measured_program(flat, n, local_n, engine,
                                                relabel, interpret=True)
    stretches = [el for el in program if el[0] == "stretch"]
    dyn = [el[1] for el in program if el[0] == "dyn"]
    relabel_events = 0
    band_passes = 0
    kernel_segments = 0
    for el in stretches:
        items = el[1]
        for it in items:
            if isinstance(it, F.BandOp):
                band_passes += 1
            elif getattr(it, "op", it).kind == "relabel":
                relabel_events += 1
        if el[2] is not None:
            kernel_segments += sum(1 for p in el[2] if p[0] == "kernel")
    rec.update({
        "devices": D,
        "local_qubits": local_n,
        "global_qubits": g,
        "engine": resolved,
        "chunk_bytes": 2 * bytes_per_real * (1 << n) // D,
        "stretches": len(stretches),
        "measurements": sum(1 for op in dyn
                            if op.kind in ("measure", "measure_dm")),
        "classical_ops": sum(1 for op in dyn if op.kind == "classical"),
        "relabel_events": relabel_events,
        "local_band_passes": band_passes,
        "kernel_segments": kernel_segments,
    })

    # predicted comm schedule: stretch items price like the static
    # engines; each measurement is one psum (all_reduce); classical
    # feedback applies its inner gates unconditionally (blended by the
    # outcome predicate), so they price at face value
    from quest_tpu.parallel import comm as C
    topo = C.topology(D)
    ici_b = topo.ici_bits(D) if topo.hierarchical else None
    predicted = []
    pred_psums = 0
    for el in program:
        if el[0] == "dyn":
            op = el[1]
            if op.kind in ("measure", "measure_dm"):
                pred_psums += 1
            else:
                for gop in op.operand[0]:
                    predicted += C.gateop_exchanges(gop, local_n, ici_b)
        else:
            predicted += C.predict_exchanges_items(el[1], local_n, ici_b)
    _merge_comm(rec, predicted,
                {"strategy": "relabel" if relabel else "plain"},
                D, bytes_per_real, topo)
    rec["comm_all_reduces"] = pred_psums
    rec["comm_matches_hlo"] = (rec["comm_matches_hlo"]
                               and pred_psums == rec["all_reduces"])
    return rec


def assert_plan_comm(plan, ops, n: int, density: bool, mesh,
                     engine: str = "banded") -> dict:
    """The plan IR's comm record asserted EQUAL to XLA's lowered
    collective accounting — plan->predict->assert for the autotuner
    (quest_tpu/plan.py): `plan.comm` was priced by pure host math;
    here the sharded program actually lowers over `mesh` and its
    StableHLO collective counts/bytes must match the plan's numbers
    exactly (scripts/check_plan_golden.py gates this on the golden
    circuits; raises AssertionError with both sides on any drift).
    Returns the lowered-schedule record for further inspection."""
    comm = plan.comm
    if comm is None:
        raise AssertionError(
            "plan carries no comm record (built without devices=) — "
            "autotune with devices/mesh before asserting")
    rec = sharded_schedule(ops, n, density, mesh, engine=engine)
    checks = (
        ("comm_exchanges", "collective_exchanges"),
        ("comm_collective_permutes", "collective_permutes"),
        ("comm_all_to_alls", "all_to_alls"),
        ("comm_bytes", "ici_bytes_per_device"),
    )
    for pk, lk in checks:
        if comm[pk] != rec[lk]:
            raise AssertionError(
                f"plan comm prediction drifted from the lowered HLO: "
                f"plan.{pk}={comm[pk]} != lowered {lk}={rec[lk]} "
                f"(engine={engine}, devices={rec['devices']}, "
                f"strategy plan={comm['comm_strategy']!r} "
                f"lowered={rec['comm_strategy']!r})")
    if comm["comm_strategy"] != rec["comm_strategy"]:
        raise AssertionError(
            f"plan comm strategy {comm['comm_strategy']!r} != the "
            f"lowered program's {rec['comm_strategy']!r}")
    return rec
