"""Collective-schedule introspection for the sharded engines.

The numbers come from the StableHLO that XLA actually lowered for the
given mesh — not from re-deriving the dispatch rules — so the report
cannot drift from the engine. Tracing allocates no state: a 40q/256-dev
schedule can be inspected on a laptop (scripts/pod_projection.py builds
its north-star projection on exactly this).

Reference analogue: none. The reference's exchange schedule is implicit
in C control flow (exchangeStateVectors call sites,
QuEST_cpu_distributed.c:481-509); there is nothing a user can ask for
short of reading the source.
"""
from __future__ import annotations

import re
from typing import Sequence

import jax
import jax.numpy as jnp


def parse_collectives(stablehlo_text: str, num_devices: int = None) -> dict:
    """Counts and per-device payload bytes of cross-device collectives
    in a lowered module's StableHLO text. all-to-all relabel events
    (parallel/relabel.py) ship (D-1)/D of their operand off-device;
    pass `num_devices` for that accounting (defaults to counting the
    whole operand, an upper bound)."""
    def payload_bytes(op_name):
        """Per-occurrence operand bytes of a StableHLO collective."""
        sizes = []
        for m in re.finditer(
                rf"stablehlo\.{op_name}.*?tensor<([0-9x]+)xf(32|64)>",
                stablehlo_text):
            e = 1
            for d in m.group(1).split("x"):
                e *= int(d)
            sizes.append(e * (4 if m.group(2) == "32" else 8))
        return sizes

    cp_elems = payload_bytes("collective_permute")
    a2a_bytes = payload_bytes("all_to_all")
    if num_devices:
        a2a_bytes = [b * (num_devices - 1) // num_devices
                     for b in a2a_bytes]
    all_reduces = len(re.findall(r"stablehlo\.all_reduce", stablehlo_text))
    return {
        "collective_permutes": len(cp_elems),
        "all_to_alls": len(a2a_bytes),
        "collective_exchanges": len(cp_elems) + len(a2a_bytes),
        "ici_bytes_per_device": int(sum(cp_elems) + sum(a2a_bytes)),
        "all_reduces": all_reduces,
    }


def sharded_schedule(ops: Sequence, n: int, density: bool, mesh,
                     engine: str = "banded") -> dict:
    """Lower (don't compile) the sharded program for `mesh` and report
    its communication schedule plus the local plan it rides on. `n` is
    the STATE-qubit count (2x the logical count for density registers),
    matching the compile_circuit_sharded* builders."""
    from quest_tpu import precision
    from quest_tpu.circuit import flatten_ops
    from quest_tpu.ops import fusion as F
    from quest_tpu.parallel import sharded as S

    builders = {"banded": S.compile_circuit_sharded_banded,
                "fused": S.compile_circuit_sharded_fused,
                "pergate": S.compile_circuit_sharded}
    if engine not in builders:
        raise ValueError(f"engine must be one of {sorted(builders)}, "
                         f"got {engine!r}")
    D = int(mesh.devices.size)
    g = D.bit_length() - 1
    local_n = n - g
    # lower with the dtype the run would really use (the engines take it
    # from the input array): byte figures must reflect f64 registers
    rdt = precision.real_dtype_of(precision.get_default_dtype())
    bytes_per_real = jnp.dtype(rdt).itemsize
    # interpret-mode kernels for the fused engine: the collective
    # schedule is identical (kernels are purely local) and non-interpret
    # pallas_call refuses to LOWER on a CPU host — which is exactly
    # where pod-scale introspection runs
    kw = {"interpret": True} if engine == "fused" else {}
    step = builders[engine](ops, n, density, mesh=mesh, donate=False, **kw)
    lowered = jax.jit(step).lower(
        jax.ShapeDtypeStruct((2, 1 << n), rdt))
    rec = parse_collectives(lowered.as_text(), num_devices=D)
    rec.update({
        "devices": D,
        "local_qubits": local_n,
        "global_qubits": g,
        "engine": engine,
        "chunk_bytes": 2 * bytes_per_real * (1 << n) // D,
    })

    flat = flatten_ops(ops, n, density)
    if engine == "pergate":
        # the per-gate engine runs one pass per op — band-plan stats
        # would describe passes it never executes
        rec["local_ops"] = sum(
            1 for op in flat if max(op.targets) < local_n)
        rec["global_ops"] = len(flat) - rec["local_ops"]
    else:
        # band layout AND op-list rewrite PER ENGINE, via the engines'
        # own helpers (S.engine_flat is the ONE home of the rewrite
        # policy) so the reported plan cannot drift from the executed
        # one — the banded and fused builders both run the
        # layer-amortized relabel pass by default, so the plan stats
        # describe the POST-relabel schedule (its remaining global
        # items are the lowered collective-permutes; its relabel
        # events are the all-to-alls)
        bands = None
        fused_bands = None
        if engine == "fused":
            fused_bands = S.fused_shard_bands(n, local_n)
            bands = fused_bands
        if bands is None:
            bands = S._shard_bands(n, local_n)
        # engine_flat schedules before relabeling; ONE scheduler run
        # serves both the plan and the reported counters
        sstats: dict = {}
        flat_r = S.engine_flat(ops, n, density, local_n,
                               sched_stats=sstats)
        rec["scheduler"] = sstats
        items = F.plan(flat_r, n, bands=bands)
        rec["local_band_passes"] = sum(
            1 for it in items
            if isinstance(it, F.BandOp) and it.ql < local_n)
        rec["global_qubit_items"] = sum(
            1 for it in items
            if isinstance(it, F.BandOp) and it.ql >= local_n)
        rec["relabel_events"] = sum(
            1 for op in flat_r if op.kind == "relabel")
        if fused_bands is not None:
            # per-shard sweep metrics through the SAME structural
            # planner the fused compiler executes
            # (sharded.plan_fused_structural + pallas_band.maybe_sweep);
            # sweep_stats keeps the metric definition consistent with
            # plan_stats — EVERY part (kernel sweep or sharded item)
            # counts as one full-state pass in hbm_sweeps
            from quest_tpu.ops import pallas_band as PB
            sparts = S.plan_fused_structural(items, local_n)
            sw = PB.sweep_stats(PB.maybe_sweep(sparts, local_n))
            rec["kernel_segments"] = sum(
                1 for p in sparts if p[0] == "segment")
            rec["hbm_sweeps"] = sw["hbm_sweeps"]
            rec["kernel_sweeps"] = sw["kernel_sweeps"]
            rec["sweep_stages"] = sw["sweep_stages"]
    return rec


def sharded_measured_schedule(ops: Sequence, n: int, density: bool, mesh,
                              engine: str = "banded",
                              relabel: bool = None) -> dict:
    """The DYNAMIC-circuit counterpart of sharded_schedule: lower the
    measured program for `mesh` and report its collective schedule plus
    the per-stretch plan (measurement-free stretches relabel/fuse like
    the static engines — parallel.sharded.plan_measured_program is the
    one home of that planning, read here so the report cannot drift
    from the execution)."""
    from quest_tpu import precision
    from quest_tpu.circuit import flatten_ops
    from quest_tpu.ops import fusion as F
    from quest_tpu.parallel import sharded as S

    D = int(mesh.devices.size)
    g = D.bit_length() - 1
    local_n = n - g
    rdt = precision.real_dtype_of(precision.get_default_dtype())
    bytes_per_real = jnp.dtype(rdt).itemsize
    # interpret-mode kernels: same collective schedule, and the only
    # form that LOWERS on a CPU host (see sharded_schedule above)
    step = S.compile_circuit_sharded_measured(
        ops, n, density, mesh, donate=False, engine=engine,
        relabel=relabel, interpret=True)
    key = jax.ShapeDtypeStruct((2,), jnp.uint32)
    lowered = jax.jit(step).lower(
        jax.ShapeDtypeStruct((2, 1 << n), rdt), key)
    rec = parse_collectives(lowered.as_text(), num_devices=D)

    engine, relabel = S.resolve_measured_engine(engine, relabel)
    flat = flatten_ops(ops, n, density)
    # interpret=True here too: this stats pass re-plans the program (the
    # compiler's own plan isn't exposed), and non-interpret segment
    # closures would be pointlessly built for counting
    program, resolved = S.plan_measured_program(flat, n, local_n, engine,
                                                relabel, interpret=True)
    stretches = [el for el in program if el[0] == "stretch"]
    dyn = [el[1] for el in program if el[0] == "dyn"]
    relabel_events = 0
    band_passes = 0
    kernel_segments = 0
    for el in stretches:
        items = el[1]
        for it in items:
            if isinstance(it, F.BandOp):
                band_passes += 1
            elif getattr(it, "op", it).kind == "relabel":
                relabel_events += 1
        if el[2] is not None:
            kernel_segments += sum(1 for p in el[2] if p[0] == "kernel")
    rec.update({
        "devices": D,
        "local_qubits": local_n,
        "global_qubits": g,
        "engine": resolved,
        "chunk_bytes": 2 * bytes_per_real * (1 << n) // D,
        "stretches": len(stretches),
        "measurements": sum(1 for op in dyn
                            if op.kind in ("measure", "measure_dm")),
        "classical_ops": sum(1 for op in dyn if op.kind == "classical"),
        "relabel_events": relabel_events,
        "local_band_passes": band_passes,
        "kernel_segments": kernel_segments,
    })
    return rec
