"""Mesh construction and sharding helpers for the amplitude axis.

Chunk layout matches the reference exactly (QuEST_cpu.c:1280-1312): device d
of D holds amplitudes [d*2^n/D, (d+1)*2^n/D) — i.e. the top log2(D) qubits
select the device. Power-of-2 device counts only (ref validateNumRanks,
QuEST_validation.c:81).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from quest_tpu.env import AMP_AXIS
from quest_tpu.state import Qureg


def make_amp_mesh(num_devices: Optional[int] = None,
                  devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over the amplitude axis. num_devices must be a power of 2."""
    if devices is None:
        devices = jax.devices()
    if num_devices is None:
        num_devices = 1 << (len(devices).bit_length() - 1)
    if num_devices & (num_devices - 1):
        raise ValueError(
            f"Invalid number of devices {num_devices}: must be a power of 2 "
            "(ref QuEST_validation.c:81)")
    if num_devices > len(devices):
        raise ValueError(f"requested {num_devices} devices, have {len(devices)}")
    return Mesh(np.array(devices[:num_devices]), (AMP_AXIS,))


def amp_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the amplitude axis of the (2, 2^n) plane array; the re/im
    plane axis is replicated (each device holds both planes of its chunk)."""
    return NamedSharding(mesh, P(None, AMP_AXIS))


def shard_qureg(q: Qureg, mesh: Mesh) -> Qureg:
    """Lay the register's amplitudes out over the mesh (one contiguous chunk
    per device). Requires 2^n >= mesh size."""
    if q.num_amps < mesh.devices.size:
        raise ValueError(
            f"register of {q.num_amps} amps cannot shard over "
            f"{mesh.devices.size} devices (ref QuEST_validation.c:129)")
    return q.replace_amps(jax.device_put(q.amps, amp_sharding(mesh)))
