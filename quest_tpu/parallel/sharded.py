"""Explicit shard_map circuit engine: the reference's distributed schedule,
re-thought for ICI.

Mapping from the reference (QuEST/src/CPU/QuEST_cpu_distributed.c):

  reference mechanism                          | here
  ---------------------------------------------|---------------------------
  chunkId / numChunks                          | lax.axis_index over the mesh
  halfMatrixBlockFitsInChunk (:356-361)        | static `target < local_n` test
  getChunkPairId = id XOR 2^(q-log2 chunk)     | ppermute permutation table
    (:303-312)                                 |   [(i, i ^ 2^gbit)]
  exchangeStateVectors MPI_Sendrecv (:481-509) | lax.ppermute of the chunk
  swap-to-local for multi-target gates         | half-chunk ppermute swap
    (:1441-1483)                               |   (_swap_global_local)
  diagonal ops never communicate               | device-bit-indexed diagonal
    (QuEST_cpu.c:2940-3109)                    |   reduction (_diagonal_op)
  MPI_Allreduce reductions                     | lax.psum

Everything below runs INSIDE one shard_map over the 1-D amplitude mesh; the
whole circuit is a single XLA program, so purely-local stretches fuse and
the collectives are laid out by the compiler over ICI.

The per-device chunk holds amplitudes whose top log2(D) index bits equal the
device index — "global" qubits. A gate is local iff all its targets are
below local_n; the op dispatch is static (targets are trace-time constants),
exactly as the reference's local/distributed split is resolved per call.
"""

from __future__ import annotations

import math
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from quest_tpu import cplx
from quest_tpu.env import AMP_AXIS
from quest_tpu.ops import apply as A
from quest_tpu.state import Qureg


def _pair_perm(num_devices: int, gbit: int):
    """Partner table: device i <-> i XOR 2^gbit (ref getChunkPairId,
    QuEST_cpu_distributed.c:303-312)."""
    return [(i, i ^ (1 << gbit)) for i in range(num_devices)]


def _split_controls(controls, cstates, local_n):
    loc_c, loc_s, glob = [], [], []
    for c, s in zip(controls, cstates):
        if c < local_n:
            loc_c.append(c)
            loc_s.append(s)
        else:
            glob.append((c - local_n, s))
    return tuple(loc_c), tuple(loc_s), tuple(glob)


def _global_pred(dev, glob_controls):
    """Traced scalar bool: this device's chunk satisfies all global-qubit
    controls (the whole chunk shares those bits)."""
    pred = None
    for bit, want in glob_controls:
        p = ((dev >> bit) & 1) == want
        pred = p if pred is None else pred & p
    return pred


def _blend(new_flat, old_flat, local_n, loc_c, loc_s, pred):
    """Keep `new` only where local control mask AND global predicate hold."""
    if not loc_c and pred is None:
        return new_flat
    if loc_c:
        mask = A._control_mask(local_n, loc_c, loc_s)
        if pred is not None:
            mask = mask & pred
        new_t = jnp.where(mask, new_flat.reshape((2,) * local_n),
                          old_flat.reshape((2,) * local_n))
        return new_t.reshape(-1)
    return jnp.where(pred, new_flat, old_flat)


def _swap_global_local(chunk, dev, D, gbit, l, local_n):
    """Distributed SWAP of global qubit (device bit `gbit`) with local qubit
    l — a half-chunk ppermute (the reference exchanges full chunks for this,
    QuEST_cpu.c:3539-3578; half is sufficient because only amplitudes whose
    two swapped bits differ move)."""
    t = chunk.reshape((2,) * local_n)
    ax = local_n - 1 - l
    g = (dev >> gbit) & 1
    moving = lax.dynamic_slice_in_dim(t, 1 - g, 1, axis=ax)
    recv = lax.ppermute(moving, AMP_AXIS, _pair_perm(D, gbit))
    t = lax.dynamic_update_slice_in_dim(t, recv, 1 - g, axis=ax)
    return t.reshape(-1)


def _matrix_op(chunk, dev, *, D, local_n, m_pair, targets, controls, cstates):
    """General k-qubit matrix gate on the local chunk, distributing over
    global target qubits when needed."""
    dtype = chunk.dtype
    glob_targets = [t for t in targets if t >= local_n]

    if not glob_targets:
        loc_c, loc_s, glob_c = _split_controls(controls, cstates, local_n)
        pred = _global_pred(dev, glob_c)
        new = A.apply_matrix(chunk, local_n, cplx.unpack(m_pair, dtype), targets)
        return _blend(new, chunk, local_n, loc_c, loc_s, pred)

    if len(targets) == 1:
        loc_c, loc_s, glob_c = _split_controls(controls, cstates, local_n)
        pred = _global_pred(dev, glob_c)
        # single-qubit butterfly via one full-chunk pair exchange
        # (ref statevec_compactUnitary distributed path, :846-881)
        gbit = targets[0] - local_n
        recv = lax.ppermute(chunk, AMP_AXIS, _pair_perm(D, gbit))
        mybit = (dev >> gbit) & 1
        m = cplx.unpack(m_pair, dtype)
        # chunk with bit 0 holds "up" amps: new_up = m00*up + m01*lo;
        # bit 1 holds "lo": new_lo = m10*up + m11*lo
        diag = jnp.where(mybit == 0, m[0, 0], m[1, 1])
        off = jnp.where(mybit == 0, m[0, 1], m[1, 0])
        new = diag * chunk + off * recv
        return _blend(new, chunk, local_n, loc_c, loc_s, pred)

    # multi-target with global targets: swap each global target into a local
    # position, apply locally, swap back (ref :1441-1483). Slots not holding
    # targets are eligible — including control qubits, whose role then moves
    # to the vacated global position (the reference's ctrlMask fixup under
    # relabeling, QuEST_cpu_distributed.c:1457-1466).
    slots = [q for q in range(local_n) if q not in targets]
    ctrl_slots = set(controls)
    slots.sort(key=lambda q: (q in ctrl_slots, q))  # prefer non-control slots
    if len(slots) < len(glob_targets):
        raise ValueError(
            f"matrix on targets {targets} needs {len(glob_targets)} local "
            f"slots but only {len(slots)} exist "
            "(ref E_CANNOT_FIT_MULTI_QUBIT_MATRIX, QuEST_validation.c:121)")
    relabeled = list(targets)
    new_controls = list(controls)
    swaps = []
    for gt in glob_targets:
        l = slots.pop(0)
        swaps.append((gt - local_n, l))
        relabeled[relabeled.index(gt)] = l
        if l in ctrl_slots:  # control at slot l now lives at global pos gt
            new_controls[new_controls.index(l)] = gt
        chunk = _swap_global_local(chunk, dev, D, gt - local_n, l, local_n)
    loc_c, loc_s, glob_c = _split_controls(new_controls, cstates, local_n)
    pred = _global_pred(dev, glob_c)
    new = A.apply_matrix(chunk, local_n, cplx.unpack(m_pair, chunk.dtype),
                         relabeled)
    chunk = _blend(new, chunk, local_n, loc_c, loc_s, pred)
    for gbit, l in reversed(swaps):
        chunk = _swap_global_local(chunk, dev, D, gbit, l, local_n)
    return chunk


def _diagonal_op(chunk, dev, *, local_n, d_pair, targets, controls, cstates):
    """Diagonal gate: never communicates. Global-target axes of the diagonal
    table are resolved by indexing with the device's fixed bit (the TPU
    analogue of the reference's global-index parity reads,
    QuEST_cpu.c:2940-3109)."""
    dtype = chunk.dtype
    loc_c, loc_s, glob_c = _split_controls(controls, cstates, local_n)
    pred = _global_pred(dev, glob_c)
    k = len(targets)
    d = cplx.unpack(d_pair, dtype).reshape((2,) * k)
    # diag index bit j <-> targets[j] <-> table axis (k-1-j). Reduce global
    # axes first (ascending j removes the highest remaining axis each time,
    # leaving lower axes untouched).
    loc_targets = []
    for j in range(k):
        if targets[j] >= local_n:
            bit = (dev >> (targets[j] - local_n)) & 1
            d = lax.dynamic_index_in_dim(d, bit, axis=k - 1 - j, keepdims=False)
    for j in range(k):
        if targets[j] < local_n:
            loc_targets.append(targets[j])
    if loc_targets:
        new = A.apply_diagonal(chunk, local_n, d.reshape(-1), loc_targets)
    else:
        new = chunk * d  # d is a traced scalar
    return _blend(new, chunk, local_n, loc_c, loc_s, pred)


def _parity_op(chunk, dev, *, local_n, targets, angle):
    """exp(-i angle/2 Z...Z): local sign tensor x traced global sign scalar."""
    rdt = chunk.real.dtype
    gsign = None
    for t in targets:
        if t >= local_n:
            s = 1.0 - 2.0 * ((dev >> (t - local_n)) & 1).astype(rdt)
            gsign = s if gsign is None else gsign * s
    sign = None
    for t in targets:
        if t < local_n:
            shape = [1] * local_n
            shape[local_n - 1 - t] = 2
            vec = jnp.array([1.0, -1.0], dtype=rdt).reshape(shape)
            sign = vec if sign is None else sign * vec
    if sign is None:
        sign = jnp.ones((), dtype=rdt)
    if gsign is not None:
        sign = sign * gsign
    half = jnp.asarray(angle, dtype=rdt) / 2.0
    factor = cplx.make(jnp.cos(half * sign), -jnp.sin(half * sign))
    t = chunk.reshape((2,) * local_n)
    return (t * factor.astype(chunk.dtype)).reshape(-1)


def _all_ones_op(chunk, dev, *, local_n, term_pair, qubits):
    """Phase `term` on amplitudes whose listed qubits are ALL 1; global
    qubits contribute a per-device scalar predicate."""
    dtype = chunk.dtype
    glob = [(q - local_n, 1) for q in qubits if q >= local_n]
    loc = [q for q in qubits if q < local_n]
    term = cplx.unpack(term_pair, dtype)
    pred = _global_pred(dev, glob)
    if pred is not None:
        one = cplx.cones((), dtype)
        term = jnp.where(pred, term, one)
    if loc:
        return A.apply_phase_on_all_ones(chunk, local_n, loc, term)
    return chunk * term


def _apply_gateop(chunk, dev, *, D, local_n, density, op):
    """One GateOp (possibly + its conjugate column-space copy for density
    registers, ref QuEST.c:8-10) on the local chunk."""
    n = local_n + int(math.log2(D))
    shift = n // 2 if density else 0

    def one(chunk, targets, controls, conj):
        if op.kind == "parity":
            ang = -op.operand if conj else op.operand
            return _parity_op(chunk, dev, local_n=local_n, targets=targets,
                              angle=ang)
        if op.kind == "allones":
            t = np.conj(op.operand) if conj else op.operand
            return _all_ones_op(chunk, dev, local_n=local_n,
                                term_pair=cplx.pack(t), qubits=targets)
        operand = np.conj(op.operand) if conj else op.operand
        pair = cplx.pack(operand)
        if op.kind == "diagonal":
            return _diagonal_op(chunk, dev, local_n=local_n, d_pair=pair,
                                targets=targets, controls=controls,
                                cstates=op.cstates)
        return _matrix_op(chunk, dev, D=D, local_n=local_n, m_pair=pair,
                          targets=targets, controls=controls,
                          cstates=op.cstates)

    chunk = one(chunk, op.targets, op.controls, conj=False)
    if density:
        chunk = one(chunk, tuple(t + shift for t in op.targets),
                    tuple(c + shift for c in op.controls), conj=True)
    return chunk


def compile_circuit_sharded(ops: Sequence, n: int, density: bool, mesh: Mesh,
                            donate: bool = True):
    """Compile a gate sequence into ONE shard_map program over the mesh —
    the explicit, reference-faithful distributed schedule. Returns a jitted
    fn: sharded flat amps -> sharded flat amps."""
    D = int(mesh.devices.size)
    g = int(math.log2(D))
    local_n = n - g
    if local_n < 1:
        raise ValueError("register too small for mesh")
    ops = tuple(ops)

    def run(chunk):
        chunk = chunk.reshape(-1)
        dev = lax.axis_index(AMP_AXIS)
        for op in ops:
            chunk = _apply_gateop(chunk, dev, D=D, local_n=local_n,
                                  density=density, op=op)
        return chunk

    sharded = jax.shard_map(run, mesh=mesh, in_specs=P(AMP_AXIS),
                            out_specs=P(AMP_AXIS))
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def apply_circuit_sharded(q: Qureg, ops: Sequence, mesh: Mesh,
                          donate: bool = True) -> Qureg:
    """One-shot convenience wrapper around compile_circuit_sharded."""
    from quest_tpu.parallel.mesh import amp_sharding
    fn = compile_circuit_sharded(ops, q.num_state_qubits, q.is_density, mesh,
                                 donate)
    amps = jax.device_put(q.amps, amp_sharding(mesh))
    return q.replace_amps(fn(amps))
