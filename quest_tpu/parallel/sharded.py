"""Explicit shard_map circuit engine: the reference's distributed schedule,
re-thought for ICI.

Mapping from the reference (QuEST/src/CPU/QuEST_cpu_distributed.c):

  reference mechanism                          | here
  ---------------------------------------------|---------------------------
  chunkId / numChunks                          | lax.axis_index over the mesh
  halfMatrixBlockFitsInChunk (:356-361)        | static `target < local_n` test
  getChunkPairId = id XOR 2^(q-log2 chunk)     | ppermute permutation table
    (:303-312)                                 |   [(i, i ^ 2^gbit)]
  exchangeStateVectors MPI_Sendrecv (:481-509) | lax.ppermute of the chunk
  swap-to-local for multi-target gates         | half-chunk ppermute swap
    (:1441-1483)                               |   (_swap_global_local)
  diagonal ops never communicate               | device-bit-indexed diagonal
    (QuEST_cpu.c:2940-3109)                    |   reduction (_diagonal_op)
  MPI_Allreduce reductions                     | lax.psum

Everything below runs INSIDE one shard_map over the 1-D amplitude mesh; the
whole circuit is a single XLA program, so purely-local stretches fuse and
the collectives are laid out by the compiler over ICI.

The per-device chunk is a (2, 2^local_n) plane pair (see quest_tpu.state)
holding amplitudes whose top log2(D) index bits equal the device index —
"global" qubits. A gate is local iff all its targets are below local_n; the
op dispatch is static (targets are trace-time constants), exactly as the
reference's local/distributed split is resolved per call.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from quest_tpu import compat
from quest_tpu import cplx
from quest_tpu.env import AMP_AXIS
from quest_tpu import validation as val
from quest_tpu.ops import apply as A
from quest_tpu.parallel import comm as C
from quest_tpu.state import Qureg


def _pair_perm(num_devices: int, gbit: int):
    """Partner table: device i <-> i XOR 2^gbit (ref getChunkPairId,
    QuEST_cpu_distributed.c:303-312)."""
    return [(i, i ^ (1 << gbit)) for i in range(num_devices)]


def _split_controls(controls, cstates, local_n):
    loc_c, loc_s, glob = [], [], []
    for c, s in zip(controls, cstates):
        if c < local_n:
            loc_c.append(c)
            loc_s.append(s)
        else:
            glob.append((c - local_n, s))
    return tuple(loc_c), tuple(loc_s), tuple(glob)


def _global_pred(dev, glob_controls):
    """Traced scalar bool: this device's chunk satisfies all global-qubit
    controls (the whole chunk shares those bits)."""
    pred = None
    for bit, want in glob_controls:
        p = ((dev >> bit) & 1) == want
        pred = p if pred is None else pred & p
    return pred


def _mask_blend(new, old, local_n, loc_c, loc_s, pred):
    """Keep `new` only where local control mask AND global predicate hold.
    new/old are (2, 2^local_n) plane pairs."""
    if not loc_c and pred is None:
        return new
    if loc_c:
        dims, axis_of = A.seg_view(local_n, tuple(sorted(loc_c, reverse=True)))
        mask = A.control_mask(len(dims), axis_of, loc_c, loc_s)
        if pred is not None:
            mask = mask & pred
        shape = (2,) + dims
        return jnp.where(mask, new.reshape(shape),
                         old.reshape(shape)).reshape(2, -1)
    return jnp.where(pred, new, old)


def _sliced_ppermute(block, D, gbit):
    """One pair exchange of `block` ((2, x) planes), split into
    QUEST_EXCHANGE_SLICES independent collective-permutes — or
    QUEST_EXCHANGE_SLICES_DCI when device bit `gbit` crosses the host
    boundary of the QUEST_COMM_TOPOLOGY model (comm.effective_slices is
    the shared clamp and comm.Topology.link_of the shared classifier,
    so the predicted and lowered collective counts agree at any knob
    value and per link class). Slicing lets the compiler overlap
    transfer with the consuming compute on real ICI/DCI —
    structure-verifiable on the CPU mesh; wall-clock A/B deferred to
    first chip run (docs/DISTRIBUTED.md)."""
    s = C.effective_slices(block.shape[-1],
                           C.topology(D).link_of(gbit, D))
    if s == 1:
        return lax.ppermute(block, AMP_AXIS, _pair_perm(D, gbit))
    xs = block.reshape(2, s, -1)
    recv = [lax.ppermute(xs[:, i], AMP_AXIS, _pair_perm(D, gbit))
            for i in range(s)]
    return jnp.concatenate(recv, axis=1)


def _swap_global_local(chunk, dev, D, gbit, l, local_n):
    """Distributed SWAP of global qubit (device bit `gbit`) with local qubit
    l — a half-chunk ppermute (the reference exchanges full chunks for this,
    QuEST_cpu.c:3539-3578; half is sufficient because only amplitudes whose
    two swapped bits differ move)."""
    dims, axis_of = A.seg_view(local_n, (l,))
    t = chunk.reshape((2,) + dims)
    ax = 1 + axis_of[l]
    g = (dev >> gbit) & 1
    moving = lax.dynamic_slice_in_dim(t, 1 - g, 1, axis=ax)
    recv = _sliced_ppermute(moving.reshape(2, -1), D, gbit).reshape(
        moving.shape)
    t = lax.dynamic_update_slice_in_dim(t, recv, 1 - g, axis=ax)
    return t.reshape(2, -1)


def _butterfly_1q(chunk, dev, *, D, local_n, m_pair, gbit, loc_c=(),
                  loc_s=(), pred=None):
    """Single-qubit butterfly on GLOBAL bit `gbit` via one full-chunk
    pair exchange (ref statevec_compactUnitary distributed path,
    :846-881), sliced per QUEST_EXCHANGE_SLICES with the combine
    consuming each received slice independently. `m_pair` may be a
    TRACED (re, im) pair — only scalar selects touch it — which is how
    the adjoint engine (quest_tpu/adjoint.py) runs parametric rx/ry on
    a global target without leaving the sharded body."""
    mybit = (dev >> gbit) & 1
    mre = jnp.asarray(m_pair[0], dtype=chunk.dtype)
    mim = jnp.asarray(m_pair[1], dtype=chunk.dtype)
    # chunk with bit 0 holds "up" amps: new_up = m00*up + m01*lo;
    # bit 1 holds "lo": new_lo = m10*up + m11*lo
    dre = jnp.where(mybit == 0, mre[0, 0], mre[1, 1])
    die = jnp.where(mybit == 0, mim[0, 0], mim[1, 1])
    ore = jnp.where(mybit == 0, mre[0, 1], mre[1, 0])
    oie = jnp.where(mybit == 0, mim[0, 1], mim[1, 0])

    def combine(part, recv):
        re, im = part[0], part[1]
        rre, rim = recv[0], recv[1]
        return jnp.stack([
            dre * re - die * im + ore * rre - oie * rim,
            dre * im + die * re + ore * rim + oie * rre,
        ])

    s = C.effective_slices(chunk.shape[-1],
                           C.topology(D).link_of(gbit, D))
    if s == 1:
        recv = lax.ppermute(chunk, AMP_AXIS, _pair_perm(D, gbit))
        new = combine(chunk, recv)
    else:
        xs = chunk.reshape(2, s, -1)
        parts = []
        for i in range(s):
            recv = lax.ppermute(xs[:, i], AMP_AXIS,
                                _pair_perm(D, gbit))
            parts.append(combine(xs[:, i], recv))
        new = jnp.concatenate(parts, axis=1)
    return _mask_blend(new, chunk, local_n, loc_c, loc_s, pred)


def _matrix_op(chunk, dev, *, D, local_n, m_pair, targets, controls, cstates):
    """General k-qubit matrix gate on the local chunk, distributing over
    global target qubits when needed. Concrete operands with global
    targets are specialized by STRUCTURE before falling back to generic
    swap-to-local (the analogue of the reference's per-channel distributed
    kernels, QuEST_cpu_distributed.c:545-697):

    - diagonal matrix (dephasing-class superops, diagonal gates): routed
      as a diagonal op — ZERO communication. NOTE this deliberately
      exempts diagonal operands from the E_CANNOT_FIT_MULTI_QUBIT_MATRIX
      fit check below: the reference rejects any dense-form matrix whose
      global targets exceed the free local slots
      (QuEST_validation.c:121) because its kernels must relabel; the
      diagonal path needs no relabeling, so the same call SUCCEEDS here
      — a strict capability extension, tested in
      test_distributed.py::test_diagonal_matrix_exempt_from_fit_check;
    - two targets with exactly one global (outer-qubit channels whose
      column-space copy crosses the shard boundary, and crossing 2q
      gates): ONE direct pair exchange, shipping only the slices the
      cross-block actually reads (half-chunk for damping- AND
      depolarising-class channels — their cross-blocks each read one
      row-slice — full chunk for dense cross-blocks like generic
      crossing 2q unitaries; either way at most half of swap-to-local's
      swap-in + swap-out round trip).

    Measured (benchmarks/channel_bytes.py, 8-device mesh): outer-qubit
    damping 4096 -> 2048 bytes per channel; dephasing 4096 -> 0.

    The routing decision itself lives in comm.matrix_route — shared with
    the comm planner's predictor, so the planned exchange schedule
    cannot drift from what executes here."""
    sup = C.dense_operand(m_pair, len(targets))
    route = C.matrix_route(sup, tuple(targets), tuple(controls), local_n)

    if route[0] == "diagonal":
        return _diagonal_op(chunk, dev, local_n=local_n,
                            d_pair=cplx.pack(np.diagonal(sup)),
                            targets=targets, controls=(), cstates=())
    if route[0] == "pair2t":
        _, _, t, jg, gbit = route
        return _pair_exchange_2t(chunk, dev, D=D, local_n=local_n,
                                 sup=sup, t=t, jg=jg, gbit=gbit)

    if route[0] == "local":
        loc_c, loc_s, glob_c = _split_controls(controls, cstates, local_n)
        pred = _global_pred(dev, glob_c)
        # local controls are handled inside apply_matrix; only the global
        # predicate needs an outer blend
        new = A.apply_matrix(chunk, local_n, m_pair, targets, loc_c, loc_s)
        if pred is not None:
            new = jnp.where(pred, new, chunk)
        return new

    if route[0] == "butterfly":
        loc_c, loc_s, glob_c = _split_controls(controls, cstates, local_n)
        pred = _global_pred(dev, glob_c)
        return _butterfly_1q(chunk, dev, D=D, local_n=local_n,
                             m_pair=m_pair, gbit=route[1], loc_c=loc_c,
                             loc_s=loc_s, pred=pred)

    # multi-target with global targets: swap each global target into a local
    # position, apply locally, swap back (ref :1441-1483). Slots not holding
    # targets are eligible — including control qubits, whose role then moves
    # to the vacated global position (the reference's ctrlMask fixup under
    # relabeling, QuEST_cpu_distributed.c:1457-1466).
    glob_targets = [t for t in targets if t >= local_n]
    slots = [q for q in range(local_n) if q not in targets]
    ctrl_slots = set(controls)
    slots.sort(key=lambda q: (q in ctrl_slots, q))  # prefer non-control slots
    if len(slots) < len(glob_targets):
        from quest_tpu.validation import QuESTError
        raise QuESTError(
            "Invalid number of target qubits: the matrix cannot fit in a "
            f"single device chunk (targets {targets} need "
            f"{len(glob_targets)} local slots, only {len(slots)} exist; "
            "ref E_CANNOT_FIT_MULTI_QUBIT_MATRIX, QuEST_validation.c:121)")
    relabeled = list(targets)
    new_controls = list(controls)
    swaps = []
    for gt in glob_targets:
        l = slots.pop(0)
        swaps.append((gt - local_n, l))
        relabeled[relabeled.index(gt)] = l
        if l in ctrl_slots:  # control at slot l now lives at global pos gt
            new_controls[new_controls.index(l)] = gt
        chunk = _swap_global_local(chunk, dev, D, gt - local_n, l, local_n)
    loc_c, loc_s, glob_c = _split_controls(new_controls, cstates, local_n)
    pred = _global_pred(dev, glob_c)
    new = A.apply_matrix(chunk, local_n, m_pair, relabeled, loc_c, loc_s)
    if pred is not None:
        new = jnp.where(pred, new, chunk)
    chunk = new
    for gbit, l in reversed(swaps):
        chunk = _swap_global_local(chunk, dev, D, gbit, l, local_n)
    return chunk


def _diagonal_op(chunk, dev, *, local_n, d_pair, targets, controls, cstates):
    """Diagonal gate: never communicates. Global-target axes of the diagonal
    table are resolved by indexing with the device's fixed bit (the TPU
    analogue of the reference's global-index parity reads,
    QuEST_cpu.c:2940-3109)."""
    loc_c, loc_s, glob_c = _split_controls(controls, cstates, local_n)
    pred = _global_pred(dev, glob_c)
    k = len(targets)
    dre = jnp.asarray(d_pair[0], dtype=chunk.dtype).reshape((2,) * k)
    dim_ = jnp.asarray(d_pair[1], dtype=chunk.dtype).reshape((2,) * k)
    # diag index bit j <-> targets[j] <-> table axis (k-1-j). Reduce global
    # axes first (ascending j removes the highest remaining axis each time,
    # leaving lower axes untouched).
    for j in range(k):
        if targets[j] >= local_n:
            bit = (dev >> (targets[j] - local_n)) & 1
            dre = lax.dynamic_index_in_dim(dre, bit, axis=k - 1 - j,
                                           keepdims=False)
            dim_ = lax.dynamic_index_in_dim(dim_, bit, axis=k - 1 - j,
                                            keepdims=False)
    loc_targets = [t for t in targets if t < local_n]
    if loc_targets:
        new = A.apply_diagonal(chunk, local_n,
                               (dre.reshape(-1), dim_.reshape(-1)),
                               loc_targets, loc_c, loc_s)
        if pred is not None:
            new = jnp.where(pred, new, chunk)
        return new
    # d is a traced complex scalar pair
    re, im = chunk[0], chunk[1]
    new = jnp.stack([re * dre - im * dim_, re * dim_ + im * dre])
    return _mask_blend(new, chunk, local_n, loc_c, loc_s, pred)


def _parity_op(chunk, dev, *, local_n, targets, angle):
    """exp(-i angle/2 Z...Z): local sign tensor x traced global sign scalar."""
    rdt = chunk.dtype
    gsign = None
    for t in targets:
        if t >= local_n:
            s = 1.0 - 2.0 * ((dev >> (t - local_n)) & 1).astype(rdt)
            gsign = s if gsign is None else gsign * s
    loc = tuple(sorted((t for t in targets if t < local_n), reverse=True))
    dims, axis_of = A.seg_view(local_n, loc)
    sign = None
    for t in loc:
        shape = [1] * len(dims)
        shape[axis_of[t]] = 2
        vec = jnp.array([1.0, -1.0], dtype=rdt).reshape(shape)
        sign = vec if sign is None else sign * vec
    if sign is None:
        sign = jnp.ones((), dtype=rdt)
    if gsign is not None:
        sign = sign * gsign
    half = jnp.asarray(angle, dtype=rdt) / 2.0
    cosf = jnp.cos(half)
    sinf = jnp.sin(half) * sign
    re = chunk[0].reshape(dims)
    im = chunk[1].reshape(dims)
    nre = re * cosf + im * sinf
    nim = im * cosf - re * sinf
    return jnp.stack([nre.reshape(-1), nim.reshape(-1)])


def _all_ones_op(chunk, dev, *, local_n, term_pair, qubits):
    """Phase `term` on amplitudes whose listed qubits are ALL 1; global
    qubits contribute a per-device scalar predicate."""
    rdt = chunk.dtype
    glob = [(q - local_n, 1) for q in qubits if q >= local_n]
    loc = [q for q in qubits if q < local_n]
    tre = jnp.asarray(term_pair[0], dtype=rdt).reshape(())
    tim = jnp.asarray(term_pair[1], dtype=rdt).reshape(())
    pred = _global_pred(dev, glob)
    if pred is not None:
        tre = jnp.where(pred, tre, jnp.ones((), dtype=rdt))
        tim = jnp.where(pred, tim, jnp.zeros((), dtype=rdt))
    if loc:
        return A.apply_phase_on_all_ones(chunk, local_n, loc, (tre, tim))
    re, im = chunk[0], chunk[1]
    return jnp.stack([re * tre - im * tim, re * tim + im * tre])


def _pair_exchange_2t(chunk, dev, *, D, local_n, sup, t, jg, gbit):
    """Two-target operator with local target `t` and the other target on
    device bit `gbit` (matrix index bit `jg`): split the 4x4 operator by
    the global index bit into same-block and cross-block 2x2s, exchange
    only what the cross-block reads."""
    rdt = chunk.dtype
    g = (dev >> gbit) & 1

    # block split + the cross-blocks' read sets come from the comm
    # planner's shared helper, so the half-vs-full exchange decision
    # here and the predicted byte count are one computation
    same, cross, need = C.pair2t_blocks(sup, jg)

    def tr(mats):  # traced per-device 2x2 (re, im) pair
        p0, p1 = cplx.pack(mats[0]), cplx.pack(mats[1])
        sel = (g == 0)
        return (jnp.where(sel, jnp.asarray(p0[0], rdt), jnp.asarray(p1[0], rdt)),
                jnp.where(sel, jnp.asarray(p0[1], rdt), jnp.asarray(p1[1], rdt)))

    new = A.apply_matrix(chunk, local_n, tr(same), (t,))

    if all(len(nd) <= 1 for nd in need):
        # half-chunk exchange: each device ships the single row-slice its
        # partner reads (ref exchangePairStateVectorHalves semantics)
        nv = [nd[0] if nd else 0 for nd in need]
        dims, axis_of = A.seg_view(local_n, (t,))
        ax = 1 + axis_of[t]
        tview = chunk.reshape((2,) + dims)
        send_idx = jnp.where(g == 0, nv[1], nv[0])
        moving = lax.dynamic_slice_in_dim(tview, send_idx, 1, axis=ax)
        recv = _sliced_ppermute(moving.reshape(2, -1), D, gbit).reshape(
            moving.shape)
        # cross contribution: out(r) += cross[g][r, need[g]] * recv
        col = [np.asarray(cross[gv])[:, nv[gv]] for gv in (0, 1)]
        shape = [1] * len(dims)
        shape[axis_of[t]] = 2

        def coef(part):
            a = jnp.asarray(part(col[0]), rdt).reshape(shape)
            b = jnp.asarray(part(col[1]), rdt).reshape(shape)
            return jnp.where(g == 0, a, b)

        cre, cim = coef(np.real), coef(np.imag)
        rre, rim = recv[0], recv[1]
        add_re = cre * rre - cim * rim
        add_im = cre * rim + cim * rre
        out = new.reshape((2,) + dims)
        out = out.at[0].add(add_re).at[1].add(add_im)
        return out.reshape(2, -1)

    # dense cross-block (generic crossing 2q unitaries; 1q channels all
    # take the half-chunk branch above): one full-chunk exchange
    recv = _sliced_ppermute(chunk, D, gbit)
    return new + A.apply_matrix(recv, local_n, tr(cross), (t,))


def _relabel_op(chunk, *, local_n, slots):
    """Whole-register relabel event: swap every device bit j with local
    slot slots[j] in ONE all-to-all collective (bytes: (1 - 1/D) of the
    chunk — vs one whole-chunk pair exchange PER global 1q gate on the
    plain schedule, ref exchangeStateVectors,
    QuEST_cpu_distributed.c:481-509). The slot bits are transposed to a
    leading axis whose value equals the destination device index; the
    received blocks land at slot-bit positions equal to the SOURCE
    device index, which is the same layout — so the inverse transpose
    restores the standard chunk view. Planned by
    parallel.relabel.plan_full_relabels; validated bit-exactly against
    a host bit-swap oracle (tests/test_lazy_relabel.py)."""
    g = len(slots)
    planes = chunk.reshape((2,) + (2,) * local_n)  # plane, b_{ln-1}..b_0
    axes_front = [1 + (local_n - 1 - q) for q in reversed(slots)]
    rest = [a for a in range(1, local_n + 1) if a not in axes_front]
    perm = [0] + axes_front + rest
    x = planes.transpose(perm).reshape(2, 1 << g, -1)
    y = lax.all_to_all(x, AMP_AXIS, split_axis=1, concat_axis=1)
    y = y.reshape((2,) + (2,) * local_n)
    inv = np.argsort(perm)
    return y.transpose(list(inv)).reshape(2, -1)


def _apply_gateop(chunk, dev, *, D, local_n, density, op):
    """One GateOp (possibly + its conjugate column-space copy for density
    registers, ref QuEST.c:8-10) on the local chunk."""
    n = local_n + int(math.log2(D))
    shift = n // 2 if density else 0

    if op.kind == "relabel":
        return _relabel_op(chunk, local_n=local_n, slots=op.operand)

    if op.kind == "superop":
        # channel superoperator on [targets, targets+N]: one matrix op on
        # the doubled register, both spaces at once (no dual); _matrix_op
        # specializes by structure (diagonal / single-crossing-target)
        from quest_tpu.ops.matrices import superop_targets
        return _matrix_op(chunk, dev, D=D, local_n=local_n,
                          m_pair=cplx.pack(op.operand),
                          targets=list(superop_targets(op.targets, shift)),
                          controls=(), cstates=())

    def one(chunk, targets, controls, conj):
        if op.kind == "parity":
            ang = -op.operand if conj else op.operand
            return _parity_op(chunk, dev, local_n=local_n, targets=targets,
                              angle=ang)
        if op.kind == "allones":
            t = np.conj(op.operand) if conj else op.operand
            return _all_ones_op(chunk, dev, local_n=local_n,
                                term_pair=cplx.pack(t), qubits=targets)
        operand = np.conj(op.operand) if conj else op.operand
        pair = cplx.pack(operand)
        if op.kind == "diagonal":
            return _diagonal_op(chunk, dev, local_n=local_n, d_pair=pair,
                                targets=targets, controls=controls,
                                cstates=op.cstates)
        return _matrix_op(chunk, dev, D=D, local_n=local_n, m_pair=pair,
                          targets=targets, controls=controls,
                          cstates=op.cstates)

    chunk = one(chunk, op.targets, op.controls, conj=False)
    if density:
        chunk = one(chunk, tuple(t + shift for t in op.targets),
                    tuple(c + shift for c in op.controls), conj=True)
    return chunk


def engine_flat(ops: Sequence, n: int, density: bool, local_n: int,
                lazy: bool = False, relabel: bool = None,
                sched_stats: Optional[dict] = None,
                bands: Sequence = None,
                comm_info: Optional[dict] = None):
    """The flat op list the banded/fused sharded engines EXECUTE:
    flatten_ops plus the one relabel-rewrite policy. The single home of
    that policy — parallel.introspect reads plan statistics through
    this same function, so the reported schedule cannot drift from the
    executed one. relabel=None means AUTO under QUEST_COMM_PLAN
    (the comm planner picks the cheapest of plain/coalesce/
    relabel-events/lazy by predicted comm_stats bytes through the
    engine's own fusion-plan pricing — parallel/comm.py; `bands` is the
    calling engine's band layout so the pricing matches what it runs)
    and plan_full_relabels when the knob is off; requesting both lazy
    and relabel explicitly raises. `sched_stats`, when a dict, receives
    the scheduler's counters from the SAME scheduler run that produced
    the returned list; `comm_info` likewise receives the comm planner's
    strategy + per-candidate costs, plus — when the auto path ran —
    the winning candidate's fusion plan under "items" so callers don't
    re-run F.plan on the identical input (introspect's consumers)."""
    from quest_tpu.circuit import flatten_ops
    from quest_tpu.ops import fusion as F

    if lazy and relabel:
        raise ValueError("lazy and relabel are mutually exclusive "
                         "relabeling strategies; pick one")
    # the commutation-aware scheduler runs BEFORE relabel planning: a
    # reorder changes which qubits co-occur between exchanges, so the
    # relabel pass must see the order that will actually execute (its
    # composition-aware A/B guard then accepts or rejects events
    # against the SCHEDULED list; composed diagonals price at zero
    # exchange cost — diagonals never communicate at any position)
    flat0 = flatten_ops(ops, n, density)
    if sched_stats is None:
        flat = F.maybe_schedule(flat0, n)
    else:
        enabled = F._schedule_enabled()
        sched, stats = F.schedule(flat0, n)
        stats["enabled"] = enabled
        sched_stats.update(stats)
        flat = sched if enabled else list(flat0)
    if lazy:
        from quest_tpu.parallel.relabel import lazy_relabel_ops
        if comm_info is not None:
            comm_info.update({"strategy": "lazy"})
        return lazy_relabel_ops(flat, n, local_n)
    if relabel is None and C.plan_enabled():
        chosen, info = C.choose_plan(
            flat, n, local_n, engine="banded",
            bands=bands if bands is not None else _shard_bands(n, local_n))
        if comm_info is not None:
            comm_info.update(info)
        return chosen
    if relabel or relabel is None:
        from quest_tpu.parallel.relabel import plan_full_relabels
        if comm_info is not None:
            comm_info.update({"strategy": "relabel"})
        return plan_full_relabels(flat, n, local_n)
    if comm_info is not None:
        comm_info.update({"strategy": "plain"})
    return flat


def comm_plan_record(ops: Sequence, n: int, density: bool,
                     devices: int) -> dict:
    """The plan IR's 'comm' record (quest_tpu/plan.py; re-emitted
    bit-for-bit by Circuit.plan_stats): the comm planner's PREDICTED
    collective schedule for the banded/fused sharded engines over
    `devices`, built through the SAME policy home they execute
    (engine_flat + the comm predictor) so the report cannot drift from
    the lowered program. Pure host math — no mesh, no compile."""
    from quest_tpu import precision
    from quest_tpu.ops import fusion as F

    if devices < 2 or devices & (devices - 1):
        raise ValueError(
            f"devices must be a power of two >= 2, got {devices}")
    g = devices.bit_length() - 1
    local_n = n - g
    if local_n < 1:
        raise ValueError(
            f"register too small to shard over {devices} devices "
            f"(ref E_DISTRIB_QUREG_TOO_SMALL)")
    cinfo: dict = {}
    bands = _shard_bands(n, local_n)
    flat_r = engine_flat(ops, n, density, local_n,
                         bands=bands, comm_info=cinfo)
    items = cinfo.get("items")
    if items is None:
        items = F.plan(flat_r, n, bands=bands)
    rdt = precision.real_dtype_of(precision.get_default_dtype())
    topo = C.topology(devices)
    ici_b = topo.ici_bits(devices) if topo.hierarchical else None
    rec = C.comm_stats(C.predict_exchanges_items(items, local_n, ici_b),
                       num_devices=devices,
                       bytes_per_real=np.dtype(rdt).itemsize,
                       topo=topo)
    rec.update({
        "devices": devices,
        "comm_strategy": cinfo.get("strategy", "plain"),
        "comm_plan_enabled": C.plan_enabled(),
        "comm_topology": topo.describe(devices),
        "relabel_events": sum(1 for op in flat_r
                              if op.kind == "relabel"),
    })
    return rec


def pergate_flat(ops: Sequence, n: int, density: bool, local_n: int,
                 lazy: bool = False,
                 comm_info: Optional[dict] = None) -> List:
    """The flat op list the PER-GATE engine (compile_circuit_sharded)
    executes — flatten (duals explicit, superops doubled) plus the comm
    planner's per-circuit choice under QUEST_COMM_PLAN (priced per
    routed op, the per-gate engine's real cost: no band composition).
    The single home of that policy, shared with parallel.introspect so
    the reported per-gate schedule cannot drift from the executed one.
    lazy=True forces the legacy lazy rewrite; QUEST_COMM_PLAN=0 keeps
    the reference-faithful plain schedule."""
    from quest_tpu.circuit import flatten_ops
    from quest_tpu.parallel.relabel import lazy_relabel_ops

    flat = flatten_ops(ops, n, density)
    if lazy:
        if comm_info is not None:
            comm_info.update({"strategy": "lazy"})
        return lazy_relabel_ops(flat, n, local_n)
    if C.plan_enabled():
        chosen, info = C.choose_plan(flat, n, local_n, engine="pergate")
        if comm_info is not None:
            comm_info.update(info)
        return chosen
    if comm_info is not None:
        comm_info.update({"strategy": "plain"})
    return list(flat)


def _shard_bands(n: int, local_n: int):
    """Band layout aligned to the shard boundary: full-width bands inside
    the local chunk, width-1 bands for global (device-index) qubits — the
    distributed analogue of pallas_band.plan_bands, so composed runs stay
    local and each global qubit costs exactly one pair exchange."""
    from quest_tpu.ops.fusion import BAND_W
    bands = []
    ql = 0
    while ql < local_n:
        w = min(BAND_W, local_n - ql)
        bands.append((ql, w))
        ql += w
    for q in range(local_n, n):
        bands.append((q, 1))
    return bands


def fused_shard_bands(n: int, local_n: int):
    """The FUSED sharded engine's band layout, or None when the Pallas
    kernel cannot host the chunk (the engine then falls back to the
    banded layout). Shared by compile_circuit_sharded_fused and
    parallel.introspect so the reported plan cannot drift from the
    executed one: local bands follow the kernel's layout, global qubits
    get width-1 bands so each composes into one 2x2 pair exchange."""
    from quest_tpu.ops import pallas_band as PB
    if not PB.usable(local_n):
        return None
    return list(PB.plan_bands(local_n)) + [(q, 1)
                                           for q in range(local_n, n)]


def _band_op_sharded(chunk, dev, *, D, local_n, bop):
    """A composed BandOp on the sharded register: local bands apply as one
    in-chunk contraction; width-1 global bands ride the single-qubit pair
    exchange. Cross-shard controls become whole-chunk predicates."""
    if bop.ql >= local_n:          # global qubit: 2x2 via pair exchange
        return _matrix_op(chunk, dev, D=D, local_n=local_n,
                          m_pair=(bop.gre, bop.gim), targets=[bop.ql],
                          controls=[q for q, _ in bop.preds],
                          cstates=[s for _, s in bop.preds])
    loc_p = [(q, s) for q, s in bop.preds if q < local_n]
    glob_p = [(q - local_n, s) for q, s in bop.preds if q >= local_n]
    pred = _global_pred(dev, glob_p)
    new = A.apply_band(chunk, local_n, (bop.gre, bop.gim), bop.ql, bop.w,
                       loc_p)
    if pred is not None:
        new = jnp.where(pred, new, chunk)
    return new


def compile_circuit_sharded_banded(ops: Sequence, n: int, density: bool,
                                   mesh: Mesh, donate: bool = True,
                                   lazy: bool = False,
                                   relabel: bool = None):
    """Band-fusion engine over the mesh: the same planner that drives the
    single-chip engines (quest_tpu/ops/fusion.py), with bands aligned to
    the shard boundary. Commuting gate runs on local qubits compose into
    one contraction per band; global-qubit runs compose into one 2x2 per
    qubit (ONE ppermute pair exchange each — the reference would exchange
    once per gate, QuEST_cpu_distributed.c:846-881); cross-shard 2q
    unitaries KAK-decompose so their entangling content travels as
    communication-free parity phases.

    relabel (default on) runs the layer-amortized relabeling pass
    (parallel/relabel.py plan_full_relabels) — this engine is the f64
    pod path, and the whole-register all-to-all events cut its ICI the
    same way they cut the fused engine's: the event is a fusion BARRIER
    between band runs, so unlike lazy's per-qubit SWAPs it cannot break
    run composition. lazy=True instead rewrites through per-qubit lazy
    relabeling — measured COUNTERPRODUCTIVE here (1152 -> 1856 B on the
    deep-global testbed: the inserted SWAPs break band runs apart);
    kept for experimentation and mutually exclusive with relabel
    (requesting both explicitly raises)."""
    from quest_tpu.ops import fusion as F

    D = int(mesh.devices.size)
    g = int(math.log2(D))
    local_n = n - g
    _reject_measure_ops(ops)
    if local_n < 1:
        val._err(val.ErrorCode.E_DISTRIB_QUREG_TOO_SMALL)
    bands = _shard_bands(n, local_n)
    cinfo: dict = {}
    flat = engine_flat(ops, n, density, local_n, lazy=lazy, relabel=relabel,
                       bands=bands, comm_info=cinfo)
    items = cinfo.get("items")
    if items is None:
        items = F.plan(flat, n, bands=bands)

    def run(chunk):
        chunk = chunk.reshape(2, -1)
        dev = lax.axis_index(AMP_AXIS)
        for it in items:
            if isinstance(it, F.BandOp):
                chunk = _band_op_sharded(chunk, dev, D=D, local_n=local_n,
                                         bop=it)
            else:
                chunk = _apply_gateop(chunk, dev, D=D, local_n=local_n,
                                      density=False, op=it.op)
        return chunk

    sharded = compat.shard_map(run, mesh, P(None, AMP_AXIS),
                               P(None, AMP_AXIS))
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def _apply_plan_item(chunk, dev, *, D, local_n, it):
    """One fusion-plan item (or bare GateOp) on the local chunk — the
    shared applier of the banded, fused and dynamic sharded engines."""
    from quest_tpu.ops import fusion as F
    if isinstance(it, F.BandOp):
        return _band_op_sharded(chunk, dev, D=D, local_n=local_n, bop=it)
    op = getattr(it, "op", it)
    return _apply_gateop(chunk, dev, D=D, local_n=local_n, density=False,
                         op=op)


def compile_plan_items_sharded(items, n: int, mesh: Mesh,
                               donate: bool = False):
    """One jitted shard_map program applying a SLICE of fusion-plan
    items to the sharded (2, 2^n) planes — the durable executor's
    per-step program (quest_tpu/resilience/durable.py): the full
    circuit's plan is cut at item boundaries (each item is one launch
    on this engine — a band contraction, a relabel all-to-all, a pair
    exchange) and each cut compiles through here, so an uninterrupted
    run and a resumed run execute the IDENTICAL program sequence and
    land on bit-identical amplitudes. Reuses the banded engine's
    shared applier (_apply_plan_item); donate defaults OFF because the
    caller snapshots the input for checkpoints."""
    D = int(mesh.devices.size)
    local_n = n - int(math.log2(D))
    if local_n < 1:
        val._err(val.ErrorCode.E_DISTRIB_QUREG_TOO_SMALL)
    items = tuple(items)

    def run(chunk):
        chunk = chunk.reshape(2, -1)
        dev = lax.axis_index(AMP_AXIS)
        for it in items:
            chunk = _apply_plan_item(chunk, dev, D=D, local_n=local_n,
                                     it=it)
        return chunk

    sharded = compat.shard_map(run, mesh, P(None, AMP_AXIS),
                               P(None, AMP_AXIS))
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def plan_fused_structural(items, local_n: int):
    """Structural fused plan of a sharded item stream: maximal runs of
    purely-local fusion-plan items become ("segment", stages, arrays)
    parts via pallas_band.segment_plan; everything else is a
    ("sharded", item) entry (which also acts as a sweep barrier). Pure
    planning — nothing is compiled — shared by _plan_fused_parts below
    and parallel.introspect, so the reported per-shard sweep counts
    cannot drift from the executed ones."""
    from quest_tpu.ops import pallas_band as PB

    def local_only(it) -> bool:
        return all(q < local_n for q in it.qubits())

    parts = []
    run_items: list = []

    def close_run():
        nonlocal run_items
        if not run_items:
            return
        for sub in PB.segment_plan(run_items, local_n):
            if sub[0] == "segment":
                parts.append(sub)
            else:
                parts.append(("sharded", sub[1]))
        run_items = []

    for it in items:
        if local_only(it):
            run_items.append(it)
        else:
            close_run()
            parts.append(("sharded", it))
    close_run()
    return parts


def _plan_fused_parts(items, local_n: int, interpret: bool, seg_cache: dict):
    """Group maximal runs of purely-local fusion-plan items into Pallas
    kernel segments, sweep-fuse geometry-compatible consecutive
    segments into single-launch HBM sweeps (pallas_band.maybe_sweep —
    the PER-SHARD sweep decision, taken after relabel planning since
    engine_flat rewrites the op stream first), and compile each sweep.
    Returns [("kernel", applier, arrays) | ("sharded", item)]. Shared by
    the static fused engine and the dynamic (measured) engine's
    measurement-free stretches; `seg_cache` lets identical-structure
    sweeps across stretches share one compiled kernel."""
    from quest_tpu.ops import pallas_band as PB

    parts = []
    for sub in PB.maybe_sweep(plan_fused_structural(items, local_n),
                              local_n):
        if sub[0] == "segment":
            seg = PB.compile_segment_cached(seg_cache, sub[1], local_n,
                                            interpret=interpret)
            parts.append(("kernel", seg, sub[2]))
        else:
            parts.append(sub)
    return parts


def compile_circuit_sharded_fused(ops: Sequence, n: int, density: bool,
                                  mesh: Mesh, donate: bool = True,
                                  interpret: bool = False,
                                  relabel: bool = None):
    """The Pallas band-segment engine over the device mesh: the pod-scale
    composition of the two fastest paths in the framework. Runs of
    purely-local fused items (band contractions, diagonals, phases, pair
    stages whose qubits and control predicates all sit inside the chunk)
    execute as mega-kernel segments — many operators per HBM pass per
    device, exactly as on one chip (quest_tpu/ops/pallas_band.py) —
    while items touching global (device-index) qubits ride the explicit
    ppermute schedule between segments. The reference has no analogue:
    its distributed backend dispatches one kernel per gate per rank
    (QuEST_cpu_distributed.c:846-881); here a whole local stretch of an
    RCS layer is one kernel launch on every device simultaneously.

    relabel=True (default) first rewrites the flat ops through the
    layer-amortized relabeling pass (parallel/relabel.py
    plan_full_relabels): stretches of global-qubit matrix work run
    locally between whole-register all-to-all events, cutting both the
    collective count and the ICI bytes of deep circuits (the pass
    leaves cheap schedules untouched — events only fire where they pay
    for themselves).

    interpret=True runs the kernels in the Pallas interpreter (CPU-mesh
    testing)."""
    from quest_tpu.ops import fusion as F
    from quest_tpu.ops import pallas_band as PB

    D = int(mesh.devices.size)
    g = int(math.log2(D))
    local_n = n - g
    _reject_measure_ops(ops)
    if local_n < 1:
        val._err(val.ErrorCode.E_DISTRIB_QUREG_TOO_SMALL)
    bands = fused_shard_bands(n, local_n)
    if bands is None:
        # the Pallas kernel cannot host this chunk: banded fallback,
        # forwarding `relabel` so a plain-vs-relabeled ablation stays
        # honest. NOT silent when the caller asked for interpret-mode
        # kernels — those do not exist on the banded path, and a
        # dropped flag here once turned a relabel test into a false
        # positive (caught in review, r4)
        if interpret:
            import sys
            print(f"[sharded] local_n={local_n} below the kernel tier's "
                  f"minimum: falling back to the BANDED engine; the "
                  f"interpret argument does not apply there",
                  file=sys.stderr)
        return compile_circuit_sharded_banded(ops, n, density, mesh,
                                              donate, relabel=relabel)

    cinfo: dict = {}
    flat = engine_flat(ops, n, density, local_n, relabel=relabel,
                       bands=bands, comm_info=cinfo)
    items = cinfo.get("items")
    if items is None:
        items = F.plan(flat, n, bands=bands)
    parts = _plan_fused_parts(items, local_n, interpret, {})

    def apply_sharded_item(chunk, dev, it):
        return _apply_plan_item(chunk, dev, D=D, local_n=local_n, it=it)

    def run(chunk):
        chunk = chunk.reshape(2, -1)
        dev = lax.axis_index(AMP_AXIS)
        if chunk.dtype != jnp.float32:
            # the kernels are f32-only; f64 registers keep full precision
            # on the XLA banded schedule over the same plan
            for it in items:
                chunk = apply_sharded_item(chunk, dev, it)
            return chunk
        for part in parts:
            if part[0] == "kernel":
                out = part[1](chunk.reshape(2, -1, PB.LANES), part[2])
                chunk = out.reshape(2, -1)
            else:
                chunk = apply_sharded_item(chunk, dev, part[1])
        return chunk

    # check_vma=False: pallas_call's out_shape carries no varying-mesh-axes
    # annotation, and every value here is explicitly per-device anyway
    sharded = compat.shard_map(run, mesh, P(None, AMP_AXIS),
                               P(None, AMP_AXIS), check_vma=False)
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def compile_circuit_sharded_fused_batched(ops: Sequence, n: int,
                                          density: bool, mesh: Mesh,
                                          batch: int, donate: bool = True,
                                          interpret: bool = False,
                                          relabel: bool = None):
    """BATCHED Pallas fused engine over the mesh: fn((B, 2, 2^n) planes
    sharded as P(None, None, AMP_AXIS)) — the batch axis stays LOCAL to
    the amplitude mesh, so every device holds all B states of ITS
    amplitude shard. Purely-local runs execute as batched sweep
    launches per device (one leading batch grid dimension,
    pallas_band.compile_segment batch=B): the per-shard launch count of
    a B-shot workload does not scale with B, exactly like the
    single-chip batched engine. Items touching global (device-index)
    qubits ride the explicit collective schedule jax.vmap'ed over the
    batch — a ppermute/all-to-all with a leading batch axis moves B
    messages over the SAME device permutation, no extra collectives.
    f64 registers fall back to the vmapped banded schedule over the
    same plan; below the kernel tier every item runs vmapped-banded."""
    from quest_tpu.ops import fusion as F
    from quest_tpu.ops import pallas_band as PB

    D = int(mesh.devices.size)
    g = int(math.log2(D))
    local_n = n - g
    _reject_measure_ops(ops)
    if local_n < 1:
        val._err(val.ErrorCode.E_DISTRIB_QUREG_TOO_SMALL)
    bands = fused_shard_bands(n, local_n)
    eff_bands = bands if bands is not None else _shard_bands(n, local_n)
    cinfo: dict = {}
    flat = engine_flat(ops, n, density, local_n, relabel=relabel,
                       bands=eff_bands, comm_info=cinfo)
    items = cinfo.get("items")
    if items is None:
        items = F.plan(flat, n, bands=eff_bands)
    parts = None
    if bands is not None:
        parts = []
        seg_cache: dict = {}
        for sub in PB.maybe_sweep(plan_fused_structural(items, local_n),
                                  local_n):
            if sub[0] == "segment":
                seg = PB.compile_segment_cached(
                    seg_cache, tuple(sub[1]), local_n,
                    interpret=interpret, batch=batch)
                parts.append(("kernel", seg, sub[2]))
            else:
                parts.append(sub)
    elif interpret:
        import sys
        print(f"[sharded] batched engine: local_n={local_n} below the "
              f"kernel tier's minimum; every item runs on the vmapped "
              f"BANDED schedule (interpret does not apply there)",
              file=sys.stderr)

    def run(chunkb):
        chunkb = chunkb.reshape(batch, 2, -1)
        dev = lax.axis_index(AMP_AXIS)

        def vmapped(it):
            return jax.vmap(lambda ch, it=it: _apply_plan_item(
                ch, dev, D=D, local_n=local_n, it=it))
        if parts is None or chunkb.dtype != jnp.float32:
            for it in items:
                chunkb = vmapped(it)(chunkb)
            return chunkb
        for part in parts:
            if part[0] == "kernel":
                out = part[1](chunkb.reshape(batch, 2, -1, PB.LANES),
                              part[2])
                chunkb = out.reshape(batch, 2, -1)
            else:
                chunkb = vmapped(part[1])(chunkb)
        return chunkb

    sharded = compat.shard_map(run, mesh, P(None, None, AMP_AXIS),
                               P(None, None, AMP_AXIS), check_vma=False)
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def _reject_measure_ops(ops):
    """The static sharded schedules don't thread keys/outcomes; dynamic
    circuits have their own compiler. One shared rejection for the three
    static sharded compilers."""
    if any(op.kind in ("measure", "measure_dm", "classical") for op in ops):
        from quest_tpu.validation import QuESTError
        raise QuESTError(
            "Invalid operation: this circuit contains mid-circuit "
            "measurements; use compile_circuit_sharded_measured (or "
            "Circuit.apply_sharded_measured) for dynamic circuits on the "
            "mesh.")


def compile_circuit_sharded(ops: Sequence, n: int, density: bool, mesh: Mesh,
                            donate: bool = True, lazy: bool = False):
    """Compile a gate sequence into ONE shard_map program over the mesh —
    the explicit, reference-faithful distributed schedule. Returns a jitted
    fn: sharded (2, 2^n) planes -> sharded (2, 2^n) planes.

    lazy=True first rewrites the (flattened) op list through lazy qubit
    relabeling (quest_tpu.parallel.relabel): global-target gates swap
    their qubit local and LEAVE it there, amortizing exchanges across
    depth (~2x less ICI on deep circuits; the reference swap-dances
    every gate, QuEST_cpu_distributed.c:1441-1483)."""
    D = int(mesh.devices.size)
    g = int(math.log2(D))
    local_n = n - g
    _reject_measure_ops(ops)
    if local_n < 1:
        val._err(val.ErrorCode.E_DISTRIB_QUREG_TOO_SMALL)
    if not density and any(op.kind == "superop" for op in ops):
        from quest_tpu.validation import QuESTError
        raise QuESTError(
            "Invalid operation: noise channels require a density-matrix "
            "register")
    if lazy or C.plan_enabled():
        # flatten + rewrite through the per-gate comm policy (the comm
        # planner's per-circuit choice, or the legacy lazy rewrite);
        # duals are explicit in the flattened list
        ops = tuple(pergate_flat(ops, n, density, local_n, lazy=lazy))
        density = False
    else:
        ops = tuple(ops)

    def run(chunk):
        chunk = chunk.reshape(2, -1)
        dev = lax.axis_index(AMP_AXIS)
        for op in ops:
            chunk = _apply_gateop(chunk, dev, D=D, local_n=local_n,
                                  density=density, op=op)
        return chunk

    sharded = compat.shard_map(run, mesh, P(None, AMP_AXIS),
                               P(None, AMP_AXIS))
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def _measure_op_sharded(chunk, dev, key, *, D, local_n, qubit, density,
                        eps):
    """Mid-circuit measurement inside the shard_map schedule: local
    partial probability + psum (the reference's MPI_Allreduce,
    QuEST_cpu_distributed.c:1263-1277), identical outcome draw on every
    device (same key), local branchless collapse — including GLOBAL
    qubits, where a device's whole chunk lives on one side of the
    butterfly and either renormalizes or zeroes."""
    n = local_n + int(math.log2(D))
    if density:
        # diagonal probability: rho[k,k] with bit `qubit` of k == 0.
        # col bits are the TOP half; this shard holds cols [c0, c0+cols)
        dim = 1 << (n // 2)
        cols_local = chunk.shape[1] // dim
        c0 = dev * cols_local
        mat = chunk[0].reshape(cols_local, dim)
        idx = c0 + jnp.arange(cols_local)
        diag = jnp.take_along_axis(mat, idx[:, None], axis=1)[:, 0]
        keep = ((idx >> qubit) & 1) == 0
        p0 = lax.psum(jnp.sum(jnp.where(keep, diag, 0.0)), AMP_AXIS)
    elif qubit < local_n:
        pre, post = 1 << (local_n - 1 - qubit), 1 << qubit
        re = chunk[0].reshape(pre, 2, post)[:, 0, :]
        im = chunk[1].reshape(pre, 2, post)[:, 0, :]
        p0 = lax.psum(jnp.sum(re * re + im * im), AMP_AXIS)
    else:
        mybit = (dev >> (qubit - local_n)) & 1
        local = jnp.sum(chunk * chunk)
        p0 = lax.psum(jnp.where(mybit == 0, local, 0.0), AMP_AXIS)

    key, sub = jax.random.split(key)
    u = jax.random.uniform(sub, dtype=p0.dtype)
    outcome = jnp.where(p0 < eps, 1,
                        jnp.where(1.0 - p0 < eps, 0,
                                  (u > p0).astype(jnp.int32)))
    prob = jnp.maximum(jnp.where(outcome == 0, p0, 1.0 - p0), eps)

    rdt = chunk.dtype
    if density:
        nq = n // 2
        qubits = tuple(sorted({qubit, qubit + nq}, reverse=True))
        dims, axis_of = A.seg_view(local_n, tuple(q for q in qubits
                                                  if q < local_n))
        mask = None
        for q in qubits:
            if q < local_n:
                m = A.bit_tensor(len(dims), axis_of[q]) == outcome
            else:
                m = ((dev >> (q - local_n)) & 1) == outcome
            mask = m if mask is None else mask & m
        factor = jnp.where(mask, 1.0 / prob, 0.0).astype(rdt)
        new = jnp.stack([chunk[0].reshape(dims) * factor,
                         chunk[1].reshape(dims) * factor])
        return new.reshape(2, -1), key, outcome
    if qubit < local_n:
        dims, axis_of = A.seg_view(local_n, (qubit,))
        keep = A.bit_tensor(len(dims), axis_of[qubit]) == outcome
        factor = keep.astype(rdt) * lax.rsqrt(prob).astype(rdt)
        new = jnp.stack([chunk[0].reshape(dims) * factor,
                         chunk[1].reshape(dims) * factor])
        return new.reshape(2, -1), key, outcome
    mybit = (dev >> (qubit - local_n)) & 1
    factor = jnp.where(mybit == outcome,
                       lax.rsqrt(prob), 0.0).astype(rdt)
    return chunk * factor, key, outcome


def plan_measured_program(flat: Sequence, n: int, local_n: int,
                          engine: str, relabel: bool,
                          interpret: bool = False):
    """The dynamic engine's executable plan: split the FLAT op list at
    dynamic barriers (measure/classical), run the layer-amortized
    relabel pass per measurement-free stretch (each stretch restores
    standard order, so barriers always see logical qubit positions),
    and band/kernel-plan each stretch per `engine`. Returns (program,
    resolved_engine) where program is a list of ("dyn", op) |
    ("stretch", items, parts-or-None) elements. The ONE home of this
    planning — compile_circuit_sharded_measured executes it and
    parallel.introspect reports it, so the reported schedule cannot
    drift from the executed one."""
    from quest_tpu.ops import fusion as F

    bands = None
    if engine == "fused":
        bands = fused_shard_bands(n, local_n)
        if bands is None:
            # chunk below the kernel tier: banded fallback — LOUD when
            # the caller asked for interpret-mode kernels, exactly like
            # the static fused compiler (a silent version of this
            # fallback turned a relabel test into a false positive, r4)
            if interpret:
                import sys
                print(f"[sharded] dynamic engine: local_n={local_n} "
                      f"below the kernel tier's minimum; falling back "
                      f"to the BANDED engine (interpret does not apply "
                      f"there)", file=sys.stderr)
            engine = "banded"
    if engine == "banded":
        bands = _shard_bands(n, local_n)

    program = []        # ("dyn", op) | ("stretch", items, parts|None)
    seg_cache: dict = {}

    def close_stretch(stretch):
        if not stretch:
            return
        if engine != "xla":
            # per-stretch scheduling: each measurement-free stretch is a
            # static sub-schedule, reordered/composed before its relabel
            # pass exactly like the static engines (barriers themselves
            # never move — the stretch split happens first)
            stretch = F.maybe_schedule(stretch, n)
        if relabel:
            from quest_tpu.parallel.relabel import plan_full_relabels
            stretch = plan_full_relabels(stretch, n, local_n)
        if engine == "xla":
            program.append(("stretch", stretch, None))
            return
        items = F.plan(stretch, n, bands=bands)
        parts = (_plan_fused_parts(items, local_n, interpret, seg_cache)
                 if engine == "fused" else None)
        program.append(("stretch", items, parts))

    cur: list = []
    for op in flat:
        if op.kind in ("measure", "measure_dm", "classical"):
            close_stretch(cur)
            cur = []
            program.append(("dyn", op))
        else:
            cur.append(op)
    close_stretch(cur)
    return program, engine


def resolve_measured_engine(engine, relabel, banded: bool = False):
    """The ONE home of the dynamic engine's argument defaulting —
    engine=None means 'xla' (or 'banded' via the legacy bool), relabel
    defaults on for the fusing engines. Shared by the compiler below and
    Circuit.compiled_sharded_measured's cache key so equivalent calls
    always resolve to (and cache as) the same program."""
    if engine is None:
        engine = "banded" if banded else "xla"
    if engine not in ("xla", "banded", "fused"):
        raise ValueError(f"engine must be 'xla', 'banded' or 'fused', "
                         f"got {engine!r}")
    if relabel is None:
        relabel = engine in ("banded", "fused")
    return engine, relabel


def compile_circuit_sharded_measured(ops: Sequence, n: int, density: bool,
                                     mesh: Mesh, donate: bool = True,
                                     banded: bool = False,
                                     engine: str = None,
                                     relabel: bool = None,
                                     interpret: bool = False):
    """DYNAMIC circuit over the mesh: one shard_map program taking
    (sharded planes, key) and returning (planes, outcomes) — mid-circuit
    measurement (psum'd probabilities, identical draws everywhere, local
    collapse even for device-index qubits) and classical feedback, at
    pod scale. The reference must host-round-trip AND MPI-broadcast per
    measurement, and its measurement path communicates per-gate and
    fuses nothing (QuEST_cpu_distributed.c:1244-1319); here the entire
    dynamic program is one compiled dispatch AND the measurement-free
    stretches get the full static-engine treatment:

    engine: 'xla' (per-gate), 'banded' (band-fusion between measurement
    barriers), or 'fused' (banded + Pallas mega-kernel segments for the
    purely-local runs, exactly like compile_circuit_sharded_fused; f64
    registers fall back to the banded schedule over the same plan).
    The legacy `banded` bool maps to engine='banded'.

    relabel (default ON for banded/fused): each measurement-free stretch
    is a static sub-schedule — the layer-amortized relabel pass
    (parallel/relabel.py plan_full_relabels) runs PER STRETCH, so deep
    global-qubit work between measurements rides whole-register
    all-to-all events instead of per-gate exchanges. Every stretch
    restores standard qubit order before its barrier, so measurements
    and classical feedback always see logical positions (the
    'measured qubit in standard position' contract, VERDICT r4 item 4);
    the pass only emits events where they pay for themselves, so cheap
    stretches are untouched."""
    from quest_tpu import precision as _prec
    from quest_tpu.circuit import flatten_ops
    from quest_tpu.ops import fusion as F

    engine, relabel = resolve_measured_engine(engine, relabel, banded)

    D = int(mesh.devices.size)
    g = int(math.log2(D))
    local_n = n - g
    if local_n < 1:
        val._err(val.ErrorCode.E_DISTRIB_QUREG_TOO_SMALL)
    if density and (1 << (n // 2)) < D:
        from quest_tpu.validation import QuESTError
        raise QuESTError(
            "Invalid operation: dynamic density circuits need at least "
            "one density-matrix column per device (2^numQubits >= mesh "
            "size) so each shard can read its diagonal slice; use fewer "
            "devices or the static engine + eager measurement.")
    flat = flatten_ops(ops, n, density)
    n_meas = sum(1 for op in flat
                 if op.kind in ("measure", "measure_dm"))
    if not n_meas:
        from quest_tpu.validation import QuESTError
        raise QuESTError(
            "Invalid operation: compile_circuit_sharded_measured requires "
            "at least one mid-circuit measurement; use "
            "compile_circuit_sharded instead.")

    program, engine = plan_measured_program(flat, n, local_n, engine,
                                            relabel, interpret)

    def run(chunk, key):
        chunk = chunk.reshape(2, -1)
        dev = lax.axis_index(AMP_AXIS)
        eps = jnp.asarray(_prec.real_eps(chunk.dtype), dtype=chunk.dtype)
        use_kernels = chunk.dtype == jnp.float32
        outs = []
        for el in program:
            if el[0] == "dyn":
                op = el[1]
                if op.kind in ("measure", "measure_dm"):
                    chunk, key, oc = _measure_op_sharded(
                        chunk, dev, key, D=D, local_n=local_n,
                        qubit=op.targets[0],
                        density=op.kind == "measure_dm", eps=eps)
                    outs.append(oc)
                else:                       # classical feedback
                    inners, conds = op.operand
                    pred = None
                    for idx, want in conds:
                        p = outs[idx] == want
                        pred = p if pred is None else pred & p
                    new = chunk
                    for gop in inners:
                        new = _apply_gateop(new, dev, D=D, local_n=local_n,
                                            density=False, op=gop)
                    chunk = jnp.where(pred, new, chunk)
                continue
            _, items, parts = el
            if parts is not None and use_kernels:
                from quest_tpu.ops import pallas_band as PB
                for part in parts:
                    if part[0] == "kernel":
                        out = part[1](chunk.reshape(2, -1, PB.LANES),
                                      part[2])
                        chunk = out.reshape(2, -1)
                    else:
                        chunk = _apply_plan_item(chunk, dev, D=D,
                                                 local_n=local_n,
                                                 it=part[1])
            else:
                for it in items:
                    chunk = _apply_plan_item(chunk, dev, D=D,
                                             local_n=local_n, it=it)
        return chunk, jnp.stack(outs)

    sharded = compat.shard_map(run, mesh,
                               (P(None, AMP_AXIS), P()),
                               (P(None, AMP_AXIS), P()),
                               check_vma=engine != "fused")
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def apply_circuit_sharded(q: Qureg, ops: Sequence, mesh: Mesh,
                          donate: bool = True) -> Qureg:
    """One-shot convenience wrapper around compile_circuit_sharded."""
    from quest_tpu.parallel.mesh import amp_sharding
    from quest_tpu.resilience import faults as _F
    # named fault site (docs/RESILIENCE.md): the mesh dispatch is the
    # sharded analogue of the serve engine's launch — soak runs inject
    # here to prove callers surface (not swallow) multi-device failures.
    # One module-flag read when no plan is armed.
    if _F.ACTIVE:
        _F.check("sharded.dispatch", num_qubits=q.num_qubits,
                 num_ops=len(ops))
    fn = compile_circuit_sharded(ops, q.num_state_qubits, q.is_density, mesh,
                                 donate)
    amps = jax.device_put(q.amps, amp_sharding(mesh))
    return q.replace_amps(fn(amps))
