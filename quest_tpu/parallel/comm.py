"""Communication planner for the sharded engines — the `sweep_plan` of ICI.

`hbm_sweeps` made the fused engine's HBM traffic a CPU-assertable plan
metric (docs/SWEEPS.md); this module does the same for the interconnect,
which the TPU-pod statevector work identifies as the binding resource at
pod scale (arXiv:2111.10466 — ICI collectives, not FLOPs, bound
distributed throughput). Three pieces, one discipline
(plan -> predict -> assert):

* **routing table** (`matrix_route`) — the single home of the sharded
  engines' per-op communication dispatch (diagonal / one-global-target
  pair exchange / single-qubit butterfly / swap-to-local dance), shared
  by `parallel.sharded._matrix_op` and the predictor below so the
  predicted schedule CANNOT drift from the executed one;

* **reshard coalescing** (`coalesce`) — mpiQulacs-style batched qubit
  reordering (arXiv:2203.16044): defer commuting global-qubit matrix
  work, then move ALL the qubits a stretch needs local in ONE
  `all_to_all` relabel event instead of per-gate exchanges or per-qubit
  SWAPs, choosing per stretch between the a2a and ppermute forms by
  predicted (bytes, collective-steps) cost. `choose_plan` then picks the
  cheapest of {plain, coalesce, relabel-events, lazy} per circuit and
  per engine through the SAME predictor — so the banded engine can never
  select a plan costlier than its incumbent (the lazy-relabel regression
  class, docs/DISTRIBUTED.md), by construction;

* **comm_stats** (`predict_*` / `comm_stats`) — CPU-side predicted
  exchange counts and per-device ICI payload bytes, asserted EQUAL to
  XLA's lowered StableHLO collective accounting
  (`parallel.introspect.parse_collectives`) in tests/test_comm.py and
  inside `bench.py multichip`. Pure host math: a 40q/256-device schedule
  prices on a laptop (scripts/pod_projection.py builds on it).

Knobs (quest_tpu/env.py registry, both keyed):

* `QUEST_COMM_PLAN` (default 1): enables the per-circuit plan choice in
  the sharded builders; 0 restores the legacy fixed policies (plain
  per-gate schedule, layer-amortized relabel on banded/fused).
* `QUEST_EXCHANGE_SLICES` (default 1): split each pair exchange into
  this many collective-permute slices so transfer can overlap the local
  compute that consumes it on real ICI (the collective-matmul overlap
  pattern). Structure-verifiable on the CPU mesh; NOT silicon-validated
  — A/B against QUEST_EXCHANGE_SLICES=1 on first chip run, exactly like
  MAX_SWEEP_STAGES.

Reference analogue: none. The reference's exchange schedule is implicit
in C control flow (QuEST_cpu_distributed.c:481-509) and fixed: one
full-chunk MPI_Sendrecv per global gate, swap-in/swap-out per relabel
(:1441-1483), nothing planned, predicted, or assertable.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np


# ---------------------------------------------------------------------------
# shared routing table
# ---------------------------------------------------------------------------

def dense_operand(m_pair, k: int) -> Optional[np.ndarray]:
    """The (2^k, 2^k) complex operator of a packed (re, im) operand pair,
    or None when either plane is traced (runtime operands skip structure
    specialization — the engines' existing contract)."""
    if not (isinstance(m_pair[0], np.ndarray)
            and isinstance(m_pair[1], np.ndarray)):
        return None
    dim = 1 << k
    return (np.asarray(m_pair[0]) + 1j * np.asarray(m_pair[1])).reshape(
        dim, dim)


def pair2t_blocks(sup: np.ndarray, jg: int):
    """Split a 4x4 two-target operator by the global index bit `jg` into
    same-block and cross-block 2x2s, plus the input values of the local
    bit each parity's cross-block actually reads (`need`). Shared by
    sharded._pair_exchange_2t and matrix_route, so the engine's
    half-vs-full-chunk exchange decision and the predictor's byte count
    come from one computation."""
    def sub(out_v, in_v):
        rows = [i for i in range(4) if ((i >> jg) & 1) == out_v]
        cols = [j for j in range(4) if ((j >> jg) & 1) == in_v]
        return sup[np.ix_(rows, cols)]

    same = [sub(0, 0), sub(1, 1)]
    cross = [sub(0, 1), sub(1, 0)]
    need = [sorted(set(np.nonzero(np.abs(cross[gv]) > 0)[1].tolist()))
            for gv in (0, 1)]
    return same, cross, need


def matrix_route(sup: Optional[np.ndarray], targets, controls,
                 local_n: int) -> Tuple:
    """Route of ONE matrix op through the sharded engines' distributed
    dispatch (parallel.sharded._matrix_op) — the single home of the
    decision table. Returns one of

      ("local",)                      all targets inside the chunk
      ("diagonal",)                   diagonal operand: rerouted, 0 comm
      ("pair2t", half, t, jg, gbit)   2 targets, 1 global: ONE direct
                                      pair exchange (half chunk when
                                      every cross-block reads <= 1
                                      column, else full chunk)
      ("butterfly", gbit)             single global target: full-chunk
                                      pair exchange
      ("swapdance", k)                k global targets swap-to-local and
                                      back (2k half-chunk exchanges)
    """
    glob = [t for t in targets if t >= local_n]
    if not glob:
        return ("local",)
    if sup is not None and not controls:
        if np.count_nonzero(sup - np.diag(np.diagonal(sup))) == 0:
            return ("diagonal",)
        if len(targets) == 2 and len(glob) == 1:
            jg = list(targets).index(glob[0])
            t = targets[1 - jg]
            if t < local_n:
                _, _, need = pair2t_blocks(sup, jg)
                half = all(len(nd) <= 1 for nd in need)
                return ("pair2t", half, t, jg, glob[0] - local_n)
    if len(targets) == 1:
        return ("butterfly", glob[0] - local_n)
    return ("swapdance", len(glob))


def route_gateop(op, local_n: int) -> Tuple:
    """matrix_route for a flat GateOp (flattened kinds + relabel).
    Superops must be flattened to doubled-target matrix ops first
    (circuit.flatten_ops) — every sharded builder's input already is."""
    kind = op.kind
    if kind == "relabel":
        return ("relabel",)
    if kind in ("diagonal", "parity", "allones"):
        return ("none",)
    if kind in ("measure", "measure_dm", "classical"):
        raise ValueError(
            f"comm planning applies to static circuits only (got "
            f"kind={op.kind!r}); the dynamic engine prices per stretch "
            "(introspect.sharded_measured_schedule)")
    from quest_tpu import cplx
    sup = dense_operand(cplx.pack(op.operand), len(op.targets))
    return matrix_route(sup, tuple(op.targets), tuple(op.controls), local_n)


# ---------------------------------------------------------------------------
# exchange slicing
# ---------------------------------------------------------------------------

def effective_slices(x: int) -> int:
    """Number of collective-permute slices one pair exchange of `x`
    per-plane elements splits into: QUEST_EXCHANGE_SLICES clamped to the
    block (slices must divide it; x is a power of two on every engine
    path, as is the validated knob). The ONE clamp — the engines' sliced
    ppermutes and the predictor both call it, so planned and lowered
    collective counts agree at any knob value."""
    from quest_tpu.env import knob_value
    s = min(int(knob_value("QUEST_EXCHANGE_SLICES")), int(x))
    while x % s:            # non-pow2 x cannot occur today; stay safe
        s >>= 1
    return max(s, 1)


def _route_exchanges(route: Tuple, local_n: int) -> List[Tuple[str, int]]:
    """(kind, per-device operand elements) collective list of one routed
    op: 'cp' = lax.ppermute (collective-permute), 'a2a' = lax.all_to_all.
    Elements count BOTH planes of the (2, 2^local_n) chunk, mirroring the
    lowered operand tensors parse_collectives sizes."""
    m = 1 << local_n
    tag = route[0]
    if tag in ("local", "none", "diagonal"):
        return []
    if tag == "relabel":
        return [("a2a", 2 * m)]
    if tag == "pair2t":
        x = (m // 2) if route[1] else m
        s = effective_slices(x)
        return [("cp", 2 * x // s)] * s
    if tag == "butterfly":
        s = effective_slices(m)
        return [("cp", 2 * m // s)] * s
    # swapdance: one half-chunk exchange in + one out per global target
    x = m // 2
    s = effective_slices(x)
    return [("cp", 2 * x // s)] * (2 * route[1] * s)


def gateop_exchanges(op, local_n: int) -> List[Tuple[str, int]]:
    return _route_exchanges(route_gateop(op, local_n), local_n)


def predict_exchanges_flat(flat: Sequence, local_n: int) -> List:
    """Collective schedule of a FLAT op list through the per-gate engine
    (compile_circuit_sharded executes exactly one routed op per list
    entry)."""
    out: List = []
    for op in flat:
        out += gateop_exchanges(op, local_n)
    return out


def predict_exchanges_items(items: Sequence, local_n: int) -> List:
    """Collective schedule of a fusion plan (F.plan output) through the
    banded/fused sharded engines: local BandOps and diagonal items never
    communicate; width-1 global BandOps ride the single-qubit routes
    (including the diagonal-2x2 zero-comm reroute); PassOps price as
    their underlying GateOp. The fused engine's kernel segments are
    purely local, so banded and fused share this walk."""
    from quest_tpu.ops import fusion as F
    out: List = []
    for it in items:
        if isinstance(it, F.BandOp):
            if it.ql < local_n:
                continue
            sup = (np.asarray(it.gre, dtype=np.complex128)
                   + 1j * np.asarray(it.gim))
            route = matrix_route(sup, (it.ql,),
                                 tuple(q for q, _ in it.preds), local_n)
            out += _route_exchanges(route, local_n)
            continue
        op = getattr(it, "op", it)
        out += gateop_exchanges(op, local_n)
    return out


def comm_stats(exchanges: Sequence, *, num_devices: int,
               bytes_per_real: int) -> dict:
    """The comm_stats record: counts plus per-device ICI payload bytes,
    in EXACTLY parse_collectives' accounting (collective-permutes ship
    their whole operand; an all_to_all ships (D-1)/D of it, floored on
    bytes) — the parity the tests assert."""
    cp = [e for k, e in exchanges if k == "cp"]
    a2a = [e for k, e in exchanges if k == "a2a"]
    d = num_devices
    return {
        "comm_collective_permutes": len(cp),
        "comm_all_to_alls": len(a2a),
        "comm_exchanges": len(cp) + len(a2a),
        "comm_bytes": int(sum(e * bytes_per_real for e in cp)
                          + sum((e * bytes_per_real) * (d - 1) // d
                                for e in a2a)),
    }


def _cost(exchanges: Sequence, num_devices: int) -> Tuple[float, int]:
    """(per-device element-bytes, collective steps) of an exchange list —
    the planner's bytes x steps cost scale. Fractional a2a payload (no
    byte floor): selection is dtype-free."""
    d = num_devices
    total = 0.0
    for k, e in exchanges:
        total += e * (d - 1) / d if k == "a2a" else float(e)
    return (total, len(exchanges))


# ---------------------------------------------------------------------------
# reshard coalescing
# ---------------------------------------------------------------------------

def _home_order(victims: List[int], tr) -> List[int]:
    """Assign the Belady-chosen victim SET to device bits so any victim
    whose occupant is an owed global logical (local_n + j) lands on its
    HOME bit j: alternating layers then undo each other's permutation
    exactly and the trailing restore costs zero events instead of two
    (measured 8 -> 6 all-to-alls on the deep-global testbed)."""
    g = len(victims)
    order: List[Optional[int]] = [None] * g
    rest = []
    for s in victims:
        j = tr.inv[s] - tr.local_n
        if 0 <= j < g and order[j] is None:
            order[j] = s
        else:
            rest.append(s)
    for j in range(g):
        if order[j] is None:
            order[j] = rest.pop()
    return order


def coalesce(flat: Sequence, n: int, local_n: int) -> List:
    """Rewrite a flat op list so commuting stretches of global-qubit
    matrix work run LOCALLY after one all_to_all relabel event each
    (mpiQulacs-style batched reordering): global-target matrix ops are
    DEFERRED while later ops that structurally commute with them slide
    ahead; when a non-commuting op (or the end) forces a flush, the
    whole pending batch localizes through either

      * ONE relabel event (all g device bits swap with g Belady-chosen
        local slots — (1 - 1/D) of the chunk, one collective), or
      * the engines' per-op exchanges at current positions,

    whichever predicts fewer (bytes, steps) — an isolated global gate
    keeps its single pair exchange; a rotation layer's g global qubits
    share one a2a. A trailing restore returns standard order (at most
    two events + free local swaps, parallel.relabel._PermTracker).

    Where plan_full_relabels walks strictly in program order — on a
    layer that rotates the currently-LOCAL half first it fires TWO
    events per layer (measured 12 events / 1344 B on the deep-global
    testbed) — the deferral here reaches the one-event-per-layer floor
    (6 events / 672 B, tests/test_comm.py goldens). Reordering is
    restricted to structurally-commuting ops (fusion._commutes), the
    same legality rule the gate scheduler uses."""
    from quest_tpu.ops import fusion as F
    from quest_tpu.parallel import relabel as R

    g = n - local_n
    if g == 0 or g > local_n:
        return list(flat)
    R.reject_dynamic_ops(flat, "coalesce")
    if not any(op.kind == "matrix" and any(t >= local_n for t in op.targets)
               for op in flat):
        return list(flat)

    uses = R._uses(flat, n)
    ptr = [0] * n
    out: List = []
    tr = R._PermTracker(n, local_n, out)
    pending: List = []        # (op, nondiag_logical, all_logical)

    def next_use(lq, i):
        u, p = uses[lq], ptr[lq]
        while p < len(u) and u[p] <= i:
            p += 1
        ptr[lq] = p
        return u[p] if p < len(u) else len(flat) + 1

    def route_phys(op):
        """The op's route at CURRENT physical positions."""
        if op.kind != "matrix":
            return ("none",)
        from quest_tpu import cplx
        sup = dense_operand(cplx.pack(op.operand), len(op.targets))
        return matrix_route(sup, tuple(tr.perm[t] for t in op.targets),
                            tuple(tr.perm[c] for c in op.controls),
                            local_n)

    def emit(op):
        out.append(dataclasses.replace(
            op, targets=tuple(tr.perm[t] for t in op.targets),
            controls=tuple(tr.perm[c] for c in op.controls)))

    def flush(i):
        if not pending:
            return
        ops_p = [op for op, _, _ in pending]
        pp: List = []
        paying = 0
        for op in ops_p:
            ex = _route_exchanges(route_phys(op), local_n)
            paying += bool(ex)
            pp += ex
        need_local = {t for op in ops_p for t in op.targets}
        slots = [s for s in range(local_n) if tr.inv[s] not in need_local]
        D = 1 << g
        a2a_cost = _cost([("a2a", 2 << local_n)], D)
        if (paying >= 2 and len(slots) >= g
                and len(need_local) <= local_n
                and a2a_cost < _cost(pp, D)):
            slots.sort(key=lambda s: next_use(tr.inv[s], i), reverse=True)
            tr.emit_relabel(_home_order(slots[:g], tr))
        for op in ops_p:
            emit(op)
        pending.clear()

    for i, op in enumerate(flat):
        nd = F._nondiag_qubits(op)
        al = frozenset(op.targets) | frozenset(op.controls)
        if (op.kind == "matrix"
                and route_phys(op)[0] in ("pair2t", "butterfly",
                                          "swapdance")):
            # exchange-paying ops JOIN the batch unconditionally: batch
            # members keep their relative order, so they need not
            # commute with each other — only ops that slide PAST the
            # batch do (the flush below preserves program order)
            pending.append((op, nd, al))
            continue
        if pending and not all(F._commutes(nd, al, pnd, pal)
                               for _, pnd, pal in pending):
            flush(i)
        emit(op)
    flush(len(flat))
    tr.restore()
    return out


# ---------------------------------------------------------------------------
# per-circuit, per-engine plan choice
# ---------------------------------------------------------------------------

def plan_enabled() -> bool:
    from quest_tpu.env import knob_value
    return bool(knob_value("QUEST_COMM_PLAN"))


def choose_plan(flat: Sequence, n: int, local_n: int, *,
                engine: str = "banded",
                bands: Optional[Sequence] = None) -> Tuple[List, dict]:
    """Pick the cheapest rewrite of `flat` among {plain, coalesce,
    relabel-events, lazy} by PREDICTED (bytes, steps) through the target
    engine's own pricing: the per-gate engine prices one routed op per
    list entry; the banded/fused engines price the fusion plan their run
    loop executes (F.plan over `bands`). The incumbent policy (plain for
    per-gate, layer-amortized relabel for banded/fused) wins ties, so no
    engine can select a plan costlier than what it ran before the
    planner existed — the lazy-relabel banded regression is impossible
    by construction. Returns (chosen list, info dict with the strategy
    and every candidate's predicted cost)."""
    from quest_tpu.parallel import relabel as R

    D = 1 << (n - local_n)
    cands = {"plain": list(flat)}
    if any(op.kind == "matrix" and any(t >= local_n for t in op.targets)
           for op in flat):
        cands["coalesce"] = coalesce(flat, n, local_n)
        cands["relabel"] = R.plan_full_relabels(flat, n, local_n)
        cands["lazy"] = R.lazy_relabel_ops(flat, n, local_n)

    plans: dict = {}

    def score(name, lst):
        if engine == "pergate":
            ex = predict_exchanges_flat(lst, local_n)
        else:
            from quest_tpu.ops import fusion as F
            plans[name] = F.plan(lst, n, bands=bands)
            ex = predict_exchanges_items(plans[name], local_n)
        return _cost(ex, D)

    incumbent = "plain" if engine == "pergate" else "relabel"
    if incumbent not in cands:
        incumbent = "plain"
    scores = {name: score(name, lst) for name, lst in cands.items()}
    best = incumbent
    for name in ("coalesce", "relabel", "plain", "lazy"):
        if name in scores and scores[name] < scores[best]:
            best = name
    info = {"strategy": best,
            "candidates": {k: {"elem_bytes": v[0], "exchanges": v[1]}
                           for k, v in scores.items()}}
    if best in plans:
        # the winner's fusion plan rides along so the calling engine
        # (and introspect) need not re-run F.plan on the identical
        # input — scoring already paid that O(ops x items) pass
        info["items"] = plans[best]
    return cands[best], info
