"""Communication planner for the sharded engines — the `sweep_plan` of ICI.

`hbm_sweeps` made the fused engine's HBM traffic a CPU-assertable plan
metric (docs/SWEEPS.md); this module does the same for the interconnect,
which the TPU-pod statevector work identifies as the binding resource at
pod scale (arXiv:2111.10466 — ICI collectives, not FLOPs, bound
distributed throughput). Three pieces, one discipline
(plan -> predict -> assert):

* **routing table** (`matrix_route`) — the single home of the sharded
  engines' per-op communication dispatch (diagonal / one-global-target
  pair exchange / single-qubit butterfly / swap-to-local dance), shared
  by `parallel.sharded._matrix_op` and the predictor below so the
  predicted schedule CANNOT drift from the executed one;

* **reshard coalescing** (`coalesce`) — mpiQulacs-style batched qubit
  reordering (arXiv:2203.16044): defer commuting global-qubit matrix
  work, then move ALL the qubits a stretch needs local in ONE
  `all_to_all` relabel event instead of per-gate exchanges or per-qubit
  SWAPs, choosing per stretch between the a2a and ppermute forms by
  predicted (bytes, collective-steps) cost. `choose_plan` then picks the
  cheapest of {plain, coalesce, relabel-events, lazy} per circuit and
  per engine through the SAME predictor — so the banded engine can never
  select a plan costlier than its incumbent (the lazy-relabel regression
  class, docs/DISTRIBUTED.md), by construction;

* **comm_stats** (`predict_*` / `comm_stats`) — CPU-side predicted
  exchange counts and per-device ICI payload bytes, asserted EQUAL to
  XLA's lowered StableHLO collective accounting
  (`parallel.introspect.parse_collectives`) in tests/test_comm.py and
  inside `bench.py multichip`. Pure host math: a 40q/256-device schedule
  prices on a laptop (scripts/pod_projection.py builds on it).

A fourth piece makes the pricing TOPOLOGY-AWARE (`Topology`,
docs/DISTRIBUTED.md §topology): devices group into hosts — low device
bits stay on intra-host ICI, high bits cross the data-center
interconnect — and every exchange carries the device bit it crosses, so
`comm_stats` splits predicted bytes into `comm_ici_bytes` /
`comm_dci_bytes` and the planner's cost scale weights DCI bytes at
their (slower) link weight. `choose_plan` then prefers plans that
defer, coalesce and cluster DCI-crossing work (`coalesce_clusters`,
the mpiQulacs rank-reordering idea lifted to a cost model:
arXiv:2203.16044; PennyLane-Lightning MPI measures the same
inter-vs-intra-node split dominating past one node, arXiv:2508.13615),
and relabel victims are placed hot-first on ICI device bits (the
lookahead in parallel/relabel.py).

Knobs (quest_tpu/env.py registry, all keyed):

* `QUEST_COMM_PLAN` (default 1): enables the per-circuit plan choice in
  the sharded builders; 0 restores the legacy fixed policies (plain
  per-gate schedule, layer-amortized relabel on banded/fused).
* `QUEST_COMM_TOPOLOGY` (default unset = auto from jax.devices() host
  ids): 'hosts=H[,ici=X][,dci=Y]' hierarchical link model; 0 forces the
  flat single-tier model, reproducing the pre-topology planner
  bit-for-bit (golden-gated in scripts/check_comm_golden.py).
* `QUEST_EXCHANGE_SLICES` (default 1): split each pair exchange into
  this many collective-permute slices so transfer can overlap the local
  compute that consumes it on real ICI (the collective-matmul overlap
  pattern). Structure-verifiable on the CPU mesh; NOT silicon-validated
  — A/B against QUEST_EXCHANGE_SLICES=1 on first chip run, exactly like
  MAX_SWEEP_STAGES.
* `QUEST_EXCHANGE_SLICES_DCI` (default 0 = follow the knob above):
  slice count for exchanges that cross the host boundary — slower
  links want finer slicing (scripts/ab_silicon.py carries the A/B
  leg).

Reference analogue: none. The reference's exchange schedule is implicit
in C control flow (QuEST_cpu_distributed.c:481-509) and fixed: one
full-chunk MPI_Sendrecv per global gate, swap-in/swap-out per relabel
(:1441-1483), nothing planned, predicted, or assertable.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np


# ---------------------------------------------------------------------------
# hierarchical mesh topology
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Topology:
    """Two-tier interconnect model of a 1-D amplitude mesh: `hosts`
    groups of contiguous devices (jax's device order is host-major, and
    the mesh builders keep it — parallel/mesh.py), intra-host links
    weighted `ici`, cross-host links `dci`. With contiguous grouping the
    LOW device-index bits connect chips on one host and the HIGH bits
    cross the data-center interconnect, so a pair exchange over global
    bit j is an ICI event iff j < ici_bits(D). hosts=1 is the flat
    single-tier model — every weight cancels and the planner prices
    exactly as it did before topologies existed (the bit-for-bit
    knob-off contract, scripts/check_comm_golden.py)."""
    hosts: int = 1
    ici: float = 1.0
    dci: float = 4.0

    @property
    def hierarchical(self) -> bool:
        return self.hosts > 1

    def devices_per_host(self, num_devices: int) -> int:
        # a topology naming more hosts than devices degenerates to one
        # device per host: every link crosses DCI
        return max(1, num_devices // min(self.hosts, num_devices))

    def ici_bits(self, num_devices: int) -> int:
        """Device-index bits whose pair exchanges stay intra-host."""
        return self.devices_per_host(num_devices).bit_length() - 1

    def link_of(self, gbit: Optional[int], num_devices: int) -> str:
        """'ici' or 'dci' for an exchange over device bit `gbit`
        (None = an all_to_all touching every bit: 'dci' whenever the
        topology is hierarchical — its payload crosses hosts).
        Delegates to the ONE classifier (_link below) the predictor
        also uses, so planned and lowered link classes cannot drift."""
        if not self.hierarchical:
            return "ici"
        return _link(gbit, self.ici_bits(num_devices))

    def weight(self, link: str) -> float:
        return self.dci if link == "dci" else self.ici

    def describe(self, num_devices: int) -> dict:
        return {"hosts": min(self.hosts, num_devices),
                "ici_weight": self.ici, "dci_weight": self.dci,
                "ici_device_bits": self.ici_bits(num_devices)}


FLAT = Topology(hosts=1, ici=1.0, dci=1.0)


def topology(num_devices: int) -> Topology:
    """The Topology the planner prices `num_devices` with, resolved
    from QUEST_COMM_TOPOLOGY: 0 -> flat; 'hosts=H,ici=X,dci=Y' -> that
    model (hosts clamped to the device count); unset -> auto-derived
    from jax.devices() process ids when the planned mesh spans the
    REAL devices (host planning of a hypothetical pod — plan_stats
    (devices=256) on a laptop — stays flat unless the knob says
    otherwise)."""
    from quest_tpu.env import knob_value
    raw = knob_value("QUEST_COMM_TOPOLOGY")
    if raw == 0:
        return FLAT
    if raw is None:
        try:
            import jax
            devs = jax.devices()
            if len(devs) != num_devices:
                return FLAT
            hosts = len({getattr(d, "process_index", 0) for d in devs})
        except Exception:        # no backend: pure host planning
            return FLAT
        if hosts <= 1 or num_devices % hosts:
            return FLAT
        return Topology(hosts=hosts)
    hosts, ici, dci = raw
    return Topology(hosts=min(hosts, num_devices), ici=ici, dci=dci)


# ---------------------------------------------------------------------------
# shared routing table
# ---------------------------------------------------------------------------

def dense_operand(m_pair, k: int) -> Optional[np.ndarray]:
    """The (2^k, 2^k) complex operator of a packed (re, im) operand pair,
    or None when either plane is traced (runtime operands skip structure
    specialization — the engines' existing contract)."""
    if not (isinstance(m_pair[0], np.ndarray)
            and isinstance(m_pair[1], np.ndarray)):
        return None
    dim = 1 << k
    return (np.asarray(m_pair[0]) + 1j * np.asarray(m_pair[1])).reshape(
        dim, dim)


def pair2t_blocks(sup: np.ndarray, jg: int):
    """Split a 4x4 two-target operator by the global index bit `jg` into
    same-block and cross-block 2x2s, plus the input values of the local
    bit each parity's cross-block actually reads (`need`). Shared by
    sharded._pair_exchange_2t and matrix_route, so the engine's
    half-vs-full-chunk exchange decision and the predictor's byte count
    come from one computation."""
    def sub(out_v, in_v):
        rows = [i for i in range(4) if ((i >> jg) & 1) == out_v]
        cols = [j for j in range(4) if ((j >> jg) & 1) == in_v]
        return sup[np.ix_(rows, cols)]

    same = [sub(0, 0), sub(1, 1)]
    cross = [sub(0, 1), sub(1, 0)]
    need = [sorted(set(np.nonzero(np.abs(cross[gv]) > 0)[1].tolist()))
            for gv in (0, 1)]
    return same, cross, need


def matrix_route(sup: Optional[np.ndarray], targets, controls,
                 local_n: int) -> Tuple:
    """Route of ONE matrix op through the sharded engines' distributed
    dispatch (parallel.sharded._matrix_op) — the single home of the
    decision table. Returns one of

      ("local",)                      all targets inside the chunk
      ("diagonal",)                   diagonal operand: rerouted, 0 comm
      ("pair2t", half, t, jg, gbit)   2 targets, 1 global: ONE direct
                                      pair exchange (half chunk when
                                      every cross-block reads <= 1
                                      column, else full chunk)
      ("butterfly", gbit)             single global target: full-chunk
                                      pair exchange
      ("swapdance", gbits)            global targets on device bits
                                      `gbits` swap-to-local and back
                                      (2 half-chunk exchanges each)
    """
    glob = [t for t in targets if t >= local_n]
    if not glob:
        return ("local",)
    if sup is not None and not controls:
        if np.count_nonzero(sup - np.diag(np.diagonal(sup))) == 0:
            return ("diagonal",)
        if len(targets) == 2 and len(glob) == 1:
            jg = list(targets).index(glob[0])
            t = targets[1 - jg]
            if t < local_n:
                _, _, need = pair2t_blocks(sup, jg)
                half = all(len(nd) <= 1 for nd in need)
                return ("pair2t", half, t, jg, glob[0] - local_n)
    if len(targets) == 1:
        return ("butterfly", glob[0] - local_n)
    return ("swapdance", tuple(t - local_n for t in glob))


def route_gateop(op, local_n: int) -> Tuple:
    """matrix_route for a flat GateOp (flattened kinds + relabel).
    Superops must be flattened to doubled-target matrix ops first
    (circuit.flatten_ops) — every sharded builder's input already is."""
    kind = op.kind
    if kind == "relabel":
        return ("relabel",)
    if kind in ("diagonal", "parity", "allones"):
        return ("none",)
    if kind in ("measure", "measure_dm", "classical"):
        raise ValueError(
            f"comm planning applies to static circuits only (got "
            f"kind={op.kind!r}); the dynamic engine prices per stretch "
            "(introspect.sharded_measured_schedule)")
    from quest_tpu import cplx
    sup = dense_operand(cplx.pack(op.operand), len(op.targets))
    return matrix_route(sup, tuple(op.targets), tuple(op.controls), local_n)


# ---------------------------------------------------------------------------
# exchange slicing
# ---------------------------------------------------------------------------

def effective_slices(x: int, link: str = "ici") -> int:
    """Number of collective-permute slices one pair exchange of `x`
    per-plane elements splits into: QUEST_EXCHANGE_SLICES — or, for
    exchanges crossing the host boundary (`link='dci'`),
    QUEST_EXCHANGE_SLICES_DCI when set — clamped to the block (slices
    must divide it; x is a power of two on every engine path, as are
    the validated knobs). The ONE clamp — the engines' sliced ppermutes
    and the predictor both call it, so planned and lowered collective
    counts agree at any knob value and per link class."""
    from quest_tpu.env import knob_value
    s = int(knob_value("QUEST_EXCHANGE_SLICES"))
    if link == "dci":
        sd = int(knob_value("QUEST_EXCHANGE_SLICES_DCI"))
        if sd:
            s = sd
    s = min(s, int(x))
    while x % s:            # non-pow2 x cannot occur today; stay safe
        s >>= 1
    return max(s, 1)


def _link(gbit: Optional[int], ici_bits: Optional[int]) -> str:
    """THE link classifier: exchange over device bit `gbit` when the
    low `ici_bits` device bits are intra-host (ici_bits None = flat:
    everything is ICI; gbit None = an all_to_all touching every bit).
    The predictor's slicing calls it directly and Topology.link_of
    (the engines' entry) delegates here — one implementation, so the
    planned and lowered slice counts cannot desynchronize."""
    if ici_bits is None:
        return "ici"
    if gbit is None:
        return "dci"
    return "ici" if gbit < ici_bits else "dci"


def _route_exchanges(route: Tuple, local_n: int,
                     ici_bits: Optional[int] = None
                     ) -> List[Tuple[str, int, Optional[int]]]:
    """(kind, per-device operand elements, crossed device bit)
    collective list of one routed op: 'cp' = lax.ppermute
    (collective-permute), 'a2a' = lax.all_to_all (bit None — it touches
    every device bit). Elements count BOTH planes of the
    (2, 2^local_n) chunk, mirroring the lowered operand tensors
    parse_collectives sizes. `ici_bits` (Topology.ici_bits) selects the
    per-link slice count — None prices flat, exactly the pre-topology
    schedule."""
    m = 1 << local_n
    tag = route[0]
    if tag in ("local", "none", "diagonal"):
        return []
    if tag == "relabel":
        return [("a2a", 2 * m, None)]
    if tag == "pair2t":
        x = (m // 2) if route[1] else m
        gbit = route[4]
        s = effective_slices(x, _link(gbit, ici_bits))
        return [("cp", 2 * x // s, gbit)] * s
    if tag == "butterfly":
        gbit = route[1]
        s = effective_slices(m, _link(gbit, ici_bits))
        return [("cp", 2 * m // s, gbit)] * s
    # swapdance: one half-chunk exchange in + one out per global target
    x = m // 2
    out: List = []
    for gbit in route[1]:
        s = effective_slices(x, _link(gbit, ici_bits))
        out += [("cp", 2 * x // s, gbit)] * (2 * s)
    return out


def gateop_exchanges(op, local_n: int,
                     ici_bits: Optional[int] = None) -> List:
    return _route_exchanges(route_gateop(op, local_n), local_n, ici_bits)


def predict_exchanges_flat(flat: Sequence, local_n: int,
                           ici_bits: Optional[int] = None) -> List:
    """Collective schedule of a FLAT op list through the per-gate engine
    (compile_circuit_sharded executes exactly one routed op per list
    entry)."""
    out: List = []
    for op in flat:
        out += gateop_exchanges(op, local_n, ici_bits)
    return out


def predict_exchanges_items(items: Sequence, local_n: int,
                            ici_bits: Optional[int] = None) -> List:
    """Collective schedule of a fusion plan (F.plan output) through the
    banded/fused sharded engines: local BandOps and diagonal items never
    communicate; width-1 global BandOps ride the single-qubit routes
    (including the diagonal-2x2 zero-comm reroute); PassOps price as
    their underlying GateOp. The fused engine's kernel segments are
    purely local, so banded and fused share this walk."""
    from quest_tpu.ops import fusion as F
    out: List = []
    for it in items:
        if isinstance(it, F.BandOp):
            if it.ql < local_n:
                continue
            sup = (np.asarray(it.gre, dtype=np.complex128)
                   + 1j * np.asarray(it.gim))
            route = matrix_route(sup, (it.ql,),
                                 tuple(q for q, _ in it.preds), local_n)
            out += _route_exchanges(route, local_n, ici_bits)
            continue
        op = getattr(it, "op", it)
        out += gateop_exchanges(op, local_n, ici_bits)
    return out


def comm_stats(exchanges: Sequence, *, num_devices: int,
               bytes_per_real: int, topo: Optional[Topology] = None
               ) -> dict:
    """The comm_stats record: counts plus per-device ICI payload bytes,
    in EXACTLY parse_collectives' accounting (collective-permutes ship
    their whole operand; an all_to_all ships (D-1)/D of it, floored on
    bytes) — the parity the tests assert. Under a hierarchical `topo`
    the bytes additionally split into `comm_ici_bytes` /
    `comm_dci_bytes` (pair exchanges classify by the device bit they
    cross; an all_to_all ships (dph-1)/D of its operand to same-host
    partners and (D-dph)/D across hosts), with ici + dci == comm_bytes
    EXACTLY (the DCI share floors, ICI takes the remainder) so the
    lowered-HLO parity stays a total-byte equality."""
    topo = topo if topo is not None else FLAT
    d = num_devices
    dph = topo.devices_per_host(d)
    ib = topo.ici_bits(d)
    total = 0
    dci = 0
    cp_n = a2a_n = dci_n = 0
    for k, e, gbit in exchanges:
        b = e * bytes_per_real
        if k == "a2a":
            a2a_n += 1
            total += b * (d - 1) // d
            share = b * (d - dph) // d
            if share:
                dci += share
                dci_n += 1
        else:
            cp_n += 1
            total += b
            if _link(gbit, ib) == "dci" and topo.hierarchical:
                dci += b
                dci_n += 1
    return {
        "comm_collective_permutes": cp_n,
        "comm_all_to_alls": a2a_n,
        "comm_exchanges": cp_n + a2a_n,
        "comm_bytes": int(total),
        "comm_ici_bytes": int(total - dci),
        "comm_dci_bytes": int(dci),
        "comm_dci_exchanges": dci_n,
    }


def _cost(exchanges: Sequence, num_devices: int,
          topo: Optional[Topology] = None) -> Tuple[float, int]:
    """(per-device weighted element-bytes, collective steps) of an
    exchange list — the planner's bytes x steps cost scale. Fractional
    a2a payload (no byte floor): selection is dtype-free. Under a
    hierarchical `topo` each exchange's elements are weighted by its
    link class (an all_to_all splits (dph-1)/D intra-host vs (D-dph)/D
    across hosts), so DCI-crossing work prices at its real relative
    cost; the flat default weights everything 1 and reproduces the
    pre-topology selection exactly."""
    topo = topo if topo is not None else FLAT
    d = num_devices
    dph = topo.devices_per_host(d)
    ib = topo.ici_bits(d)
    w_i, w_d = topo.ici, topo.dci
    total = 0.0
    for k, e, gbit in exchanges:
        if k == "a2a":
            total += e * ((dph - 1) / d * w_i + (d - dph) / d * w_d)
        else:
            total += e * (w_d if (topo.hierarchical
                                  and _link(gbit, ib) == "dci") else w_i)
    return (total, len(exchanges))


# ---------------------------------------------------------------------------
# reshard coalescing
# ---------------------------------------------------------------------------

def _home_order(victims: List[int], tr,
                hot_key=None) -> List[int]:
    """Assign the Belady-chosen victim SET to device bits so any victim
    whose occupant is an owed global logical (local_n + j) lands on its
    HOME bit j: alternating layers then undo each other's permutation
    exactly and the trailing restore costs zero events instead of two
    (measured 8 -> 6 all-to-alls on the deep-global testbed).

    `hot_key` (hierarchical topologies only) orders the NON-home
    victims by their occupant's next use, soonest first, onto the
    lowest free device bits — intra-host ICI under the contiguous host
    grouping — so the qubits the upcoming window touches most stay a
    cheap exchange away while cold qubits absorb the DCI bits (the
    hot-qubit victim rule, docs/DISTRIBUTED.md §topology). None keeps
    the flat planner's original fill order bit-for-bit."""
    g = len(victims)
    order: List[Optional[int]] = [None] * g
    rest = []
    for s in victims:
        j = tr.inv[s] - tr.local_n
        if 0 <= j < g and order[j] is None:
            order[j] = s
        else:
            rest.append(s)
    if hot_key is None:
        for j in range(g):
            if order[j] is None:
                order[j] = rest.pop()
    else:
        rest.sort(key=hot_key)          # soonest next use first
        for j in range(g):              # ascending bit = ICI first
            if order[j] is None:
                order[j] = rest.pop(0)
    return order


def coalesce(flat: Sequence, n: int, local_n: int,
             topo: Optional[Topology] = None) -> List:
    """Rewrite a flat op list so commuting stretches of global-qubit
    matrix work run LOCALLY after one all_to_all relabel event each
    (mpiQulacs-style batched reordering): global-target matrix ops are
    DEFERRED while later ops that structurally commute with them slide
    ahead; when a non-commuting op (or the end) forces a flush, the
    whole pending batch localizes through either

      * ONE relabel event (all g device bits swap with g Belady-chosen
        local slots — (1 - 1/D) of the chunk, one collective), or
      * the engines' per-op exchanges at current positions,

    whichever predicts fewer (bytes, steps) — an isolated global gate
    keeps its single pair exchange; a rotation layer's g global qubits
    share one a2a. A trailing restore returns standard order (at most
    two events + free local swaps, parallel.relabel._PermTracker).

    Where plan_full_relabels walks strictly in program order — on a
    layer that rotates the currently-LOCAL half first it fires TWO
    events per layer (measured 12 events / 1344 B on the deep-global
    testbed) — the deferral here reaches the one-event-per-layer floor
    (6 events / 672 B, tests/test_comm.py goldens). Reordering is
    restricted to structurally-commuting ops (fusion._commutes), the
    same legality rule the gate scheduler uses.

    `topo` (default flat) weights the flush's a2a-vs-per-op decision by
    link class and orders event victims hot-first onto ICI device bits;
    the flat default reproduces the pre-topology rewrite bit-for-bit."""
    from quest_tpu.ops import fusion as F
    from quest_tpu.parallel import relabel as R

    topo = topo if topo is not None else FLAT
    g = n - local_n
    ici_b = topo.ici_bits(1 << g) if topo.hierarchical else None
    if g == 0 or g > local_n:
        return list(flat)
    R.reject_dynamic_ops(flat, "coalesce")
    if not any(op.kind == "matrix" and any(t >= local_n for t in op.targets)
               for op in flat):
        return list(flat)

    uses = R._uses(flat, n)
    ptr = [0] * n
    out: List = []
    tr = R._PermTracker(n, local_n, out)
    pending: List = []        # (op, nondiag_logical, all_logical)

    def next_use(lq, i):
        u, p = uses[lq], ptr[lq]
        while p < len(u) and u[p] <= i:
            p += 1
        ptr[lq] = p
        return u[p] if p < len(u) else len(flat) + 1

    def route_phys(op):
        """The op's route at CURRENT physical positions."""
        if op.kind != "matrix":
            return ("none",)
        from quest_tpu import cplx
        sup = dense_operand(cplx.pack(op.operand), len(op.targets))
        return matrix_route(sup, tuple(tr.perm[t] for t in op.targets),
                            tuple(tr.perm[c] for c in op.controls),
                            local_n)

    def emit(op):
        out.append(dataclasses.replace(
            op, targets=tuple(tr.perm[t] for t in op.targets),
            controls=tuple(tr.perm[c] for c in op.controls)))

    def flush(i):
        if not pending:
            return
        ops_p = [op for op, _, _ in pending]
        pp: List = []
        paying = 0
        for op in ops_p:
            ex = _route_exchanges(route_phys(op), local_n, ici_b)
            paying += bool(ex)
            pp += ex
        need_local = {t for op in ops_p for t in op.targets}
        slots = [s for s in range(local_n) if tr.inv[s] not in need_local]
        D = 1 << g
        a2a_cost = _cost([("a2a", 2 << local_n, None)], D, topo)
        if (paying >= 2 and len(slots) >= g
                and len(need_local) <= local_n
                and a2a_cost < _cost(pp, D, topo)):
            slots.sort(key=lambda s: next_use(tr.inv[s], i), reverse=True)
            hot = ((lambda s: next_use(tr.inv[s], i))
                   if topo.hierarchical else None)
            tr.emit_relabel(_home_order(slots[:g], tr, hot_key=hot))
        for op in ops_p:
            emit(op)
        pending.clear()

    for i, op in enumerate(flat):
        nd = F._nondiag_qubits(op)
        al = frozenset(op.targets) | frozenset(op.controls)
        if (op.kind == "matrix"
                and route_phys(op)[0] in ("pair2t", "butterfly",
                                          "swapdance")):
            # exchange-paying ops JOIN the batch unconditionally: batch
            # members keep their relative order, so they need not
            # commute with each other — only ops that slide PAST the
            # batch do (the flush below preserves program order)
            pending.append((op, nd, al))
            continue
        if pending and not all(F._commutes(nd, al, pnd, pal)
                               for _, pnd, pal in pending):
            flush(i)
        emit(op)
    flush(len(flat))
    tr.restore()
    return out


# ---------------------------------------------------------------------------
# hot-qubit cluster coalescing (hierarchical topologies)
# ---------------------------------------------------------------------------


def _price_ops(ops, local_n: int, ici_b, D: int, topo: Topology):
    """Weighted cost of already-rewritten ops (PHYSICAL positions):
    relabel events price as their a2a, matrix ops through the shared
    route table — the scale the restore choice below compares on."""
    from quest_tpu import cplx
    ex: List = []
    for op in ops:
        if op.kind == "relabel":
            ex += [("a2a", 2 << local_n, None)]
        elif op.kind == "matrix":
            sup = dense_operand(cplx.pack(op.operand), len(op.targets))
            ex += _route_exchanges(
                matrix_route(sup, tuple(op.targets), tuple(op.controls),
                             local_n), local_n, ici_b)
    return _cost(ex, D, topo)


def _weighted_restore(tr, local_n: int, ici_b, D: int,
                      topo: Topology) -> None:
    """Restore standard order through whichever of the two mechanisms
    predicts cheaper under the topology weights: the event-based
    _PermTracker.restore (at most two a2as + free local swaps — each
    a2a crosses DCI) or a per-qubit SWAP walk (half-chunk exchanges,
    each priced at ITS OWN device bit's link class — often entirely ICI
    when only intra-host bits are misplaced). The flat planner never
    calls this; its restore stays the event form bit-for-bit."""
    from quest_tpu.parallel import relabel as R

    def sim(strategy):
        sink: List = []
        c = R._PermTracker(tr.n, local_n, sink)
        c.perm[:] = tr.perm
        c.inv[:] = tr.inv
        strategy(c)
        return sink

    def swap_walk(c):
        for q in range(c.n):
            while c.perm[q] != q:
                a, b = c.perm[q], q
                if a >= local_n and b >= local_n:
                    # global-global: conjugate through local slot 0
                    # (lazy_relabel_ops' restore idiom)
                    c.emit_swap(a, 0)
                    c.emit_swap(b, 0)
                    c.emit_swap(a, 0)
                else:
                    c.emit_swap(a, b)

    events = sim(lambda c: c.restore())
    swaps = sim(swap_walk)
    chosen = events
    if _price_ops(swaps, local_n, ici_b, D, topo) \
            < _price_ops(events, local_n, ici_b, D, topo):
        chosen = swaps
    for op in chosen:
        if op.kind == "relabel":
            tr.emit_relabel(op.operand)
        else:
            tr.emit_swap(op.targets[0], op.targets[1])


def coalesce_clusters(flat: Sequence, n: int, local_n: int,
                      topo: Topology) -> List:
    """Hot-qubit lookahead rewrite for HIERARCHICAL topologies: defer
    exchange-paying work per qubit CLUSTER (connected components of the
    op stream's qubit-sharing graph, grown op by op) instead of per
    commuting stretch, so all the work one cluster of qubits will ever
    do localizes behind a single exchange for that cluster — a
    DCI-crossing qubit pays its hop ONCE for its whole gate chain
    instead of once per layer.

    Where `coalesce` must flush its whole pending batch the moment ANY
    later op fails to commute with it — on the deep-global testbed
    every layer's trailing entangler does, so every layer pays one
    all_to_all whose (D-dph)/D payload crosses DCI — clusters are
    support-disjoint by construction, so a conflicting op simply JOINS
    its cluster and disjoint clusters keep deferring past it
    (disjoint-support ops always structurally commute, the same
    fusion._commutes legality rule). Each cluster flushes at most once
    (when its qubit set outgrows the chunk, or at the end of the
    stream), localizing through the cheapest of {per-op exchanges, one
    a2a relabel event with hot-ordered victims, one half-chunk SWAP per
    global qubit priced at its own link class} under the topology
    weights; the trailing restore picks event-vs-swap form the same
    way. Measured on the deep-global hosts=2 testbed: 6 DCI-crossing
    a2as (384 B DCI) -> the cluster plan's <= 2 DCI events
    (tests/test_topology.py pins the exact counts;
    scripts/check_comm_golden.py gates the >= 2x byte ceiling).

    Only `choose_plan` calls this, and only under a hierarchical
    topology — the weighted rescoring there is the final arbiter, so a
    cluster plan ships only when the exact cost model prefers it."""
    from quest_tpu import cplx
    from quest_tpu.ops import fusion as F
    from quest_tpu.parallel import relabel as R

    g = n - local_n
    if g == 0 or g > local_n:
        return list(flat)
    R.reject_dynamic_ops(flat, "coalesce_clusters")
    if not any(op.kind == "matrix" and any(t >= local_n for t in op.targets)
               for op in flat):
        return list(flat)

    D = 1 << g
    ici_b = topo.ici_bits(D)
    uses = R._uses(flat, n)
    ptr = [0] * n
    out: List = []
    tr = R._PermTracker(n, local_n, out)
    clusters: List[dict] = []     # {"qubits": set, "ops": [(op, nd, al)]}

    def next_use(lq, i):
        u, p = uses[lq], ptr[lq]
        while p < len(u) and u[p] <= i:
            p += 1
        ptr[lq] = p
        return u[p] if p < len(u) else len(flat) + 1

    def route_phys(op):
        if op.kind != "matrix":
            return ("none",)
        sup = dense_operand(cplx.pack(op.operand), len(op.targets))
        return matrix_route(sup, tuple(tr.perm[t] for t in op.targets),
                            tuple(tr.perm[c] for c in op.controls),
                            local_n)

    def emit(op):
        out.append(dataclasses.replace(
            op, targets=tuple(tr.perm[t] for t in op.targets),
            controls=tuple(tr.perm[c] for c in op.controls)))

    def flush_cluster(cl, i):
        """Localize one cluster's needed qubits through the cheapest
        weighted mechanism, then emit its ops in arrival order."""
        ops_c = [op for op, _, _ in cl["ops"]]
        need_local = {t for op in ops_c if op.kind == "matrix"
                      for t in op.targets}
        glob_need = sorted(q for q in need_local
                           if tr.perm[q] >= local_n)
        # option A: per-op exchanges at current positions (always legal)
        pp: List = []
        for op in ops_c:
            pp += _route_exchanges(route_phys(op), local_n, ici_b)
        best_cost = _cost(pp, D, topo)
        mechanism = "plain"
        free = [s for s in range(local_n) if tr.inv[s] not in need_local]
        if glob_need and len(need_local) <= local_n:
            if len(free) >= g:
                a2a_cost = _cost([("a2a", 2 << local_n, None)], D, topo)
                if a2a_cost < best_cost:
                    best_cost, mechanism = a2a_cost, "event"
            if len(free) >= len(glob_need):
                sw: List = []
                for q in glob_need:
                    gbit = tr.perm[q] - local_n
                    s = effective_slices(1 << (local_n - 1),
                                         _link(gbit, ici_b))
                    sw += [("cp", (1 << local_n) // s, gbit)] * s
                sw_cost = _cost(sw, D, topo)
                if sw_cost < best_cost:
                    best_cost, mechanism = sw_cost, "swaps"
        if mechanism == "event":
            free.sort(key=lambda s: next_use(tr.inv[s], i), reverse=True)
            tr.emit_relabel(_home_order(
                free[:g], tr, hot_key=lambda s: next_use(tr.inv[s], i)))
        elif mechanism == "swaps":
            for q in glob_need:
                free.sort(key=lambda s: next_use(tr.inv[s], i),
                          reverse=True)
                victim = free.pop(0)
                tr.emit_swap(tr.perm[q], victim)
        for op in ops_c:
            emit(op)

    for i, op in enumerate(flat):
        nd = F._nondiag_qubits(op)
        al = frozenset(op.targets) | frozenset(op.controls)
        hit = [c for c in clusters if c["qubits"] & al]
        pays = (op.kind == "matrix"
                and route_phys(op)[0] in ("pair2t", "butterfly",
                                          "swapdance"))
        if not hit:
            if pays:
                clusters.append({"qubits": set(al), "ops": [(op, nd, al)]})
            else:
                # support-disjoint from every pending cluster: commutes
                # with all deferred work, safe to slide ahead
                emit(op)
            continue
        commutes = all(F._commutes(nd, al, pnd, pal)
                       for c in hit for _, pnd, pal in c["ops"])
        if commutes and not pays:
            emit(op)
            continue
        # join: merge every intersected cluster (their op sets are
        # mutually support-disjoint up to now, so concatenating in
        # cluster-creation order is a legal interleaving), then append
        merged = hit[0]
        for c in hit[1:]:
            merged["qubits"] |= c["qubits"]
            merged["ops"] += c["ops"]
            clusters.remove(c)
        merged["qubits"] |= al
        merged["ops"].append((op, nd, al))
        need = {t for o, _, _ in merged["ops"] if o.kind == "matrix"
                for t in o.targets}
        if len(need) > local_n:
            # the cluster outgrew the chunk: no single localization can
            # host it — flush now (per-op exchanges remain legal)
            flush_cluster(merged, i)
            clusters.remove(merged)
    for cl in clusters:
        flush_cluster(cl, len(flat))
    _weighted_restore(tr, local_n, ici_b, D, topo)
    return out


# ---------------------------------------------------------------------------
# per-circuit, per-engine plan choice
# ---------------------------------------------------------------------------

def plan_enabled() -> bool:
    from quest_tpu.env import knob_value
    return bool(knob_value("QUEST_COMM_PLAN"))


def choose_plan(flat: Sequence, n: int, local_n: int, *,
                engine: str = "banded",
                bands: Optional[Sequence] = None,
                topo: Optional[Topology] = None) -> Tuple[List, dict]:
    """Pick the cheapest rewrite of `flat` among {plain, coalesce,
    relabel-events, lazy — plus hot-qubit clustering under a
    hierarchical topology} by PREDICTED weighted (bytes, steps) through
    the target engine's own pricing: the per-gate engine prices one
    routed op per list entry; the banded/fused engines price the fusion
    plan their run loop executes (F.plan over `bands`). The incumbent
    policy (plain for per-gate, layer-amortized relabel for
    banded/fused) wins ties, so no engine can select a plan costlier
    than what it ran before the planner existed — the lazy-relabel
    banded regression is impossible by construction. `topo` defaults to
    topology(D) (the QUEST_COMM_TOPOLOGY resolution); the flat model
    weights every link 1 and selects exactly the pre-topology plans.
    Returns (chosen list, info dict with the strategy, every
    candidate's predicted cost, and the topology priced under)."""
    from quest_tpu.parallel import relabel as R

    D = 1 << (n - local_n)
    if topo is None:
        topo = topology(D)
    ici_b = topo.ici_bits(D) if topo.hierarchical else None
    cands = {"plain": list(flat)}
    if any(op.kind == "matrix" and any(t >= local_n for t in op.targets)
           for op in flat):
        cands["coalesce"] = coalesce(flat, n, local_n, topo=topo)
        cands["relabel"] = R.plan_full_relabels(flat, n, local_n,
                                                topo=topo)
        cands["lazy"] = R.lazy_relabel_ops(flat, n, local_n)
        if topo.hierarchical:
            cands["hier"] = coalesce_clusters(flat, n, local_n, topo)

    plans: dict = {}

    def score(name, lst):
        if engine == "pergate":
            ex = predict_exchanges_flat(lst, local_n, ici_b)
        else:
            from quest_tpu.ops import fusion as F
            plans[name] = F.plan(lst, n, bands=bands)
            ex = predict_exchanges_items(plans[name], local_n, ici_b)
        return _cost(ex, D, topo)

    incumbent = "plain" if engine == "pergate" else "relabel"
    if incumbent not in cands:
        incumbent = "plain"
    scores = {name: score(name, lst) for name, lst in cands.items()}
    best = incumbent
    for name in ("hier", "coalesce", "relabel", "plain", "lazy"):
        if name in scores and scores[name] < scores[best]:
            best = name
    info = {"strategy": best,
            "candidates": {k: {"elem_bytes": v[0], "exchanges": v[1]}
                           for k, v in scores.items()},
            "topology": topo.describe(D)}
    if best in plans:
        # the winner's fusion plan rides along so the calling engine
        # (and introspect) need not re-run F.plan on the identical
        # input — scoring already paid that O(ops x items) pass
        info["items"] = plans[best]
    return cands[best], info
