"""Distribution subsystem: amplitude sharding over a device mesh.

The reference distributes the 2^N-amplitude array as equal contiguous chunks
per MPI rank (QuEST/src/CPU/QuEST_cpu_distributed.c) with three mechanisms:
pair-rank full-chunk exchange for high-qubit gates (exchangeStateVectors,
:481-509), SWAP-relabeling of high target qubits into the local range for
multi-target gates (:1441-1483), and MPI_Allreduce for reductions.

Here the same distribution strategy is expressed TPU-natively:
  - the amplitude array is sharded over a 1-D `jax.sharding.Mesh`; the top
    log2(D) qubits are the "global" (device-index) qubits — identical chunk
    layout to the reference;
  - pair exchange is `lax.ppermute` over the mesh axis (ICI neighbours when
    the hot qubit maps to the innermost mesh dimension);
  - swap-relabeling is a half-chunk ppermute (cheaper than the reference's
    full-chunk exchange);
  - reductions are `lax.psum` (inserted explicitly in the shard_map engine,
    or automatically by GSPMD for the eager path).

Two execution paths, mirroring the reference's local/distributed split:
  - GSPMD (automatic): every eager op in quest_tpu.ops runs unchanged on
    sharded arrays; XLA partitions and inserts collectives.
  - Explicit (quest_tpu.parallel.sharded): a whole Circuit runs inside ONE
    shard_map with hand-placed ppermutes — the reference-faithful
    communication-avoiding schedule, used by the benchmark path.
"""

from quest_tpu.parallel.mesh import make_amp_mesh, amp_sharding, shard_qureg
from quest_tpu.parallel.sharded import apply_circuit_sharded
from quest_tpu.parallel.introspect import sharded_schedule

__all__ = [
    "make_amp_mesh",
    "amp_sharding",
    "shard_qureg",
    "apply_circuit_sharded",
    "sharded_schedule",
]
