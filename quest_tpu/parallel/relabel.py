"""Lazy qubit relabeling: amortize shard-boundary exchanges across depth.

The reference localizes a global-qubit gate by swapping the qubit into
the chunk, applying, and swapping straight back
(QuEST_cpu_distributed.c:1441-1483) — two exchanges per gate, every
time. For deep circuits that is the dominant ICI traffic: an RCS layer
touches every global qubit every layer.

This pass rewrites a flat op list so that matrix ops target local
positions whenever a free slot exists (ops whose targets+controls
exhaust the chunk keep their global targets and engine-swap-dance as
before): each global target is swapped into a local slot by an
EXPLICIT 2q SWAP op and LEFT there (the logical->physical permutation is
tracked and all later ops' qubits are remapped through it); a restore
sequence at the end returns the register to standard order. Swap
victims are chosen Belady-style — evict the local slot whose logical
occupant is used farthest in the future — so hot qubits stay local.
Diagonal/parity/all-ones ops never communicate at any position and
simply follow the permutation.

Net effect on a depth-d circuit rotating all g global qubits per layer:
2*g*d half-chunk-pair exchanges (swap-to-local, in+out) collapse to
g*d single HALF-chunk exchanges (each inserted SWAP has one-column
cross-blocks, so the engines' _pair_exchange_2t ships half a chunk) +
O(g) restore swaps. Measured via XLA collective accounting
(tests/test_lazy_relabel.py, 8-device mesh, deep-global testbed):
PER-GATE engine 2304 -> 896 bytes (2.6x). The BANDED engine measured
1152 -> 1856 on the same testbed — its run composition already
amortizes global exchanges to ~one per qubit per layer and the inserted
SWAPs break band runs apart — so lazy stays opt-in there. The idea
follows mpiQulacs' qubit-reordering (arXiv:2203.16044), recast as a
pure op-list rewrite so every sharded engine consumes it unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence

import numpy as np

SWAP = np.array([[1, 0, 0, 0],
                 [0, 0, 1, 0],
                 [0, 1, 0, 0],
                 [0, 0, 0, 1]], dtype=np.complex128)


def _uses(flat, n):
    """Per logical qubit, the sorted indices of ops where it is a MATRIX
    TARGET — the only role that demands a local slot (controls are free
    predicates at any position; diagonal/parity/all-ones ops never
    communicate). Scoring anything else would evict hot targets to keep
    qubits that never need locality."""
    uses = [[] for _ in range(n)]
    for i, op in enumerate(flat):
        if op.kind == "matrix":
            for q in op.targets:
                uses[q].append(i)
    return uses


def lazy_relabel_ops(flat: Sequence, n: int, local_n: int) -> List:
    """Rewrite `flat` (GateOps with kinds matrix/diagonal/parity/allones)
    into an equivalent list in which matrix ops target local positions
    whenever a free slot exists (slot-exhausted ops keep their global
    targets and engine-swap-dance as before). Returns the new list;
    raises nothing new."""
    any_global_matrix = any(
        op.kind == "matrix" and any(t >= local_n for t in op.targets)
        for op in flat)
    if not any_global_matrix:
        return list(flat)

    uses = _uses(flat, n)
    ptr = [0] * n                  # per-qubit cursor into its use list
    perm = list(range(n))          # logical -> physical
    inv = list(range(n))           # physical -> logical
    out: List = []

    def next_use(lq, i):
        u = uses[lq]
        p = ptr[lq]
        while p < len(u) and u[p] <= i:
            p += 1
        ptr[lq] = p
        return u[p] if p < len(u) else len(flat) + 1

    def emit_swap(a: int, b: int):
        """Physical swap of positions a, b as an explicit 2q SWAP op."""
        from quest_tpu.circuit import GateOp
        out.append(GateOp(kind="matrix", targets=(a, b), operand=SWAP))
        la, lb = inv[a], inv[b]
        perm[la], perm[lb] = b, a
        inv[a], inv[b] = lb, la

    def localize(G: int, busy, i) -> int:
        """Swap physical-global position G into the best local slot."""
        best, best_score = None, -1
        for slot in range(local_n):
            if slot in busy:
                continue
            score = next_use(inv[slot], i)
            if score > best_score:
                best, best_score = slot, score
        if best is None:
            return G  # no free slot: leave global, engine swap-dances it
        emit_swap(G, best)
        return best

    for i, op in enumerate(flat):
        t_phys = [perm[t] for t in op.targets]
        c_phys = [perm[c] for c in op.controls]
        if op.kind == "matrix":
            busy = set(t_phys) | set(c_phys)
            for j, t in enumerate(t_phys):
                if t >= local_n:
                    new = localize(t, busy, i)
                    busy.add(new)
                    t_phys[j] = new
                    # controls keep their positions (global controls are
                    # free predicates); only the swapped target moved
        out.append(dataclasses.replace(
            op, targets=tuple(t_phys), controls=tuple(c_phys)))

    # restore standard order: logical q back to physical q
    for q in range(n):
        while perm[q] != q:
            a, b = perm[q], q
            if a >= local_n and b >= local_n:
                # global-global: route through local slot 0 (the 3-swap
                # conjugation leaves slot 0's occupant in place)
                emit_swap(a, 0)
                emit_swap(b, 0)
                emit_swap(a, 0)
            else:
                emit_swap(a, b)
    return out
