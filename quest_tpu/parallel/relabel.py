"""Lazy qubit relabeling: amortize shard-boundary exchanges across depth.

The reference localizes a global-qubit gate by swapping the qubit into
the chunk, applying, and swapping straight back
(QuEST_cpu_distributed.c:1441-1483) — two exchanges per gate, every
time. For deep circuits that is the dominant ICI traffic: an RCS layer
touches every global qubit every layer.

This pass rewrites a flat op list so that matrix ops target local
positions whenever a free slot exists (ops whose targets+controls
exhaust the chunk keep their global targets and engine-swap-dance as
before): each global target is swapped into a local slot by an
EXPLICIT 2q SWAP op and LEFT there (the logical->physical permutation is
tracked and all later ops' qubits are remapped through it); a restore
sequence at the end returns the register to standard order. Swap
victims are chosen Belady-style — evict the local slot whose logical
occupant is used farthest in the future — so hot qubits stay local.
Diagonal/parity/all-ones ops never communicate at any position and
simply follow the permutation.

Net effect on a depth-d circuit rotating all g global qubits per layer:
2*g*d half-chunk-pair exchanges (swap-to-local, in+out) collapse to
g*d single HALF-chunk exchanges (each inserted SWAP has one-column
cross-blocks, so the engines' _pair_exchange_2t ships half a chunk) +
O(g) restore swaps. Measured via XLA collective accounting
(tests/test_lazy_relabel.py, 8-device mesh, deep-global testbed):
PER-GATE engine 2304 -> 896 bytes (2.6x). The BANDED engine measured
1152 -> 1856 on the same testbed — its run composition already
amortizes global exchanges to ~one per qubit per layer and the inserted
SWAPs break band runs apart — so lazy stays opt-in there. The idea
follows mpiQulacs' qubit-reordering (arXiv:2203.16044), recast as a
pure op-list rewrite so every sharded engine consumes it unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence

import numpy as np

SWAP = np.array([[1, 0, 0, 0],
                 [0, 0, 1, 0],
                 [0, 1, 0, 0],
                 [0, 0, 0, 1]], dtype=np.complex128)

# meta tag on every SWAP the relabel passes themselves insert: marks the
# op as layout movement (excluded from the elastic boundary map's
# canonical op count), distinguishing it from a user-authored SWAP
# unitary that merely shares the matrix value
INSERTED_META = ("relabel", "inserted-swap")


def reject_dynamic_ops(flat: Sequence, pass_name: str) -> None:
    """Dynamic-circuit ops carry NESTED gate lists in their operands that
    the relabel/comm rewrites do not remap — the sharded builders that
    call these passes reject measure ops up front (_reject_measure_ops);
    this guard keeps a future caller from silently corrupting a dynamic
    circuit. Shared by plan_full_relabels and comm.coalesce."""
    for op in flat:
        if op.kind in ("measure", "measure_dm", "classical"):
            raise ValueError(
                f"{pass_name} cannot rewrite dynamic-circuit ops (got "
                f"kind={op.kind!r}); relabeling applies to static "
                "circuits only")


class _PermTracker:
    """Logical->physical permutation bookkeeping for the rewrite passes
    that move qubits (plan_full_relabels, comm.coalesce): emits relabel
    events / explicit SWAPs into `out` while keeping perm (logical ->
    physical) and inv (physical -> logical) consistent, and restores
    standard order at the end in at most two events + free local swaps.
    The ONE home of this bookkeeping — a drifted copy here and in the
    comm planner would break the restore invariant silently."""

    def __init__(self, n: int, local_n: int, out: List):
        self.n, self.local_n, self.out = n, local_n, out
        self.g = n - local_n
        self.perm = list(range(n))
        self.inv = list(range(n))

    def emit_relabel(self, slots) -> None:
        """slots[j] is the local slot swapping with device bit j."""
        from quest_tpu.circuit import GateOp
        self.out.append(GateOp(kind="relabel",
                               targets=tuple(range(self.n)),
                               operand=tuple(slots)))
        for j, s in enumerate(slots):
            gpos = self.local_n + j
            ls, lg = self.inv[s], self.inv[gpos]
            self.perm[ls], self.perm[lg] = gpos, s
            self.inv[s], self.inv[gpos] = lg, ls

    def emit_swap(self, a: int, b: int) -> None:
        """Physical 2q SWAP of positions a, b. The meta marker tags the
        op as PASS-INSERTED layout movement (vs a user-authored SWAP
        unitary): the durable executor's elastic boundary map classifies
        flat ops through it (docs/RESILIENCE.md §elastic); replay_perm
        keeps its value-match so pre-marker op lists replay unchanged."""
        from quest_tpu.circuit import GateOp
        self.out.append(GateOp(kind="matrix", targets=(a, b), operand=SWAP,
                               meta=INSERTED_META))
        la, lb = self.inv[a], self.inv[b]
        self.perm[la], self.perm[lb] = b, a
        self.inv[a], self.inv[b] = lb, la

    def restore(self) -> None:
        """Restore standard order in at most two events + free swaps:
        (1) if the device bits need fixing and any owed logical
        (local_n+j) sits at SOME device bit, one event pulls ALL
        device-bit occupants into local slots — slots chosen so no owed
        logical gets evicted back out; (2) one event sends each owed
        logical to its own device bit; (3) the remaining mismatches are
        local-local, communication-free in-chunk 2q swaps. A purely
        local-local residual (device bits already home) emits ZERO
        events — only free swaps."""
        perm, inv, local_n, g = self.perm, self.inv, self.local_n, self.g
        if perm == list(range(self.n)):
            return
        needs_fix = any(inv[local_n + j] != local_n + j for j in range(g))
        owed_at_device = any(perm[local_n + j] >= local_n
                             for j in range(g))
        safe = [s for s in range(local_n) if inv[s] < local_n]
        if needs_fix and owed_at_device and len(safe) < g:
            # tiny chunk: not enough safe slots for the two-step
            # restore; fall back to plain swaps (the engine swap-dances
            # the global ones, global-global pairs route through local
            # slot 0 like lazy_relabel_ops' restore)
            for q in range(self.n):
                while perm[q] != q:
                    a, b = perm[q], q
                    if a >= local_n and b >= local_n:
                        self.emit_swap(a, 0)
                    else:
                        self.emit_swap(a, b)
        else:
            if needs_fix:
                if owed_at_device:
                    self.emit_relabel(safe[:g])
                slots = [perm[local_n + j] for j in range(g)]
                assert (all(s < local_n for s in slots)
                        and len(set(slots)) == g)
                self.emit_relabel(slots)
            for q in range(local_n):
                while perm[q] != q:
                    a, b = perm[q], q
                    assert a < local_n and b < local_n
                    self.emit_swap(a, b)
        assert perm == list(range(self.n))


def replay_perm(flat_prefix: Sequence, n: int, local_n: int) -> List[int]:
    """Logical->physical permutation after executing `flat_prefix` of a
    relabel-rewritten op list, REPLAYED through the same _PermTracker
    bookkeeping that produced it: relabel events apply their slot
    updates, explicit inserted SWAPs (value-matched against the pass's
    SWAP operand) apply their position swap; everything else leaves the
    permutation alone. The durable executor stores this in its
    checkpoint cursor and re-derives it on resume — a mismatch means
    the plan drifted between save and resume (a knob flip, a planner
    change) and the cut amplitudes would be interpreted under the wrong
    layout (quest_tpu/resilience/durable.py). Note: SWAPs that the
    fusion planner composed INTO band operators are invisible here by
    construction — both sides of the comparison replay the same op
    list, so the fingerprint stays exact."""
    sink: List = []
    tr = _PermTracker(n, local_n, sink)
    for op in flat_prefix:
        kind = getattr(op, "kind", None)
        if kind == "relabel":
            tr.emit_relabel(op.operand)
        elif (kind == "matrix" and len(op.targets) == 2
              and not op.controls and np.array_equal(op.operand, SWAP)):
            tr.emit_swap(op.targets[0], op.targets[1])
    return list(tr.perm)


def is_inserted_layout_op(op) -> bool:
    """True for ops the relabel passes INSERTED as layout movement: the
    whole-register relabel events and the meta-tagged SWAPs. These ops
    move data without consuming circuit semantics, so the durable
    elastic boundary map excludes them from the canonical op count
    (quest_tpu/resilience/durable.py, docs/RESILIENCE.md §elastic)."""
    kind = getattr(op, "kind", None)
    if kind == "relabel":
        return True
    return (kind == "matrix"
            and getattr(op, "meta", None) == INSERTED_META)


# ---------------------------------------------------------------------------
# canonical <-> physical plane layout (the elastic checkpoint contract)
# ---------------------------------------------------------------------------
#
# A sharded engine's live amplitude array is laid out in PHYSICAL
# positions: after relabel events / inserted SWAPs, column-index bit p
# holds logical qubit inv[p] (perm[l] = physical position of logical
# qubit l — the _PermTracker convention replay_perm reconstructs). A
# checkpoint stored in that layout is only meaningful to a reader that
# replays the same relabel history on the same mesh. The two helpers
# below convert between that layout and CANONICAL LOGICAL ORDER
# (column-index bit l = logical qubit l) as a pure, exact index
# permutation — zero floating-point arithmetic, so a canonicalize ->
# physicalize round trip is bit-identical (tests/test_elastic.py).


def _perm_axes(perm: Sequence[int]):
    """numpy transpose axes converting a (2,)*n bit-tensor view of the
    planes from physical to canonical bit order. Axis 1 + i of the
    reshaped (2, 2, ..., 2) array corresponds to column bit n-1-i
    (row-major reshape: leading axes are high bits)."""
    n = len(perm)
    # out axis for logical bit l must read the in axis of physical bit
    # perm[l]: axes[out_pos] = in_pos with bit b at pos n-1-b (+1 for
    # the plane axis)
    axes = [0] + [0] * n
    for l in range(n):
        axes[1 + (n - 1 - l)] = 1 + (n - 1 - perm[l])
    return axes


def canonicalize_planes(planes: np.ndarray, perm: Sequence[int]
                        ) -> np.ndarray:
    """Reorder (2, 2^n) planes from the physical layout under `perm`
    (perm[l] = physical position of logical qubit l) into canonical
    logical order. Identity perm returns the input unchanged."""
    perm = list(perm)
    n = len(perm)
    if perm == list(range(n)):
        return planes
    planes = np.asarray(planes)
    if planes.shape != (2, 1 << n):
        raise ValueError(
            f"planes of shape {tuple(planes.shape)} do not match the "
            f"{n}-position permutation {perm}")
    view = planes.reshape((2,) + (2,) * n)
    return np.ascontiguousarray(
        np.transpose(view, _perm_axes(perm))).reshape(2, 1 << n)


def physicalize_planes(planes: np.ndarray, perm: Sequence[int]
                       ) -> np.ndarray:
    """Inverse of canonicalize_planes: reorder canonical-order planes
    into the physical layout under `perm` (exact; round trips bit-
    identically)."""
    perm = list(perm)
    n = len(perm)
    if perm == list(range(n)):
        return planes
    inv = [0] * n
    for l, p in enumerate(perm):
        inv[p] = l
    return canonicalize_planes(planes, inv)


def _uses(flat, n):
    """Per logical qubit, the sorted indices of ops where it is a MATRIX
    TARGET — the only role that demands a local slot (controls are free
    predicates at any position; diagonal/parity/all-ones ops never
    communicate). Scoring anything else would evict hot targets to keep
    qubits that never need locality."""
    uses = [[] for _ in range(n)]
    for i, op in enumerate(flat):
        if op.kind == "matrix":
            for q in op.targets:
                uses[q].append(i)
    return uses


def lazy_relabel_ops(flat: Sequence, n: int, local_n: int) -> List:
    """Rewrite `flat` (GateOps with kinds matrix/diagonal/parity/allones)
    into an equivalent list in which matrix ops target local positions
    whenever a free slot exists (slot-exhausted ops keep their global
    targets and engine-swap-dance as before). Returns the new list;
    raises nothing new."""
    any_global_matrix = any(
        op.kind == "matrix" and any(t >= local_n for t in op.targets)
        for op in flat)
    if not any_global_matrix:
        return list(flat)

    uses = _uses(flat, n)
    ptr = [0] * n                  # per-qubit cursor into its use list
    perm = list(range(n))          # logical -> physical
    inv = list(range(n))           # physical -> logical
    out: List = []

    def next_use(lq, i):
        u = uses[lq]
        p = ptr[lq]
        while p < len(u) and u[p] <= i:
            p += 1
        ptr[lq] = p
        return u[p] if p < len(u) else len(flat) + 1

    def emit_swap(a: int, b: int):
        """Physical swap of positions a, b as an explicit 2q SWAP op."""
        from quest_tpu.circuit import GateOp
        out.append(GateOp(kind="matrix", targets=(a, b), operand=SWAP,
                          meta=INSERTED_META))
        la, lb = inv[a], inv[b]
        perm[la], perm[lb] = b, a
        inv[a], inv[b] = lb, la

    def localize(G: int, busy, i) -> int:
        """Swap physical-global position G into the best local slot."""
        best, best_score = None, -1
        for slot in range(local_n):
            if slot in busy:
                continue
            score = next_use(inv[slot], i)
            if score > best_score:
                best, best_score = slot, score
        if best is None:
            return G  # no free slot: leave global, engine swap-dances it
        emit_swap(G, best)
        return best

    for i, op in enumerate(flat):
        t_phys = [perm[t] for t in op.targets]
        c_phys = [perm[c] for c in op.controls]
        if op.kind == "matrix":
            busy = set(t_phys) | set(c_phys)
            for j, t in enumerate(t_phys):
                if t >= local_n:
                    new = localize(t, busy, i)
                    busy.add(new)
                    t_phys[j] = new
                    # controls keep their positions (global controls are
                    # free predicates); only the swapped target moved
        out.append(dataclasses.replace(
            op, targets=tuple(t_phys), controls=tuple(c_phys)))

    # restore standard order: logical q back to physical q
    for q in range(n):
        while perm[q] != q:
            a, b = perm[q], q
            if a >= local_n and b >= local_n:
                # global-global: route through local slot 0 (the 3-swap
                # conjugation leaves slot 0's occupant in place)
                emit_swap(a, 0)
                emit_swap(b, 0)
                emit_swap(a, 0)
            else:
                emit_swap(a, b)
    return out


def _compose_free_flags(flat: Sequence) -> List[bool]:
    """Per-op: True for an uncontrolled single-target matrix op that the
    banded engines would COMPOSE into the previous matrix run on the
    same qubit — no other op has touched that qubit since its last
    matrix op, so the pair becomes ONE band operator and the second op
    pays no exchange of its own (the fusion planner walks backward past
    structurally-commuting ops, quest_tpu/ops/fusion.py). Conservative:
    multi-target or controlled matrix ops, and every diagonal/parity/
    allones op, mark their qubits touched (a diagonal on q does NOT
    commute with a matrix run on q)."""
    seen_matrix = set()
    dirty = set()
    out = [False] * len(flat)
    for i, op in enumerate(flat):
        if (op.kind == "matrix" and len(op.targets) == 1
                and not op.controls):
            t = op.targets[0]
            out[i] = t in seen_matrix and t not in dirty
            seen_matrix.add(t)
            dirty.discard(t)
        else:
            for q in tuple(op.targets) + tuple(op.controls):
                dirty.add(q)
    return out


def _op_exchange_price(op, pperm, local_n: int) -> float:
    """Chunk-equivalents THIS PASS's greedy placer and A/B accept test
    price ONE matrix op at — deliberately a simplified, optimistic
    table (no diagonal-operand reroute, one-way swap-dance cost): the
    optimistic count places events denser, which measured BETTER plans
    on the deep-global testbed (see exchange_cost below). The EXACT
    engine-faithful model lives in parallel/comm.py
    (matrix_route/_route_exchanges, shared with the engines) and is
    the final arbiter: comm.choose_plan rescores this pass's output
    with it against the other candidates, so a plan shaped by these
    heuristic prices can win only when the exact model agrees."""
    if op.kind != "matrix":
        return 0.0               # diagonal/parity/allones never move data
    t_phys = [pperm[t] for t in op.targets]
    n_glob = sum(1 for t in t_phys if t >= local_n)
    if n_glob == 0:
        return 0.0
    if len(t_phys) == 1:
        return 1.0               # whole-chunk pair exchange (_matrix_op)
    return 0.5 * n_glob          # half-chunk swap-to-local per global t


def _schedule_cost(ops_list: Sequence, n: int, local_n: int) -> float:
    """Chunk-equivalents of ICI a sharded banded/fused engine ships for
    an op list whose targets are PHYSICAL positions, under the
    composition-aware model: relabel events cost (D-1)/D, matrix ops
    that compose into the previous run on their qubit cost nothing, and
    the rest pay the engine's exchange prices. Used for the plan-time
    A/B that keeps plan_full_relabels honest (below)."""
    D = 1 << (n - local_n)
    flags = _compose_free_flags(ops_list)
    identity = list(range(n))
    total = 0.0
    for i, op in enumerate(ops_list):
        if op.kind == "relabel":
            total += (D - 1) / D
            continue
        if flags[i]:
            continue
        total += _op_exchange_price(op, identity, local_n)
    return total


def plan_full_relabels(flat: Sequence, n: int, local_n: int,
                       min_saved_chunks: float = 2.0,
                       topo=None) -> List:
    """Layer-amortized relabeling for the FUSED sharded engine: rewrite
    `flat` so that stretches of global-qubit matrix work run LOCALLY
    between whole-register relabel events, each ONE all-to-all
    collective.

    Where lazy_relabel_ops localizes one qubit per inserted SWAP (a
    half-chunk exchange each, and the SWAPs break band runs — its
    measured failure on the banded engine), a relabel event swaps ALL
    g device bits with g chosen local slots at once:

      * bytes: one all-to-all ships (1 - 1/D) of the chunk — k single
        swap-dances ship k/2 chunks, and the per-gate global path ships
        k whole chunks (ref exchangeStateVectors,
        QuEST_cpu_distributed.c:481-509; the reference pays this blindly
        per gate);
      * collectives: ONE per event instead of one per qubit;
      * band runs: ops between events are untouched — the fusion
        planner sees ordinary local gates, so whole RCS layers still
        compose into per-band contractions (the event is an explicit
        barrier item, quest_tpu/ops/fusion.py).

    Victim slots are Belady-chosen (occupants with the farthest next
    matrix-target use go global). An event is only emitted when the
    no-relabel cost of the upcoming window exceeds `min_saved_chunks`
    chunk-equivalents — an isolated global gate keeps the engine's
    half-chunk swap-dance, which is cheaper than a whole-register
    exchange. Emits kind='relabel' GateOps whose operand is the tuple
    of local slots receiving device bits (slot[j] <-> device bit j);
    the trailing restore costs at most two events + free local swaps.

    `topo` (a comm.Topology, default flat) activates the hot-qubit
    victim rule on hierarchical meshes: the Belady victim SET is
    unchanged, but its assignment to device bits reverses so the
    occupant with the SOONEST next matrix-target use lands on the
    lowest device bit — intra-host ICI under the contiguous host
    grouping — and the coldest absorb the DCI bits, keeping the qubits
    the upcoming window touches most a cheap exchange away
    (docs/DISTRIBUTED.md §topology). The flat default keeps the
    original farthest-first order bit-for-bit."""
    hot = topo is not None and getattr(topo, "hierarchical", False)
    g = n - local_n
    if g == 0 or g > local_n:
        # a full relabel swaps all g device bits with g DISTINCT local
        # slots, so it needs g <= local_n; tiny chunks keep the plain
        # swap-dance schedule
        return list(flat)
    reject_dynamic_ops(flat, "plan_full_relabels")

    def exchange_cost(op, pperm):
        """Per-op price via the shared table (_op_exchange_price).
        Deliberately NO band-run composition discount here: the
        optimistic count places events denser, which measured BETTER
        plans on the deep-global testbed (6 events/43 KB vs the
        accurate count's 6 events + 2 stray permutes/59 KB) — the
        composition-aware model's job is the final accept test below,
        not greedy placement."""
        return _op_exchange_price(op, pperm, local_n)

    uses = _uses(flat, n)
    ptr = [0] * n
    out: List = []
    tr = _PermTracker(n, local_n, out)
    perm, inv = tr.perm, tr.inv

    def next_use(lq, i):
        u, p = uses[lq], ptr[lq]
        while p < len(u) and u[p] <= i:
            p += 1
        ptr[lq] = p
        return u[p] if p < len(u) else len(flat) + 1

    def plan_event(i):
        """(slots, fires) for a relabel at op i: pick the g Belady
        victims among local slots — never a slot holding one of op i's
        OWN targets (next_use looks strictly past i, so without the
        exclusion the triggering op's local co-target ranks as
        farthest-use and its eviction kills the event at j=i) — then
        simulate forward until the new layout would itself pay an
        exchange, summing what the OLD layout would have shipped over
        that window. Stops as soon as the savings clear
        min_saved_chunks — the only question asked — so planning stays
        O(window), not O(circuit), per candidate. Returns fires=False
        when the current targets leave fewer than g evictable slots."""
        cur = set(flat[i].targets)
        pool = [s for s in range(local_n) if inv[s] not in cur]
        if len(pool) < g:
            return [], False
        scores = sorted(pool, key=lambda s: next_use(inv[s], i),
                        reverse=True)
        victims = scores[:g]
        # new local set: everything except the victims' occupants
        new_local = set(range(n)) - {inv[s] for s in victims}
        saved = 0.0
        for j in range(i, len(flat)):
            op = flat[j]
            if op.kind == "matrix" and any(t not in new_local
                                           for t in op.targets):
                break
            saved += exchange_cost(op, perm)
            if saved >= min_saved_chunks:
                return victims, True
        return victims, saved >= min_saved_chunks

    for i, op in enumerate(flat):
        if (op.kind == "matrix"
                and any(perm[t] >= local_n for t in op.targets)):
            victims, fires = plan_event(i)
            if fires:
                # victims arrive farthest-use first; the hot-qubit rule
                # reverses the bit assignment (soonest reuse -> lowest
                # = ICI device bit) without changing the victim set
                tr.emit_relabel(list(reversed(victims)) if hot
                                else victims)
        out.append(dataclasses.replace(
            op, targets=tuple(perm[t] for t in op.targets),
            controls=tuple(perm[c] for c in op.controls)))

    tr.restore()

    # plan-time A/B: the greedy event cascade can lose on workloads
    # whose runs all compose (every qubit's gates merge into ONE band
    # operator, so the plain schedule ships almost nothing — measured
    # 8 KB relabeled vs 3 KB plain lowered ICI on an
    # all-rotation-layers testbed before this guard). Keep the rewrite
    # only when the composition-aware model says it actually ships
    # less; the flat list's targets are logical == physical (identity
    # permutation), so the same cost fn applies to both sides.
    if _schedule_cost(out, n, local_n) >= _schedule_cost(list(flat), n,
                                                         local_n):
        return list(flat)
    return out
