"""Measurement: outcome probabilities, collapse, and sampling.

Mirrors the reference's semantics (QuEST_common.c:154-169, 360-374;
QuEST_cpu.c:3111-3495): the outcome probability is a psum-style reduction,
the outcome is drawn from the seeded host RNG (identical on every shard),
and collapse renormalizes the kept amplitudes (by 1/sqrt(p) for
statevectors, by 1/p for density matrices) while zeroing the rest.

A fully-traced variant (`measure_functional`) keeps measurement inside jit
using a jax.random key and branchless collapse, for circuit-level
compilation on TPU.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from quest_tpu import precision
from quest_tpu import random_ as rng
from quest_tpu import validation as val
from quest_tpu.ops import apply as A
from quest_tpu.state import Qureg


@partial(jax.jit, static_argnames=("n", "qubit", "density"))
def _prob_of_zero(amps, *, n, qubit, density):
    acc = precision.accum_dtype(amps.dtype)
    if density:
        # probability from the diagonal: rho[k,k] with bit `qubit` of k == 0
        # (ref densmatr_findProbabilityOfZeroLocal, QuEST_cpu.c:3111-3157)
        dim = 1 << (n // 2)
        d = jnp.diagonal(amps[0].reshape((dim, dim)))  # diag is transpose-proof
        k = jnp.arange(dim)
        keep = ((k >> qubit) & 1) == 0
        return jnp.sum(jnp.where(keep, d, 0.0).astype(acc)).astype(amps.dtype)
    pre, post = 1 << (n - 1 - qubit), 1 << qubit
    re = amps[0].reshape(pre, 2, post)[:, 0, :]
    im = amps[1].reshape(pre, 2, post)[:, 0, :]
    return jnp.sum((re * re + im * im).astype(acc)).astype(amps.dtype)


@partial(jax.jit, static_argnames=("n", "qubit", "density"))
def _collapse(amps, outcome, prob, *, n, qubit, density):
    rdt = amps.dtype
    prob = jnp.asarray(prob, dtype=rdt)
    if density:
        nq = n // 2
        qubits = tuple(sorted({qubit, qubit + nq}, reverse=True))
        dims, axis_of = A.seg_view(n, qubits)
        keep = ((A.bit_tensor(len(dims), axis_of[qubit]) == outcome) &
                (A.bit_tensor(len(dims), axis_of[qubit + nq]) == outcome))
        renorm = 1.0 / prob
    else:
        dims, axis_of = A.seg_view(n, (qubit,))
        keep = A.bit_tensor(len(dims), axis_of[qubit]) == outcome
        renorm = jax.lax.rsqrt(prob)
    factor = keep.astype(rdt) * renorm
    re = amps[0].reshape(dims) * factor
    im = amps[1].reshape(dims) * factor
    return jnp.stack([re.reshape(-1), im.reshape(-1)])


def calc_prob_of_outcome(q: Qureg, qubit: int, outcome: int) -> float:
    val.validate_target(q, qubit)
    val.validate_outcome(outcome)
    p0 = _prob_of_zero(q.amps, n=q.num_state_qubits, qubit=qubit,
                       density=q.is_density)
    return float(p0) if outcome == 0 else float(1.0 - p0)


def collapse_to_outcome(q: Qureg, qubit: int, outcome: int) -> Tuple[Qureg, float]:
    """Project onto `outcome` and renormalize; returns (state, prob)."""
    val.validate_target(q, qubit)
    val.validate_outcome(outcome)
    prob = calc_prob_of_outcome(q, qubit, outcome)
    val.validate_measurement_prob(prob, precision.real_eps(q.dtype))
    amps = _collapse(q.amps, jnp.asarray(outcome),
                     jnp.asarray(prob, dtype=q.real_dtype),
                     n=q.num_state_qubits, qubit=qubit, density=q.is_density)
    return q.replace_amps(amps), prob


def measure_with_stats(q: Qureg, qubit: int) -> Tuple[Qureg, int, float]:
    """Sample an outcome, collapse, return (state, outcome, outcomeProb)
    (ref statevec_measureWithStats, QuEST_common.c:360-366)."""
    val.validate_target(q, qubit)
    eps = precision.real_eps(q.dtype)
    zero_prob = calc_prob_of_outcome(q, qubit, 0)
    # identical draw on every shard (ref generateMeasurementOutcome)
    if zero_prob < eps:
        outcome = 1
    elif 1 - zero_prob < eps:
        outcome = 0
    else:
        outcome = int(rng.uniform() > zero_prob)
    prob = zero_prob if outcome == 0 else 1 - zero_prob
    amps = _collapse(q.amps, jnp.asarray(outcome),
                     jnp.asarray(prob, dtype=q.real_dtype),
                     n=q.num_state_qubits, qubit=qubit, density=q.is_density)
    return q.replace_amps(amps), outcome, prob


def measure(q: Qureg, qubit: int) -> Tuple[Qureg, int]:
    q, outcome, _ = measure_with_stats(q, qubit)
    return q, outcome


@partial(jax.jit, static_argnames=("n", "qubit", "density"))
def _measure_traced(amps, key, *, n, qubit, density):
    p0 = _prob_of_zero(amps, n=n, qubit=qubit, density=density)
    # degenerate-branch threshold at the REGISTER's precision (1e-5 f32 /
    # 1e-13 f64, like the host path and the reference's REAL_EPS guard,
    # QuEST_common.c:154-169) — the old hardcoded f32 eps would force the
    # outcome of a legitimate p=1e-6 branch on an f64 register
    eps = jnp.asarray(precision.real_eps(amps.dtype), dtype=p0.dtype)
    u = jax.random.uniform(key, dtype=p0.dtype)
    # force the outcome when one branch has (numerically) zero probability,
    # like the host path (ref generateMeasurementOutcome, QuEST_common.c:154)
    outcome = jnp.where(p0 < eps, 1,
                        jnp.where(1.0 - p0 < eps, 0,
                                  (u > p0).astype(jnp.int32)))
    prob = jnp.where(outcome == 0, p0, 1.0 - p0)
    prob = jnp.maximum(prob, eps)  # collapse never divides by zero
    new = _collapse(amps, outcome, prob, n=n, qubit=qubit, density=density)
    return new, outcome, prob


def measure_functional(q: Qureg, qubit: int, key) -> Tuple[Qureg, jax.Array, jax.Array]:
    """Fully-traced measurement for use inside jitted circuits: outcome and
    probability are device values; the RNG is an explicit jax.random key
    (TPU-native improvement over the reference's host RNG)."""
    val.validate_target(q, qubit)
    amps, outcome, prob = _measure_traced(
        q.amps, key, n=q.num_state_qubits, qubit=qubit, density=q.is_density)
    return q.replace_amps(amps), outcome, prob


def _stable_cdf(probs):
    """Cumulative sum with bounded rounding error at the 2^30 scale.

    A plain f32 cumsum over 2^30 probabilities accumulates a random-walk
    drift of order sqrt(N)*eps ~ 1e-3, which visibly biases tail samples
    (the reference sidesteps this with f64 Kahan sums,
    QuEST_cpu_distributed.c:64-117). TPU-native fix: split into ~sqrt(N)
    blocks, cumsum each block in the plane dtype, and carry the running
    block totals in an f64 exclusive scan. The f64 carry array is only
    sqrt(N) long; the output stays in the plane dtype, so memory and
    bandwidth match the naive cumsum. Error is then bounded by the
    WITHIN-block drift (~sqrt(sqrt(N))*eps per unit of block mass)."""
    N = probs.shape[0]
    k = (N - 1).bit_length()
    if N <= (1 << 14) or (1 << k) != N:
        acc = precision.accum_dtype(probs.dtype)
        return jnp.cumsum(probs.astype(acc)).astype(probs.dtype)
    B = 1 << (k // 2)
    within = jnp.cumsum(probs.reshape(B, N // B), axis=1)
    acc = precision.accum_dtype(probs.dtype)
    totals = within[:, -1].astype(acc)
    carry = jnp.concatenate([jnp.zeros((1,), dtype=acc),
                             jnp.cumsum(totals)[:-1]])
    # the add happens in the accumulator dtype: the exact sequence is then
    # monotone and rounding to the plane dtype preserves monotonicity
    # (searchsorted requires a sorted CDF); the converts fuse elementwise,
    # so nothing accumulator-sized is materialized
    out = (within.astype(acc) + carry[:, None]).astype(probs.dtype).reshape(-1)
    if np.dtype(acc) == np.dtype(probs.dtype):
        # no wider accumulator (x64 off): repair possible 1-ulp boundary
        # inversions with a running max
        out = jax.lax.cummax(out)
    return out


@partial(jax.jit, static_argnames=("n", "density", "num_shots"))
def _sample_traced(amps, key, *, n, density, num_shots):
    if density:
        dim = 1 << (n // 2)
        probs = jnp.diagonal(amps[0].reshape((dim, dim)))
    else:
        probs = amps[0] * amps[0] + amps[1] * amps[1]
    # inverse-CDF sampling: O(2^n + shots) memory (categorical would
    # materialize a (shots, 2^n) Gumbel tensor)
    cdf = _stable_cdf(probs)
    u = jax.random.uniform(key, (num_shots,), dtype=probs.dtype) * cdf[-1]
    return jnp.searchsorted(cdf, u, side="right").astype(jnp.int32)


# jitted shard_map sampling wrappers, keyed (mesh, n, density, drawn, D)
_SHARDED_SAMPLE_RUNS: dict = {}


def _sample_sharded_body(amps, key, *, n, density, num_shots, D):
    """Per-shard inverse-CDF sampling: local CDFs + a D-scalar all_gather
    carry (the only cross-shard traffic). Every device draws the SAME
    uniforms; the cumsum of shard totals (identical everywhere) defines a
    consistent, gap-free ownership partition, and each shard resolves its
    own shots with a local searchsorted. ICI cost: D scalars + one psum
    over (num_shots,) ints — the state NEVER gathers (GSPMD would have
    compiled the naive path to a single-device program, an impossible
    8+ TB gather at pod scale)."""
    from quest_tpu.env import AMP_AXIS

    dev = jax.lax.axis_index(AMP_AXIS)
    if density:
        dim = 1 << (n // 2)
        cols_local = amps.shape[1] // dim
        mat = amps[0].reshape(cols_local, dim)
        idx = dev * cols_local + jnp.arange(cols_local)
        probs = jnp.take_along_axis(mat, idx[:, None], axis=1)[:, 0]
    else:
        probs = amps[0] * amps[0] + amps[1] * amps[1]
    local_cdf = _stable_cdf(probs)
    totals = jax.lax.all_gather(local_cdf[-1], AMP_AXIS)      # (D,)
    acc = precision.accum_dtype(probs.dtype)
    cuml = jnp.cumsum(totals.astype(acc))
    lo = jnp.where(dev > 0, cuml[jnp.maximum(dev - 1, 0)], 0.0)
    hi = cuml[dev]
    grand = cuml[-1]
    u = jax.random.uniform(key, (num_shots,), dtype=acc) * grand
    mine = (u >= lo) & (u < hi)
    loc = jnp.searchsorted(local_cdf,
                           (u - lo).astype(local_cdf.dtype), side="right")
    loc = jnp.minimum(loc, probs.shape[0] - 1)
    idt = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
    glob = (dev.astype(idt) * probs.shape[0] + loc.astype(idt))
    return jax.lax.psum(jnp.where(mine, glob, 0), AMP_AXIS)


def sample(q: Qureg, num_shots: int, key=None) -> jax.Array:
    """Draw `num_shots` full-register computational-basis samples WITHOUT
    collapsing the state — one device-side categorical draw over the
    probability distribution. The reference can only sample by repeated
    measure() calls that destroy the state (its RCS-style workloads
    re-prepare the state per shot); batched sampling is the TPU-native
    replacement. Sharded registers sample in place: per-shard CDFs with a
    scalar carry, no state gather. Returns an int array of basis-state
    indices.

    The COMPILED shot count is bucketed: `num_shots` pads up to
    `env.batch_bucket(num_shots)` (pow2 under the default
    QUEST_BATCH_BUCKET=pow2) inside the traced draw and the surplus
    slices off after, so a serving workload sweeping shot counts —
    shots=100, 120, 128 — shares ONE compiled program per bucket
    instead of retracing per distinct count (the same bucketing
    discipline as compiled_batched, docs/BATCHING.md; pinned
    zero-retrace in tests/test_serve.py). Each returned shot is still
    an independent inverse-CDF draw; only how many uniforms the traced
    program draws is padded."""
    if num_shots < 1:
        raise val.QuESTError("Invalid number of shots: must be positive.")
    if key is None:
        # derive from the seeded host stream, so seedQuEST makes the whole
        # program — including sampling — reproducible like the reference;
        # a full 32-bit word, not int(uniform()*2^31) — that mapping
        # zeroes bit 31 (half the key space) and collides nearby draws
        key = jax.random.PRNGKey(rng.uint32())
    from quest_tpu.env import batch_bucket
    drawn = batch_bucket(num_shots)
    sh = getattr(q.amps, "sharding", None)
    mesh = getattr(sh, "mesh", None)
    if mesh is not None and mesh.devices.size > 1:
        from jax.sharding import PartitionSpec as P

        from quest_tpu.env import AMP_AXIS

        if AMP_AXIS in mesh.axis_names:
            # cache the jitted shard_map per (mesh, register, bucket):
            # rebuilding the wrapper every call would retrace every
            # sample — the bucketing above only pays off if the wrapper
            # survives between calls. Holding the mesh OBJECT in the key
            # (not id(mesh)) pins it so a reused id can never alias.
            ck = (mesh, q.num_state_qubits, q.is_density, drawn,
                  int(mesh.devices.size))
            run = _SHARDED_SAMPLE_RUNS.get(ck)
            if run is None:
                body = partial(_sample_sharded_body, n=q.num_state_qubits,
                               density=q.is_density, num_shots=drawn,
                               D=int(mesh.devices.size))
                from quest_tpu import compat
                run = _SHARDED_SAMPLE_RUNS[ck] = jax.jit(compat.shard_map(
                    body, mesh, (P(None, AMP_AXIS), P()), P()))
            return run(q.amps, key)[:num_shots]
    return _sample_traced(q.amps, key, n=q.num_state_qubits,
                          density=q.is_density, num_shots=drawn
                          )[:num_shots]
