"""Circuit transpiler: deterministic gate-count reduction BEFORE planning.

Every engine in the stack executes the op stream as the author wrote it —
fusion packs gates into bands, the autotuner picks the cheapest engine/
comm/geometry, but nothing reduces the gate count itself. Foreign circuits
(OpenQASM corpora, Qiskit exports) arrive rebased into long 1q+CX chains
(the Q-GEAR observation, arXiv:2504.03967): adjacent inverses, mergeable
1q runs, foldable Rz chains and re-synthesizable 2q runs all pay full HBM
sweeps. This module rewrites the stream into a provably-equivalent cheaper
one; `plan.autotune` prices raw-vs-transpiled with the same
incumbent-wins-ties discipline as every other plan axis (docs/TRANSPILE.md).

Five composable passes, applied per measurement-free stretch (dynamic ops
— measure / classical feedback / noise channels — are barriers; the
stream between barriers is rewritten, the barriers themselves never move):

  cancel     adjacent gate/inverse pairs, including through structurally-
             commuting separators (fusion._commutes legality), plus
             identity and global-phase elimination. The residual global
             phase is re-emitted as ONE [c, c] diagonal so statevector
             equivalence is exact, not up-to-phase.
  fold       same-axis parametric runs merge additively: Rz(a)·Rz(b) ->
             Rz(a+b) via the `as_rotation` contract (PR 19), elementwise
             products for diagonal/allones pairs. Parity folding adds the
             stored operands directly, so TRACED angles stay trace-time
             operands — a transpiled VQE ansatz retraces nothing.
  merge1q    maximal single-qubit runs composed into one u3 (exact 2x2
             product accumulated in complex128); a diagonal result is
             emitted as a diagonal op so it stays poolable downstream.
  resynth2q  maximal 2-qubit runs are KAK-decomposed through ops/kak.py
             into <= 3 parity cores + a 1q layer, accepted ONLY when the
             rewrite is cheaper under the target engine's own cost model
             (fusion.plan_stats full-state passes, tie-broken on op
             count) — never a blind rebase.
  cancel3q   identity-window elimination over <= 3-qubit neighborhoods:
             a prefix-product scan drops every contiguous window whose
             dense composition is a global phase — the block-level
             cancellations pairwise peephole can't see (a toffoli pair
             in its 15-op Clifford+T form, an uncompute block).

Equivalence contract (pinned in tests/test_transpile.py and
scripts/check_transpile_golden.py):

  * exact_only=True restricts to the bit-identical subset: only ops whose
    pairwise product is EXACTLY the identity (permutation matrices,
    exact-inverse diagonal tables) are cancelled, and only exact
    identities are dropped. Executing the rewritten stream is
    bit-for-bit the original on every engine.
  * The default mode additionally merges/resynthesizes: rewritten
    unitaries are eps-close to the dense composed oracle (f32 1e-5 /
    f64 1e-12), the same honesty split PR 14 established for elastic
    bit-identity.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

from quest_tpu import circuit as CC
from quest_tpu.circuit import Circuit, GateOp
from quest_tpu.ops import fusion as F
from quest_tpu.ops import kak as K
from quest_tpu.ops import matrices as M

PASSES = ("cancel", "fold", "merge1q", "resynth2q", "cancel3q")

# op kinds the passes may touch; everything else (measure, measure_dm,
# classical, superop, relabel, future kinds) is a barrier the rewrite
# never crosses and never reorders
_STATIC_KINDS = frozenset({"matrix", "diagonal", "parity", "allones"})

_ID2 = np.eye(2, dtype=np.complex128)
_ATOL = 1e-12          # complex128 composition tolerance
_FIXPOINT_ITERS = 8    # peephole cascade bound per stretch


# ---------------------------------------------------------------------------
# structural helpers
# ---------------------------------------------------------------------------


def _all_qubits(op: GateOp) -> frozenset:
    return frozenset(op.targets) | frozenset(op.controls)


def _commutes(a: GateOp, b: GateOp) -> bool:
    return F._commutes(F._nondiag_qubits(a), _all_qubits(a),
                       F._nondiag_qubits(b), _all_qubits(b))


def _static(op: GateOp) -> bool:
    """Ops the rewrite may reason about. Controlled allones ops are
    excluded (the eager applier ignores allones controls — see
    fusion._diag_class — so their semantics are not the dense embedding);
    scheduler-shaped ComposedDiag items (carry `parts`) never appear in a
    raw builder stream but are excluded defensively."""
    if op.kind not in _STATIC_KINDS:
        return False
    if op.kind == "allones" and op.controls:
        return False
    if getattr(op, "parts", None) is not None:
        return False
    return True


def _concrete(op: GateOp) -> bool:
    return F._concrete(op.operand)


def _ctrl_sig(op: GateOp):
    """Order-insensitive (control qubit -> required state) signature.
    Circuit._add always fills cstates, but normalize anyway."""
    cstates = op.cstates if op.cstates else (1,) * len(op.controls)
    return frozenset(zip(op.controls, cstates))


def _identity_phase(op: GateOp, exact_only: bool) -> Optional[complex]:
    """c such that dropping `op` and multiplying the global phase by c is
    equivalent, or None. In exact mode only EXACT identities (c == 1,
    operand bitwise trivial) qualify — executing them is bit-identical to
    skipping them (multiply by exact 1.0/0.0)."""
    if not _static(op) or not _concrete(op):
        return None
    if op.kind == "parity":
        return 1.0 if float(op.operand) == 0.0 else None
    if op.kind == "allones":
        return 1.0 if complex(op.operand) == 1.0 else None
    if op.kind == "diagonal":
        d = np.asarray(op.operand)
        if exact_only:
            return 1.0 if np.array_equal(d, np.ones_like(d)) else None
        c = complex(d.flat[0])
        if not np.allclose(d, c, atol=_ATOL):
            return None
        if abs(c - 1.0) <= _ATOL:
            return 1.0
        # a uniform non-1 diagonal is a global phase only when uncontrolled
        return c if not op.controls and abs(abs(c) - 1.0) <= _ATOL else None
    # matrix
    m = np.asarray(op.operand)
    if m.ndim != 2 or m.shape[0] != m.shape[1]:
        return None
    eye = np.eye(m.shape[0], dtype=m.dtype)
    if exact_only:
        return 1.0 if np.array_equal(m, eye) else None
    c = complex(m[0, 0])
    if not np.allclose(m, c * eye, atol=_ATOL):
        return None
    if abs(c - 1.0) <= _ATOL:
        return 1.0
    return c if not op.controls and abs(abs(c) - 1.0) <= _ATOL else None


# ---------------------------------------------------------------------------
# pass 1+3: peephole cancellation + rotation folding (one backward scan)
# ---------------------------------------------------------------------------


def _cancel_rule(a: GateOp, b: GateOp, exact_only: bool):
    """('drop2', phase) when b composed onto a is the identity up to a
    unit global phase (exact mode: exactly the identity), else None."""
    if a.kind != b.kind or _ctrl_sig(a) != _ctrl_sig(b):
        return None
    if not (_concrete(a) and _concrete(b)):
        return None
    if a.kind == "matrix":
        if a.targets != b.targets:
            return None
        p = np.asarray(b.operand) @ np.asarray(a.operand)
        eye = np.eye(p.shape[0], dtype=p.dtype)
        if exact_only:
            return ("drop2", 1.0) if np.array_equal(p, eye) else None
        c = complex(p[0, 0])
        if np.allclose(p, c * eye, atol=_ATOL) and abs(abs(c) - 1.0) <= _ATOL:
            if abs(c - 1.0) <= _ATOL:
                return ("drop2", 1.0)
            if not a.controls:
                return ("drop2", c)
        return None
    if a.kind == "diagonal":
        if a.targets != b.targets:
            return None
        p = np.asarray(a.operand) * np.asarray(b.operand)
        if exact_only:
            return (("drop2", 1.0)
                    if np.array_equal(p, np.ones_like(p)) else None)
        c = complex(p.flat[0])
        if np.allclose(p, c, atol=_ATOL) and abs(abs(c) - 1.0) <= _ATOL:
            if abs(c - 1.0) <= _ATOL:
                return ("drop2", 1.0)
            if not a.controls:
                return ("drop2", c)
        return None
    if a.kind == "parity":
        if frozenset(a.targets) != frozenset(b.targets):
            return None
        # IEEE: x + (-x) == 0.0 exactly, so the inverse-pair case is hit
        # without a tolerance; removal is eps-valid (strictly MORE
        # accurate than executing both rotations)
        if exact_only:
            return None
        return ("drop2", 1.0) if float(a.operand) + float(b.operand) == 0.0 \
            else None
    if a.kind == "allones":
        if frozenset(a.targets) != frozenset(b.targets):
            return None
        p = complex(a.operand) * complex(b.operand)
        if exact_only:
            return ("drop2", 1.0) if p == 1.0 else None
        return ("drop2", 1.0) if abs(p - 1.0) <= _ATOL else None
    return None


def _fold_rule(a: GateOp, b: GateOp):
    """('merge', op) folding b into a: additive parity angles (traced
    operands stay traced — the runtime-operand property), elementwise
    diagonal/allones products, same-axis rx/ry via as_rotation."""
    if a.kind != b.kind or _ctrl_sig(a) != _ctrl_sig(b):
        return None
    if a.kind == "parity":
        if frozenset(a.targets) != frozenset(b.targets):
            return None
        return ("merge", dataclasses.replace(a, operand=a.operand + b.operand))
    if not (_concrete(a) and _concrete(b)):
        return None
    if a.kind == "diagonal":
        if a.targets != b.targets:
            return None
        return ("merge", dataclasses.replace(
            a, operand=np.asarray(a.operand) * np.asarray(b.operand)))
    if a.kind == "allones":
        if frozenset(a.targets) != frozenset(b.targets):
            return None
        return ("merge", dataclasses.replace(
            a, operand=complex(a.operand) * complex(b.operand)))
    if a.kind == "matrix" and a.targets == b.targets and not a.controls:
        ra, rb = CC.as_rotation(a), CC.as_rotation(b)
        if ra is None or rb is None or ra[0] != rb[0]:
            return None
        if ra[0] == "rx":
            axis = (1.0, 0.0, 0.0)
        elif ra[0] == "ry":
            axis = (0.0, 1.0, 0.0)
        else:
            return None
        return ("merge", dataclasses.replace(
            a, operand=np.asarray(M.rotation(ra[1] + rb[1], axis))))
    return None


def _peephole(ops: List[GateOp], exact_only: bool, stats: dict,
              phase_cell: List[complex]) -> List[GateOp]:
    """One forward pass with a backward commuting-separator scan: each
    incoming op walks back through the output past structurally-commuting
    ops (fusion._commutes legality) looking for a cancel partner or a
    fold partner. Cascades (X Y Y X -> empty) because later ops rescan
    the shortened output.

    The scan is indexed per qubit: ops DISJOINT from the incoming op
    always commute (fusion._commutes on an empty shared set) and can
    never be rule partners (both rules require equal targets), so only
    ops that share a qubit are visited — the walk is bounded by the
    per-qubit overlap depth, not the stream length (the difference
    between O(ops) and O(ops^2) on wide foreign circuits). Cancelled
    ops become tombstones (None) compacted at the end so the per-qubit
    indices stay valid; a non-static op is a full barrier exactly as in
    the linear scan (no candidate behind it is reachable)."""
    out: List[Optional[GateOp]] = []
    touch: dict = {}            # qubit -> indices into out (append-only)
    barrier = -1                # index of the newest non-static op
    for op in ops:
        c = _identity_phase(op, exact_only)
        if c is not None:
            stats["identity"] += 1
            phase_cell[0] *= c
            continue
        if not _static(op):
            barrier = len(out)
            out.append(op)
            continue
        lists = []
        ptrs = []
        for q in {*op.targets, *op.controls}:
            lst = touch.get(q)
            if lst:
                lists.append(lst)
                ptrs.append(len(lst) - 1)
        placed = False
        while True:
            # lazy descending merge of the per-qubit index lists: the
            # scan almost always stops at the first overlapping op, so
            # materializing/sorting the union would dominate the pass
            j = -1
            for i, lst in enumerate(lists):
                p = ptrs[i]
                if p >= 0 and lst[p] > j:
                    j = lst[p]
            if j <= barrier:
                break
            for i, lst in enumerate(lists):
                p = ptrs[i]
                while p >= 0 and lst[p] >= j:
                    p -= 1
                ptrs[i] = p
            prev = out[j]
            if prev is None:
                continue
            r = _cancel_rule(prev, op, exact_only)
            if r is not None:
                out[j] = None
                stats["cancel"] += 1
                phase_cell[0] *= r[1]
                placed = True
                break
            if not exact_only:
                r = _fold_rule(prev, op)
                if r is not None:
                    merged = r[1]
                    cm = _identity_phase(merged, exact_only)
                    if cm is not None:
                        out[j] = None
                        phase_cell[0] *= cm
                    else:
                        out[j] = merged
                    stats["fold"] += 1
                    placed = True
                    break
            if not _commutes(prev, op):
                break
        if not placed:
            idx = len(out)
            out.append(op)
            for q in op.targets:
                touch.setdefault(q, []).append(idx)
            for q in op.controls:
                touch.setdefault(q, []).append(idx)
    return [o for o in out if o is not None]


# ---------------------------------------------------------------------------
# pass 2: 1q run merging
# ---------------------------------------------------------------------------


def _u2_of(op: GateOp) -> Optional[np.ndarray]:
    """The 2x2 unitary of an eligible uncontrolled single-qubit op."""
    if not _static(op) or op.controls or len(op.targets) != 1 \
            or not _concrete(op):
        return None
    if op.kind == "matrix":
        m = np.asarray(op.operand, dtype=np.complex128)
        return m if m.shape == (2, 2) else None
    if op.kind == "diagonal":
        d = np.asarray(op.operand, dtype=np.complex128)
        return np.diag(d) if d.shape == (2,) else None
    if op.kind == "parity":
        half = float(op.operand) / 2.0
        return np.diag([np.exp(-1j * half), np.exp(1j * half)])
    # allones on one target: phase on |1>
    return np.diag([1.0, complex(op.operand)])


def _op_from_2x2(u: np.ndarray, q: int) -> Optional[GateOp]:
    """Re-emit a composed 2x2 as the cheapest op kind: None for identity
    (caller handles the phase), a diagonal op when the off-diagonals
    vanish (stays poolable downstream), else one dense u3 matrix op."""
    if abs(u[0, 1]) <= _ATOL and abs(u[1, 0]) <= _ATOL:
        d = np.array([u[0, 0], u[1, 1]], dtype=np.complex128)
        return GateOp("diagonal", (q,), operand=d)
    return GateOp("matrix", (q,), operand=np.asarray(u, dtype=np.complex128))


def _merge1q(ops: List[GateOp], stats: dict,
             phase_cell: List[complex]) -> List[GateOp]:
    """Compose maximal per-qubit runs of uncontrolled 1q ops into one op,
    emitted at the LAST member's position (ops between run members never
    touch the run qubit, so the move commutes)."""
    runs: dict = {}                 # qubit -> [indices of open run]
    replace: dict = {}              # last index -> composed GateOp | None
    drop = set()
    mats = [None] * len(ops)

    def close(q):
        run = runs.pop(q, None)
        if run is None or len(run) < 2:
            return
        u = _ID2
        for i in run:
            u = mats[i] @ u
        c = complex(u[0, 0])
        if (abs(u[0, 1]) <= _ATOL and abs(u[1, 0]) <= _ATOL
                and abs(u[1, 1] - c) <= _ATOL and abs(abs(c) - 1.0) <= _ATOL):
            phase_cell[0] *= c
            newop = None
            removed = len(run)
        else:
            newop = _op_from_2x2(u, q)
            removed = len(run) - 1
        for i in run[:-1]:
            drop.add(i)
        replace[run[-1]] = newop
        if newop is None:
            drop.add(run[-1])
        stats["merge1q"] += removed

    for i, op in enumerate(ops):
        u = _u2_of(op)
        if u is not None:
            q = op.targets[0]
            mats[i] = u
            runs.setdefault(q, []).append(i)
            continue
        for q in sorted(_all_qubits(op)):
            close(q)
        if op.kind not in _STATIC_KINDS and not _all_qubits(op):
            for q in sorted(runs):       # unknown claim: close everything
                close(q)
    for q in sorted(runs):
        close(q)

    out: List[GateOp] = []
    for i, op in enumerate(ops):
        if i in drop and i not in replace:
            continue
        if i in replace:
            if replace[i] is not None:
                out.append(replace[i])
            continue
        out.append(op)
    return out


# ---------------------------------------------------------------------------
# dense composition (shared by pass 4, the tests, and the goldens)
# ---------------------------------------------------------------------------


def dense_unitary(ops: Sequence[GateOp], qubits: Sequence[int]) -> np.ndarray:
    """The exact 2^k x 2^k unitary of an op sequence whose support lies
    inside `qubits` (little-endian: matrix bit j <-> qubits[j], the
    tests/oracle.py convention), accumulated in complex128."""
    qubits = tuple(int(q) for q in qubits)
    k = len(qubits)
    idx = {q: j for j, q in enumerate(qubits)}
    u = np.eye(1 << k, dtype=np.complex128)
    for op in ops:
        u = _embed(op, idx, k) @ u
    return u


def _embed(op: GateOp, idx: dict, k: int) -> np.ndarray:
    dim = 1 << k
    if not _static(op) or not _concrete(op):
        raise ValueError(f"dense_unitary: cannot embed op kind "
                         f"{op.kind!r} (controls={op.controls})")
    controls = tuple(idx[c] for c in op.controls)
    cstates = op.cstates if op.cstates else (1,) * len(op.controls)

    def ctrl_ok(i):
        return all(((i >> c) & 1) == s for c, s in zip(controls, cstates))

    if op.kind == "matrix":
        m = np.asarray(op.operand, dtype=np.complex128)
        tbits = [idx[t] for t in op.targets]
        out = np.zeros((dim, dim), dtype=np.complex128)
        for col in range(dim):
            if not ctrl_ok(col):
                out[col, col] = 1.0
                continue
            a = 0
            for bit, t in enumerate(tbits):
                a |= ((col >> t) & 1) << bit
            rest = col
            for t in tbits:
                rest &= ~(1 << t)
            for ap in range(1 << len(tbits)):
                row = rest
                for bit, t in enumerate(tbits):
                    if (ap >> bit) & 1:
                        row |= 1 << t
                out[row, col] = m[ap, a]
        return out

    vals = np.ones(dim, dtype=np.complex128)
    if op.kind == "diagonal":
        d = np.asarray(op.operand, dtype=np.complex128).reshape(-1)
        tbits = [idx[t] for t in op.targets]
        for i in range(dim):
            if not ctrl_ok(i):
                continue
            a = 0
            for bit, t in enumerate(tbits):
                a |= ((i >> t) & 1) << bit
            vals[i] = d[a]
    elif op.kind == "parity":
        # exp(-i theta/2 Z..Z): factor exp(-i theta/2 * (-1)^parity)
        # (apply.apply_parity_phase, ref statevec_multiRotateZ)
        half = float(op.operand) / 2.0
        tbits = [idx[t] for t in op.targets]
        for i in range(dim):
            ones = sum((i >> t) & 1 for t in tbits) & 1
            vals[i] = np.exp(-1j * half * (1.0 - 2.0 * ones))
    else:                                        # allones (uncontrolled)
        term = complex(op.operand)
        tbits = [idx[t] for t in op.targets]
        for i in range(dim):
            if all((i >> t) & 1 for t in tbits):
                vals[i] = term
    return np.diag(vals)


# ---------------------------------------------------------------------------
# pass 4: 2q KAK resynthesis
# ---------------------------------------------------------------------------


def _stream_cost(ops: Sequence[GateOp], n: int) -> Tuple[int, int]:
    """(full-state passes, op count) under the banded engine's own cost
    model — the acceptance metric for resynthesis."""
    items = F.plan(list(ops), n)
    return (F.plan_stats(items)["full_state_passes"], len(ops))


def _try_kak(items: List[GateOp], qubits: frozenset, n: int,
             stats: dict, phase_cell: List[complex]) -> Optional[List[GateOp]]:
    if len(qubits) != 2 or len(items) < 2:
        return None
    if sum(1 for op in items if len(_all_qubits(op)) == 2) < 2:
        return None
    qa, qb = sorted(qubits)
    try:
        u4 = dense_unitary(items, (qa, qb))
        seq = K.kak_gate_sequence(u4, qa, qb)
    except Exception:
        return None
    new_ops: List[GateOp] = []
    local_phase = 1.0
    for kind, where, what in seq:
        if kind == "1q":
            u = np.asarray(what, dtype=np.complex128)
            c = complex(u[0, 0])
            if (abs(u[0, 1]) <= _ATOL and abs(u[1, 0]) <= _ATOL
                    and abs(u[1, 1] - c) <= _ATOL
                    and abs(abs(c) - 1.0) <= _ATOL):
                local_phase *= c
                continue
            new_ops.append(_op_from_2x2(u, where))
        else:                                    # ("parity", (qa, qb), ang)
            new_ops.append(GateOp("parity", tuple(where),
                                  operand=float(what)))
    # kak_gate_sequence emits raw conjugation layers (H / S.H pairs
    # bracketing each interaction core); clean them up locally before
    # pricing, with a scratch stats sink so the report only attributes
    # the net resynthesis
    scratch = {"cancel": 0, "identity": 0, "global_phase": 0, "fold": 0,
               "merge1q": 0, "resynth2q": 0, "cancel3q": 0}
    ph = [1.0 + 0.0j]
    for _ in range(4):
        before = len(new_ops)
        new_ops = _peephole(new_ops, False, scratch, ph)
        if len(new_ops) == before:
            break
    new_ops = _merge1q(new_ops, scratch, ph)
    new_ops = _peephole(new_ops, False, scratch, ph)
    local_phase *= ph[0]
    if abs(local_phase - 1.0) > _ATOL:
        # keep the phase local so the rewrite is exactly unitary-equal
        new_ops.append(GateOp("diagonal", (qa,), operand=np.array(
            [local_phase, local_phase], dtype=np.complex128)))
    try:
        err = np.max(np.abs(dense_unitary(new_ops, (qa, qb)) - u4))
    except Exception:
        return None
    if err > 1e-9:
        return None
    # candidate B: the run as ONE dense 2q op — a diagonal table when the
    # composition is diagonal (poolable downstream: a cp chain becomes
    # one diag item), else a 4x4 matrix (a 3-cx swap becomes one band op)
    if np.allclose(u4, np.diag(np.diag(u4)), atol=_ATOL):
        dense_ops = [GateOp("diagonal", (qa, qb),
                            operand=np.diag(u4).astype(np.complex128))]
    else:
        dense_ops = [GateOp("matrix", (qa, qb), operand=u4)]
    old_cost = _stream_cost(items, n)
    best, best_cost = None, old_cost
    for cand in (new_ops, dense_ops):
        cost = _stream_cost(cand, n)
        if cost < best_cost:
            best, best_cost = cand, cost
    if best is not None:
        stats["resynth2q"] += 1
        return best
    return None


def _drop_identity_windows(items: List[GateOp], qubits, stats: dict,
                           phase_cell: List[complex]):
    """Erase every contiguous window of `items` (all supported inside
    `qubits`, <= 3 of them) whose dense composition is a global phase
    c*I — the block-level cancellations pairwise peephole can't see: a
    toffoli pair in its 15-op Clifford+T form, a conjugation sandwich
    closing over its own inverse, an uncompute block. Prefix-product
    scan: with P_j = U_j ... U_1, a window (i, j] composes to c*I iff
    P_i^dag P_j ~ c*I; greedy longest-window-first, re-scanned until
    dry. Exact-mode streams never reach here (fp products)."""
    qubits = tuple(sorted(qubits))
    k = len(qubits)
    idx = {q: j for j, q in enumerate(qubits)}
    dim = 1 << k
    changed = False
    while len(items) >= 2:
        pre = [np.eye(dim, dtype=np.complex128)]
        for op in items:
            pre.append(_embed(op, idx, k) @ pre[-1])
        hit = None
        for width in range(len(items), 1, -1):
            for i in range(len(items) - width + 1):
                m = pre[i].conj().T @ pre[i + width]
                c = np.trace(m) / dim
                if abs(abs(c) - 1.0) < 1e-9 and \
                        np.max(np.abs(m - c * np.eye(dim))) < 1e-9:
                    hit = (i, width, c)
                    break
            if hit is not None:
                break
        if hit is None:
            break
        i, width, c = hit
        items = items[:i] + items[i + width:]
        phase_cell[0] *= c
        stats["cancel3q"] += 1
        changed = True
    return items, changed


def _cancel_windows3(ops: List[GateOp], n: int, stats: dict,
                     phase_cell: List[complex]) -> List[GateOp]:
    """Pass 5: identity-window elimination over <= 3-qubit
    neighborhoods. Same concurrent-run collection discipline as
    _resynth2q but with a 3-qubit support budget; each run is scanned
    by _drop_identity_windows, and a rewritten run is accepted only
    when it prices no worse under the banded cost model (dropping ops
    can never add sweeps in practice — the guard is against a greedy
    band packer pathologically preferring the longer stream)."""
    out: List[GateOp] = []
    open_runs: List[dict] = []

    def flush(run):
        open_runs.remove(run)
        items = run["items"]
        if len(items) < 2:
            out.extend(items)
            return
        scratch = dict(stats)
        ph = [1.0 + 0.0j]
        new, changed = _drop_identity_windows(
            items, run["qubits"], scratch, ph)
        if not changed or _stream_cost(new, n) > _stream_cost(items, n):
            out.extend(items)
            return
        stats["cancel3q"] = scratch["cancel3q"]
        phase_cell[0] *= ph[0]
        out.extend(new)

    for op in ops:
        support = _all_qubits(op)
        eligible = _static(op) and _concrete(op) and 0 < len(support) <= 3
        touching = [r for r in open_runs if r["qubits"] & support]
        if not eligible:
            for r in list(touching):
                flush(r)
            if op.kind not in _STATIC_KINDS and not support:
                for r in list(open_runs):        # unknown claim
                    flush(r)
            out.append(op)
            continue
        union = set(support)
        for r in touching:
            union |= r["qubits"]
        if touching and len(union) <= 3:
            first = touching[0]
            for r in touching[1:]:               # merge overlapping runs
                first["items"].extend(r["items"])
                first["qubits"] |= r["qubits"]
                open_runs.remove(r)
            first["qubits"] = union
            first["items"].append(op)
        else:
            for r in list(touching):
                flush(r)
            open_runs.append({"qubits": set(support), "items": [op]})
    for r in list(open_runs):
        flush(r)
    return out


def _resynth2q(ops: List[GateOp], n: int, stats: dict,
               phase_cell: List[complex]) -> List[GateOp]:
    """Collect maximal runs whose support fits in one qubit pair (runs on
    disjoint pairs stay concurrently open; ops disjoint from every open
    run pass straight through) and KAK-resynthesize each run when the
    rewrite prices cheaper."""
    out: List[GateOp] = []
    open_runs: List[dict] = []      # {qubits: set, items: [GateOp]}

    def flush(run):
        open_runs.remove(run)
        new = _try_kak(run["items"], frozenset(run["qubits"]), n, stats,
                       phase_cell)
        out.extend(new if new is not None else run["items"])

    for op in ops:
        support = _all_qubits(op)
        eligible = _static(op) and _concrete(op) and 0 < len(support) <= 2
        touching = [r for r in open_runs if r["qubits"] & support]
        if not eligible:
            for r in list(touching):
                flush(r)
            if op.kind not in _STATIC_KINDS and not support:
                for r in list(open_runs):        # unknown claim
                    flush(r)
            out.append(op)
            continue
        union = set(support)
        for r in touching:
            union |= r["qubits"]
        if touching and len(union) <= 2:
            if len(touching) == 2:               # merge two 1q partials
                touching[0]["items"].extend(touching[1]["items"])
                touching[0]["qubits"] |= touching[1]["qubits"]
                open_runs.remove(touching[1])
            run = touching[0]
            run["qubits"] = union
            run["items"].append(op)
        else:
            for r in list(touching):
                flush(r)
            open_runs.append({"qubits": set(support), "items": [op]})
    for r in list(open_runs):
        flush(r)
    return out


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def _transpile_stretch(ops: List[GateOp], n: int, exact_only: bool,
                       stats: dict) -> List[GateOp]:
    cur = list(ops)
    phase = [1.0 + 0.0j]
    for _ in range(_FIXPOINT_ITERS):
        before = len(cur)
        snap = (stats["cancel"], stats["fold"], stats["identity"])
        cur = _peephole(cur, exact_only, stats, phase)
        if len(cur) == before and snap == (stats["cancel"], stats["fold"],
                                           stats["identity"]):
            break
    if not exact_only:
        cur = _merge1q(cur, stats, phase)
        cur = _resynth2q(cur, n, stats, phase)
        cur = _cancel_windows3(cur, n, stats, phase)
        for _ in range(_FIXPOINT_ITERS):
            before = len(cur)
            snap = (stats["cancel"], stats["fold"], stats["identity"])
            cur = _peephole(cur, exact_only, stats, phase)
            if len(cur) == before and snap == (stats["cancel"],
                                               stats["fold"],
                                               stats["identity"]):
                break
        cur = _merge1q(cur, stats, phase)
    if abs(phase[0] - 1.0) > _ATOL:
        # exact mode never accumulates phase (!= 1 products are rejected)
        stats["global_phase"] += 1
        cur.append(GateOp("diagonal", (0,), operand=np.array(
            [phase[0], phase[0]], dtype=np.complex128)))
    return cur


def transpile_ops(ops: Sequence[GateOp], num_qubits: int, *,
                  exact_only: bool = False) -> Tuple[List[GateOp], dict]:
    """Rewrite an op stream; returns (new_ops, report). Dynamic/noise ops
    are barriers: each measurement-free stretch is rewritten
    independently, barriers keep their positions."""
    ops = list(ops)
    stats = {"cancel": 0, "identity": 0, "global_phase": 0, "fold": 0,
             "merge1q": 0, "resynth2q": 0, "cancel3q": 0}
    out: List[GateOp] = []
    stretch: List[GateOp] = []
    nstretches = 0
    for op in ops:
        if _static(op):
            stretch.append(op)
            continue
        if stretch:
            nstretches += 1
            out.extend(_transpile_stretch(stretch, num_qubits, exact_only,
                                          stats))
            stretch = []
        out.append(op)
    if stretch:
        nstretches += 1
        out.extend(_transpile_stretch(stretch, num_qubits, exact_only,
                                      stats))
    report = {
        "ops_in": len(ops),
        "ops_out": len(out),
        "stretches": nstretches,
        "exact_only": bool(exact_only),
        "changed": any(v > 0 for v in stats.values()),
        "passes": dict(stats),
    }
    return out, report


def transpile(circuit: Circuit, *,
              exact_only: bool = False) -> Tuple[Circuit, dict]:
    """Rewrite a Circuit into an equivalent cheaper one. The result is a
    fresh Circuit over the same qubit count; the input is not mutated."""
    new_ops, report = transpile_ops(circuit.ops, circuit.num_qubits,
                                    exact_only=exact_only)
    if not report["changed"]:
        return circuit, report
    out = Circuit(circuit.num_qubits)
    out.ops = list(new_ops)
    out._transpile_report = report
    return out, report


def transpile_cached(circuit: Circuit, *,
                     exact_only: bool = False) -> Tuple[Circuit, dict]:
    """transpile() memoized per circuit (Circuit._add clears the memo on
    mutation, which is exactly the invalidation we need). The memo is
    NOT Circuit._compiled: planning-only surfaces (explain, plan_stats)
    transpile, and they contract to leave the compiled-program cache
    empty."""
    key = ("transpiled", bool(exact_only))
    cache = getattr(circuit, "_transpiled", None)
    if cache is None:               # circuits from older pickles
        cache = circuit._transpiled = {}
    hit = cache.get(key)
    if hit is None:
        hit = transpile(circuit, exact_only=exact_only)
        cache[key] = hit
    return hit


def stream_cost(circuit: Circuit) -> Tuple[Optional[int], int]:
    """(banded full-state passes | None for noise circuits, op count) —
    the comparison key maybe_transpile/'auto' routes on."""
    ops = list(circuit.ops)
    if any(op.kind == "superop" for op in ops):
        return (None, len(ops))
    flat = CC.flatten_ops(ops, circuit.num_qubits, False)
    try:
        passes = F.plan_stats(F.plan(flat, circuit.num_qubits))[
            "full_state_passes"]
    except Exception:
        return (None, len(ops))
    return (passes, len(flat))


def maybe_transpile(circuit: Circuit) -> Tuple[Circuit, Optional[dict]]:
    """Route a circuit through the transpiler per QUEST_TRANSPILE:
    '0' never rewrites; '1' takes the rewritten stream whenever it
    changed; 'auto' takes it only when STRICTLY cheaper (banded
    full-state passes, then op count) — the incumbent raw stream wins
    ties, mirroring the planner's discipline."""
    from quest_tpu.env import knob_value
    knob = knob_value("QUEST_TRANSPILE")
    if knob == "0":
        return circuit, None
    tc, report = transpile_cached(circuit)
    if not report["changed"]:
        return circuit, report
    if knob == "1":
        return tc, report
    raw_p, raw_ops = stream_cost(circuit)
    new_p, new_ops = stream_cost(tc)
    if raw_p is not None and new_p is not None:
        take = (new_p, new_ops) < (raw_p, raw_ops)
    else:
        take = new_ops < raw_ops
    return (tc, report) if take else (circuit, report)
