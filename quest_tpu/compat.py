"""JAX version compatibility shims.

The engines are written against the current public names; older JAX
releases (this container ships 0.4.37) spell several of them differently.
Every version-sensitive lookup lives HERE, resolved once at import, so an
API rename is a one-line fix instead of a grep across engines:

  shard_map       jax.shard_map (new) / jax.experimental.shard_map (old,
                  where the replication check is spelled `check_rep`;
                  SAME polarity as the new `check_vma` — True enables
                  the check on both APIs, so the shim passes the value
                  through unchanged)
  enable_x64      jax.enable_x64 (new) / jax.experimental.enable_x64
  Pallas TPU      pltpu.MemorySpace.{HBM,VMEM} (new) /
                  pltpu.TPUMemorySpace.{ANY,VMEM} (old — ANY means
                  "compiler-chosen, HBM-resident for large buffers")
                  and CompilerParams / TPUCompilerParams
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):
    def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
else:  # jax <= 0.4.x
    def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
        from jax.experimental.shard_map import shard_map as _sm
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=check_vma)

if hasattr(jax, "enable_x64"):
    enable_x64 = jax.enable_x64
else:  # jax <= 0.4.x
    from jax.experimental import enable_x64  # noqa: F401


def enable_cpu_collectives() -> bool:
    """Switch the CPU backend's cross-process collectives onto gloo,
    returning whether the option exists. Must run BEFORE
    jax.distributed.initialize. jax 0.4.x ships a CPU backend whose
    default collectives implementation is 'none' — a multi-process
    global mesh then fails at dispatch with 'Multiprocess computations
    aren't implemented on the CPU backend' (the tier-1 env-failure of
    tests/test_multihost.py). Newer releases select gloo automatically
    and drop the config knob, hence the hasattr guard."""
    # probe by update, not hasattr: jax.config only materializes option
    # attributes on first read, so hasattr is False for never-read
    # options even when the knob exists (measured on 0.4.37)
    for key, value in (("jax_cpu_collectives_implementation", "gloo"),
                       ("jax_cpu_enable_gloo_collectives", True)):
        try:
            jax.config.update(key, value)
            return True
        except (AttributeError, KeyError, ValueError):
            continue
    return False


def pallas_tpu_names():
    """(memory-space enum with .HBM/.VMEM attributes, CompilerParams
    class) for the installed Pallas TPU module."""
    from jax.experimental.pallas import tpu as pltpu

    params = getattr(pltpu, "CompilerParams", None)
    if params is None:
        params = pltpu.TPUCompilerParams
    spaces = getattr(pltpu, "MemorySpace", None)
    if spaces is not None and hasattr(spaces, "HBM"):
        return spaces, params

    class _Spaces:
        HBM = pltpu.TPUMemorySpace.ANY
        VMEM = pltpu.TPUMemorySpace.VMEM

    return _Spaces, params
