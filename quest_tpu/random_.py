"""Seeded RNG for measurement outcomes.

The reference uses a globally-seeded Mersenne Twister (mt19937ar.c), seeded
by time+pid by default, with the seed broadcast to all MPI ranks so every
rank draws identical outcomes (QuEST_cpu_distributed.c:1321-1332).

Here the native host runtime (native/quest_host.cpp, reference-exact
init_by_array + genrand_real1) plays that role: with identical seeds the
outcome stream matches the reference binary bit-for-bit. If no C++
toolchain is available it falls back to numpy's MT19937 (same generator,
different seeding schedule — still deterministic per seed, without
cross-binary parity). `jax.random` keys serve fully-traced in-jit
measurement instead (quest_tpu.measurement.measure_functional).
"""

from __future__ import annotations

import os
import time

import numpy as np

from quest_tpu import native

_np_rng = None
_use_native = None


def seed_quest(seeds) -> None:
    """Seed the measurement RNG from a list of ints (ref seedQuEST,
    QuEST_common.c:207-213)."""
    global _np_rng, _use_native
    seeds = [int(s) for s in np.asarray(seeds, dtype=np.uint64)]
    _use_native = native.init_by_array(seeds)
    if not _use_native:
        import warnings
        warnings.warn(
            "quest_tpu native RNG unavailable (no C++ toolchain?): falling "
            "back to numpy MT19937 — deterministic per seed, but outcome "
            "streams will not match the reference binary bit-for-bit",
            RuntimeWarning, stacklevel=2)
        _np_rng = np.random.Generator(np.random.MT19937(seeds))


def seed_quest_default() -> None:
    """Seed from time + pid (ref getQuESTDefaultSeedKey,
    QuEST_common.c:181-203)."""
    seed_quest([int(time.time() * 1000) & 0xFFFFFFFF, os.getpid()])


def uniform() -> float:
    """One uniform draw in [0, 1] (ref genrand_real1)."""
    if _use_native is None:
        seed_quest_default()
    if _use_native:
        return native.genrand_real1()
    return float(_np_rng.random())


def uint32() -> int:
    """One full 32-bit word from the seeded stream (ref genrand_int32).

    The whole word, not `int(uniform() * 2**31)` — that mapping wastes
    half the seed space (bit 31 always 0) and collides distinct stream
    states onto one value; PRNGKey derivation (measurement.sample) needs
    the full-entropy word."""
    if _use_native is None:
        seed_quest_default()
    if _use_native:
        return native.genrand_int32()
    return int(_np_rng.integers(0, 1 << 32, dtype=np.uint64))
