"""Seeded RNG for measurement outcomes.

The reference uses a globally-seeded Mersenne Twister (mt19937ar.c), seeded
by time+pid by default, with the seed broadcast to all MPI ranks so every
rank draws identical outcomes (QuEST_cpu_distributed.c:1321-1332). Here a
module-level numpy Generator plays that role for the eager API (all devices
see the same host, so the identical-outcome invariant is structural), and
`jax.random` keys are used for fully-traced in-jit measurement.
"""

from __future__ import annotations

import os
import time

import numpy as np

_rng = None


def seed_quest(seeds) -> None:
    """Seed the measurement RNG from a list of ints (ref seedQuEST,
    QuEST_common.c:207-213)."""
    global _rng
    _rng = np.random.Generator(np.random.MT19937(list(np.asarray(seeds, dtype=np.uint64))))


def seed_quest_default() -> None:
    """Seed from time + pid (ref getQuESTDefaultSeedKey, QuEST_common.c:181-203)."""
    seed_quest([int(time.time() * 1000) & 0xFFFFFFFF, os.getpid()])


def uniform() -> float:
    """One uniform draw in [0, 1]."""
    global _rng
    if _rng is None:
        seed_quest_default()
    return float(_rng.random())
