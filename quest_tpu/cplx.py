"""Host-side complex packing.

The framework stores amplitudes as split (re, im) float planes throughout
(see quest_tpu/state.py) and never materializes complex-dtype device
buffers: the axon TPU runtime cannot move complex arrays across the
host<->device boundary and fails on complex constants baked into programs
(one failure can poison the process). All complex data therefore enters
programs as (re, im) float pairs produced by `pack`; results leave as
float planes reassembled on the host (state.to_dense).

Incidentally this matches the reference's storage model, which also keeps
real and imaginary parts in separate arrays (QuEST.h ComplexArray).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def pack(x) -> Tuple[np.ndarray, np.ndarray]:
    """Host side: complex ndarray -> contiguous (re, im) float64 pair,
    safe to pass as jit arguments or bake into traced programs."""
    x = np.asarray(x)
    # np.array (not ascontiguousarray — that promotes 0-d to (1,))
    return (np.array(x.real, dtype=np.float64, order="C"),
            np.array(x.imag, dtype=np.float64, order="C"))
