"""Complex-value plumbing that never transfers complex data host<->device.

Some TPU runtimes (the axon PJRT plugin in particular) cannot move
complex-dtype buffers across the host<->device boundary, and fail on complex
constants baked into programs — while complex arithmetic on device-produced
values works fine. The framework therefore follows one convention:

  * complex data ENTERS a program as a (re, im) float pair, reconstructed
    on device with `lax.complex` (see `pack` / `unpack`);
  * complex data LEAVES via jnp.real/jnp.imag splits fetched as floats
    (see quest_tpu.host.fetch);
  * traced code never writes complex literals (no `1j`, no
    `jnp.zeros(..., complex)`) — use the constructors below.

Incidentally this matches the reference's storage model, which also keeps
real and imaginary parts in separate arrays (QuEST.h ComplexArray).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np
from jax import lax

from quest_tpu.precision import real_dtype_of as real_dtype


def pack(x) -> Tuple[np.ndarray, np.ndarray]:
    """Host side: complex ndarray -> contiguous (re, im) float64 pair,
    safe to pass as jit arguments."""
    x = np.asarray(x)
    # np.array (not ascontiguousarray — that promotes 0-d to (1,))
    return (np.array(x.real, dtype=np.float64, order="C"),
            np.array(x.imag, dtype=np.float64, order="C"))


def unpack(pair, cdtype):
    """Traced: (re, im) floats -> complex array of dtype `cdtype`."""
    rdt = real_dtype(cdtype)
    re = jnp.asarray(pair[0], dtype=rdt)
    im = jnp.asarray(pair[1], dtype=rdt)
    return lax.complex(re, im)


def make(re, im):
    """Traced: elementwise complex from float re/im (dtype follows inputs)."""
    re = jnp.asarray(re)
    im = jnp.asarray(im, dtype=re.dtype)
    return lax.complex(re, im)


def czeros(shape, cdtype):
    rdt = real_dtype(cdtype)
    z = jnp.zeros(shape, dtype=rdt)
    return lax.complex(z, z)


def cones(shape, cdtype):
    rdt = real_dtype(cdtype)
    return lax.complex(jnp.ones(shape, dtype=rdt), jnp.zeros(shape, dtype=rdt))


def expi(theta):
    """e^{i theta} for real traced theta, without complex literals."""
    theta = jnp.asarray(theta)
    return lax.complex(jnp.cos(theta), jnp.sin(theta))


def scale_i(x):
    """Multiply by the imaginary unit: i*x = complex(-im, re)."""
    return lax.complex(-jnp.imag(x), jnp.real(x))
