"""Device->host transfer helpers.

Some TPU runtimes (notably the axon PJRT plugin used in this environment)
cannot transfer complex-dtype buffers to the host, while float transfers
work. Every host fetch of complex amplitudes therefore goes through a
jitted split into (real, imag) float pairs, reassembled in numpy.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def _split(x):
    return jnp.real(x), jnp.imag(x)


def fetch(x) -> np.ndarray:
    """device_get that is safe for complex arrays on any backend."""
    x = jnp.asarray(x)
    if not jnp.issubdtype(x.dtype, jnp.complexfloating):
        return np.asarray(jax.device_get(x))
    re, im = _split(x)
    re = np.asarray(jax.device_get(re))
    im = np.asarray(jax.device_get(im))
    return re + 1j * im


def fetch_scalar(x) -> complex:
    return complex(fetch(x).reshape(()))
