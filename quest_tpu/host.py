"""Native host (CPU) circuit engine: cache-blocked C++ kernel execution.

The framework's counterpart of the reference's CPU backend
(QuEST_cpu.c) — but planned for the host memory hierarchy instead of
translated: consecutive gates whose TARGETS all sit below a block
boundary B are grouped, and the native runner (native/host_kernels.cpp)
applies the whole group to one 2^B-amplitude block while it is resident
in L2 before moving on. A 16-gate layer on low qubits costs ONE
read+write sweep of the state instead of sixteen — the host analogue of
the TPU band-fusion engine (quest_tpu/ops/fusion.py), and the reason
this engine beats the reference's per-gate sweeps (QuEST_cpu.c:1656-1713
touches the full state once per gate) on the same silicon.

This engine exists for the CPU-fallback path (bench.py's ladder when no
TPU is reachable) and as a fast host-side oracle; the TPU engines remain
the primary compute path. Supported op kinds after flatten_ops:
matrix / diagonal / parity / allones (superops arrive pre-flattened as
matrix ops) on the static path (compile_circuit_host); dynamic
circuits — mid-circuit measurement + classical feedback, statevector
AND density — run natively through compile_circuit_host_measured.
Traced operands and over-wide targets raise HostEngineUnsupported so
callers fall back loudly.
"""

from __future__ import annotations

import ctypes
from typing import List, Sequence, Tuple

import numpy as np

from quest_tpu import native

# block-size default (QUEST_HOST_BLOCK) lives in the knob registry
# (env.KNOBS): 2^17 amps x 2 planes x 4 B = 1 MiB, inside a 2 MiB L2.
# Measured on the bench circuit (16 rx over qubits 1..16 @ 24q):
# 2^17 -> 140 gates/s, 2^16 -> 114, 2^18 -> 130, 2^15 -> 121
# (reference CPU build: 8.98)
_MAX_TARGETS = 6


class HostEngineUnsupported(RuntimeError):
    """Raised when a circuit cannot run on the native host engine
    (traced operands, too many targets, or no native lib — dynamic ops
    on the STATIC entry point belong on compile_circuit_host_measured);
    callers fall back to an XLA engine and report the fallback."""


def _bind(lib: ctypes.CDLL) -> None:
    for name, fp in (("qh_run_program_f32", ctypes.c_float),
                     ("qh_run_program_f64", ctypes.c_double)):
        fn = getattr(lib, name)
        fn.argtypes = [
            ctypes.POINTER(fp), ctypes.POINTER(fp), ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
            ctypes.c_int, ctypes.c_int,
        ]
        fn.restype = ctypes.c_int
    for name, fp in (("qh_prob0_sv_f32", ctypes.c_float),
                     ("qh_prob0_sv_f64", ctypes.c_double)):
        fn = getattr(lib, name)
        fn.argtypes = [ctypes.POINTER(fp), ctypes.POINTER(fp),
                       ctypes.c_int, ctypes.c_int]
        fn.restype = ctypes.c_double
    for name, fp in (("qh_collapse_sv_f32", ctypes.c_float),
                     ("qh_collapse_sv_f64", ctypes.c_double),
                     ("qh_collapse_dm_f32", ctypes.c_float),
                     ("qh_collapse_dm_f64", ctypes.c_double)):
        fn = getattr(lib, name)
        fn.argtypes = [ctypes.POINTER(fp), ctypes.POINTER(fp),
                       ctypes.c_int, ctypes.c_int, ctypes.c_int,
                       ctypes.c_double]
        fn.restype = None
    for name, fp in (("qh_prob0_dm_f32", ctypes.c_float),
                     ("qh_prob0_dm_f64", ctypes.c_double)):
        fn = getattr(lib, name)
        fn.argtypes = [ctypes.POINTER(fp), ctypes.c_int, ctypes.c_int]
        fn.restype = ctypes.c_double


_lib = None
_lib_tried = False


def _load():
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    _lib = native.load_with(_bind)
    return _lib


def available() -> bool:
    return _load() is not None


def _as_concrete(operand) -> np.ndarray:
    try:
        arr = np.asarray(operand)
    except Exception as e:      # jax TracerArrayConversionError et al.
        raise HostEngineUnsupported(f"traced operand ({type(e).__name__})")
    if arr.dtype == object or not np.issubdtype(arr.dtype, np.number):
        raise HostEngineUnsupported("traced/non-numeric operand")
    return arr.astype(np.complex128)


def _encode(flat_ops, n: int):
    """(prog int32[], coef float64[], groups int32[], block_log) for the
    native runner. Raises HostEngineUnsupported on anything the C side
    does not implement."""
    from quest_tpu.env import knob_value
    block_log = min(knob_value("QUEST_HOST_BLOCK"), n)

    prog: List[int] = []
    coef: List[float] = []
    records = []        # (max_target, prog record) per gate

    def emit(kind, targets, controls, cstates, values):
        coff = len(coef)
        coef.extend(values)
        rec = [kind, len(targets), len(controls), *targets, *controls,
               *cstates, coff]
        records.append((max(targets), rec))

    for op in flat_ops:
        if op.kind in ("measure", "measure_dm", "classical"):
            raise HostEngineUnsupported(f"dynamic op {op.kind!r}")
        controls = tuple(int(c) for c in op.controls)
        cstates = tuple(int(s) for s in (op.cstates or (1,) * len(controls)))
        targets = tuple(int(t) for t in op.targets)
        if op.kind == "matrix":
            m = _as_concrete(op.operand).reshape(1 << len(targets),
                                                 1 << len(targets))
            if len(targets) > _MAX_TARGETS:
                raise HostEngineUnsupported(
                    f"{len(targets)}-target matrix (max {_MAX_TARGETS})")
            vals = np.empty(2 * m.size)
            vals[0::2] = m.real.ravel()
            vals[1::2] = m.imag.ravel()
            emit(0, targets, controls, cstates, vals.tolist())
        elif op.kind == "diagonal":
            d = _as_concrete(op.operand).reshape(-1)
            if d.size != 1 << len(targets):
                raise HostEngineUnsupported("diagonal size mismatch")
            if len(targets) > _MAX_TARGETS:
                raise HostEngineUnsupported(
                    f"{len(targets)}-target diagonal (max {_MAX_TARGETS})")
            vals = np.empty(2 * d.size)
            vals[0::2] = d.real
            vals[1::2] = d.imag
            emit(1, targets, controls, cstates, vals.tolist())
        elif op.kind == "allones":
            # phase `term` where ALL listed qubits are 1 — matches
            # apply_phase_on_all_ones: a [1, term] diagonal on targets[0]
            # controlled on the rest (circuit._apply_one ignores
            # op.controls for this kind, as does the XLA path)
            term = complex(_as_concrete(op.operand).reshape(()))
            qubits = targets
            emit(1, (qubits[0],), qubits[1:], (1,) * (len(qubits) - 1),
                 [1.0, 0.0, term.real, term.imag])
        elif op.kind == "parity":
            # exp(-i angle/2 * Z..Z): even-parity factor exp(-i a/2),
            # odd-parity exp(+i a/2)  (ops/apply.py:apply_parity_phase)
            a = float(np.asarray(op.operand).reshape(()))
            f0 = complex(np.cos(a / 2), -np.sin(a / 2))
            f1 = complex(np.cos(a / 2), +np.sin(a / 2))
            emit(2, targets, (), (), [f0.real, f0.imag, f1.real, f1.imag])
        else:
            raise HostEngineUnsupported(f"op kind {op.kind!r}")

    # greedy blocked grouping: gates whose targets all sit below the block
    # boundary share one L2-resident sweep; others run as full sweeps
    groups: List[int] = []
    cur = 0             # pending blocked-group size
    for max_t, rec in records:
        # parity is elementwise on absolute indices — blockable at any
        # target position; matrix/diag need their targets inside the block
        blockable = rec[0] == 2 or max_t < block_log
        if blockable:
            cur += 1
        else:
            if cur:
                groups += [cur, 1]
                cur = 0
            groups += [1, 0]
        prog.extend(rec)
    if cur:
        groups += [cur, 1]

    return (np.asarray(prog, dtype=np.int32),
            np.asarray(coef, dtype=np.float64),
            np.asarray(groups, dtype=np.int32),
            block_log)


def plan_summary(flat_ops, n: int) -> str:
    """Human-readable sweep plan (for Circuit.explain): how many full
    state sweeps the blocked schedule costs vs the per-gate count."""
    prog, coef, groups, block_log = _encode(flat_ops, n)
    ngates = 0
    sweeps = 0
    it = iter(groups.tolist())
    for count, blocked in zip(it, it):
        ngates += count
        sweeps += 1 if blocked else count
    return (f"host engine: {ngates} gates in {sweeps} state sweep(s) "
            f"(block=2^{block_log} amps)")


def compile_circuit_host(ops, n: int, density: bool, iters: int = 1):
    """step(state) -> state running the whole (flattened) circuit through
    the native blocked runner, `iters` times per call. `state` is the
    (2, 2^n) split-plane register (numpy or any array-protocol object;
    jax host arrays convert on first call); float32 and float64 planes
    both dispatch to matching kernels. The returned array is updated
    in place across calls (donation semantics — the input buffer is the
    output buffer once it is a writable numpy array)."""
    from quest_tpu.circuit import flatten_ops

    lib = _load()
    if lib is None:
        raise HostEngineUnsupported("native host library unavailable")
    flat = flatten_ops(ops, n, density)
    if not flat:
        return lambda state: state
    prog, coef, groups, block_log = _encode(flat, n)

    def step(state):
        arr = _as_planes(state, n)
        _run_native(lib, arr, n, prog, coef, groups, block_log, iters)
        return arr

    return step


def _as_planes(state, n: int) -> np.ndarray:
    arr = np.asarray(state)
    if arr.shape != (2, 1 << n):
        raise ValueError(f"state shape {arr.shape} != (2, {1 << n})")
    if arr.dtype not in (np.float32, np.float64):
        arr = arr.astype(np.float32)
    if not (arr.flags.c_contiguous and arr.flags.writeable):
        arr = np.array(arr)         # ONE copy: contiguous + writable
    return arr


def _run_native(lib, arr, n, prog, coef, groups, block_log, iters):
    if arr.dtype == np.float32:
        fn, fp = lib.qh_run_program_f32, ctypes.c_float
    else:
        fn, fp = lib.qh_run_program_f64, ctypes.c_double
    rc = fn(arr[0].ctypes.data_as(ctypes.POINTER(fp)),
            arr[1].ctypes.data_as(ctypes.POINTER(fp)), n,
            prog.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(prog),
            coef.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            groups.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(groups) // 2, block_log, iters)
    if rc != 0:
        raise RuntimeError(f"native host runner failed (rc={rc})")


def _measure_native(lib, arr, n: int, qubit: int, draw,
                    density: bool = False) -> int:
    """Native measurement MIRRORING the eager API's logic
    (measurement.measure_with_stats): native probability pass, then the
    outcome draw happens HERE — `draw()` is only called when the
    outcome is not eps-forced, exactly like the eager path, so
    identically-seeded host and eager trajectories consume the same
    MT19937 stream — then a native collapse pass (1/sqrt(prob) for
    statevectors, 1/prob both-space for density registers). Returns the
    outcome."""
    from quest_tpu import precision
    eps = float(precision.real_eps(arr.dtype))
    kind = "dm" if density else "sv"
    bits = "f32" if arr.dtype == np.float32 else "f64"
    fp = ctypes.c_float if arr.dtype == np.float32 else ctypes.c_double
    p_fn = getattr(lib, f"qh_prob0_{kind}_{bits}")
    c_fn = getattr(lib, f"qh_collapse_{kind}_{bits}")
    re_p = arr[0].ctypes.data_as(ctypes.POINTER(fp))
    im_p = arr[1].ctypes.data_as(ctypes.POINTER(fp))
    if density:
        p0 = float(p_fn(re_p, n, qubit))
    else:
        p0 = float(p_fn(re_p, im_p, n, qubit))
    if p0 < eps:
        outcome = 1
    elif 1.0 - p0 < eps:
        outcome = 0
    else:
        outcome = int(float(draw()) > p0)
    prob = max(p0 if outcome == 0 else 1.0 - p0, eps)
    c_fn(re_p, im_p, n, qubit, outcome, prob)
    return outcome


def compile_circuit_host_measured(ops, n: int, density: bool = False):
    """DYNAMIC circuit on the native host engine: step(state, draws=None)
    -> (state, outcomes int array). Measurement-free stretches run
    through the blocked native runner; measurements collapse natively
    (qh_prob0_*/qh_collapse_*); classical feedback evaluates on the host and
    conditionally runs its inner ops as their own native program.

    `draws` supplies the per-measurement uniforms; default draws from
    quest_tpu.random_ (the reference-exact MT19937 when the native
    library is loaded) — the SAME stream the eager measurement API uses
    (measurement.measure_with_stats), so identically-seeded host and
    eager trajectories match outcome-for-outcome. Density registers
    measure natively too (diagonal probability + both-space 1/prob
    collapse, qh_prob0_dm_* / qh_collapse_dm_*)."""
    from quest_tpu.circuit import flatten_ops

    lib = _load()
    if lib is None:
        raise HostEngineUnsupported("native host library unavailable")
    flat = flatten_ops(ops, n, density)

    # split at dynamic barriers; encode each static piece (and each
    # classical op's inner gate list) as its own native program
    def encode(piece):
        if not piece:
            return None
        prog, coef, groups, block_log = _encode(piece, n)
        return (prog, coef, groups, block_log)

    program = []        # ("run", enc) | ("measure", qubit) |
                        # ("classical", conds, enc)
    cur = []
    n_meas = 0
    for op in flat:
        if op.kind in ("measure", "measure_dm"):
            # flatten_ops tags every measure as measure_dm iff density;
            # the executor closes over `density` (one source of truth)
            program.append(("run", encode(cur)))
            cur = []
            program.append(("measure", int(op.targets[0])))
            n_meas += 1
        elif op.kind == "classical":
            program.append(("run", encode(cur)))
            cur = []
            inners, conds = op.operand
            program.append(("classical", tuple(conds),
                            encode(list(inners))))
        else:
            cur.append(op)
    program.append(("run", encode(cur)))
    if not n_meas:
        from quest_tpu.validation import QuESTError
        raise QuESTError(
            "Invalid operation: compile_circuit_host_measured requires "
            "at least one mid-circuit measurement; use "
            "compile_circuit_host instead.")

    def step(state, draws=None):
        from quest_tpu import random_ as R
        arr = _as_planes(state, n)
        it = iter(draws) if draws is not None else None

        def draw():
            if it is None:
                return R.uniform()
            try:
                return next(it)
            except StopIteration:
                raise ValueError(
                    f"draws exhausted: this circuit has {n_meas} "
                    f"measurements (forced outcomes consume none)")

        outcomes = []
        for el in program:
            if el[0] == "run":
                if el[1] is not None:
                    prog, coef, groups, block_log = el[1]
                    _run_native(lib, arr, n, prog, coef, groups,
                                block_log, 1)
            elif el[0] == "measure":
                outcomes.append(_measure_native(lib, arr, n, el[1],
                                                draw, density=density))
            else:                           # classical feedback
                _, conds, enc = el
                if all(outcomes[i] == want for i, want in conds) \
                        and enc is not None:
                    prog, coef, groups, block_log = enc
                    _run_native(lib, arr, n, prog, coef, groups,
                                block_log, 1)
        return arr, np.asarray(outcomes, dtype=np.int32)

    return step
