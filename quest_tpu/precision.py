"""Precision policy.

The reference fixes precision at compile time (QuEST/include/QuEST_precision.h:
QuEST_PREC in {1,2,4} -> qreal in {float, double, long double}, with
REAL_EPS = 1e-5 / 1e-13 / 1e-14). On TPU, precision is a runtime dtype choice:
complex64 is the fast native path (f32 pairs on the VPU/MXU), complex128 is
available for CPU verification and high-accuracy runs (requires
jax_enable_x64). There is no quad-precision analogue.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

_COMPLEX_DTYPES = (jnp.complex64, jnp.complex128)

# Validation/comparison tolerance per precision, mirroring the role of the
# reference's REAL_EPS (QuEST_precision.h:35,48).
_REAL_EPS = {
    np.dtype(np.complex64): 1e-5,
    np.dtype(np.complex128): 1e-13,
    np.dtype(np.float32): 1e-5,
    np.dtype(np.float64): 1e-13,
}

_default_dtype = jnp.complex64


def set_default_dtype(dtype) -> None:
    """Set the default amplitude dtype for newly created Quregs."""
    global _default_dtype
    dtype = jnp.dtype(dtype)
    if dtype not in (np.dtype(np.complex64), np.dtype(np.complex128)):
        raise ValueError(f"amplitude dtype must be complex64 or complex128, got {dtype}")
    if dtype == np.dtype(np.complex128) and not jax.config.jax_enable_x64:
        raise ValueError("complex128 requires jax_enable_x64=True")
    _default_dtype = dtype


def get_default_dtype():
    return _default_dtype


_matmul_precision = None  # lazily resolved from env on first use


def set_matmul_precision(p) -> None:
    """Set the lax.Precision used for every state-amplitude contraction
    (band matmuls, many-target gates, superoperators). Accepts a
    jax.lax.Precision or one of 'default' | 'high' | 'highest'.

    The value is read at TRACE time: Circuit keys its compiled-program
    cache on it (so new compiled()/compiled_fused() calls see a change),
    but already-returned step functions keep the precision they were
    traced with."""
    global _matmul_precision
    if isinstance(p, str):
        # the knob registry's parser is the ONE string validator
        # (env.KNOBS; quest-lint QL004)
        from quest_tpu.env import KNOBS
        p = KNOBS["QUEST_MATMUL_PRECISION"].parse(p)
    _matmul_precision = p


def matmul_precision():
    """lax.Precision for state-amplitude contractions. HIGHEST (6-pass
    bf16 — bit-exact f32) is the default: TPU dots otherwise run single
    bf16 passes and total probability drifts ~1e-3. 'high' (3-pass) keeps
    ~f32 accuracy on well-conditioned unitaries at up to 2x the MXU
    throughput on compute-bound circuits; opt in via
    QUEST_MATMUL_PRECISION=high or set_matmul_precision."""
    global _matmul_precision
    if _matmul_precision is None:
        from quest_tpu.env import knob_value
        set_matmul_precision(knob_value("QUEST_MATMUL_PRECISION"))
    return _matmul_precision


_CACHE_STATS = {"dir": None}
_cache_listener_installed = False


def _cache_counters():
    """The structured persistent-cache tallies: counters
    `compile_cache_hits` / `compile_cache_misses` in the serving metrics
    registry (quest_tpu.serve.metrics.REGISTRY — stdlib-only, safe to
    import from here). What used to be a stderr-scrape-only summary is
    now programmatically readable: `serve.metrics.snapshot()` carries
    the tallies, and the stderr lines below are DERIVED from these
    counters rather than a private dict."""
    from quest_tpu.serve import metrics as M
    return (M.REGISTRY.counter("compile_cache_hits"),
            M.REGISTRY.counter("compile_cache_misses"))


def _install_cache_listener() -> None:
    """Register a jax monitoring listener that tallies persistent-cache
    hits/misses into serve.metrics counters and logs them on stderr:
    every MISS is announced as it happens (a miss is when you pay the
    compile — the f64-26q warmup is ~297 s on chip), hits are counted
    and summarized at exit so repeat bench runs show what the cache
    saved without per-dispatch spam. Left installed for the process
    lifetime (jax 0.4.x has no public unregister), like
    analysis.audit.CompileAuditor's listener."""
    global _cache_listener_installed
    if _cache_listener_installed:
        return
    import atexit
    import sys
    hits, misses = _cache_counters()

    from jax._src import monitoring

    def on_event(event: str, **kw) -> None:
        if event.endswith("/cache_hits"):
            hits.inc()
            if hits.value == 1:
                print(f"[quest_tpu] compile cache HIT "
                      f"({_CACHE_STATS['dir']})", file=sys.stderr,
                      flush=True)
        elif event.endswith("/cache_misses"):
            misses.inc()
            print(f"[quest_tpu] compile cache MISS "
                  f"#{misses.value} (compiling; cached for "
                  f"the next run)", file=sys.stderr, flush=True)

    monitoring.register_event_listener(on_event)

    def summary() -> None:
        if hits.value or misses.value:
            print(f"[quest_tpu] compile cache: {hits.value} "
                  f"hit(s), {misses.value} miss(es) "
                  f"({_CACHE_STATS['dir']})", file=sys.stderr, flush=True)

    atexit.register(summary)
    _cache_listener_installed = True


def enable_compile_cache(path: str = None,
                         min_compile_secs: float = 1.0) -> None:
    """Turn on JAX's persistent compile cache (one shared location for the
    test suite, bench, probes and the driver entry points — circuit
    programs are compile-dominated on first run). The default location
    is `.jax_cache` under the repo so the cache survives /tmp cleanups
    and rides along with checkouts; override with `path` or the
    QUEST_COMPILE_CACHE_DIR knob (docs/CONFIG.md). Hits/misses tally
    into the `compile_cache_hits`/`compile_cache_misses` counters of
    `quest_tpu.serve.metrics` (programmatically readable via
    `metrics.snapshot()`) and are logged on stderr, derived from those
    counters (_install_cache_listener)."""
    import os

    import jax
    if path is None:
        from quest_tpu.env import knob_value
        path = knob_value("QUEST_COMPILE_CACHE_DIR")
        if path is None:
            repo = os.path.dirname(os.path.dirname(os.path.abspath(
                __file__)))
            path = os.path.join(repo, ".jax_cache")
            # the repo default only makes sense for checkout use; an
            # INSTALLED package would resolve into site-packages —
            # fall back to the old always-writable /tmp location
            # rather than silently losing persistence (or polluting
            # site-packages)
            try:
                os.makedirs(path, exist_ok=True)
                writable = os.access(path, os.W_OK)
            except OSError:
                writable = False
            if not writable:
                import sys
                import tempfile
                path = os.path.join(tempfile.gettempdir(),
                                    "jax_cache_quest_tpu")
                print(f"[quest_tpu] repo cache dir not writable; "
                      f"compile cache at {path}", file=sys.stderr)
    _CACHE_STATS["dir"] = path
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      min_compile_secs)
    _install_cache_listener()


def accum_dtype(plane_dtype=None):
    """Accumulator dtype for full-register reductions (norms, overlaps,
    probability sums, sampling CDFs). The reference Kahan-sums its f64
    reductions (QuEST_cpu_distributed.c:64-117); the TPU-native analogue
    is to accumulate in f64 regardless of the plane dtype — the convert
    fuses into the reduce, so nothing f64-sized is ever materialized.
    Falls back to the plane dtype when x64 is disabled (then the chunked
    CDF in measurement.py still bounds the error pairwise)."""
    if jax.config.jax_enable_x64:
        return np.dtype(np.float64)
    return np.dtype(plane_dtype) if plane_dtype is not None else np.dtype(np.float32)


def real_eps(dtype) -> float:
    """Numerical tolerance for the given amplitude dtype."""
    return _REAL_EPS[np.dtype(dtype)]


def real_dtype_of(dtype):
    """The real scalar dtype paired with a complex amplitude dtype
    (host-side mapping; never touches the device). Anything outside the
    two supported tiers is rejected explicitly — in particular a
    quad/complex256 request, which the framework REFUSES by policy
    (docs/PRECISION.md: TPU f64 is already software-emulated and the
    reference's own GPU build lacks the tier too)."""
    d = np.dtype(dtype)
    if d == np.dtype(np.complex64):
        return np.dtype(np.float32)
    if d == np.dtype(np.complex128):
        return np.dtype(np.float64)
    if d in (np.dtype(np.float32), np.dtype(np.float64)):
        return d
    from quest_tpu.validation import QuESTError
    raise QuESTError(
        f"unsupported amplitude dtype {d}: the precision tiers are "
        f"complex64 (f32 planes) and complex128 (f64 planes); wider "
        f"tiers are explicitly refused (docs/PRECISION.md)")


def complex_dtype_of(dtype):
    """The logical complex dtype for a real plane dtype (inverse of
    real_dtype_of)."""
    d = np.dtype(dtype)
    if d == np.dtype(np.float32):
        return np.dtype(np.complex64)
    if d == np.dtype(np.float64):
        return np.dtype(np.complex128)
    return d
