"""Circuit abstraction: trace a whole gate sequence into ONE XLA program.

The reference dispatches each gate eagerly into a fresh kernel launch
(QuEST.c validate->dispatch per call). On TPU the idiomatic — and much
faster — shape is to trace the entire circuit under one jit so XLA fuses
adjacent elementwise/diagonal gates, keeps the state resident in HBM/VMEM,
and (with donation) updates it in place. This is a genuine capability the
reference architecture cannot express, and the main single-chip perf lever
(SURVEY.md section 7 step 8).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from quest_tpu import cplx
from quest_tpu import precision
from quest_tpu.ops import apply as A
from quest_tpu.ops import matrices as M
from quest_tpu.state import Qureg


@dataclasses.dataclass(frozen=True)
class GateOp:
    kind: str                 # 'matrix' | 'diagonal' | 'parity' | 'allones' | 'superop'
    targets: Tuple[int, ...]
    controls: Tuple[int, ...] = ()
    cstates: Tuple[int, ...] = ()
    operand: object = None    # matrix / diag vector / angle / phase term
    meta: object = None       # side-channel the engines may read but never
    # execute from: Circuit.kraus stores ("kraus", <raw operator tuple>)
    # so the trajectory unraveling (trajectories.run_batched) can recover
    # the channel's Kraus decomposition from the superoperator op


# op-count threshold above which the PER-GATE XLA engine (Circuit.apply
# / compiled / trace — one HLO op chain per gate) warns about its
# compile time: XLA-CPU compile of a ~100-op per-gate program measured
# PATHOLOGICALLY slow (minutes; observed on the PR-13 evolution
# circuits — the banded/fused engines compile the same circuit in
# seconds because band composition collapses the chain). 64 keeps the
# oracle path quiet for the small fuzz circuits the tests trace while
# catching every real workload-sized circuit.
PERGATE_COMPILE_WARN_OPS = 64

_pergate_warned = False


def _warn_pergate_compile_once(num_ops: int) -> None:
    """Once-per-process stderr nudge toward the fusing engines: the
    per-gate path is the semantic ORACLE, not the way to run a deep
    circuit (docs/SCHEDULER.md)."""
    global _pergate_warned
    if _pergate_warned:
        return
    _pergate_warned = True
    import sys
    print(f"[quest_tpu.circuit] compiling a {num_ops}-op circuit "
          f"through the PER-GATE XLA engine (Circuit.apply/compiled): "
          f"XLA compile time grows pathologically with per-gate op "
          f"chains (minutes at ~100 ops on XLA-CPU). Use "
          f"Circuit.apply_banded or compiled_fused — the fusing "
          f"engines compose the same circuit into band passes and "
          f"compile in seconds (threshold: "
          f"PERGATE_COMPILE_WARN_OPS={PERGATE_COMPILE_WARN_OPS}; "
          f"warned once per process)", file=sys.stderr, flush=True)


def dual_of(op: GateOp, shift: int):
    """The column-space dual of a gate on a density register: conjugated
    operand on targets/controls shifted by N (ref QuEST.c:8-10). The ONE
    place the dual rules live — used by the XLA path, the fused-engine
    expansion, and anything else that flattens density circuits.
    Superoperators already act on both spaces: no dual (returns None);
    measurements handle the density register directly (no dual)."""
    if op.kind in ("superop", "measure", "measure_dm", "classical"):
        return None
    if op.kind == "parity":
        return dataclasses.replace(
            op, targets=tuple(t + shift for t in op.targets),
            operand=-op.operand)
    parts = getattr(op, "parts", None)
    if parts:
        # a scheduler-shaped ComposedDiag (fusion.ComposedDiag) carries
        # its phase components in `parts` alongside the composed table;
        # the dual must conjugate BOTH representations — negating each
        # part's angle is exactly the conjugate of its phase factor —
        # or the Pallas MultiPhaseStage lowering (which reads parts)
        # would disagree with the conjugated operand
        return dataclasses.replace(
            op, targets=tuple(t + shift for t in op.targets),
            controls=tuple(c + shift for c in op.controls),
            operand=np.conj(op.operand),
            parts=tuple((kind, bits, -ang) for kind, bits, ang in parts))
    return dataclasses.replace(
        op, targets=tuple(t + shift for t in op.targets),
        controls=tuple(c + shift for c in op.controls),
        operand=np.conj(op.operand))


_LOOP_UNROLL_MAX = 32


def _engine_mode_key():
    """The trace-time mode flags every compiled-program cache key must
    carry, DERIVED from the knob registry (env.engine_mode_key): every
    keyed knob's effective value — matmul precision, the f64-MXU
    limb-scheme switch, the limb chunk size (all change what ops/apply
    traces), the gate-scheduler and fused-scan switches (change what
    the fusing engines plan) and the host-engine block size. Omitting
    any returns stale programs when a user flips the knob mid-process —
    the cache-key discipline of ADVICE r4 item 2 / review r5; the knob
    registry makes the list mechanical instead of hand-maintained
    (quest-lint QL001 checks read sites against it). The apply-layer
    subset is A.mode_key(), shared with the eager per-gate jit workers
    (ops/gates.py) whose cache needs the same discipline."""
    from quest_tpu.env import engine_mode_key
    return engine_mode_key()

# named-gate recovery for Circuit.to_qasm (the builder stores operands;
# the QASM recorder prefers gate names, like the eager API)
_NAMED_2x2 = (("h", M.HADAMARD), ("x", M.PAULI_X), ("y", M.PAULI_Y),
              ("z", M.PAULI_Z))


def _named_1q(u):
    """(gate name, params) of a stored 2x2 operand, or None: the fixed
    Cliffords by exact match, rx/ry by structural recovery of the angle
    (modulo the rotation's 4pi matrix period)."""
    for name, mat in _NAMED_2x2:
        if np.array_equal(u, mat):
            return (name, ())
    c, o = u[0, 0], u[0, 1]
    if (abs(c.imag) < 1e-14 and abs(o.real) < 1e-14
            and np.allclose(u, [[c, o], [o, c]])):
        th = 2.0 * np.arctan2(-o.imag, c.real)
        if np.allclose(u, M.rotation(th, (1.0, 0.0, 0.0))):
            return ("rx", (th,))
    if (np.allclose(u.imag, 0.0, atol=1e-14)
            and np.allclose(u, [[c, o], [-o, c]])):
        th = 2.0 * np.arctan2(-o.real, c.real)
        if np.allclose(u, M.rotation(th, (0.0, 1.0, 0.0))):
            return ("ry", (th,))
    return None


def _named_diag(d):
    """(gate name, params) of a stored (2,) diagonal operand, or None."""
    if np.array_equal(d, M.Z_DIAG):
        return ("z", ())
    if np.array_equal(d, M.S_DIAG):
        return ("s", ())
    if np.array_equal(d, M.T_DIAG):
        return ("t", ())
    if abs(d[0] - 1.0) < 1e-14 and abs(abs(d[1]) - 1.0) < 1e-14:
        return ("phase", (float(np.angle(d[1])),))
    return None


def as_rotation(op: GateOp):
    """(family, theta) of a parametric op, or None for a constant gate.

    The structural inverse of the builder emitters: every angle-taking
    Circuit method stores a dense operand (rx/ry -> 2x2 matrix, phase/
    cphase -> diagonal/allones term, rz/parity/multi_rotate_* -> parity
    angle), and the adjoint engine (quest_tpu/adjoint.py) needs the
    angle BACK to differentiate the gate. Families and their appliers:

      'parity'  exp(-i th/2 Z..Z)  theta = stored angle
      'rx'/'ry' M.rotation(th, x/y axis), recovered via arctan2 over
                the full 4pi matrix period (same recovery as _named_1q)
      'phase'   diagonal [1, e^{i th}] on one target
      'allones' phase e^{i th} on the all-ones subspace (cphase)

    EXACT constant gates (h/x/y/z, z/s/t diagonals, cz's -1 term) return
    None — they carry no parameter. The builder emitters never produce
    those exact constants from a generic angle (np.exp(1j*pi) retains a
    residual imaginary part), so round-tripping every parametric emitter
    is loss-free; pinned in tests/test_adjoint.py."""
    if op.kind == "parity":
        return ("parity", float(op.operand))
    if op.kind == "matrix":
        u = np.asarray(op.operand)
        if u.shape != (2, 2):
            return None
        for _, mat in _NAMED_2x2:
            if np.array_equal(u, mat):
                return None
        c, o = u[0, 0], u[0, 1]
        if (abs(c.imag) < 1e-14 and abs(o.real) < 1e-14
                and np.allclose(u, [[c, o], [o, c]])):
            th = 2.0 * np.arctan2(-o.imag, c.real)
            if np.allclose(u, M.rotation(th, (1.0, 0.0, 0.0))):
                return ("rx", float(th))
        if (np.allclose(u.imag, 0.0, atol=1e-14)
                and np.allclose(u, [[c, o], [-o, c]])):
            th = 2.0 * np.arctan2(-o.real, c.real)
            if np.allclose(u, M.rotation(th, (0.0, 1.0, 0.0))):
                return ("ry", float(th))
        return None
    if op.kind == "diagonal":
        d = np.asarray(op.operand)
        if d.shape != (2,):
            return None
        if (np.array_equal(d, M.Z_DIAG) or np.array_equal(d, M.S_DIAG)
                or np.array_equal(d, M.T_DIAG)):
            return None
        if abs(d[0] - 1.0) < 1e-14 and abs(abs(d[1]) - 1.0) < 1e-14:
            return ("phase", float(np.angle(d[1])))
        return None
    if op.kind == "allones":
        term = complex(op.operand)
        if abs(term + 1.0) < 1e-14:      # cz/ccz: exact constant
            return None
        if abs(abs(term) - 1.0) < 1e-14:
            return ("allones", float(np.angle(term)))
        return None
    return None


def inverse_op(op: GateOp) -> GateOp:
    """The adjoint of ONE GateOp (matrix -> U+, diagonal/allones ->
    conjugate, parity -> negated angle; controls preserved). The single
    place the per-op inverse rules live — Circuit.inverse reverses the
    stream through here, and the adjoint backward walk (adjoint.py)
    un-applies gates one at a time through the same rules. Raises on
    non-invertible kinds, naming the op."""
    if op.kind in ("superop", "measure", "measure_dm", "classical"):
        from quest_tpu.validation import QuESTError
        what = {"superop": "noise channels",
                "measure": "measurements",
                "measure_dm": "measurements",
                "classical": "classically-controlled gates"}
        raise QuESTError(
            f"Invalid operation: a circuit containing "
            f"{what[op.kind]} has no inverse.")
    if op.kind == "matrix":
        operand = np.asarray(op.operand).conj().T
    elif op.kind in ("diagonal", "allones"):
        operand = np.conj(op.operand)
    else:                      # parity: exp(-i a/2 Z..Z)
        operand = -op.operand
    parts = getattr(op, "parts", None)
    if parts:
        # ComposedDiag: keep the phase components in step with the
        # conjugated table (see dual_of)
        return dataclasses.replace(
            op, operand=operand,
            parts=tuple((k, b, -a) for k, b, a in parts))
    return dataclasses.replace(op, operand=operand)


def flatten_ops(ops, n: int, density: bool) -> List[GateOp]:
    """Expand density duals into a flat op list (ref QuEST.c:8-10);
    superops become explicit matrix ops on the doubled targets. The ONE
    place this expansion lives — every engine (XLA, banded, fused,
    sharded) flattens through here."""
    if not density and any(op.kind == "superop" for op in ops):
        from quest_tpu.validation import QuESTError
        raise QuESTError(
            "Invalid operation: noise channels require a density-matrix "
            "register")
    flat: List[GateOp] = []
    for op in ops:
        if op.kind == "superop":
            flat.append(dataclasses.replace(
                op, kind="matrix",
                targets=M.superop_targets(op.targets, n // 2)))
            continue
        if op.kind == "measure":
            # the measurement worker handles the density register itself
            # (diagonal probability + both-space collapse); tag it so the
            # flat executors, which otherwise run with density=False,
            # know which math to use. The tagged op CLAIMS both the qubit
            # and its column-space dual (targets[0] stays the logical
            # qubit): the fusion planner must not commute a later gate's
            # dual back across the collapse.
            if density:
                q0 = op.targets[0]
                flat.append(dataclasses.replace(
                    op, kind="measure_dm", targets=(q0, q0 + n // 2)))
            else:
                flat.append(op)
            continue
        if op.kind == "classical":
            inners, conds = op.operand
            if density:
                expanded, claim = [], []
                for g in inners:
                    expanded.append(g)
                    claim += list(g.targets) + list(g.controls)
                    d = dual_of(g, n // 2)
                    if d is not None:
                        expanded.append(d)
                        claim += list(d.targets) + list(d.controls)
                flat.append(dataclasses.replace(
                    op, targets=tuple(dict.fromkeys(claim)),
                    operand=(tuple(expanded), conds)))
            else:
                flat.append(op)
            continue
        flat.append(op)
        if density:
            dual = dual_of(op, n // 2)
            if dual is not None:
                flat.append(dual)
    return flat


def _loop(body, amps, iters: int):
    """Apply `body` to the state `iters` times inside one program, so deep
    repetition costs ONE dispatch (dispatch through the TPU tunnel costs
    ~5 ms; see scripts/probe_dispatch.py). Small counts unroll — measured
    ~5 ms/iteration cheaper than lax.fori_loop's carry handling; large
    counts use fori_loop to bound program size."""
    if iters == 1:
        return body(amps)
    if iters <= _LOOP_UNROLL_MAX:
        for _ in range(iters):
            amps = body(amps)
        return amps
    from jax import lax
    return lax.fori_loop(0, iters, lambda _, a: body(a), amps)


def _apply_one(amps, n, op: GateOp):
    operand = op.operand
    if op.kind == "parity":
        return A.apply_parity_phase(amps, n, op.targets, operand)
    if op.kind == "allones":
        return A.apply_phase_on_all_ones(amps, n, op.targets,
                                         cplx.pack(operand))
    if op.kind == "superop":
        # channel superoperator on [targets, targets + N] of the doubled
        # register (ref QuEST_common.c:540-673)
        return A.apply_matrix(amps, n, cplx.pack(operand),
                              M.superop_targets(op.targets, n // 2))
    fn = A.apply_diagonal if op.kind == "diagonal" else A.apply_matrix
    return fn(amps, n, cplx.pack(operand), op.targets, op.controls,
              op.cstates)


def _apply_banded_items(amps, n, items):
    """Apply an already-computed band-fusion plan (loop-invariant: callers
    hoist the planning out of repeated bodies)."""
    from quest_tpu.ops import fusion as F
    for it in items:
        if isinstance(it, F.BandOp):
            amps = A.apply_band(amps, n, (it.gre, it.gim), it.ql, it.w,
                                it.preds)
        elif isinstance(it, F.DiagItem):
            amps = _apply_one(amps, n, it.op)
        else:
            amps = _apply_op(amps, n, False, it.op)
    return amps


def _apply_op(amps, n, density, op: GateOp):
    amps = _apply_one(amps, n, op)
    if density:
        dual = dual_of(op, n // 2)
        if dual is not None:
            amps = _apply_one(amps, n, dual)
    return amps


# Chip-generation cost-model table (VERDICT r4 item 7: the estimate must
# NAME its constants' provenance per chip instead of silently applying
# v5e numbers everywhere). Constants are ms at 30q (16 GiB state):
#   base_pass — one HBM read+write sweep (DMA floor)
#   sc / scb / b1_extra / pair / phase — per-stage compute adders (see
#   the v5e entry's notes; other generations scale them)
_COST_MODELS = {
    "v5e": {
        "provenance": "MEASURED on v5e (docs/KERNELS.md, r4 calibration; "
                      "re-derive: python -m quest_tpu.profiling --n 30)",
        # one HBM pass at the chip's REAL in-place 461 GB/s (56% of the
        # 819 GB/s datasheet rate)
        "base_pass": 34.7,
        # elementwise butterfly, VPU-bound: ~23 ms each when stacked
        # (7 stacked sc stages measured 160 ms; a lone one hides under
        # DMA)
        "sc": 23.0,
        # an scb's MXU time is ~FLAT in its dot dim — a small-M dot
        # idles most of the systolic array, so stage time follows
        # output size, not MACs (top/mid/bottom d=8 all ~40 ms alone vs
        # d=128's 42.6; the pre-r4 d-scaled model underestimated narrow
        # stacked stages 10x and motivated a Kron-split that measured
        # 3.8x SLOWER)
        "scb": 25.0,
        "b1_extra": 4.0,       # b1 frame relayout (data movement)
        "pair": 12.0,
        # phase/parity/diagvec: calibrated on QFT-30 (~5.5 ms per stage:
        # 14 passes of ~32 phases measured 3.11 s steady)
        "phase": 5.5,
    },
    "v5p": {
        "provenance": "PROJECTED from the v5e measurements: DMA terms x "
                      "461/1550 (datasheet 2765 GB/s x the 0.56 in-place "
                      "derate measured on v5e), compute terms x 394/918 "
                      "bf16-TFLOP ratio — no v5p has been measured "
                      "(docs/POD_PROJECTION.md)",
        "base_pass": 34.7 * (461.0 / 1550.0),
        "sc": 23.0 * (394.0 / 918.0),
        "scb": 25.0 * (394.0 / 918.0),
        "b1_extra": 4.0 * (461.0 / 1550.0),
        "pair": 12.0 * (394.0 / 918.0),
        "phase": 5.5 * (461.0 / 1550.0),
    },
}


def _cost_model_for(device_kind: str):
    """(model dict, matched bool) for a jax device_kind string; unknown
    generations fall back to the v5e constants WITH matched=False so
    explain() can caution instead of silently mis-scaling."""
    k = device_kind.lower()
    if "v5p" in k or "v5 p" in k:
        return _COST_MODELS["v5p"], True
    # v5e reports as 'TPU v5 lite' / 'v5e'; match THAT generation only —
    # a future 'v6 lite' must fall through to matched=False so explain()
    # cautions instead of claiming v5e-measured provenance
    if "v5e" in k or ("v5" in k and "lite" in k):
        return _COST_MODELS["v5e"], True
    return _COST_MODELS["v5e"], False


def _estimate_ms(parts, n, model=None):
    """(lo, hi) estimated steady-state ms per application, from the
    chip-keyed cost model (_COST_MODELS; default v5e — the measured
    entry). The pipeline overlaps compute with the DMA stream at depth
    (scripts/probe_stack.py), so the honest answer is the
    [max(DMA, compute), DMA + compute] range — the measured bench
    application (79.9 ms) sits AT its lo (79), and a lone mirrored
    scb-128 pass (42.6 ms) just above its 34.7 DMA floor."""
    from quest_tpu.ops import fusion as F
    from quest_tpu.ops import pallas_band as PB

    if model is None:
        model = _COST_MODELS["v5e"]
    scale = (1 << n) / (1 << 30)
    base = model["base_pass"]

    def compute_ms(st):
        if isinstance(st, PB.MatStage):
            if st.kind == "sc":
                return model["sc"]
            # real_only discounts only the MXU dot passes; the b1 frame
            # relayout is data movement
            return (model["scb"] * (2 / 3 if st.real_only else 1.0)
                    + (model["b1_extra"] if st.kind == "b1" else 0.0))
        if isinstance(st, PB.PairStage):
            return model["pair"]
        if isinstance(st, PB.MultiPhaseStage):
            # PROJECTED from the measured per-phase constant, not yet
            # calibrated on chip: each row keeps the mask-accumulate
            # (~1/3 of a lone phase stage's mask + trig blend), and the
            # trig + complex multiply tail is paid once for the group
            return model["phase"] * (0.7 + 0.3 * len(st.forms))
        return model["phase"]

    lo = hi = 0.0
    for part in parts:
        if part[0] == "segment":
            comp = sum(compute_ms(st) for st in part[1])
            lo += max(base, comp)
            hi += base + comp
        else:
            # XLA band passthrough: 1.6-2x the state bytes
            it = part[1]
            mult = 1.8 if isinstance(it, F.BandOp) else 1.0
            lo += base * mult
            hi += base * mult
    return lo * scale, hi * scale


def _scan_partition(parts, scan_min: int):
    """Group maximal runs of >= scan_min consecutive kernel segments
    sharing ONE structure (identical stage tuple; operands differ) into
    ('scan', stages, [arrays, ...]) elements; everything else passes
    through as ('one', part). scan_min <= 0 disables grouping. Pure
    planning — unit-tested directly (tests/test_pallas.py), since the
    EXECUTED scan path is chip-only (interpret-mode Pallas inside a
    scan body explodes XLA-CPU compile, measured r4: >15 min for a
    4-segment program)."""
    out = []
    i = 0
    while i < len(parts):
        part = parts[i]
        if scan_min > 0 and part[0] == "segment":
            seg_key = tuple(part[1])
            j = i
            while (j < len(parts) and parts[j][0] == "segment"
                   and tuple(parts[j][1]) == seg_key):
                j += 1
            if j - i >= scan_min:
                out.append(("scan", part[1], [p[2] for p in parts[i:j]]))
                i = j
                continue
        out.append(("one", part))
        i += 1
    return out


def make_scan_applier(seg, arrays_run):
    """One lax.scan over a run of consecutive segments sharing ONE
    kernel structure (operands differ, stage tuple identical — QFT's
    repeated 32-phase mid-segments are the canonical case). The traced
    program carries the kernel call ONCE with stacked operands instead
    of len(run) inlined copies — the program-size lever for the relay's
    per-byte first-execution cost (compile_latency note in
    benchmarks/measured_tpu.json). Opt-in via QUEST_FUSED_SCAN=1 until
    its steady-state cost is measured on chip. Interpret mode ignores
    the flag (compiled_fused passes scan_min=0): the Pallas
    interpreter's DMA emulation traced into a scan body explodes
    XLA-CPU compile time, so the executed scan path is validated on
    silicon by scripts/tpu_revalidate.sh's fused-scan stage (QFT-20
    with and without the flag, amplitudes compared); the grouping and
    operand stacking are unit-tested off-chip via _scan_partition and
    this function with a stub segment."""
    # numpy stack: operands stay HOST-side closure constants that
    # upload with the program, like the non-scan path (segment_plan's
    # host-side-operand design)
    stacked = tuple(
        np.stack([arrs[j] for arrs in arrays_run])
        for j in range(len(arrays_run[0])))

    def apply(amps, seg=seg, stacked=stacked):
        def body(a, xs):
            return seg(a, list(xs)), None
        out, _ = jax.lax.scan(body, amps, stacked)
        return out
    return apply


def _xla_part_applier(part, n):
    """Per-STATE applier (on the (2, rows, 128) kernel layout) for a
    non-segment plan part — the XLA passthrough path shared by
    compiled_fused and the batched engine, which jax.vmap's it over the
    leading batch axis (the kernel segments get a real batch grid
    dimension instead; quest_tpu/ops/pallas_band.py)."""
    from quest_tpu.ops import fusion as F

    it = part[1]
    if isinstance(it, F.BandOp):
        xla_fn = (lambda a, it=it: A.apply_band(
            a, n, (it.gre, it.gim), it.ql, it.w, it.preds))
    elif isinstance(it, F.DiagItem):
        xla_fn = lambda a, it=it: _apply_one(a, n, it.op)
    elif it.op.kind == "matrix":
        # matrix passthroughs (cross-band multi-target ops, channel
        # superops) stay in the (2, rows, 128) kernel layout — a flat
        # round-trip at this size costs a full-state layout copy (the
        # 8 GiB copy that OOMed the 30q density bench; see
        # apply_matrix_rows)
        op = it.op
        return (lambda amps, op=op: A.apply_matrix_rows(
            amps, n, cplx.pack(op.operand), op.targets,
            op.controls, op.cstates))
    else:
        xla_fn = lambda a, it=it: _apply_op(a, n, False, it.op)
    return (lambda amps, f=xla_fn:
            f(amps.reshape(2, -1)).reshape(amps.shape))


def _bucketed_wrapper(inner, bucket: int, api: str):
    """The bucketing calling convention, in ONE place (docs/BATCHING.md):
    wrap a bucket-shaped program so callers may pass ANY leading batch
    b <= bucket — zero-pad to the bucket (every engine op is a linear
    map, so pad states stay zero), run the one compiled program, slice
    back — and reject b > bucket loudly, naming the `api` to re-request.
    Shared by compiled_batched and compiled_sharded_batched so the
    contract cannot drift between engines."""
    def wrapper(amps_b):
        b = amps_b.shape[0]
        if b > bucket:
            raise ValueError(
                f"batch {b} exceeds this program's bucket {bucket}; "
                f"request {api}({b}) instead")
        shape = amps_b.shape
        flat_b = amps_b.reshape(b, 2, -1)
        if b < bucket:
            pad = jnp.zeros((bucket - b,) + flat_b.shape[1:],
                            flat_b.dtype)
            out = inner(jnp.concatenate([flat_b, pad], axis=0))
            return out[:b].reshape(shape)
        return inner(flat_b).reshape(shape)

    wrapper.bucket = bucket
    wrapper.inner = inner
    return wrapper


def _human_bytes(b: int) -> str:
    if b >= 2**29:
        return f"{b / 2**30:.2f} GiB"
    if b >= 2**19:
        return f"{b / 2**20:.2f} MiB"
    return f"{b / 2**10:.2f} KiB"


def _comm_plan_line(rec: dict) -> str:
    """The comm planner's line in explain_sharded: the PREDICTED
    exchange schedule (parallel/comm.py) and whether it matches what XLA
    actually lowered — 'MISMATCH' here means the predictor drifted from
    the engine and tests/test_comm.py would be red."""
    verdict = ("matches" if rec.get("comm_matches_hlo")
               else "MISMATCH vs")
    line = (f"  comm plan: {rec.get('comm_strategy', '?')} "
            f"(QUEST_COMM_PLAN={1 if rec.get('comm_plan_enabled') else 0})"
            f": {rec.get('comm_exchanges', 0)} exchange(s) = "
            f"{rec.get('comm_collective_permutes', 0)} collective-"
            f"permute(s) + {rec.get('comm_all_to_alls', 0)} "
            f"all-to-all(s), {_human_bytes(rec.get('comm_bytes', 0))} "
            f"ICI per device planned [{verdict} lowered StableHLO]")
    topo = rec.get("comm_topology") or {}
    if topo.get("hosts", 1) > 1:
        line += (f"\n  topology: {topo['hosts']} host(s), "
                 f"{rec.get('comm_dci_exchanges', 0)} DCI-crossing "
                 f"exchange(s), "
                 f"{_human_bytes(rec.get('comm_dci_bytes', 0))} DCI + "
                 f"{_human_bytes(rec.get('comm_ici_bytes', 0))} ICI "
                 f"per device (weights ici={topo['ici_weight']}, "
                 f"dci={topo['dci_weight']})")
    return line


class Circuit:
    """Builder for a fixed gate sequence over `num_qubits` qubits.

    Gate operands are baked into the compiled program as constants; the
    compiled function is cached per (num_state_qubits, density, dtype).
    """

    def __init__(self, num_qubits: int):
        self.num_qubits = num_qubits
        self.ops: List[GateOp] = []
        self._compiled = {}
        self._transpiled = {}   # transpile.transpile_cached memo —
        # separate from _compiled so planning-only surfaces (explain,
        # plan_stats) never make that cache non-empty

    # -- builders (chainable) ------------------------------------------------

    def _add(self, kind, targets, operand, controls=(), cstates=None,
             meta=None):
        targets = tuple(int(t) for t in targets)
        controls = tuple(int(c) for c in controls)
        cstates = tuple(cstates) if cstates is not None else (1,) * len(controls)
        for qb in targets + controls:
            if not (0 <= qb < self.num_qubits):
                raise ValueError(f"qubit {qb} out of range")
        if len(set(targets)) != len(targets):
            raise ValueError("target qubits must be unique")
        if len(set(controls)) != len(controls):
            raise ValueError("control qubits must be unique")
        if set(targets) & set(controls):
            raise ValueError("control and target qubits must be disjoint")
        self.ops.append(GateOp(kind, targets, controls, cstates, operand,
                               meta))
        self._compiled.clear()
        self._transpiled.clear()
        return self

    def gate(self, matrix, targets, controls=(), cstates=None):
        return self._add("matrix", targets, np.asarray(matrix, dtype=np.complex128),
                         controls, cstates)

    def h(self, t):
        return self._add("matrix", (t,), M.HADAMARD)

    def x(self, t, *controls):
        return self._add("matrix", (t,), M.PAULI_X, controls)

    def y(self, t):
        return self._add("matrix", (t,), M.PAULI_Y)

    def z(self, t):
        return self._add("diagonal", (t,), M.Z_DIAG)

    def s(self, t):
        return self._add("diagonal", (t,), M.S_DIAG)

    def t(self, tq):
        return self._add("diagonal", (tq,), M.T_DIAG)

    def phase(self, t, angle):
        return self._add("diagonal", (t,),
                         np.array([1.0, np.exp(1j * angle)]))

    def rx(self, t, angle):
        return self._add("matrix", (t,), np.asarray(M.rotation(angle, (1., 0., 0.))))

    def ry(self, t, angle):
        return self._add("matrix", (t,), np.asarray(M.rotation(angle, (0., 1., 0.))))

    def rz(self, t, angle):
        return self._add("parity", (t,), float(angle))

    def cnot(self, control, target):
        return self._add("matrix", (target,), M.PAULI_X, (control,))

    def cz(self, q1, q2):
        return self._add("allones", (q1, q2), -1.0 + 0.0j)

    def swap(self, q1, q2):
        return self._add("matrix", (q1, q2), M.SWAP)

    def multi_rotate_z(self, targets, angle):
        return self._add("parity", tuple(targets), float(angle))

    def measure(self, qubit):
        """MID-CIRCUIT measurement of `qubit` in the computational basis:
        the outcome is drawn inside the traced program (jax.random key,
        branchless collapse — quest_tpu.measurement._measure_traced) and
        returned as a device value. Circuits containing measurements run
        through compiled_measured / apply_measured, which take a PRNG key
        and return the outcome sequence alongside the state. The
        reference can only measure eagerly between kernel launches
        (statevec_measureWithStats, QuEST_common.c:360-366); here a
        dynamic circuit stays ONE compiled program."""
        return self._add("measure", (int(qubit),), None)

    def gate_if(self, matrix, targets, when, controls=(), cstates=None):
        """CLASSICALLY-CONTROLLED gate: apply `matrix` only when earlier
        mid-circuit measurement outcomes match `when` — a (measurement
        index, wanted bit) pair or a sequence of them (indices count
        measure() calls in program order). The condition is a traced
        predicate (branchless where-blend), so feedback stays inside the
        ONE compiled program — the reference must round-trip to the host
        for any feed-forward. Enables teleportation-class dynamic
        circuits (examples/teleportation.py)."""
        when = tuple(when)
        if when and all(hasattr(w, "__len__") for w in when):
            when = tuple(tuple(w) for w in when)
        else:
            when = (when,)
        if not all(len(w) == 2 for w in when) or not when:
            raise ValueError(
                "gate_if condition must be a (measurement index, wanted "
                "bit) pair or a non-empty sequence of such pairs")
        n_meas = self._measure_count()
        for idx, want in when:
            if not (0 <= int(idx) < n_meas):
                raise ValueError(
                    f"gate_if condition references measurement {idx}, but "
                    f"only {n_meas} measure() calls precede it")
            if int(want) not in (0, 1):
                raise ValueError("wanted outcome must be 0 or 1")
        inner = GateOp("matrix", tuple(int(t) for t in targets),
                       tuple(int(c) for c in controls),
                       tuple(cstates) if cstates is not None
                       else (1,) * len(controls),
                       np.asarray(matrix, dtype=np.complex128))
        return self._add(
            "classical", inner.targets + inner.controls,
            ((inner,), tuple((int(i), int(w)) for i, w in when)))

    def x_if(self, target, when):
        return self.gate_if(M.PAULI_X, (target,), when)

    def reset(self, qubit):
        """Reset `qubit` to |0> mid-circuit: measure it and flip on
        outcome 1 (the standard dynamic-circuit reset; destroys this
        qubit's coherences, preserves the rest of the register). The
        measurement outcome still appears in the returned sequence."""
        self.measure(qubit)
        return self.x_if(qubit, (self._measure_count() - 1, 1))

    def z_if(self, target, when):
        return self.gate_if(M.PAULI_Z, (target,), when)

    def _measure_count(self) -> int:
        return sum(1 for op in self.ops if op.kind == "measure")

    def _dynamic_count(self) -> int:
        return sum(1 for op in self.ops
                   if op.kind in ("measure", "classical"))

    def _reject_measure(self, what: str):
        if self._dynamic_count():
            from quest_tpu.validation import QuESTError
            raise QuESTError(
                f"Invalid operation: this circuit contains mid-circuit "
                f"measurements; use compiled_measured/apply_measured "
                f"instead of {what}.")

    def multi_rotate_pauli(self, targets, paulis, angle):
        """exp(-i angle/2 * P1 x P2 x ...) as basis rotations around a
        parity phase (ref statevec_multiRotatePauli,
        QuEST_common.c:410-447). In a traced circuit this decomposition
        is the right form: the 1q basis changes compose into the
        surrounding band operators and the parity core is
        communication-free on every engine (the eager gates path uses
        the one-pass flip-form instead, gates.multi_rotate_pauli)."""
        f = 1.0 / np.sqrt(2.0)
        to_z = {1: np.array([[f, f], [-f, f]]),          # Ry(-pi/2)
                2: np.array([[f, -1j * f], [-1j * f, f]])}  # Rx(pi/2)*
        z_targets = []
        for t, p in zip(targets, paulis):
            p = int(p)
            if p == 0:
                continue
            z_targets.append(int(t))
            if p in to_z:
                self._add("matrix", (int(t),), to_z[p])
        if z_targets:
            self._add("parity", tuple(z_targets), float(angle))
        for t, p in zip(targets, paulis):
            p = int(p)
            if p in to_z:
                self._add("matrix", (int(t),), to_z[p].conj().T)
        return self

    def sqrt_swap(self, q1, q2):
        return self._add("matrix", (q1, q2), M.SQRT_SWAP)

    # -- noise channels (density-matrix circuits only) -----------------------

    def kraus(self, targets, ops):
        """General Kraus map as a compiled circuit step (superoperator on
        the doubled register, ref QuEST_common.c:540-673). Validated at
        build time exactly like the eager mixKrausMap."""
        from quest_tpu import validation as val
        t = (targets,) if np.isscalar(targets) else tuple(targets)
        k = len(t)
        val.validate_kraus_ops(ops, k, max_ops=1 << (2 * k))
        # keep the raw (validated) Kraus decomposition next to the
        # composed superoperator: the density engines execute the
        # superop; the trajectory unraveling (trajectories.run_batched)
        # needs the branches — recovering them from the superoperator
        # would cost a Choi decomposition per channel
        raw = tuple(np.asarray(K, dtype=np.complex128) for K in ops)
        return self._add("superop", t, M.kraus_superoperator(ops),
                         meta=("kraus", raw))

    def damping(self, target, prob):
        from quest_tpu import validation as val
        p = float(prob)
        val.validate_one_qubit_damping_prob(p)
        return self.kraus(target, M.damping_kraus(p))

    def depolarising(self, target, prob):
        from quest_tpu import validation as val
        p = float(prob)
        val.validate_one_qubit_depol_prob(p)
        return self.kraus(target, M.depolarising_kraus(p))

    def dephasing(self, target, prob):
        from quest_tpu import validation as val
        p = float(prob)
        val.validate_one_qubit_dephase_prob(p)
        return self.kraus(target, M.dephasing_kraus(p))

    def cu(self, matrix, target, *controls, cstates=None):
        """Arbitrary single/multi-controlled k-qubit unitary."""
        t = (target,) if np.isscalar(target) else tuple(target)
        return self._add("matrix", t, np.asarray(matrix, dtype=np.complex128),
                         controls, cstates)

    def cphase(self, angle, *qubits):
        """Symmetric controlled phase e^{i angle} on all-ones of qubits."""
        return self._add("allones", tuple(qubits), np.exp(1j * float(angle)))

    def compiled_measured(self, n: int, density: bool, donate: bool = True,
                          engine: str = "banded"):
        """Compiled DYNAMIC circuit: returns fn(amps, key) ->
        (amps, outcomes) where outcomes is an int32 array of the
        mid-circuit measurement results in program order. The whole
        dynamic circuit — gates, outcome draws, branchless collapses —
        is ONE XLA program (the reference must come back to the host
        between measurements). engine: 'banded' (band-fusion between
        measurement barriers; the fusion planner treats a measurement
        as an opaque item that commutes only with disjoint-qubit ops)
        or 'xla' (per-gate)."""
        if engine not in ("banded", "xla"):
            raise ValueError(f"engine must be 'banded' or 'xla', got {engine!r}")
        if not self._measure_count():
            from quest_tpu.validation import QuESTError
            raise QuESTError(
                "Invalid operation: compiled_measured requires at least "
                "one mid-circuit measurement; use compiled() instead.")
        key_ = ("measured", engine, n, density, donate,
                _engine_mode_key())
        fn = self._compiled.get(key_)
        if fn is not None:
            return fn

        flat = flatten_ops(self.ops, n, density)

        def measure_item(amps, key, op):
            from quest_tpu import measurement as meas
            key, sub = jax.random.split(key)
            amps, outcome, _ = meas._measure_traced(
                amps, sub, n=n, qubit=op.targets[0],
                density=op.kind == "measure_dm")
            return amps, key, outcome.astype(jnp.int32)

        def classical_item(amps, outs, op):
            # feed-forward: branchless where-blend under a traced
            # predicate over earlier outcomes
            inners, conds = op.operand
            pred = None
            for idx, want in conds:
                p = outs[idx] == want
                pred = p if pred is None else pred & p
            new = amps
            for g in inners:
                new = _apply_one(new, n, g)
            return jnp.where(pred, new, amps)

        if engine == "banded":
            from quest_tpu.ops import fusion as F
            # the scheduler treats measure/classical ops as barriers, so
            # dynamic circuits reorder only within measurement-free
            # stretches
            items = F.plan(F.maybe_schedule(flat, n), n)

            def run(amps, key):
                outs = []
                for it in items:
                    if isinstance(it, F.BandOp):
                        amps = A.apply_band(amps, n, (it.gre, it.gim),
                                            it.ql, it.w, it.preds)
                    elif isinstance(it, F.DiagItem):
                        amps = _apply_one(amps, n, it.op)
                    elif it.op.kind in ("measure", "measure_dm"):
                        amps, key, oc = measure_item(amps, key, it.op)
                        outs.append(oc)
                    elif it.op.kind == "classical":
                        amps = classical_item(amps, outs, it.op)
                    else:
                        amps = _apply_op(amps, n, False, it.op)
                return amps, jnp.stack(outs)
        else:
            def run(amps, key):
                outs = []
                for op in flat:
                    if op.kind in ("measure", "measure_dm"):
                        amps, key, oc = measure_item(amps, key, op)
                        outs.append(oc)
                    elif op.kind == "classical":
                        amps = classical_item(amps, outs, op)
                    else:
                        amps = _apply_one(amps, n, op)
                return amps, jnp.stack(outs)

        fn = jax.jit(run, donate_argnums=(0,) if donate else ())
        self._compiled[key_] = fn
        return fn

    def apply_measured(self, q: Qureg, key, donate: bool = False,
                       engine: str = "banded"):
        """Apply a dynamic circuit: (new register, outcomes int32 array
        in program order). `key` is a jax.random key; identical keys
        reproduce identical trajectories."""
        if self.num_qubits != q.num_qubits:
            raise ValueError("circuit/register size mismatch")
        if not self._measure_count():
            from quest_tpu.validation import QuESTError
            raise QuESTError(
                "Invalid operation: apply_measured requires at least one "
                "mid-circuit measurement; use apply() instead.")
        fn = self.compiled_measured(q.num_state_qubits, q.is_density,
                                    donate, engine)
        amps, outcomes = fn(q.amps, key)
        return q.replace_amps(amps), outcomes

    def inverse(self) -> "Circuit":
        """The adjoint circuit: ops reversed, each operand conjugate-
        transposed (matrix -> U+, diagonal/allones -> conjugate, parity
        -> negated angle). Controls/control-states are preserved (the
        adjoint of a controlled U is the same-controlled U+). Circuits
        containing noise channels are not invertible and raise. No
        reference analogue (QuEST has no circuit object); enables
        uncomputation patterns like QPE's inverse QFT."""
        inv = Circuit(self.num_qubits)
        for op in reversed(self.ops):
            inv.ops.append(inverse_op(op))
        return inv

    @classmethod
    def from_qasm(cls, text: str, u_dialect: str | None = None,
                  transpile: bool | None = None) -> "Circuit":
        """Parse OPENQASM 2.0 text into a Circuit — the recorder's own
        dialect (Ctrl- prefixes, U(rz2, ry, rz1) lines) and standard
        qelib1 gates both load; see quest_tpu/qasm_import.py. The
        reference has no importer (its QASM support is write-only,
        QuEST_qasm.c). `u_dialect` ('spec' | 'recorder') pins the
        capital-U parameter convention when the marker heuristic can't.
        `transpile` (None follows QUEST_TRANSPILE) routes the imported
        stream through the circuit transpiler (docs/TRANSPILE.md)."""
        from quest_tpu.qasm_import import circuit_from_qasm
        return circuit_from_qasm(text, u_dialect=u_dialect,
                                 transpile=transpile)

    def to_qasm(self) -> str:
        """OPENQASM 2.0 text of this circuit, through the same logger the
        eager API records with (quest_tpu/qasm.py; ref QuEST_qasm.c).
        Named gates (h/x/y/z/s/t/rx/ry/rz/phase/swap/sqrtswap) are
        recovered from the stored operands and emitted by name like the
        eager recorder; general operands fall back to ZYZ U-lines; ops
        with no QASM equivalent degrade to comments. Phase/rotation
        angles are recovered from operands modulo their period (the
        recorder's restore lines keep the emitted unitary exact)."""
        from quest_tpu import qasm as Q

        log = Q.QASMLogger(self.num_qubits)
        log.is_logging = True
        for op in self.ops:
            targets, controls = op.targets, op.controls
            cstates = op.cstates or (1,) * len(controls)
            if op.kind == "measure":
                log.record_measurement(targets[0])
                continue
            if op.kind == "classical":
                log.record_comment(
                    "Here a classically-controlled gate was applied "
                    f"(conditions on measurements {list(op.operand[1])})")
                continue
            if op.kind == "parity":
                if len(targets) == 1 and not controls:
                    log.record_gate("rz", targets[0], (), (op.operand,))
                else:
                    log.record_comment(
                        f"Here a multiRotateZ of angle {op.operand:g} was "
                        f"applied to qubits {list(targets)}")
            elif op.kind == "allones":
                term = complex(op.operand)
                qubits = tuple(targets) + tuple(controls)
                if any(s == 0 for s in cstates):
                    # a control-on-0 all-ones phase is NOT symmetric in
                    # (targets, controls) — keep the control states and
                    # anchor the diag on a condition-on-1 TARGET qubit
                    log.record_multi_state_controlled_unitary(
                        np.diag([1.0, term]),
                        tuple(targets[:-1]) + tuple(controls),
                        (1,) * (len(targets) - 1) + tuple(cstates),
                        targets[-1])
                elif abs(term + 1.0) < 1e-14:
                    log.record_gate("z", qubits[-1], qubits[:-1])
                else:
                    log.record_gate("phase", qubits[-1], qubits[:-1],
                                    (float(np.angle(term)),))
            elif op.kind == "diagonal" and len(targets) == 1:
                d = np.asarray(op.operand).reshape(-1)
                named = _named_diag(d)
                if any(s == 0 for s in cstates):
                    log.record_multi_state_controlled_unitary(
                        np.diag(d), controls, cstates, targets[0])
                elif named is not None:
                    log.record_gate(named[0], targets[0], controls,
                                    named[1])
                else:
                    log.record_unitary(np.diag(d), targets[0], controls)
            elif op.kind == "matrix" and len(targets) == 1:
                u = np.asarray(op.operand)
                named = _named_1q(u)
                if any(s == 0 for s in cstates):
                    log.record_multi_state_controlled_unitary(
                        u, controls, cstates, targets[0])
                elif named is not None:
                    log.record_gate(named[0], targets[0], controls,
                                    named[1])
                else:
                    log.record_unitary(u, targets[0], controls)
            elif (op.kind == "matrix" and len(targets) == 2
                  and not controls):
                u = np.asarray(op.operand)
                if np.array_equal(u, M.SWAP):
                    log.record_gate("swap", targets[1], (targets[0],))
                elif np.allclose(u, M.SQRT_SWAP):
                    log.record_gate("sqrtswap", targets[1], (targets[0],))
                else:
                    log.record_comment("Here a multi-qubit gate was "
                                       "applied (no QASM equivalent)")
            else:
                log.record_comment("Here a multi-qubit gate was applied "
                                   "(no QASM equivalent)")
        return log.recorded()

    # -- compilation & execution --------------------------------------------

    def trace(self, amps, n: int, density: bool):
        """Apply all ops to raw amplitudes inside an existing trace."""
        self._reject_measure("trace")
        if not density and any(op.kind == "superop" for op in self.ops):
            from quest_tpu.validation import QuESTError
            raise QuESTError(
                "Invalid operation: noise channels require a density-matrix "
                "register")
        for op in self.ops:
            amps = _apply_op(amps, n, density, op)
        return amps

    def compiled(self, n: int, density: bool, donate: bool = True,
                 iters: int = 1):
        self._reject_measure("compiled")
        # compiled-program size, not work: past _LOOP_UNROLL_MAX the
        # iteration rides ONE fori_loop whose body traces len(ops) HLO
        # ops (_loop), so only an UNROLLED iters multiplies what XLA
        # must compile
        unroll = iters if 1 <= iters <= _LOOP_UNROLL_MAX else 1
        emitted = len(self.ops) * unroll
        if emitted > PERGATE_COMPILE_WARN_OPS:
            _warn_pergate_compile_once(emitted)
        key = (n, density, donate, iters,
               _engine_mode_key())
        fn = self._compiled.get(key)
        if fn is None:
            def run(amps):
                return _loop(lambda a: self.trace(a, n, density), amps, iters)
            fn = jax.jit(run, donate_argnums=(0,) if donate else ())
            self._compiled[key] = fn
        return fn

    def apply(self, q: Qureg, donate: bool = False) -> Qureg:
        """Apply the circuit to a register (donate=True invalidates q).

        Above PERGATE_COMPILE_WARN_OPS ops the dispatch auto-routes
        through the banded engine (QUEST_APPLY_AUTOROUTE, default on):
        the per-gate XLA chain compiles pathologically slowly there —
        minutes at ~100 ops on XLA-CPU — while the banded program
        compiles in seconds and applies the same unitaries
        (eps-identical in general, BIT-identical for permutation/phase
        gates at HIGHEST — tests/test_plan.py pins both). 0 restores
        the legacy warn-only per-gate dispatch (docs/PLANNING.md)."""
        n = q.num_state_qubits
        if self.num_qubits != q.num_qubits:
            raise ValueError("circuit/register size mismatch")
        if (len(self.ops) > PERGATE_COMPILE_WARN_OPS
                and not self._dynamic_count()
                and not any(op.kind == "superop" for op in self.ops)):
            from quest_tpu.env import knob_value
            if knob_value("QUEST_APPLY_AUTOROUTE"):
                return self.apply_banded(q, donate)
        return q.replace_amps(self.compiled(n, q.is_density, donate)(q.amps))

    def _flat_ops(self, n: int, density: bool) -> List[GateOp]:
        return flatten_ops(self.ops, n, density)

    def _planned_flat(self, n: int, density: bool) -> List[GateOp]:
        """The flat op list the FUSING engines plan from: flattened,
        then reordered/composed by the commutation-aware scheduler
        (quest_tpu.ops.fusion.schedule, QUEST_SCHEDULE knob). The
        per-gate XLA engine (compiled / trace) deliberately stays
        unscheduled — it is the semantic oracle the scheduled engines
        are fuzzed against (tests/test_scheduler.py)."""
        from quest_tpu.ops import fusion as F
        return F.maybe_schedule(self._flat_ops(n, density), n)

    def compiled_banded(self, n: int, density: bool, donate: bool = True,
                        iters: int = 1):
        """Compiled program using the band-fusion engine
        (quest_tpu.ops.fusion): runs of commuting gates compose into one
        operator per 7-qubit band, each applied as a single MXU axis
        contraction (apply_band). Diagonal/parity ops stay elementwise and
        XLA fuses them into the neighbouring passes. A layer of n
        single-qubit gates costs ~ceil(n/7) memory passes instead of n."""
        self._reject_measure("compiled_banded")
        key = ("banded", n, density, donate, iters,
               _engine_mode_key())
        fn = self._compiled.get(key)
        if fn is not None:
            return fn

        from quest_tpu.ops import fusion as F
        items = F.plan(self._planned_flat(n, density), n)

        def run(amps):
            return _loop(lambda a: _apply_banded_items(a, n, items), amps,
                         iters)

        fn = jax.jit(run, donate_argnums=(0,) if donate else ())
        self._compiled[key] = fn
        return fn

    def compiled_host(self, n: int, density: bool, iters: int = 1):
        """Compiled program on the NATIVE HOST engine (quest_tpu.host):
        cache-blocked C++ kernels applying whole gate groups per
        L2-resident block — the CPU-backend counterpart of the
        reference's per-gate sweeps (QuEST_cpu.c:1656-1713), used by the
        bench fallback ladder when no TPU is reachable. Returns
        step(state)->state over numpy (2, 2^n) planes (jax host arrays
        convert on first call); ALWAYS updates writable numpy input in
        place (callers wanting a pristine input pass a copy — see
        apply_host). Raises host.HostEngineUnsupported on dynamic ops /
        traced operands so callers fall back loudly."""
        self._reject_measure("compiled_host")
        from quest_tpu import host as H
        # QUEST_HOST_BLOCK is read at encode time; it is a keyed knob in
        # the registry, so _engine_mode_key() covers it — flipping it
        # mid-process can't return a stale program (the cache-key
        # discipline from ADVICE r4 item 2)
        key = ("host", n, density, iters, _engine_mode_key())
        fn = self._compiled.get(key)
        if fn is None:
            fn = H.compile_circuit_host(self.ops, n, density, iters)
            self._compiled[key] = fn
        return fn

    def apply_host(self, q: Qureg, donate: bool = False) -> Qureg:
        """Apply via the native host engine (numpy planes). donate=False
        copies first so q's buffer survives (the engine itself is
        in-place). Donation only takes effect for registers backed by a
        writable numpy array: jax device buffers are immutable, so a
        jax-backed q.amps costs exactly one host copy either way (the
        engine's _as_planes makes it when the view is read-only)."""
        if self.num_qubits != q.num_qubits:
            raise ValueError("circuit/register size mismatch")
        fn = self.compiled_host(q.num_state_qubits, q.is_density)
        import numpy as _np
        amps = _np.array(q.amps) if not donate else q.amps
        return q.replace_amps(jnp.asarray(fn(amps)))

    def compiled_host_measured(self, n: int, density: bool = False):
        """DYNAMIC circuit on the NATIVE HOST engine: step(state,
        draws=None) -> (planes, outcomes). Measurement-free stretches
        run blocked native kernels; measurements collapse natively;
        default draws come from the reference-exact MT19937 — the same
        stream the eager API uses, so identically-seeded host and eager
        trajectories match outcome-for-outcome (quest_tpu/host.py
        compile_circuit_host_measured); density registers collapse
        both spaces natively."""
        from quest_tpu import host as H
        key = ("host-measured", n, density, _engine_mode_key())
        fn = self._compiled.get(key)
        if fn is None:
            fn = H.compile_circuit_host_measured(self.ops, n, density)
            self._compiled[key] = fn
        return fn

    def banded_trace(self, amps, n: int, density: bool):
        """Apply the band-fusion plan to raw amplitudes inside an existing
        trace (the un-jitted core of compiled_banded)."""
        self._reject_measure("banded_trace")
        from quest_tpu.ops import fusion as F
        items = F.plan(self._planned_flat(n, density), n)
        return _apply_banded_items(amps, n, items)

    def apply_banded(self, q: Qureg, donate: bool = False) -> Qureg:
        """Apply via the band-fusion engine."""
        if self.num_qubits != q.num_qubits:
            raise ValueError("circuit/register size mismatch")
        fn = self.compiled_banded(q.num_state_qubits, q.is_density, donate)
        return q.replace_amps(fn(q.amps))

    def compiled_fused(self, n: int, density: bool, donate: bool = True,
                       interpret: bool = False, iters: int = 1):
        """Compiled program using the Pallas band-segment engine
        (quest_tpu.ops.pallas_band): each segment of band operators,
        diagonals and parity phases executes in ONE kernel launch / one
        HBM pass; band ops above the block top and cross-band unitaries
        run through the XLA band path between segments. `interpret=True`
        runs the kernels in the Pallas interpreter (for CPU testing)."""
        self._reject_measure("compiled_fused")
        from quest_tpu.ops import fusion as F
        from quest_tpu.ops import pallas_band as PB
        from quest_tpu.env import knob_value
        scan_flag = knob_value("QUEST_FUSED_SCAN")
        # scan_flag is a keyed registry knob, so _engine_mode_key()
        # already carries it in the cache key below
        key = ("fused", n, density, donate, interpret, iters,
               _engine_mode_key())
        fn = self._compiled.get(key)
        if fn is not None:
            return fn
        if not PB.usable(n):
            fn = self.compiled_banded(n, density, donate, iters=iters)
            self._compiled[key] = fn
            return fn

        flat = self._planned_flat(n, density)
        # PB.plan_bands now matches fusion's default 7-wide layout, so the
        # same plan serves both the kernel segmentation and the f64 XLA
        # band path
        items = F.plan(flat, n, bands=PB.plan_bands(n))
        parts = PB.segment_plan(items, n)
        # sweep fusion (QUEST_SWEEP_FUSION, keyed — _engine_mode_key
        # carries it): merge geometry-compatible consecutive segments
        # into single-launch HBM sweeps, INCLUDING across the unrolled
        # iterations of this program — a repeated block-resident circuit
        # (the bench's headline/chain steps) collapses from `iters`
        # kernel launches per dispatch to ~iters/k, each streaming the
        # state once (quest_tpu/ops/pallas_band.py sweep_plan,
        # docs/SWEEPS.md). Unrolling the parts list here replaces
        # _loop's own unroll for the same iteration range, so program
        # size is unchanged when nothing merges.
        unroll = iters if 1 < iters <= _LOOP_UNROLL_MAX else 1
        if PB.sweep_enabled():
            parts = PB.sweep_plan(parts * unroll, n)
        else:
            unroll = 1
        loop_iters = iters // unroll
        seg_cache = {}  # identical-structure segments share one kernel

        def make_applier(part):
            # segment appliers work on (2, rows, 128); XLA passthroughs
            # flatten and restore around their op (_xla_part_applier)
            if part[0] == "segment":
                _, stages, arrays = part
                seg = PB.compile_segment_cached(seg_cache, stages, n,
                                                interpret=interpret)
                return lambda amps, seg=seg, arrays=arrays: seg(amps, arrays)
            return _xla_part_applier(part, n)

        scan_min = 3 if (scan_flag and not interpret) else 0
        appliers = []
        for grp in _scan_partition(parts, scan_min):
            if grp[0] == "scan":
                seg = PB.compile_segment_cached(
                    seg_cache, grp[1], n, interpret=interpret)
                appliers.append(make_scan_applier(seg, grp[2]))
            else:
                appliers.append(make_applier(grp[1]))

        def run(amps):
            # the Pallas kernels are f32-only; f64 registers keep their
            # precision on the XLA band path
            if amps.dtype != jnp.float32:
                flat_in = amps.reshape(2, -1)
                out = _loop(lambda a: _apply_banded_items(a, n, items),
                            flat_in, iters)
                return out.reshape(amps.shape)
            shape = amps.shape

            def body(a):
                for f in appliers:
                    a = f(a)
                return a
            out = _loop(body, amps.reshape(2, -1, PB.LANES), loop_iters)
            return out.reshape(shape)

        fn = jax.jit(run, donate_argnums=(0,) if donate else ())
        self._compiled[key] = fn
        return fn

    def apply_fused(self, q: Qureg, donate: bool = False,
                    interpret: bool = False) -> Qureg:
        """Apply via the Pallas fused-segment engine."""
        if self.num_qubits != q.num_qubits:
            raise ValueError("circuit/register size mismatch")
        fn = self.compiled_fused(q.num_state_qubits, q.is_density, donate,
                                 interpret)
        return q.replace_amps(fn(q.amps))

    def compiled_batched(self, batch: int, density: bool = False,
                         donate: bool = True, interpret: bool = False,
                         engine: str = None):
        """BATCHED fused engine: ONE compiled program applying this
        circuit to a whole batch of states — (B, 2, 2^n) planes in, same
        out. Each kernel sweep carries a leading batch grid dimension
        and streams the bucket's states through HBM back-to-back with
        the same stage list (quest_tpu/ops/pallas_band.py), so the
        LAUNCH COUNT of a B-shot workload does not scale with B — the
        throughput shape trajectories, multi-shot sampling and parameter
        sweeps want (docs/BATCHING.md; Q-GEAR's batched-circuit win,
        arXiv:2504.03967). f64 registers and registers below the kernel
        tier ride a vmapped banded-XLA program instead (full precision /
        no Pallas), still one compiled dispatch for the whole batch.

        Batch-size BUCKETING: the compiled size is
        env.batch_bucket(batch) — B rounds up to the next power of two
        under QUEST_BATCH_BUCKET=pow2 (default) — and the returned
        wrapper accepts ANY leading batch b <= bucket, zero-padding to
        the bucket and slicing back (every engine op is a linear map, so
        padding states stay zero and cost only their share of the
        launch). Calls whose batches share a bucket return the SAME
        wrapper object: serving mixed batch sizes hits one persistent
        compile-cache entry instead of retracing per size
        (tests/test_batched.py pins this with the CompileAuditor).

        `engine` pins the program family instead of auto-resolving:
        None (default) rides the Pallas kernels when the register
        reaches the kernel tier, 'banded' FORCES the vmapped banded-XLA
        program (the serve degradation ladder's fallback rung — it
        must stay dispatchable when the fused compile is the thing
        that's broken, docs/RESILIENCE.md), 'fused' demands the kernel
        path and raises below the kernel tier."""
        self._reject_measure("compiled_batched")
        if engine not in (None, "fused", "banded"):
            raise ValueError(
                f"engine must be None, 'fused' or 'banded', got {engine!r}")
        from quest_tpu.env import batch_bucket
        n = self.num_qubits * 2 if density else self.num_qubits
        bucket = batch_bucket(batch)
        key = ("batched", n, density, donate, interpret, bucket, engine,
               _engine_mode_key())
        fn = self._compiled.get(key)
        if fn is not None:
            return fn

        from quest_tpu.ops import fusion as F
        from quest_tpu.ops import pallas_band as PB

        if engine == "fused" and not PB.usable(n):
            raise ValueError(
                f"engine='fused' requires the kernel tier; a {n}-qubit "
                f"register rides the banded program (engine='banded' or "
                f"None)")
        flat = self._planned_flat(n, density)
        use_kernels = engine != "banded" and PB.usable(n)
        if use_kernels:
            items = F.plan(flat, n, bands=PB.plan_bands(n))
            parts = PB.maybe_sweep(PB.segment_plan(items, n), n)
        else:
            items = F.plan(flat, n)
            parts = None
        seg_cache = {}

        def make_appliers():
            appliers = []
            for part in parts:
                if part[0] == "segment":
                    seg = PB.compile_segment_cached(
                        seg_cache, tuple(part[1]), n,
                        interpret=interpret, batch=bucket)
                    appliers.append(
                        lambda a, seg=seg, arrays=part[2]: seg(a, arrays))
                else:
                    appliers.append(jax.vmap(_xla_part_applier(part, n)))
            return appliers

        appliers = make_appliers() if use_kernels else None

        def run(amps_b):
            flat_b = amps_b.reshape(bucket, 2, -1)
            if appliers is None or amps_b.dtype != jnp.float32:
                # vmapped banded program: f64 keeps the limb-scheme
                # precision; sub-kernel-tier registers skip Pallas
                return jax.vmap(
                    lambda a: _apply_banded_items(a, n, items))(flat_b)
            a = flat_b.reshape(bucket, 2, -1, PB.LANES)
            for f in appliers:
                a = f(a)
            return a.reshape(bucket, 2, -1)

        inner = jax.jit(run, donate_argnums=(0,) if donate else ())
        wrapper = _bucketed_wrapper(inner, bucket, "compiled_batched")
        self._compiled[key] = wrapper
        return wrapper

    def program_key(self, density: bool = False, interpret: bool = False,
                    dtype=np.float32) -> Tuple:
        """Hashable PROGRAM IDENTITY of the batched-engine program
        family this circuit resolves to — the serving layer's
        batch-compatibility rule (quest_tpu.serve, docs/SERVING.md):
        two requests may share one `compiled_batched` launch iff their
        program keys are EQUAL. The key carries the circuit object
        itself (op lists are compared by identity, not value — holding
        the object also pins its id, so a GC'd-then-reused id can never
        alias two circuits, the id(mesh) bug class of VERDICT r3), the
        op count (a circuit mutated after submit forms a new family),
        the register kind/size, the plane dtype (f32 rides the kernels,
        f64 the banded fallback — different programs), the interpret
        flag, and `engine_mode_key()` (a keyed-knob flip changes which
        program a batched call resolves to). Bucket size is NOT part of
        the identity: all buckets of one family share the planner and
        the per-bucket wrapper cache (docs/BATCHING.md)."""
        n = self.num_qubits * 2 if density else self.num_qubits
        return ("batched", self, len(self.ops), n, density, interpret,
                np.dtype(dtype).str, _engine_mode_key())

    def apply_batched(self, amps_b, density: bool = False,
                      donate: bool = False, interpret: bool = False):
        """Apply this circuit to a (B, 2, 2^n) batch of raw amplitude
        planes through the batched fused engine (compiled_batched)."""
        fn = self.compiled_batched(int(amps_b.shape[0]), density=density,
                                   donate=donate, interpret=interpret)
        return fn(amps_b)

    def plan_stats(self, density: bool = False,
                   batch: int = None, devices: int = None) -> dict:
        """Hardware-independent plan statistics — the pass-count metric
        the commutation-aware scheduler is judged by, assertable on CPU
        (no compile, no chip): 'banded' is fusion.plan_stats's model
        (BandOps + PassOps + maximal DiagItem runs, each one full-state
        HBM pass on the banded XLA engine); 'fused' — when the register
        reaches the kernel tier — counts the Pallas engine's segments +
        passthroughs (each one HBM pass per application), plus the
        scheduler's own counters. Computed under the CURRENT
        QUEST_SCHEDULE setting; toggle the knob and diff to see what
        scheduling buys (docs/SCHEDULER.md, tests/test_scheduler.py).
        `batch` adds a 'batched' record (batch, bucket,
        states_per_sweep, hbm_sweeps) describing what compiled_batched
        would execute for that many states — its hbm_sweeps equals the
        unbatched fused plan's by construction: launches do not scale
        with B (docs/BATCHING.md; scripts/check_batch_golden.py).
        `devices` adds a 'comm' record — the comm planner's PREDICTED
        collective schedule for the banded/fused sharded engines over
        that many devices (strategy, exchange counts, per-device ICI
        bytes at the session dtype) — pure host math, no mesh: a
        40q/256-device schedule prices on a laptop
        (docs/DISTRIBUTED.md; scripts/check_comm_golden.py holds the
        goldens and tests/test_comm.py pins it equal to the lowered
        StableHLO accounting).

        Since PR 16 this dict is a VIEW of the ProgramPlan IR
        (quest_tpu/plan.py builds one object, this method re-emits its
        historical shape bit-for-bit — docs/PLANNING.md); query
        plan.build_plan / plan.autotune for the typed structure."""
        self._reject_measure("plan_stats")
        from quest_tpu import plan as P
        return P.build_plan(self, density=density, batch=batch,
                            devices=devices).stats()

    def transpiled(self, exact_only: bool = False) -> "Circuit":
        """An equivalent circuit rewritten by the transpiler
        (quest_tpu/transpile.py, docs/TRANSPILE.md): peephole
        cancellation through commuting separators, rotation folding,
        1q-run merging and cost-model-priced 2q KAK resynthesis.
        Returns self when no pass fires. `exact_only` restricts to the
        bit-identical subset (exact inverse pairs / exact identities
        only). The rewrite report rides on the result as
        `_transpile_report`; memoized until this circuit mutates."""
        from quest_tpu import transpile as T
        return T.transpile_cached(self, exact_only=exact_only)[0]

    def _comm_plan_stats(self, n: int, density: bool, devices: int) -> dict:
        """The plan_stats 'comm' record: predicted collective schedule
        of the banded/fused sharded engines over `devices`, through the
        SAME policy home they execute (parallel.sharded.comm_plan_record
        wraps engine_flat + the comm predictor) so it cannot drift from
        the lowered program."""
        from quest_tpu.parallel import sharded as S
        return S.comm_plan_record(self.ops, n, density, devices)

    def explain(self, density: bool = False, batch: int = None) -> str:
        """Human-readable fused-engine schedule: what compiled_fused will
        actually execute, WITHOUT paying a compile — one line per part
        (kernel segment with its stage mix, or XLA passthrough), then
        totals: segments, distinct Mosaic kernels, HBM passes and the
        estimated bytes one application moves. Performance introspection
        the reference cannot offer (it executes gate by gate; there is
        no schedule to explain)."""
        self._reject_measure("explain")
        from quest_tpu.ops import fusion as F
        from quest_tpu.ops import pallas_band as PB

        n = self.num_qubits * 2 if density else self.num_qubits
        pass_bytes = 2 * 4 * (1 << n) * 2   # r+w of both f32 planes
        lines = [f"fused schedule for {len(self.ops)} ops on "
                 f"{self.num_qubits} qubits"
                 + (f" (density: {n}-qubit register)" if density else "")]
        flat = self._flat_ops(n, density)
        # ONE scheduler run serves both the stats line and the plan below
        sched_ops, sched = F.schedule(flat, n)
        enabled = F._schedule_enabled()
        if enabled:
            lines.append(
                f"  scheduler: on (QUEST_SCHEDULE=1): "
                f"{sched['delayed']} diagonal op(s) delayed, "
                f"{sched['hoisted']} hoisted, {sched['fused_ops']} "
                f"composed into {sched['fused_groups']} group(s)")
        else:
            lines.append(
                f"  scheduler: OFF (QUEST_SCHEDULE=0); on, it would "
                f"compose {sched['fused_ops']} diagonal op(s) into "
                f"{sched['fused_groups']} group(s)")

        def host_line():
            # the CPU-fallback story: what the native host engine would
            # do with this circuit (the bench ladder's first off-chip
            # rung) — omitted when the native library or an op's host
            # kernel is unavailable, never fatal to explain()
            try:
                from quest_tpu import host as H
                if H.available():
                    lines.append("  cpu fallback "
                                 + H.plan_summary(flat, n))
            except Exception:
                pass

        def plan_line():
            # the one unified plan line (docs/PLANNING.md): the priced
            # autotuner's verdict for this circuit — chosen engine,
            # estimated ms/application, incumbent and candidate count.
            # Searched fresh (persist=False: explain never reads or
            # writes the plan cache); omitted, never fatal, when a
            # subsystem cannot price (traced operands)
            try:
                from quest_tpu import plan as P
                lines.append("  " + P.autotune(
                    self, state_kind="density" if density else "pure",
                    batch=batch, persist=False).line())
            except Exception:
                pass

        def transpile_line():
            # the transpile axis's verdict for this stream
            # (docs/TRANSPILE.md): what the rewriter buys under the
            # current knob — omitted on dynamic streams, never fatal
            try:
                from quest_tpu.env import knob_value
                knob = knob_value("QUEST_TRANSPILE")
                if knob == "0":
                    lines.append("  transpile: off (QUEST_TRANSPILE=0)")
                    return
                from quest_tpu import transpile as T
                tc, rep = T.transpile_cached(self)
                if not rep["changed"]:
                    lines.append(
                        f"  transpile: no rewrite ({rep['ops_in']} op(s) "
                        f"already minimal under the pass catalog; "
                        f"QUEST_TRANSPILE={knob})")
                    return
                attr = ", ".join(f"{k}={v}"
                                 for k, v in rep["passes"].items() if v)
                lines.append(
                    f"  transpile: {rep['ops_in']} -> {rep['ops_out']} "
                    f"op(s) [{attr}] (QUEST_TRANSPILE={knob}; "
                    f"docs/TRANSPILE.md)")
            except Exception:
                pass

        if not PB.usable(n):
            lines.append(f"  register below the kernel tier's minimum "
                         f"({PB.LANE_QUBITS + 3} qubits): the banded XLA "
                         f"engine runs instead")
            transpile_line()
            plan_line()
            host_line()
            return "\n".join(lines)

        # the plan compiled_fused will actually execute: scheduled when
        # the knob is on (host_line above deliberately keeps the raw
        # flat list — the host engine consumes Circuit.ops directly)
        items = F.plan(sched_ops if enabled else flat, n,
                       bands=PB.plan_bands(n))
        parts = PB.segment_plan(items, n)
        # sweep fusion: report the plan compiled_fused will execute for
        # ONE application (cross-iteration merging depends on iters,
        # which explain() doesn't take); the hypothetical count rides
        # along when the knob is off, mirroring the scheduler line
        swept = PB.sweep_plan(parts, n)
        nseg = sum(1 for p in parts if p[0] == "segment")
        nsw = sum(1 for p in swept if p[0] == "segment")
        if PB.sweep_enabled():
            lines.append(
                f"  sweep fusion: on (QUEST_SWEEP_FUSION=1): {nseg} "
                f"kernel segment(s) -> {nsw} sweep(s), {len(swept)} HBM "
                f"pass(es) per application")
            parts = swept
        else:
            lines.append(
                f"  sweep fusion: OFF (QUEST_SWEEP_FUSION=0); on, it "
                f"would merge {nseg} segment(s) into {nsw} sweep(s)")
        kernels = set()
        passes = 0
        for i, part in enumerate(parts):
            if part[0] == "segment":
                _, stages, _arrays = part
                kernels.add(tuple(stages))
                passes += 1
                mix = {}
                for st in stages:
                    name = type(st).__name__.removesuffix("Stage").lower()
                    if hasattr(st, "kind"):
                        name = f"{name}:{st.kind}"
                    mix[name] = mix.get(name, 0) + 1
                desc = " ".join(f"{k}x{v}" if v > 1 else k
                                for k, v in mix.items())
                lines.append(f"  [{i}] kernel segment  "
                             f"{len(stages)} stages  ({desc})")
            else:
                it = part[1]
                passes += 1
                what = (f"band q{it.ql}..q{it.ql + it.w - 1}"
                        if isinstance(it, F.BandOp) else
                        "diagonal" if isinstance(it, F.DiagItem)
                        else f"op {getattr(it.op, 'kind', '?')}")
                lines.append(f"  [{i}] XLA passthrough  {what}")
        moved = passes * pass_bytes
        lines.append(
            f"  total: {passes} HBM pass{'es' if passes != 1 else ''} "
            f"({_human_bytes(moved)} moved per application at {n}q), "
            f"{sum(1 for p in parts if p[0] == 'segment')} segments, "
            f"{len(kernels)} distinct kernels")
        if batch is not None:
            from quest_tpu.env import batch_bucket
            bucket = batch_bucket(batch)
            lines.append(
                f"  batched: B={batch} -> bucket {bucket} states per "
                f"launch (QUEST_BATCH_BUCKET); {passes} launch(es) per "
                f"application independent of B — "
                f"{_human_bytes(moved * bucket)} moved for the bucket")
        # chip-keyed constants (_COST_MODELS): each generation's entry
        # NAMES its provenance — v5e measured, v5p projected from
        # datasheet x measured derate; an unrecognized chip falls back
        # to v5e numbers WITH a caution (VERDICT r4 item 7). Only
        # consult the device when this process has ALREADY committed to
        # a backend: explain() is pure host math and must stay safe to
        # call before ensure_live_backend — an in-process jax.devices()
        # with the tunnel down hangs indefinitely, and with it up would
        # commit the backend early (env.py ordering contract).
        kind = "?"
        try:
            # backends_are_initialized() is the named API for "has this
            # process committed to a backend" (pinned by
            # tests/test_docs.py::test_backend_probe_api so a JAX
            # upgrade that renames it fails loudly instead of silently
            # dropping the wrong-chip caution — ADVICE r4 item 3)
            from jax._src import xla_bridge as _xb
            if _xb.backends_are_initialized():
                kind = str(getattr(jax.devices()[0], "device_kind", "?"))
        except Exception:               # pragma: no cover - no backend
            pass
        model, matched = _cost_model_for(kind)
        lo, hi = _estimate_ms(parts, n, model)
        chip = "v5p" if model is _COST_MODELS["v5p"] else "v5e"
        tag = ("" if matched or kind == "?" else
               f" [CAUTION: no cost model for {kind!r} — using v5e "
               f"constants; treat as relative, not absolute]")
        lines.append(
            f"  estimated steady state on one {chip}: {lo:.1f}-{hi:.1f} "
            f"ms per application at HIGHEST "
            f"(constants: {model['provenance']}){tag}")
        transpile_line()
        plan_line()
        host_line()
        return "\n".join(lines)

    def explain_sharded(self, mesh, density: bool = False,
                        engine: str = "banded",
                        batch: int = None) -> str:
        """The distributed counterpart of explain(): lower (not compile)
        the sharded program for `mesh` and report the communication
        schedule XLA actually emitted — collective exchanges and their
        per-device ICI bytes, psum reductions, local band passes — plus
        the shard geometry. Derived from the lowered StableHLO, so it
        cannot drift from the engine (quest_tpu.parallel.introspect).
        The reference's exchange schedule is implicit in C control flow
        (QuEST_cpu_distributed.c:481-509) and cannot be asked for.

        DYNAMIC circuits (mid-circuit measurements / feedback) report
        through the measured engine's planner instead: per-stretch
        relabel events, kernel segments, and the psum-per-measurement
        schedule (parallel.introspect.sharded_measured_schedule)."""
        n = self.num_qubits * 2 if density else self.num_qubits
        if self._measure_count():
            from quest_tpu.parallel.introspect import (
                sharded_measured_schedule)
            # the static engines call the per-gate schedule 'pergate';
            # the dynamic compiler calls it 'xla' — accept both here
            dyn_engine = {"pergate": "xla"}.get(engine, engine)
            rec = sharded_measured_schedule(self.ops, n, density, mesh,
                                            engine=dyn_engine)
            return "\n".join([
                f"sharded DYNAMIC ({rec['engine']}) schedule for "
                f"{len(self.ops)} ops on {self.num_qubits} qubits over "
                f"{rec['devices']} devices"
                + (f" (density: {n}-qubit register)" if density else ""),
                f"  shard geometry: {rec['local_qubits']} local + "
                f"{rec['global_qubits']} device qubits, "
                f"{_human_bytes(rec['chunk_bytes'])} chunk per device",
                f"  {rec['measurements']} measurement(s) + "
                f"{rec['classical_ops']} feedback op(s) splitting "
                f"{rec['stretches']} static stretch(es)",
                f"  local band passes: {rec['local_band_passes']}"
                + (f" ({rec['kernel_segments']} kernel segments)"
                   if rec['kernel_segments'] else ""),
                f"  relabel events: {rec['relabel_events']}",
                _comm_plan_line(rec),
                f"  collective exchanges: {rec['collective_exchanges']} "
                f"({_human_bytes(rec['ici_bytes_per_device'])} ICI per "
                f"device per application)",
                f"  psum reductions: {rec['all_reduces']}",
            ])
        from quest_tpu.parallel.introspect import sharded_schedule

        rec = sharded_schedule(self.ops, n, density, mesh, engine=engine)
        if engine == "pergate":
            plan_lines = [f"  local ops: {rec['local_ops']}",
                          f"  device-qubit ops: {rec['global_ops']}"]
        else:
            sch = rec.get("scheduler", {})
            if sch.get("enabled"):
                sch_line = (f"  scheduler: on "
                            f"({sch.get('fused_ops', 0)} diagonal op(s) "
                            f"composed into {sch.get('fused_groups', 0)} "
                            f"group(s), {sch.get('hoisted', 0)} hoisted)")
            else:
                # the plan below is UNSCHEDULED — report the dry-run
                # counts as hypothetical, like explain() does
                sch_line = (f"  scheduler: OFF (QUEST_SCHEDULE=0); on, "
                            f"it would compose {sch.get('fused_ops', 0)} "
                            f"diagonal op(s) into "
                            f"{sch.get('fused_groups', 0)} group(s)")
            plan_lines = [
                sch_line,
                f"  local band passes: {rec['local_band_passes']}",
                f"  global-qubit items: {rec['global_qubit_items']}"]
            if "kernel_sweeps" in rec:
                plan_lines.append(
                    f"  local kernel sweeps: {rec['kernel_sweeps']} per "
                    f"device (from {rec['kernel_segments']} segment(s); "
                    f"QUEST_SWEEP_FUSION)")
            if batch is not None and "hbm_sweeps" in rec:
                from quest_tpu.env import AMP_AXIS, batch_bucket
                bucket = batch_bucket(batch)
                plan_lines.append(
                    f"  batched: B={batch} -> bucket {bucket} states "
                    f"ride each per-shard sweep; the batch axis stays "
                    f"LOCAL to the amplitude mesh (sharding "
                    f"P(None, None, {AMP_AXIS!r}) — no batch "
                    f"collectives), {rec['hbm_sweeps']} per-shard "
                    f"launch(es) independent of B")
        return "\n".join([
            f"sharded ({engine}) schedule for {len(self.ops)} ops on "
            f"{self.num_qubits} qubits over {rec['devices']} devices"
            + (f" (density: {n}-qubit register)" if density else ""),
            f"  shard geometry: {rec['local_qubits']} local + "
            f"{rec['global_qubits']} device qubits, "
            f"{_human_bytes(rec['chunk_bytes'])} chunk per device",
            *plan_lines,
            _comm_plan_line(rec),
            f"  collective exchanges: {rec['collective_exchanges']} "
            f"({_human_bytes(rec['ici_bytes_per_device'])} ICI per device "
            f"per application)",
            *([f"  of which relabel all-to-alls: {rec['all_to_alls']}"]
              if rec.get("all_to_alls") else []),
            f"  psum reductions: {rec['all_reduces']}",
        ])

    def compiled_sharded(self, n: int, density: bool, mesh, donate: bool = True):
        """Compiled explicit-distribution program (one shard_map over the
        whole circuit, reference-style ppermute schedule — see
        quest_tpu.parallel.sharded)."""
        self._reject_measure("compiled_sharded")
        from quest_tpu.parallel import sharded as S
        # the Mesh itself keys the cache: jax Mesh equality is by VALUE
        # (axis names/types, device shape + identity), so a rebuilt Mesh
        # over the same devices hits, while a same-shape Mesh over
        # different devices — or a GC'd-then-reused object id — never
        # aliases (the id(mesh) bug, VERDICT r3 weak item 2)
        key = ("sharded", n, density, mesh,
               donate, _engine_mode_key())
        fn = self._compiled.get(key)
        if fn is None:
            fn = S.compile_circuit_sharded(self.ops, n, density, mesh, donate)
            self._compiled[key] = fn
        return fn

    def compiled_sharded_banded(self, n: int, density: bool, mesh,
                                donate: bool = True):
        """Band-fusion engine over the device mesh (one shard_map program;
        see quest_tpu.parallel.sharded.compile_circuit_sharded_banded)."""
        self._reject_measure("compiled_sharded_banded")
        from quest_tpu.parallel import sharded as S
        key = ("sharded-banded", n, density, mesh, donate,
               _engine_mode_key())
        fn = self._compiled.get(key)
        if fn is None:
            fn = S.compile_circuit_sharded_banded(self.ops, n, density, mesh,
                                                  donate)
            self._compiled[key] = fn
        return fn

    def compiled_sharded_fused(self, n: int, density: bool, mesh,
                               donate: bool = True,
                               interpret: bool = False):
        """Pallas band-segment engine over the device mesh (local fused
        mega-kernel segments between explicit ppermute exchanges; see
        quest_tpu.parallel.sharded.compile_circuit_sharded_fused)."""
        from quest_tpu.parallel import sharded as S
        self._reject_measure("compiled_sharded_fused")
        key = ("sharded-fused", n, density, mesh, donate, interpret,
               _engine_mode_key())
        fn = self._compiled.get(key)
        if fn is None:
            fn = S.compile_circuit_sharded_fused(self.ops, n, density, mesh,
                                                 donate, interpret)
            self._compiled[key] = fn
        return fn

    def compiled_sharded_batched(self, batch: int, mesh,
                                 density: bool = False,
                                 donate: bool = True,
                                 interpret: bool = False):
        """BATCHED fused engine over the device mesh: one shard_map
        program applying this circuit to (B, 2, 2^n) planes whose
        AMPLITUDE axis is sharded and whose batch axis is kept LOCAL to
        every device (parallel.sharded.compile_circuit_sharded_fused_
        batched) — per-shard sweeps stream the whole bucket per launch,
        collectives vmap over the batch. Buckets and pads exactly like
        compiled_batched: calls sharing a bucket return the SAME
        wrapper (one compiled program per bucket)."""
        self._reject_measure("compiled_sharded_batched")
        from quest_tpu.env import batch_bucket
        from quest_tpu.parallel import sharded as S
        n = self.num_qubits * 2 if density else self.num_qubits
        bucket = batch_bucket(batch)
        key = ("sharded-batched", n, density, mesh, donate, interpret,
               bucket, _engine_mode_key())
        fn = self._compiled.get(key)
        if fn is not None:
            return fn
        inner = S.compile_circuit_sharded_fused_batched(
            self.ops, n, density, mesh, bucket, donate, interpret)
        wrapper = _bucketed_wrapper(inner, bucket,
                                    "compiled_sharded_batched")
        self._compiled[key] = wrapper
        return wrapper

    def apply_sharded_fused(self, q: Qureg, mesh, donate: bool = False,
                            interpret: bool = False) -> Qureg:
        """Apply via the Pallas fused shard_map engine."""
        if self.num_qubits != q.num_qubits:
            raise ValueError("circuit/register size mismatch")
        from quest_tpu.parallel import mesh as MM
        fn = self.compiled_sharded_fused(q.num_state_qubits, q.is_density,
                                         mesh, donate, interpret)
        amps = jax.device_put(q.amps, MM.amp_sharding(mesh))
        return q.replace_amps(fn(amps))

    def apply_sharded_banded(self, q: Qureg, mesh,
                             donate: bool = False) -> Qureg:
        """Apply via the band-fusion shard_map engine."""
        if self.num_qubits != q.num_qubits:
            raise ValueError("circuit/register size mismatch")
        from quest_tpu.parallel import mesh as MM
        fn = self.compiled_sharded_banded(q.num_state_qubits, q.is_density,
                                          mesh, donate)
        amps = jax.device_put(q.amps, MM.amp_sharding(mesh))
        return q.replace_amps(fn(amps))

    def compiled_sharded_measured(self, n: int, density: bool, mesh,
                                  donate: bool = True, engine: str = None,
                                  relabel: bool = None,
                                  interpret: bool = False):
        """Cached compile of the dynamic sharded program (see
        quest_tpu.parallel.sharded.compile_circuit_sharded_measured).
        engine: 'xla' (default) | 'banded' | 'fused'; relabel (default
        on for banded/fused) runs the layer-amortized relabel pass per
        measurement-free stretch."""
        from quest_tpu.parallel import sharded as S
        # the compiler's own defaulting, so equivalent calls share one
        # compiled program
        engine, relabel = S.resolve_measured_engine(engine, relabel)
        key_ = ("sharded-measured", n, density, mesh, donate, engine,
                relabel, interpret, _engine_mode_key())
        fn = self._compiled.get(key_)
        if fn is None:
            fn = S.compile_circuit_sharded_measured(
                self.ops, n, density, mesh, donate, engine=engine,
                relabel=relabel, interpret=interpret)
            self._compiled[key_] = fn
        return fn

    def apply_sharded_measured(self, q: Qureg, key, mesh,
                               donate: bool = False, engine: str = None,
                               relabel: bool = None,
                               interpret: bool = False):
        """Dynamic circuit over the device mesh: (register, outcomes).
        Mid-circuit measurement (psum probabilities, identical draws on
        every device) and classical feedback inside ONE shard_map
        program; measurement-free stretches relabel and fuse like the
        static engines (engine='banded'/'fused')."""
        from quest_tpu.parallel.mesh import amp_sharding
        if self.num_qubits != q.num_qubits:
            raise ValueError("circuit/register size mismatch")
        fn = self.compiled_sharded_measured(q.num_state_qubits,
                                            q.is_density, mesh, donate,
                                            engine, relabel, interpret)
        amps = jax.device_put(q.amps, amp_sharding(mesh))
        amps, outcomes = fn(amps, key)
        return q.replace_amps(amps), outcomes

    def apply_sharded(self, q: Qureg, mesh, donate: bool = False) -> Qureg:
        """Apply via the explicit shard_map engine on a mesh-sharded register."""
        if self.num_qubits != q.num_qubits:
            raise ValueError("circuit/register size mismatch")
        from quest_tpu.parallel import mesh as MM
        fn = self.compiled_sharded(q.num_state_qubits, q.is_density, mesh, donate)
        amps = jax.device_put(q.amps, MM.amp_sharding(mesh))
        return q.replace_amps(fn(amps))


# ---------------------------------------------------------------------------
# Benchmark circuit generators
# ---------------------------------------------------------------------------


def random_circuit(num_qubits: int, depth: int, seed: int = 0,
                   entangler: str = "cz") -> Circuit:
    """RCS-style benchmark circuit: layers of random single-qubit rotations
    followed by a brick pattern of entangling gates (BASELINE.json config
    '30-qubit random-circuit-sampling statevector')."""
    rng = np.random.default_rng(seed)
    c = Circuit(num_qubits)
    for d in range(depth):
        for q in range(num_qubits):
            angle = float(rng.uniform(0, 2 * np.pi))
            kind = rng.integers(0, 3)
            if kind == 0:
                c.rx(q, angle)
            elif kind == 1:
                c.ry(q, angle)
            else:
                c.rz(q, angle)
        start = d % 2
        for q in range(start, num_qubits - 1, 2):
            if entangler == "cz":
                c.cz(q, q + 1)
            else:
                c.cnot(q, q + 1)
    return c


def qft_circuit(num_qubits: int) -> Circuit:
    """Quantum Fourier transform (BASELINE.json config 'distributed QFT')."""
    c = Circuit(num_qubits)
    for q in reversed(range(num_qubits)):
        c.h(q)
        for j in range(q):
            angle = np.pi / (1 << (q - j))
            c._add("allones", (j, q), np.exp(1j * angle))
    for q in range(num_qubits // 2):
        c.swap(q, num_qubits - 1 - q)
    return c
