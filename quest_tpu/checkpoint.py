"""First-class state checkpoint / resume.

The reference's only state persistence is debug-grade CSV
(reportState / initStateFromSingleFile, QuEST_common.c:215-231,
QuEST_cpu.c:1593-1642 — kept, see quest_tpu.api). SURVEY.md flags this as
a real gap; here checkpointing is a first-class feature:

  * `save` / `load`: binary .npz of the (2, 2^n) float planes + register
    metadata — exact to the bit, any register size, any platform.
  * `save_sharded` / `load_sharded`: orbax-backed checkpoint of the
    sharded device array (per-shard files, suitable for multi-host pods
    where no single host holds the full state). Falls back with a clear
    error if orbax is unavailable.

Both paths restore INTO a freshly created register, so a checkpoint can be
reloaded under a different mesh/sharding than it was saved with (the
analogue of changing MPI rank counts between runs — something the
reference's CSV path also supports, one rank at a time).
"""

from __future__ import annotations

import json
import os


import jax
import numpy as np

from quest_tpu import precision
from quest_tpu import validation
from quest_tpu.state import Qureg, create_density_qureg, create_qureg

_META_NAME = "qureg_meta.json"
_AMPS_NAME = "amps.npz"
_ORBAX_DIR = "orbax"


def _meta(qureg: Qureg) -> dict:
    return {
        "num_qubits": qureg.num_qubits,
        "is_density": qureg.is_density,
        "real_dtype": str(np.dtype(qureg.real_dtype)),
        "format_version": 1,
    }


def save(qureg: Qureg, directory: str) -> None:
    """Write the full state to `directory` (host-gathered .npz planes)."""
    os.makedirs(directory, exist_ok=True)
    planes = np.asarray(jax.device_get(qureg.amps))
    np.savez(os.path.join(directory, _AMPS_NAME), planes=planes)
    with open(os.path.join(directory, _META_NAME), "w") as f:
        json.dump(_meta(qureg), f)


def load(directory: str, env=None, dtype=None) -> Qureg:
    """Recreate a register from a checkpoint written by `save`."""
    with open(os.path.join(directory, _META_NAME)) as f:
        meta = json.load(f)
    with np.load(os.path.join(directory, _AMPS_NAME)) as data:
        planes = data["planes"]
    rdt = np.dtype(meta["real_dtype"])
    cdt = dtype if dtype is not None else precision.complex_dtype_of(rdt)
    make = create_density_qureg if meta["is_density"] else create_qureg
    q = make(meta["num_qubits"], env=env, dtype=cdt)
    if planes.shape != q.amps.shape:
        raise validation.QuESTError(
            f"Invalid checkpoint: planes shape {planes.shape} does not match "
            f"a {meta['num_qubits']}-qubit register "
            f"(expected {tuple(q.amps.shape)})")
    amps = jax.device_put(jax.numpy.asarray(planes.astype(q.real_dtype)),
                          q.amps.sharding)
    return q.replace_amps(amps)


# ---------------------------------------------------------------------------
# sharded checkpoints (orbax): per-device files, no host gather
# ---------------------------------------------------------------------------


def _orbax():
    try:
        import orbax.checkpoint as ocp
        return ocp
    except ImportError as e:  # pragma: no cover
        raise validation.QuESTError(
            "Sharded checkpointing requires orbax-checkpoint; use "
            "quest_tpu.checkpoint.save/load for the host-gathered path"
        ) from e


class PendingCheckpoint:
    """Handle for an in-flight async checkpoint: `wait()` blocks until
    the files are durable. The state array was snapshotted at save time
    (orbax holds the device buffers), so the caller may keep mutating
    the register while the write streams out."""

    def __init__(self, ckptr):
        self._ckptr = ckptr

    def wait(self) -> None:
        self._ckptr.wait_until_finished()


def save_sharded(qureg: Qureg, directory: str,
                 block: bool = True) -> PendingCheckpoint:
    """Checkpoint the device array WITHOUT gathering to one host: each
    shard writes its own slice (orbax/tensorstore OCDBT).

    block=False returns immediately with a PendingCheckpoint while the
    write streams in the background — simulation continues overlapping
    the IO (the TPU-native pattern for multi-GB states; the snapshot is
    consistent even if the register keeps evolving, because the
    functional engine never mutates buffers in place unless donated —
    do NOT donate the checkpointed array before wait())."""
    ocp = _orbax()
    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    with open(os.path.join(directory, _META_NAME), "w") as f:
        json.dump(_meta(qureg), f)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(directory, _ORBAX_DIR), {"amps": qureg.amps},
               force=True)
    pending = PendingCheckpoint(ckptr)
    if block:
        pending.wait()
    return pending


def load_sharded(directory: str, env=None, dtype=None) -> Qureg:
    """Restore a sharded checkpoint directly into the target sharding
    (each device reads only its slice)."""
    ocp = _orbax()
    directory = os.path.abspath(directory)
    with open(os.path.join(directory, _META_NAME)) as f:
        meta = json.load(f)
    rdt = np.dtype(meta["real_dtype"])
    cdt = dtype if dtype is not None else precision.complex_dtype_of(rdt)
    make = create_density_qureg if meta["is_density"] else create_qureg
    q = make(meta["num_qubits"], env=env, dtype=cdt)
    target = jax.ShapeDtypeStruct(q.amps.shape, q.amps.dtype,
                                  sharding=q.amps.sharding)
    ckptr = ocp.StandardCheckpointer()
    restored = ckptr.restore(os.path.join(directory, _ORBAX_DIR),
                             {"amps": target})
    return q.replace_amps(restored["amps"])
