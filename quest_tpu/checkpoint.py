"""First-class state checkpoint / resume.

The reference's only state persistence is debug-grade CSV
(reportState / initStateFromSingleFile, QuEST_common.c:215-231,
QuEST_cpu.c:1593-1642 — kept, see quest_tpu.api). SURVEY.md flags this as
a real gap; here checkpointing is a first-class feature:

  * `save` / `load`: binary .npz of the (2, 2^n) float planes + register
    metadata — exact to the bit, any register size, any platform.
  * `save_sharded` / `load_sharded`: orbax-backed checkpoint of the
    sharded device array (per-shard files, suitable for multi-host pods
    where no single host holds the full state). Falls back with a clear
    error if orbax is unavailable.

Both paths restore INTO a freshly created register, so a checkpoint can be
reloaded under a different mesh/sharding than it was saved with (the
analogue of changing MPI rank counts between runs — something the
reference's CSV path also supports, one rank at a time).
"""

from __future__ import annotations

import json
import os


import jax
import numpy as np

from quest_tpu import precision
from quest_tpu import validation
from quest_tpu.state import Qureg, create_density_qureg, create_qureg

_META_NAME = "qureg_meta.json"
_AMPS_NAME = "amps.npz"
_ORBAX_DIR = "orbax"
# magic + version written since format 2: load() can tell "not a quest
# checkpoint at all" from "a quest checkpoint from the future" from "a
# quest checkpoint that's merely corrupt" — three different clear
# errors instead of one leaked KeyError/BadZipFile. Version-1
# checkpoints predate the field and load tolerantly.
_MAGIC = "quest-checkpoint"
_FORMAT_VERSION = 2


class CheckpointError(validation.QuESTError):
    """A checkpoint could not be read: missing/corrupt/truncated files
    or metadata that does not match the register being restored. The
    message always names the offending file and the mismatch — numpy /
    orbax internals never leak to the caller (docs/RESILIENCE.md)."""


def _meta(qureg: Qureg) -> dict:
    return {
        "magic": _MAGIC,
        "num_qubits": qureg.num_qubits,
        "is_density": qureg.is_density,
        "real_dtype": str(np.dtype(qureg.real_dtype)),
        "format_version": _FORMAT_VERSION,
    }


def _read_meta(directory: str) -> dict:
    """Read + validate the checkpoint metadata, raising ONE clear
    CheckpointError (naming the file and the problem) for every way the
    file can be missing, truncated, non-JSON, not-a-checkpoint, from a
    future format, or incomplete. Pre-magic (format 1) checkpoints load
    tolerantly."""
    path = os.path.join(directory, _META_NAME)
    try:
        with open(path) as f:
            meta = json.load(f)
    except FileNotFoundError:
        raise CheckpointError(
            f"Invalid checkpoint: metadata file {path!r} is missing — "
            f"{directory!r} is not a checkpoint directory") from None
    except (OSError, ValueError) as e:
        raise CheckpointError(
            f"Invalid checkpoint: metadata file {path!r} is corrupt or "
            f"truncated (not parseable JSON: {e})") from e
    if not isinstance(meta, dict):
        raise CheckpointError(
            f"Invalid checkpoint: metadata file {path!r} does not hold "
            f"a JSON object (got {type(meta).__name__})")
    magic = meta.get("magic")
    if magic is not None and magic != _MAGIC:
        raise CheckpointError(
            f"Invalid checkpoint: {path!r} carries magic {magic!r}, "
            f"expected {_MAGIC!r} — not a quest_tpu checkpoint")
    version = meta.get("format_version", 1)
    if not isinstance(version, int) or version > _FORMAT_VERSION:
        raise CheckpointError(
            f"Invalid checkpoint: {path!r} is format_version "
            f"{version!r}, newer than this build supports "
            f"(<= {_FORMAT_VERSION}) — upgrade quest_tpu to load it")
    missing = [k for k in ("num_qubits", "is_density", "real_dtype")
               if k not in meta]
    if missing:
        raise CheckpointError(
            f"Invalid checkpoint: {path!r} is missing required "
            f"field(s) {missing}")
    return meta


def save(qureg: Qureg, directory: str) -> None:
    """Write the full state to `directory` (host-gathered .npz planes)."""
    os.makedirs(directory, exist_ok=True)
    planes = np.asarray(jax.device_get(qureg.amps))
    np.savez(os.path.join(directory, _AMPS_NAME), planes=planes)
    with open(os.path.join(directory, _META_NAME), "w") as f:
        json.dump(_meta(qureg), f)


def load(directory: str, env=None, dtype=None) -> Qureg:
    """Recreate a register from a checkpoint written by `save`. Every
    failure mode — missing/corrupt/truncated files, metadata that does
    not match the stored planes — raises CheckpointError naming the
    file and the mismatch (never a leaked numpy/zipfile internal)."""
    meta = _read_meta(directory)
    amps_path = os.path.join(directory, _AMPS_NAME)
    try:
        with np.load(amps_path) as data:
            if "planes" not in data:
                raise CheckpointError(
                    f"Invalid checkpoint: {amps_path!r} holds no "
                    f"'planes' array (found {sorted(data.files)})")
            planes = data["planes"]
    except CheckpointError:
        raise
    except FileNotFoundError:
        raise CheckpointError(
            f"Invalid checkpoint: amplitude file {amps_path!r} is "
            f"missing") from None
    except Exception as e:
        # np.load surfaces truncation/corruption as BadZipFile, OSError,
        # ValueError or EOFError depending on WHERE the bytes stop —
        # collapse them into the one documented error
        raise CheckpointError(
            f"Invalid checkpoint: amplitude file {amps_path!r} is "
            f"corrupt or truncated ({type(e).__name__}: {e})") from e
    try:
        rdt = np.dtype(meta["real_dtype"])
    except TypeError as e:
        raise CheckpointError(
            f"Invalid checkpoint: metadata in {directory!r} names "
            f"unknown real_dtype {meta['real_dtype']!r}") from e
    cdt = dtype if dtype is not None else precision.complex_dtype_of(rdt)
    make = create_density_qureg if meta["is_density"] else create_qureg
    q = make(meta["num_qubits"], env=env, dtype=cdt)
    if planes.shape != q.amps.shape:
        raise CheckpointError(
            f"Invalid checkpoint: {amps_path!r} holds planes of shape "
            f"{tuple(planes.shape)}, which does not match the "
            f"{meta['num_qubits']}-qubit register its metadata declares "
            f"(expected {tuple(q.amps.shape)})")
    amps = jax.device_put(jax.numpy.asarray(planes.astype(q.real_dtype)),
                          q.amps.sharding)
    return q.replace_amps(amps)


# ---------------------------------------------------------------------------
# sharded checkpoints (orbax): per-device files, no host gather
# ---------------------------------------------------------------------------


def _orbax():
    try:
        import orbax.checkpoint as ocp
        return ocp
    except ImportError as e:  # pragma: no cover
        raise validation.QuESTError(
            "Sharded checkpointing requires orbax-checkpoint; use "
            "quest_tpu.checkpoint.save/load for the host-gathered path"
        ) from e


class PendingCheckpoint:
    """Handle for an in-flight async checkpoint: `wait()` blocks until
    the files are durable. The state array was snapshotted at save time
    (orbax holds the device buffers), so the caller may keep mutating
    the register while the write streams out."""

    def __init__(self, ckptr):
        self._ckptr = ckptr

    def wait(self) -> None:
        self._ckptr.wait_until_finished()


def save_sharded(qureg: Qureg, directory: str,
                 block: bool = True) -> PendingCheckpoint:
    """Checkpoint the device array WITHOUT gathering to one host: each
    shard writes its own slice (orbax/tensorstore OCDBT).

    block=False returns immediately with a PendingCheckpoint while the
    write streams in the background — simulation continues overlapping
    the IO (the TPU-native pattern for multi-GB states; the snapshot is
    consistent even if the register keeps evolving, because the
    functional engine never mutates buffers in place unless donated —
    do NOT donate the checkpointed array before wait())."""
    ocp = _orbax()
    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    with open(os.path.join(directory, _META_NAME), "w") as f:
        json.dump(_meta(qureg), f)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(directory, _ORBAX_DIR), {"amps": qureg.amps},
               force=True)
    pending = PendingCheckpoint(ckptr)
    if block:
        pending.wait()
    return pending


def load_sharded(directory: str, env=None, dtype=None) -> Qureg:
    """Restore a sharded checkpoint directly into the target sharding
    (each device reads only its slice)."""
    ocp = _orbax()
    directory = os.path.abspath(directory)
    meta = _read_meta(directory)
    try:
        rdt = np.dtype(meta["real_dtype"])
    except TypeError as e:
        raise CheckpointError(
            f"Invalid checkpoint: metadata in {directory!r} names "
            f"unknown real_dtype {meta['real_dtype']!r}") from e
    cdt = dtype if dtype is not None else precision.complex_dtype_of(rdt)
    make = create_density_qureg if meta["is_density"] else create_qureg
    q = make(meta["num_qubits"], env=env, dtype=cdt)
    target = jax.ShapeDtypeStruct(q.amps.shape, q.amps.dtype,
                                  sharding=q.amps.sharding)
    orbax_dir = os.path.join(directory, _ORBAX_DIR)
    ckptr = ocp.StandardCheckpointer()
    try:
        restored = ckptr.restore(orbax_dir, {"amps": target})
    except Exception as e:
        # orbax/tensorstore failures (missing dir, corrupt OCDBT shards,
        # shape/dtype mismatch vs the target) surface as a zoo of
        # library-internal types — collapse to the one documented error,
        # keeping the cause chained for debugging
        raise CheckpointError(
            f"Invalid checkpoint: sharded payload under {orbax_dir!r} "
            f"is missing, corrupt, or does not match the "
            f"{meta['num_qubits']}-qubit register its metadata declares "
            f"({type(e).__name__}: {str(e)[:300]})") from e
    return q.replace_amps(restored["amps"])
