"""First-class state checkpoint / resume.

The reference's only state persistence is debug-grade CSV
(reportState / initStateFromSingleFile, QuEST_common.c:215-231,
QuEST_cpu.c:1593-1642 — kept, see quest_tpu.api). SURVEY.md flags this as
a real gap; here checkpointing is a first-class feature:

  * `save` / `load`: binary .npz of the (2, 2^n) float planes + register
    metadata — exact to the bit, any register size, any platform.
    Writes are ATOMIC (temp dir + rename commit), so a crash mid-save
    never leaves a half-written checkpoint where a complete one stood.
  * per-plane SHA-256 digests stamped at save (format_version 3) and
    verified at load: a flipped bit on disk raises `CheckpointError`
    NAMING the corrupt plane and the expected/got digests instead of
    silently resuming from garbage. v1/v2 checkpoints (pre-digest)
    still load, with a one-time stderr warning (the native.py degrade
    pattern).
  * `save_step` / `step_dirs`: versioned `ckpt-<step>` checkpoints under
    one root with keep-last-K retention (`QUEST_CHECKPOINT_KEEP`) — the
    durable executor's resume chain (quest_tpu/resilience/durable.py,
    docs/RESILIENCE.md §durable).
  * `save_sharded` / `load_sharded`: orbax-backed checkpoint of the
    sharded device array (per-shard files, suitable for multi-host pods
    where no single host holds the full state). Falls back with a clear
    error if orbax is unavailable.

Both npz paths restore INTO a freshly created register, so a checkpoint
can be reloaded under a different mesh/sharding than it was saved with
(the analogue of changing MPI rank counts between runs — something the
reference's CSV path also supports, one rank at a time).

Fault sites (docs/RESILIENCE.md): `checkpoint.save` fires at the commit
point (after the temp files are written, before the rename) — an
injected error there emulates a crash mid-save and must leave the
previous checkpoint loadable; `checkpoint.load` fires at the top of the
read path.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import sys
import uuid


import jax
import numpy as np

from quest_tpu import precision
from quest_tpu import validation
from quest_tpu.resilience import faults
from quest_tpu.state import Qureg, create_density_qureg, create_qureg

_META_NAME = "qureg_meta.json"
_AMPS_NAME = "amps.npz"
_ORBAX_DIR = "orbax"
# magic + version written since format 2: load() can tell "not a quest
# checkpoint at all" from "a quest checkpoint from the future" from "a
# quest checkpoint that's merely corrupt" — three different clear
# errors instead of one leaked KeyError/BadZipFile. Version-1
# checkpoints predate the field; format 3 adds per-plane digests.
# Pre-3 checkpoints load tolerantly (one stderr warning per process).
_MAGIC = "quest-checkpoint"
_FORMAT_VERSION = 3
# {:08d} zero-pads SMALL steps; a step past 10^8 (trajectory chains
# index by shots done) widens the field, so the matcher must accept it
_STEP_RE = re.compile(r"^ckpt-(\d{8,})$")

_legacy_warned = False


class CheckpointError(validation.QuESTError):
    """A checkpoint could not be read: missing/corrupt/truncated files,
    a failed per-plane integrity digest, or metadata that does not match
    the register being restored. The message always names the offending
    file (and for digest failures, the plane plus expected/got digests)
    — numpy / orbax internals never leak to the caller
    (docs/RESILIENCE.md)."""


def _warn_legacy_once(directory: str, version: int) -> None:
    """One warning per process when a pre-digest (v1/v2) checkpoint
    loads: the load is tolerant — the fields are additive — but the
    planes carry no integrity checksums, so corruption there is
    undetectable (the native.py degrade-to-Python warn-once pattern)."""
    global _legacy_warned
    if _legacy_warned:
        return
    _legacy_warned = True
    print(f"[quest_tpu.checkpoint] loading format_version {version} "
          f"checkpoint from {directory!r}: no per-plane checksums "
          f"(added in format 3) — corruption on disk cannot be "
          f"detected; re-save to upgrade", file=sys.stderr, flush=True)


def _digest(arr: np.ndarray) -> str:
    h = hashlib.sha256()
    # feed the array's buffer directly — .tobytes() would copy the
    # whole plane per checkpoint (checkpoint cadence is a hot path for
    # the durable executor's overhead budget)
    h.update(memoryview(np.ascontiguousarray(arr)).cast("B"))
    return h.hexdigest()


def _meta_digest(meta: dict) -> str:
    """Self-digest of the metadata (canonical JSON, the digest field
    itself excluded): the meta carries the durable RESUME CURSOR, and a
    corrupted-but-parseable cursor (one flipped digit in 'step') would
    otherwise resume silently to wrong amplitudes — the per-plane
    digests only cover the array bytes."""
    clean = {k: v for k, v in meta.items() if k != "meta_digest"}
    return hashlib.sha256(
        json.dumps(clean, sort_keys=True,
                   separators=(",", ":")).encode()).hexdigest()


def _plane_digests(arrays: dict) -> dict:
    """Per-plane SHA-256 digests of a checkpoint payload: the 'planes'
    array's leading re/im planes digest separately (so the error can
    name WHICH plane rotted), every other array digests whole."""
    out = {}
    for name, arr in arrays.items():
        arr = np.asarray(arr)
        if name == "planes" and arr.ndim >= 1 and arr.shape[0] == 2:
            out["planes[re]"] = _digest(arr[0])
            out["planes[im]"] = _digest(arr[1])
        else:
            out[name] = _digest(arr)
    return out


def _digest_target(name: str, arrays: dict):
    """The array (or plane slice) a digest entry names, or None when its
    base array is absent from the payload."""
    m = re.match(r"^(.*)\[(re|im)\]$", name)
    if m:
        base = arrays.get(m.group(1))
        if base is None or base.ndim < 1 or base.shape[0] < 2:
            # a corrupt rewrite can shrink the stored array below the
            # plane index: treat it as the plane being missing (one
            # documented CheckpointError, never a leaked IndexError —
            # the durable resume chain must SKIP this, not crash)
            return None
        return base[0 if m.group(2) == "re" else 1]
    return arrays.get(name)


def _meta(qureg: Qureg) -> dict:
    return {
        "magic": _MAGIC,
        "num_qubits": qureg.num_qubits,
        "is_density": qureg.is_density,
        "real_dtype": str(np.dtype(qureg.real_dtype)),
        "format_version": _FORMAT_VERSION,
    }


def _read_meta(directory: str) -> dict:
    """Read + validate the checkpoint metadata, raising ONE clear
    CheckpointError (naming the file and the problem) for every way the
    file can be missing, truncated, non-JSON, not-a-checkpoint, from a
    future format, or incomplete. Pre-magic (format 1) checkpoints load
    tolerantly."""
    path = os.path.join(directory, _META_NAME)
    try:
        with open(path) as f:
            meta = json.load(f)
    except FileNotFoundError:
        raise CheckpointError(
            f"Invalid checkpoint: metadata file {path!r} is missing — "
            f"{directory!r} is not a checkpoint directory") from None
    except (OSError, ValueError) as e:
        raise CheckpointError(
            f"Invalid checkpoint: metadata file {path!r} is corrupt or "
            f"truncated (not parseable JSON: {e})") from e
    if not isinstance(meta, dict):
        raise CheckpointError(
            f"Invalid checkpoint: metadata file {path!r} does not hold "
            f"a JSON object (got {type(meta).__name__})")
    magic = meta.get("magic")
    if magic is not None and magic != _MAGIC:
        raise CheckpointError(
            f"Invalid checkpoint: {path!r} carries magic {magic!r}, "
            f"expected {_MAGIC!r} — not a quest_tpu checkpoint")
    version = meta.get("format_version", 1)
    if not isinstance(version, int) or version > _FORMAT_VERSION:
        raise CheckpointError(
            f"Invalid checkpoint: {path!r} is format_version "
            f"{version!r}, newer than this build supports "
            f"(<= {_FORMAT_VERSION}) — upgrade quest_tpu to load it")
    if meta.get("payload", "qureg") == "qureg":
        missing = [k for k in ("num_qubits", "is_density", "real_dtype")
                   if k not in meta]
        if missing:
            raise CheckpointError(
                f"Invalid checkpoint: {path!r} is missing required "
                f"field(s) {missing}")
    return meta


# ---------------------------------------------------------------------------
# atomic write + verified read of the npz payload
# ---------------------------------------------------------------------------


def _write_atomic(directory: str, meta: dict, arrays: dict) -> None:
    """Write a complete checkpoint into a sibling temp dir, then commit
    with one directory rename: a crash at ANY point before the commit
    leaves the target untouched (either absent or the previous complete
    checkpoint); a crash after it leaves the new complete checkpoint.
    The `checkpoint.save` fault site fires at the commit point so
    tests/soaks can emulate the mid-save crash deterministically. The
    overwrite path (target already a directory) swaps via a second
    sibling rename — never half-written, but a hard kill inside its
    two-syscall window leaves the target absent with the previous
    payload stranded under a `.old-<tag>` sibling (recoverable by
    hand); the versioned save_step path therefore ALWAYS commits to a
    fresh name (same-step leftovers are deleted first) and is fully
    atomic."""
    directory = os.path.abspath(directory)
    parent = os.path.dirname(directory) or "."
    os.makedirs(parent, exist_ok=True)
    if os.path.isdir(directory) and os.listdir(directory) \
            and not os.path.exists(os.path.join(directory, _META_NAME)):
        # the swap below REPLACES the whole target directory; silently
        # rmtree'ing a non-checkpoint directory a caller pointed at by
        # mistake would destroy unrelated files (the old merge-write
        # behavior tolerated that call; refusing loudly is safer)
        raise ValueError(
            f"refusing to overwrite {directory!r}: it exists, is not "
            f"empty, and holds no {_META_NAME} — not a checkpoint "
            f"directory; pick a new/empty path")
    meta = dict(meta)
    meta["plane_digests"] = _plane_digests(arrays)
    meta["meta_digest"] = _meta_digest(meta)
    tag = f"{os.getpid()}-{uuid.uuid4().hex[:8]}"
    tmp = f"{directory}.tmp-{tag}"
    os.makedirs(tmp)
    try:
        np.savez(os.path.join(tmp, _AMPS_NAME), **arrays)
        with open(os.path.join(tmp, _META_NAME), "w") as f:
            json.dump(meta, f)
        # the commit point: an injected error here aborts BEFORE the
        # rename, so the previous checkpoint (if any) stays loadable —
        # the mid-save-crash contract (a python-level abort also cleans
        # its temp dir below; only a hard kill leaves one behind, and
        # sweep_stale/prune_steps reclaims those)
        if faults.ACTIVE:
            faults.check("checkpoint.save", directory=directory, tmp=tmp)
        if os.path.isdir(directory):
            if not os.listdir(directory):
                os.rmdir(directory)          # empty dir: plain commit
                os.rename(tmp, directory)
            else:
                old = f"{directory}.old-{tag}"
                os.rename(directory, old)
                try:
                    os.rename(tmp, directory)
                except BaseException:
                    # best-effort rollback so a python-level rename
                    # failure doesn't leave the target absent
                    os.rename(old, directory)
                    raise
                shutil.rmtree(old, ignore_errors=True)
        else:
            os.rename(tmp, directory)
    except BaseException:
        # a FAILED (python-level) save must not leak a payload-sized
        # temp dir per attempt — long durable runs on flaky disks would
        # otherwise grow the checkpoint root unboundedly. (A hard kill
        # still leaves the tmp; step_dirs ignores it and sweep_stale
        # reclaims it.)
        shutil.rmtree(tmp, ignore_errors=True)
        raise


def load_arrays(directory: str, require=()):
    """(meta, arrays) of a checkpoint written by `save` / `save_arrays`
    / `save_step`, with every per-plane digest VERIFIED against the
    stored bytes (format 3; pre-digest checkpoints warn once on stderr
    and load unverified). `require` names arrays that must be present
    (the qureg loader requires 'planes'). Every failure mode raises
    CheckpointError naming the file and the mismatch."""
    if faults.ACTIVE:
        faults.check("checkpoint.load", directory=directory)
    meta = _read_meta(directory)
    amps_path = os.path.join(directory, _AMPS_NAME)
    try:
        with np.load(amps_path) as data:
            arrays = {k: data[k] for k in data.files}
    except FileNotFoundError:
        raise CheckpointError(
            f"Invalid checkpoint: amplitude file {amps_path!r} is "
            f"missing") from None
    except Exception as e:
        # np.load surfaces truncation/corruption as BadZipFile, OSError,
        # ValueError or EOFError depending on WHERE the bytes stop —
        # collapse them into the one documented error
        raise CheckpointError(
            f"Invalid checkpoint: amplitude file {amps_path!r} is "
            f"corrupt or truncated ({type(e).__name__}: {e})") from e
    for name in require:
        if name not in arrays:
            raise CheckpointError(
                f"Invalid checkpoint: {amps_path!r} holds no "
                f"{name!r} array (found {sorted(arrays)})")
    version = meta.get("format_version", 1)
    md = meta.get("meta_digest")
    if md is not None and _meta_digest(meta) != md:
        raise CheckpointError(
            f"Invalid checkpoint: metadata in {directory!r} fails its "
            f"self-digest — the cursor/fields were altered after the "
            f"save (corrupt meta resumes to WRONG amplitudes; refusing "
            f"to load)")
    if md is None and version >= 3:
        raise CheckpointError(
            f"Invalid checkpoint: metadata in {directory!r} claims "
            f"format_version {version} but carries no meta_digest — "
            f"the integrity metadata was stripped or the file is "
            f"corrupt")
    digests = meta.get("plane_digests")
    if digests:
        for name, expect in sorted(digests.items()):
            target = _digest_target(name, arrays)
            if target is None:
                raise CheckpointError(
                    f"Invalid checkpoint: {amps_path!r} is missing the "
                    f"digested array behind plane {name!r} "
                    f"(found {sorted(arrays)})")
            got = _digest(np.asarray(target))
            if got != expect:
                raise CheckpointError(
                    f"Invalid checkpoint: plane {name!r} in "
                    f"{amps_path!r} fails its integrity digest "
                    f"(expected sha256 {expect[:16]}…, got {got[:16]}…)"
                    f" — the stored bytes are corrupt; refusing to "
                    f"restore from them")
    elif version >= 3:
        # a v3 meta with the digest table stripped is not "old and
        # tolerable", it is tampered/corrupt: loading it unverified
        # would silently void the format-3 integrity guarantee
        raise CheckpointError(
            f"Invalid checkpoint: metadata in {directory!r} claims "
            f"format_version {version} but carries no plane_digests "
            f"table — the integrity metadata was stripped or the file "
            f"is corrupt; refusing to load unverified planes")
    else:
        _warn_legacy_once(directory, version)
    return meta, arrays


def read_extra(directory: str):
    """The `extra` payload stored by save(..., extra=) — the durable
    executor's cursor — without touching the amplitude arrays. Returns
    None when the checkpoint carries no extra payload."""
    return _read_meta(directory).get("extra")


def save(qureg: Qureg, directory: str, extra=None) -> None:
    """Write the full state to `directory` (host-gathered .npz planes),
    ATOMICALLY: the payload lands in a temp dir and commits with one
    rename, so a crash mid-save never corrupts an existing checkpoint
    at the same path. Per-plane digests are stamped into the metadata
    (format 3) and verified on load. `extra` (a JSON-serializable dict)
    rides in the metadata — the durable executor's cursor; read it back
    with `read_extra` / the meta of `load_arrays`."""
    planes = np.asarray(jax.device_get(qureg.amps))
    meta = _meta(qureg)
    if extra is not None:
        meta["extra"] = extra
    _write_atomic(directory, meta, {"planes": planes})


def save_arrays(directory: str, arrays: dict, extra=None) -> None:
    """Atomic checkpoint of raw named arrays (payload='arrays'): the
    durable TRAJECTORY executor's accumulated (shots, 2, 2^n) planes +
    draws, digested and verified exactly like the qureg payload. Load
    with `load_arrays`; `load` rejects it loudly (it is not a register
    snapshot)."""
    for name in arrays:
        if re.search(r"\[(re|im)\]$", name):
            # such a name would collide with the per-plane digest
            # entries ('planes[re]'/'planes[im]') and write a
            # checkpoint _digest_target can never resolve — i.e. a
            # valid save that is permanently unreadable
            raise ValueError(
                f"array name {name!r} must not end with '[re]'/'[im]' "
                f"(reserved for per-plane digest entries)")
    meta = {"magic": _MAGIC, "format_version": _FORMAT_VERSION,
            "payload": "arrays"}
    if extra is not None:
        meta["extra"] = extra
    _write_atomic(directory, meta,
                  {k: np.asarray(jax.device_get(v))
                   for k, v in arrays.items()})


def load(directory: str, env=None, dtype=None) -> Qureg:
    """Recreate a register from a checkpoint written by `save`. Every
    failure mode — missing/corrupt/truncated files, a failed per-plane
    digest, metadata that does not match the stored planes — raises
    CheckpointError naming the file and the mismatch (never a leaked
    numpy/zipfile internal)."""
    meta, arrays = load_arrays(directory, require=("planes",))
    if meta.get("payload", "qureg") != "qureg":
        raise CheckpointError(
            f"Invalid checkpoint: {directory!r} holds a "
            f"{meta['payload']!r} payload, not a register snapshot — "
            f"use checkpoint.load_arrays")
    planes = arrays["planes"]
    amps_path = os.path.join(directory, _AMPS_NAME)
    try:
        rdt = np.dtype(meta["real_dtype"])
    except TypeError as e:
        raise CheckpointError(
            f"Invalid checkpoint: metadata in {directory!r} names "
            f"unknown real_dtype {meta['real_dtype']!r}") from e
    cdt = dtype if dtype is not None else precision.complex_dtype_of(rdt)
    make = create_density_qureg if meta["is_density"] else create_qureg
    q = make(meta["num_qubits"], env=env, dtype=cdt)
    if planes.shape != q.amps.shape:
        raise CheckpointError(
            f"Invalid checkpoint: {amps_path!r} holds planes of shape "
            f"{tuple(planes.shape)}, which does not match the "
            f"{meta['num_qubits']}-qubit register its metadata declares "
            f"(expected {tuple(q.amps.shape)})")
    amps = jax.device_put(jax.numpy.asarray(planes.astype(q.real_dtype)),
                          q.amps.sharding)
    return q.replace_amps(amps)


# ---------------------------------------------------------------------------
# versioned step checkpoints: the durable executor's resume chain
# ---------------------------------------------------------------------------


def step_path(root: str, step: int) -> str:
    return os.path.join(root, f"ckpt-{int(step):08d}")


def step_dirs(root: str):
    """[(step, path)] of the versioned checkpoints under `root`,
    ascending by step. Temp/old dirs from interrupted saves and foreign
    entries are ignored — only committed `ckpt-<step>` names count."""
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        m = _STEP_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(root, name)))
    return sorted(out)


_STALE_RE = re.compile(r"^ckpt-\d{8,}\.(tmp|old)-")


def sweep_stale(root: str) -> int:
    """Reclaim payload-sized `.tmp-*`/`.old-*` leftovers that hard
    kills strand under a step-checkpoint root (the preemptible-pod
    headline scenario kills mid-save REPEATEDLY — without a sweep the
    root grows by a full-state payload per kill). Safe under the
    chain's single-writer contract: a live save's temp dir belongs to
    THIS process and is never mid-flight while prune_steps runs.
    Returns the number of entries removed."""
    if not os.path.isdir(root):
        return 0
    removed = 0
    for name in os.listdir(root):
        if _STALE_RE.match(name):
            shutil.rmtree(os.path.join(root, name), ignore_errors=True)
            removed += 1
    return removed


def prune_steps(root: str, keep: int = None) -> None:
    """Keep-last-K retention over the versioned checkpoints under
    `root` (default: the QUEST_CHECKPOINT_KEEP knob, 2): at least two
    survivors means a checkpoint that turns out corrupt on resume
    always leaves an older valid one to fall back to. Also sweeps
    stale `.tmp-*`/`.old-*` leftovers from killed saves."""
    if keep is None:
        from quest_tpu.env import knob_value
        keep = knob_value("QUEST_CHECKPOINT_KEEP")
    keep = int(keep)
    if keep < 1:
        raise ValueError(f"keep must be >= 1, got {keep}")
    for _, path in step_dirs(root)[:-keep]:
        shutil.rmtree(path, ignore_errors=True)
    sweep_stale(root)


def save_step(root: str, step: int, *, qureg: Qureg = None, arrays=None,
              extra=None, keep: int = None) -> str:
    """Atomic versioned checkpoint `root/ckpt-<step>` of either a
    register (`qureg=`) or raw arrays (`arrays=`), then keep-last-K
    retention (prune_steps). Step numbers must be distinct per root —
    the durable executor's monotone cut index. Returns the committed
    path."""
    if (qureg is None) == (arrays is None):
        raise ValueError("save_step takes exactly one of qureg=/arrays=")
    path = step_path(root, step)
    if os.path.isdir(path):
        # a same-step leftover is either corrupt (the durable resume
        # skipped it and is now replaying past its cut) or identical by
        # deterministic replay; removing it first keeps the commit on
        # the fully-atomic fresh-name rename — the two-rename overwrite
        # swap has a crash window that strands the old payload under an
        # undiscoverable .old- name, and an older valid checkpoint
        # survives either way (keep-last-K), so deleting loses nothing
        shutil.rmtree(path, ignore_errors=True)
    if qureg is not None:
        save(qureg, path, extra=extra)
    else:
        save_arrays(path, arrays, extra=extra)
    prune_steps(root, keep)
    return path


# ---------------------------------------------------------------------------
# gang-consistent multi-host step checkpoints (two-phase commit)
# ---------------------------------------------------------------------------
#
# The durable executor on a MULTI-HOST mesh (2-process gloo in tests, a
# real pod slice in production) cannot use save_step: no single host
# holds the full planes, and H independent per-host checkpoints could
# commit on some hosts and not others — a resume would then splice two
# different cuts. The gang protocol below writes ONE checkpoint per
# cursor step, committed ALL-OR-NOTHING, with NO collectives in the
# protocol itself (a host killed mid-save must never hang the
# survivors in a barrier — the reason this is hand-rolled instead of
# riding orbax's coordination-service save, whose internal barriers
# would deadlock exactly the mid-save-kill case the tests pin;
# docs/RESILIENCE.md §gang-consistent durable):
#
#   PREPARE  each host atomically writes its addressable slice
#            (shard-<p>.npz) plus its own digested meta (meta-<p>.json,
#            carrying the cursor) into the SHARED tmp dir, then stamps
#            prepared-<p>. The checkpoint.save fault site fires before
#            the stamp — an injected mid-save crash leaves the gang
#            unprepared forever.
#   COMMIT   whichever host completes the prepared set LAST renames the
#            tmp dir to ckpt-<step> — one atomic syscall; the rename
#            race between simultaneous completers is benign (one wins,
#            the loser sees the committed target). A missing stamp
#            means NO host ever commits: all hosts stamp or none do.
#
# Validity is a GANG property computed identically on every host:
# load_step_gang verifies EVERY shard's digests (not just its own), so
# corruption anywhere makes all hosts skip to the same older
# checkpoint — hosts can never resume from different cuts without a
# coordinator. Requires a shared filesystem across hosts (GCS/NFS on a
# pod; /tmp in the gloo tests), like every multi-host checkpointer.


def _gang_shard_meta(qureg: Qureg, process_index: int,
                     process_count: int, extra) -> Tuple[dict, dict]:
    """(meta, arrays) of THIS host's contiguous slice of the sharded
    plane pair. The slice bounds ride the meta so load can reassemble
    without knowing the sharding that wrote it."""
    shards = sorted(qureg.amps.addressable_shards,
                    key=lambda s: s.index[-1].start or 0)
    lo = shards[0].index[-1].start or 0
    nxt = lo
    datas = []
    for s in shards:
        start = s.index[-1].start or 0
        if start != nxt:
            raise CheckpointError(
                f"gang checkpointing requires a contiguous per-host "
                f"slice (1-D amplitude meshes); got shard at column "
                f"{start}, expected {nxt}")
        data = np.asarray(jax.device_get(s.data))
        datas.append(data)
        nxt = start + data.shape[-1]
    block = np.concatenate(datas, axis=-1)
    meta = dict(_meta(qureg))
    meta.update({
        "payload": "gang-shard",
        "process_index": process_index,
        "process_count": process_count,
        "slice_lo": int(lo),
        "slice_hi": int(lo + block.shape[-1]),
    })
    if extra is not None:
        meta["extra"] = extra
    return meta, {"planes": block}


def save_step_gang(root: str, step: int, *, qureg: Qureg, extra=None,
                   keep: int = None) -> Optional[str]:
    """Gang-consistent versioned checkpoint `root/ckpt-<step>` of a
    multi-host sharded register: every participating process calls this
    with the same arguments; each writes only its addressable slice
    into the SHARED tmp dir, stamps prepared-<p>, and whichever host
    completes the stamp set commits with one atomic rename — all hosts
    stamp or none do, and no step of the protocol waits on another
    host (docs/RESILIENCE.md §gang-consistent durable).

    Returns the committed path when THIS host performed the commit,
    None otherwise (the commit may land on any host; it is
    all-or-nothing either way). A retry of the same step — a resumed
    run replaying to the same cut after a mid-save kill — reuses the
    tmp dir: execution is deterministic from the shared resume point,
    so a surviving stale shard is bit-identical to what the retry
    would rewrite, and a peer committing mid-rewrite is benign (the
    writes below tolerate the tmp dir vanishing into a committed
    target). Single-process meshes fall through to the plain atomic
    save_step."""
    p = jax.process_index()
    nproc = jax.process_count()
    if nproc == 1:
        return save_step(root, step, qureg=qureg, extra=extra, keep=keep)
    path = step_path(root, step)
    tmp = f"{path}.tmp-gang"
    meta, arrays = _gang_shard_meta(qureg, p, nproc, extra)
    meta["plane_digests"] = _plane_digests(arrays)
    meta["meta_digest"] = _meta_digest(meta)
    tag = f"{os.getpid()}-{uuid.uuid4().hex[:8]}"

    def _put(name, write):
        """Atomically publish tmp/<name>: write to a dotfile sibling,
        rename into place. A committed target with NO tmp beside it
        means a peer already took this very step (deterministic-replay
        retry race) — checked before makedirs, which would otherwise
        resurrect the renamed-away tmp dir and strand a stray copy
        holding only this host's files. ENOENT mid-write means the tmp
        vanished under us (a peer committed, or finished the run and
        cleared the chain); either way THIS host's contribution is
        moot and skipping is benign (the checkpoint is all-or-nothing
        regardless)."""
        try:
            if not os.path.isdir(tmp) and os.path.isdir(path):
                return False
            os.makedirs(tmp, exist_ok=True)
            scratch = os.path.join(tmp, f".{name}-{tag}")
            write(scratch)
            os.rename(scratch, os.path.join(tmp, name))
            return True
        except FileNotFoundError:
            return False

    def _write_npz(dst):
        with open(dst, "wb") as f:
            np.savez(f, **arrays)

    def _write_meta(dst):
        with open(dst, "w") as f:
            json.dump(meta, f)

    def _write_stamp(dst):
        with open(dst, "w") as f:
            f.write("ok")

    if not _put(f"shard-{p}.npz", _write_npz) \
            or not _put(f"meta-{p}.json", _write_meta):
        return None          # a peer committed this very step already
    # the mid-save crash point: firing here (AFTER the payload, BEFORE
    # the stamp) emulates a host killed mid-save — its stamp never
    # appears, so NO host ever commits this step (all-or-nothing)
    if faults.ACTIVE:
        faults.check("checkpoint.save", directory=path, tmp=tmp,
                     process=p)
    if not _put(f"prepared-{p}", _write_stamp):
        return None
    committed = None
    if all(os.path.exists(os.path.join(tmp, f"prepared-{q}"))
           for q in range(nproc)):
        # this host completed the set: commit. Two completers may race
        # here — exactly one rename succeeds; the loser's tmp is GONE
        # (the winner renamed it away, and may even have finished the
        # run and consumed the chain already), which is success by
        # proxy, not an error. A same-step leftover target (an earlier
        # chain generation whose commit was later skipped corrupt)
        # still holds tmp in place: clear it and retry once.
        for attempt in range(2):
            try:
                os.rename(tmp, path)
                committed = path
                break
            except OSError:
                if not os.path.isdir(tmp):
                    break            # a peer took the commit
                if os.path.isdir(path) and attempt == 0:
                    shutil.rmtree(path, ignore_errors=True)
                    continue
                raise
    if committed:
        # keep-last-K over COMMITTED checkpoints only. prune_steps'
        # stale sweep is deliberately skipped here: a live gang tmp
        # belongs to every host at once, and a fast host sweeping
        # while a slow one still writes would tear the save —
        # uncommitted leftovers are reclaimed at resume/completion
        # instead (durable.py), when no save can be in flight.
        if keep is None:
            from quest_tpu.env import knob_value
            keep = knob_value("QUEST_CHECKPOINT_KEEP")
        for _, old in step_dirs(root)[:-max(int(keep), 1)]:
            shutil.rmtree(old, ignore_errors=True)
    return committed


def load_step_gang(path: str, *, kind_extra: str = None):
    """(metas, planes) of a gang checkpoint committed by
    save_step_gang: `metas` is the per-process meta list (cursors
    verified IDENTICAL across hosts), `planes` the reassembled full
    (2, 2^n) array. EVERY shard's digests verify on EVERY host — gang
    validity must be a pure function of the shared directory, or two
    hosts could resume from different cuts. Raises CheckpointError on
    any missing/corrupt/mismatched piece."""
    if faults.ACTIVE:
        faults.check("checkpoint.load", directory=path)
        # the gang-specific site: chaos plans target gang resume /
        # elastic reassembly without arming every plain load
        faults.check("checkpoint.load_gang", directory=path)
    meta0_path = os.path.join(path, "meta-0.json")
    if not os.path.exists(meta0_path):
        raise CheckpointError(
            f"Invalid checkpoint: {path!r} holds no gang meta "
            f"(meta-0.json) — not a gang checkpoint directory")
    metas = []
    try:
        with open(meta0_path) as f:
            meta0 = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointError(
            f"Invalid checkpoint: {meta0_path!r} is corrupt or "
            f"truncated ({e})") from e
    if not isinstance(meta0, dict) \
            or _meta_digest(meta0) != meta0.get("meta_digest"):
        # verify the self-digest BEFORE touching any field: a
        # corrupt-but-parseable meta must surface as the one documented
        # error the resume chain skips, never a leaked KeyError
        raise CheckpointError(
            f"Invalid checkpoint: {meta0_path!r} fails its meta "
            f"self-digest — altered after save; refusing to load")
    nproc = meta0.get("process_count")
    if not isinstance(nproc, int) or nproc < 1:
        raise CheckpointError(
            f"Invalid checkpoint: {meta0_path!r} carries no valid "
            f"process_count")
    nq = meta0.get("num_qubits")
    dens = meta0.get("is_density")
    if not isinstance(nq, int) or not isinstance(dens, bool) \
            or not 0 < nq < 64:
        raise CheckpointError(
            f"Invalid checkpoint: {meta0_path!r} carries no valid "
            f"num_qubits/is_density")
    total = 1 << (2 * nq if dens else nq)
    planes = None
    extra0 = None
    for q in range(nproc):
        mpath = os.path.join(path, f"meta-{q}.json")
        spath = os.path.join(path, f"shard-{q}.npz")
        try:
            with open(mpath) as f:
                meta = json.load(f)
        except (OSError, ValueError) as e:
            raise CheckpointError(
                f"Invalid checkpoint: {mpath!r} is missing or corrupt "
                f"({e})") from e
        md = meta.get("meta_digest")
        if md is None or _meta_digest(meta) != md:
            raise CheckpointError(
                f"Invalid checkpoint: {mpath!r} fails its meta "
                f"self-digest — cursor altered after save; refusing "
                f"to load")
        try:
            with np.load(spath) as data:
                block = data["planes"]
        except Exception as e:
            raise CheckpointError(
                f"Invalid checkpoint: shard file {spath!r} is missing, "
                f"corrupt or truncated ({type(e).__name__}: {e})") from e
        for name, expect in sorted(meta.get("plane_digests",
                                            {}).items()):
            target = _digest_target(name, {"planes": block})
            if target is None or _digest(np.asarray(target)) != expect:
                raise CheckpointError(
                    f"Invalid checkpoint: plane {name!r} of {spath!r} "
                    f"fails its integrity digest — refusing to restore")
        lo, hi = meta["slice_lo"], meta["slice_hi"]
        if block.shape[-1] != hi - lo or hi > total:
            raise CheckpointError(
                f"Invalid checkpoint: shard {q} of {path!r} declares "
                f"slice [{lo}, {hi}) but holds {block.shape[-1]} "
                f"columns of a {total}-amp register")
        if planes is None:
            planes = np.zeros(block.shape[:-1] + (total,),
                              dtype=block.dtype)
        planes[..., lo:hi] = block
        ex = meta.get("extra")
        if q == 0:
            extra0 = ex
        elif ex != extra0:
            raise CheckpointError(
                f"Invalid checkpoint: gang cursors disagree between "
                f"process 0 and {q} under {path!r} — a torn save; "
                f"refusing to load")
        metas.append(meta)
    if kind_extra is not None:
        cur = extra0 if isinstance(extra0, dict) else {}
        if cur.get("kind") != kind_extra:
            raise CheckpointError(
                f"Invalid checkpoint: {path!r} carries no "
                f"{kind_extra!r} durable cursor")
    return metas, planes


# ---------------------------------------------------------------------------
# elastic (mesh-independent) step loading
# ---------------------------------------------------------------------------


def is_gang_step(path: str) -> bool:
    """True when `path` is a COMMITTED gang-format step checkpoint
    (save_step_gang's per-host shard layout) rather than a plain
    single-process one — the elastic loader's format dispatch."""
    return os.path.exists(os.path.join(path, "meta-0.json"))


def load_step_elastic(path: str, *, mesh=None, perm=None):
    """(cursor, planes) of ONE committed step checkpoint in CANONICAL
    LOGICAL ORDER, whatever wrote it (docs/RESILIENCE.md §elastic):

      * a gang checkpoint (any host count) reassembles through
        load_step_gang — every shard's digests re-verified — and the
        cursor's relabel permutation normalizes the physical layout;
      * a plain checkpoint written canonical (cursor layout
        'canonical') loads as-is; a LEGACY physical-layout one (older
        chains) normalizes tolerantly through its recorded perm —
        old-format checkpoints either load correctly or fail loudly,
        never resume wrong.

    The cursor must be a durable STATE cursor carrying the fields the
    normalization needs; anything else raises CheckpointError. `mesh`
    re-enters the planes onto a target mesh's amplitude sharding via
    make_array_from_callback (required on multi-host meshes, where a
    device_put cannot target non-addressable devices), after applying
    `perm` (the TARGET plan's cut permutation, logical -> physical;
    None/identity for canonical entry) — the durable executor passes
    its re-derived boundary perm through this."""
    from quest_tpu.parallel import relabel as R

    if is_gang_step(path):
        metas, planes = load_step_gang(path, kind_extra="state")
        cursor = metas[0].get("extra")
        layout = "physical"
    else:
        meta, arrays = load_arrays(path, require=("planes",))
        cursor = meta.get("extra")
        if not isinstance(cursor, dict) or cursor.get("kind") != "state":
            raise CheckpointError(
                f"Invalid checkpoint: {path!r} carries no durable "
                f"state cursor — not an elastically loadable step")
        planes = np.asarray(arrays["planes"])
        layout = cursor.get("layout", "physical")
    if not isinstance(cursor, dict):
        raise CheckpointError(
            f"Invalid checkpoint: {path!r} carries no durable cursor")
    if layout != "canonical":
        src_perm = cursor.get("perm")
        if src_perm is not None:
            if (not isinstance(src_perm, (list, tuple))
                    or (1 << len(src_perm)) != planes.shape[-1]):
                raise CheckpointError(
                    f"Invalid checkpoint: {path!r} carries a relabel "
                    f"perm of {src_perm!r} that does not match its "
                    f"{planes.shape[-1]}-amp planes — refusing to "
                    f"normalize (a wrong layout resumes to wrong "
                    f"amplitudes)")
            planes = R.canonicalize_planes(planes, list(src_perm))
    if mesh is not None:
        if perm:
            planes = R.physicalize_planes(np.asarray(planes), perm)
        from quest_tpu.parallel.mesh import amp_sharding
        arr = np.asarray(planes)
        planes = jax.make_array_from_callback(
            arr.shape, amp_sharding(mesh), lambda idx: arr[idx])
    return cursor, planes


# ---------------------------------------------------------------------------
# sharded checkpoints (orbax): per-device files, no host gather
# ---------------------------------------------------------------------------


def _orbax():
    try:
        import orbax.checkpoint as ocp
        return ocp
    except ImportError as e:  # pragma: no cover
        raise validation.QuESTError(
            "Sharded checkpointing requires orbax-checkpoint; use "
            "quest_tpu.checkpoint.save/load for the host-gathered path"
        ) from e


class PendingCheckpoint:
    """Handle for an in-flight async checkpoint: `wait()` blocks until
    the files are durable. The state array was snapshotted at save time
    (orbax holds the device buffers), so the caller may keep mutating
    the register while the write streams out."""

    def __init__(self, ckptr):
        self._ckptr = ckptr

    def wait(self) -> None:
        self._ckptr.wait_until_finished()


def save_sharded(qureg: Qureg, directory: str,
                 block: bool = True) -> PendingCheckpoint:
    """Checkpoint the device array WITHOUT gathering to one host: each
    shard writes its own slice (orbax/tensorstore OCDBT).

    block=False returns immediately with a PendingCheckpoint while the
    write streams in the background — simulation continues overlapping
    the IO (the TPU-native pattern for multi-GB states; the snapshot is
    consistent even if the register keeps evolving, because the
    functional engine never mutates buffers in place unless donated —
    do NOT donate the checkpointed array before wait())."""
    ocp = _orbax()
    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    # temp+rename so a crash mid-write can never leave a torn meta the
    # resume path would half-parse (quest-lint QL008)
    meta_path = os.path.join(directory, _META_NAME)
    tmp = meta_path + f".tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(_meta(qureg), f)
    os.replace(tmp, meta_path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(directory, _ORBAX_DIR), {"amps": qureg.amps},
               force=True)
    pending = PendingCheckpoint(ckptr)
    if block:
        pending.wait()
    return pending


def load_sharded(directory: str, env=None, dtype=None) -> Qureg:
    """Restore a sharded checkpoint directly into the target sharding
    (each device reads only its slice)."""
    ocp = _orbax()
    directory = os.path.abspath(directory)
    meta = _read_meta(directory)
    try:
        rdt = np.dtype(meta["real_dtype"])
    except TypeError as e:
        raise CheckpointError(
            f"Invalid checkpoint: metadata in {directory!r} names "
            f"unknown real_dtype {meta['real_dtype']!r}") from e
    cdt = dtype if dtype is not None else precision.complex_dtype_of(rdt)
    make = create_density_qureg if meta["is_density"] else create_qureg
    q = make(meta["num_qubits"], env=env, dtype=cdt)
    target = jax.ShapeDtypeStruct(q.amps.shape, q.amps.dtype,
                                  sharding=q.amps.sharding)
    orbax_dir = os.path.join(directory, _ORBAX_DIR)
    ckptr = ocp.StandardCheckpointer()
    try:
        restored = ckptr.restore(orbax_dir, {"amps": target})
    except Exception as e:
        # orbax/tensorstore failures (missing dir, corrupt OCDBT shards,
        # shape/dtype mismatch vs the target) surface as a zoo of
        # library-internal types — collapse to the one documented error,
        # keeping the cause chained for debugging
        raise CheckpointError(
            f"Invalid checkpoint: sharded payload under {orbax_dir!r} "
            f"is missing, corrupt, or does not match the "
            f"{meta['num_qubits']}-qubit register its metadata declares "
            f"({type(e).__name__}: {str(e)[:300]})") from e
    return q.replace_amps(restored["amps"])
