"""Pallas TPU kernel engine: many gates per HBM pass.

The XLA path applies one gate per memory pass (~400 GB/s measured on v5e
— each butterfly reads and writes the whole state). This engine fuses a
SEGMENT of gates into one Pallas kernel so the state streams through VMEM
once per segment, the TPU-native analogue of the reference's single-pass
OpenMP/CUDA kernels (QuEST_cpu.c, QuEST_gpu.cu) but covering MANY gates
per pass.

Layout: the (2^n,) plane is a 2-D matrix M[row, lane] with 128 lanes —
lane index bits are qubits 0..6, row index bit j is qubit 7+j. The grid
tiles rows into blocks of ROWS_PER_BLOCK; each kernel instance holds its
(2, ROWS, 128) block in VMEM and applies the segment's stages in order:

  lane stage   any gate(s) living entirely on qubits 0..6 (including
               controls): composed host-side into ONE 128x128 operator G
               and applied as M @ G^T on the MXU — consecutive lane gates
               cost a single matmul regardless of count. This is the TPU
               answer to the reference's central kernel-engineering
               problem (strided butterflies at small stride map terribly
               onto tiles; as a lane matmul they ARE the hardware's
               native operation).
  rowmat       1-qubit gate on a row qubit: leading-dim butterfly
               (reshape touches only leading axes — layout-free).
  rowdiag      diagonal 1-qubit gate on a row qubit: per-row factor.
  parity       multiRotateZ on any in-block qubits: sign tensor from
               lane-bit x row-bit products.

Controls anywhere are honored: lane controls fold into G or mask lanes;
row controls become row-predicate blends (global row id from the grid
index). Gates touching qubits >= 7 + log2(ROWS_PER_BLOCK) (or multi-
target gates with row targets) break the segment and run on the XLA path.

All operands are trace-time constants (circuit operands are baked), so G
composition happens in numpy at trace time.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

LANE_QUBITS = 7           # qubits 0..6 live in the 128-lane axis
LANES = 1 << LANE_QUBITS
MAX_ROWS_PER_BLOCK = 2048  # (2, 2048, 128) f32 = 2 MiB per block buffer.
# Sized for the default 16 MiB scoped-VMEM limit on v5e: Pallas double-
# buffers the grid pipeline, so in+out cost 2*(2+2) = 8 MiB, leaving
# headroom for lane-operator blocks. 4096-row blocks hit exactly 16.04 MiB
# and fail to compile on the real chip (measured; the axon terminal
# overrides client XLA_FLAGS, so the limit cannot be raised).


# ---------------------------------------------------------------------------
# stages
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class LaneStage:
    gre: np.ndarray            # (128, 128) f32
    gim: np.ndarray
    row_preds: Tuple[Tuple[int, int], ...] = ()   # (row_bit, want)


@dataclasses.dataclass(frozen=True)
class RowMatStage:
    j: int                     # row bit
    m: Tuple[float, ...]       # (re00,im00,re01,im01,re10,im10,re11,im11)
    lane_preds: Tuple[Tuple[int, int], ...] = ()  # (lane_bit, want)
    row_preds: Tuple[Tuple[int, int], ...] = ()


@dataclasses.dataclass(frozen=True)
class RowDiagStage:
    j: int
    d: Tuple[float, ...]       # (re0, im0, re1, im1)
    lane_preds: Tuple[Tuple[int, int], ...] = ()
    row_preds: Tuple[Tuple[int, int], ...] = ()


@dataclasses.dataclass(frozen=True)
class ParityStage:
    lane_targets: Tuple[int, ...]
    row_targets: Tuple[int, ...]   # as row bits
    angle: float


Stage = object


# ---------------------------------------------------------------------------
# host-side operator composition for lane stages
# ---------------------------------------------------------------------------


def _lane_operator(matrix: np.ndarray, targets, controls, cstates) -> np.ndarray:
    """Embed a k-qubit operator (+ controls) into the full 2^7-dim lane
    space (same construction as the reference's getFullOperatorMatrix,
    tests oracle)."""
    matrix = np.asarray(matrix, dtype=np.complex128)
    targets = list(targets)
    k = len(targets)
    controls = list(controls)
    cstates = list(cstates) if cstates else [1] * len(controls)
    op = np.zeros((LANES, LANES), dtype=np.complex128)
    for col in range(LANES):
        if any(((col >> c) & 1) != s for c, s in zip(controls, cstates)):
            op[col, col] = 1.0
            continue
        sub = 0
        for bit, t in enumerate(targets):
            sub |= ((col >> t) & 1) << bit
        rest = col
        for t in targets:
            rest &= ~(1 << t)
        for sub_out in range(1 << k):
            row = rest
            for bit, t in enumerate(targets):
                if (sub_out >> bit) & 1:
                    row |= 1 << t
            op[row, col] = matrix[sub_out, sub]
    return op


def _diag_as_matrix(diag: np.ndarray) -> np.ndarray:
    return np.diag(np.asarray(diag, dtype=np.complex128).reshape(-1))


# ---------------------------------------------------------------------------
# segment planning
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Plan:
    """Alternating pallas segments and passthrough ops, in program order."""
    items: List  # ("segment", [stages]) | ("op", GateOp-like)


def _split_preds(controls, cstates):
    lane_p, row_p = [], []
    for c, s in zip(controls, cstates or [1] * len(controls)):
        if c < LANE_QUBITS:
            lane_p.append((c, s))
        else:
            row_p.append((c - LANE_QUBITS, s))
    return tuple(lane_p), tuple(row_p)


def _mat8(m: np.ndarray) -> Tuple[float, ...]:
    m = np.asarray(m, dtype=np.complex128)
    return (m[0, 0].real, m[0, 0].imag, m[0, 1].real, m[0, 1].imag,
            m[1, 0].real, m[1, 0].imag, m[1, 1].real, m[1, 1].imag)


def plan_ops(ops: Sequence, n: int, qmax: int) -> Plan:
    """Partition circuit GateOps into fusable stages and passthrough ops.
    qmax = LANE_QUBITS + log2(rows_per_block): first qubit the kernel
    cannot reach."""
    items: List = []
    stages: List[Stage] = []

    def flush():
        nonlocal stages
        if stages:
            items.append(("segment", stages))
            stages = []

    def add_lane(op_np):
        # merge into the previous lane stage when it has no row preds
        if stages and isinstance(stages[-1], LaneStage) and \
                not stages[-1].row_preds:
            prev = stages[-1]
            g = op_np @ (prev.gre.astype(np.complex128)
                         + 1j * prev.gim.astype(np.complex128))
            stages[-1] = LaneStage(g.real.astype(np.float32),
                                   g.imag.astype(np.float32))
        else:
            stages.append(LaneStage(op_np.real.astype(np.float32),
                                    op_np.imag.astype(np.float32)))

    for op in ops:
        targets = tuple(op.targets)
        controls = tuple(op.controls)
        cstates = tuple(op.cstates) if op.cstates else (1,) * len(controls)
        allq = targets + controls
        if any(q >= qmax for q in allq):
            flush()
            items.append(("op", op))
            continue

        if op.kind == "parity":
            stages.append(ParityStage(
                tuple(q for q in targets if q < LANE_QUBITS),
                tuple(q - LANE_QUBITS for q in targets if q >= LANE_QUBITS),
                float(op.operand)))
            continue

        if op.kind == "allones":
            # phase `term` on all-ones of `targets`: diagonal on the lowest
            # qubit controlled on the rest
            tlo = min(targets)
            rest = tuple(q for q in targets if q != tlo)
            diag = np.array([1.0, complex(op.operand)])
            lane_p, row_p = _split_preds(rest, (1,) * len(rest))
            if tlo < LANE_QUBITS:
                g = _lane_operator(_diag_as_matrix(diag), (tlo,),
                                   [c for c, _ in lane_p],
                                   [s for _, s in lane_p])
                if row_p:
                    stages.append(LaneStage(g.real.astype(np.float32),
                                            g.imag.astype(np.float32), row_p))
                else:
                    add_lane(g)
            else:
                stages.append(RowDiagStage(
                    tlo - LANE_QUBITS,
                    (1.0, 0.0, complex(op.operand).real,
                     complex(op.operand).imag), lane_p, row_p))
            continue

        operand = np.asarray(op.operand, dtype=np.complex128)
        is_diag = op.kind == "diagonal"
        if all(q < LANE_QUBITS for q in targets):
            # lane-target gate; lane controls fold into G, row controls
            # become row-predicate blends
            lane_c = [(c, s) for c, s in zip(controls, cstates)
                      if c < LANE_QUBITS]
            row_p = tuple((c - LANE_QUBITS, s) for c, s in
                          zip(controls, cstates) if c >= LANE_QUBITS)
            mat = _diag_as_matrix(operand) if is_diag else operand
            g = _lane_operator(mat, targets, [c for c, _ in lane_c],
                               [s for _, s in lane_c])
            if row_p:
                stages.append(LaneStage(g.real.astype(np.float32),
                                        g.imag.astype(np.float32), row_p))
            else:
                add_lane(g)
            continue

        if len(targets) == 1 and targets[0] >= LANE_QUBITS:
            j = targets[0] - LANE_QUBITS
            lane_p, row_p = _split_preds(controls, cstates)
            if is_diag:
                d = operand.reshape(-1)
                stages.append(RowDiagStage(
                    j, (d[0].real, d[0].imag, d[1].real, d[1].imag),
                    lane_p, row_p))
            else:
                stages.append(RowMatStage(j, _mat8(operand), lane_p, row_p))
            continue

        # multi-target matrix with a row target: not fusable here
        flush()
        items.append(("op", op))

    flush()
    return Plan(items)


# ---------------------------------------------------------------------------
# the kernel
# ---------------------------------------------------------------------------


def _row_mask(rows: int, pid, preds):
    """(rows, 1) bool: global-row predicates hold."""
    base = pid * rows
    ids = base + jax.lax.broadcasted_iota(jnp.int32, (rows, 1), 0)
    mask = None
    for bit, want in preds:
        m = ((ids >> bit) & 1) == want
        mask = m if mask is None else (mask & m)
    return mask


def _lane_mask(preds):
    ids = jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)
    mask = None
    for bit, want in preds:
        m = ((ids >> bit) & 1) == want
        mask = m if mask is None else (mask & m)
    return mask


def _combine_masks(rows, pid, lane_preds, row_preds):
    mask = None
    if lane_preds:
        mask = _lane_mask(lane_preds)
    if row_preds:
        rm = _row_mask(rows, pid, row_preds)
        mask = rm if mask is None else (mask & rm)
    return mask


def _apply_stage(re, im, stage, rows, pid, lane_mats=None):
    f32 = jnp.float32
    if isinstance(stage, LaneStage):
        gre_t, gim_t = lane_mats  # (128,128) G^T planes, kernel inputs
        nre = (jnp.dot(re, gre_t, preferred_element_type=f32)
               - jnp.dot(im, gim_t, preferred_element_type=f32))
        nim = (jnp.dot(re, gim_t, preferred_element_type=f32)
               + jnp.dot(im, gre_t, preferred_element_type=f32))
        mask = _combine_masks(rows, pid, (), stage.row_preds)
        if mask is not None:
            nre = jnp.where(mask, nre, re)
            nim = jnp.where(mask, nim, im)
        return nre, nim

    if isinstance(stage, RowMatStage):
        j = stage.j
        r2 = rows >> (j + 1)
        shape4 = (r2, 2, 1 << j, LANES)
        (a, b, c, d, e, f, g, h) = (np.float32(x) for x in stage.m)
        vre = re.reshape(shape4)
        vim = im.reshape(shape4)
        r0, r1 = vre[:, 0:1], vre[:, 1:2]
        i0, i1 = vim[:, 0:1], vim[:, 1:2]
        n0r = a * r0 - b * i0 + c * r1 - d * i1
        n0i = a * i0 + b * r0 + c * i1 + d * r1
        n1r = e * r0 - f * i0 + g * r1 - h * i1
        n1i = e * i0 + f * r0 + g * i1 + h * r1
        nre = jnp.concatenate([n0r, n1r], axis=1).reshape(rows, LANES)
        nim = jnp.concatenate([n0i, n1i], axis=1).reshape(rows, LANES)
        mask = _combine_masks(rows, pid, stage.lane_preds, stage.row_preds)
        if mask is not None:
            nre = jnp.where(mask, nre, re)
            nim = jnp.where(mask, nim, im)
        return nre, nim

    if isinstance(stage, RowDiagStage):
        (r0, i0, r1, i1) = (np.float32(x) for x in stage.d)
        bitv = (_row_mask(rows, pid, ((stage.j, 1),))).astype(jnp.float32)
        dre = r0 + (r1 - r0) * bitv
        dim = i0 + (i1 - i0) * bitv
        nre = re * dre - im * dim
        nim = re * dim + im * dre
        mask = _combine_masks(rows, pid, stage.lane_preds, stage.row_preds)
        if mask is not None:
            nre = jnp.where(mask, nre, re)
            nim = jnp.where(mask, nim, im)
        return nre, nim

    assert isinstance(stage, ParityStage)
    sign = jnp.ones((1, 1), dtype=jnp.float32)
    if stage.lane_targets:
        ids = jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)
        s = jnp.ones((1, LANES), dtype=jnp.float32)
        for q in stage.lane_targets:
            s = s * (1.0 - 2.0 * ((ids >> q) & 1).astype(jnp.float32))
        sign = sign * s
    if stage.row_targets:
        base = pid * rows
        ids = base + jax.lax.broadcasted_iota(jnp.int32, (rows, 1), 0)
        s = jnp.ones((rows, 1), dtype=jnp.float32)
        for j in stage.row_targets:
            s = s * (1.0 - 2.0 * ((ids >> j) & 1).astype(jnp.float32))
        sign = sign * s
    half = stage.angle / 2.0
    cosf = np.float32(np.cos(half))
    sinf = np.float32(np.sin(half)) * sign
    nre = re * cosf + im * sinf
    nim = im * cosf - re * sinf
    return nre, nim


def _segment_kernel(in_ref, *rest, stages, rows, num_lane):
    # rest = [laneG_0, ..., laneG_{num_lane-1}, out_ref]; each laneG ref is
    # a (2, 128, 128) block holding (G^T re, G^T im)
    lane_refs = rest[:num_lane]
    out_ref = rest[num_lane]
    pid = pl.program_id(0)
    blk = in_ref[...]
    re = blk[0]
    im = blk[1]
    lane_i = 0
    for stage in stages:
        mats = None
        if isinstance(stage, LaneStage):
            g = lane_refs[lane_i][...]
            mats = (g[0], g[1])
            lane_i += 1
        re, im = _apply_stage(re, im, stage, rows, pid, mats)
    out_ref[0] = re
    out_ref[1] = im


def compile_segment(stages: Sequence[Stage], n: int, interpret: bool = False):
    """(2, 2^n) planes -> (2, 2^n) planes applying `stages` in one kernel
    launch (grid over row blocks). Lane operators ride along as (2,128,128)
    G^T inputs (Pallas kernels may not capture large constants)."""
    total_rows = 1 << (n - LANE_QUBITS)
    rows = min(MAX_ROWS_PER_BLOCK, total_rows)
    # every row bit a stage touches must be inside the block
    need = 0
    for st in stages:
        if isinstance(st, (RowMatStage, RowDiagStage)):
            need = max(need, st.j + 1)
        elif isinstance(st, ParityStage) and st.row_targets:
            need = max(need, max(st.row_targets) + 1)
    rows = max(rows, 1 << need)
    if rows > total_rows:
        raise ValueError("stage touches a qubit beyond the planned qmax")
    grid = (total_rows // rows,)

    lane_inputs = [np.stack([st.gre.T, st.gim.T]).astype(np.float32)
                   for st in stages if isinstance(st, LaneStage)]
    num_lane = len(lane_inputs)

    kernel = functools.partial(_segment_kernel, stages=tuple(stages),
                               rows=rows, num_lane=num_lane)
    g_spec = pl.BlockSpec((2, LANES, LANES), lambda i: (0, 0, 0))
    fn = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((2, rows, LANES), lambda i: (0, i, 0))]
                 + [g_spec] * num_lane,
        out_specs=pl.BlockSpec((2, rows, LANES), lambda i: (0, i, 0)),
        out_shape=jax.ShapeDtypeStruct((2, total_rows, LANES), jnp.float32),
        input_output_aliases={0: 0},  # in-place on the state buffer
        interpret=interpret,
    )
    lane_inputs = [jnp.asarray(g) for g in lane_inputs]

    def apply(amps):
        out = fn(amps.reshape(2, total_rows, LANES), *lane_inputs)
        return out.reshape(2, -1)

    return apply


def qmax_for(n: int) -> int:
    total_rows = 1 << (n - LANE_QUBITS)
    rows = min(MAX_ROWS_PER_BLOCK, total_rows)
    return LANE_QUBITS + max(0, rows.bit_length() - 1)


def usable(n: int) -> bool:
    """The kernel layout needs >= 8 rows of 128 lanes (f32 tile)."""
    return n >= LANE_QUBITS + 3
