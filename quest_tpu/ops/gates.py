"""Functional gate layer: Qureg -> Qureg operations for the full gate set.

Each public QuEST gate (QuEST/include/QuEST.h doc-groups "unitaries" and
"operators") has a functional equivalent here. Density matrices are handled
exactly as the reference does (QuEST/src/QuEST.c:8-10): a gate U on targets
T of a density register additionally applies conj(U) on the column-space
copy T + N (Choi isomorphism) — both halves are traced into ONE jitted
program.

Operands and compilation caching:
  * named constant gates (X, H, SWAP, ...) are passed as STATIC nested
    tuples, so their zero entries are skipped at trace time (an X gate
    compiles to pure data movement — the analogue of the reference's
    dedicated pauliX kernel, QuEST_cpu.c:2464) and each gate compiles once
    per (n, targets, controls) shape;
  * parameterized gates (rotations, phase shifts) pass real scalar
    parameters dynamically and build the operator INSIDE the trace, so a
    new angle reuses the compiled program;
  * user-supplied matrices pass dynamic (re, im) float pairs — complex
    values never cross the host<->device boundary (quest_tpu.cplx).
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from quest_tpu import cplx
from quest_tpu import validation as val
from quest_tpu.ops import apply as A
from quest_tpu.ops import matrices as M
from quest_tpu.state import Qureg

# ---------------------------------------------------------------------------
# jitted workers
#
# Every worker carries a static `mode` argument fed A.mode_key(): the
# traced program depends on environment read at TRACE time (matmul
# precision tier, QUEST_F64_MXU, QUEST_F64_CHUNK), so the jit cache must
# key on it — without it, flipping a knob mid-process returned the STALE
# eager program (the cache-key discipline of ADVICE r5 item 2; the
# compiled-circuit engines carry the same key via _engine_mode_key).
# ---------------------------------------------------------------------------


def _shift(qubits, by):
    return tuple(q + by for q in qubits)


@partial(jax.jit, static_argnames=(
    "n", "targets", "controls", "cstates", "density", "op_re", "op_im",
    "diagonal", "dual", "mode"))
def _const_gate_worker(amps, *, n, targets, controls, cstates, density,
                       op_re, op_im, diagonal, dual, mode):
    pair = (np.array(op_re, dtype=np.float64), np.array(op_im, dtype=np.float64))
    fn = A.apply_diagonal if diagonal else A.apply_matrix
    amps = fn(amps, n, pair, targets, controls, cstates)
    if density and dual:
        conj = (pair[0], -pair[1])
        amps = fn(amps, n, conj, _shift(targets, n // 2),
                  _shift(controls, n // 2), cstates)
    return amps


@partial(jax.jit, static_argnames=(
    "n", "targets", "controls", "cstates", "density", "builder", "diagonal",
    "mode"))
def _dyn_gate_worker(amps, params, *, n, targets, controls, cstates, density,
                     builder, diagonal, mode):
    if builder is not None:
        pair = builder(*[jnp.asarray(p) for p in params])
    else:
        pair = (jnp.asarray(params[0]), jnp.asarray(params[1]))
    fn = A.apply_diagonal if diagonal else A.apply_matrix
    amps = fn(amps, n, pair, targets, controls, cstates)
    if density:
        conj = (pair[0], -pair[1])
        amps = fn(amps, n, conj, _shift(targets, n // 2),
                  _shift(controls, n // 2), cstates)
    return amps


@partial(jax.jit, static_argnames=("n", "targets", "density", "mode"))
def _parity_phase_worker(amps, angle, *, n, targets, density, mode):
    amps = A.apply_parity_phase(amps, n, targets, angle)
    if density:
        amps = A.apply_parity_phase(amps, n, _shift(targets, n // 2), -angle)
    return amps


@partial(jax.jit, static_argnames=("n", "qubits", "density", "mode"))
def _all_ones_phase_worker(amps, term_re, term_im, *, n, qubits, density,
                           mode):
    amps = A.apply_phase_on_all_ones(amps, n, qubits, (term_re, term_im))
    if density:
        amps = A.apply_phase_on_all_ones(
            amps, n, _shift(qubits, n // 2), (term_re, -term_im))
    return amps


def _tt(arr):
    """numpy 2-D/1-D array -> hashable nested tuple."""
    arr = np.asarray(arr)
    if arr.ndim == 1:
        return tuple(float(x) for x in arr)
    return tuple(tuple(float(x) for x in row) for row in arr)


def _run(q: Qureg, op, targets, controls=(), cstates=None, builder=None,
         diagonal=False, dual=True, static=False) -> Qureg:
    """Dispatch one gate. `op` is a concrete numpy complex matrix/diagonal
    when builder is None, else a tuple of real scalar parameters.

    static=True bakes the operand into the compiled program (named constant
    gates: zero entries skipped, one compile per shape); user-supplied
    matrices stay dynamic so fresh values reuse the compiled program."""
    targets = tuple(int(t) for t in targets)
    controls = tuple(int(c) for c in controls)
    cstates = tuple(int(s) for s in cstates) if cstates is not None \
        else (1,) * len(controls)
    if static:
        re, im = cplx.pack(op)
        amps = _const_gate_worker(
            q.amps, n=q.num_state_qubits, targets=targets, controls=controls,
            cstates=cstates, density=q.is_density, op_re=_tt(re),
            op_im=_tt(im), diagonal=diagonal, dual=dual, mode=A.mode_key())
    elif builder is None:
        amps = _dyn_gate_worker(
            q.amps, cplx.pack(op), n=q.num_state_qubits, targets=targets,
            controls=controls, cstates=cstates, density=q.is_density,
            builder=None, diagonal=diagonal, mode=A.mode_key())
    else:
        amps = _dyn_gate_worker(
            q.amps, op, n=q.num_state_qubits, targets=targets,
            controls=controls, cstates=cstates, density=q.is_density,
            builder=builder, diagonal=diagonal, mode=A.mode_key())
    return q.replace_amps(amps)


def _phase_all_ones(q: Qureg, qubits, term_re, term_im) -> Qureg:
    amps = _all_ones_phase_worker(
        q.amps, jnp.asarray(term_re, dtype=q.real_dtype),
        jnp.asarray(term_im, dtype=q.real_dtype), n=q.num_state_qubits,
        qubits=tuple(int(x) for x in qubits), density=q.is_density,
        mode=A.mode_key())
    return q.replace_amps(amps)


# ---------------------------------------------------------------------------
# traced builders (module-level for stable jit cache keys; all parameters
# are real scalars; operators are (re, im) float array pairs)
# ---------------------------------------------------------------------------


def _assemble_compact(a_re, a_im, b_re, b_im):
    """[[alpha, -conj(beta)], [beta, conj(alpha)]] as an (re, im) pair."""
    re = jnp.stack([jnp.stack([a_re, -b_re]), jnp.stack([b_re, a_re])])
    im = jnp.stack([jnp.stack([a_im, b_im]), jnp.stack([b_im, -a_im])])
    return re, im


def _build_compact(a_re, a_im, b_re, b_im):
    return _assemble_compact(a_re, a_im, b_re, b_im)


def _build_rotation(angle, ax, ay, az):
    """cos(t/2) I - i sin(t/2) (n . sigma) via the reference's (alpha, beta)
    parameterization (QuEST_common.c:114-122)."""
    norm = jnp.sqrt(ax * ax + ay * ay + az * az)
    ux, uy, uz = ax / norm, ay / norm, az / norm
    half = angle / 2.0
    c, s = jnp.cos(half), jnp.sin(half)
    return _assemble_compact(c, -s * uz, s * uy, -s * ux)


def _build_phase_diag(angle):
    """diag(1, e^{i angle}) as an (re, im) pair."""
    one = jnp.ones_like(angle)
    zero = jnp.zeros_like(angle)
    return (jnp.stack([one, jnp.cos(angle)]),
            jnp.stack([zero, jnp.sin(angle)]))


# ---------------------------------------------------------------------------
# single-qubit unitaries (ref QuEST.c:109-331)
# ---------------------------------------------------------------------------


def _compact_params(alpha, beta):
    a, b = complex(alpha), complex(beta)
    return (a.real, a.imag, b.real, b.imag)


def compact_unitary(q: Qureg, target: int, alpha, beta) -> Qureg:
    val.validate_target(q, target)
    val.validate_unitary_complex_pair(alpha, beta, eps=val.eps_for(q))
    return _run(q, _compact_params(alpha, beta), (target,), builder=_build_compact)


def controlled_compact_unitary(q: Qureg, control: int, target: int, alpha, beta) -> Qureg:
    val.validate_control_target(q, control, target)
    val.validate_unitary_complex_pair(alpha, beta, eps=val.eps_for(q))
    return _run(q, _compact_params(alpha, beta), (target,), (control,),
                builder=_build_compact)


def unitary(q: Qureg, target: int, matrix) -> Qureg:
    val.validate_target(q, target)
    val.validate_unitary(matrix, 1, eps=val.eps_for(q))
    return _run(q, matrix, (target,))


def controlled_unitary(q: Qureg, control: int, target: int, matrix) -> Qureg:
    val.validate_control_target(q, control, target)
    val.validate_unitary(matrix, 1, eps=val.eps_for(q))
    return _run(q, matrix, (target,), (control,))


def multi_controlled_unitary(q: Qureg, controls: Sequence[int], target: int, matrix) -> Qureg:
    val.validate_multi_controls_targets(q, controls, (target,))
    val.validate_unitary(matrix, 1, eps=val.eps_for(q))
    return _run(q, matrix, (target,), tuple(controls))


def multi_state_controlled_unitary(
        q: Qureg, controls: Sequence[int], control_states: Sequence[int],
        target: int, matrix) -> Qureg:
    val.validate_multi_controls_targets(q, controls, (target,))
    val.validate_control_states(controls, control_states)
    val.validate_unitary(matrix, 1, eps=val.eps_for(q))
    return _run(q, matrix, (target,), tuple(controls), tuple(control_states))


def pauli_x(q: Qureg, target: int) -> Qureg:
    val.validate_target(q, target)
    return _run(q, M.PAULI_X, (target,), static=True)


def pauli_y(q: Qureg, target: int) -> Qureg:
    val.validate_target(q, target)
    return _run(q, M.PAULI_Y, (target,), static=True)


def pauli_z(q: Qureg, target: int) -> Qureg:
    val.validate_target(q, target)
    return _run(q, M.Z_DIAG, (target,), diagonal=True, static=True)


def hadamard(q: Qureg, target: int) -> Qureg:
    val.validate_target(q, target)
    return _run(q, M.HADAMARD, (target,), static=True)


def s_gate(q: Qureg, target: int) -> Qureg:
    val.validate_target(q, target)
    return _run(q, M.S_DIAG, (target,), diagonal=True, static=True)


def t_gate(q: Qureg, target: int) -> Qureg:
    val.validate_target(q, target)
    return _run(q, M.T_DIAG, (target,), diagonal=True, static=True)


def phase_shift(q: Qureg, target: int, angle) -> Qureg:
    val.validate_target(q, target)
    return _run(q, (float(angle),), (target,), builder=_build_phase_diag,
                diagonal=True)


def controlled_not(q: Qureg, control: int, target: int) -> Qureg:
    val.validate_control_target(q, control, target)
    return _run(q, M.PAULI_X, (target,), (control,), static=True)


def controlled_pauli_y(q: Qureg, control: int, target: int) -> Qureg:
    val.validate_control_target(q, control, target)
    return _run(q, M.PAULI_Y, (target,), (control,), static=True)


# -- rotations ---------------------------------------------------------------


def rotate_around_axis(q: Qureg, target: int, angle, axis) -> Qureg:
    val.validate_target(q, target)
    val.validate_vector(axis)
    ax = np.asarray(axis, dtype=np.float64)
    return _run(q, (float(angle), ax[0], ax[1], ax[2]), (target,),
                builder=_build_rotation)


def rotate_x(q: Qureg, target: int, angle) -> Qureg:
    return rotate_around_axis(q, target, angle, (1.0, 0.0, 0.0))


def rotate_y(q: Qureg, target: int, angle) -> Qureg:
    return rotate_around_axis(q, target, angle, (0.0, 1.0, 0.0))


def rotate_z(q: Qureg, target: int, angle) -> Qureg:
    return rotate_around_axis(q, target, angle, (0.0, 0.0, 1.0))


def controlled_rotate_around_axis(q: Qureg, control: int, target: int, angle, axis) -> Qureg:
    val.validate_control_target(q, control, target)
    val.validate_vector(axis)
    ax = np.asarray(axis, dtype=np.float64)
    return _run(q, (float(angle), ax[0], ax[1], ax[2]), (target,), (control,),
                builder=_build_rotation)


def controlled_rotate_x(q: Qureg, control: int, target: int, angle) -> Qureg:
    return controlled_rotate_around_axis(q, control, target, angle, (1.0, 0.0, 0.0))


def controlled_rotate_y(q: Qureg, control: int, target: int, angle) -> Qureg:
    return controlled_rotate_around_axis(q, control, target, angle, (0.0, 1.0, 0.0))


def controlled_rotate_z(q: Qureg, control: int, target: int, angle) -> Qureg:
    return controlled_rotate_around_axis(q, control, target, angle, (0.0, 0.0, 1.0))


# -- symmetric phase family --------------------------------------------------


def controlled_phase_shift(q: Qureg, qubit1: int, qubit2: int, angle) -> Qureg:
    val.validate_unique_targets(q, qubit1, qubit2)
    a = float(angle)
    return _phase_all_ones(q, (qubit1, qubit2), np.cos(a), np.sin(a))


def multi_controlled_phase_shift(q: Qureg, qubits: Sequence[int], angle) -> Qureg:
    val.validate_multi_targets(q, qubits)
    a = float(angle)
    return _phase_all_ones(q, tuple(qubits), np.cos(a), np.sin(a))


def controlled_phase_flip(q: Qureg, qubit1: int, qubit2: int) -> Qureg:
    val.validate_unique_targets(q, qubit1, qubit2)
    return _phase_all_ones(q, (qubit1, qubit2), -1.0, 0.0)


def multi_controlled_phase_flip(q: Qureg, qubits: Sequence[int]) -> Qureg:
    val.validate_multi_targets(q, qubits)
    return _phase_all_ones(q, tuple(qubits), -1.0, 0.0)


def multi_rotate_z(q: Qureg, qubits: Sequence[int], angle) -> Qureg:
    val.validate_multi_targets(q, qubits)
    return q.replace_amps(_parity_phase_worker(
        q.amps, jnp.asarray(float(angle)), n=q.num_state_qubits,
        targets=tuple(int(x) for x in qubits), density=q.is_density,
        mode=A.mode_key()))


@partial(jax.jit, static_argnames=("n", "term", "conj", "mode"))
def _pauli_rot_worker(amps, angle, *, n, term, conj, mode):
    """exp(-i angle/2 * P) = cos(angle/2) I - i sin(angle/2) P applied as
    ONE fused pass: the P image is the flip-form apply_pauli_string (no
    basis-rotation passes). conj=True applies the complex conjugate
    (the density dual): conj(P) = (-1)^{#Y} P, so only sin's sign
    changes."""
    rdt = amps.dtype
    half = jnp.asarray(angle, dtype=rdt) / 2.0
    c = jnp.cos(half)
    s = jnp.sin(half)
    if conj:
        ny = sum(1 for p in term if p == 2)
        s = -s if ny % 2 == 0 else s
    w = A.apply_pauli_string(amps, n, term)
    # psi*c - i*s*(P psi):  re = c re + s w_im ; im = c im - s w_re
    return jnp.stack([c * amps[0] + s * w[1], c * amps[1] - s * w[0]])


def multi_rotate_pauli(q: Qureg, targets: Sequence[int], paulis: Sequence[int],
                       angle) -> Qureg:
    """exp(-i angle/2 * P1 x P2 x ...) in ONE fused pass per register
    side: cos(a/2) psi - i sin(a/2) P psi, with P psi the flip-form
    Pauli-string image (ops.apply.apply_pauli_string). The reference
    rotates each X/Y target's basis, multiRotateZs, and rotates back —
    2k+1 full-state passes (statevec_multiRotatePauli,
    QuEST_common.c:410-447); here the whole exponential is one pass.
    All-identity strings are a no-op, exactly like the reference's
    'does nothing if there are no qubits to rotate' (:435-436)."""
    val.validate_multi_targets(q, targets)
    val.validate_pauli_targets(targets, paulis)
    val.validate_pauli_codes(paulis)
    n = q.num_state_qubits
    term = [0] * n
    for t, p in zip(targets, paulis):
        term[int(t)] = int(p)
    if not any(term):
        return q
    angle = jnp.asarray(float(angle))
    amps = _pauli_rot_worker(q.amps, angle, n=n, term=tuple(term),
                             conj=False, mode=A.mode_key())
    if q.is_density:
        shift = n // 2
        dual = [0] * n
        for t, p in zip(targets, paulis):
            dual[int(t) + shift] = int(p)
        amps = _pauli_rot_worker(amps, angle, n=n, term=tuple(dual),
                                 conj=True, mode=A.mode_key())
    return q.replace_amps(amps)


# -- multi-qubit unitaries ---------------------------------------------------


def swap_gate(q: Qureg, qubit1: int, qubit2: int) -> Qureg:
    val.validate_unique_targets(q, qubit1, qubit2)
    return _run(q, M.SWAP, (qubit1, qubit2), static=True)


def sqrt_swap_gate(q: Qureg, qubit1: int, qubit2: int) -> Qureg:
    val.validate_unique_targets(q, qubit1, qubit2)
    return _run(q, M.SQRT_SWAP, (qubit1, qubit2), static=True)


def two_qubit_unitary(q: Qureg, target1: int, target2: int, matrix) -> Qureg:
    val.validate_multi_targets(q, (target1, target2))
    val.validate_unitary(matrix, 2, eps=val.eps_for(q))
    return _run(q, matrix, (target1, target2))


def controlled_two_qubit_unitary(q: Qureg, control: int, target1: int,
                                 target2: int, matrix) -> Qureg:
    val.validate_multi_controls_targets(q, (control,), (target1, target2))
    val.validate_unitary(matrix, 2, eps=val.eps_for(q))
    return _run(q, matrix, (target1, target2), (control,))


def multi_controlled_two_qubit_unitary(q: Qureg, controls: Sequence[int],
                                       target1: int, target2: int, matrix) -> Qureg:
    val.validate_multi_controls_targets(q, controls, (target1, target2))
    val.validate_unitary(matrix, 2, eps=val.eps_for(q))
    return _run(q, matrix, (target1, target2), tuple(controls))


def multi_qubit_unitary(q: Qureg, targets: Sequence[int], matrix) -> Qureg:
    val.validate_multi_targets(q, targets)
    val.validate_unitary(matrix, len(tuple(targets)), eps=val.eps_for(q))
    return _run(q, matrix, tuple(targets))


def controlled_multi_qubit_unitary(q: Qureg, control: int,
                                   targets: Sequence[int], matrix) -> Qureg:
    val.validate_multi_controls_targets(q, (control,), targets)
    val.validate_unitary(matrix, len(tuple(targets)), eps=val.eps_for(q))
    return _run(q, matrix, tuple(targets), (control,))


def multi_controlled_multi_qubit_unitary(q: Qureg, controls: Sequence[int],
                                         targets: Sequence[int], matrix) -> Qureg:
    val.validate_multi_controls_targets(q, controls, targets)
    val.validate_unitary(matrix, len(tuple(targets)), eps=val.eps_for(q))
    return _run(q, matrix, tuple(targets), tuple(controls))


# -- non-unitary helpers -----------------------------------------------------


def apply_pauli_prod(q: Qureg, targets: Sequence[int], paulis: Sequence[int]) -> Qureg:
    """Left-multiply by a product of Pauli operators (possibly non-trace-
    preserving on density matrices; ref statevec_applyPauliProd,
    QuEST_common.c:450-461). NOTE: on density registers this multiplies the
    ROW space only (P rho, not P rho P+), exactly like the reference.
    One fused flip-form pass regardless of factor count (the reference
    applies one kernel per factor)."""
    val.validate_pauli_targets(targets, paulis)
    term = [0] * q.num_state_qubits
    for t, p in zip(targets, paulis):
        term[int(t)] = int(p)
    if not any(term):
        return q
    return q.replace_amps(_pauli_string_worker(
        q.amps, n=q.num_state_qubits, term=tuple(term), mode=A.mode_key()))


@partial(jax.jit, static_argnames=("n", "term", "mode"))
def _pauli_string_worker(amps, *, n, term, mode):
    return A.apply_pauli_string(amps, n, term)


@jax.jit
def _weighted_sum(a1, a2, a_out, facs):
    def scale(planes, fr, fi):
        return jnp.stack([fr * planes[0] - fi * planes[1],
                          fr * planes[1] + fi * planes[0]])
    return (scale(a1, facs[0], facs[1]) + scale(a2, facs[2], facs[3])
            + scale(a_out, facs[4], facs[5]))


def set_weighted_qureg(fac1, q1: Qureg, fac2, q2: Qureg, fac_out, out: Qureg) -> Qureg:
    """out = fac1*q1 + fac2*q2 + facOut*out (ref QuEST_cpu.c:3579-3620)."""
    val.validate_match(q1, q2)
    val.validate_match(q1, out)
    val.validate_matching_types(q1, q2)
    val.validate_matching_types(q1, out)
    rdt = out.real_dtype
    f1, f2, fo = complex(fac1), complex(fac2), complex(fac_out)
    facs = jnp.asarray([f1.real, f1.imag, f2.real, f2.imag, fo.real, fo.imag],
                       dtype=rdt)
    amps = _weighted_sum(q1.amps.astype(rdt), q2.amps.astype(rdt), out.amps,
                         facs)
    return out.replace_amps(amps)
