"""Pallas TPU mega-kernel over band-fusion plans: many bands per HBM pass.

The XLA band engine (quest_tpu/ops/fusion.py + apply_band) costs one full
memory pass per band contraction — and for bands whose bits are not the
minor axis, XLA inserts full-state transposes around the matmul (measured:
bands 1/2 access 1.6-2x the state bytes; see scripts/probe_band_hlo.py).
This kernel runs a whole SEGMENT of band operators in one pass: each grid
step holds a (2, rows, 128) block of the split re/im planes in VMEM and
applies every stage there, where relayout costs VPU/XLU shuffles instead
of HBM traffic. It is the TPU-native analogue of the reference's
single-pass OpenMP/CUDA per-gate kernels (QuEST_cpu.c:1656-3620,
QuEST_gpu.cu) — except one pass covers MANY gates.

In-block geometry (block_row_bits = log2 rows, lanes = 128):
  band 0   qubits 0..6          lane axis: X @ G^T on the MXU
  band 1   qubits 7..13         sublane axis: cheap (T,s,l)->(s,T,l)
                                relayout, one (128, T*128) MXU dot, undo
  band 2   qubits 14..7+brb-1   tile axis: (D,D) @ (D, rows*128/D) dot
  diagonals / parity / controls on ANY qubit (including grid bits beyond
  the block): elementwise factors from lane iota x global row id
  (pid * rows + iota) — they never break a segment.

Band operators ride along as (2, D, D) kernel INPUTS, not baked
constants, so segments with identical structure but different angles
compile to the same kernel (layer reuse across RCS depth).

Gates that MIX grid bits (non-diagonal targets above the block top) are
not expressible in one contiguous-block pass; the circuit layer splits
the plan into segments at those ops and applies them through the XLA
band path (quest_tpu/circuit.py compiled_fused).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from quest_tpu.ops import fusion as F

LANE_QUBITS = 7
LANES = 1 << LANE_QUBITS
DEFAULT_BLOCK_ROW_BITS = 11   # 2048-row blocks: 1 MiB per plane per block
VMEM_LIMIT_BYTES = 100 * (1 << 20)  # v5e has 128 MiB VMEM; the default
# 16 MiB scoped limit rejects multi-stage kernels (measured round 1/2)


def plan_bands(n: int, block_row_bits: int) -> List[Tuple[int, int]]:
    """Band layout matching the kernel's reach: 7-qubit lane and sublane
    bands, a tile band up to the block top, then 7-wide grid bands (those
    compose too — they just run through the XLA path)."""
    inner_top = LANE_QUBITS + block_row_bits
    bands = []
    ql = 0
    while ql < n:
        if ql < inner_top:
            w = min(LANE_QUBITS, n - ql, inner_top - ql)
        else:
            w = min(LANE_QUBITS, n - ql)
        bands.append((ql, w))
        ql += w
    return bands


# ---------------------------------------------------------------------------
# stage descriptors (structure only — matrices are kernel inputs)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class MatStage:
    kind: str                  # 'b0' | 'b1' | 'b2'
    dim: int                   # operator dimension D
    real_only: bool
    lane_preds: Tuple[Tuple[int, int], ...]   # (lane bit, want)
    row_preds: Tuple[Tuple[int, int], ...]    # (GLOBAL row bit, want)


@dataclasses.dataclass(frozen=True)
class PhaseStage:
    """allones phase: multiply amplitudes whose listed bits are all `want`
    by (tre + i*tim)."""
    lane_bits: Tuple[Tuple[int, int], ...]
    row_bits: Tuple[Tuple[int, int], ...]     # GLOBAL row bits
    tre: float
    tim: float


@dataclasses.dataclass(frozen=True)
class ParityStage:
    lane_targets: Tuple[int, ...]
    row_targets: Tuple[int, ...]              # GLOBAL row bits
    angle: float


@dataclasses.dataclass(frozen=True)
class DiagVecStage:
    """General k-qubit diagonal: multiply each amplitude by the entry
    selected by its target-bit pattern (identity where controls unmet).
    Entry index bit j corresponds to targets[j]."""
    targets: Tuple[int, ...]                  # GLOBAL qubits
    dre: Tuple[float, ...]                    # 2^k entries
    dim_: Tuple[float, ...]
    lane_preds: Tuple[Tuple[int, int], ...]
    row_preds: Tuple[Tuple[int, int], ...]


# ---------------------------------------------------------------------------
# segmentation of a fusion plan
# ---------------------------------------------------------------------------


def _split_preds(preds, n):
    lane_p, row_p = [], []
    for q, s in preds:
        if q < LANE_QUBITS:
            lane_p.append((q, s))
        else:
            row_p.append((q - LANE_QUBITS, s))
    return tuple(lane_p), tuple(row_p)


def segment_plan(items: Sequence, n: int, block_row_bits: int):
    """Split fusion-plan items into kernel segments and XLA passthroughs.
    Returns a list of ("segment", [stages], [op_arrays]) and
    ("xla", item) entries, in program order."""
    inner_top = LANE_QUBITS + block_row_bits
    parts: List = []
    stages: List = []
    arrays: List = []

    def flush():
        nonlocal stages, arrays
        if stages:
            parts.append(("segment", stages, arrays))
            stages, arrays = [], []

    for it in items:
        if isinstance(it, F.BandOp):
            if it.ql + it.w <= inner_top:
                real_only = bool(np.all(it.gim == 0.0))
                lane_p, row_p = _split_preds(it.preds, n)
                if it.ql == 0:
                    kind = "b0"
                    g = it.gre.T + 1j * it.gim.T       # X @ G^T form
                elif it.ql == LANE_QUBITS:
                    kind = "b1"
                    g = it.gre + 1j * it.gim
                else:
                    kind = "b2"
                    g = it.gre + 1j * it.gim
                d = 1 << it.w
                stages.append(MatStage(kind, d, real_only, lane_p, row_p))
                arr = np.stack([g.real, g.imag]).astype(np.float32)
                arrays.append(jnp.asarray(arr))
                continue
            flush()
            parts.append(("xla", it))
            continue
        if isinstance(it, F.DiagItem):
            op = it.op
            targets = tuple(op.targets)
            if op.kind == "parity":
                stages.append(ParityStage(
                    tuple(q for q in targets if q < LANE_QUBITS),
                    tuple(q - LANE_QUBITS for q in targets
                          if q >= LANE_QUBITS),
                    float(op.operand)))
                continue
            if op.kind == "diagonal":
                d = np.asarray(op.operand, dtype=np.complex128).reshape(-1)
                lane_p, row_p = _split_preds(
                    tuple(zip(op.controls, op.cstates or
                              (1,) * len(op.controls))), n)
                stages.append(DiagVecStage(
                    targets, tuple(d.real), tuple(d.imag), lane_p, row_p))
                continue
            if op.kind == "allones" and isinstance(
                    op.operand, (int, float, complex)):
                bits = targets + tuple(op.controls)
                want = (1,) * len(targets) + (tuple(op.cstates) or
                                              (1,) * len(op.controls))
                lane_b = tuple((q, s) for q, s in zip(bits, want)
                               if q < LANE_QUBITS)
                row_b = tuple((q - LANE_QUBITS, s) for q, s in
                              zip(bits, want) if q >= LANE_QUBITS)
                t = complex(op.operand)
                stages.append(PhaseStage(lane_b, row_b, t.real, t.imag))
                continue
            flush()
            parts.append(("xla", it))
            continue
        flush()
        parts.append(("xla", it))
    flush()
    return parts


# ---------------------------------------------------------------------------
# kernel
# ---------------------------------------------------------------------------


def _lane_iota():
    return jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)


def _row_iota(rows, pid):
    base = pid * rows
    return base + jax.lax.broadcasted_iota(jnp.int32, (rows, 1), 0)


def _mask_of(rows, pid, lane_preds, row_preds):
    mask = None
    if lane_preds:
        ids = _lane_iota()
        for bit, want in lane_preds:
            m = ((ids >> bit) & 1) == want
            mask = m if mask is None else (mask & m)
    if row_preds:
        ids = _row_iota(rows, pid)
        for bit, want in row_preds:
            m = ((ids >> bit) & 1) == want
            mask = m if mask is None else (mask & m)
    return mask


def _cdot(contract, re, im, gre, gim, real_only):
    """Complex 'contract' of state planes with operator planes, via the
    Gauss 3-multiplication identity (3 MXU passes instead of 4):
      t1 = Gre x_re, t2 = Gim x_im, t3 = (Gre+Gim)(x_re+x_im)
      out_re = t1 - t2, out_im = t3 - t1 - t2."""
    if real_only:
        return contract(gre, re), contract(gre, im)
    t1 = contract(gre, re)
    t2 = contract(gim, im)
    t3 = contract(gre + gim, re + im)
    return t1 - t2, t3 - t1 - t2


def _apply_mat_stage(re, im, st: MatStage, gref, rows, pid):
    g = gref[...]
    gre, gim = g[0], g[1]
    f32 = jnp.float32

    hi = jax.lax.Precision.HIGHEST  # TPU dots default to bf16 passes;
    # f32 amplitudes need full-precision passes (norm drifts ~1e-3 else)

    if st.kind == "b0":
        def contract(gg, x):     # x (rows, LANES) @ G^T (LANES, LANES)
            return jnp.dot(x, gg, preferred_element_type=f32, precision=hi)
        nre, nim = _cdot(contract, re, im, gre, gim, st.real_only)
    elif st.kind == "b1":
        d = st.dim               # sublane band: row bits [0, log2 d)
        a = rows // d

        def contract(gg, x):
            xt = x.reshape(a, d, LANES).transpose(1, 0, 2)
            xt = xt.reshape(d, a * LANES)
            out = jax.lax.dot_general(
                gg, xt, (((1,), (0,)), ((), ())),
                preferred_element_type=f32, precision=hi)
            return out.reshape(d, a, LANES).transpose(1, 0, 2) \
                      .reshape(rows, LANES)
        nre, nim = _cdot(contract, re, im, gre, gim, st.real_only)
    else:  # b2: tile-axis contraction
        d = st.dim

        def contract(gg, x):
            x2 = x.reshape(d, (rows // d) * LANES)
            out = jax.lax.dot_general(
                gg, x2, (((1,), (0,)), ((), ())),
                preferred_element_type=f32, precision=hi)
            return out.reshape(rows, LANES)
        nre, nim = _cdot(contract, re, im, gre, gim, st.real_only)

    mask = _mask_of(rows, pid, st.lane_preds, st.row_preds)
    if mask is not None:
        nre = jnp.where(mask, nre, re)
        nim = jnp.where(mask, nim, im)
    return nre, nim


def _apply_phase_stage(re, im, st: PhaseStage, rows, pid):
    mask = _mask_of(rows, pid, st.lane_bits, st.row_bits)
    tre, tim = np.float32(st.tre), np.float32(st.tim)
    nre = re * tre - im * tim
    nim = re * tim + im * tre
    if mask is None:            # global phase
        return nre, nim
    return jnp.where(mask, nre, re), jnp.where(mask, nim, im)


def _apply_parity_stage(re, im, st: ParityStage, rows, pid):
    sign = None
    if st.lane_targets:
        ids = _lane_iota()
        s = jnp.ones((1, LANES), dtype=jnp.float32)
        for q in st.lane_targets:
            s = s * (1.0 - 2.0 * ((ids >> q) & 1).astype(jnp.float32))
        sign = s
    if st.row_targets:
        ids = _row_iota(rows, pid)
        s = jnp.ones((rows, 1), dtype=jnp.float32)
        for j in st.row_targets:
            s = s * (1.0 - 2.0 * ((ids >> j) & 1).astype(jnp.float32))
        sign = s if sign is None else sign * s
    half = st.angle / 2.0
    cosf = np.float32(np.cos(half))
    sinf = np.float32(np.sin(half)) * sign
    nre = re * cosf + im * sinf
    nim = im * cosf - re * sinf
    return nre, nim


def _bit_of(q, rows, pid):
    """(broadcastable) value of bit `q` of each amplitude's global index."""
    if q < LANE_QUBITS:
        return (_lane_iota() >> q) & 1
    return (_row_iota(rows, pid) >> (q - LANE_QUBITS)) & 1


def _apply_diagvec_stage(re, im, st: DiagVecStage, rows, pid):
    k = len(st.targets)
    fre = jnp.full((1, 1), np.float32(st.dre[0]))
    fim = jnp.full((1, 1), np.float32(st.dim_[0]))
    for b in range(1, 1 << k):
        sel = None
        for j, q in enumerate(st.targets):
            m = _bit_of(q, rows, pid) == ((b >> j) & 1)
            sel = m if sel is None else (sel & m)
        fre = jnp.where(sel, np.float32(st.dre[b]), fre)
        fim = jnp.where(sel, np.float32(st.dim_[b]), fim)
    nre = re * fre - im * fim
    nim = re * fim + im * fre
    mask = _mask_of(rows, pid, st.lane_preds, st.row_preds)
    if mask is not None:
        nre = jnp.where(mask, nre, re)
        nim = jnp.where(mask, nim, im)
    return nre, nim


def _segment_kernel(in_ref, *rest, stages, rows):
    num_mats = sum(isinstance(s, MatStage) for s in stages)
    mat_refs = rest[:num_mats]
    out_ref = rest[num_mats]
    pid = pl.program_id(0)
    blk = in_ref[...]
    re, im = blk[0], blk[1]
    mi = 0
    for st in stages:
        if isinstance(st, MatStage):
            re, im = _apply_mat_stage(re, im, st, mat_refs[mi], rows, pid)
            mi += 1
        elif isinstance(st, PhaseStage):
            re, im = _apply_phase_stage(re, im, st, rows, pid)
        elif isinstance(st, DiagVecStage):
            re, im = _apply_diagvec_stage(re, im, st, rows, pid)
        else:
            re, im = _apply_parity_stage(re, im, st, rows, pid)
    out_ref[0] = re
    out_ref[1] = im


def compile_segment(stages: Sequence, n: int,
                    block_row_bits: int = DEFAULT_BLOCK_ROW_BITS,
                    interpret: bool = False):
    """Build fn(amps, mat_arrays) -> amps applying `stages` in one kernel
    launch (grid over contiguous row blocks)."""
    total_rows = 1 << (n - LANE_QUBITS)
    rows = min(1 << block_row_bits, total_rows)
    grid = (total_rows // rows,)

    mat_stages = [s for s in stages if isinstance(s, MatStage)]
    kernel = functools.partial(_segment_kernel, stages=tuple(stages),
                               rows=rows)
    in_specs = [pl.BlockSpec((2, rows, LANES), lambda i: (0, i, 0))]
    for st in mat_stages:
        in_specs.append(pl.BlockSpec((2, st.dim, st.dim),
                                     lambda i: (0, 0, 0)))
    fn = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((2, rows, LANES), lambda i: (0, i, 0)),
        out_shape=jax.ShapeDtypeStruct((2, total_rows, LANES), jnp.float32),
        input_output_aliases={0: 0},  # in-place on the state buffer
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=VMEM_LIMIT_BYTES),
        interpret=interpret,
    )

    def apply(amps, mat_arrays):
        out = fn(amps.reshape(2, total_rows, LANES), *mat_arrays)
        return out.reshape(2, -1)

    return apply


def usable(n: int) -> bool:
    """Need at least one (8, 128) f32 tile per block."""
    return n >= LANE_QUBITS + 3
