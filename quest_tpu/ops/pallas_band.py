"""Pallas TPU mega-kernel over band-fusion plans: many gates per HBM pass.

The XLA band engine (quest_tpu/ops/fusion.py + apply_band) costs one full
memory pass per band contraction — and for bands whose bits are not the
minor axis, XLA inserts full-state transposes around the matmul (measured:
those bands access 1.6-2x the state bytes; scripts/probe_band_hlo.py).
This kernel runs a whole SEGMENT of operators in one pass; relayout inside
the block costs VPU/XLU shuffles instead of HBM traffic. It is the
TPU-native analogue of the reference's single-pass OpenMP/CUDA per-gate
kernels (QuEST_cpu.c:1656-3620, QuEST_gpu.cu) — except one pass covers
MANY gates.

Block geometry. The (2, 2^n) split re/im planes are viewed as
(2, ...row axes..., 128): qubits 0..6 are the lane axis; row bits make up
the rest. Each grid step's block holds:

  inner rows   the lowest `inner_bits` row bits, contiguous —
               qubits 7..7+inner_bits-1
  scattered    up to SCATTER_MAX individual HIGH row bits, each exposed
               as its own size-2 axis of the view so the block contains
               BOTH butterfly halves of that qubit (the BlockSpec gathers
               the strips in one DMA) — this is how gates on ARBITRARY
               high qubits stay fused, the on-chip analogue of the
               reference's pair-rank exchange (getChunkPairId,
               QuEST_cpu_distributed.c:303-312)

Stages inside the block:
  b0   composed 128x128 operator on the lane band: X @ G^T on the MXU
  b1   composed operator on the sublane band (qubits 7..13): cheap
       (A,d,l)->(A*l,d) tile relayout, one LARGE-M MXU dot X @ G^T, undo
       (the (d,A*l) small-m orientation measured +17 ms/pass vs +4)
  scb  composed 2^w x 2^w operator on a HIGH band (qubits 14+): ONE MXU
       dot over the band's w merged scattered axes — a whole layer of
       gates on qubits 14..20 costs one dot instead of 7 serial VPU
       butterflies (measured 4x on those bands at 29q)
  sc   composed 2x2 on one scattered qubit (width-1 remainder bands):
       elementwise butterfly
  diagonal / all-ones / parity phases on ANY qubits (global row ids from
       the grid indices) — these never break a segment
  controls anywhere become lane/global-row-id masks

Operator matrices ride along as kernel INPUTS, not baked constants, so
segments with identical structure but different angles compile to the
same kernel (layer reuse across RCS depth).

A segment ends when the next stage's scattered row bits would exceed
SCATTER_MAX, or when the in-block row bits (sublane floor from b1/pair
stages + scattered axes) would exceed MAX_BLOCK_ROW_BITS — the VMEM
budget; a b1 stage and a full 7-bit scb therefore land in separate
segments. Ops the kernel cannot host at all (>=3-target cross-band
unitaries, oversized single stages under a caller-shrunk scatter budget)
run as XLA band passthroughs between segments (quest_tpu/circuit.py
compiled_fused).
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from quest_tpu import compat
from quest_tpu import precision
from quest_tpu.ops import fusion as F

_MEMSPACE, _COMPILER_PARAMS = compat.pallas_tpu_names()

LANE_QUBITS = 7
LANES = 1 << LANE_QUBITS
SUBLANE_TOP = 2 * LANE_QUBITS  # first qubit above the sublane band
ROWS_EFF_BITS = 12    # log2 of rows held per block (scattered x inner):
# (2, 4096, 128) f32 = 4 MiB per block buffer; with Pallas double-buffering
# and stage temporaries this stays within VMEM_LIMIT_BYTES
SCATTER_MAX = 7       # scattered row bits per segment: enough for one
# full high band as an scb stage
MAX_BLOCK_ROW_BITS = 13  # cap on in-block row bits (sublane floor +
# scattered axes) under the GRID driver: a 2^13-row block is
# 2 x 8192 x 128 f32 = 8 MiB; the automatic pipeline holds it
# double-buffered in+out plus stage temporaries (measured: 2^14 rows hit
# 118 MiB of scoped VMEM and failed to compile)
PIPELINED_MAX_BLOCK_ROW_BITS = 13  # the pipelined driver's in-place
# slots halve BLOCK buffer memory, but 2^14-row blocks still fail on
# chip: Mosaic's register allocator spills ~96 MiB of block-sized SSA
# values for the stage chain (measured r4: 144.12 MiB total vs the
# 128 MiB physical VMEM; chunking the b1 contraction did not move it —
# the spills are chain-wide, not per-stage). A b1 stage and a full
# 7-bit scb therefore stay in separate passes on EVERY driver; do not
# retry without evidence the spill behavior changed.
MAX_SEGMENT_STAGES = 32  # stages per kernel launch: operand blocks are
# resident in VMEM (a 128x128 operator pair is 131 KiB), so unbounded
# deep circuits at small n — where few flushes happen naturally — would
# otherwise accumulate hundreds of operands per segment
VMEM_LIMIT_BYTES = 100 * (1 << 20)  # v5e has 128 MiB VMEM; the default
# 16 MiB scoped limit rejects multi-stage kernels (measured round 1/2)


def plan_bands(n: int) -> List[Tuple[int, int]]:
    """Band layout matching the kernel's reach: 7-qubit bands everywhere.
    The lane band contracts on the lane axis, the sublane band on the
    sublane axis, and each HIGH band becomes one MXU contraction over its
    merged scattered axes (an 'scb' stage) — so a whole layer of gates on
    qubits 14..20 costs ONE dot instead of 7 serial VPU butterflies
    (measured 4x on those bands at 29q). Width-1 remainders stay
    scattered-axis butterflies."""
    bands = []
    ql = 0
    while ql < n:
        w = min(LANE_QUBITS, n - ql)
        bands.append((ql, w))
        ql += w
    return bands


# ---------------------------------------------------------------------------
# stage descriptors (structure only — matrices are kernel inputs)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class MatStage:
    kind: str                  # 'b0' | 'b1' | 'sc' | 'scb'
    dim: int                   # operator dimension D
    real_only: bool
    lane_preds: Tuple[Tuple[int, int], ...]   # (lane bit, want)
    row_preds: Tuple[Tuple[int, int], ...]    # (GLOBAL row bit, want)
    bit: int = -1              # 'sc': the GLOBAL row bit this acts on;
    # 'scb': the LOWEST of the log2(dim) contiguous row bits the composed
    # high-band operator contracts over (each a scattered block axis)


@dataclasses.dataclass(frozen=True)
class PhaseStage:
    """allones phase: multiply amplitudes whose condition bits match by
    (tre + i*tim). The stage carries NO structure at all — the phase
    value AND the bit predicates ride in one (1, 8) kernel input
    [tre, tim, lane_mask, lane_want, row_mask_lo, row_mask_hi,
    row_want_lo, row_want_hi] (row masks split at bit 15 so each half
    is an exact integer in f32). Every phase stage in a program
    therefore shares ONE compiled kernel structure: QFT-30's 435
    distinct controlled-phase qubit pairs cost one Mosaic compile, not
    one per pair (measured: 14 -> 8 distinct kernels for the whole
    QFT-30 schedule)."""


@dataclasses.dataclass(frozen=True)
class ParityStage:
    """exp(-i angle/2 Z...Z); like PhaseStage, carries no structure:
    the (1, 8) kernel input is [cos, sin, lane_mask, row_mask_lo,
    row_mask_hi, 0, 0, 0] of the half angle and the target-bit masks
    (parity computed in-kernel by XOR-folding the masked index bits)."""


@dataclasses.dataclass(frozen=True)
class PairStage:
    """General (possibly non-unitary) 2-qubit matrix on (q_op, q_sliced):
    the sliced qubit's two halves select 2x2 blocks M[r][c], each applied
    on the op-side qubit — out_r = sum_c M_rc x_c. This is how Kraus
    superoperators on the doubled density register (targets (t, t+N),
    ref QuEST_common.c:540-673) stay fused at any register size.

    op_kind: 'lane' (M_rc embedded 128x128, right-matmul) |
             'b1'   (M_rc embedded 128x128 on the sublane axis) |
             'sc'   (M_rc 2x2 scalars; q_op has its own scattered axis)
    sliced_kind: 'scat' (own scattered axis) | 'sub' (sublane bit; only
             valid when op_kind == 'lane')."""
    op_kind: str
    op_dim: int                               # 128 or 2
    op_bit: int                               # 'sc': GLOBAL row bit
    sliced_kind: str
    sliced_bit: int                           # GLOBAL row bit
    real_only: bool
    lane_preds: Tuple[Tuple[int, int], ...]
    row_preds: Tuple[Tuple[int, int], ...]


@dataclasses.dataclass(frozen=True)
class MultiPhaseStage:
    """A scheduler-composed GROUP of unit phases in ONE stage, applied
    ADDITIVELY: each row contributes an angle (an allones row adds its
    theta where all masked bits are 1; a parity row adds -half*(-1)^par)
    and the stage pays cos/sin + one complex multiply ONCE for the whole
    group — m mask-accumulates instead of m full phase stages (each with
    its own trig blend), and ONE stage against MAX_SEGMENT_STAGES
    instead of m. The (m, 8) operand rows are
    [angle, lane_mask, row_mask_lo, row_mask_hi, 0, 0, 0, 0] (row masks
    split at bit 15 so each half is exact in f32); `forms` carries the
    static per-row interpretation: 'a' = allones, 'p' = parity."""
    forms: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class BatchSelStage:
    """Per-STATE 2x2 operator on one GLOBAL qubit — the batched
    trajectory engine's channel stage (docs/BATCHING.md). The operand is
    a (batch, 8) f32 table of rows [g00re, g00im, g01re, g01im, g10re,
    g10im, g11re, g11im]: each state's drawn Kraus branch (with the
    1/sqrt(p) renormalization folded in) rides as its own row, and the
    kernel selects row `batch index` — a per-state one-hot select inside
    the sweep instead of a vmap of eager per-gate workers. The 2x2 stays
    UNEMBEDDED whatever the qubit position (scattered bits butterfly on
    per-state scalars; lane/sublane bits build their embedded operator
    in-kernel from the 8 scalars via iota masks, _batchsel_embed), so
    the operand is batch x 32 bytes for ANY qubit — a host-side
    embedding would cost batch x 128 KiB of VMEM for lane qubits.

    `index` is the channel ordinal in the program: plan-time operand
    arrays are ZERO PLACEHOLDERS sized (batch, 8) — they thread the
    batch through the sweep operand-byte budget — and the engines
    substitute the traced per-state operand for slot `index` at call
    time. `barrier` marks operands that depend on the PRE-channel state
    (general Kraus: Born probabilities need the state), which pins the
    stage to the FRONT of its launch: segment_plan flushes before it and
    sweep_plan never merges its segment into an earlier one. Unitary
    mixtures (state-independent probabilities) set barrier=False and
    fuse anywhere."""
    qubit: int
    index: int
    barrier: bool = True


@dataclasses.dataclass(frozen=True)
class ChannelItem:
    """Plan-stream marker for a batched per-state channel on GLOBAL
    qubit `qubit` (trajectories.run_batched interleaves these with the
    fusion plan's items); segment_plan turns each into a BatchSelStage
    with a (batch, 8) placeholder operand."""
    qubit: int
    index: int
    barrier: bool = True

    def qubits(self):
        return (self.qubit,)


@dataclasses.dataclass(frozen=True)
class DiagVecStage:
    """General k-qubit diagonal: multiply each amplitude by the entry
    selected by its target-bit pattern (identity where controls unmet).
    Entry index bit j corresponds to targets[j]; the (2, 2^k) re/im
    entry table rides as a kernel input."""
    targets: Tuple[int, ...]                  # GLOBAL qubits
    lane_preds: Tuple[Tuple[int, int], ...]
    row_preds: Tuple[Tuple[int, int], ...]


# ---------------------------------------------------------------------------
# segmentation of a fusion plan
# ---------------------------------------------------------------------------


def _split_preds(preds):
    lane_p, row_p = [], []
    for q, s in preds:
        if q < LANE_QUBITS:
            lane_p.append((q, s))
        else:
            row_p.append((q - LANE_QUBITS, s))
    return tuple(lane_p), tuple(row_p)


def stage_requirements(stages) -> Tuple[set, int]:
    """(scattered GLOBAL row bits, sublane floor) a stage list needs
    resident in one block — the block-geometry contract shared by
    compile_segment (which sizes the block from it) and the sweep-fusion
    layer (which merges segments only when the UNION still fits the
    budgets). One accounting, two consumers, so the merge rule cannot
    drift from what the kernel actually allocates."""
    scat: set = set()
    floor = 0
    for st in stages:
        if isinstance(st, MatStage):
            if st.kind == "sc":
                scat.add(st.bit)
            elif st.kind == "scb":
                scat |= set(range(st.bit, st.bit + st.dim.bit_length() - 1))
            elif st.kind == "b1":
                floor = max(floor, st.dim.bit_length() - 1)
        elif isinstance(st, PairStage):
            if st.sliced_kind == "scat":
                scat.add(st.sliced_bit)
            if st.op_kind == "sc":
                scat.add(st.op_bit)
            if st.op_kind == "b1":
                floor = max(floor, LANE_QUBITS)
            if st.sliced_kind == "sub":
                floor = max(floor, st.sliced_bit + 1)
        elif isinstance(st, BatchSelStage):
            if st.qubit >= SUBLANE_TOP:
                scat.add(st.qubit - LANE_QUBITS)
            elif st.qubit >= LANE_QUBITS:
                # sublane bit j contracts the lowest j+1 row bits
                floor = max(floor, st.qubit - LANE_QUBITS + 1)
    return scat, floor


def max_block_row_bits() -> int:
    """The in-block row-bit budget for the ACTIVE kernel driver. Both
    budgets are currently 13 — the pipelined driver's in-place slots
    were expected to afford a 14th bit but measured out on chain-wide
    register spills (see PIPELINED_MAX_BLOCK_ROW_BITS) — but planning
    keeps asking per driver so a future driver with a real memory edge
    changes ONE constant, not the planner."""
    return (PIPELINED_MAX_BLOCK_ROW_BITS
            if _driver_override() == "pipelined" else MAX_BLOCK_ROW_BITS)


def segment_plan(items: Sequence, n: int, scatter_max: int = SCATTER_MAX,
                 batch: int = 1, attr: Optional[list] = None):
    """Split fusion-plan items into kernel segments and XLA passthroughs.
    Returns a list of ("segment", [stages], [op_arrays]) and
    ("xla", item) entries, in program order. `batch` sizes the
    (batch, 8) zero-placeholder operands of ChannelItem stages (batched
    trajectory channels) — the one place the batch enters the plan's
    operand-byte accounting; all other stage operands are shared across
    the batch and stay batch-independent. `attr`, when a list, receives
    one tuple of input ITEM indices per emitted part (the durable
    executor's elastic cut-boundary attribution, composed with
    fusion.plan's per-item op attribution — docs/RESILIENCE.md
    §elastic)."""
    parts: List = []
    part_src: List[tuple] = []      # item indices per emitted part
    seg_src: List[int] = []         # item indices in the open segment
    cur_item = -1
    stages: List = []
    arrays: List = []
    scat_bits: set = set()
    b1_floor = 0    # in-block sublane bits forced by b1/pair stages
    row_budget = max_block_row_bits()

    def flush():
        nonlocal stages, arrays, scat_bits, b1_floor, seg_src
        if stages:
            parts.append(("segment", stages, arrays))
            part_src.append(tuple(seg_src))
            stages, arrays = [], []
        seg_src = []
        scat_bits = set()
        b1_floor = 0

    def emit_xla(it):
        parts.append(("xla", it))
        part_src.append((cur_item,))

    def reserve(bits=frozenset(), floor=0):
        """Claim scattered row bits / a sublane-floor for the next stage,
        flushing first if the block would outgrow its VMEM budget
        (MAX_BLOCK_ROW_BITS rows — the kernel stack holds the block
        double-buffered in+out plus stage temporaries) or its scattered-
        axis budget. Returns False — claiming nothing — when the stage's
        OWN requirement exceeds the budgets even in a fresh segment (the
        caller must fall back to an XLA passthrough)."""
        nonlocal scat_bits, b1_floor
        if (len(set(bits)) > scatter_max
                or floor + len(set(bits)) > row_budget):
            return False
        new_scat = scat_bits | set(bits)
        new_floor = max(b1_floor, floor)
        if (len(new_scat) > scatter_max
                or new_floor + len(new_scat) > row_budget):
            flush()
            new_scat = set(bits)
            new_floor = floor
        scat_bits = new_scat
        b1_floor = new_floor
        return True

    for cur_item, it in enumerate(items):
        if len(stages) >= MAX_SEGMENT_STAGES:
            flush()
        if isinstance(it, ChannelItem):
            # batched per-state channel: a barrier channel's operand is
            # computed from the state BETWEEN launches, so the running
            # segment flushes first and the stage opens a fresh one
            # (following stages still fuse in after it); a mixture
            # channel's operand depends only on the per-state keys and
            # fuses like any stage
            if it.barrier:
                flush()
            q = it.qubit

            def reserve_channel():
                if q >= SUBLANE_TOP:
                    return reserve(bits=(q - LANE_QUBITS,))
                if q >= LANE_QUBITS:
                    return reserve(floor=q - LANE_QUBITS + 1)
                return True
            if not reserve_channel():
                # only reachable under a caller-shrunk scatter budget;
                # a single channel's bit/floor always fits a fresh
                # segment, so a failed retry means the budget cannot
                # hold ANY channel stage — refuse loudly (a real raise,
                # not an assert: appending an unreserved stage would
                # silently corrupt the block geometry under python -O)
                flush()
                if not reserve_channel():
                    raise ValueError(
                        f"channel qubit {q} does not fit an empty "
                        f"segment under the caller's scatter budget")
            stages.append(BatchSelStage(q, it.index, it.barrier))
            seg_src.append(cur_item)
            arrays.append(np.zeros((batch, 8), dtype=np.float32))
            continue
        if isinstance(it, F.BandOp):
            lane_p, row_p = _split_preds(it.preds)
            if it.ql == 0:
                kind, bit = "b0", -1
                g = it.gre.T + 1j * it.gim.T       # X @ G^T form
            elif it.ql == LANE_QUBITS:
                kind, bit = "b1", -1
                # X @ G^T form, pre-transposed on the host like b0's —
                # the kernel never pays a per-block gate transpose
                g = (it.gre + 1j * it.gim).T
                reserve(floor=it.w)
            elif it.w == 1:
                kind, bit = "sc", it.ql - LANE_QUBITS
                g = it.gre + 1j * it.gim
                if not reserve(bits=(bit,)):
                    flush()
                    emit_xla(it)
                    continue
            else:                  # high band: one MXU dot over its
                kind = "scb"       # merged scattered axes
                bit = it.ql - LANE_QUBITS
                g = it.gre + 1j * it.gim
                w = it.w
                # a run that only mixed SOME of the band's qubits (QFT's
                # per-qubit Hadamards, sparse circuits) is often an exact
                # embedding over a narrower sub-range: contract only the
                # spanning sub-band — a 2x2 butterfly instead of a padded
                # 128-dot for a lone gate, fewer scattered axes always
                nd = sorted(q - it.ql for q in it.nondiag
                            if it.ql <= q < it.ql + it.w)
                if nd and (nd[0] > 0 or nd[-1] < it.w - 1):
                    j0, w2 = nd[0], nd[-1] - nd[0] + 1
                    idx = [x << j0 for x in range(1 << w2)]
                    sub = g[np.ix_(idx, idx)]
                    if np.allclose(g, F.embed_operator(
                            sub, list(range(j0, j0 + w2)), [], [], it.w)):
                        kind = "scb" if w2 > 1 else "sc"
                        bit = bit + j0
                        g = sub
                        w = w2
                if not reserve(bits=range(bit, bit + w)):
                    flush()
                    emit_xla(it)
                    continue
                # do NOT Kron-split a factorizable band operator into
                # narrow per-factor dots: measured r4, a narrow scb's
                # MXU time is ~flat in d (~40 ms/stage at 30q — a
                # small-M dot idles most of the systolic array, so time
                # scales with output size, not MACs), and splitting one
                # 42.6 ms d=128 stage into d4+d4+d8 measured 161 ms.
                # The single wide dot is already the cheapest form.
            real_only = bool(np.all(g.imag == 0.0))
            if kind == "scb" and g.shape[0] == LANES:
                # X @ G^T form for the full-width band, matching the
                # kernel's large-d mirrored frame (small d keeps the
                # left-dot: its dot is cheap and the 8<->128 tile swaps
                # of the mirror are not — measured 538 ms/application
                # when applied to a d=8 stage)
                g = g.T
            stages.append(MatStage(kind, g.shape[0], real_only, lane_p,
                                   row_p, bit))
            seg_src.append(cur_item)
            # keep operator arrays HOST-side (numpy): as closure
            # constants they upload with the program instead of occupying
            # HBM and round-tripping device->host at trace time
            arrays.append(np.stack([g.real, g.imag]).astype(np.float32))
            continue
        if isinstance(it, F.DiagItem):
            op = it.op
            targets = tuple(op.targets)
            if op.kind == "parity":
                half = float(op.operand) / 2.0
                lm = sum(1 << q for q in targets if q < LANE_QUBITS)
                rm = sum(1 << (q - LANE_QUBITS) for q in targets
                         if q >= LANE_QUBITS)
                stages.append(ParityStage())
                seg_src.append(cur_item)
                arrays.append(np.array(
                    [[np.cos(half), np.sin(half), lm,
                      rm & 0x7FFF, rm >> 15, 0, 0, 0]], dtype=np.float32))
                continue
            if op.kind == "diagonal":
                parts_rel = getattr(op, "parts", ())
                if parts_rel:
                    # scheduler-composed phase group (fusion.ComposedDiag
                    # with target-relative parts): one additive
                    # MultiPhaseStage instead of a 2^k select chain
                    rows, forms = [], []
                    for form, bits, val in parts_rel:
                        qs = [targets[b] for b in bits]
                        lm = sum(1 << q for q in qs if q < LANE_QUBITS)
                        rm = sum(1 << (q - LANE_QUBITS) for q in qs
                                 if q >= LANE_QUBITS)
                        ang = val if form == "allones" else -val / 2.0
                        rows.append([ang, lm, rm & 0x7FFF, rm >> 15,
                                     0, 0, 0, 0])
                        forms.append("a" if form == "allones" else "p")
                    stages.append(MultiPhaseStage(tuple(forms)))
                    seg_src.append(cur_item)
                    arrays.append(np.array(rows, dtype=np.float32))
                    continue
                d = np.asarray(op.operand, dtype=np.complex128).reshape(-1)
                lane_p, row_p = _split_preds(
                    tuple(zip(op.controls, op.cstates or
                              (1,) * len(op.controls))))
                stages.append(DiagVecStage(targets, lane_p, row_p))
                seg_src.append(cur_item)
                arrays.append(np.stack([d.real, d.imag]).astype(np.float32))
                continue
            if op.kind == "allones" and isinstance(
                    op.operand, (int, float, complex)):
                bits = targets + tuple(op.controls)
                want = (1,) * len(targets) + (tuple(op.cstates) or
                                              (1,) * len(op.controls))
                lm = lw = rm = rw = 0
                for q, s in zip(bits, want):
                    if q < LANE_QUBITS:
                        lm |= 1 << q
                        lw |= s << q
                    else:
                        rm |= 1 << (q - LANE_QUBITS)
                        rw |= s << (q - LANE_QUBITS)
                t = complex(op.operand)
                stages.append(PhaseStage())
                seg_src.append(cur_item)
                arrays.append(np.array(
                    [[t.real, t.imag, lm, lw, rm & 0x7FFF, rm >> 15,
                      rw & 0x7FFF, rw >> 15]], dtype=np.float32))
                continue
            flush()
            emit_xla(it)
            continue
        if isinstance(it, F.PassOp):
            st = _try_pair_stage(it, scatter_max)
            if st is not None:
                stage, arr, new_scat = st
                floor = 0
                if stage.op_kind == "b1":
                    floor = LANE_QUBITS
                if stage.sliced_kind == "sub":
                    floor = max(floor, stage.sliced_bit + 1)
                if reserve(bits=new_scat or frozenset(), floor=floor):
                    stages.append(stage)
                    seg_src.append(cur_item)
                    arrays.append(arr)
                    continue
        flush()
        emit_xla(it)
    flush()
    if attr is not None:
        attr.extend(part_src)
    return parts


def _try_pair_stage(it, scatter_max):
    """PassOp -> (PairStage, operand array, scat bits needed) when the op
    is an uncontrolled 2-target matrix whose qubits the kernel can reach;
    None otherwise."""
    op = it.op
    if op.kind != "matrix" or len(op.targets) != 2 or op.controls:
        return None
    m = np.asarray(op.operand)
    if m.shape != (4, 4) or not np.issubdtype(m.dtype, np.number):
        return None
    qa, qb = op.targets           # matrix bit 0 = qa, bit 1 = qb

    def locate(q):
        if q < LANE_QUBITS:
            return "lane"
        if q < SUBLANE_TOP:
            return "sub"
        return "scat"

    la, lb = locate(qa), locate(qb)
    # pick the sliced qubit: prefer a scattered one; a sublane qubit may
    # only be sliced when the op side is a lane qubit
    if lb == "scat":
        q_op, q_sl, bit_op = qa, qb, 0
    elif la == "scat":
        q_op, q_sl, bit_op = qb, qa, 1
    elif la == "lane" and lb == "sub":
        q_op, q_sl, bit_op = qa, qb, 0
    elif lb == "lane" and la == "sub":
        q_op, q_sl, bit_op = qb, qa, 1
    else:
        return None               # same-band pairs are composed upstream
    op_loc = locate(q_op)
    sliced_kind = "scat" if locate(q_sl) == "scat" else "sub"

    need = set()
    if sliced_kind == "scat":
        need.add(q_sl - LANE_QUBITS)
    if op_loc == "scat":
        need.add(q_op - LANE_QUBITS)
    if len(need) > scatter_max:
        return None

    m = m.astype(np.complex128)
    blocks = np.empty((2, 4), dtype=object)
    for r in range(2):
        for c in range(2):
            sub = np.empty((2, 2), dtype=np.complex128)
            for ao in range(2):
                for ai in range(2):
                    row = (ao << bit_op) | (r << (1 - bit_op))
                    col = (ai << bit_op) | (c << (1 - bit_op))
                    sub[ao, ai] = m[row, col]
            if op_loc == "lane":
                emb = _embed_2x2(sub, q_op).T            # X @ G^T form
            elif op_loc == "sub":
                emb = _embed_2x2(sub, q_op - LANE_QUBITS).T  # X @ G^T form
            else:
                emb = sub
            blocks[0, r * 2 + c] = emb.real.astype(np.float32)
            blocks[1, r * 2 + c] = emb.imag.astype(np.float32)
    d = blocks[0, 0].shape[0]
    arr = np.stack([np.stack(list(blocks[p])) for p in range(2)])
    kind = {"lane": "lane", "sub": "b1", "scat": "sc"}[op_loc]
    real_only = bool(np.all(m.imag == 0.0))
    st = PairStage(kind, d, q_op - LANE_QUBITS if op_loc == "scat" else -1,
                   sliced_kind, q_sl - LANE_QUBITS, real_only, (), ())
    return st, arr, (need if need else None)


def _embed_2x2(sub, pos):
    """Embed a 2x2 at bit `pos` of a 7-bit space (lane or sublane)."""
    return F.embed_operator(sub, [pos], [], [], LANE_QUBITS)


# ---------------------------------------------------------------------------
# sweep fusion: many segments per HBM pass
# ---------------------------------------------------------------------------
#
# segment_plan flushes a segment whenever the NEXT stage's block
# requirement would outgrow the running budget — a greedy, forward-only
# split. Two split causes are recoverable after the fact:
#
#   * the MAX_SEGMENT_STAGES cap (a VMEM-operand-residency guard sized
#     for the worst case of 32 dense 128x128 operators — most stages'
#     operands are a few hundred bytes);
#   * the per-APPLICATION boundary: Circuit engines repeat the whole
#     part list `iters` times per dispatch, and the last segment of one
#     application is usually block-compatible with the first segment of
#     the next (the fusion-resistant chain benchmark is the extreme
#     case — every application is ONE segment, so consecutive
#     applications always merge until a sweep budget binds).
#
# sweep_plan re-merges CONSECUTIVE segment parts whose combined stage
# list still fits one block geometry: scattered-bit UNION within the
# scatter budget, sublane floor + scattered axes within the row budget
# (stage_requirements — the same accounting compile_segment sizes the
# block from), bounded stage count, and an explicit operand-byte budget
# replacing the blunt per-segment stage cap (operand arrays are
# whole-array VMEM-resident for the duration of a launch, next to the
# NBUF in-place block slots of the pipelined driver). Any non-segment
# part (an XLA passthrough) is a barrier. The merged kernel streams
# each state block HBM->VMEM ONCE, applies the whole stage sequence,
# and writes back — with the pipelined driver's double-buffered
# make_async_copy schedule overlapping the next block's DMA-in and the
# previous block's DMA-out with compute (docs/SWEEPS.md).

MAX_SWEEP_STAGES = 64   # stages per merged sweep: twice the per-segment
# cap. NOT validated on silicon — Mosaic register pressure grows with
# the stage chain (the 2^14-row spills of PIPELINED_MAX_BLOCK_ROW_BITS
# were chain-wide), so the first on-chip run should A/B this against
# QUEST_SWEEP_FUSION=0 before trusting deep sweeps.
SWEEP_OPERAND_BYTES = 48 * (1 << 20)  # VMEM operand budget per sweep
# under the LEGACY in-place slot driver: 100 MiB scoped limit minus
# NBUF (3) 8 MiB block slots and headroom for stage temporaries. 48 MiB
# holds ~380 dense 128x128 operator pairs — the stage cap binds first
# on real plans.
PIPELINE_IN_SLOTS = 2   # decoupled pipeline: VMEM slots per DMA ring.
PIPELINE_OUT_SLOTS = 2  # 2 in + 2 out = the read stream one full step
# ahead of compute and the write stream one full step behind, each on
# its OWN semaphore chain — in(s+1) never waits for out(s+1-nbuf) to
# drain (the in-place coupling that made nbuf=2 stall a full out-DMA
# per step: measured 23.8 vs 20.5 ms on the 28q bench).
PIPELINE_SWEEP_OPERAND_BYTES = 40 * (1 << 20)  # the decoupled rings
# hold 4 block slots (32 MiB at the 2^13-row cap) where the legacy
# driver held 3 (24 MiB); the operand budget gives the extra slot back
# so slots + operands + headroom still fit the 100 MiB scoped limit —
# the same stage_requirements()-anchored accounting, one more slot.


def pipeline_enabled() -> bool:
    """QUEST_FUSED_PIPELINE knob: '1' (default) runs the decoupled
    multi-buffer pipeline in the manually pipelined driver; '0' keeps
    the legacy in-place NBUF slot schedule (the silicon A/B control).
    Keyed in the registry, so every compiled-program cache key carries
    it (env.engine_mode_key; flip-audited in tests/test_lint.py)."""
    from quest_tpu.env import knob_value
    return knob_value("QUEST_FUSED_PIPELINE")


def decoupled_active() -> bool:
    """Whether compiled segments will run the decoupled pipeline: the
    manual slot driver is selected AND the pipeline knob is on. The ONE
    predicate shared by compile_segment (driver pick), sweep_plan's
    operand budget and pipeline_stats, so the planner, the budget and
    the introspection can never disagree about the active schedule."""
    return _driver_override() == "pipelined" and pipeline_enabled()


def sweep_operand_budget() -> int:
    """Effective per-sweep VMEM operand budget for the ACTIVE kernel
    schedule: the decoupled pipeline's 4 block slots leave
    PIPELINE_SWEEP_OPERAND_BYTES; the legacy in-place driver (knob off,
    or the grid driver) keeps the original SWEEP_OPERAND_BYTES —
    bit-for-bit the old plans when QUEST_FUSED_PIPELINE=0."""
    if decoupled_active():
        return PIPELINE_SWEEP_OPERAND_BYTES
    return SWEEP_OPERAND_BYTES


def sweep_plan(parts, n: int, *, scatter_max: int = SCATTER_MAX,
               row_budget: int = None, max_stages: int = MAX_SWEEP_STAGES,
               operand_bytes: int = None, attr: Optional[list] = None,
               part_attrs: Optional[Sequence] = None):
    """Merge consecutive ("segment", stages, arrays) parts of a
    segment_plan (or a concatenation of several applications' plans)
    into maximal single-launch sweeps, preserving program order.
    Returns the same part format, so every downstream consumer
    (compile_segment, _scan_partition, the sharded compilers) is
    unchanged. `n` is unused by the merge rule itself but kept so the
    layer sits uniformly between segment_plan(items, n) and the kernel
    compilers. `attr`, when a list, receives one tuple of attribution
    entries per OUTPUT part, merged from `part_attrs` (one tuple per
    input part, e.g. segment_plan's item attribution; defaults to each
    input part's own index) — the durable elastic layer's cut-boundary
    bookkeeping (docs/RESILIENCE.md §elastic)."""
    del n
    if row_budget is None:
        row_budget = max_block_row_bits()
    if operand_bytes is None:
        operand_bytes = sweep_operand_budget()
    if part_attrs is None:
        part_attrs = [(i,) for i in range(len(parts))]
    out = []
    out_attr: List[tuple] = []
    cur_scat: set = set()
    cur_floor = 0
    cur_bytes = 0
    for pi, part in enumerate(parts):
        src = tuple(part_attrs[pi])
        if part[0] != "segment":
            out.append(part)            # XLA passthrough: a sweep barrier
            out_attr.append(src)
            cur_scat, cur_floor, cur_bytes = set(), 0, 0
            continue
        stages, arrays = list(part[1]), list(part[2])
        scat, floor = stage_requirements(stages)
        nbytes = sum(a.nbytes for a in arrays)
        # a barrier BatchSelStage (general-Kraus channel) reads the
        # state as it stands at ITS launch boundary — segment_plan put
        # it first in its segment, and merging that segment into an
        # earlier one would slide stages in front of it. Batched operand
        # bytes (the (batch, 8) placeholders) already ride `nbytes`.
        barrier = any(isinstance(st, BatchSelStage) and st.barrier
                      for st in stages)
        if out and out[-1][0] == "segment" and not barrier:
            u_scat = cur_scat | scat
            u_floor = max(cur_floor, floor)
            prev = out[-1]
            if (len(prev[1]) + len(stages) <= max_stages
                    and len(u_scat) <= scatter_max
                    and u_floor + len(u_scat) <= row_budget
                    and cur_bytes + nbytes <= operand_bytes):
                out[-1] = ("segment", prev[1] + stages, prev[2] + arrays)
                out_attr[-1] = out_attr[-1] + src
                cur_scat, cur_floor = u_scat, u_floor
                cur_bytes += nbytes
                continue
        out.append(("segment", stages, arrays))
        out_attr.append(src)
        cur_scat, cur_floor, cur_bytes = set(scat), floor, nbytes
    if attr is not None:
        attr.extend(out_attr)
    return out


def sweep_enabled() -> bool:
    """QUEST_SWEEP_FUSION knob: '1' (default) runs sweep fusion behind
    every fused-engine planner; '0' executes the raw segment plan.
    Keyed in the registry, so every compiled-program cache key carries
    it (env.engine_mode_key; flip-audited in tests/test_lint.py)."""
    from quest_tpu.env import knob_value
    return knob_value("QUEST_SWEEP_FUSION")


def maybe_sweep(parts, n: int):
    """sweep_plan honoring the QUEST_SWEEP_FUSION knob — the engines'
    entry point (stats consumers call sweep_plan/sweep_stats)."""
    if not sweep_enabled():
        return list(parts)
    return sweep_plan(parts, n)


def sweep_stats(parts) -> dict:
    """CPU-assertable sweep statistics of a (possibly swept) part list:
    every part — kernel sweep or XLA passthrough — is one full-state
    HBM pass per application, so `hbm_sweeps` is THE fused-engine
    memory-traffic metric (Circuit.plan_stats reports it next to the
    per-stage pass counts it undercuts)."""
    segs = [p for p in parts if p[0] == "segment"]
    return {
        "hbm_sweeps": len(parts),
        "kernel_sweeps": len(segs),
        "xla_passthroughs": len(parts) - len(segs),
        "sweep_stages": [len(p[1]) for p in segs],
    }


def batched_stats(parts, batch: int, bucket: int = None) -> dict:
    """CPU-assertable batched-plan statistics of a (swept) part list:
    every state in the bucket rides every sweep of the SAME part list,
    so `hbm_sweeps` (launches per application) does NOT scale with the
    batch — the whole point of the batched engine: a B-shot workload
    pays the unbatched plan's launch count once, with B states streamed
    back-to-back per launch (`states_per_sweep`). Surfaced through
    Circuit.plan_stats()["batched"] and trajectories.plan_stats; the
    B-independence golden lives in scripts/check_batch_golden.py."""
    sw = sweep_stats(parts)
    bucket = int(batch) if bucket is None else int(bucket)
    return {
        "batch": int(batch),
        "bucket": bucket,
        "states_per_sweep": bucket,
        "hbm_sweeps": sw["hbm_sweeps"],
        "kernel_sweeps": sw["kernel_sweeps"],
        "batched_stages": sum(
            1 for p in parts if p[0] == "segment"
            for st in p[1] if isinstance(st, BatchSelStage)),
    }


def sweep_steps(stages, n: int, batch: int = 1) -> int:
    """Grid steps one compiled sweep walks (blocks per state x batch)
    — from segment_geometry, the SAME resolution compile_segment sizes
    the kernel with, so the CPU-side schedule numbers below cannot
    drift from the lowered program."""
    geo = segment_geometry(stages, n)
    steps = 1
    for (lo, w) in geo.gaps:
        steps *= 1 << w
    return steps * int(batch)


def pipeline_stats(parts, n: int, batch: int = 1) -> dict:
    """CPU-assertable schedule of the decoupled sweep pipeline over a
    (swept) part list — pipeline_in_slots / pipeline_out_slots /
    pipeline_overlap_steps, the plan_stats()['fused'] keys
    scripts/check_sweep_golden.py gates without a chip.

    `pipeline_overlap_steps` is the MINIMUM read-ahead depth across the
    plan's kernel sweeps: steps the HBM read stream runs ahead of
    compute (in_slots - 1, clamped by the sweep's step count — a
    single-block sweep has nothing to read ahead). >= 1 on the
    headline plan means every launch overlaps the next block's DMA
    under the current block's stage loop.

    Returns {} when the decoupled pipeline is not the active schedule
    (QUEST_FUSED_PIPELINE=0 or the grid driver) — the knob-off fused
    record stays bit-for-bit the legacy one."""
    if not decoupled_active():
        return {}
    overlaps = []
    for p in parts:
        if p[0] != "segment":
            continue
        steps = sweep_steps(p[1], n, batch)
        overlaps.append(min(PIPELINE_IN_SLOTS, steps) - 1)
    return {
        "pipeline_in_slots": PIPELINE_IN_SLOTS,
        "pipeline_out_slots": PIPELINE_OUT_SLOTS,
        "pipeline_overlap_steps": min(overlaps) if overlaps else 0,
    }


def fused_record(parts, swept, n: int) -> dict:
    """The plan IR's 'fused' record — the fused engine's CPU-assertable
    geometry in ONE home (quest_tpu/plan.py builds it, Circuit.plan_stats
    re-emits it bit-for-bit): segment/passthrough counts and stage mix
    from the RAW segment plan `parts`, HBM sweep counts from the SWEPT
    plan, plus the decoupled pipeline's slot schedule
    (scripts/check_sweep_golden.py gates these keys)."""
    segs = sum(1 for p in parts if p[0] == "segment")
    sw = sweep_stats(swept)
    rec = {
        "kernel_segments": segs,
        "xla_passthroughs": len(parts) - segs,
        "full_state_passes": len(parts),
        "stages": sum(len(p[1]) for p in parts if p[0] == "segment"),
        "sweeps_enabled": sweep_enabled(),
        "hbm_sweeps": sw["hbm_sweeps"],
        "sweep_stages": sw["sweep_stages"],
    }
    rec.update(pipeline_stats(swept, n))
    return rec


def sweep_vmem_bytes(stages, arrays, n: int, batch: int = 1) -> dict:
    """CPU-assertable VMEM residency of ONE compiled sweep launch:
    slot buffers (the in/out rings of the decoupled pipeline, or the
    legacy NBUF in-place slots) + whole-array operand residency. The
    accounting behind the sweep budgets: `total_bytes <= budget_bytes`
    must hold for every plannable geometry (unit-tested over
    adversarial geometries in tests/test_sweeps.py), which is what
    lets sweep_plan merge on byte budgets instead of compiling to
    find out."""
    geo = segment_geometry(stages, n)
    steps = sweep_steps(stages, n, batch)
    block_bytes = 2 * geo.rows_eff * LANES * 4          # f32 planes
    if decoupled_active():
        slots = (min(PIPELINE_IN_SLOTS, steps)
                 + min(PIPELINE_OUT_SLOTS, steps))
    elif _driver_override() == "pipelined":
        slots = min(NBUF, steps)
    else:
        slots = 2                # the grid driver's double buffering
    operand_bytes = sum(int(a.nbytes) for a in arrays)
    return {
        "block_bytes": block_bytes,
        "slots": slots,
        "slot_bytes": slots * block_bytes,
        "operand_bytes": operand_bytes,
        "total_bytes": slots * block_bytes + operand_bytes,
        "budget_bytes": VMEM_LIMIT_BYTES,
    }


# ---------------------------------------------------------------------------
# kernel
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class _Geometry:
    """Block/row geometry of one compiled segment."""
    n: int
    scat: Tuple[int, ...]       # scattered GLOBAL row bits, descending
    inner_bits: int
    gaps: Tuple[Tuple[int, int], ...]  # grid dims as (lo_bit, width_bits),
    # outermost first — one per gap above/between scattered axes plus the
    # gap between the lowest scattered bit and the inner rows

    @property
    def rows_eff(self) -> int:
        return 1 << (len(self.scat) + self.inner_bits)

    def view_dims(self):
        """Row-space view dims (outer->inner) and the block-shape entry
        per dim (1 for grid axes, full extent otherwise)."""
        dims, blocks = [], []
        for (lo, width) in self.gaps[:-1]:
            dims.append(1 << width)
            blocks.append(1)
            dims.append(2)
            blocks.append(2)
        lo, width = self.gaps[-1]
        dims.append(1 << width)
        blocks.append(1)
        dims.append(1 << self.inner_bits)
        blocks.append(1 << self.inner_bits)
        return tuple(dims), tuple(blocks)


def _geometry(n: int, scat_bits, rows_eff_bits: int) -> _Geometry:
    total_row_bits = n - LANE_QUBITS
    scat = tuple(sorted(scat_bits, reverse=True))
    h = len(scat)
    inner_bits = min(rows_eff_bits - h,
                     scat[-1] if scat else total_row_bits,
                     total_row_bits)
    # grid dims: the bit gaps (top .. scat[0]), (scat[a] .. scat[a+1]),
    # ..., (scat[-1] .. inner) — possibly zero-width (size-1 grid dims)
    gaps = []
    hi = total_row_bits
    for s in scat:
        gaps.append((s + 1, hi - s - 1))
        hi = s
    gaps.append((inner_bits, hi - inner_bits))
    return _Geometry(n, scat, inner_bits, tuple(gaps))


def _row_ids(geo: _Geometry, pids):
    """(rows_eff, 1) int32 GLOBAL row id of each block row."""
    base = 0
    for (lo, _), pid in zip(geo.gaps, pids):
        base = base + pid * (1 << lo)
    j = jax.lax.broadcasted_iota(jnp.int32, (geo.rows_eff, 1), 0)
    ids = base + (j & ((1 << geo.inner_bits) - 1))
    h = len(geo.scat)
    for a, s in enumerate(geo.scat):
        bit = (j >> (geo.inner_bits + h - 1 - a)) & 1
        ids = ids + (bit << s)
    return ids


def _lane_iota():
    return jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)


def _mask_of(row_ids, lane_preds, row_preds):
    mask = None
    if lane_preds:
        ids = _lane_iota()
        for bit, want in lane_preds:
            m = ((ids >> bit) & 1) == want
            mask = m if mask is None else (mask & m)
    if row_preds:
        for bit, want in row_preds:
            m = ((row_ids >> bit) & 1) == want
            mask = m if mask is None else (mask & m)
    return mask


def _cdot(contract, re, im, gre, gim, real_only):
    """Complex 'contract' of state planes with operator planes, via the
    Gauss 3-multiplication identity (3 MXU passes instead of 4):
      t1 = Gre x_re, t2 = Gim x_im, t3 = (Gre+Gim)(x_re+x_im)
      out_re = t1 - t2, out_im = t3 - t1 - t2."""
    if real_only:
        return contract(gre, re), contract(gre, im)
    t1 = contract(gre, re)
    t2 = contract(gim, im)
    t3 = contract(gre + gim, re + im)
    return t1 - t2, t3 - t1 - t2


def _mxu_dot_general(a, b, dnums):
    """State-amplitude dot at the session precision knob.

    HIGHEST (default): one f32 dot = 6 bf16 MXU passes, ~3e-7 relative
    error — full f32, matches the reference's PRECISION=1 envelope.
    HIGH: the double-bf16 3-pass scheme (a = a_hi + a_lo split by
    integer mantissa masking, keep the three highest-order products,
    f32 accumulation) — HALF the MXU passes of HIGHEST at ~2.3e-5
    relative error per 128-dot (measured ON CHIP against an f64
    oracle; docs/PRECISION.md). Mosaic does not
    lower Precision.HIGH, so the split is done explicitly here; XLA's
    own bf16_3x does the same thing on the banded/per-gate paths.
    DEFAULT: one bf16 pass, ~1e-3 — exposed but not recommended."""
    p = precision.matmul_precision()
    f32 = jnp.float32
    if p == jax.lax.Precision.HIGH:
        # Two hard-won ON-CHIP lessons in this scheme (both invisible to
        # interpret mode, caught by test_high_precision_tier_on_chip):
        #   1. operands must STAY f32 — explicit bfloat16 inputs make
        #      Mosaic accumulate the dot in bf16 as well, and a 128-term
        #      bf16 accumulator costs ~sqrt(128)*2^-8 ~ 4e-2 relative
        #      (measured 4.3e-2). A DEFAULT-precision f32 dot truncates
        #      the INPUTS to bf16 in the MXU but accumulates f32.
        #   2. the hi part is derived via integer mantissa masking, not
        #      x.astype(bf16).astype(f32), which Mosaic folds to the
        #      identity — zeroing the residual and collapsing the scheme
        #      to one plain bf16 pass (measured 9.3e-3).
        # hi is exactly bf16-representable so its truncation is lossless;
        # the residual rounds to bf16 at the MXU input, keeping ~16
        # mantissa bits overall (~1e-5 per 128-dot vs the f64 oracle).
        def split(x):
            xi = jax.lax.bitcast_convert_type(x, jnp.int32)
            hi = jax.lax.bitcast_convert_type(
                xi & jnp.int32(-65536), f32)       # 0xFFFF0000
            return hi, x - hi

        ah, al = split(a)
        bh, bl = split(b)

        def mm(x, y):
            return jax.lax.dot_general(
                x, y, dnums, preferred_element_type=f32,
                precision=jax.lax.Precision.DEFAULT)
        return mm(ah, bh) + mm(ah, bl) + mm(al, bh)
    return jax.lax.dot_general(a, b, dnums, preferred_element_type=f32,
                               precision=p)


_DN_2D = (((1,), (0,)), ((), ()))   # plain 2-D matmul dimension numbers


def _sublane_contract(d):
    """Contraction over the lowest log2(d) row bits of an (R, LANES)
    block, in the b0-SHAPED frame: (A, d, l) -> (A*l, d) via the cheap
    (0,2,1) tile transpose, one LARGE-M MXU dot x @ G^T, undo. The
    (d, A*l) small-m orientation costs ~30% of a whole pass in MXU
    inefficiency (measured 49.9 -> 38.5 ms/pass at 30q for b1).
    Expects gg PRE-TRANSPOSED (X @ G^T form, packed host-side).
    Used by the b1-op PairStage path (Kraus superoperators)."""
    def contract(gg, x):
        rows = x.size // LANES
        a = rows // d
        xt = (x.reshape(a, d, LANES).transpose(0, 2, 1)
              .reshape(a * LANES, d))
        out = _mxu_dot_general(xt, gg, _DN_2D)
        return (out.reshape(a, LANES, d).transpose(0, 2, 1)
                .reshape(x.shape))
    return contract


def _framed_cdot(to_frame, from_frame, re, im, gre, gim, real_only,
                 right=False):
    """Hoist the contraction frame change OUT of the Gauss trick: _cdot
    invokes its contraction three times (t1, t2, t3), so a
    frame-changing contract would pay its relayouts per invocation.
    One frame change in, three plain MXU dots, one frame change out.
    right=True contracts as X @ G (the caller passes G pre-transposed)
    — the large-m orientation the MXU wants."""
    fre, fim = to_frame(re), to_frame(im)

    if right:
        def contract(gg, xt):
            return _mxu_dot_general(xt, gg, _DN_2D)
    else:
        def contract(gg, xt):
            return _mxu_dot_general(gg, xt, _DN_2D)

    nre, nim = _cdot(contract, fre, fim, gre, gim, real_only)
    return from_frame(nre), from_frame(nim)


def _apply_mat_stage(re, im, st: MatStage, gref, geo: _Geometry, row_ids):
    g = gref[...]
    gre, gim = g[0], g[1]
    rows = geo.rows_eff

    if st.kind == "b0":
        def contract(gg, x):     # x (rows, LANES) @ G^T (LANES, LANES)
            return _mxu_dot_general(x, gg, _DN_2D)
        nre, nim = _cdot(contract, re, im, gre, gim, st.real_only)
    elif st.kind == "b1":
        # contract in the b0-SHAPED frame (large-m dot (a*l, d) @ G^T):
        # the (d, a*l) orientation costs ~30% of a whole pass in MXU
        # inefficiency (measured 49.9 -> 38.5 ms/pass at 30q — the
        # lane<->sublane tile transpose is cheap, the small-m dot is not)
        d = st.dim
        a = rows // d

        def to_frame(x):
            return (x.reshape(a, d, LANES).transpose(0, 2, 1)
                    .reshape(a * LANES, d))

        def from_frame(x):
            return (x.reshape(a, LANES, d).transpose(0, 2, 1)
                    .reshape(rows, LANES))
        nre, nim = _framed_cdot(to_frame, from_frame, re, im,
                                gre, gim, st.real_only, right=True)
    elif st.kind == "scb":
        # composed high-band operator: ONE dot over the merged scattered
        # axes (they are adjacent row dims of the block — the scat tuple
        # is bit-descending, so the merged index's MSB is the band's top
        # qubit, matching the operator's index convention).
        d = st.dim
        w = d.bit_length() - 1
        p = geo.scat.index(st.bit + w - 1)
        assert geo.scat[p:p + w] == tuple(
            range(st.bit + w - 1, st.bit - 1, -1)), \
            (geo.scat, st.bit, w)
        pre = 1 << p
        post = rows >> (p + w)

        if d == LANES:
            # full-width band: contract in the b0-shaped LARGE-M frame,
            # reached by TWO cheap-class transposes — a row-only swap
            # then a sublane<->lane tile swap. The direct (d, rest*l)
            # small-m dot measured 46.3 ms/pass at 30q and the
            # single-permutation mirror 61.4 (the fused lane<->leading
            # transpose is the expensive kind); the two-step route runs
            # at the 34.0 ms pass baseline. Operand arrives
            # pre-transposed (X @ G^T form).
            def to_frame(x):
                v = x.reshape(pre, d, post, LANES)
                v = v.transpose(0, 2, 1, 3)    # row-only swap
                v = v.transpose(0, 1, 3, 2)    # sublane<->lane tile swap
                return v.reshape(pre * post * LANES, d)

            def from_frame(x):
                v = x.reshape(pre, post, LANES, d)
                v = v.transpose(0, 1, 3, 2)
                v = v.transpose(0, 2, 1, 3)
                return v.reshape(rows, LANES)
            nre, nim = _framed_cdot(to_frame, from_frame, re, im,
                                    gre, gim, st.real_only, right=True)
        else:
            # narrow band: the left-dot is already cheap (cost scales
            # with d) and the mirror's d<->128 tile swaps are NOT
            # (measured: 538 ms/application on a d=8 stage, padding-
            # heavy relayouts); keep the transpose-free frame
            if pre == 1:
                def to_frame(x):
                    return x.reshape(d, post * LANES)

                def from_frame(x):
                    return x.reshape(rows, LANES)
            else:
                def to_frame(x):
                    return (x.reshape(pre, d, post * LANES)
                            .transpose(1, 0, 2).reshape(d, -1))

                def from_frame(x):
                    return (x.reshape(d, pre, post * LANES)
                            .transpose(1, 0, 2).reshape(rows, LANES))
            nre, nim = _framed_cdot(to_frame, from_frame, re, im,
                                    gre, gim, st.real_only)
    else:                        # 'sc': butterfly on one scattered axis
        a = geo.scat.index(st.bit)
        pre = 1 << a
        post = (rows >> (a + 1)) * LANES

        def halves(x):
            v = x.reshape(pre, 2, post)
            return v[:, 0, :], v[:, 1, :]

        r0, r1 = halves(re)
        i0, i1 = halves(im)

        def cmul(cr, ci, xr, xi):
            return cr * xr - ci * xi, cr * xi + ci * xr

        a0r, a0i = cmul(gre[0, 0], gim[0, 0], r0, i0)
        b0r, b0i = cmul(gre[0, 1], gim[0, 1], r1, i1)
        a1r, a1i = cmul(gre[1, 0], gim[1, 0], r0, i0)
        b1r, b1i = cmul(gre[1, 1], gim[1, 1], r1, i1)
        nre = jnp.stack([a0r + b0r, a1r + b1r], axis=1).reshape(rows, LANES)
        nim = jnp.stack([a0i + b0i, a1i + b1i], axis=1).reshape(rows, LANES)

    mask = _mask_of(row_ids, st.lane_preds, st.row_preds)
    if mask is not None:
        nre = jnp.where(mask, nre, re)
        nim = jnp.where(mask, nim, im)
    return nre, nim


def _row_halves(lo, hi):
    """Recombine a row mask split at bit 15 (each half exact in f32)."""
    return lo.astype(jnp.int32) | (hi.astype(jnp.int32) << 15)


def _xor_fold(x, top_shift):
    """Parity bit of each element's set bits: XOR-fold down to bit 0."""
    s = top_shift
    while s >= 1:
        x = x ^ (x >> s)
        s //= 2
    return x & 1


def _apply_phase_stage(re, im, st: PhaseStage, gref, row_ids):
    # (1, 8) operand: [tre, tim, lane_mask, lane_want,
    #                  row_mask_lo, row_mask_hi, row_want_lo, row_want_hi]
    # — predicates are DATA, so every phase stage shares one kernel
    g = gref[...]
    tre, tim = g[0, 0], g[0, 1]
    lm = g[0, 2].astype(jnp.int32)
    lw = g[0, 3].astype(jnp.int32)
    rm = _row_halves(g[0, 4], g[0, 5])
    rw = _row_halves(g[0, 6], g[0, 7])
    mask = (((_lane_iota() & lm) == lw)
            & ((row_ids & rm) == rw))   # empty masks: all-true
    nre = re * tre - im * tim
    nim = re * tim + im * tre
    return jnp.where(mask, nre, re), jnp.where(mask, nim, im)


def _apply_parity_stage(re, im, st: ParityStage, gref, row_ids):
    # (1, 8) operand: [cos, sin, lane_mask, row_mask_lo, row_mask_hi,
    #                  0, 0, 0] of the half angle and target-bit masks
    g = gref[...]
    lm = g[0, 2].astype(jnp.int32)
    rm = _row_halves(g[0, 3], g[0, 4])
    par = (_xor_fold(_lane_iota() & lm, 4)
           ^ _xor_fold(row_ids & rm, 16))
    sign = 1.0 - 2.0 * par.astype(jnp.float32)
    cosf = g[0, 0]
    sinf = g[0, 1] * sign
    nre = re * cosf + im * sinf
    nim = im * cosf - re * sinf
    return nre, nim


def _apply_multiphase_stage(re, im, st: MultiPhaseStage, gref, row_ids):
    # (m, 8) operand rows: [angle, lane_mask, row_mask_lo, row_mask_hi,
    # 0, 0, 0, 0]; st.forms[r] picks the static interpretation. The
    # group's total angle accumulates per element, then ONE cos/sin +
    # complex multiply applies the whole group (vs one trig blend per
    # phase when each rides its own Phase/ParityStage).
    g = gref[...]
    lane = _lane_iota()
    tot = None
    for r, form in enumerate(st.forms):
        ang = g[r, 0]
        lm = g[r, 1].astype(jnp.int32)
        rm = _row_halves(g[r, 2], g[r, 3])
        if form == "a":
            match = ((lane & lm) == lm) & ((row_ids & rm) == rm)
            contrib = jnp.where(match, ang, 0.0)
        else:
            par = _xor_fold(lane & lm, 4) ^ _xor_fold(row_ids & rm, 16)
            sign = 1.0 - 2.0 * par.astype(jnp.float32)
            contrib = ang * sign
        tot = contrib if tot is None else tot + contrib
    cosf = jnp.cos(tot)
    sinf = jnp.sin(tot)
    nre = re * cosf - im * sinf
    nim = re * sinf + im * cosf
    return nre, nim


def _bit_of(q, row_ids):
    """(broadcastable) value of bit `q` of each amplitude's global index."""
    if q < LANE_QUBITS:
        return (_lane_iota() >> q) & 1
    return (row_ids >> (q - LANE_QUBITS)) & 1


def _apply_diagvec_stage(re, im, st: DiagVecStage, gref, row_ids):
    g = gref[...]               # (2, 2^k) re/im entry table
    k = len(st.targets)
    fre = g[0, 0].reshape(1, 1)
    fim = g[1, 0].reshape(1, 1)
    for b in range(1, 1 << k):
        sel = None
        for j, q in enumerate(st.targets):
            m = _bit_of(q, row_ids) == ((b >> j) & 1)
            sel = m if sel is None else (sel & m)
        fre = jnp.where(sel, g[0, b], fre)
        fim = jnp.where(sel, g[1, b], fim)
    nre = re * fre - im * fim
    nim = re * fim + im * fre
    mask = _mask_of(row_ids, st.lane_preds, st.row_preds)
    if mask is not None:
        nre = jnp.where(mask, nre, re)
        nim = jnp.where(mask, nim, im)
    return nre, nim


def _batchsel_embed(v, bit, width, transpose=False):
    """Embed one state's 2x2 (8 scalars [g00re, g00im, g01re, g01im,
    g10re, g10im, g11re, g11im]) at `bit` of a 2^width space, built
    IN-KERNEL from iota masks: emb[r, c] = G[r_bit, c_bit] where the
    non-target bits of r and c agree, else 0. Keeps BatchSelStage
    operands at (batch, 8) bytes for lane/sublane qubits — a host-side
    embedding would ship batch x 128 KiB to VMEM at d=128.
    transpose=True returns G^T (the X @ G^T frame of the dot paths)."""
    d = 1 << width
    ri = jax.lax.broadcasted_iota(jnp.int32, (d, d), 0)
    ci = jax.lax.broadcasted_iota(jnp.int32, (d, d), 1)
    other = ((ri ^ ci) & jnp.int32((d - 1) & ~(1 << bit))) == 0
    sel = other.astype(jnp.float32)
    br = ((ri >> bit) & 1).astype(jnp.float32)
    bc = ((ci >> bit) & 1).astype(jnp.float32)
    if transpose:
        br, bc = bc, br

    def emb(v00, v01, v10, v11):
        return sel * ((1.0 - br) * (1.0 - bc) * v00
                      + (1.0 - br) * bc * v01
                      + br * (1.0 - bc) * v10
                      + br * bc * v11)
    return (emb(v[0], v[2], v[4], v[6]), emb(v[1], v[3], v[5], v[7]))


def _apply_batchsel_stage(re, im, st: BatchSelStage, gref,
                          geo: _Geometry, row_ids, bsel):
    """Apply the CURRENT state's row of a (batch, 8) per-state operand
    table: the one-hot-selected (renormalized) Kraus branch of a batched
    trajectory channel, applied inside the sweep. `bsel` is the i32
    batch index (the leading grid dimension / the pipelined driver's
    unraveled step quotient)."""
    g = pl.load(gref, (pl.ds(bsel, 1), slice(None)))   # (1, 8)
    v = [g[0, j] for j in range(8)]
    q = st.qubit
    rows = geo.rows_eff

    if q >= SUBLANE_TOP:
        # scattered axis: elementwise butterfly on per-state scalars
        # (the 'sc' MatStage math with traced matrix entries)
        a = geo.scat.index(q - LANE_QUBITS)
        pre = 1 << a
        post = (rows >> (a + 1)) * LANES

        def halves(x):
            t = x.reshape(pre, 2, post)
            return t[:, 0, :], t[:, 1, :]

        r0, r1 = halves(re)
        i0, i1 = halves(im)

        def cmul(cr, ci_, xr, xi):
            return cr * xr - ci_ * xi, cr * xi + ci_ * xr

        a0r, a0i = cmul(v[0], v[1], r0, i0)
        b0r, b0i = cmul(v[2], v[3], r1, i1)
        a1r, a1i = cmul(v[4], v[5], r0, i0)
        b1r, b1i = cmul(v[6], v[7], r1, i1)
        nre = jnp.stack([a0r + b0r, a1r + b1r], axis=1).reshape(rows, LANES)
        nim = jnp.stack([a0i + b0i, a1i + b1i], axis=1).reshape(rows, LANES)
        return nre, nim

    if q >= LANE_QUBITS:
        # sublane bit j: contract the lowest j+1 row bits in the b1
        # large-M frame (X @ G^T; the embedded operator is built
        # pre-transposed so the kernel pays no per-block transpose)
        j = q - LANE_QUBITS
        d = 1 << (j + 1)
        gre, gim = _batchsel_embed(v, j, j + 1, transpose=True)
        a = rows // d

        def to_frame(x):
            return (x.reshape(a, d, LANES).transpose(0, 2, 1)
                    .reshape(a * LANES, d))

        def from_frame(x):
            return (x.reshape(a, LANES, d).transpose(0, 2, 1)
                    .reshape(rows, LANES))
        return _framed_cdot(to_frame, from_frame, re, im, gre, gim,
                            False, right=True)

    # lane bit: embedded 128x128, one b0-style dot X @ G^T
    gre, gim = _batchsel_embed(v, q, LANE_QUBITS, transpose=True)

    def contract(gg, x):
        return _mxu_dot_general(x, gg, _DN_2D)
    return _cdot(contract, re, im, gre, gim, False)


def _apply_pair_stage(re, im, st: PairStage, gref, geo: _Geometry,
                      row_ids):
    g = gref[...]                 # (2, 4, D, D) block operators
    rows = geo.rows_eff

    if st.op_kind == "sc":
        # both qubits on scattered axes: 4 input slices, 16 scalar cmuls
        a_sl = geo.scat.index(st.sliced_bit)
        a_op = geo.scat.index(st.op_bit)
        ax1, ax2 = sorted((a_sl, a_op))
        p1 = 1 << ax1
        p2 = 1 << (ax2 - ax1 - 1)
        p3 = (rows >> (ax2 + 1)) * LANES

        def split(x):
            v = x.reshape(p1, 2, p2, 2, p3)
            return {(b1, b2): v[:, b1, :, b2, :]
                    for b1 in range(2) for b2 in range(2)}

        def bits(b1, b2):       # -> (sliced value, op value)
            return (b1, b2) if a_sl == ax1 else (b2, b1)

        xr, xi = split(re), split(im)
        outr, outi = {}, {}
        for b1 in range(2):
            for b2 in range(2):
                r, ao = bits(b1, b2)
                nr = ni = None
                for c in range(2):
                    for ai in range(2):
                        gre = g[0, r * 2 + c, ao, ai]
                        sb1, sb2 = (c, ai) if a_sl == ax1 else (ai, c)
                        if st.real_only:
                            tr = gre * xr[(sb1, sb2)]
                            ti = gre * xi[(sb1, sb2)]
                        else:
                            gim = g[1, r * 2 + c, ao, ai]
                            tr = gre * xr[(sb1, sb2)] - gim * xi[(sb1, sb2)]
                            ti = gre * xi[(sb1, sb2)] + gim * xr[(sb1, sb2)]
                        nr = tr if nr is None else nr + tr
                        ni = ti if ni is None else ni + ti
                outr[(b1, b2)], outi[(b1, b2)] = nr, ni

        def join(d):
            rows_of = [jnp.stack([d[(b1, 0)], d[(b1, 1)]], axis=2)
                       for b1 in range(2)]
            return jnp.stack(rows_of, axis=1).reshape(rows, LANES)
        nre, nim = join(outr), join(outi)
    else:
        # sliced qubit halves select embedded 128-dim block operators
        if st.sliced_kind == "scat":
            a = geo.scat.index(st.sliced_bit)
            pre = 1 << a
            post = (rows >> (a + 1)) * LANES
        else:                     # sublane bit (op side is the lane space)
            j = st.sliced_bit
            pre = rows >> (j + 1)
            post = (1 << j) * LANES

        def halves(x):
            v = x.reshape(pre, 2, post)
            return v[:, 0, :], v[:, 1, :]

        def rejoin(x0, x1):
            return jnp.stack([x0, x1], axis=1).reshape(rows, LANES)

        if st.op_kind == "lane":
            def block(gg, x):     # g packed pre-transposed: X @ G^T
                return _mxu_dot_general(
                    x.reshape(-1, LANES), gg, _DN_2D).reshape(x.shape)
        else:                     # 'b1': sublane-axis contraction
            block = _sublane_contract(LANES)

        xr, xi = halves(re), halves(im)
        outs = []
        for r in range(2):
            nr = ni = None
            for c in range(2):
                tr, ti = _cdot(block, xr[c], xi[c], g[0, r * 2 + c],
                               g[1, r * 2 + c], st.real_only)
                nr = tr if nr is None else nr + tr
                ni = ti if ni is None else ni + ti
            outs.append((nr, ni))
        nre = rejoin(outs[0][0], outs[1][0])
        nim = rejoin(outs[0][1], outs[1][1])

    mask = _mask_of(row_ids, st.lane_preds, st.row_preds)
    if mask is not None:
        nre = jnp.where(mask, nre, re)
        nim = jnp.where(mask, nim, im)
    return nre, nim


def _apply_stages(re, im, stages, mat_refs, geo: _Geometry, row_ids,
                  bsel=None):
    """The stage chain shared by both kernel drivers. `bsel` is the i32
    batch index under the batched grid (None: unbatched — BatchSelStage
    operands then hold a single row)."""
    for st, ref in zip(stages, mat_refs):
        if isinstance(st, MatStage):
            re, im = _apply_mat_stage(re, im, st, ref, geo, row_ids)
        elif isinstance(st, PairStage):
            re, im = _apply_pair_stage(re, im, st, ref, geo, row_ids)
        elif isinstance(st, BatchSelStage):
            re, im = _apply_batchsel_stage(
                re, im, st, ref, geo, row_ids,
                jnp.int32(0) if bsel is None else bsel)
        elif isinstance(st, PhaseStage):
            re, im = _apply_phase_stage(re, im, st, ref, row_ids)
        elif isinstance(st, MultiPhaseStage):
            re, im = _apply_multiphase_stage(re, im, st, ref, row_ids)
        elif isinstance(st, DiagVecStage):
            re, im = _apply_diagvec_stage(re, im, st, ref, row_ids)
        else:
            re, im = _apply_parity_stage(re, im, st, ref, row_ids)
    return re, im


def _segment_kernel(in_ref, *rest, stages, geo: _Geometry,
                    batched: bool = False):
    mat_refs = rest[:len(stages)]   # one operand ref per stage
    out_ref = rest[len(stages)]
    # the batch rides as the OUTERMOST grid dimension: program_id(0) is
    # the i32 state index (dtype-pinned by Pallas itself), row grids
    # shift up by one
    off = 1 if batched else 0
    bsel = pl.program_id(0) if batched else None
    pids = [pl.program_id(off + d) for d in range(len(geo.gaps))]
    row_ids = _row_ids(geo, pids)
    blk = in_ref[...].reshape(2, geo.rows_eff, LANES)
    re = blk[0]
    im = blk[1]
    re, im = _apply_stages(re, im, stages, mat_refs, geo, row_ids, bsel)
    shape = out_ref.shape
    out_ref[...] = jnp.stack([re, im]).reshape(shape)


def _nbuf_override() -> int:
    """QUEST_FUSED_NBUF experiment knob: VMEM slots in the manually
    pipelined driver. Slot buffers are IN-PLACE (one buffer is DMA-in
    target, compute scratch and DMA-out source), which couples the two
    DMA directions — in(s+1) may only start once out(s+1-nbuf) drained —
    so nbuf=2 stalls a full out-DMA per step (measured 23.8 vs 20.5 ms
    on the 28q bench) and nbuf < 2 would wait on an out-DMA that has
    not started. nbuf=3 gives the drain a whole step of slack at 3
    block buffers of VMEM. Malformed/out-of-range values fall back to
    the default, loudly (same discipline as _rows_eff_override)."""
    from quest_tpu.env import KNOBS, knob_value
    try:
        return knob_value("QUEST_FUSED_NBUF")
    except ValueError as e:
        import sys
        print(f"[pallas_band] ignoring QUEST_FUSED_NBUF: {e}",
              file=sys.stderr)
        return KNOBS["QUEST_FUSED_NBUF"].default


NBUF = _nbuf_override()


def _step_index(grid, block_shape, batched):
    """idx_of(step) -> (index tuple, pids, batch id) for the manual
    slot drivers: the index tuple selecting step's block in the state
    view, derived from the BLOCK SHAPE exactly like the grid driver's
    index_map (block entry 1 = a grid axis taking the unraveled step
    id, anything else rides whole) — one layout convention, not two.
    A size-1 inner axis also has block 1; the default 0 indexes it,
    mirroring index_map's zip-shortest behavior. Batched: the step
    space is (nbatch, *grid) with the batch SLOWEST, so each state's
    blocks stream back-to-back — the quotient left after dividing out
    the row grid is the i32 batch index (the drivers pin their loop
    counters int32, so every derived pid stays 32-bit). Shared by the
    legacy in-place driver and the decoupled pipeline so the two
    schedules can never disagree about which block a step touches."""
    def idx_of(step):
        pids = []
        rem = step
        for g in reversed(grid):
            pids.append(rem % g)
            rem = rem // g
        pids = pids[::-1]
        b = rem                              # batch index (0 unbatched)
        it = iter(pids)
        idx = [pl.ds(b, 1)] if batched else []   # leading batch axis
        idx.append(slice(None))              # plane axis
        for blk in block_shape[1:-1]:        # row-view axes
            idx.append(pl.ds(next(it, 0), 1) if blk == 1
                       else slice(None))
        idx.append(slice(None))              # lane axis
        return tuple(idx), pids, b
    return idx_of


def _pipelined_kernel(in_hbm, *rest, stages, geo: _Geometry, grid,
                      block_shape, nbuf, nbatch=1, batched=None):
    """LEGACY manually pipelined segment driver (QUEST_FUSED_PIPELINE=0
    — the silicon A/B control): the state stays in HBM
    (memory_space=ANY); the kernel walks the same step space as the grid
    driver with `nbuf` IN-PLACE VMEM slot buffers — DMA step s+1 in and
    step s-1 out while the stage chain computes step s. In-place slots
    couple the two DMA directions: in(s+1) may only start once
    out(s+1-nbuf) drained from the same buffer (the serialization the
    decoupled driver below removes).

    Measured r4 (scripts/probe_stack.py, docs/KERNELS.md round-4
    findings): PARITY with the automatic BlockSpec pipeline on the
    bench step (79.7 vs 79.9 ms) and the best RCS 30q d20 number
    (2.097 vs 2.153 s) — the default driver on that margin. The hoped
    second win did NOT materialize: in-place slots halve block-buffer
    VMEM, but 2^14-row blocks still fail on ~96 MiB of chain-wide
    register-allocator spills (see PIPELINED_MAX_BLOCK_ROW_BITS), so
    the row-bit budget stays 13 on both drivers."""
    mat_refs = rest[:len(stages)]
    out_hbm = rest[len(stages)]
    if batched is None:          # legacy callers key batched-ness on B
        batched = nbatch > 1
    steps = int(np.prod(grid)) * nbatch
    nbuf = min(nbuf, steps)
    idx_of = _step_index(grid, block_shape, batched)
    slot_shape = (1, *block_shape) if batched else block_shape

    def body(scratch, in_sems, out_sems):
        def get_in(step, slot):
            idx, _, _ = idx_of(step)
            return pltpu.make_async_copy(
                in_hbm.at[idx], scratch.at[slot], in_sems.at[slot])

        def get_out(step, slot):
            idx, _, _ = idx_of(step)
            return pltpu.make_async_copy(
                scratch.at[slot], out_hbm.at[idx], out_sems.at[slot])

        get_in(0, 0).start()

        def step_body(s, _):
            # explicit i32 operands: under jax_enable_x64 a Python-int
            # operand traces as i64, and a mixed-dtype rem fails to
            # lower (interpret mode) or legalize (Mosaic)
            slot = jax.lax.rem(s, jnp.int32(nbuf))
            nslot = jax.lax.rem(s + 1, jnp.int32(nbuf))

            @pl.when(s + 1 < steps)
            def _():
                # the next slot is free once ITS previous out-DMA landed
                @pl.when(s + 1 >= nbuf)
                def _():
                    get_out(s + 1 - nbuf, nslot).wait()
                get_in(s + 1, nslot).start()

            get_in(s, slot).wait()
            _, pids, b = idx_of(s)
            row_ids = _row_ids(geo, pids)
            blk = scratch[slot].reshape(2, geo.rows_eff, LANES)
            re = blk[0]
            im = blk[1]
            re, im = _apply_stages(re, im, stages, mat_refs, geo, row_ids,
                                   b if batched else None)
            scratch[slot] = jnp.stack([re, im]).reshape(slot_shape)
            get_out(s, slot).start()
            return jnp.int32(0)

        # int32 bounds pin the loop counter (and everything derived from
        # it in idx_of) to 32 bits: under jax_enable_x64 Python-int
        # bounds trace as int64, which Mosaic cannot lower (the x64 test
        # suite's on-chip smoke run hits exactly this)
        jax.lax.fori_loop(jnp.int32(0), jnp.int32(steps), step_body,
                          jnp.int32(0))
        for j in range(nbuf):                # drain the tail out-DMAs
            s = steps - nbuf + j
            if s >= 0:
                get_out(s, s % nbuf).wait()

    pl.run_scoped(
        body,
        scratch=pltpu.VMEM((nbuf, *slot_shape), jnp.float32),
        in_sems=pltpu.SemaphoreType.DMA((nbuf,)),
        out_sems=pltpu.SemaphoreType.DMA((nbuf,)),
    )


def _decoupled_kernel(in_hbm, *rest, stages, geo: _Geometry, grid,
                      block_shape, in_slots, out_slots, nbatch=1,
                      batched=None):
    """DECOUPLED multi-buffer sweep pipeline (QUEST_FUSED_PIPELINE=1,
    the default): separate in-slot and out-slot rings, each with its
    own DMA semaphore chain, so the three streams of a sweep —

        HBM read  ->  per-stage MXU/VPU compute  ->  HBM write

    each run a full step ahead of the next. The legacy driver's
    in-place slots made one buffer serve as DMA-in target, compute
    scratch AND DMA-out source, which serializes the two DMA
    directions: in(s+1) had to wait for out(s+1-nbuf) to drain the
    same buffer — a stall of a whole out-DMA per step at nbuf=2
    (measured 23.8 vs 20.5 ms on the 28q bench) and a whole extra
    block of slack-buffer VMEM at nbuf=3. Here the read ring refills
    the moment compute has consumed a slot, regardless of where the
    write stream is:

        warm-up   in(0..in_slots-1) start          read ring fills
        step s    wait in(s)                       [in sems]
                  stage chain on in-slot s%I       compute
                  wait out(s-out_slots) drained    [out sems]
                  write out-slot s%O; out(s) start
                  in(s+in_slots) start             ring refill
        drain     wait the last out_slots out-DMAs

    During the stage loop of step s the DMAs for blocks s+1..s+I-1
    (started by earlier iterations / the warm-up) and the write-backs
    of blocks s-O..s-1 are all in flight — stage-level overlap of the
    next block's DMA under the current block's compute, with neither
    DMA direction gating the other. The refill for step s+I starts
    only AFTER the stage chain (its in-slot holds the block compute is
    reading until then); with in_slots >= 2 the read stream still runs
    a full step ahead. VMEM cost: in_slots + out_slots block buffers
    (4 x 8 MiB at the 2^13-row cap) vs the legacy 3 — paid back out of
    the sweep operand budget (PIPELINE_SWEEP_OPERAND_BYTES), so the
    total stays inside the 100 MiB scoped limit; sweep_vmem_bytes is
    the CPU-assertable accounting.

    Bit-identity with the legacy driver holds by construction: the
    same _step_index walk, the same _apply_stages chain, the same
    float ops per block — only the buffer/semaphore schedule differs
    (pinned across the randomized sweep suite in tests/test_sweeps.py).

    The in/out waits sit inside jax.named_scope regions
    ('quest:dma_in_wait' / 'quest:dma_out_wait' / 'quest:stages') so a
    chip profile can attribute residual stall time to the read stream,
    the write stream or the stage chain directly
    (profiling.sweep_dma_report is the host-side split)."""
    mat_refs = rest[:len(stages)]
    out_hbm = rest[len(stages)]
    if batched is None:
        batched = nbatch > 1
    steps = int(np.prod(grid)) * nbatch
    n_in = min(in_slots, steps)
    n_out = min(out_slots, steps)
    idx_of = _step_index(grid, block_shape, batched)
    slot_shape = (1, *block_shape) if batched else block_shape

    def body(in_scr, out_scr, in_sems, out_sems):
        def get_in(step, slot):
            idx, _, _ = idx_of(step)
            return pltpu.make_async_copy(
                in_hbm.at[idx], in_scr.at[slot], in_sems.at[slot])

        def get_out(step, slot):
            idx, _, _ = idx_of(step)
            return pltpu.make_async_copy(
                out_scr.at[slot], out_hbm.at[idx], out_sems.at[slot])

        for j in range(n_in):                # fill the read ring
            get_in(j, j).start()

        def step_body(s, _):
            # explicit i32 operands: under jax_enable_x64 a Python-int
            # operand traces as i64, and a mixed-dtype rem fails to
            # lower (interpret mode) or legalize (Mosaic)
            islot = jax.lax.rem(s, jnp.int32(n_in))
            oslot = jax.lax.rem(s, jnp.int32(n_out))
            with jax.named_scope("quest:dma_in_wait"):
                get_in(s, islot).wait()
            _, pids, b = idx_of(s)
            row_ids = _row_ids(geo, pids)
            blk = in_scr[islot].reshape(2, geo.rows_eff, LANES)
            re = blk[0]
            im = blk[1]
            with jax.named_scope("quest:stages"):
                re, im = _apply_stages(re, im, stages, mat_refs, geo,
                                       row_ids, b if batched else None)
            # the out slot is free once ITS previous occupant drained —
            # the only cross-stream ordering left, and it trails compute
            # by a whole out_slots steps
            @pl.when(s >= n_out)
            def _():
                with jax.named_scope("quest:dma_out_wait"):
                    get_out(s - n_out, oslot).wait()
            out_scr[oslot] = jnp.stack([re, im]).reshape(slot_shape)
            get_out(s, oslot).start()

            # refill the read ring: in-slot s%I was consumed by the
            # stage chain above, so block s+I may stream in now —
            # it will be in flight under the NEXT steps' stage loops
            @pl.when(s + n_in < steps)
            def _():
                get_in(s + n_in, islot).start()
            return jnp.int32(0)

        # int32 bounds pin the loop counter (and everything derived
        # from it in idx_of) to 32 bits — see _pipelined_kernel
        jax.lax.fori_loop(jnp.int32(0), jnp.int32(steps), step_body,
                          jnp.int32(0))
        for j in range(n_out):               # drain the tail out-DMAs
            s = steps - n_out + j
            if s >= 0:
                get_out(s, s % n_out).wait()

    pl.run_scoped(
        body,
        in_scr=pltpu.VMEM((n_in, *slot_shape), jnp.float32),
        out_scr=pltpu.VMEM((n_out, *slot_shape), jnp.float32),
        in_sems=pltpu.SemaphoreType.DMA((n_in,)),
        out_sems=pltpu.SemaphoreType.DMA((n_out,)),
    )


def _rows_eff_override():
    """QUEST_ROWS_EFF_BITS block-size experiment knob, parsed ONCE at
    import (mid-process changes are deliberately ignored: the value is
    not part of any compiled-program cache key, so honoring them would
    silently return stale kernels — sweep via subprocesses instead,
    like scripts' block experiments do). Malformed/out-of-range values
    fall back to the default, loudly."""
    from quest_tpu.env import knob_value
    try:
        v = knob_value("QUEST_ROWS_EFF_BITS")
    except ValueError as e:
        import sys
        print(f"[pallas_band] ignoring QUEST_ROWS_EFF_BITS: {e}",
              file=sys.stderr)
        return ROWS_EFF_BITS
    if v is None:
        return ROWS_EFF_BITS
    if v > max_block_row_bits():
        # upper bound depends on the device's VMEM — checkable only here,
        # not in the registry parser
        import sys
        print(f"[pallas_band] ignoring QUEST_ROWS_EFF_BITS={v} above "
              f"max_block_row_bits()={max_block_row_bits()}",
              file=sys.stderr)
        return ROWS_EFF_BITS
    return v


_ROWS_EFF_BITS_EFFECTIVE = None  # resolved lazily on first compile


_DRIVER_EFFECTIVE = None  # resolved once on first compile


def _driver_override() -> str:
    """QUEST_FUSED_DRIVER experiment knob: 'pipelined' (default) or
    'grid' (the automatic BlockSpec pipeline — kept for A/B probes and
    as a fallback). Resolved ONCE per process (like NBUF): compiled
    programs cache across engines without carrying the knob in every
    cache key, and flipping the env mid-process cannot hand back a
    program built with the other driver (ADVICE r4 item 2) — sweep via
    subprocesses like the block experiments."""
    global _DRIVER_EFFECTIVE
    if _DRIVER_EFFECTIVE is not None:
        return _DRIVER_EFFECTIVE
    from quest_tpu.env import KNOBS, knob_value
    try:
        v = knob_value("QUEST_FUSED_DRIVER")
    except ValueError as e:
        import sys
        print(f"[pallas_band] ignoring QUEST_FUSED_DRIVER: {e}",
              file=sys.stderr)
        v = KNOBS["QUEST_FUSED_DRIVER"].default
    _DRIVER_EFFECTIVE = v
    return v


def segment_geometry(stages: Sequence, n: int,
                     rows_eff_bits: int | None = None) -> _Geometry:
    """Block geometry of a compiled stage list — the rows_eff
    resolution + stage_requirements accounting compile_segment sizes
    its block from, factored out so the CPU-side schedule introspection
    (pipeline_stats, sweep_vmem_bytes) derives step counts and slot
    bytes from EXACTLY what the kernel will allocate, never a parallel
    re-derivation."""
    global _ROWS_EFF_BITS_EFFECTIVE
    if rows_eff_bits is None:
        if _ROWS_EFF_BITS_EFFECTIVE is None:
            _ROWS_EFF_BITS_EFFECTIVE = _rows_eff_override()
        rows_eff_bits = _ROWS_EFF_BITS_EFFECTIVE
    total_row_bits = n - LANE_QUBITS
    rows_eff_bits = min(rows_eff_bits, total_row_bits)
    # block geometry from the shared requirements accounting (the same
    # scat/floor contract sweep_plan merges under)
    scat_bits, b1_bits = stage_requirements(stages)
    rows_eff_bits = max(rows_eff_bits, b1_bits + len(scat_bits))
    return _geometry(n, scat_bits, rows_eff_bits)


def compile_segment(stages: Sequence, n: int,
                    rows_eff_bits: int | None = None,
                    interpret: bool = False, batch: int | None = None):
    """Build fn(amps, mat_arrays) -> amps applying `stages` in one kernel
    launch (the manually pipelined slot driver by default; the automatic
    grid pipeline via QUEST_FUSED_DRIVER=grid). batch=B (any B >= 1)
    adds a leading batch grid dimension: the launch streams B states
    through HBM back-to-back with the SAME stage list — one launch for
    the whole bucket instead of one per state — and apply takes/returns
    (B, 2, rows, 128) even at B=1 so callers keep one calling convention
    per bucket (docs/BATCHING.md). batch=None compiles the unbatched
    kernel over (2, rows, 128). Block geometry, VMEM residency and the
    stage chain are per-state and unchanged; only BatchSelStage operands
    carry a per-state axis."""
    geo = segment_geometry(stages, n, rows_eff_bits)
    dims, blocks = geo.view_dims()
    grid = tuple(1 << w for (lo, w) in geo.gaps)
    grid_axes = [i for i, b in enumerate(blocks) if b == 1]
    batched = batch is not None
    nbatch = batch if batched else 1

    def index_map(*ids):
        # batched: the leading grid id selects the state; row-axis
        # offsets shift one slot right for the batch view axis
        if batched:
            b, ids = ids[0], ids[1:]
            out = [b] + [0] * (len(dims) + 2)
            off = 2
        else:
            out = [0] * (len(dims) + 2)   # + plane axis, + lane axis
            off = 1
        for ax, i in zip(grid_axes, ids):
            out[off + ax] = i
        return tuple(out)

    block_shape = (2, *blocks, LANES)
    view_shape = (2, *dims, LANES)
    if batched:
        full_view = (nbatch, *view_shape)
        full_block = (1, *block_shape)
        full_grid = (nbatch, *grid)
    else:
        full_view, full_block, full_grid = view_shape, block_shape, grid

    if _driver_override() == "pipelined":
        if pipeline_enabled():
            # decoupled multi-buffer pipeline (default): separate
            # in/out slot rings, independent DMA semaphore chains
            kernel = functools.partial(
                _decoupled_kernel, stages=tuple(stages), geo=geo,
                grid=grid, block_shape=block_shape,
                in_slots=PIPELINE_IN_SLOTS, out_slots=PIPELINE_OUT_SLOTS,
                nbatch=nbatch, batched=batched)
        else:
            # legacy in-place slot schedule (QUEST_FUSED_PIPELINE=0 —
            # the silicon A/B control)
            kernel = functools.partial(
                _pipelined_kernel, stages=tuple(stages), geo=geo,
                grid=grid, block_shape=block_shape, nbuf=NBUF,
                nbatch=nbatch, batched=batched)
        # the state stays in HBM; the kernel DMAs its own blocks through
        # the in-place slot buffers. Operands are whole-array VMEM.
        in_specs = [pl.BlockSpec(memory_space=_MEMSPACE.HBM)]
        for _ in stages:
            in_specs.append(
                pl.BlockSpec(memory_space=_MEMSPACE.VMEM))
        fn = pl.pallas_call(
            kernel,
            in_specs=in_specs,
            out_specs=pl.BlockSpec(memory_space=_MEMSPACE.HBM),
            out_shape=jax.ShapeDtypeStruct(full_view, jnp.float32),
            input_output_aliases={0: 0},  # in-place on the state buffer
            compiler_params=_COMPILER_PARAMS(
                vmem_limit_bytes=VMEM_LIMIT_BYTES),
            interpret=interpret,
        )
    else:
        kernel = functools.partial(_segment_kernel, stages=tuple(stages),
                                   geo=geo, batched=batched)
        in_specs = [pl.BlockSpec(full_block, index_map)]
        for st in stages:
            if isinstance(st, PairStage):
                d = st.op_dim
                in_specs.append(
                    pl.BlockSpec((2, 4, d, d), lambda *ids: (0, 0, 0, 0)))
            elif isinstance(st, MatStage):
                d = st.dim
                in_specs.append(
                    pl.BlockSpec((2, d, d), lambda *ids: (0, 0, 0)))
            elif isinstance(st, BatchSelStage):
                # the whole per-state table rides resident (batch x 32
                # bytes); the kernel row-selects by the batch grid id
                in_specs.append(
                    pl.BlockSpec((nbatch, 8), lambda *ids: (0, 0)))
            elif isinstance(st, MultiPhaseStage):
                in_specs.append(
                    pl.BlockSpec((len(st.forms), 8), lambda *ids: (0, 0)))
            elif isinstance(st, DiagVecStage):
                k = len(st.targets)
                in_specs.append(
                    pl.BlockSpec((2, 1 << k), lambda *ids: (0, 0)))
            else:                # PhaseStage / ParityStage packed
                # values + predicate masks, (1, 8) — see the dataclasses
                in_specs.append(pl.BlockSpec((1, 8), lambda *ids: (0, 0)))
        fn = pl.pallas_call(
            kernel,
            grid=full_grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec(full_block, index_map),
            out_shape=jax.ShapeDtypeStruct(full_view, jnp.float32),
            input_output_aliases={0: 0},  # in-place on the state buffer
            compiler_params=_COMPILER_PARAMS(
                vmem_limit_bytes=VMEM_LIMIT_BYTES),
            interpret=interpret,
        )

    def apply(amps, mat_arrays):
        # callers keep the state in (2, rows, 128) between segments: that
        # shape and every segment view share the same (8, 128) physical
        # tiling, so these reshapes are free bitcasts. A flat (2, 2^n)
        # boundary would get XLA's T(2,128) tiling and cost a whole-state
        # retile copy per dispatch (the 8 GB HLO temp that OOMed 30q).
        # The kernel is pure f32/int32; trace it with x64 disabled —
        # under jax_enable_x64 stray int64 ops fail Mosaic legalization.
        # Interpret mode keeps the caller's x64 setting: its emulated
        # grid loop mixes its own index dtypes with the surrounding
        # trace, and flipping x64 mid-trace is what breaks it (i32
        # carry vs i64 bound); there is no Mosaic pass to appease there.
        if interpret:
            out = fn(amps.reshape(full_view), *mat_arrays)
        else:
            with compat.enable_x64(False):
                out = fn(amps.reshape(full_view), *mat_arrays)
        if batched:
            return out.reshape(nbatch, 2, -1, LANES)
        return out.reshape(2, -1, LANES)

    return apply


def compile_segment_cached(cache: dict, stages: Sequence, n: int,
                           interpret: bool = False,
                           batch: int | None = None):
    """Kernel-sharing wrapper around compile_segment: stages are pure
    STRUCTURE (operand values ride as kernel inputs), so segments that
    differ only in values — e.g. RCS layers with different angles —
    share one compiled kernel. The ONE place the cache key lives
    (batch is part of it: a bucket's kernels are shaped for it)."""
    key = (tuple(stages), n, interpret, batch)
    fn = cache.get(key)
    if fn is None:
        fn = compile_segment(stages, n, interpret=interpret, batch=batch)
        cache[key] = fn
    return fn


def usable(n: int) -> bool:
    """Need at least one (8, 128) f32 tile per block."""
    return n >= LANE_QUBITS + 3
