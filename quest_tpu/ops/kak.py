"""KAK (Cartan) decomposition of two-qubit unitaries.

Any U in U(4) factors as

    U = e^{i phi} (A1 x B1) . exp(i (x XX + y YY + z ZZ)) . (A2 x B2)

(the standard magic-basis construction; e.g. Vatan & Williams,
quant-ph/0308006). The framework uses it to keep CROSS-BAND two-qubit
unitaries fused: the local factors are single-qubit gates (band-composable
anywhere), and each interaction exponential becomes a PARITY rotation in a
local basis —

    exp(i t XX) = (H x H)   exp(i t ZZ) (H x H)
    exp(i t YY) = (V x V)   exp(i t ZZ) (V x V)^dagger,  V = S H
    exp(i t ZZ) = the engine's parity phase (multiRotateZ semantics),

and parity phases fuse on ANY pair of qubits (they read only the index
parity — the insight the reference uses to skip communication,
QuEST_cpu.c:3069-3109). So a general 2q gate across bands costs ~13
fusable ops instead of a multi-pass XLA fallback. This replaces the
reference's swap-to-local relabeling for multi-target gates
(QuEST_cpu_distributed.c:1441-1483) with pure gate algebra.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

_MAGIC = np.array([[1, 0, 0, 1j],
                   [0, 1j, 1, 0],
                   [0, 1j, -1, 0],
                   [1, 0, 0, -1j]], dtype=np.complex128) / np.sqrt(2)

_H = np.array([[1, 1], [1, -1]], dtype=np.complex128) / np.sqrt(2)
_S = np.diag([1.0, 1.0j]).astype(np.complex128)
_V = _S @ _H                       # X = H Z H ; Y = V Z V^dagger


def _kron_factor(m4: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Factor a (numerically) rank-1 Kronecker product m4 = A (x) B."""
    t = m4.reshape(2, 2, 2, 2).transpose(0, 2, 1, 3).reshape(4, 4)
    u, s, vh = np.linalg.svd(t)
    a = (u[:, 0] * np.sqrt(s[0])).reshape(2, 2)
    b = (vh[0, :] * np.sqrt(s[0])).reshape(2, 2)
    # balance the scalar so both factors are unitary (up to joint phase)
    da = np.sqrt(np.abs(np.linalg.det(a)))
    if da > 1e-12:
        a, b = a / da, b * da
    return a, b


def _orthogonal_diagonalize(p: np.ndarray) -> np.ndarray:
    """Real orthogonal O with O^T p O diagonal, for a complex symmetric
    unitary p (its commuting real/imag parts share an eigenbasis)."""
    pr, pi = p.real, p.imag
    rng = np.random.default_rng(7)
    for _ in range(16):
        t = rng.standard_normal()
        _, o = np.linalg.eigh(pr + t * pi)
        d = o.T @ p @ o
        if np.max(np.abs(d - np.diag(np.diag(d)))) < 1e-9:
            return o
    raise ValueError("failed to jointly diagonalize magic-basis product")


def kak_decompose(u: np.ndarray):
    """Decompose a 4x4 unitary (matrix bit 0 = first target) into
    (a1, b1, (x, y, z), a2, b2, phase) with
    u = phase * (b1 (x) a1) @ CAN(x,y,z) @ (b2 (x) a2),
    CAN = exp(i (x XX + y YY + z ZZ)) — Kronecker order matches the
    little-endian matrix convention (kron(B, A) acts with A on bit 0)."""
    u = np.asarray(u, dtype=np.complex128)
    m = _MAGIC.conj().T @ u @ _MAGIC
    p = m.T @ m
    o2 = _orthogonal_diagonalize(p)
    if np.linalg.det(o2) < 0:
        o2[:, 0] = -o2[:, 0]
    d = np.diag(o2.T @ p @ o2)
    dsq = np.exp(1j * np.angle(d) / 2.0)      # principal branch of sqrt(d)
    # fix the branch product so det factors come out +1:
    # prod(dsq)^2 = det(p) = det(m)^2, so prod(dsq) = +-det(m)
    detm = np.linalg.det(m)
    if np.abs(np.prod(dsq) - detm) > np.abs(np.prod(dsq) + detm):
        dsq = dsq.copy()
        dsq[0] = -dsq[0]
    o1 = m @ o2 @ np.diag(1.0 / dsq)
    if np.max(np.abs(o1.imag)) > 1e-7:
        raise ValueError("kak: left factor not real")
    o1 = o1.real
    # det(o1) = det(m)/prod(dsq) * det(o2) = +1 by the fixes above
    # interaction angles: angle(dsq) = g*1 + x*cx + y*cy + z*cz with the
    # generator diagonals cx/cy/cz computed once from the magic basis
    hp = np.angle(dsq)
    g, x, y, z = np.linalg.solve(_GEN_COEFF, hp)
    k1 = _MAGIC @ o1 @ _MAGIC.conj().T
    k2 = _MAGIC @ o2.T @ _MAGIC.conj().T
    b1, a1 = _kron_factor(k1)
    b2, a2 = _kron_factor(k2)
    phase = np.exp(1j * g)
    # absorb any residual scalar (kron-factor phase conventions) by
    # comparing against the input once
    recon = phase * np.kron(b1, a1) @ _canonical(x, y, z) @ np.kron(b2, a2)
    scale = u[np.unravel_index(np.argmax(np.abs(u)), u.shape)] / \
        recon[np.unravel_index(np.argmax(np.abs(u)), u.shape)]
    phase = phase * scale
    return a1, b1, (x, y, z), a2, b2, phase


_X2 = np.array([[0, 1], [1, 0]], dtype=np.complex128)
_Y2 = np.array([[0, -1j], [1j, 0]], dtype=np.complex128)
_Z2 = np.diag([1.0, -1.0]).astype(np.complex128)


def _canonical(x, y, z):
    from scipy.linalg import expm
    gen = (x * np.kron(_X2, _X2) + y * np.kron(_Y2, _Y2)
           + z * np.kron(_Z2, _Z2))
    return expm(1j * gen)


def _gen_diag(pauli):
    g = np.kron(pauli, pauli)
    d = _MAGIC.conj().T @ g @ _MAGIC
    assert np.max(np.abs(d - np.diag(np.diag(d)))) < 1e-12
    return np.real(np.diag(d))


_GEN_COEFF = np.stack([np.ones(4), _gen_diag(_X2), _gen_diag(_Y2),
                       _gen_diag(_Z2)], axis=1)


def kak_gate_sequence(u: np.ndarray, qa: int, qb: int) -> List[Tuple]:
    """Gate sequence implementing the 2q unitary `u` on qubits (qa, qb)
    (qa = matrix bit 0), in application order. Items:
      ("1q", qubit, 2x2 matrix) | ("parity", (qa, qb), angle)
    where "parity" uses the engine's exp(-i angle/2 Z x Z) convention."""
    a1, b1, (x, y, z), a2, b2, phase = kak_decompose(u)
    seq: List[Tuple] = []
    seq.append(("1q", qa, a2))
    seq.append(("1q", qb, b2))
    # exp(i x XX)
    if abs(x) > 1e-12:
        seq.append(("1q", qa, _H))
        seq.append(("1q", qb, _H))
        seq.append(("parity", (qa, qb), -2.0 * x))
        seq.append(("1q", qa, _H))
        seq.append(("1q", qb, _H))
    # exp(i y YY)
    if abs(y) > 1e-12:
        vdg = _V.conj().T
        seq.append(("1q", qa, vdg))
        seq.append(("1q", qb, vdg))
        seq.append(("parity", (qa, qb), -2.0 * y))
        seq.append(("1q", qa, _V))
        seq.append(("1q", qb, _V))
    # exp(i z ZZ)
    if abs(z) > 1e-12:
        seq.append(("parity", (qa, qb), -2.0 * z))
    # locals + global phase (folded into the qa factor)
    seq.append(("1q", qa, phase * a1))
    seq.append(("1q", qb, b1))
    return seq
