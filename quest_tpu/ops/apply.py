"""Core gate-application machinery: tensor contractions on the (2,)*n view.

Where the reference hand-rolls strided butterfly loops per gate
(e.g. statevec_compactUnitaryLocal, QuEST_cpu.c:1656-1713, and the general
gather/matvec/scatter kernel QuEST_cpu.c:1814-1898), the TPU-native design
expresses every gate as a tensor contraction over the state viewed as a
rank-n tensor of shape (2,)*n. XLA then tiles the contraction onto the
MXU/VPU, fuses adjacent gates traced into the same program, and — when the
amplitude axis is sharded over a device mesh — inserts the necessary
collectives (the GSPMD analogue of the reference's MPI pair exchange).

Index conventions (identical to the reference, QuEST.h little-endian):
  - flat amplitude index i; qubit q is bit q of i
  - tensor view t = amps.reshape((2,)*n) puts qubit q on axis (n-1-q)
  - a k-qubit operator matrix m[(r, c)] uses bit j of r/c for targets[j]
    (targets[0] is the LEAST significant matrix bit, matching the reference's
    multiQubitUnitary semantics, QuEST_cpu.c:1814-1898)

Control qubits are handled by computing the transformed tensor and blending
with the original under a broadcast boolean mask over the control axes —
branch-free, fusion-friendly, and equivalent to the reference's ctrl-mask
skip logic (QuEST.c:285-345).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from quest_tpu import cplx

Axes = Tuple[int, ...]


def _taxis(n: int, q: int) -> int:
    """Tensor axis of qubit q in the (2,)*n view."""
    return n - 1 - q


def _control_mask(n: int, controls: Axes, control_states: Axes, dtype=jnp.bool_):
    """Boolean tensor broadcastable against (2,)*n, True where all control
    qubits carry their required state."""
    shape = [1] * n
    mask = None
    for c, s in zip(controls, control_states):
        ax = _taxis(n, c)
        vec_shape = list(shape)
        vec_shape[ax] = 2
        vec = (jnp.arange(2) == s).reshape(vec_shape)
        mask = vec if mask is None else (mask & vec)
    return mask


def _blend(new_t, old_t, n, controls, control_states):
    if not controls:
        return new_t
    mask = _control_mask(n, tuple(controls), tuple(control_states))
    return jnp.where(mask, new_t, old_t)


def apply_matrix(
    amps: jax.Array,
    n: int,
    matrix: jax.Array,
    targets: Sequence[int],
    controls: Sequence[int] = (),
    control_states: Sequence[int] = (),
) -> jax.Array:
    """Apply a (2^k, 2^k) operator to `targets` of the n-qubit state `amps`.

    Non-unitary matrices are fine (the same path applies Kraus superoperators
    to the doubled density register). Returns new flat amplitudes.
    """
    targets = tuple(int(t) for t in targets)
    k = len(targets)
    t = amps.reshape((2,) * n)
    m = jnp.asarray(matrix, dtype=amps.dtype).reshape((2,) * (2 * k))
    # matrix row bit j -> reshaped axis (k-1-j); col bit j -> axis (2k-1-j)
    col_axes = tuple(2 * k - 1 - j for j in range(k))
    state_axes = tuple(_taxis(n, targets[j]) for j in range(k))
    # HIGHEST precision: TPU matmuls otherwise run bf16 passes, which is
    # far outside simulation tolerance (observed ~1e-3 norm drift)
    out = jnp.tensordot(m, t, axes=(col_axes, state_axes),
                        precision=lax.Precision.HIGHEST)
    # out axes: (row bit k-1, ..., row bit 0, <remaining state axes in order>)
    # row bit j belongs at tensor axis of targets[j]
    dest = tuple(_taxis(n, targets[k - 1 - i]) for i in range(k))
    out = jnp.moveaxis(out, tuple(range(k)), dest)
    out = _blend(out, t, n, tuple(controls), tuple(control_states))
    return out.reshape(-1)


def apply_diagonal(
    amps: jax.Array,
    n: int,
    diag: jax.Array,
    targets: Sequence[int],
    controls: Sequence[int] = (),
    control_states: Sequence[int] = (),
) -> jax.Array:
    """Multiply by a diagonal operator given as a (2^k,) vector over targets.

    Diagonal gates never permute amplitudes — the reference exploits this to
    skip communication entirely (QuEST_cpu.c:2940-3109); here it compiles to
    a pure elementwise multiply which XLA fuses into neighbouring ops.
    """
    targets = tuple(int(t) for t in targets)
    k = len(targets)
    t = amps.reshape((2,) * n)
    d = jnp.asarray(diag, dtype=amps.dtype).reshape((2,) * k)
    # d axis i corresponds to target bit (k-1-i) -> qubit targets[k-1-i]
    # Build a broadcastable (1 or 2 per axis) factor tensor.
    taxes = [_taxis(n, targets[k - 1 - i]) for i in range(k)]
    order = sorted(range(k), key=lambda i: taxes[i])
    d = jnp.transpose(d, order)
    shape = [1] * n
    for i in order:
        shape[taxes[i]] = 2
    d = d.reshape(shape)
    out = t * d
    out = _blend(out, t, n, tuple(controls), tuple(control_states))
    return out.reshape(-1)


def apply_parity_phase(
    amps: jax.Array,
    n: int,
    targets: Sequence[int],
    angle: jax.Array,
) -> jax.Array:
    """exp(-i angle/2 * Z x Z x ... x Z) over `targets`
    (ref statevec_multiRotateZ semantics, QuEST_cpu.c:3069-3109).

    The phase of each amplitude depends only on the parity of its target
    bits: factor exp(-i angle/2 * (-1)^parity), computed via a broadcast
    product of per-axis (+1, -1) sign vectors — no 2^k table, no permutation.
    """
    targets = tuple(int(t) for t in targets)
    t = amps.reshape((2,) * n)
    sign = None
    for q in targets:
        shape = [1] * n
        shape[_taxis(n, q)] = 2
        vec = jnp.array([1.0, -1.0], dtype=amps.real.dtype).reshape(shape)
        sign = vec if sign is None else sign * vec
    half = jnp.asarray(angle, dtype=amps.real.dtype) / 2.0
    factor = cplx.make(jnp.cos(half * sign), -jnp.sin(half * sign))
    out = t * factor.astype(amps.dtype)
    return out.reshape(-1)


def apply_phase_on_all_ones(
    amps: jax.Array,
    n: int,
    qubits: Sequence[int],
    term: jax.Array,
) -> jax.Array:
    """Multiply amplitudes whose `qubits` bits are ALL 1 by scalar `term`.

    Implements the symmetric multi-controlled phase family
    (controlledPhaseShift / multiControlledPhaseShift / ...PhaseFlip,
    ref QuEST_cpu.c:2960-3035) — all listed qubits play identical roles.
    """
    qubits = tuple(int(q) for q in qubits)
    term = jnp.asarray(term, dtype=amps.dtype)
    rdt = amps.real.dtype
    diag = cplx.make(
        jnp.stack([jnp.ones((), dtype=rdt), jnp.real(term)]),
        jnp.stack([jnp.zeros((), dtype=rdt), jnp.imag(term)]))
    return apply_diagonal(amps, n, diag, (qubits[0],),
                          controls=qubits[1:],
                          control_states=(1,) * (len(qubits) - 1))
