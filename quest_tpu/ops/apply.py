"""Core gate application: low-rank segment views over split re/im planes.

TPU-native storage: a register of 2^n amplitudes is ONE real array of shape
(2, 2^n) — plane 0 real parts, plane 1 imaginary parts. Measured on TPU
(v5e) this is 2.3x faster than XLA's interleaved complex64 for the
memory-bound butterfly kernels, and it sidesteps two hard platform limits:
complex buffers cannot cross the host<->device boundary here, and the naive
(2,)*n tensor view exceeds the TPU backend's supported rank for n >~ 16.

Instead of viewing the state as a rank-n tensor, every operation reshapes
each plane into a LOW-RANK "segment view": only the qubits the gate touches
get their own size-2 axis; the contiguous index ranges between them stay
fused as large segments. A k-target gate with c controls therefore works on
a rank-(2(k+c)+1) tensor regardless of n — large contiguous dims that XLA
tiles well.

A k-qubit gate is applied as a FLIP-FORM butterfly:

    out = sum over d in {0,1}^k of  C_d * rev_d(x)

where rev_d reverses the target axes selected by bit-pattern d and C_d is
the coefficient tensor C_d[b] = m[b, b XOR d], broadcast along the
non-target axes. Every term is elementwise (multiply-accumulate against an
axis-reversed read of the SAME input buffer), so XLA fuses the whole gate
into one memory pass with exactly two live full-state buffers — the
in-place discipline of the reference's kernels (QuEST_cpu.c:1656-1713).
[The earlier slice/concat reassembly made XLA materialize a fresh
full-state temp per concat and OOMed a 16 GB chip at 26 qubits.]

For CONCRETE numpy operands, zero C_d terms are skipped at trace time — an
X gate emits a pure axis reversal, no arithmetic (the analogue of the
reference's dedicated pauliX kernel vs its general unitary kernel,
QuEST_cpu.c:2464 vs 1656).

Index conventions (identical to the reference, QuEST.h little-endian):
  - flat amplitude index i; qubit q is bit q of i
  - a k-qubit operator matrix m[r, c] uses bit j of r/c for targets[j]
    (targets[0] is the LEAST significant matrix bit, matching the
    reference's multiQubitUnitary semantics, QuEST_cpu.c:1814-1898)

Operands are (re, im) float pairs — numpy arrays (concrete: baked into the
program, zeros skipped) or traced jnp arrays (dynamic parameters).
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from quest_tpu import precision

Axes = Tuple[int, ...]


def seg_view(n: int, qubits_desc: Sequence[int]):
    """Reshape dims for a (2^n,) plane giving each qubit in `qubits_desc`
    (sorted strictly descending) its own size-2 axis, with the index ranges
    between them left as fused segments. Returns (dims, axis_of)."""
    dims = []
    axis_of: Dict[int, int] = {}
    prev = n
    for q in qubits_desc:
        dims.append(1 << (prev - 1 - q))
        axis_of[q] = len(dims)
        dims.append(2)
        prev = q
    dims.append(1 << prev)
    return tuple(dims), axis_of


def _split_view(n: int, targets, controls):
    qubits = tuple(sorted(set(targets) | set(controls), reverse=True))
    return seg_view(n, qubits)


def bit_tensor(ndims: int, axis: int):
    """(0, 1) along `axis`, broadcastable against a segment view."""
    shape = [1] * ndims
    shape[axis] = 2
    return jnp.arange(2).reshape(shape)


def apply_pauli_string(amps, n, term):
    """P|psi> for a whole Pauli string in ONE fused elementwise pass.

    A Pauli string is a bit-flip permutation (its X/Y factors) times a
    per-index sign (its Z/Y factors) times the global phase (-i)^{#Y}:

        (P psi)[j] = (-i)^{ny} * (-1)^{parity(j & zy)} * psi[j ^ x]

    One flip+sign+scale pass on the planes — no matmuls, no per-factor
    passes (the reference applies the factors gate-by-gate,
    QuEST_common.c:449-462). `term` is one Pauli code (0..3) per qubit.
    Serves calc_expec_pauli_sum / apply_pauli_sum (calculations.py) and
    the fused multi_rotate_pauli (gates.py)."""
    x_bits = tuple(q for q, p in enumerate(term) if p in (1, 2))
    zy_bits = tuple(q for q, p in enumerate(term) if p in (2, 3))
    ny = sum(1 for p in term if p == 2)
    if not x_bits and not zy_bits:
        return amps
    involved = tuple(sorted(set(x_bits) | set(zy_bits), reverse=True))
    dims, axis_of = seg_view(n, involved)
    re = amps[0].reshape(dims)
    im = amps[1].reshape(dims)
    axes = [axis_of[q] for q in x_bits]
    if axes:
        re = jnp.flip(re, axis=axes)
        im = jnp.flip(im, axis=axes)
    sign = parity_sign(len(dims), axis_of, zy_bits, amps.dtype)
    if sign is not None:
        re = re * sign
        im = im * sign
    # global phase (-i)^{ny}: a quarter-turn plane rotation, not a multiply
    k = ny % 4
    if k == 1:      # * -i
        re, im = im, -re
    elif k == 2:    # * -1
        re, im = -re, -im
    elif k == 3:    # * i
        re, im = -im, re
    return jnp.stack([re.reshape(-1), im.reshape(-1)])


def parity_sign(ndims: int, axis_of, qubits, dtype):
    """(-1)^{parity of the listed qubits' bits} as a broadcast product of
    per-axis (+1, -1) vectors — no 2^k table, no permutation. Returns
    None for an empty qubit list. The ONE home of this idiom
    (apply_parity_phase, the Pauli flip-form in calculations.py)."""
    sign = None
    for q in qubits:
        shape = [1] * ndims
        shape[axis_of[q]] = 2
        vec = jnp.array([1.0, -1.0], dtype=dtype).reshape(shape)
        sign = vec if sign is None else sign * vec
    return sign


def norm_control_states(controls, control_states):
    """Empty `control_states` means all-ones. The ONE place this
    normalization lives: a silent zip truncation against default-empty
    states once DROPPED controls entirely (found by the variational
    tests) — every consumer that pairs controls with states must
    normalize through here first."""
    if controls and not control_states:
        return (1,) * len(controls)
    if len(controls) != len(control_states):
        from quest_tpu import validation as val
        val._err("Invalid control state: must give exactly one bit per "
                 "control qubit.")
    return tuple(control_states)


def control_mask(ndims: int, axis_of, controls, control_states):
    """Boolean tensor broadcastable against the segment view, True where all
    control qubits carry their required state; None if no controls."""
    control_states = norm_control_states(controls, control_states)
    mask = None
    for c, s in zip(controls, control_states):
        vec = bit_tensor(ndims, axis_of[c]) == s
        mask = vec if mask is None else (mask & vec)
    return mask


def _as_pair(op_pair, rdtype):
    """Normalize an operand pair. Concrete numpy pairs stay numpy (so zero
    entries can be skipped at trace time); traced values become jnp arrays."""
    re, im = op_pair
    if isinstance(re, np.ndarray) and isinstance(im, np.ndarray):
        return np.asarray(re, dtype=rdtype), np.asarray(im, dtype=rdtype), True
    return (jnp.asarray(re, dtype=rdtype), jnp.asarray(im, dtype=rdtype),
            False)


_UNROLL_MAX_TARGETS = 4  # beyond this the 2^k-term flip butterfly explodes
                         # compile time; use the gather+matmul path instead


def apply_matrix(
    amps: jax.Array,
    n: int,
    op_pair,
    targets: Sequence[int],
    controls: Sequence[int] = (),
    control_states: Sequence[int] = (),
) -> jax.Array:
    """Apply a (2^k, 2^k) operator (as an (re, im) pair) to `targets` of the
    n-qubit state `amps` of shape (2, 2^n). Non-unitary operators are fine
    (the same path applies Kraus superoperators to the doubled density
    register). Returns the new (2, 2^n) planes."""
    targets = tuple(int(t) for t in targets)
    controls = tuple(int(c) for c in controls)
    control_states = norm_control_states(controls, control_states)
    k = len(targets)
    if k > _UNROLL_MAX_TARGETS:
        return _apply_matrix_matmul(amps, n, op_pair, targets, controls,
                                    control_states)
    if n >= 14 and any(q < _LANE_QUBITS for q in targets):
        # Large registers: a segment view exposing a low qubit leaves a
        # tiny minor dim, which the TPU pads to (8, 128) tiles — up to
        # 64x memory (measured OOM on 24-state-qubit channels). Keep the
        # minor dim at 128 lanes: low-qubit content becomes embedded
        # 128x128 lane operators, high target bits become block slices.
        return _apply_matrix_laneblock(amps, n, op_pair, targets, controls,
                                       control_states)
    mre, mim, concrete = _as_pair(op_pair, amps.dtype)
    mre = mre.reshape(1 << k, 1 << k)
    mim = mim.reshape(1 << k, 1 << k)
    dims, axis_of = _split_view(n, targets, controls)
    ndims = len(dims)
    re = amps[0].reshape(dims)
    im = amps[1].reshape(dims)
    taxes = [axis_of[t] for t in targets]
    nre, nim = _flip_form(re, im, mre, mim, concrete, targets, dims,
                          axis_of, taxes)
    mask = control_mask(ndims, axis_of, controls, control_states)
    if mask is not None:
        nre = jnp.where(mask, nre, re)
        nim = jnp.where(mask, nim, im)
    return jnp.stack([nre.reshape(-1), nim.reshape(-1)])


def apply_matrix_rows(amps3, n, op_pair, targets,
                      controls: Sequence[int] = (),
                      control_states: Sequence[int] = ()):
    """apply_matrix on the fused-engine layout: `amps3` is the
    (2, 2^(n-7), 128) shaped state the Pallas segment kernels consume,
    and the result keeps that shape. The point is what does NOT happen:
    no flat (2, 2^n) intermediate ever exists, so XLA never converts
    between the (rows, 128)-tiled kernel layout and the flat layout — a
    conversion that materializes a full-state copy (measured: the 8 GiB
    copy_bitcast that pushed the 30-qubit density-channel bench past
    HBM). All row-axis reshapes here split the major axis only, which is
    layout-free. Matrix ops with a lane-qubit (< 7) target ride the
    128x128 lane-block embedding (_laneblock_core); all-row-target ops
    ride the flip-form butterfly over the row view with the lane axis as
    trailing batch. Oversized operators (k > _UNROLL_MAX_TARGETS) fall
    back to the flat path with one explicit round-trip."""
    targets = tuple(int(t) for t in targets)
    controls = tuple(int(c) for c in controls)
    control_states = norm_control_states(controls, control_states)
    k = len(targets)
    if k > _UNROLL_MAX_TARGETS:
        flat = apply_matrix(amps3.reshape(2, -1), n, op_pair, targets,
                            controls, control_states)
        return flat.reshape(amps3.shape)
    if any(t < _LANE_QUBITS for t in targets):
        return _laneblock_core(amps3, n, op_pair, targets, controls,
                               control_states)
    # every target in row space; controls may sit on either side
    mre, mim, concrete = _as_pair(op_pair, amps3.dtype)
    mre = mre.reshape(1 << k, 1 << k)
    mim = mim.reshape(1 << k, 1 << k)
    rows_n = n - _LANE_QUBITS
    row_ts = tuple(t - _LANE_QUBITS for t in targets)
    hi_cs = [(c - _LANE_QUBITS, s)
             for c, s in zip(controls, control_states) if c >= _LANE_QUBITS]
    lo_cs = [(c, s)
             for c, s in zip(controls, control_states) if c < _LANE_QUBITS]
    qubits = tuple(sorted(set(row_ts) | {c for c, _ in hi_cs},
                          reverse=True))
    rdims, axis_of = seg_view(rows_n, qubits)
    dims = rdims + (_LANES,)
    re = amps3[0].reshape(dims)
    im = amps3[1].reshape(dims)
    taxes = [axis_of[t] for t in row_ts]
    nre, nim = _flip_form(re, im, mre, mim, concrete, row_ts, dims,
                          axis_of, taxes)
    mask = control_mask(len(dims), axis_of,
                        tuple(c for c, _ in hi_cs),
                        tuple(s for _, s in hi_cs))
    if lo_cs:
        # lane-qubit controls: a (128,) predicate on the lane axis — the
        # lane axis is never split (that would break the 128-lane tiling)
        lane = np.arange(_LANES)
        lmask = np.ones(_LANES, dtype=bool)
        for c, s in lo_cs:
            lmask &= ((lane >> c) & 1) == s
        lvec = jnp.asarray(lmask).reshape((1,) * (len(dims) - 1)
                                          + (_LANES,))
        mask = lvec if mask is None else (mask & lvec)
    if mask is not None:
        nre = jnp.where(mask, nre, re)
        nim = jnp.where(mask, nim, im)
    shape = amps3.shape[1:]
    return jnp.stack([nre.reshape(shape), nim.reshape(shape)])


def _flip_form(re, im, mre, mim, concrete, targets, dims, axis_of, taxes):
    """The flip-form butterfly loop (module docstring): out = sum_d
    C_d * rev_d(x) over the target axes `taxes` of the segment views
    `re`/`im`. Control masking is the caller's job. Shared by the flat
    apply_matrix and the shaped row-view path (apply_matrix_rows)."""
    k = len(targets)
    lib = np if concrete else jnp
    rows = np.arange(1 << k)
    nre = None
    nim = None
    for d in range(1 << k):
        # coefficient vector c[b] = m[b, b ^ d], laid out along target axes
        cre = mre[rows, rows ^ d]
        cim = mim[rows, rows ^ d]
        if concrete and np.all(cre == 0.0) and np.all(cim == 0.0):
            continue
        rev = [taxes[j] for j in range(k) if (d >> j) & 1]
        xr = jnp.flip(re, rev) if rev else re
        xi = jnp.flip(im, rev) if rev else im
        fre = _diag_broadcast(cre, k, targets, dims, axis_of, lib)
        fim = _diag_broadcast(cim, k, targets, dims, axis_of, lib)
        if concrete and np.all(cim == 0.0):
            if np.all(cre == 1.0):
                tr, ti = xr, xi       # pure amplitude permutation (X-like)
            else:
                tr, ti = fre * xr, fre * xi
        elif concrete and np.all(cre == 0.0):
            tr, ti = -fim * xi, fim * xr
        else:
            tr = fre * xr - fim * xi
            ti = fre * xi + fim * xr
        nre = tr if nre is None else nre + tr
        nim = ti if nim is None else nim + ti

    if nre is None:  # all-zero matrix
        nre = jnp.zeros_like(re)
        nim = jnp.zeros_like(im)
    return nre, nim


def _f64_mxu_enabled() -> bool:
    """Whether f64 band contractions ride the MXU limb scheme
    (_limb_band_contract). Default: on for TPU backends (where native
    f64 dots are software-emulated scalar-by-scalar — the measured
    9 gates/s @ 26q wall, VERDICT r4 item 2), off elsewhere (XLA-CPU
    has real f64 units). QUEST_F64_MXU=1/0 forces either way (1 is how
    the CPU test suite exercises the scheme's numerics); parse and
    default live in the knob registry (env.KNOBS)."""
    from quest_tpu.env import knob_value
    return knob_value("QUEST_F64_MXU")


_LIMB_BITS = 8          # limb width: bf16-exact integers (<= 2^8)
_LIMB_RADIX = float(1 << _LIMB_BITS)
_LIMB_CUTOFF = 5        # keep pair-dots with i+j <= CUTOFF: representation
                        # + truncation error ~2^-49 of the row max, under
                        # the f64 REAL_EPS 1e-13 with margin; 21 dots per
                        # real contraction


def _limb_band_contract(g64, x64):
    """f64 band contraction out[p,a,q] = sum_b g[a,b] x[p,b,q] computed
    EXACTLY on f32/bf16 matmul hardware via fixed-point limb slicing
    (the Ozaki-scheme idea, recast for the band layout):

      * each contraction vector (x over b per (p,q); g row over b) is
        scaled by its own max and sliced into 8-bit INTEGER limbs —
        integers <= 2^8 are exact in bf16, their products are <= 2^16,
        and a 128-term f32 accumulation of those stays < 2^24, so every
        limb-pair dot is EXACT even at DEFAULT (single-bf16-pass) MXU
        precision;
      * pair-dots are summed as int32 (native VPU ops; up to 6 exact
        integer pair-dots per weight class), and only the final
        6-term weighted combine runs in (emulated) f64.

    Error: ~2^-49 relative to each contraction row's max — norm-class
    f64 accuracy — at 21 single-pass MXU dots per real contraction
    instead of a software-emulated f64 matmul. The per-row scaling is
    what makes the accuracy NORM-relative: a global scale would swamp
    small-amplitude rows (a 30q uniform superposition sits at 2^-15)."""
    f32, f64 = jnp.float32, jnp.float64
    nl = _LIMB_CUTOFF + 1

    def limbs(v, axis):
        s = jnp.max(jnp.abs(v), axis=axis, keepdims=True)
        s = jnp.where(s == 0.0, 1.0, s)
        # snap the scale UP to a power of two: the normalizing division
        # and the final recombine multiply are then EXACT, leaving limb
        # truncation as the scheme's only error term (and grid-aligned
        # inputs round-trip bit-exactly). The guard row protects the
        # |r| <= 1 invariant against log2 rounding down — an li > 256
        # would silently break the exact-bf16-product argument.
        s = jnp.exp2(jnp.ceil(jnp.log2(s)))
        r = v / s
        s = jnp.where(jnp.max(jnp.abs(r), axis=axis, keepdims=True) > 1.0,
                      s * 2.0, s)
        r = v / s
        out = []
        for _ in range(nl):
            r = r * _LIMB_RADIX
            li = jnp.round(r)
            r = r - li
            out.append(li.astype(f32))
        return s, out

    sg, gl = limbs(g64, axis=1)             # g: (band, band), rows over b
    sx, xl = limbs(x64, axis=1)             # x: (pre, band, post) over b

    def pair_dot(gj, xi):
        return jnp.einsum("ab,pbq->paq", gj, xi,
                          precision=jax.lax.Precision.DEFAULT)

    total = None
    for s_tot in range(_LIMB_CUTOFF + 1):
        sub = None
        for i in range(min(s_tot + 1, nl)):
            j = s_tot - i
            if j >= nl:
                continue
            d = pair_dot(gl[j], xl[i]).astype(jnp.int32)
            sub = d if sub is None else sub + d
        term = sub.astype(f64) * (_LIMB_RADIX ** -(s_tot + 2))
        total = term if total is None else total + term
    return sg.reshape(1, -1, 1) * sx * total


def _f64_chunk_elems() -> int:
    """Row-chunk size (elements) for the f64 limb path. The un-chunked
    scheme materializes six full-band f32 limb slices per limbs() call
    (three calls per complex contraction via Gauss) plus int32 partials
    — ~4x the f64 state in HLO temps, which OOMed 28q on a 15.75 GiB
    v5e (scripts/probe_f64.py, measured 2026-08-02). Chunking the
    contraction bounds the temps at chunk size; the path is HBM-bound,
    so per-chunk MXU efficiency is unaffected at this granularity.
    QUEST_F64_CHUNK overrides (elements per chunk; 0 disables chunking);
    knobs parse loudly per the config convention — the registry parser
    (env.KNOBS) rejects non-integers, negatives and non-powers-of-two
    HERE instead of as an opaque reshape error deep inside tracing
    (_limb_apply_chunked derives its chunk count by exact division;
    ADVICE r5 item 1)."""
    from quest_tpu.env import knob_value
    return knob_value("QUEST_F64_CHUNK")


_LIMB_TEMP_MULT = 4     # measured working-set multiplier of the limb
# application: six f32 limb slices per limbs() call (x two live calls,
# g's being negligible) plus the int32 weight-class partials come to
# ~4x the f64 bytes being contracted. The UN-chunked form materializes
# this against the whole state — the ~4x working set that OOMed 28q on
# a 15.75 GiB v5e (scripts/probe_f64.py probe_28q, 2026-08-02); the
# chunked form pays it per chunk only.

_V5E_HBM_BYTES = int(15.75 * 2 ** 30)   # the recognized-family default
# (read off the chip's own OOM report, r3) — bench.py's _hbm_limit
# refines it from live device stats / QUEST_HBM_BYTES when available


def f64_capacity_stats(n: int, chunk_elems: int = None,
                       hbm_bytes: int = None) -> dict:
    """CPU-side peak-memory model of an f64 limb band pass at register
    size `n` — the plan_stats()['f64'] record that answers the
    28q-capacity sizing question WITHOUT a chip (docs/PRECISION.md):

        peak = 2 x state (in + out planes around the donated update)
             + _LIMB_TEMP_MULT x the f64 bytes one chunk contracts

    chunk_elems defaults to the effective QUEST_F64_CHUNK (0 = chunking
    off — the un-chunked ~4x-state working set); hbm_bytes to the
    QUEST_HBM_BYTES override when set (the same knob the bench's OOM
    gate honors — a non-v5e chip answers for ITS capacity), else the
    v5e constant the bench assumes when the device hides memory stats.
    `fits_hbm` is the routing gate bench.py's f64 ladder checks before
    paying a 28q compile (the un-chunked 28q attempt burned its full
    compile before the guaranteed OOM)."""
    state_bytes = 2 * 8 * (1 << n)          # f64 re+im planes
    if chunk_elems is None:
        chunk_elems = _f64_chunk_elems()
    chunk_elems = int(chunk_elems)
    if chunk_elems and chunk_elems < (1 << n):
        chunk_bytes = 2 * 8 * chunk_elems   # re+im chunk pair
    else:
        chunk_elems = 0                     # effectively un-chunked
        chunk_bytes = state_bytes
    temp_bytes = _LIMB_TEMP_MULT * chunk_bytes
    if hbm_bytes is None:
        from quest_tpu.env import knob_value
        hbm_bytes = knob_value("QUEST_HBM_BYTES")   # parses loudly
        if hbm_bytes is None:
            hbm_bytes = _V5E_HBM_BYTES
    peak = 2 * state_bytes + temp_bytes
    # deliberately NO backend-dependent fields (e.g. the QUEST_F64_MXU
    # default probes jax.default_backend()): plan_stats must stay pure
    # host math — callable with a dead tunnel, before backend init
    return {
        "n": int(n),
        "state_bytes": state_bytes,
        "chunk_elems": chunk_elems,
        "chunk_temp_bytes": temp_bytes,
        "peak_bytes": peak,
        "hbm_bytes": int(hbm_bytes),
        "fits_hbm": peak <= int(hbm_bytes),
    }


def mode_key():
    """The apply-level trace-mode flags: everything THIS module reads
    from the environment at trace time, derived from the knob registry
    (env.engine_mode_key, layer='apply' = matmul precision, the f64-MXU
    switch, the limb chunk size). Any jit cache over functions that
    trace through ops/apply must carry this key, or flipping a knob
    mid-process returns stale programs (ADVICE r5 item 2: the eager
    per-gate workers in ops/gates.py had exactly that hole). circuit's
    _engine_mode_key is the all-layer superset."""
    from quest_tpu.env import engine_mode_key
    return engine_mode_key(layer="apply")


def _chunk_grid(pre: int, band: int, post: int,
                chunk_elems: int) -> Tuple[int, int]:
    """(chunks along pre, chunks along post) for _limb_apply_chunked.
    The larger axis splits first (its chunks stay contiguous); the
    other axis splits ONLY when the first alone cannot reach the
    needed chunk count — the wide-band/unbalanced case (e.g. pre=4,
    band=128, post=4096 with a small QUEST_F64_CHUNK) where the old
    single-axis split left chunks of band*post elements and broke the
    "temps never exceed chunk size" guarantee (ADVICE r5 item 3).

    Every quantity is a power of two (state sizes are; the registry
    parser pins chunk_elems), so all divisions here are exact. The
    resulting chunk size (pre//ncp) * band * (post//ncq) is <=
    chunk_elems whenever chunk_elems >= band; one band row is the
    floor — the band axis itself is never split (the contraction
    needs it whole)."""
    size = pre * band * post
    nc_needed = max(1, size // int(chunk_elems))
    if pre >= post:
        ncp = min(pre, nc_needed)
        ncq = min(post, nc_needed // ncp)
    else:
        ncq = min(post, nc_needed)
        ncp = min(pre, nc_needed // ncq)
    chunk = (pre // ncp) * band * (post // ncq)
    assert chunk <= max(int(chunk_elems), band), \
        (pre, band, post, chunk_elems, ncp, ncq)
    return ncp, ncq


def _limb_apply_chunked(gre, gim, re, im, real_only, chunk_elems):
    """The complex f64 band application of apply_band, computed through
    _limb_band_contract one row-chunk at a time under jax.lax.map so
    the limb slices and int32 partials never exceed chunk size (strict
    for chunk_elems >= band; the band axis is the floor — see
    _chunk_grid). The larger of the pre/post axes chunks first and the
    other splits only when needed, so balanced shapes keep the old
    single-relayout behavior while wide-band/unbalanced shapes still
    honor the bound."""
    pre, band, post = re.shape
    ncp, ncq = _chunk_grid(pre, band, post, chunk_elems)
    pc, qc = pre // ncp, post // ncq
    gre64 = jnp.asarray(gre, jnp.float64)
    gim64 = jnp.asarray(gim, jnp.float64)

    def resh(x):
        x = x.reshape(ncp, pc, band, ncq, qc)
        x = jnp.moveaxis(x, 3, 1)           # (ncp, ncq, pc, band, qc)
        return x.reshape(ncp * ncq, pc, band, qc)

    def unresh(x):
        x = x.reshape(ncp, ncq, pc, band, qc)
        x = jnp.moveaxis(x, 1, 3)
        return x.reshape(pre, band, post)

    def body(xs):
        re_c, im_c = xs
        if real_only:
            return (_limb_band_contract(gre64, re_c),
                    _limb_band_contract(gre64, im_c))
        t1 = _limb_band_contract(gre64, re_c)
        t2 = _limb_band_contract(gim64, im_c)
        t3 = _limb_band_contract(gre64 + gim64, re_c + im_c)
        return t1 - t2, t3 - t1 - t2

    nre, nim = jax.lax.map(body, (resh(re), resh(im)))
    return unresh(nre), unresh(nim)


def apply_band(
    amps: jax.Array,
    n: int,
    op_pair,
    ql: int,
    w: int,
    preds: Sequence[Tuple[int, int]] = (),
) -> jax.Array:
    """Apply a composed (2^w, 2^w) band operator to qubits [ql, ql+w) of
    the n-qubit state `amps` (2, 2^n), optionally masked by out-of-band
    (qubit, want) control predicates.

    The band occupies one contiguous bit-range of the amplitude index, so
    the state reshapes to (pre, 2^w, post) and the operator applies as ONE
    axis contraction — a batched matmul on the MXU (out[p,a,q] =
    sum_b G[a,b] x[p,b,q]). This is how every single-qubit gate reaches
    the matrix unit; see quest_tpu/ops/fusion.py for the planner."""
    gre, gim, concrete = _as_pair(op_pair, amps.dtype)
    real_only = concrete and np.all(gim == 0.0)
    band = 1 << w
    post = 1 << ql
    pre = (1 << n) >> (ql + w)
    re = amps[0].reshape(pre, band, post)
    im = amps[1].reshape(pre, band, post)
    gre = jnp.asarray(gre).reshape(band, band)
    gim = jnp.asarray(gim).reshape(band, band)
    hi = precision.matmul_precision()

    limb64 = amps.dtype == jnp.float64 and _f64_mxu_enabled()
    chunk = _f64_chunk_elems() if limb64 else 0
    if limb64 and chunk and re.size > chunk:
        # large-register f64: chunked limb application keeps the HLO
        # temps bounded (28q would OOM un-chunked; _f64_chunk_elems)
        nre, nim = _limb_apply_chunked(gre, gim, re, im, real_only, chunk)
    else:
        if limb64:
            # f64 on matmul hardware without f64 dots: exact-integer
            # limb slices on the MXU (see _limb_band_contract)
            def contract(g, x):
                return _limb_band_contract(jnp.asarray(g, jnp.float64), x)
        else:
            def contract(g, x):
                return jnp.einsum("ab,pbq->paq", g, x, precision=hi)

        if real_only:
            nre = contract(gre, re)
            nim = contract(gre, im)
        else:
            # Gauss 3-multiplication complex matmul (25% fewer MXU passes)
            t1 = contract(gre, re)
            t2 = contract(gim, im)
            t3 = contract(gre + gim, re + im)
            nre = t1 - t2
            nim = t3 - t1 - t2

    if preds:
        mask = None
        for q, s in preds:
            if q < ql:
                ids = jnp.arange(post).reshape(1, 1, post)
            else:
                ids = jnp.arange(pre).reshape(pre, 1, 1)
                q = q - (ql + w)
            bit = ((ids >> q) & 1) == s
            mask = bit if mask is None else (mask & bit)
        nre = jnp.where(mask, nre, re)
        nim = jnp.where(mask, nim, im)
    return jnp.stack([nre.reshape(-1), nim.reshape(-1)])


_LANE_QUBITS = 7
_LANES = 1 << _LANE_QUBITS


import functools


@functools.lru_cache(maxsize=256)
def _lane_basis(low_rel, lc_rel, lcs):
    """(2^kl, 2^kl, 128, 128) basis: entry (i, j) is the lane-space
    embedding of e_ij over the low target qubits with low controls; plus
    the identity-on-unsatisfied-controls completion. Cached per
    (targets, controls) signature — deep circuits reuse it."""
    from quest_tpu.ops import fusion as F
    kl = len(low_rel)
    dim = 1 << kl
    unsat = F.embed_operator(np.zeros((dim, dim)), low_rel, lc_rel, lcs,
                             _LANE_QUBITS).real
    basis = np.zeros((dim, dim, _LANES, _LANES))
    for i in range(dim):
        for j in range(dim):
            e = np.zeros((dim, dim))
            e[i, j] = 1.0
            # embed_operator folds identity-on-unsatisfied-controls into
            # EVERY embedding; strip it so the linear combination
            # L = sum sub[i,j] B_ij scales only the gate content
            basis[i, j] = F.embed_operator(e, low_rel, lc_rel, lcs,
                                           _LANE_QUBITS).real - unsat
    return basis, unsat


def _apply_matrix_laneblock(amps, n, op_pair, targets, controls,
                            control_states):
    """Matrix on a big register where some target is a lane
    qubit (< 7): per high-target bit pattern pair (r, c), a 128x128 lane
    operator applies as (rows, 128) @ L_rc^T — the minor dim never drops
    below 128 lanes (TPU tiling stays 1x). Works for traced operands (the
    embedding is a linear combination of precomputed basis matrices)."""
    rows = 1 << (n - _LANE_QUBITS)
    out = _laneblock_core(amps.reshape(2, rows, _LANES), n, op_pair,
                          targets, controls, control_states)
    return out.reshape(2, -1)


_PASSTHROUGH_CHUNKS = 8          # capacity-mode sweep granularity
_CHUNK_MIN_BYTES = 1 << 30       # chunk once a plane reaches 1 GiB


def _laneblock_core(st2, n, op_pair, targets, controls,
                    control_states, chunks=None):
    """_apply_matrix_laneblock's body on the STACKED (2, rows, 128)
    planes, returning the same shape — shared with apply_matrix_rows,
    whose callers keep the state in the kernel layout and must never
    see a flat (2, 2^n) intermediate (the layout round-trip costs a
    full-state copy on TPU). The stacked carry matters for the chunked
    path: a fori_loop over separate per-plane carries forces XLA to
    materialize each plane as its own buffer (measured: +8 GiB at 30q),
    while ONE stacked carry aliases the donated input. `chunks`: None =
    auto (chunk the sweep once a plane reaches _CHUNK_MIN_BYTES), 1 =
    whole-plane, N = force N chunks (tests exercise the chunked path at
    small sizes)."""
    rdtype = st2.dtype
    mre, mim, concrete = _as_pair(op_pair, rdtype)
    k = len(targets)
    mre = mre.reshape(1 << k, 1 << k)
    mim = mim.reshape(1 << k, 1 << k)
    low_idx = [j for j, t in enumerate(targets) if t < _LANE_QUBITS]
    high_idx = [j for j, t in enumerate(targets) if t >= _LANE_QUBITS]
    kl, kh = len(low_idx), len(high_idx)
    lc = [c for c in controls if c < _LANE_QUBITS]
    lcs = [s for c, s in zip(controls, control_states) if c < _LANE_QUBITS]
    hc = [(c, s) for c, s in zip(controls, control_states)
          if c >= _LANE_QUBITS]
    basis, unsat = _lane_basis(tuple(targets[j] for j in low_idx),
                               tuple(lc), tuple(lcs))
    lib = np if concrete else jnp
    # cast in BOTH branches: the float64 basis otherwise promotes a
    # float32 state to float64 under jax_enable_x64 (doubling the state
    # buffer — the very OOM this path prevents)
    if concrete:
        basis_l = basis.astype(rdtype)
        unsat_l = unsat.astype(rdtype)
    else:
        basis_l = jnp.asarray(basis, dtype=rdtype)
        unsat_l = jnp.asarray(unsat, dtype=rdtype)

    def _indices(hpat):
        """Matrix indices whose low bits sweep and high bits equal hpat."""
        out = np.zeros(1 << kl, dtype=np.int64)
        for a in range(1 << kl):
            v = 0
            for b, j in enumerate(low_idx):
                v |= ((a >> b) & 1) << j
            for b, j in enumerate(high_idx):
                v |= ((hpat >> b) & 1) << j
            out[a] = v
        return out

    def sub_block(m, rh, ch):
        """(2^kl, 2^kl) sub-matrix for high pattern (rh, ch)."""
        rows, cols = _indices(rh), _indices(ch)
        return m[np.ix_(rows, cols)] if concrete else m[rows][:, cols]

    def lane_op(m, rh, ch, with_unsat):
        sub = sub_block(m, rh, ch)
        L = lib.tensordot(sub, basis_l, axes=([0, 1], [0, 1]))
        if with_unsat:
            L = L + unsat_l
        return L

    # row-space view: high target bits get axes; trailing lane axis 128
    rows_n = n - _LANE_QUBITS
    high_bits = sorted({targets[j] - _LANE_QUBITS for j in high_idx} |
                       {c - _LANE_QUBITS for c, _ in hc}, reverse=True)
    rdims, raxis = seg_view(rows_n, tuple(high_bits))
    dims = rdims + (_LANES,)
    view = st2.reshape((2,) + dims)
    taxes = [raxis[targets[j] - _LANE_QUBITS] for j in high_idx]
    ndims = len(dims)

    hi = precision.matmul_precision()

    def matmul(x, L):
        flat = x.reshape(-1, _LANES)
        return jnp.matmul(flat, L.T, precision=hi).reshape(x.shape)

    def apply_view(vre, vim):
        """The block-matmul sweep on one view with the `dims` axis
        structure (the chunked path calls it with a shorter free axis —
        only sizes change, never axis numbering)."""

        def block(x, combo):
            idx = [slice(None)] * ndims
            for b, ax in enumerate(taxes):
                v = (combo >> b) & 1
                idx[ax] = slice(v, v + 1)
            return x[tuple(idx)]

        out_re = [None] * (1 << kh)
        out_im = [None] * (1 << kh)
        for rh in range(1 << kh):
            nr = ni = None
            for ch in range(1 << kh):
                Lre = lane_op(mre, rh, ch, with_unsat=(rh == ch))
                Lim = lane_op(mim, rh, ch, with_unsat=False)
                xr, xi_ = block(vre, ch), block(vim, ch)
                if concrete and np.all(np.asarray(Lim) == 0.0):
                    if np.all(np.asarray(Lre) == 0.0):
                        continue
                    tr, ti = matmul(xr, Lre), matmul(xi_, Lre)
                else:
                    t1 = matmul(xr, Lre)
                    t2 = matmul(xi_, Lim)
                    t3 = matmul(xr + xi_, Lre + Lim)
                    tr, ti = t1 - t2, t3 - t1 - t2
                nr = tr if nr is None else nr + tr
                ni = ti if ni is None else ni + ti
            if nr is None:
                nr = jnp.zeros_like(block(vre, rh))
                ni = jnp.zeros_like(block(vim, rh))
            out_re[rh] = nr
            out_im[rh] = ni

        for b in range(kh):
            ax = taxes[b]
            out_re = [jnp.concatenate([out_re[2 * i], out_re[2 * i + 1]],
                                      axis=ax)
                      for i in range(len(out_re) // 2)]
            out_im = [jnp.concatenate([out_im[2 * i], out_im[2 * i + 1]],
                                      axis=ax)
                      for i in range(len(out_im) // 2)]
        nre, nim = out_re[0], out_im[0]

        if hc:
            mask = None
            for c, s in hc:
                shape = [1] * ndims
                shape[raxis[c - _LANE_QUBITS]] = 2
                vec = jnp.arange(2).reshape(shape) == s
                mask = vec if mask is None else (mask & vec)
            nre = jnp.where(mask, nre, vre)
            nim = jnp.where(mask, nim, vim)
        return nre, nim

    # Near HBM capacity the block matmuls cost full-plane layout copies
    # (measured at 30q: XLA hoists a 4 GiB transposed copy PER PLANE so
    # the strided target-axis blocks become contiguous — with the state
    # itself that is 20 GiB > v5e's 15.75). Chunk the sweep over the
    # largest FREE segment axis (the op never mixes it): a fori_loop
    # reads one chunk, applies the sweep, and writes it back in place,
    # so only chunk-sized temps are ever live.
    free_axes = [ax for ax in range(ndims - 1)
                 if ax not in raxis.values()]
    chunk_ax = max(free_axes, key=lambda ax: dims[ax], default=None)
    if chunks is None:
        plane_bytes = st2[0].size * st2.dtype.itemsize
        chunks = _PASSTHROUGH_CHUNKS if plane_bytes >= _CHUNK_MIN_BYTES \
            else 1
    if chunk_ax is not None and chunks > 1:
        chunks = min(chunks, dims[chunk_ax])   # powers of 2 throughout
    if chunk_ax is not None and chunks > 1 \
            and dims[chunk_ax] % chunks == 0:
        cs = dims[chunk_ax] // chunks
        vax = chunk_ax + 1                     # skip the plane axis

        def body(i, carry):
            start = i * cs
            chunk = lax.dynamic_slice_in_dim(carry, start, cs, axis=vax)
            nr, ni = apply_view(chunk[0], chunk[1])
            return lax.dynamic_update_slice_in_dim(
                carry, jnp.stack([nr, ni]), start, axis=vax)

        out = lax.fori_loop(0, chunks, body, view)
    else:
        nre, nim = apply_view(view[0], view[1])
        out = jnp.stack([nre, nim])
    return out.reshape(st2.shape)


def _apply_matrix_matmul(amps, n, op_pair, targets, controls,
                         control_states):
    """Many-target path: move target axes minor-most, apply the operator as
    a (rest, 2^k) @ (2^k, 2^k) matmul (MXU once 2^k is lane-sized), move
    back. This is the analogue of the reference's general gather/matvec/
    scatter kernel (QuEST_cpu.c:1814-1898) expressed as one contraction."""
    k = len(targets)
    mre, mim, concrete = _as_pair(op_pair, amps.dtype)
    lib = np if concrete else jnp
    m_re = mre.reshape((2,) * (2 * k))
    m_im = mim.reshape((2,) * (2 * k))
    # matrix row/col bit j <-> axis (k-1-j) / (2k-1-j); permute so both row
    # and col axes run in DESCENDING target-qubit order (matching the order
    # target axes appear in the state's segment view)
    order = sorted(range(k), key=lambda j: -targets[j])
    perm = [k - 1 - j for j in order] + [2 * k - 1 - j for j in order]
    m2 = lib.transpose(m_re, perm).reshape(1 << k, 1 << k)
    m2i = lib.transpose(m_im, perm).reshape(1 << k, 1 << k)

    dims, axis_of = _split_view(n, targets, controls)
    ndims = len(dims)
    taxes = [axis_of[t] for t in sorted(targets, reverse=True)]
    rest_axes = [a for a in range(ndims) if a not in taxes]
    fwd = rest_axes + taxes

    def to2d(x):
        t = jnp.transpose(x.reshape(dims), fwd)
        return t.reshape(-1, 1 << k)

    re2 = to2d(amps[0])
    im2 = to2d(amps[1])
    hi = precision.matmul_precision()
    # new[r, s'] = sum_s m2[s', s] v[r, s] -> v @ m2^T
    m2_t, m2i_t = jnp.asarray(m2).T, jnp.asarray(m2i).T
    nre = jnp.matmul(re2, m2_t, precision=hi) - jnp.matmul(im2, m2i_t,
                                                           precision=hi)
    nim = jnp.matmul(re2, m2i_t, precision=hi) + jnp.matmul(im2, m2_t,
                                                            precision=hi)

    inv = [0] * ndims
    for pos, a in enumerate(fwd):
        inv[a] = pos
    tshape = [dims[a] for a in fwd]

    def back(x2):
        return jnp.transpose(x2.reshape(tshape), inv)

    nre_t, nim_t = back(nre), back(nim)
    mask = control_mask(ndims, axis_of, controls, control_states)
    if mask is not None:
        nre_t = jnp.where(mask, nre_t, amps[0].reshape(dims))
        nim_t = jnp.where(mask, nim_t, amps[1].reshape(dims))
    return jnp.stack([nre_t.reshape(-1), nim_t.reshape(-1)])


def _diag_broadcast(d, k, targets, dims, axis_of, lib):
    """Reshape a (2^k,) diagonal so entry bits line up with target axes of
    the segment view. d index bit j corresponds to targets[j]."""
    view = d.reshape((2,) * k)  # axis i <-> bit (k-1-i) <-> targets[k-1-i]
    qubit_of_axis = [targets[k - 1 - i] for i in range(k)]
    # transpose to descending qubit order (= ascending view-axis order)
    perm = sorted(range(k), key=lambda i: -qubit_of_axis[i])
    view = lib.transpose(view, perm) if k > 1 else view
    shape = [1] * len(dims)
    for t in targets:
        shape[axis_of[t]] = 2
    return view.reshape(shape)


def apply_diagonal(
    amps: jax.Array,
    n: int,
    d_pair,
    targets: Sequence[int],
    controls: Sequence[int] = (),
    control_states: Sequence[int] = (),
) -> jax.Array:
    """Multiply by a diagonal operator given as a (2^k,) (re, im) pair over
    `targets`. Diagonal gates never permute amplitudes — the reference
    exploits this to skip communication (QuEST_cpu.c:2940-3109); here it is
    a pure broadcast multiply that XLA fuses into neighbouring ops."""
    targets = tuple(int(t) for t in targets)
    controls = tuple(int(c) for c in controls)
    k = len(targets)
    dre, dim_, concrete = _as_pair(d_pair, amps.dtype)
    dims, axis_of = _split_view(n, targets, controls)
    ndims = len(dims)
    re = amps[0].reshape(dims)
    im = amps[1].reshape(dims)
    lib = np if concrete else jnp
    fre = _diag_broadcast(dre.reshape(-1), k, targets, dims, axis_of, lib)
    fim = _diag_broadcast(dim_.reshape(-1), k, targets, dims, axis_of, lib)
    if concrete and np.all(fim == 0.0):
        nre, nim = re * fre, im * fre
    else:
        nre = re * fre - im * fim
        nim = re * fim + im * fre
    mask = control_mask(ndims, axis_of, controls, control_states)
    if mask is not None:
        nre = jnp.where(mask, nre, re)
        nim = jnp.where(mask, nim, im)
    return jnp.stack([nre.reshape(-1), nim.reshape(-1)])


def apply_parity_phase(
    amps: jax.Array,
    n: int,
    targets: Sequence[int],
    angle: jax.Array,
) -> jax.Array:
    """exp(-i angle/2 * Z x ... x Z) over `targets`
    (ref statevec_multiRotateZ semantics, QuEST_cpu.c:3069-3109).

    The phase of each amplitude depends only on the parity of its target
    bits: factor exp(-i angle/2 * (-1)^parity), via a broadcast product of
    per-axis (+1, -1) sign vectors — no 2^k table, no permutation."""
    targets = tuple(int(t) for t in targets)
    dims, axis_of = _split_view(n, targets, ())
    re = amps[0].reshape(dims)
    im = amps[1].reshape(dims)
    rdt = amps.dtype
    sign = parity_sign(len(dims), axis_of, targets, rdt)
    half = jnp.asarray(angle, dtype=rdt) / 2.0
    cosf = jnp.cos(half)          # even in sign
    sinf = jnp.sin(half) * sign   # odd in sign
    nre = re * cosf + im * sinf
    nim = im * cosf - re * sinf
    return jnp.stack([nre.reshape(-1), nim.reshape(-1)])


def apply_phase_on_all_ones(
    amps: jax.Array,
    n: int,
    qubits: Sequence[int],
    term_pair,
) -> jax.Array:
    """Multiply amplitudes whose `qubits` bits are ALL 1 by the scalar
    `term` = (re, im). Implements the symmetric multi-controlled phase
    family (controlledPhaseShift / multiControlledPhaseShift / ...PhaseFlip,
    ref QuEST_cpu.c:2960-3035) — all listed qubits play identical roles."""
    qubits = tuple(int(q) for q in qubits)
    tre, tim, concrete = _as_pair(term_pair, amps.dtype)
    lib = np if concrete else jnp
    one = lib.ones((), dtype=amps.dtype)
    zero = lib.zeros((), dtype=amps.dtype)
    dre = lib.stack([one, lib.asarray(tre, dtype=amps.dtype).reshape(())])
    dim_ = lib.stack([zero, lib.asarray(tim, dtype=amps.dtype).reshape(())])
    return apply_diagonal(amps, n, (dre, dim_), (qubits[0],),
                          controls=qubits[1:],
                          control_states=(1,) * (len(qubits) - 1))
