from quest_tpu.ops import apply, matrices, gates, channels
