"""Band-fusion planner: compose gate runs into per-band operators.

THE central TPU kernel-engineering idea of this framework (SURVEY.md §7
"hard parts"): strided 2-element butterflies map terribly onto the TPU's
(8, 128) tiles and the 128x128 MXU, but a 7-qubit-aligned BAND of the
amplitude index is exactly one hardware axis:

    band 0 = qubits 0..6    the 128-lane axis
    band 1 = qubits 7..13   the sublane axis (rows within a 128-row tile)
    band 2 = qubits 14..20  the tile index
    band 3 = qubits 21..27  ... and so on, 7 bits per axis.

Any single-qubit gate (with controls anywhere) therefore becomes a
128x128 operator acting on ONE axis of the reshaped state — a batched
matmul the MXU executes natively. Consecutive commuting gates in the same
band compose into a single operator at trace time (numpy), so a whole
layer of single-qubit rotations costs ceil(n/7) memory passes instead of
n, each pass a dense contraction.

This is the role the reference's per-gate kernel zoo plays on CPU/GPU
(QuEST_cpu.c:1656-3620, QuEST_gpu.cu) — re-thought for the MXU instead of
translated.

Fused item kinds produced by `plan`:
  BandOp      composed 2^w x 2^w operator on one band, with optional
              out-of-band control predicates (masked matmul)
  DiagItem    diagonal / parity / all-ones phase GateOp — elementwise,
              any qubits; XLA fuses these into neighbouring passes for
              free (the reference's "diagonals never communicate" insight,
              QuEST_cpu.c:2940-3109, taken one step further)
  PassOp      anything else (cross-band multi-target unitaries, Kraus
              superoperators) — falls through to the general apply path.

Commutation rule used when merging across intervening items: two ops
commute if on every shared qubit BOTH act diagonally (controls and
diagonal/parity ops act diagonally; matrix targets do not). This is a
sufficient condition, checked structurally — no numerics involved.
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence, Tuple

import numpy as np

BAND_W = 7  # qubits per hardware axis: 2^7 = 128 lanes / sublanes / tiles

_SWAP_MATRIX = np.array([[1, 0, 0, 0], [0, 0, 1, 0],
                         [0, 1, 0, 0], [0, 0, 0, 1]], dtype=np.complex128)


@dataclasses.dataclass(frozen=True)
class _PhaseOp:
    """Synthetic GateOp-shaped record for planner-generated phase ops."""
    kind: str
    targets: Tuple[int, ...]
    controls: Tuple[int, ...]
    cstates: Tuple[int, ...]
    operand: object


# ---------------------------------------------------------------------------
# plan items
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class BandOp:
    ql: int                     # first qubit of the band
    w: int                      # band width in qubits (<= BAND_W)
    gre: np.ndarray             # (2^w, 2^w) composed operator, real part
    gim: np.ndarray
    preds: Tuple[Tuple[int, int], ...]  # out-of-band (qubit, want) controls
    nondiag: frozenset          # qubits the operator genuinely mixes
    touched: frozenset          # all qubits involved (targets + controls)

    def qubits(self):
        return self.touched | {q for q, _ in self.preds}


@dataclasses.dataclass
class DiagItem:
    op: object                  # the original GateOp (diag/parity/allones)
    qubits_: frozenset

    def qubits(self):
        return self.qubits_


@dataclasses.dataclass
class PassOp:
    op: object
    nondiag: frozenset
    qubits_: frozenset

    def qubits(self):
        return self.qubits_


# ---------------------------------------------------------------------------
# operator embedding (band-local)
# ---------------------------------------------------------------------------


def embed_operator(matrix: np.ndarray, targets_rel: Sequence[int],
                   controls_rel: Sequence[int], cstates: Sequence[int],
                   width: int) -> np.ndarray:
    """Embed a k-qubit operator with in-band controls into the full
    2^width-dim band space (the full-operator construction the reference's
    test oracle uses, tests/utilities.hpp getFullOperatorMatrix — here it
    runs at trace time to build composed band operators)."""
    matrix = np.asarray(matrix, dtype=np.complex128)
    k = len(targets_rel)
    dim = 1 << width
    op = np.zeros((dim, dim), dtype=np.complex128)
    for col in range(dim):
        if any(((col >> c) & 1) != s for c, s in zip(controls_rel, cstates)):
            op[col, col] = 1.0
            continue
        sub = 0
        for bit, t in enumerate(targets_rel):
            sub |= ((col >> t) & 1) << bit
        rest = col
        for t in targets_rel:
            rest &= ~(1 << t)
        for sub_out in range(1 << k):
            row = rest
            for bit, t in enumerate(targets_rel):
                if (sub_out >> bit) & 1:
                    row |= 1 << t
            op[row, col] = matrix[sub_out, sub]
    return op


def _diag_to_matrix(operand, kind) -> np.ndarray:
    if kind == "diagonal":
        return np.diag(np.asarray(operand, dtype=np.complex128).reshape(-1))
    raise ValueError(kind)


# ---------------------------------------------------------------------------
# the planner
# ---------------------------------------------------------------------------


def _commutes(a_nondiag, a_all, b_nondiag, b_all) -> bool:
    """Structural commutation: every shared qubit must be diagonal-acting
    on both sides."""
    shared = a_all & b_all
    if not shared:
        return True
    return not (shared & (a_nondiag | b_nondiag))


def _band_of(q: int) -> int:
    return q // BAND_W


def band_range(n: int, b: int) -> Tuple[int, int]:
    """(first qubit, width) of band b for an n-qubit register."""
    ql = b * BAND_W
    return ql, min(BAND_W, n - ql)


def plan(ops: Sequence, n: int, bands: Sequence[Tuple[int, int]] = None) -> List:
    """Fuse a GateOp sequence into [BandOp | DiagItem | PassOp], preserving
    semantics. Gate operands must be concrete (numpy) to compose; ops with
    traced operands become PassOps.

    `bands` optionally overrides the default 7-wide band layout with a
    list of (ql, w) ranges covering [0, n) — the Pallas engine uses this
    to align the tile band with its block top (pallas_band.plan_bands)."""
    if bands is None:
        band_of = _band_of
        band_rng = lambda b: band_range(n, b)  # noqa: E731
    else:
        starts = [ql for ql, _ in bands]

        def band_of(q):
            import bisect
            return bisect.bisect_right(starts, q) - 1

        def band_rng(b):
            return bands[b]

    items: List = []

    def try_merge(band: int, emb: np.ndarray, preds, nondiag, touched):
        """Merge emb into an existing BandOp for `band` if every item in
        between commutes with the new op. Returns True on success."""
        new_all = frozenset(touched) | {q for q, _ in preds}
        for i in range(len(items) - 1, -1, -1):
            g = items[i]
            if (isinstance(g, BandOp) and band_of(g.ql) == band
                    and g.preds == preds):
                comp = emb @ (g.gre.astype(np.complex128) + 1j * g.gim)
                items[i] = BandOp(g.ql, g.w, comp.real, comp.imag, preds,
                                  g.nondiag | nondiag, g.touched | touched)
                return True
            g_nondiag = getattr(g, "nondiag", frozenset())
            if not _commutes(nondiag, new_all, g_nondiag, g.qubits()):
                return False
        return False

    for op in ops:
        targets = tuple(op.targets)
        controls = tuple(op.controls)
        cstates = tuple(op.cstates) if op.cstates else (1,) * len(controls)

        if op.kind in ("measure", "measure_dm", "classical"):
            # dynamic-circuit items: opaque to fusion (a measurement or a
            # classically-conditioned gate commutes only with ops on
            # disjoint qubits; targets already claim density duals)
            items.append(PassOp(op, frozenset(targets),
                                frozenset(targets) | frozenset(controls)))
            continue

        if op.kind == "relabel":
            # whole-register relabel event (parallel/relabel.py
            # plan_full_relabels): a full barrier — it re-homes every
            # qubit, so nothing commutes across it
            items.append(PassOp(op, frozenset(range(n)),
                                frozenset(range(n))))
            continue

        if op.kind in ("parity", "allones"):
            # single-band phase ops fold into the band operator as diagonal
            # embeddings (an rz or a neighbour CZ costs nothing once the
            # band matmul runs anyway); cross-band ones stay elementwise
            opbands = {band_of(q) for q in targets + controls}
            if len(opbands) == 1 and isinstance(op.operand,
                                                (int, float, complex)):
                b = opbands.pop()
                ql, w = band_rng(b)
                if op.kind == "parity":
                    half = float(op.operand) / 2.0
                    diag = np.ones(1 << len(targets), dtype=np.complex128)
                    for i in range(diag.size):
                        parity = bin(i).count("1") & 1
                        diag[i] = np.exp(-1j * half * (-1.0) ** parity)
                    mat = np.diag(diag)
                    emb = embed_operator(mat, [t - ql for t in targets],
                                         [], [], w)
                else:  # allones: phase `term` where all listed qubits are 1
                    mat = np.diag([1.0, complex(op.operand)])
                    emb = embed_operator(
                        mat, [targets[0] - ql],
                        [q - ql for q in targets[1:] + controls],
                        [1] * (len(targets) - 1 + len(controls)), w)
                touched = frozenset(targets) | frozenset(controls)
                # fold ONLY into an existing band matmul (then it is free);
                # a phase op alone is cheaper elementwise than as a matmul
                if try_merge(b, emb, (), frozenset(), touched):
                    continue
            items.append(DiagItem(op, frozenset(targets) | frozenset(controls)))
            continue

        operand = op.operand
        if not isinstance(operand, np.ndarray):
            operand = np.asarray(operand)
        if operand.dtype == object or not np.issubdtype(
                operand.dtype, np.number):
            items.append(PassOp(op, frozenset(targets),
                                frozenset(targets) | frozenset(controls)))
            continue

        tbands = {band_of(t) for t in targets}
        if len(tbands) != 1:
            # cross-band SWAP: decompose into 3 CNOTs (each a 1q target
            # with a control — controls fuse as masks, so the whole swap
            # stays in-kernel). The reference instead relabels qubits via
            # distributed swaps (QuEST_cpu_distributed.c:1441-1483).
            if (op.kind == "matrix" and len(targets) == 2 and not controls
                    and operand.shape == (4, 4)
                    and np.allclose(operand, _SWAP_MATRIX)):
                a_q, b_q = targets
                x_mat = np.array([[0.0, 1.0], [1.0, 0.0]])
                for tgt, ctl in ((b_q, a_q), (a_q, b_q), (b_q, a_q)):
                    # targets sit in different bands, so the control is
                    # always out-of-band: a masked-matmul predicate
                    b = band_of(tgt)
                    ql, w = band_rng(b)
                    preds = ((ctl, 1),)
                    emb = embed_operator(x_mat, [tgt - ql], [], [], w)
                    nd = frozenset((tgt,))
                    tc = frozenset((tgt, ctl))
                    if not try_merge(b, emb, preds, nd, tc):
                        items.append(BandOp(ql, w, emb.real, emb.imag,
                                            preds, nd, tc))
                continue
            # general cross-band 2q UNITARY: KAK-decompose into local 1q
            # factors + parity rotations (quest_tpu/ops/kak.py) — every
            # piece fuses, so the gate never leaves the kernel
            if (op.kind == "matrix" and len(targets) == 2 and not controls
                    and operand.shape == (4, 4)
                    and np.allclose(operand @ operand.conj().T, np.eye(4),
                                    atol=1e-9)):
                from quest_tpu.ops import kak as K
                for item in K.kak_gate_sequence(operand, *targets):
                    if item[0] == "1q":
                        _, tq, mat = item
                        b = band_of(tq)
                        ql, w = band_rng(b)
                        emb = embed_operator(mat, [tq - ql], [], [], w)
                        nd, tc = frozenset((tq,)), frozenset((tq,))
                        if not try_merge(b, emb, (), nd, tc):
                            items.append(BandOp(ql, w, emb.real, emb.imag,
                                                (), nd, tc))
                    else:
                        _, pq, ang = item
                        pop = _PhaseOp("parity", tuple(pq), (), (),
                                       float(ang))
                        items.append(DiagItem(pop, frozenset(pq)))
                continue
            # remaining cross-band multi-target ops (superop targets,
            # controlled 2q across bands, non-unitary) — general apply path
            items.append(PassOp(op, frozenset(targets),
                                frozenset(targets) | frozenset(controls)))
            continue

        b = tbands.pop()
        ql, w = band_rng(b)
        in_c = [c for c in controls if band_of(c) == b]
        in_s = [s for c, s in zip(controls, cstates) if band_of(c) == b]
        preds = tuple(sorted((c, s) for c, s in zip(controls, cstates)
                             if band_of(c) != b))
        mat = (_diag_to_matrix(operand, "diagonal")
               if op.kind == "diagonal" else np.asarray(operand))
        emb = embed_operator(mat, [t - ql for t in targets],
                             [c - ql for c in in_c], in_s, w)
        nondiag = (frozenset() if op.kind == "diagonal"
                   else frozenset(targets))
        touched = frozenset(targets) | frozenset(controls)
        if try_merge(b, emb, preds, nondiag, touched):
            continue
        if op.kind == "diagonal":
            # same policy as parity/allones: a diagonal alone is cheaper
            # elementwise than as a band matmul
            items.append(DiagItem(op, touched))
            continue
        items.append(BandOp(ql, w, emb.real, emb.imag, preds, nondiag,
                            touched))
    return items
