"""Band-fusion planner: compose gate runs into per-band operators.

THE central TPU kernel-engineering idea of this framework (SURVEY.md §7
"hard parts"): strided 2-element butterflies map terribly onto the TPU's
(8, 128) tiles and the 128x128 MXU, but a 7-qubit-aligned BAND of the
amplitude index is exactly one hardware axis:

    band 0 = qubits 0..6    the 128-lane axis
    band 1 = qubits 7..13   the sublane axis (rows within a 128-row tile)
    band 2 = qubits 14..20  the tile index
    band 3 = qubits 21..27  ... and so on, 7 bits per axis.

Any single-qubit gate (with controls anywhere) therefore becomes a
128x128 operator acting on ONE axis of the reshaped state — a batched
matmul the MXU executes natively. Consecutive commuting gates in the same
band compose into a single operator at trace time (numpy), so a whole
layer of single-qubit rotations costs ceil(n/7) memory passes instead of
n, each pass a dense contraction.

This is the role the reference's per-gate kernel zoo plays on CPU/GPU
(QuEST_cpu.c:1656-3620, QuEST_gpu.cu) — re-thought for the MXU instead of
translated.

Fused item kinds produced by `plan`:
  BandOp      composed 2^w x 2^w operator on one band, with optional
              out-of-band control predicates (masked matmul)
  DiagItem    diagonal / parity / all-ones phase GateOp — elementwise,
              any qubits; XLA fuses these into neighbouring passes for
              free (the reference's "diagonals never communicate" insight,
              QuEST_cpu.c:2940-3109, taken one step further)
  PassOp      anything else (cross-band multi-target unitaries, Kraus
              superoperators) — falls through to the general apply path.

Commutation rule used when merging across intervening items: two ops
commute if on every shared qubit BOTH act diagonally (controls and
diagonal/parity ops act diagonally; matrix targets do not). This is a
sufficient condition, checked structurally — no numerics involved.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

BAND_W = 7  # qubits per hardware axis: 2^7 = 128 lanes / sublanes / tiles

_SWAP_MATRIX = np.array([[1, 0, 0, 0], [0, 0, 1, 0],
                         [0, 1, 0, 0], [0, 0, 0, 1]], dtype=np.complex128)


@dataclasses.dataclass(frozen=True)
class _PhaseOp:
    """Synthetic GateOp-shaped record for planner-generated phase ops."""
    kind: str
    targets: Tuple[int, ...]
    controls: Tuple[int, ...]
    cstates: Tuple[int, ...]
    operand: object


# ---------------------------------------------------------------------------
# plan items
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class BandOp:
    ql: int                     # first qubit of the band
    w: int                      # band width in qubits (<= BAND_W)
    gre: np.ndarray             # (2^w, 2^w) composed operator, real part
    gim: np.ndarray
    preds: Tuple[Tuple[int, int], ...]  # out-of-band (qubit, want) controls
    nondiag: frozenset          # qubits the operator genuinely mixes
    touched: frozenset          # all qubits involved (targets + controls)

    def qubits(self):
        return self.touched | {q for q, _ in self.preds}


@dataclasses.dataclass
class DiagItem:
    op: object                  # the original GateOp (diag/parity/allones)
    qubits_: frozenset

    def qubits(self):
        return self.qubits_


@dataclasses.dataclass
class PassOp:
    op: object
    nondiag: frozenset
    qubits_: frozenset

    def qubits(self):
        return self.qubits_


# ---------------------------------------------------------------------------
# operator embedding (band-local)
# ---------------------------------------------------------------------------


def embed_operator(matrix: np.ndarray, targets_rel: Sequence[int],
                   controls_rel: Sequence[int], cstates: Sequence[int],
                   width: int) -> np.ndarray:
    """Embed a k-qubit operator with in-band controls into the full
    2^width-dim band space (the full-operator construction the reference's
    test oracle uses, tests/utilities.hpp getFullOperatorMatrix — here it
    runs at trace time to build composed band operators)."""
    matrix = np.asarray(matrix, dtype=np.complex128)
    k = len(targets_rel)
    dim = 1 << width
    op = np.zeros((dim, dim), dtype=np.complex128)
    for col in range(dim):
        if any(((col >> c) & 1) != s for c, s in zip(controls_rel, cstates)):
            op[col, col] = 1.0
            continue
        sub = 0
        for bit, t in enumerate(targets_rel):
            sub |= ((col >> t) & 1) << bit
        rest = col
        for t in targets_rel:
            rest &= ~(1 << t)
        for sub_out in range(1 << k):
            row = rest
            for bit, t in enumerate(targets_rel):
                if (sub_out >> bit) & 1:
                    row |= 1 << t
            op[row, col] = matrix[sub_out, sub]
    return op


def _diag_to_matrix(operand, kind) -> np.ndarray:
    if kind == "diagonal":
        return np.diag(np.asarray(operand, dtype=np.complex128).reshape(-1))
    raise ValueError(kind)


# ---------------------------------------------------------------------------
# the planner
# ---------------------------------------------------------------------------


def _commutes(a_nondiag, a_all, b_nondiag, b_all) -> bool:
    """Structural commutation: every shared qubit must be diagonal-acting
    on both sides."""
    shared = a_all & b_all
    if not shared:
        return True
    return not (shared & (a_nondiag | b_nondiag))


def _band_of(q: int) -> int:
    return q // BAND_W


def band_range(n: int, b: int) -> Tuple[int, int]:
    """(first qubit, width) of band b for an n-qubit register."""
    ql = b * BAND_W
    return ql, min(BAND_W, n - ql)


class _SrcTrackedList(list):
    """plan()'s item list with per-item input-op attribution: append
    records the planner loop's current op index (`cur`) into a parallel
    `src` list; try_merge unions merged ops' indices in place. Kept
    inside the planner — callers see plain items plus the optional
    `attr` out-list."""

    __slots__ = ("src", "cur")

    def __init__(self):
        super().__init__()
        self.src: List[set] = []
        self.cur = -1

    def append(self, x):
        super().append(x)
        self.src.append({self.cur})


def plan(ops: Sequence, n: int, bands: Sequence[Tuple[int, int]] = None,
         attr: Optional[List] = None) -> List:
    """Fuse a GateOp sequence into [BandOp | DiagItem | PassOp], preserving
    semantics. Gate operands must be concrete (numpy) to compose; ops with
    traced operands become PassOps.

    `bands` optionally overrides the default 7-wide band layout with a
    list of (ql, w) ranges covering [0, n) — the Pallas engine uses this
    to align the tile band with its block top (pallas_band.plan_bands).

    `attr`, when a list, receives one frozenset per emitted item holding
    the INPUT op indices that item consumed (composition unions them; an
    op the planner decomposes — cross-band SWAP/KAK — attributes every
    piece). The durable executor's elastic-resume layer maps plan-step
    boundaries back to op-stream positions through this
    (quest_tpu/resilience/durable.py, docs/RESILIENCE.md §elastic)."""
    if bands is None:
        band_of = _band_of
        band_rng = lambda b: band_range(n, b)  # noqa: E731
    else:
        starts = [ql for ql, _ in bands]

        def band_of(q):
            import bisect
            return bisect.bisect_right(starts, q) - 1

        def band_rng(b):
            return bands[b]

    items = _SrcTrackedList()

    def try_merge(band: int, emb: np.ndarray, preds, nondiag, touched):
        """Merge emb into an existing BandOp for `band` if every item in
        between commutes with the new op. Returns True on success."""
        new_all = frozenset(touched) | {q for q, _ in preds}
        for i in range(len(items) - 1, -1, -1):
            g = items[i]
            if (isinstance(g, BandOp) and band_of(g.ql) == band
                    and g.preds == preds):
                comp = emb @ (g.gre.astype(np.complex128) + 1j * g.gim)
                items[i] = BandOp(g.ql, g.w, comp.real, comp.imag, preds,
                                  g.nondiag | nondiag, g.touched | touched)
                items.src[i].add(items.cur)
                return True
            g_nondiag = getattr(g, "nondiag", frozenset())
            if not _commutes(nondiag, new_all, g_nondiag, g.qubits()):
                return False
        return False

    for op_idx, op in enumerate(ops):
        items.cur = op_idx
        targets = tuple(op.targets)
        controls = tuple(op.controls)
        cstates = tuple(op.cstates) if op.cstates else (1,) * len(controls)

        if op.kind in ("measure", "measure_dm", "classical"):
            # dynamic-circuit items: opaque to fusion (a measurement or a
            # classically-conditioned gate commutes only with ops on
            # disjoint qubits; targets already claim density duals)
            items.append(PassOp(op, frozenset(targets),
                                frozenset(targets) | frozenset(controls)))
            continue

        if op.kind == "relabel":
            # whole-register relabel event (parallel/relabel.py
            # plan_full_relabels): a full barrier — it re-homes every
            # qubit, so nothing commutes across it
            items.append(PassOp(op, frozenset(range(n)),
                                frozenset(range(n))))
            continue

        if op.kind in ("parity", "allones"):
            # single-band phase ops fold into the band operator as diagonal
            # embeddings (an rz or a neighbour CZ costs nothing once the
            # band matmul runs anyway); cross-band ones stay elementwise
            opbands = {band_of(q) for q in targets + controls}
            if len(opbands) == 1 and isinstance(op.operand,
                                                (int, float, complex)):
                b = opbands.pop()
                ql, w = band_rng(b)
                if op.kind == "parity":
                    half = float(op.operand) / 2.0
                    diag = np.ones(1 << len(targets), dtype=np.complex128)
                    for i in range(diag.size):
                        parity = bin(i).count("1") & 1
                        diag[i] = np.exp(-1j * half * (-1.0) ** parity)
                    mat = np.diag(diag)
                    emb = embed_operator(mat, [t - ql for t in targets],
                                         [], [], w)
                else:  # allones: phase `term` where all listed qubits are 1
                    mat = np.diag([1.0, complex(op.operand)])
                    emb = embed_operator(
                        mat, [targets[0] - ql],
                        [q - ql for q in targets[1:] + controls],
                        [1] * (len(targets) - 1 + len(controls)), w)
                touched = frozenset(targets) | frozenset(controls)
                # fold ONLY into an existing band matmul (then it is free);
                # a phase op alone is cheaper elementwise than as a matmul
                if try_merge(b, emb, (), frozenset(), touched):
                    continue
            items.append(DiagItem(op, frozenset(targets) | frozenset(controls)))
            continue

        if (op.kind == "diagonal" and _concrete(op.operand)
                and len({band_of(q) for q in targets + controls}) > 1):
            # CONCRETE cross-band multi-qubit diagonal (the scheduler's
            # composed groups land here): elementwise on any qubits,
            # exactly like parity/allones — never a PassOp (a PassOp
            # would serialize a full general-apply pass AND split kernel
            # segments). Traced operands keep falling through to the
            # PassOp guard below: segment_plan's DiagVecStage lowering
            # needs a numpy table.
            items.append(DiagItem(op, frozenset(targets)
                                  | frozenset(controls)))
            continue

        operand = op.operand
        if not isinstance(operand, np.ndarray):
            operand = np.asarray(operand)
        if operand.dtype == object or not np.issubdtype(
                operand.dtype, np.number):
            items.append(PassOp(op, frozenset(targets),
                                frozenset(targets) | frozenset(controls)))
            continue

        tbands = {band_of(t) for t in targets}
        if len(tbands) != 1:
            # cross-band SWAP: decompose into 3 CNOTs (each a 1q target
            # with a control — controls fuse as masks, so the whole swap
            # stays in-kernel). The reference instead relabels qubits via
            # distributed swaps (QuEST_cpu_distributed.c:1441-1483).
            if (op.kind == "matrix" and len(targets) == 2 and not controls
                    and operand.shape == (4, 4)
                    and np.allclose(operand, _SWAP_MATRIX)):
                a_q, b_q = targets
                x_mat = np.array([[0.0, 1.0], [1.0, 0.0]])
                for tgt, ctl in ((b_q, a_q), (a_q, b_q), (b_q, a_q)):
                    # targets sit in different bands, so the control is
                    # always out-of-band: a masked-matmul predicate
                    b = band_of(tgt)
                    ql, w = band_rng(b)
                    preds = ((ctl, 1),)
                    emb = embed_operator(x_mat, [tgt - ql], [], [], w)
                    nd = frozenset((tgt,))
                    tc = frozenset((tgt, ctl))
                    if not try_merge(b, emb, preds, nd, tc):
                        items.append(BandOp(ql, w, emb.real, emb.imag,
                                            preds, nd, tc))
                continue
            # general cross-band 2q UNITARY: KAK-decompose into local 1q
            # factors + parity rotations (quest_tpu/ops/kak.py) — every
            # piece fuses, so the gate never leaves the kernel
            if (op.kind == "matrix" and len(targets) == 2 and not controls
                    and operand.shape == (4, 4)
                    and np.allclose(operand @ operand.conj().T, np.eye(4),
                                    atol=1e-9)):
                from quest_tpu.ops import kak as K
                for item in K.kak_gate_sequence(operand, *targets):
                    if item[0] == "1q":
                        _, tq, mat = item
                        b = band_of(tq)
                        ql, w = band_rng(b)
                        emb = embed_operator(mat, [tq - ql], [], [], w)
                        nd, tc = frozenset((tq,)), frozenset((tq,))
                        if not try_merge(b, emb, (), nd, tc):
                            items.append(BandOp(ql, w, emb.real, emb.imag,
                                                (), nd, tc))
                    else:
                        _, pq, ang = item
                        pop = _PhaseOp("parity", tuple(pq), (), (),
                                       float(ang))
                        items.append(DiagItem(pop, frozenset(pq)))
                continue
            # remaining cross-band multi-target ops (superop targets,
            # controlled 2q across bands, non-unitary) — general apply path
            items.append(PassOp(op, frozenset(targets),
                                frozenset(targets) | frozenset(controls)))
            continue

        b = tbands.pop()
        ql, w = band_rng(b)
        in_c = [c for c in controls if band_of(c) == b]
        in_s = [s for c, s in zip(controls, cstates) if band_of(c) == b]
        preds = tuple(sorted((c, s) for c, s in zip(controls, cstates)
                             if band_of(c) != b))
        mat = (_diag_to_matrix(operand, "diagonal")
               if op.kind == "diagonal" else np.asarray(operand))
        emb = embed_operator(mat, [t - ql for t in targets],
                             [c - ql for c in in_c], in_s, w)
        nondiag = (frozenset() if op.kind == "diagonal"
                   else frozenset(targets))
        touched = frozenset(targets) | frozenset(controls)
        if try_merge(b, emb, preds, nondiag, touched):
            continue
        if op.kind == "diagonal":
            # same policy as parity/allones: a diagonal alone is cheaper
            # elementwise than as a band matmul
            items.append(DiagItem(op, touched))
            continue
        items.append(BandOp(ql, w, emb.real, emb.imag, preds, nondiag,
                            touched))
    if attr is not None:
        attr.extend(frozenset(s) for s in items.src)
    return list(items)


# ---------------------------------------------------------------------------
# commutation-aware gate scheduler (runs BEFORE plan)
# ---------------------------------------------------------------------------
#
# plan() composes runs in PROGRAM ORDER: try_merge walks backward past
# structurally-commuting items, but a diagonal op emitted between two
# non-commuting gates stays where the program put it. On phase-heavy
# circuits that order is the binding constraint — QFT-30 interleaves its
# 435 controlled phases with the Hadamard cascade, so the fused engine
# sees 465 alternating stages and flushes a kernel segment every
# MAX_SEGMENT_STAGES of them (14 full-state HBM passes measured r5;
# the 3x QFT-vs-RCS gates/s gap of VERDICT r5 weak #3).
#
# schedule() legally reorders the flat op list before planning:
#
#   * every diagonal-class op (diagonal / parity / allones — ops that act
#     diagonally on ALL their qubits) is held in a pending pool and
#     DELAYED past later ops it structurally commutes with (the same
#     diagonal-on-shared-qubits rule plan() merges by, used in the other
#     direction);
#   * a non-diagonal op forces out only the pool entries sharing one of
#     its mixed qubits — everything else keeps floating, so phases from
#     MANY original layers pool together;
#   * each forced flush greedily packs the pooled ops into groups of
#     union support <= DIAG_FUSE_MAX qubits and COMPOSES every group
#     into one explicit k-qubit diagonal (a 2^k table op all engines
#     already execute: apply_diagonal on XLA, DiagVecStage or the
#     additive MultiPhaseStage in the Pallas kernels, the
#     communication-free _diagonal_op on the mesh). QFT's per-layer
#     phase runs collapse into ~a group per support-window instead of
#     one stage per phase.
#
# The reorder never crosses a dynamic op (measure / classical), a
# relabel event, or any op the pooled diagonal shares a mixed qubit
# with — the commutation argument is exactly plan()'s structural rule,
# so scheduled and unscheduled programs are unitarily identical (up to
# float reassociation inside composed tables; equivalence-fuzzed across
# engines in tests/test_scheduler.py).

DIAG_FUSE_MAX = 7   # composed-diagonal support cap: 2^7 table entries,
                    # segment views stay rank <= 15 (TPU-supported), and
                    # one band can still host the whole table


def _schedule_enabled() -> bool:
    """QUEST_SCHEDULE knob: '1' (default) runs the commutation-aware
    scheduler in front of every fusing engine's planner; '0' disables.
    Parsed loudly per the config convention; part of every compiled
    program's cache key (circuit._engine_mode_key)."""
    from quest_tpu.env import knob_value
    return knob_value("QUEST_SCHEDULE")


@dataclasses.dataclass(frozen=True)
class ComposedDiag:
    """Scheduler-built k-qubit diagonal: the composition of a group of
    commuting diagonal-class GateOps. Duck-types as a GateOp of kind
    'diagonal' (every engine applies `operand` as a (2^k,) table over
    `targets`); `parts` additionally carries the components in
    TARGET-RELATIVE form — ('allones', idx_tuple, theta) /
    ('parity', idx_tuple, angle), idx indexing into `targets` — so the
    Pallas planner can lower phase-only groups to one additive
    MultiPhaseStage instead of a 2^k select chain. Relative encoding
    keeps parts valid under target remapping (the sharded relabel pass
    rewrites targets via dataclasses.replace)."""
    kind: str
    targets: Tuple[int, ...]
    controls: Tuple[int, ...]
    cstates: Tuple[int, ...]
    operand: object
    parts: Tuple = ()


def _diag_class(op) -> bool:
    """Ops the scheduler may pool: structurally diagonal on every qubit
    they touch AND spanning more than one 7-qubit band. Single-band
    diagonals are deliberately left in program order — plan() folds them
    into the neighbouring band operator for FREE (try_merge), which
    beats any composition; pooling them away from their band op was
    measured to UNDO that fold (band passes 48 -> 73 on QFT-30).
    Controlled allones ops are excluded — the eager XLA applier ignores
    allones controls (circuit._apply_one), so their semantics are not
    uniform enough to move around."""
    if op.kind == "diagonal":
        qs = tuple(op.targets) + tuple(op.controls)
    elif op.kind in ("parity", "allones") and not op.controls:
        qs = tuple(op.targets)
    else:
        return False
    return len({_band_of(q) for q in qs}) > 1


def _concrete(x) -> bool:
    if isinstance(x, (int, float, complex)):
        return True
    if isinstance(x, np.ndarray):
        return (x.dtype != object
                and np.issubdtype(x.dtype, np.number))
    return False


def _nondiag_qubits(op) -> frozenset:
    """Qubits on which `op` acts NON-diagonally (the set a pooled
    diagonal must not share): matrix targets mix; controls are diagonal;
    dynamic/relabel ops conservatively claim everything they touch."""
    if op.kind in ("measure", "measure_dm", "classical", "relabel"):
        return frozenset(op.targets) | frozenset(op.controls)
    if op.kind in ("diagonal", "parity", "allones"):
        return frozenset()
    return frozenset(op.targets)


def _compose_diag_group(group) -> ComposedDiag:
    """Multiply a group of commuting diagonal-class ops into ONE
    explicit diagonal over the sorted union of their qubits. Exact
    up to float reassociation: every component is itself diagonal, so
    the product is the elementwise product of their embedded tables."""
    support = sorted(set().union(*(set(op.targets) | set(op.controls)
                                   for op in group)))
    idx_of = {q: j for j, q in enumerate(support)}
    k = len(support)
    table = np.ones(1 << k, dtype=np.complex128)
    ids = np.arange(1 << k)
    parts: List[Tuple] = []
    phase_only = True
    for op in group:
        if op.kind == "parity":
            bits = tuple(idx_of[q] for q in op.targets)
            sel = np.zeros(1 << k, dtype=np.int64)
            for b in bits:
                sel ^= (ids >> b) & 1
            half = float(op.operand) / 2.0
            table *= np.exp(-1j * half * np.where(sel, -1.0, 1.0))
            parts.append(("parity", bits, float(op.operand)))
        elif op.kind == "allones":
            bits = tuple(idx_of[q] for q in op.targets)
            match = np.ones(1 << k, dtype=bool)
            for b in bits:
                match &= ((ids >> b) & 1) == 1
            t = complex(op.operand)
            table = np.where(match, table * t, table)
            if abs(abs(t) - 1.0) < 1e-12:
                parts.append(("allones", bits, float(np.angle(t))))
            else:
                phase_only = False
        else:  # diagonal (possibly controlled)
            d = np.asarray(op.operand,
                           dtype=np.complex128).reshape(-1)
            tbits = [idx_of[q] for q in op.targets]
            sub = np.zeros(1 << k, dtype=np.int64)
            for j, b in enumerate(tbits):
                sub |= ((ids >> b) & 1) << j
            factor = d[sub]
            cstates = op.cstates or (1,) * len(op.controls)
            for c, s in zip(op.controls, cstates):
                factor = np.where(((ids >> idx_of[c]) & 1) == s,
                                  factor, 1.0)
            table *= factor
            phase_only = False
    return ComposedDiag("diagonal", tuple(support), (), (), table,
                        tuple(parts) if phase_only else ())


def compose_diag_runs(ops: Sequence, diag_max: int = DIAG_FUSE_MAX
                      ) -> List:
    """Pooling entry for SYNTHESIZED diagonal layers (the evolution
    compiler's Trotter blocks, quest_tpu/evolution.py): greedily pack a
    flat run of diagonal-class ops — parity / allones / concrete
    diagonal, which all mutually commute by construction — into
    `ComposedDiag` groups of union support <= diag_max, preserving
    first-op order between groups.

    This deliberately pools SINGLE-band diagonals too: schedule()'s
    `_diag_class` leaves those in program order because a neighbouring
    band matmul absorbs them for free (try_merge), but a synthesized
    diagonal layer has no adjacent band operator — left unpooled, a
    30-term Trotter diagonal block runs as 30 separate kernel phase
    stages where ~5 additive MultiPhaseStage groups carry the same
    math. Ops that cannot compose (traced operands, support wider than
    diag_max, non-diagonal kinds) pass through unchanged in place.

    The caller asserts mutual commutation — this entry does NO
    commutation analysis, unlike schedule(); do not feed it ops that
    mix with non-diagonal gates."""
    groups: List[list] = []       # [support_set, [ops], first_pos]
    passthrough: List[Tuple[int, object]] = []
    for pos, op in enumerate(ops):
        qs = set(op.targets) | set(op.controls)
        # controlled parity/allones pass through: _compose_diag_group's
        # parity/allones branches read targets only (schedule()'s
        # _diag_class excludes them for the same reason) — composing
        # one would silently drop its controls; controlled 'diagonal'
        # composes fine (the group table embeds controls as identity
        # rows)
        composable = (op.kind in ("parity", "allones", "diagonal")
                      and _concrete(op.operand) and len(qs) <= diag_max
                      and not (op.controls and op.kind != "diagonal"))
        if not composable:
            passthrough.append((pos, op))
            continue
        placed = False
        for g in groups:
            if len(g[0] | qs) <= diag_max:
                g[0] |= qs
                g[1].append(op)
                placed = True
                break
        if not placed:
            groups.append([qs, [op], pos])
    emitted: List[Tuple[int, object]] = list(passthrough)
    for _, members, pos in groups:
        if len(members) >= 2:
            emitted.append((pos, _compose_diag_group(members)))
        else:
            emitted.append((pos, members[0]))
    emitted.sort(key=lambda e: e[0])
    return [op for _, op in emitted]


def fixed_run_plan(ops: Sequence, n: int) -> List:
    """Band-fuse a CONSTANT op run for the adjoint engine's fixed
    segments (quest_tpu/adjoint.py): a plain `plan()` call with the
    adjoint contract asserted up front — every operand concrete (a
    traced operand would silently become an unfusable PassOp and the
    backward walk could no longer invert it exactly) and no dynamic
    ops (measurement/classical control have no inverse stream). The
    returned items feed circuit._apply_banded_items on both the
    forward sweep and, rebuilt from the inverted run, the backward
    walk."""
    for i, op in enumerate(ops):
        if op.kind in ("superop", "measure", "measure_dm", "classical",
                       "relabel"):
            raise ValueError(
                f"fixed_run_plan: op {i} ({op.kind}) is not a constant "
                f"invertible gate")
        if not _concrete(op.operand):
            raise ValueError(
                f"fixed_run_plan: op {i} ({op.kind}) carries a traced "
                f"operand; the adjoint engine needs concrete gates")
    return plan(ops, n)


def schedule(flat: Sequence, n: int,
             diag_max: int = DIAG_FUSE_MAX) -> Tuple[List, dict]:
    """Commutation-aware reorder + diagonal composition of a FLAT op
    list (density duals already expanded — run after flatten_ops).
    Returns (new op list, stats). Stats keys:

      pooled       diagonal-class ops that entered the pool
      delayed      pool entries that legally crossed >= 1 later op
      hoisted      emitted diagonals moved EARLIER past commuting ops
      fused_ops    ops absorbed into composed diagonals
      fused_groups composed diagonals emitted (size >= 2)
    """
    out: List = []
    pool: List[list] = []    # [op, delayed_flag]
    stats = {"pooled": 0, "delayed": 0, "fused_ops": 0,
             "fused_groups": 0, "hoisted": 0}

    def _insert_diag(op):
        """Place an emitted diagonal at its EARLIEST legal position in
        `out`: walk backward past every op it structurally commutes
        with (all diagonals, and non-diagonal ops on disjoint qubits).
        Without this hoist a forced group lands right before the gate
        that forced it — BETWEEN a band operator and the same-band gate
        try_merge would have composed into it (measured on QFT-30:
        emission-order placement broke the Hadamard band composition,
        52 -> 98 banded passes). Hoisting also piles the groups of
        neighbouring flushes into adjacent runs, which is what lets the
        banded engine fuse them into one elementwise pass."""
        qs = frozenset(op.targets) | frozenset(op.controls)
        i = len(out)
        while i > 0:
            prev = out[i - 1]
            if prev.kind in ("measure", "measure_dm", "classical",
                             "relabel"):
                break
            if _nondiag_qubits(prev) & qs:
                break
            i -= 1
        if i != len(out):
            stats["hoisted"] += 1
        out.insert(i, op)

    def flush(conflict: Optional[frozenset]):
        """Emit pool entries touching `conflict` (None = all), packing
        them — plus any still-floating entries that fit — into composed
        groups of union support <= diag_max."""
        if not pool:
            return
        if conflict is None:
            forced = list(pool)
        else:
            forced = [e for e in pool
                      if (frozenset(e[0].targets)
                          | frozenset(e[0].controls)) & conflict]
        if not forced:
            return
        groups: List[list] = []      # [support_set, [entries], open]
        # membership by IDENTITY: GateOp equality compares ndarray
        # operands elementwise, which raises on duplicate ops
        forced_ids = {id(e) for e in forced}
        floating = [e for e in pool if id(e) not in forced_ids]
        for e in forced + floating:
            op = e[0]
            qs = set(op.targets) | set(op.controls)
            composable = (_concrete(op.operand)
                          and len(qs) <= diag_max)
            placed = False
            if composable:
                for g in groups:
                    if g[2] and len(g[0] | qs) <= diag_max:
                        g[0] |= qs
                        g[1].append(e)
                        placed = True
                        break
            if id(e) in forced_ids and not placed:
                # no room, or the op itself is uncomposable (traced
                # operand / support wider than diag_max): a CLOSED
                # single-op group — later ops must not join it, or the
                # emission below would compose past the diag_max cap
                groups.append([qs, [e], composable])
            elif not placed:
                continue             # floating op stays pooled
        emitted = set()
        for _, entries, open_ in groups:
            ops = [e[0] for e in entries]
            if open_ and len(ops) >= 2:
                _insert_diag(_compose_diag_group(ops))
                stats["fused_ops"] += len(ops)
                stats["fused_groups"] += 1
            else:
                for o in ops:
                    _insert_diag(o)
            for e in entries:
                if e[1]:
                    stats["delayed"] += 1
                emitted.add(id(e))
        pool[:] = [e for e in pool if id(e) not in emitted]

    for op in flat:
        if _diag_class(op):
            pool.append([op, False])
            stats["pooled"] += 1
            continue
        if op.kind in ("measure", "measure_dm", "classical", "relabel"):
            flush(None)
            out.append(op)
            continue
        flush(_nondiag_qubits(op))
        for e in pool:
            e[1] = True              # survived past a later op
        out.append(op)
    flush(None)
    return out, stats


def maybe_schedule(flat: Sequence, n: int) -> List:
    """schedule() honoring the QUEST_SCHEDULE knob — the engines' entry
    point (stats consumers call schedule() / schedule_summary)."""
    if not _schedule_enabled():
        return list(flat)
    return schedule(flat, n)[0]


def schedule_summary(flat: Sequence, n: int) -> dict:
    """Scheduler stats for introspection (explain / explain_sharded):
    runs the scheduler on a copy whether or not the knob is on, and
    reports whether the engines will actually use it."""
    enabled = _schedule_enabled()
    _, stats = schedule(flat, n)
    stats["enabled"] = enabled
    return stats


def plan_stats(items: Sequence) -> dict:
    """Hardware-independent pass statistics of a fusion plan under the
    BANDED-engine cost model: every BandOp and PassOp is one full-state
    pass; a maximal run of consecutive DiagItems fuses into ONE pass
    (XLA fuses adjacent elementwise ops). The Pallas engine's segment
    count is the fused-model equivalent (pallas_band.segment_plan);
    circuit.Circuit.plan_stats reports both."""
    band_passes = sum(1 for it in items if isinstance(it, BandOp))
    pass_ops = sum(1 for it in items if isinstance(it, PassOp))
    diag_items = 0
    diag_runs = 0
    prev_diag = False
    for it in items:
        is_diag = isinstance(it, DiagItem)
        if is_diag:
            diag_items += 1
            if not prev_diag:
                diag_runs += 1
        prev_diag = is_diag
    return {
        "band_passes": band_passes,
        "pass_ops": pass_ops,
        "diag_items": diag_items,
        "diag_runs": diag_runs,
        "full_state_passes": band_passes + pass_ops + diag_runs,
    }
