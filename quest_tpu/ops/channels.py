"""Decoherence channels on density-matrix registers.

The analytic channels (dephasing / depolarising / damping) act elementwise
or pairwise on the doubled register and compile to fused masked multiplies;
general Kraus maps become a superoperator Sum_k conj(K) (x) K applied as a
2k-qubit operator on [targets, targets + N] — the same reduction the
reference performs (QuEST_common.c:540-673), but running through the one
general apply path over split re/im planes.

Superoperators are assembled from real/imaginary float parts (complex data
never crosses the host<->device boundary — see quest_tpu.cplx).

Reference semantics (QuEST.h decoherence doc-group):
  mixDephasing(p):      rho -> (1-p) rho + p Z rho Z                (p <= 1/2)
  mixTwoQubitDephasing: rho -> (1-p) rho + p/3 (Z1 + Z2 + Z1Z2 terms) (p <= 3/4)
  mixDepolarising(p):   rho -> (1-p) rho + p/3 (X+Y+Z terms)        (p <= 3/4)
  mixTwoQubitDepolarising: uniform over the 15 non-identity 2q Paulis (p <= 15/16)
  mixDamping(p):        K0 = [[1,0],[0,sqrt(1-p)]], K1 = [[0,sqrt(p)],[0,0]]
  mixPauli(px,py,pz):   4-op Kraus map (ref QuEST_common.c:675-695)
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from quest_tpu import cplx
from quest_tpu import validation as val
from quest_tpu.ops import apply as A
from quest_tpu.ops import matrices as M
from quest_tpu.state import Qureg


# ---------------------------------------------------------------------------
# dephasing: pure elementwise factors on mismatched row/col bits
# ---------------------------------------------------------------------------


def _dephase_mask(n, dims, axis_of, pairs):
    """True where ANY (row-bit, col-bit) pair differs."""
    differ = None
    for (r, c) in pairs:
        d = A.bit_tensor(len(dims), axis_of[r]) != \
            A.bit_tensor(len(dims), axis_of[c])
        differ = d if differ is None else (differ | d)
    return differ


@partial(jax.jit, static_argnames=("n", "targets"))
def _dephase(amps, fac, *, n, targets):
    """Scale amplitudes whose row/col bits differ on any target by `fac`
    (ref densmatr_mixDephasing / TwoQubitDephase, QuEST_cpu.c:48-173)."""
    nq = n // 2
    qubits = tuple(sorted(
        set(targets) | set(t + nq for t in targets), reverse=True))
    dims, axis_of = A.seg_view(n, qubits)
    differ = _dephase_mask(n, dims, axis_of,
                           [(t, t + nq) for t in targets])
    re = amps[0].reshape(dims)
    im = amps[1].reshape(dims)
    nre = jnp.where(differ, re * fac, re)
    nim = jnp.where(differ, im * fac, im)
    return jnp.stack([nre.reshape(-1), nim.reshape(-1)])


def mix_dephasing(q: Qureg, target: int, prob) -> Qureg:
    val.validate_density_matr(q)
    val.validate_target(q, target)
    val.validate_one_qubit_dephase_prob(float(prob))
    fac = jnp.asarray(1.0 - 2.0 * float(prob), dtype=q.real_dtype)
    return q.replace_amps(_dephase(q.amps, fac, n=q.num_state_qubits,
                                   targets=(int(target),)))


def mix_two_qubit_dephasing(q: Qureg, t1: int, t2: int, prob) -> Qureg:
    val.validate_density_matr(q)
    val.validate_multi_targets(q, (t1, t2))
    val.validate_two_qubit_dephase_prob(float(prob))
    fac = jnp.asarray(1.0 - 4.0 * float(prob) / 3.0, dtype=q.real_dtype)
    return q.replace_amps(_dephase(q.amps, fac, n=q.num_state_qubits,
                                   targets=(int(t1), int(t2))))


# ---------------------------------------------------------------------------
# depolarising / damping / Kraus: superoperator on [targets, targets+N]
# ---------------------------------------------------------------------------

# Sum over all Pauli tensor-products of conj(P) (x) P, as float (re, im)
# parts (safe to bake into traced programs).
def _pauli_twirl_matrix(num_qubits: int) -> np.ndarray:
    dim = 1 << num_qubits
    acc = np.zeros((dim * dim, dim * dim), dtype=np.complex128)
    paulis = M.PAULIS
    if num_qubits == 1:
        group = list(paulis)
    else:
        # matrix bit 0 = first target => first target is the LSB factor
        group = [np.kron(p2, p1) for p2 in paulis for p1 in paulis]
    for p in group:
        acc += np.kron(np.conj(p), p)
    return acc


_TWIRL1_RE, _TWIRL1_IM = cplx.pack(_pauli_twirl_matrix(1))
_TWIRL2_RE, _TWIRL2_IM = cplx.pack(_pauli_twirl_matrix(2))


def _superop_targets(targets, nq):
    return M.superop_targets(targets, nq)


@partial(jax.jit, static_argnames=("n", "targets"))
def _apply_packed_superop(amps, sup_pair, *, n, targets):
    return A.apply_matrix(amps, n, sup_pair,
                          _superop_targets(targets, n // 2))


@partial(jax.jit, static_argnames=("n", "target"))
def _depol_one(amps, p, *, n, target):
    rdt = amps.dtype
    p = jnp.asarray(p, dtype=rdt)
    eye = jnp.eye(4, dtype=rdt)
    sup_re = (1.0 - p) * eye + (p / 3.0) * (jnp.asarray(_TWIRL1_RE, rdt) - eye)
    sup_im = (p / 3.0) * jnp.asarray(_TWIRL1_IM, rdt)
    return A.apply_matrix(amps, n, (sup_re, sup_im),
                          _superop_targets((target,), n // 2))


@partial(jax.jit, static_argnames=("n", "t1", "t2"))
def _depol_two(amps, p, *, n, t1, t2):
    rdt = amps.dtype
    p = jnp.asarray(p, dtype=rdt)
    eye = jnp.eye(16, dtype=rdt)
    sup_re = (1.0 - p) * eye + (p / 15.0) * (jnp.asarray(_TWIRL2_RE, rdt) - eye)
    sup_im = (p / 15.0) * jnp.asarray(_TWIRL2_IM, rdt)
    return A.apply_matrix(amps, n, (sup_re, sup_im),
                          _superop_targets((t1, t2), n // 2))


@partial(jax.jit, static_argnames=("n", "target"))
def _damping(amps, p, *, n, target):
    rdt = amps.dtype
    p = jnp.asarray(p, dtype=rdt)
    s = jnp.sqrt(1.0 - p)
    # superop = conj(K0) (x) K0 + conj(K1) (x) K1 — all entries real:
    # rows/cols over (col-bit, row-bit):
    #   [[1, 0, 0, p], [0, s, 0, 0], [0, 0, s, 0], [0, 0, 0, 1-p]]
    zero = jnp.zeros_like(p)
    one = jnp.ones_like(p)
    sup_re = jnp.stack([
        jnp.stack([one, zero, zero, p]),
        jnp.stack([zero, s, zero, zero]),
        jnp.stack([zero, zero, s, zero]),
        jnp.stack([zero, zero, zero, one - p]),
    ])
    return A.apply_matrix(amps, n, (sup_re, jnp.zeros_like(sup_re)),
                          _superop_targets((target,), n // 2))


def _mix_packed(q: Qureg, targets, sup_np) -> Qureg:
    """Apply a concrete superoperator (numpy complex) via float packing."""
    return q.replace_amps(_apply_packed_superop(
        q.amps, cplx.pack(sup_np),
        n=q.num_state_qubits, targets=tuple(int(t) for t in targets)))


def mix_depolarising(q: Qureg, target: int, prob) -> Qureg:
    val.validate_density_matr(q)
    val.validate_target(q, target)
    val.validate_one_qubit_depol_prob(float(prob))
    return q.replace_amps(_depol_one(q.amps, float(prob),
                                     n=q.num_state_qubits, target=int(target)))


def mix_two_qubit_depolarising(q: Qureg, t1: int, t2: int, prob) -> Qureg:
    val.validate_density_matr(q)
    val.validate_multi_targets(q, (t1, t2))
    val.validate_two_qubit_depol_prob(float(prob))
    return q.replace_amps(_depol_two(q.amps, float(prob),
                                     n=q.num_state_qubits, t1=int(t1), t2=int(t2)))


def mix_damping(q: Qureg, target: int, prob) -> Qureg:
    val.validate_density_matr(q)
    val.validate_target(q, target)
    val.validate_one_qubit_damping_prob(float(prob))
    return q.replace_amps(_damping(q.amps, float(prob),
                                   n=q.num_state_qubits, target=int(target)))


def mix_pauli(q: Qureg, target: int, prob_x, prob_y, prob_z) -> Qureg:
    """4-op Kraus map from Pauli error probabilities
    (ref densmatr_mixPauli, QuEST_common.c:675-695)."""
    val.validate_density_matr(q)
    val.validate_target(q, target)
    val.validate_pauli_probs(float(prob_x), float(prob_y), float(prob_z))
    ops = M.pauli_kraus(float(prob_x), float(prob_y), float(prob_z))
    return _mix_packed(q, (target,), M.kraus_superoperator(ops))


def mix_kraus_map(q: Qureg, target: int, ops: Sequence) -> Qureg:
    val.validate_density_matr(q)
    val.validate_target(q, target)
    val.validate_kraus_ops(ops, 1, eps=val.eps_for(q), max_ops=4)
    return _mix_packed(q, (target,), M.kraus_superoperator(ops))


def mix_two_qubit_kraus_map(q: Qureg, t1: int, t2: int, ops: Sequence) -> Qureg:
    val.validate_density_matr(q)
    val.validate_multi_targets(q, (t1, t2))
    val.validate_kraus_ops(ops, 2, eps=val.eps_for(q), max_ops=16)
    return _mix_packed(q, (t1, t2), M.kraus_superoperator(ops))


def mix_multi_qubit_kraus_map(q: Qureg, targets: Sequence[int], ops: Sequence) -> Qureg:
    val.validate_density_matr(q)
    val.validate_multi_targets(q, targets)
    k = len(tuple(targets))
    val.validate_kraus_ops(ops, k, eps=val.eps_for(q), max_ops=(1 << (2 * k)))
    return _mix_packed(q, tuple(targets), M.kraus_superoperator(ops))


@jax.jit
def _mix_combine(a, b, p):
    return a + p * (b - a)


def mix_density_matrix(q: Qureg, prob, other: Qureg) -> Qureg:
    """rho -> (1-p) rho + p sigma (ref densmatr_mixDensityMatrix)."""
    val.validate_density_matr(q)
    val.validate_density_matr(other)
    val.validate_match(q, other)
    val.validate_prob(float(prob))
    p = jnp.asarray(float(prob), dtype=q.real_dtype)
    return q.replace_amps(_mix_combine(q.amps, other.amps.astype(q.real_dtype), p))
