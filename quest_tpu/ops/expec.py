"""One-sweep Pauli-sum expectation engine: grouped, sweep-fused
Hamiltonian reductions (docs/EXPECTATION.md).

The reference evaluates an M-term Pauli sum by cloning the register and
applying each term to a workspace — one full apply pass PLUS one inner
product per term, ~2M HBM sweeps (QuEST_common.c:479-491); the port's
legacy `_expec_pauli_sum` kept that per-term pass structure inside one
program. Information-theoretically the job is 1-2 sweeps: every term's
value is an elementwise functional of the state read against ONE
bit-flip-permuted view of itself,

    <P> = sum_j conj(a_j) * (-i)^{ny} * (-1)^{parity(j & zy)} * a_{j^x}

where x is the term's X/Y support (its FLIP MASK), zy its Z/Y support
and ny its Y count (the flip-form of ops/apply.apply_pauli_string). So:

  * all DIAGONAL terms (x == 0: I/Z-only) reduce from |a_j|^2 under
    per-term parity sign masks — ONE pass over the state for the whole
    diagonal block, coefficients applied per element;
  * OFF-DIAGONAL terms sharing a flip mask share one
    conj(a_j) * a_{j^x} product pass — the flipped read is the cost,
    the per-term zy signs are broadcast sign-vector multiplies;
  * distinct masks CO-RIDE one fused reduction up to the
    QUEST_EXPEC_MAX_MASKS budget (the expectation-engine analogue of
    sweep_plan's stage budget, pallas_band.stage_requirements): the
    packed groups' contributions add elementwise and reduce once.

A whole Hamiltonian therefore evaluates in O(#mask-groups) HBM sweeps
instead of O(M). The evaluators are pure jnp elementwise+reduce
programs — XLA fuses each sweep into one loop over the state (no Pallas
kernel needed; there is no MXU work to win), which also makes the whole
engine differentiable: `jax.grad` traces straight through the fused
forward (the autodiff contract of docs/EXPECTATION.md — no custom VJP,
no fallback path).

Coefficients are RUNTIME operands: the term structure (codes) is the
static plan key, the coefficient vector is a traced array, so a VQE
optimizer changing weights every step never retraces (pinned under
CompileAuditor in tests/test_expec.py).

Sharded statevectors compute per-shard partial sums + one psum
(shard_map over the amp mesh, the measurement.sample pattern): local
flip bits flip in-shard, GLOBAL flip bits become one lax.ppermute
chunk exchange per distinct global mask (the reference's
MPI pair exchange, QuEST_cpu_distributed.c:481-509), shared by every
group in the plan that carries the same global mask. Density registers
get the grouped tr(H rho) strided-trace: each mask group reads ONE
flipped diagonal of 2^N entries from the 4^N register
(the `_pauli_term_trace` trick, now amortized over the group).

Introspection: `plan_stats()` reports `expec_groups` /
`expec_hbm_sweeps` CPU-side (no compile, no chip) — the golden
discipline of Circuit.plan_stats, gated in
scripts/check_expec_golden.py and tests/test_expec.py.
"""

from __future__ import annotations

import dataclasses
import functools
from functools import partial
from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from quest_tpu import precision

# Axis chunk width for parity-sign tables: every non-flip axis of a
# group view spans at most 2^_SEG_BITS indices, so per-term signs are
# concrete host tables of <= 256 entries broadcast along <= n/8 axes —
# NEVER a rank-n tensor (the (2,)*n view exceeds the TPU backend's
# supported rank for n >~ 16, ops/apply.py module docstring) and never
# a materialized 2^n sign plane.
_SEG_BITS = 8


# ---------------------------------------------------------------------------
# term parsing (memoized by value — the validate_kraus_ops pattern)
# ---------------------------------------------------------------------------


_PARSE_CACHE: Dict = {}


def parse_pauli_sum(all_codes, num_qubits: int) -> Tuple[Tuple[int, ...], ...]:
    """Validated (M, num_qubits) Pauli-code rows as a nested tuple key,
    memoized BY VALUE: repeated VQE-step calls with the same Hamiltonian
    re-validate nothing (the `validate_kraus_ops` memo pattern of
    trajectories.py; call-count-pinned in tests/test_expec.py). The
    returned tuple is the plan/jit cache key, so equal code arrays from
    different callers resolve to the same compiled programs."""
    codes = np.ascontiguousarray(
        np.asarray(all_codes, dtype=np.int32).reshape(-1, num_qubits))
    key = (num_qubits, codes.shape[0], codes.tobytes())
    hit = _PARSE_CACHE.get(key)
    if hit is not None:
        return hit
    from quest_tpu import validation as val
    val.validate_num_pauli_sum_terms(codes.shape[0])
    val.validate_pauli_codes(codes)
    codes_key = tuple(tuple(int(c) for c in row) for row in codes)
    _PARSE_CACHE[key] = codes_key
    return codes_key


# ---------------------------------------------------------------------------
# the plan
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class _Term:
    """One Pauli string in flip form: coefficient row `index`, X/Y
    support `x_bits` (the flip mask), Z/Y support `zy_bits` (the sign
    mask), Y count `ny` (the (-i)^ny phase quarter-turn)."""
    index: int
    x_bits: Tuple[int, ...]
    zy_bits: Tuple[int, ...]
    ny: int


@dataclasses.dataclass(frozen=True)
class _Group:
    """Terms sharing one flip mask; x_bits == () is the diagonal group."""
    x_bits: Tuple[int, ...]
    terms: Tuple[_Term, ...]


@dataclasses.dataclass(frozen=True)
class ExpecPlan:
    """Static (hashable) evaluation plan: jit programs key on it, so a
    plan is one compiled program per (register shape, dtype) — and the
    coefficient VECTOR stays a runtime operand."""
    n: int                                  # state qubits (2N for density)
    density: bool
    num_terms: int
    groups: Tuple[_Group, ...]
    sweeps: Tuple[Tuple[int, ...], ...]     # packs of group indices


def fusion_enabled() -> bool:
    """QUEST_EXPEC_FUSION (keyed, default on): grouped sweep-fused
    Pauli-sum evaluation; 0 restores the legacy per-term pass structure
    (calculations._expec_pauli_sum / the workspace prod path)."""
    from quest_tpu.env import knob_value
    return knob_value("QUEST_EXPEC_FUSION")


def max_masks_per_sweep() -> int:
    """QUEST_EXPEC_MAX_MASKS (keyed): how many off-diagonal flip-mask
    groups may co-ride one fused reduction — the expectation engine's
    stage budget (sweep_plan's MAX_SWEEP_STAGES analogue)."""
    from quest_tpu.env import knob_value
    return knob_value("QUEST_EXPEC_MAX_MASKS")


def _flip_form(term: Sequence[int], index: int) -> _Term:
    x_bits = tuple(q for q, p in enumerate(term) if p in (1, 2))
    zy_bits = tuple(q for q, p in enumerate(term) if p in (2, 3))
    ny = sum(1 for p in term if p == 2)
    return _Term(index, x_bits, zy_bits, ny)


@functools.lru_cache(maxsize=512)
def _plan_cached(codes_key, n: int, density: bool,
                 max_masks: int) -> ExpecPlan:
    terms = [_flip_form(t, i) for i, t in enumerate(codes_key)]
    by_mask: Dict[Tuple[int, ...], list] = {}
    order = []
    for t in terms:
        if t.x_bits not in by_mask:
            by_mask[t.x_bits] = []
            order.append(t.x_bits)
        by_mask[t.x_bits].append(t)
    # diagonal group first: it is always its own (|a|^2) sweep
    order.sort(key=lambda m: (m != (),))
    groups = tuple(_Group(m, tuple(by_mask[m])) for m in order)
    sweeps = []
    pack = []
    for gi, g in enumerate(groups):
        if not g.x_bits:
            sweeps.append((gi,))
            continue
        pack.append(gi)
        if len(pack) >= max_masks:
            sweeps.append(tuple(pack))
            pack = []
    if pack:
        sweeps.append(tuple(pack))
    return ExpecPlan(n=n, density=density, num_terms=len(terms),
                     groups=groups, sweeps=tuple(sweeps))


def plan_expec(codes_key, num_qubits: int, *, density: bool) -> ExpecPlan:
    """Build (or fetch) the grouped plan for validated code rows.
    `num_qubits` is the LOGICAL qubit count (codes width); a density
    plan evaluates on the doubled 2N-qubit register."""
    n = 2 * num_qubits if density else num_qubits
    return _plan_cached(tuple(tuple(t) for t in codes_key), n,
                        bool(density), max_masks_per_sweep())


# ---------------------------------------------------------------------------
# view geometry + parity sign tables
# ---------------------------------------------------------------------------


def _group_view(n: int, x_bits: Tuple[int, ...], seg_bits: int = _SEG_BITS):
    """Axis layout for a (2^n,) plane: each flip bit gets its own size-2
    axis (so jnp.flip reverses it), and the contiguous bit ranges
    between them split into chunks of at most `seg_bits` bits (so
    per-term parity signs are small concrete tables, never rank-n).
    Returns (dims, axis_of_flip_bit, ranges) with ranges[axis] =
    (lo_bit, width) in little-endian bit coordinates, axes MSB-first
    (the ops/apply.seg_view convention)."""
    dims, ranges = [], []
    axis_of: Dict[int, int] = {}

    def push(lo, hi):
        cut = hi
        while cut > lo:
            w = min(seg_bits, cut - lo)
            dims.append(1 << w)
            ranges.append((cut - w, w))
            cut -= w

    prev = n
    for q in sorted(x_bits, reverse=True):
        if prev > q + 1:
            push(q + 1, prev)
        dims.append(2)
        ranges.append((q, 1))
        axis_of[q] = len(dims) - 1
        prev = q
    if prev > 0:
        push(0, prev)
    if not dims:                      # n == 0 edge (never hit in practice)
        dims, ranges = [1], [(0, 0)]
    return tuple(dims), axis_of, tuple(ranges)


def _parity_tables(ranges, zy_bits, rdt):
    """[(axis, concrete (+1/-1) vector)] for the axes whose bit range
    intersects `zy_bits`: table[v] = (-1)^{parity(v & local mask)}. The
    broadcast PRODUCT of these along the group view is the term's full
    parity sign — factored per axis, so nothing 2^n-sized ever exists
    (the parity_sign idiom of ops/apply.py, generalized from size-2
    axes to bit-range chunks)."""
    zy = frozenset(zy_bits)
    out = []
    for ax, (lo, w) in enumerate(ranges):
        bits = [b for b in range(lo, lo + w) if b in zy]
        if not bits:
            continue
        idx = np.arange(1 << w)
        par = np.zeros(1 << w, dtype=np.int64)
        for b in bits:
            par ^= (idx >> (b - lo)) & 1
        out.append((ax, (1.0 - 2.0 * par).astype(rdt)))
    return out


def _signed_weight(cf, t: _Term, extra_sign=None):
    """Traced scalar weight of term `t`: its coefficient times the sign
    of the real part of the (-i)^ny quarter-turn (Re[(-i)^ny z] is
    +zr, +zi, -zr, -zi for ny%4 = 0..3 — the plane itself is selected
    by the caller). `extra_sign` multiplies in a per-shard global
    parity sign (the sharded path's device-bit contribution)."""
    w = cf[t.index]
    if t.ny % 4 in (2, 3):
        w = -w
    if extra_sign is not None:
        w = w * extra_sign
    return w


def _apply_sign_tables(plane, tables, ndims):
    for ax, tab in tables:
        shape = [1] * ndims
        shape[ax] = tab.size
        plane = plane * jnp.asarray(tab).reshape(shape)
    return plane


# ---------------------------------------------------------------------------
# statevector evaluation
# ---------------------------------------------------------------------------


def _group_contrib_sv(ar, ai, fr, fi, group: _Group, cf, ranges, ndims):
    """Elementwise contribution of one mask group over its view: the
    shared conj(a) * a_flip products, each term's parity-sign
    broadcast multiply and runtime coefficient, summed — ONE fused
    XLA expression reading the state (and its flipped image) once.
    `fr`/`fi` are the (already flipped) source planes; for the diagonal
    group they alias `ar`/`ai`."""
    if group.x_bits:
        base_re = ar * fr + ai * fi          # Re conj(a_j) a_{j^x}
        need_im = any(t.ny % 2 for t in group.terms)
        base_im = (ar * fi - ai * fr) if need_im else None
    else:
        base_re = ar * ar + ai * ai          # |a_j|^2; ny == 0 for I/Z
        base_im = None
    rdt = np.dtype(base_re.dtype)
    contrib = None
    for t in group.terms:
        plane = base_re if t.ny % 2 == 0 else base_im
        term = _apply_sign_tables(plane, _parity_tables(ranges, t.zy_bits,
                                                        rdt), ndims)
        term = term * _signed_weight(cf, t)
        contrib = term if contrib is None else contrib + term
    return contrib


def _sweep_value_sv(amps, cf, plan: ExpecPlan, pack, acc):
    """One co-ride pack = one fused reduction: every group's elementwise
    contribution flattens and adds, then reduces ONCE (the f64
    accumulator convert fuses into the reduce — the _sum_sq
    discipline)."""
    flat = None
    for gi in pack:
        g = plan.groups[gi]
        dims, axis_of, ranges = _group_view(plan.n, g.x_bits)
        ar = amps[0].reshape(dims)
        ai = amps[1].reshape(dims)
        if g.x_bits:
            axes = [axis_of[q] for q in g.x_bits]
            fr = jnp.flip(ar, axes)
            fi = jnp.flip(ai, axes)
        else:
            fr, fi = ar, ai
        c = _group_contrib_sv(ar, ai, fr, fi, g, cf, ranges,
                              len(dims)).reshape(-1)
        flat = c if flat is None else flat + c
    return jnp.sum(flat.astype(acc))


def expec_traced(amps, coeffs, plan: ExpecPlan):
    """The traced fused evaluation — sum_t c_t <P_t> over `plan` with
    runtime `coeffs`. Composable: variational energies and the serve
    reducers trace through this inside their own jit; jax.grad flows
    through every op (docs/EXPECTATION.md autodiff contract)."""
    acc = precision.accum_dtype(amps.dtype)
    cf = jnp.asarray(coeffs, dtype=amps.dtype)
    total = jnp.zeros((), dtype=acc)
    for pack in plan.sweeps:
        if plan.density:
            total = total + _sweep_value_density(amps, cf, plan, pack, acc)
        else:
            total = total + _sweep_value_sv(amps, cf, plan, pack, acc)
    return total


@partial(jax.jit, static_argnames=("plan",))
def _expec_fused(amps, coeffs, *, plan: ExpecPlan):
    return expec_traced(amps, coeffs, plan)


def _quarter_turn(k: int, fr, fi):
    """(re, im) planes of (-i)^k (fr + i fi) — the per-term Y-count
    phase applied as a plane swap/negate, never a complex multiply."""
    if k == 0:
        return fr, fi
    if k == 1:
        return fi, -fr
    if k == 2:
        return -fr, -fi
    return -fi, fr


def apply_pauli_sum_planes(amps, coeffs, plan: ExpecPlan):
    """|out> = (sum_t c_t P_t) |a> on (2, 2^n) planes — the OPERATOR
    application companion of `expec_traced` over the same grouped plan:

        out_j = sum_t c_t (-i)^{ny_t} (-1)^{parity(j & zy_t)} a_{j^{x_t}}

    One flipped read per mask group (terms sharing a flip mask share
    it), per-term parity signs as broadcast chunk tables, the (-i)^ny
    phase as a quarter-turn plane select. This seeds the adjoint
    engine's bra register lambda = H|psi_L> (quest_tpu/adjoint.py) in
    O(#mask-groups) sweeps with no 2^n x 2^n operator ever formed.
    Statevector plans only (plan.density must be False — the density
    walk runs on the doubled register through the sv form)."""
    assert not plan.density
    cf = jnp.asarray(coeffs, dtype=amps.dtype)
    out_re = jnp.zeros_like(amps[0])
    out_im = jnp.zeros_like(amps[1])
    for g in plan.groups:
        dims, axis_of, ranges = _group_view(plan.n, g.x_bits)
        ar = amps[0].reshape(dims)
        ai = amps[1].reshape(dims)
        if g.x_bits:
            axes = [axis_of[q] for q in g.x_bits]
            fr = jnp.flip(ar, axes)
            fi = jnp.flip(ai, axes)
        else:
            fr, fi = ar, ai
        rdt = np.dtype(ar.dtype)
        gre = gim = None
        for t in g.terms:
            pre, pim = _quarter_turn(t.ny % 4, fr, fi)
            tabs = _parity_tables(ranges, t.zy_bits, rdt)
            w = cf[t.index]
            tre = _apply_sign_tables(pre, tabs, len(dims)) * w
            tim = _apply_sign_tables(pim, tabs, len(dims)) * w
            gre = tre if gre is None else gre + tre
            gim = tim if gim is None else gim + tim
        out_re = out_re + gre.reshape(-1)
        out_im = out_im + gim.reshape(-1)
    return jnp.stack([out_re, out_im])


# ---------------------------------------------------------------------------
# density evaluation: grouped tr(H rho) strided trace
# ---------------------------------------------------------------------------


def flipped_trace_diag(amps, N: int, x_bits):
    """(Re, Im) of the flipped diagonal rho[k, k^x] as (2^N,) vectors —
    the 2^N entries a Pauli trace touches in the 4^N register.

    Stored layout: flat = row + col*2^N, so the row-major (dim, dim)
    view M has M[a, b] = rho[b, a]; flipping the listed first-axis bits
    and reading the main diagonal yields rho[k, k^x]. The ONE home of
    this extraction — the grouped density sweeps here and the legacy
    per-term `_pauli_term_trace` (calculations.py) both call it."""
    from quest_tpu.ops import apply as A

    dim = 1 << N
    re = amps[0].reshape((dim, dim))
    im = amps[1].reshape((dim, dim))
    if x_bits:
        x_desc = tuple(sorted(x_bits, reverse=True))
        dims_a, axis_of_a = A.seg_view(N, x_desc)
        axes = [axis_of_a[q] for q in x_bits]
        shape = tuple(dims_a) + (dim,)
        re = jnp.flip(re.reshape(shape), axis=axes).reshape((dim, dim))
        im = jnp.flip(im.reshape(shape), axis=axes).reshape((dim, dim))
    return jnp.diagonal(re), jnp.diagonal(im)


def _sweep_value_density(amps, cf, plan: ExpecPlan, pack, acc):
    """Density pack: each group reads ONE flipped diagonal — 2^N
    entries of the 4^N register, Tr(P rho) = sum_k coef(k) rho[k, k^x]
    (the `_pauli_term_trace` gather, amortized over every term sharing
    the mask) — then per-term parity signs and coefficients apply on
    the (2^N,) diagonal and the pack reduces once."""
    N = plan.n // 2
    flat = None
    for gi in pack:
        g = plan.groups[gi]
        rdiag, idiag = flipped_trace_diag(amps, N, g.x_bits)
        dims, _, ranges = _group_view(N, ())
        rdiag = rdiag.reshape(dims)
        idiag = idiag.reshape(dims)
        rdt = np.dtype(rdiag.dtype)
        contrib = None
        for t in g.terms:
            # Re(i^{ny} (rdiag + i idiag)): +r, -i, -r, +i per ny % 4
            k = t.ny % 4
            plane = rdiag if k % 2 == 0 else idiag
            w = cf[t.index]
            if k in (1, 2):
                w = -w
            term = _apply_sign_tables(plane,
                                      _parity_tables(ranges, t.zy_bits, rdt),
                                      len(dims))
            term = term * w
            contrib = term if contrib is None else contrib + term
        contrib = contrib.reshape(-1)
        flat = contrib if flat is None else flat + contrib
    return jnp.sum(flat.astype(acc))


# ---------------------------------------------------------------------------
# sharded statevector evaluation (per-shard partials + psum)
# ---------------------------------------------------------------------------


# jitted shard_map evaluators, keyed (mesh object, plan, D) — the
# measurement.sample cache discipline: rebuilding the wrapper per call
# would retrace every evaluation
_SHARDED_RUNS: Dict = {}


def _device_parity_sign(dev, bits, rdt):
    """(+1/-1) traced scalar: parity of the device index over the
    listed (device-local) global bit positions."""
    par = None
    for b in bits:
        bit = (dev >> b) & 1
        par = bit if par is None else par ^ bit
    return (1 - 2 * par).astype(rdt)


def _group_contrib_sharded(amps, cf, local_n, dev, group: _Group,
                           exchanged: Dict):
    """Per-shard contribution of one mask group. Local flip bits flip
    in-shard; GLOBAL flip bits are one ppermute chunk exchange with
    device dev ^ gmask (the reference's MPI pair exchange), fetched
    once per distinct global mask and shared by every group carrying
    it. Global zy bits contribute a per-device scalar sign (their
    parity is constant over the shard)."""
    from quest_tpu.env import AMP_AXIS

    lx = tuple(q for q in group.x_bits if q < local_n)
    gxm = 0
    for q in group.x_bits:
        if q >= local_n:
            gxm |= 1 << (q - local_n)
    src = amps
    if gxm:
        src = exchanged.get(gxm)
        if src is None:
            D = exchanged["__D__"]
            perm = [(d, d ^ gxm) for d in range(D)]
            src = jax.lax.ppermute(amps, AMP_AXIS, perm)
            exchanged[gxm] = src
    dims, axis_of, ranges = _group_view(local_n, lx)
    ar = amps[0].reshape(dims)
    ai = amps[1].reshape(dims)
    sr = src[0].reshape(dims)
    si = src[1].reshape(dims)
    if lx:
        axes = [axis_of[q] for q in lx]
        sr = jnp.flip(sr, axes)
        si = jnp.flip(si, axes)
    if group.x_bits:
        base_re = ar * sr + ai * si
        need_im = any(t.ny % 2 for t in group.terms)
        base_im = (ar * si - ai * sr) if need_im else None
    else:
        base_re = ar * ar + ai * ai
        base_im = None
    rdt = np.dtype(base_re.dtype)
    ndims = len(dims)
    contrib = None
    for t in group.terms:
        plane = base_re if t.ny % 2 == 0 else base_im
        lzy = tuple(b for b in t.zy_bits if b < local_n)
        term = _apply_sign_tables(plane, _parity_tables(ranges, lzy, rdt),
                                  ndims)
        gzy = tuple(b - local_n for b in t.zy_bits if b >= local_n)
        extra = _device_parity_sign(dev, gzy, amps.dtype) if gzy else None
        term = term * _signed_weight(cf, t, extra)
        contrib = term if contrib is None else contrib + term
    return contrib.reshape(-1)


def apply_pauli_sum_planes_sharded(amps, cf, local_n: int, dev,
                                   plan: ExpecPlan, exchanged: Dict):
    """Per-shard |out> = H |a|: the apply_pauli_sum_planes companion of
    `_group_contrib_sharded`, run INSIDE a shard_map body. Local flip
    bits flip in-shard; each distinct GLOBAL flip mask costs one
    ppermute pair exchange, fetched once and shared via `exchanged`
    (seed it with {"__D__": D}). Global zy bits fold into a per-device
    scalar sign. `amps` is this shard's (2, 2^local_n) chunk; `cf` an
    already-traced coefficient vector."""
    from quest_tpu.env import AMP_AXIS

    out_re = jnp.zeros_like(amps[0])
    out_im = jnp.zeros_like(amps[1])
    for g in plan.groups:
        lx = tuple(q for q in g.x_bits if q < local_n)
        gxm = 0
        for q in g.x_bits:
            if q >= local_n:
                gxm |= 1 << (q - local_n)
        src = amps
        if gxm:
            src = exchanged.get(gxm)
            if src is None:
                D = exchanged["__D__"]
                perm = [(d, d ^ gxm) for d in range(D)]
                src = jax.lax.ppermute(amps, AMP_AXIS, perm)
                exchanged[gxm] = src
        dims, axis_of, ranges = _group_view(local_n, lx)
        sr = src[0].reshape(dims)
        si = src[1].reshape(dims)
        if lx:
            axes = [axis_of[q] for q in lx]
            sr = jnp.flip(sr, axes)
            si = jnp.flip(si, axes)
        rdt = np.dtype(sr.dtype)
        ndims = len(dims)
        gre = gim = None
        for t in g.terms:
            pre, pim = _quarter_turn(t.ny % 4, sr, si)
            lzy = tuple(b for b in t.zy_bits if b < local_n)
            tabs = _parity_tables(ranges, lzy, rdt)
            gzy = tuple(b - local_n for b in t.zy_bits if b >= local_n)
            w = cf[t.index]
            if gzy:
                w = w * _device_parity_sign(dev, gzy, amps.dtype)
            tre = _apply_sign_tables(pre, tabs, ndims) * w
            tim = _apply_sign_tables(pim, tabs, ndims) * w
            gre = tre if gre is None else gre + tre
            gim = tim if gim is None else gim + tim
        out_re = out_re + gre.reshape(-1)
        out_im = out_im + gim.reshape(-1)
    return jnp.stack([out_re, out_im])


def _expec_sharded_body(amps, coeffs, *, plan: ExpecPlan, D: int):
    from quest_tpu.env import AMP_AXIS

    local_n = plan.n - (D.bit_length() - 1)
    dev = jax.lax.axis_index(AMP_AXIS)
    acc = precision.accum_dtype(amps.dtype)
    cf = jnp.asarray(coeffs, dtype=amps.dtype)
    exchanged: Dict = {"__D__": D}
    total = jnp.zeros((), dtype=acc)
    for pack in plan.sweeps:
        flat = None
        for gi in pack:
            c = _group_contrib_sharded(amps, cf, local_n, dev,
                                       plan.groups[gi], exchanged)
            flat = c if flat is None else flat + c
        total = total + jnp.sum(flat.astype(acc))
    return jax.lax.psum(total, AMP_AXIS)


def expec_sharded(amps, coeffs, plan: ExpecPlan, mesh):
    """Fused expectation of a mesh-sharded statevector: per-shard
    partial sums + one psum, the state never gathers. Bit-/eps-equal to
    the single-device fused result (pinned on the 2-dev CPU mesh in
    tests/test_expec.py)."""
    from jax.sharding import PartitionSpec as P

    from quest_tpu import compat
    from quest_tpu.env import AMP_AXIS

    D = int(mesh.devices.size)
    ck = (mesh, plan, D)
    run = _SHARDED_RUNS.get(ck)
    if run is None:
        body = partial(_expec_sharded_body, plan=plan, D=D)
        run = jax.jit(compat.shard_map(body, mesh,
                                       (P(None, AMP_AXIS), P()), P()))
        _SHARDED_RUNS[ck] = run
    return run(amps, coeffs)


# ---------------------------------------------------------------------------
# register-level entry + introspection
# ---------------------------------------------------------------------------


def expec_value(q, coeffs, codes_key) -> float:
    """sum_t c_t <P_t> of register `q` through the grouped fused
    engine. Dispatch: sharded statevectors ride the shard_map
    partial-sum path; everything else (single device, density — GSPMD
    partitions the density trace fine) the jitted fused program."""
    plan = plan_expec(codes_key, q.num_qubits, density=q.is_density)
    cf = jnp.asarray(coeffs, dtype=q.real_dtype)
    if not q.is_density:
        from quest_tpu.env import AMP_AXIS
        mesh = getattr(getattr(q.amps, "sharding", None), "mesh", None)
        if (mesh is not None and mesh.devices.size > 1
                and AMP_AXIS in mesh.axis_names):
            return float(expec_sharded(q.amps, cf, plan, mesh))
    return float(_expec_fused(q.amps, cf, plan=plan))


def plan_stats(all_codes, num_qubits: int, *, density: bool = False) -> dict:
    """CPU-assertable plan introspection (no compile, no chip — the
    Circuit.plan_stats discipline): term/group/sweep counts of the
    grouped plan vs the per-term baseline's pass count. With
    QUEST_EXPEC_FUSION=0 the reported `expec_hbm_sweeps` is the
    baseline's (that is what dispatch would run)."""
    codes_key = parse_pauli_sum(all_codes, num_qubits)
    plan = plan_expec(codes_key, num_qubits, density=density)
    diag = sum(len(g.terms) for g in plan.groups if not g.x_bits)
    # baseline: one workspace apply + one inner-product pass per term
    # (statevector); one strided diagonal gather per term (density)
    baseline = (1 if density else 2) * plan.num_terms
    fused = fusion_enabled()
    return {
        "terms": plan.num_terms,
        "expec_groups": len(plan.groups),
        "diagonal_terms": diag,
        "expec_hbm_sweeps": len(plan.sweeps) if fused else baseline,
        "baseline_hbm_sweeps": baseline,
        "max_masks_per_sweep": max_masks_per_sweep(),
        "fusion": fused,
    }


def explain(all_codes, num_qubits: int, *, density: bool = False) -> str:
    """Human-readable plan dump (the explain() counterpart of
    plan_stats): one line per sweep with its mask groups."""
    codes_key = parse_pauli_sum(all_codes, num_qubits)
    plan = plan_expec(codes_key, num_qubits, density=density)
    stats = plan_stats(all_codes, num_qubits, density=density)
    of_kind = "density tr(H rho)" if density else "statevec"
    lines = [f"expec plan: {plan.num_terms} terms -> "
             f"{stats['expec_groups']} mask groups -> "
             f"{len(plan.sweeps)} sweeps ({of_kind}; baseline "
             f"{stats['baseline_hbm_sweeps']} passes)"]
    for si, pack in enumerate(plan.sweeps):
        parts = []
        for gi in pack:
            g = plan.groups[gi]
            mask = ("diagonal" if not g.x_bits
                    else "x=" + ",".join(map(str, g.x_bits)))
            parts.append(f"{mask}({len(g.terms)}t)")
        lines.append(f"  sweep {si}: " + "  ".join(parts))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Pauli-sum observable spec (serve / variational surface)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PauliSum:
    """Value-hashable Pauli-sum spec: `codes` is an (M, num_qubits)
    nested tuple of Pauli codes (0=I 1=X 2=Y 3=Z), `coeffs` the M real
    weights. Build via `PauliSum.of(...)` (validates + normalizes).
    Accepted by `ServeEngine.submit(observable=...)` and
    `variational.expectation` — both resolve it to the grouped fused
    reduction; equal specs resolve to the SAME reducer object, so a
    serve batch of like requests runs one compiled reduction per
    launch."""
    codes: Tuple[Tuple[int, ...], ...]
    coeffs: Tuple[float, ...]

    @classmethod
    def of(cls, all_codes, coeffs, num_qubits: int) -> "PauliSum":
        codes_key = parse_pauli_sum(all_codes, num_qubits)
        cf = np.asarray(coeffs, dtype=np.float64).reshape(-1)
        if len(cf) != len(codes_key):
            from quest_tpu import validation as val
            val._err("Invalid Pauli sum: must give exactly one "
                     "coefficient per term.")
        return cls(codes=codes_key, coeffs=tuple(float(c) for c in cf))

    @property
    def num_qubits(self) -> int:
        return len(self.codes[0]) if self.codes else 0

    def plan_stats(self, density: bool = False) -> dict:
        """The module-level plan_stats for this spec — the observable
        counterpart of Circuit.plan_stats, so a (circuit, observable)
        pair introspects through one idiom (quest_tpu/plan.py consumers,
        docs/PLANNING.md)."""
        return plan_stats(self.codes, self.num_qubits, density=density)


def batched_reducer(spec: PauliSum, num_qubits: int, density: bool = False):
    """(B, 2, 2^n) planes -> (B,) fused expectations — the serve
    `observable=` reduction (engine.py demux contract: reduce the
    CONSTANT bucket-shaped planes on device, values sliced per request
    after). lru-cached by spec VALUE plus the co-ride budget (the
    keyed-knob contract: a QUEST_EXPEC_MAX_MASKS flip must resolve to
    a fresh plan, never a stale cached reducer): equal PauliSums from
    different requests share one callable, so the demux's per-id
    reduction cache coalesces them into one launch-side reduction.
    Zero-padded batch rows reduce to 0 and are sliced off by the
    caller."""
    return _batched_reducer_cached(spec, num_qubits, density,
                                   max_masks_per_sweep())


@functools.lru_cache(maxsize=128)
def _batched_reducer_cached(spec: PauliSum, num_qubits: int, density: bool,
                            max_masks: int):
    plan = _plan_cached(spec.codes,
                        2 * num_qubits if density else num_qubits,
                        density, max_masks)
    coeffs = np.asarray(spec.coeffs, dtype=np.float64)

    @jax.jit
    def reduce(planes_b):
        planes_b = jnp.asarray(planes_b)
        cf = jnp.asarray(coeffs, dtype=planes_b.dtype)
        return jax.vmap(lambda a: expec_traced(a, cf, plan))(planes_b)

    return reduce


def resolve_observable(spec, num_qubits: int, density: bool = False):
    """Serve-side spec resolution: a `PauliSum` (or a bare
    (codes, coeffs) pair) becomes the cached batched fused reducer.
    Width mismatches fail loudly at submit time, not at demux."""
    if not isinstance(spec, PauliSum):
        if isinstance(spec, tuple) and len(spec) == 2:
            spec = PauliSum.of(spec[0], spec[1], num_qubits)
        else:
            raise TypeError(
                f"observable must be a callable, a PauliSum, or a "
                f"(codes, coeffs) pair; got {type(spec).__name__}")
    if spec.num_qubits != num_qubits:
        raise ValueError(
            f"PauliSum is over {spec.num_qubits} qubits but the "
            f"circuit has {num_qubits}")
    return batched_reducer(spec, num_qubits, density)
