"""Gate-matrix constructors — host-side numpy.

All functions here produce concrete numpy complex matrices on the host;
they are packed into (re, im) float pairs at the jit boundary (see
quest_tpu.cplx — complex data never crosses host<->device directly).
Parameterized gates that must stay dynamic under jit are built inside the
trace by the builders in quest_tpu.ops.gates instead.

Conventions follow the reference exactly:
  - compactUnitary(alpha, beta) = [[alpha, -conj(beta)], [beta, conj(alpha)]]
    (ref QuEST_cpu.c:1656-1713 butterfly)
  - rotateAroundAxis(theta, n) = cos(t/2) I - i sin(t/2) (n . sigma)
    (ref getComplexPairFromRotation, QuEST_common.c:114-122)
  - phaseShift(theta) = diag(1, e^{i theta}); S = diag(1, i);
    T = diag(1, e^{i pi/4}) (ref QuEST_common.c:250-290)
  - sqrtSwap per ref QuEST_common.c:383-407
  - Kraus superoperator Sum_k conj(K) (x) K with the conj factor on the
    high (column-space) matrix bits (ref macro_populateKrausOperator,
    QuEST_common.c:540-600)
"""

from __future__ import annotations

import numpy as np

_SQRT2_INV = 1.0 / np.sqrt(2.0)

PAULI_I = np.eye(2, dtype=np.complex128)
PAULI_X = np.array([[0, 1], [1, 0]], dtype=np.complex128)
PAULI_Y = np.array([[0, -1j], [1j, 0]], dtype=np.complex128)
PAULI_Z = np.array([[1, 0], [0, -1]], dtype=np.complex128)
PAULIS = (PAULI_I, PAULI_X, PAULI_Y, PAULI_Z)

HADAMARD = np.array([[1, 1], [1, -1]], dtype=np.complex128) * _SQRT2_INV

# SWAP exchanges |01> and |10> (matrix bit 0 = first target)
SWAP = np.array(
    [[1, 0, 0, 0],
     [0, 0, 1, 0],
     [0, 1, 0, 0],
     [0, 0, 0, 1]], dtype=np.complex128)

SQRT_SWAP = np.array(
    [[1, 0, 0, 0],
     [0, 0.5 + 0.5j, 0.5 - 0.5j, 0],
     [0, 0.5 - 0.5j, 0.5 + 0.5j, 0],
     [0, 0, 0, 1]], dtype=np.complex128)

S_DIAG = np.array([1, 1j], dtype=np.complex128)
T_DIAG = np.array([1, _SQRT2_INV * (1 + 1j)], dtype=np.complex128)
Z_DIAG = np.array([1, -1], dtype=np.complex128)


def compact_unitary(alpha, beta) -> np.ndarray:
    alpha, beta = complex(alpha), complex(beta)
    return np.array([[alpha, -np.conj(beta)], [beta, np.conj(alpha)]])


def rotation_pair(angle, axis):
    """(alpha, beta) for rotateAroundAxis; axis normalized on the fly."""
    ax = np.asarray(axis, dtype=np.float64)
    ax = ax / np.linalg.norm(ax)
    half = float(angle) / 2.0
    c, s = np.cos(half), np.sin(half)
    return complex(c, -s * ax[2]), complex(s * ax[1], -s * ax[0])


def rotation(angle, axis) -> np.ndarray:
    alpha, beta = rotation_pair(angle, axis)
    return compact_unitary(alpha, beta)


def phase_diag(angle) -> np.ndarray:
    """diag(1, e^{i angle})."""
    return np.array([1.0, np.exp(1j * float(angle))])


def damping_kraus(p: float):
    """Amplitude-damping Kraus pair {K0=diag(1,sqrt(1-p)), K1=sqrt(p)|0><1|}
    (ref mixDamping operators, QuEST_cpu.c:130-180). The ONE place these
    live — shared by the density channels, circuit builders, and the
    trajectory unraveling."""
    return [np.array([[1.0, 0.0], [0.0, np.sqrt(1.0 - p)]]),
            np.array([[0.0, np.sqrt(p)], [0.0, 0.0]])]


def dephasing_kraus(p: float):
    """Phase-damping pair {sqrt(1-p) I, sqrt(p) Z} (ref mixDephasing)."""
    return [np.sqrt(1.0 - p) * PAULI_I, np.sqrt(p) * PAULI_Z]


def depolarising_kraus(p: float):
    """Depolarising quadruple (ref mixDepolarising)."""
    return [np.sqrt(1.0 - p) * PAULI_I, np.sqrt(p / 3.0) * PAULI_X,
            np.sqrt(p / 3.0) * PAULI_Y, np.sqrt(p / 3.0) * PAULI_Z]


def pauli_kraus(px: float, py: float, pz: float):
    """Probabilistic-Pauli quadruple (ref densmatr_mixPauli,
    QuEST_common.c:675-695)."""
    return [np.sqrt(1.0 - px - py - pz) * PAULI_I, np.sqrt(px) * PAULI_X,
            np.sqrt(py) * PAULI_Y, np.sqrt(pz) * PAULI_Z]


def kraus_superoperator(ops) -> np.ndarray:
    """Sum_k conj(K_k) (x) K_k, a 2k-qubit operator on the doubled register.

    Row/col index layout: low k bits act on the row-space copy of the targets
    (the K factor), high k bits on the column-space copy (the conj(K) factor)
    — matching the reference's allTargets = [targs..., targs+N...] ordering
    (QuEST_common.c:601-640).
    """
    ops = [np.asarray(op, dtype=np.complex128) for op in ops]
    dim = ops[0].shape[0]
    sup = np.zeros((dim * dim, dim * dim), dtype=np.complex128)
    for op in ops:
        sup += np.kron(np.conj(op), op)
    return sup


def controlled_embed(matrix: np.ndarray, num_controls: int) -> np.ndarray:
    """Embed a k-qubit matrix as a (k+c)-qubit matrix controlled on the HIGH
    c bits being all-1. Used by the dense test oracle and QASM tooling."""
    m = np.asarray(matrix, dtype=np.complex128)
    dim = m.shape[0]
    full = np.eye(dim << num_controls, dtype=np.complex128)
    full[-dim:, -dim:] = m
    return full


def superop_targets(targets, num_qubits):
    """The doubled-register target list [targets, targets + N] a channel
    superoperator acts on (ref QuEST_common.c:601-640 allTargets layout).
    THE single definition — circuit/sharded/channel engines all use it."""
    return tuple(targets) + tuple(t + num_qubits for t in targets)
