"""``python -m quest_tpu.analysis`` — run quest-lint from the shell."""

import sys

from quest_tpu.analysis.cli import main

sys.exit(main(sys.argv[1:]))
