"""Command-line front-end for quest-lint (python -m quest_tpu.analysis)."""

from __future__ import annotations

import argparse
import json
import os
from typing import List, Optional, Sequence

from quest_tpu.analysis.lint import RULES, run_lint


def _default_paths() -> List[str]:
    """quest_tpu/, scripts/ and tests/ of the repository containing the
    installed package (the layout the tier-1 test lints)."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    repo = os.path.dirname(pkg)
    out = [pkg]
    for extra in ("scripts", "tests"):
        p = os.path.join(repo, extra)
        if os.path.isdir(p):
            out.append(p)
    return out


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m quest_tpu.analysis",
        description="quest-lint: static analyzer for quest_tpu's "
                    "compiled-path and concurrency invariants "
                    "(QL001-QL009; docs/ANALYSIS.md)")
    ap.add_argument("paths", nargs="*",
                    help="files or directories to lint (default: the "
                         "repo's quest_tpu/, scripts/ and tests/)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset, e.g. QL001,QL004")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, doc in sorted(RULES.items()):
            print(f"{rule}  {doc}")
        return 0

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rules if r not in RULES]
        if unknown:
            ap.error(f"unknown rule(s): {unknown}; known: {sorted(RULES)}")

    paths = list(args.paths) or _default_paths()
    violations = run_lint(paths, rules=rules)

    if args.format == "json":
        # stable machine-readable schema: exactly these keys, in this
        # order, sorted by (path, line, col, rule) like the text form —
        # CI annotators and scripts/lint.sh --format=json rely on it
        print(json.dumps([{"rule": v.rule, "path": v.path,
                           "line": v.line, "col": v.col,
                           "message": v.message}
                          for v in violations], indent=2))
    else:
        for v in violations:
            print(v.render(root=os.getcwd()))
        n = len(violations)
        print(f"quest-lint: {n} violation{'s' if n != 1 else ''} in "
              f"{len(paths)} path(s)")
    return 1 if violations else 0
