"""Runtime audit harness: retrace accounting + knob-flip cache audits.

The static rules (quest_tpu.analysis.lint) prove every compiled-path
knob is REGISTERED; this module proves the registration actually works
at run time:

  * CompileAuditor — a context manager hooked into jax's monitoring
    events that counts traces/compiles while it is active. The golden
    retrace check runs a circuit set twice and asserts the second pass
    compiles NOTHING (a nonzero count means some cache key is unstable
    — the silent recompile tax).

  * audit_knob_flips — for every keyed knob in the registry, warms the
    circuit-level compiled cache and the eager per-gate jit workers,
    asserts a same-value rerun does NOT retrace, then flips the knob
    and asserts the caches MISS (a hit means the knob is missing from
    the cache key: the exact stale-program bug of ADVICE r4 item 2 /
    r5 item 2, reintroduced and caught in tests/test_lint.py).

Run from pytest (tier-1: tests/test_lint.py) — the audits build tiny
3-qubit programs, so a full sweep costs seconds, not minutes.
"""

from __future__ import annotations

import contextlib
import os
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np


class StaleCacheError(AssertionError):
    """A compiled-program cache returned a stale program (or retraced
    when it should not have) during a knob-flip audit."""


class CompileAuditor:
    """Counts jit traces while active, via jax's monitoring events
    (one '/jax/core/compile/jaxpr_trace_duration' duration event fires
    per trace; backend compiles are counted separately). Nestable and
    re-enterable; the process-wide listener is registered on first
    enter and left installed (jax 0.4.x has no public unregister) —
    events only reach auditors currently in `_installed`, so exited
    auditors cost one empty-list iteration."""

    _installed: List["CompileAuditor"] = []
    _listener_registered = False

    def __init__(self):
        self.traces = 0
        self.backend_compiles = 0

    # -- event plumbing ---------------------------------------------------
    @classmethod
    def _ensure_listener(cls) -> None:
        if cls._listener_registered:
            return
        from jax._src import monitoring

        def on_duration(event: str, duration: float, **kw) -> None:
            if event.endswith("jaxpr_trace_duration"):
                for aud in cls._installed:
                    aud.traces += 1
            elif event.endswith("backend_compile_duration"):
                for aud in cls._installed:
                    aud.backend_compiles += 1

        monitoring.register_event_duration_secs_listener(on_duration)
        cls._listener_registered = True

    def __enter__(self) -> "CompileAuditor":
        type(self)._ensure_listener()
        self.traces = 0
        self.backend_compiles = 0
        type(self)._installed.append(self)
        return self

    def __exit__(self, *exc) -> None:
        with contextlib.suppress(ValueError):
            type(self)._installed.remove(self)

    # -- assertions -------------------------------------------------------
    def assert_no_retrace(self, what: str = "golden circuit set") -> None:
        if self.traces:
            raise StaleCacheError(
                f"{self.traces} unexpected retrace(s) while re-running "
                f"the {what}: some compiled-program cache key is "
                f"unstable (every rerun pays a silent recompile)")


# ---------------------------------------------------------------------------
# golden circuit set
# ---------------------------------------------------------------------------


def golden_circuits():
    """Small circuits covering the per-gate XLA engine and the banded
    fusion engine — the compiled surfaces whose cache discipline the
    audits exercise. Deliberately tiny (3 qubits) so a full audit sweep
    stays in seconds."""
    from quest_tpu.circuit import Circuit
    c1 = Circuit(3).h(0).cnot(0, 1).rz(2, 0.25).cz(1, 2).rx(0, 0.5)
    c2 = Circuit(3)
    for q in range(3):
        c2.h(q)
    c2.cnot(0, 2).t(1)
    return [c1, c2]


def _base_state(n: int = 3) -> np.ndarray:
    amps = np.zeros((2, 1 << n), dtype=np.float32)
    amps[0, 0] = 1.0
    return amps


def run_golden(circuits) -> None:
    """One pass of a golden set through the compiled engines. Callers
    must pass the SAME circuit objects across passes: the compiled
    caches live on the Circuit instances, so a fresh set per pass
    measures construction cost, not cache stability."""
    for c in circuits:
        amps = _base_state(c.num_qubits)
        c.compiled(c.num_qubits, False, donate=False)(amps)
        c.compiled_banded(c.num_qubits, False, donate=False)(amps)


def golden_retrace_check(circuits=None) -> CompileAuditor:
    """THE golden retrace audit: build the set once, warm every engine,
    re-run the identical pass under a CompileAuditor and assert zero
    retraces. Returns the (exited) auditor for inspection. A failure
    means some compiled-program cache key is unstable — every rerun of
    identical work pays a silent recompile."""
    circuits = golden_circuits() if circuits is None else circuits
    run_golden(circuits)
    with CompileAuditor() as aud:
        run_golden(circuits)
    aud.assert_no_retrace()
    return aud


# ---------------------------------------------------------------------------
# knob flipping
# ---------------------------------------------------------------------------


def _apply_flip(name: str, raw: str) -> None:
    """Flip a knob the way its docs say to flip it mid-process: env var
    for env-read knobs; the setter for setter-backed knobs (matmul
    precision resolves the env once, then set_matmul_precision is the
    documented mid-process switch)."""
    if name == "QUEST_MATMUL_PRECISION":
        from quest_tpu import precision
        precision.set_matmul_precision(raw)
    else:
        os.environ[name] = raw


@contextlib.contextmanager
def _knob_guard(name: str):
    """Save/restore the env var AND any setter-backed effective value."""
    saved_env = os.environ.get(name)
    saved_eff = None
    if name == "QUEST_MATMUL_PRECISION":
        from quest_tpu import precision
        saved_eff = precision.matmul_precision()
    try:
        yield
    finally:
        if saved_env is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = saved_env
        if saved_eff is not None:
            from quest_tpu import precision
            precision.set_matmul_precision(saved_eff)


def _eager_cache_size() -> int:
    """Total jit-cache entries across the eager per-gate workers."""
    from quest_tpu.ops import gates
    total = 0
    for worker in (gates._const_gate_worker, gates._dyn_gate_worker):
        size = getattr(worker, "_cache_size", None)
        if size is not None:
            total += size()
    return total


def _run_eager() -> None:
    """One eager-path gate through the const worker (H is a named
    constant gate: static operand, _const_gate_worker)."""
    from quest_tpu import state
    from quest_tpu.ops import gates
    q = state.create_qureg(3)
    gates.hadamard(q, 0)


def audit_knob_flips(names: Optional[Sequence[str]] = None,
                     circuit=None) -> List[Dict]:
    """For each keyed registry knob with registered flip values: assert
    the circuit-level compiled cache and (for apply-layer knobs) the
    eager gate workers MISS when the knob flips, and do NOT retrace
    when it does not. Raises StaleCacheError on the first violation;
    returns a per-knob report on success.

    `circuit` injects the warm subject (tests use it to re-introduce
    the PR-1 stale-eager-worker bug shape and prove the audit trips)."""
    from quest_tpu.env import KNOBS
    from quest_tpu.circuit import Circuit

    targets = [KNOBS[n] for n in names] if names else [
        k for k in KNOBS.values() if k.scope == "keyed" and k.flips]
    report: List[Dict] = []

    for knob in targets:
        if not knob.flips:
            raise ValueError(f"{knob.name} has no registered flip values")
        with _knob_guard(knob.name):
            _apply_flip(knob.name, knob.flips[0])
            c = circuit if circuit is not None \
                else Circuit(3).h(0).cnot(0, 1).rz(2, 0.25)
            amps = _base_state(c.num_qubits)

            # warm, then prove a same-value rerun is cache-stable
            c.compiled(c.num_qubits, False, donate=False)(amps)
            _run_eager()
            with CompileAuditor() as stable:
                c.compiled(c.num_qubits, False, donate=False)(amps)
            stable.assert_no_retrace(
                f"compiled circuit with {knob.name}={knob.flips[0]}")
            eager_before = _eager_cache_size()
            _run_eager()
            if _eager_cache_size() != eager_before:
                raise StaleCacheError(
                    f"eager gate workers retraced on a same-value rerun "
                    f"({knob.name}={knob.flips[0]}): unstable cache key")

            # flip: the circuit-level cache must MISS for every keyed
            # knob, the eager workers for every apply-layer knob
            _apply_flip(knob.name, knob.flips[1])
            with CompileAuditor() as flipped:
                c.compiled(c.num_qubits, False, donate=False)(amps)
            if flipped.traces == 0:
                raise StaleCacheError(
                    f"flipping {knob.name} {knob.flips[0]!r} -> "
                    f"{knob.flips[1]!r} did NOT miss the circuit-level "
                    f"compiled cache: the knob is missing from "
                    f"engine_mode_key() and the engine returned a STALE "
                    f"program (ADVICE r4 item 2 class)")
            eager_missed = None
            if knob.layer == "apply":
                before = _eager_cache_size()
                _run_eager()
                eager_missed = _eager_cache_size() > before
                if not eager_missed:
                    raise StaleCacheError(
                        f"flipping {knob.name} did NOT miss the eager "
                        f"gate workers' jit cache: the apply-layer mode "
                        f"key is not threaded through their static "
                        f"`mode` argument (the PR-1 stale-eager-worker "
                        f"bug, ADVICE r5 item 2)")
            report.append({
                "knob": knob.name,
                "flips": knob.flips,
                "circuit_cache_missed": True,
                "eager_cache_missed": eager_missed,
            })
    return report


def audit_eager_worker(run_gate: Callable[[], None],
                       cache_size: Callable[[], int],
                       knob_name: str) -> None:
    """Knob-flip audit against an INJECTED eager worker: `run_gate`
    dispatches one gate through it, `cache_size` reports its jit cache
    size. Used by the negative test that re-introduces the PR-1
    eager-worker bug (a worker whose static args omit the mode key) and
    asserts this audit catches it. Raises StaleCacheError when flipping
    `knob_name` does not grow the worker's cache."""
    from quest_tpu.env import KNOBS
    knob = KNOBS[knob_name]
    if not knob.flips:
        raise ValueError(f"{knob_name} has no registered flip values")
    with _knob_guard(knob.name):
        _apply_flip(knob.name, knob.flips[0])
        run_gate()
        before = cache_size()
        _apply_flip(knob.name, knob.flips[1])
        run_gate()
        if cache_size() <= before:
            raise StaleCacheError(
                f"flipping {knob.name} did not miss the injected eager "
                f"worker's jit cache: its static arguments omit the "
                f"mode key (the PR-1 stale-eager-worker bug shape)")


# ---------------------------------------------------------------------------
# lock-order auditing (the dynamic half of quest-lint QL005/QL007)
# ---------------------------------------------------------------------------


class LockOrderError(AssertionError):
    """Two audited locks were acquired in opposite orders by different
    threads: a latent ABBA deadlock the static rules cannot see."""


class _AuditedLock:
    """Transparent proxy over a Lock/RLock/Condition that reports every
    acquire/release to its LockOrderAuditor. Forwards everything else
    (`wait`/`notify` on a wrapped Condition still work: during `wait`
    the blocked thread acquires nothing, so the held-stack stays
    truthful for ordering purposes)."""

    def __init__(self, auditor: "LockOrderAuditor", name: str, inner):
        self._auditor = auditor
        self._name = name
        self._inner = inner

    def acquire(self, *args, **kwargs) -> bool:
        got = self._inner.acquire(*args, **kwargs)
        if got:
            self._auditor._note_acquire(self._name)
        return got

    def release(self) -> None:
        self._inner.release()
        self._auditor._note_release(self._name)

    def __enter__(self) -> "_AuditedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __getattr__(self, attr):
        return getattr(self._inner, attr)


class LockOrderAuditor:
    """Records the acquisition-order graph of every wrapped lock and
    fails on a cycle.

        auditor = LockOrderAuditor()
        engine._cond = auditor.wrap("engine", engine._cond)
        fleet._lock = auditor.wrap("fleet", fleet._lock)
        ... run the workload ...
        auditor.assert_acyclic()

    Every `acquire` of lock B while a thread already holds lock A adds
    the directed edge A -> B; a cycle in that graph means two threads
    can acquire the same pair in opposite orders — the ABBA deadlock.
    Same-name re-entry (the ServeFleet RLock contract from PR 11) is
    counted, not edged: a reentrant self-acquire cannot deadlock.
    Thread-safe; the held-stack is thread-local."""

    _GUARDED_BY = {"_mu": ("edges", "reentries", "acquisitions")}

    def __init__(self):
        import threading
        self._mu = threading.Lock()
        self._tls = threading.local()
        self.edges: Dict[str, set] = {}           # A -> {B acquired under A}
        self.reentries: Dict[str, int] = {}       # name -> self-reacquires
        self.acquisitions: Dict[str, int] = {}    # name -> total acquires

    def wrap(self, name: str, inner) -> _AuditedLock:
        with self._mu:
            self.edges.setdefault(name, set())
        return _AuditedLock(self, name, inner)

    def _held(self) -> List[str]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _note_acquire(self, name: str) -> None:
        stack = self._held()
        with self._mu:
            self.acquisitions[name] = self.acquisitions.get(name, 0) + 1
            if name in stack:
                self.reentries[name] = self.reentries.get(name, 0) + 1
            else:
                for held in set(stack):
                    self.edges.setdefault(held, set()).add(name)
        stack.append(name)

    def _note_release(self, name: str) -> None:
        stack = self._held()
        # release orders can interleave (Condition.wait releases out of
        # band); drop the innermost matching entry
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == name:
                del stack[i]
                return

    def find_cycle(self) -> Optional[List[str]]:
        """A lock-name cycle ['a', 'b', 'a'] if one exists, else None."""
        with self._mu:
            edges = {k: sorted(v) for k, v in self.edges.items()}
        WHITE, GREY, BLACK = 0, 1, 2
        color = {n: WHITE for n in edges}
        path: List[str] = []

        def visit(n: str) -> Optional[List[str]]:
            color[n] = GREY
            path.append(n)
            for nxt in edges.get(n, ()):
                c = color.get(nxt, WHITE)
                if c == GREY:
                    return path[path.index(nxt):] + [nxt]
                if c == WHITE:
                    got = visit(nxt)
                    if got:
                        return got
            color[n] = BLACK
            path.pop()
            return None

        for n in sorted(edges):
            if color.get(n, WHITE) == WHITE:
                got = visit(n)
                if got:
                    return got
        return None

    def assert_acyclic(self) -> None:
        cycle = self.find_cycle()
        if cycle:
            raise LockOrderError(
                f"lock acquisition-order cycle {' -> '.join(cycle)}: "
                f"two threads can take these locks in opposite orders "
                f"and deadlock; impose one global order "
                f"(docs/ANALYSIS.md §lock-order)")
