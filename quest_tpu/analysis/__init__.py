"""Project-invariant static analysis + runtime audit harness.

quest-lint (``quest_tpu.analysis.lint``) enforces the compiled-path
invariants that code review kept re-finding by hand (QL001-QL004:
cache-key completeness, i32 kernel hygiene, tracer leaks, loud knob
parsing); the audit harness (``quest_tpu.analysis.audit``) checks the
dynamic halves — zero unexpected retraces over a golden circuit set and
actual cache misses when a registered knob flips.

CLI: ``python -m quest_tpu.analysis [paths ...]`` (defaults to the
repository's quest_tpu/, scripts/ and tests/; exits non-zero on any
violation). Tier-1 enforcement lives in tests/test_lint.py; the rule
catalog with per-rule motivating bugs is docs/ANALYSIS.md.
"""

from quest_tpu.analysis.lint import RULES, Violation, run_lint  # noqa: F401
