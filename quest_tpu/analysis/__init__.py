"""Project-invariant static analysis + runtime audit harness.

quest-lint (``quest_tpu.analysis.lint``) enforces the compiled-path
invariants that code review kept re-finding by hand (QL001-QL004:
cache-key completeness, i32 kernel hygiene, tracer leaks, loud knob
parsing) plus the concurrency + memory-safety invariants of the
threaded serve/durable stack (QL005-QL009: _GUARDED_BY lock
discipline, use-after-donate, blocking-under-lock, atomic-write
discipline, fault-site catalog integrity); the audit harness
(``quest_tpu.analysis.audit``) checks the dynamic halves — zero
unexpected retraces over a golden circuit set, actual cache misses
when a registered knob flips, and an acyclic lock acquisition-order
graph (LockOrderAuditor).

CLI: ``python -m quest_tpu.analysis [paths ...]`` (defaults to the
repository's quest_tpu/, scripts/ and tests/; exits non-zero on any
violation). Tier-1 enforcement lives in tests/test_lint.py; the rule
catalog with per-rule motivating bugs is docs/ANALYSIS.md.
"""

from quest_tpu.analysis.lint import RULES, Violation, run_lint  # noqa: F401
